"""Shared-memory ring-buffer broker: the zero-copy high-rate transport.

The reference deployment leans on Kafka's page-cache + sendfile path to
move record batches without copying them through user space; this broker
is the single-host rebuild of that idea for the speed layer's 100K+
events/s input stream. Each topic partition is one mmap'ed ring file that
every producer/consumer process maps into its own address space; record
batches travel as binary frames (bus/blockcodec.py) written once into the
ring and *decoded as numpy array views over the mapped memory* — a
consumer's parse stage is pointer arithmetic, not text splitting, and the
bytes are never copied out of the transport (LMAX-disruptor shape: one
writer cursor, per-consumer guard cursors, wrap with sequence gating).

Layout of ``<root>/<topic>/partition-<i>.ring``::

    [0, 4096)      header page
        0   u64  ring file magic
        8   u64  ring_bytes (data region size)
        16  u64  head        monotonic byte offset of the write frontier
        24  u64  tail        monotonic byte offset of the reclaim floor
        32  u64  next_seqno  record offset the next frame starts at
        40  u64  base_seqno  earliest retained record offset
        1024     consumer slot table: 64 slots x 32 bytes
                 [pid u64, guard_pos u64, heartbeat_ns u64, reserved u64]
    [4096, 4096 + ring_bytes)   frame data (bus/blockcodec.py frames)

Invariants that make the lock-free read side safe:

- ``head`` is published LAST, after a frame's header+payload bytes are in
  place, so a producer that dies mid-write leaves the ring exactly as it
  was — torn writes are invisible. (A *corrupted* frame under head — e.g.
  bad RAM, or a test poking bytes — fails its CRC; the consumer skips the
  frame by its header length, counts ``bus.shm.crc-resyncs`` and carries
  on at the next frame boundary.)
- Frames never straddle the ring end: when the remainder at the end is
  too small for the next frame the writer emits a PAD frame (kind 0)
  covering it, and a remainder smaller than one header is dead space both
  sides skip arithmetically. Readers and writers therefore agree on frame
  boundaries from (position % ring_bytes) alone.
- The writer may only advance ``tail`` (reclaim space) past bytes that
  every *live* registered consumer guard has released: backpressure is
  bounded blocking (``oryx.bus.shm.full-block-ms``, then BlockingIOError
  — an OSError, so layer retry policies see an ordinary transient), never
  a silent drop. Guards of dead processes are evicted by pid liveness.
- Consumer guards auto-advance at poll entry: views handed out by one
  poll stay valid until the next poll (the GuardedBlockFeed contract).
  ``pin()``/``release()`` freeze the guard across a multi-poll drain.

Writers serialize through the same fcntl flock the file bus uses, so any
number of producer processes can share a partition. Group offsets reuse
the file bus ledger (``__offsets__/<group>.json``) — positions are record
offsets with the same clamp-forward-on-retention semantics, so at-least-
once resume behaves exactly like the file bus.
"""

from __future__ import annotations

import json
import logging
import mmap
import os
import struct
import time
import zlib
from pathlib import Path

import numpy as np

from oryx_tpu.bus import blockcodec
from oryx_tpu.bus.core import (
    Broker,
    KeyMessage,
    TopicConsumer,
    TopicProducer,
    partition_for,
    resolve_partitions,
)
from oryx_tpu.bus.filebus import FileBroker, _Flock
from oryx_tpu.common import metrics, storage, tracing
from oryx_tpu.common.crashpoints import crashpoint

log = logging.getLogger(__name__)

RING_FILE_MAGIC = 0x31676E5278797230  # b"0ryxRng1" little-endian

_HEADER_PAGE = 4096
_OFF_MAGIC = 0
_OFF_RING_BYTES = 8
_OFF_HEAD = 16
_OFF_TAIL = 24
_OFF_NEXT_SEQNO = 32
_OFF_BASE_SEQNO = 40
_SLOTS_OFF = 1024
_SLOT_BYTES = 32
_MAX_SLOTS = 64

_U64 = struct.Struct("<Q")

# one buffered text frame's worth of records when batching send_many
_TEXT_FRAME_SLICE_BYTES = 1 << 20

_DEF_RING_MB = 64
_DEF_SLOTS = 64
_DEF_FULL_BLOCK_MS = 2000.0
_DEF_FRAME_RECORDS = 65536


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    return True


def _cfg(key: str, default):
    try:
        from oryx_tpu.common.config import get_default

        v = get_default().get(f"oryx.bus.shm.{key}", None)
    except Exception:
        return default
    return default if v is None else v


class _Ring:
    """One mmap'ed partition ring (process-local handle; the mapped pages
    are shared with every other process that opens the same file)."""

    def __init__(self, path: Path) -> None:
        self.path = path
        self.lock_path = path.with_suffix(".lock")
        self._closed = False
        self._f = open(path, "r+b")
        try:
            self.mm = mmap.mmap(self._f.fileno(), 0)
        except BaseException:
            self._f.close()
            raise
        if self.u64(_OFF_MAGIC) != RING_FILE_MAGIC:
            self.close()
            raise OSError(f"not a shm ring file: {path}")
        self.ring_bytes = self.u64(_OFF_RING_BYTES)
        if (
            self.ring_bytes <= 0
            or _HEADER_PAGE + self.ring_bytes > os.fstat(self._f.fileno()).st_size
        ):
            # the size word itself is garbled: nothing downstream can be
            # trusted and nothing in-file can rebuild it — refuse loudly
            # (ShmBroker.repair recreates the ring from topic meta)
            self.close()
            raise OSError(f"corrupt shm ring header (ring_bytes) in {path}")
        # repair-on-open: a torn multi-word header update (or external
        # corruption) shows up as impossible head/tail/seqno geometry
        if self._header_insane():
            with _Flock(self.lock_path):
                if self._header_insane():
                    self._reset_empty()
        from oryx_tpu.common import ledger

        ledger.register("ring", self, live=lambda r: not r._closed)

    def _header_insane(self) -> bool:
        head, tail = self.u64(_OFF_HEAD), self.u64(_OFF_TAIL)
        nxt, base = self.u64(_OFF_NEXT_SEQNO), self.u64(_OFF_BASE_SEQNO)
        return tail > head or head - tail > self.ring_bytes or base > nxt

    def _reset_empty(self) -> None:
        """Loud last-resort repair: empty the ring at a consistent seqno.
        Unconsumed frames are lost — upstream layers replay from their
        offset ledgers (at-least-once), nothing is served silently wrong.
        Caller holds the writer flock."""
        seq = max(self.u64(_OFF_NEXT_SEQNO), self.u64(_OFF_BASE_SEQNO))
        self.set_u64(_OFF_HEAD, 0)
        self.set_u64(_OFF_TAIL, 0)
        self.set_u64(_OFF_NEXT_SEQNO, seq)
        self.set_u64(_OFF_BASE_SEQNO, seq)
        metrics.registry.counter("bus.repair.shm-reset").inc()
        log.warning(
            "bus repair: reset shm ring %s to empty at seqno %d "
            "(impossible head/tail geometry)", self.path, seq,
        )

    # -- header words -------------------------------------------------------

    def u64(self, off: int) -> int:
        return _U64.unpack_from(self.mm, off)[0]

    def set_u64(self, off: int, v: int) -> None:
        _U64.pack_into(self.mm, off, v)

    def close(self) -> None:
        if self._closed:  # idempotent: brokers and consumers both reach here
            return
        self._closed = True
        try:
            self.mm.close()
        except BufferError:
            # numpy views over the map are still alive somewhere; the OS
            # reclaims the mapping at process exit
            pass
        self._f.close()

    # -- consumer slots -----------------------------------------------------

    def claim_slot_and_snapshot(self, usable_slots: int) -> tuple[int, int, int, int, int]:
        """Claim a free guard slot (under the writer lock, so the claim and
        the head/tail/seqno snapshot are mutually consistent). Returns
        (slot, head, tail, next_seqno, base_seqno); the guard starts at
        ``tail`` so nothing can be reclaimed out from under the caller
        while it decides where to start."""
        with _Flock(self.lock_path):
            tail = self.u64(_OFF_TAIL)
            for s in range(min(usable_slots, _MAX_SLOTS)):
                off = _SLOTS_OFF + s * _SLOT_BYTES
                pid = self.u64(off)
                if pid != 0 and _pid_alive(pid):
                    continue
                if pid != 0:
                    metrics.registry.counter("bus.shm.evicted-consumers").inc()
                _U64.pack_into(self.mm, off + 8, tail)
                _U64.pack_into(self.mm, off + 16, time.monotonic_ns())
                self.set_u64(off, os.getpid())
                return (
                    s,
                    self.u64(_OFF_HEAD),
                    tail,
                    self.u64(_OFF_NEXT_SEQNO),
                    self.u64(_OFF_BASE_SEQNO),
                )
        raise OSError(
            f"shm ring {self.path.name}: all {min(usable_slots, _MAX_SLOTS)} "
            "consumer slots are claimed by live processes"
        )

    def set_guard(self, slot: int, pos: int) -> None:
        off = _SLOTS_OFF + slot * _SLOT_BYTES
        _U64.pack_into(self.mm, off + 8, pos)
        _U64.pack_into(self.mm, off + 16, time.monotonic_ns())

    def release_slot(self, slot: int) -> None:
        self.set_u64(_SLOTS_OFF + slot * _SLOT_BYTES, 0)

    def _min_guard(self) -> int | None:
        """Smallest guard position over live registered consumers (dead
        pids are evicted on sight). None when no consumer is registered."""
        best: int | None = None
        for s in range(_MAX_SLOTS):
            off = _SLOTS_OFF + s * _SLOT_BYTES
            pid = self.u64(off)
            if pid == 0:
                continue
            if not _pid_alive(pid):
                self.set_u64(off, 0)
                metrics.registry.counter("bus.shm.evicted-consumers").inc()
                continue
            pos = self.u64(off + 8)
            best = pos if best is None else min(best, pos)
        return best

    # -- write side (always under the partition flock) ----------------------

    def append(self, frames, full_block_ms: float) -> int:
        """Append (kind, flags, count, payload, crc|None) frames; assigns
        seqnos and publishes head after each frame. Returns records
        appended. ``crc=None`` computes it; a precomputed crc lets replay
        producers pay only a header pack + memcpy per frame."""
        rb = self.ring_bytes
        n = 0
        with _Flock(self.lock_path):
            head = self.u64(_OFF_HEAD)
            seq = self.u64(_OFF_NEXT_SEQNO)
            deadline = time.monotonic() + full_block_ms / 1000.0
            for kind, flags, count, payload, crc in frames:
                wire = blockcodec.HEADER_BYTES + blockcodec.pad8(len(payload))
                if wire > rb // 2:
                    raise ValueError(
                        f"frame of {wire} bytes exceeds half the shm ring "
                        f"({rb} bytes); raise oryx.bus.shm.ring-mb"
                    )
                rem = rb - head % rb
                if rem < blockcodec.HEADER_BYTES:
                    # dead zone too small for any header: skipped by rule
                    self._ensure_space(head, rem, deadline)
                    head += rem
                elif rem < wire:
                    head = self._write_pad(head, rem, seq, deadline)
                head = self._write_frame(
                    head, kind, flags, seq, count, payload, crc, wire, deadline
                )
                if kind != blockcodec.KIND_PAD:
                    seq += count
                    n += count
        return n

    def _write_frame(self, head, kind, flags, seq, count, payload, crc, wire, deadline):
        self._ensure_space(head, wire, deadline)
        if crc is None:
            crc = zlib.crc32(payload)
        off = _HEADER_PAGE + head % self.ring_bytes
        mm = self.mm
        blockcodec.HEADER.pack_into(
            mm, off, blockcodec.MAGIC, kind, flags, seq, count, len(payload), crc
        )
        body = off + blockcodec.HEADER_BYTES
        mm[body : body + len(payload)] = payload
        pad = blockcodec.pad8(len(payload)) - len(payload)
        if pad:
            mm[body + len(payload) : off + wire] = b"\x00" * pad
        if kind != blockcodec.KIND_PAD:
            self.set_u64(_OFF_NEXT_SEQNO, seq + count)
        crashpoint("bus.shm.publish.pre")
        self.set_u64(_OFF_HEAD, head + wire)  # publish last: torn = invisible
        crashpoint("bus.shm.publish.post")
        return head + wire

    def _write_pad(self, head, rem, seq, deadline):
        """A PAD frame covering the too-small remainder at the ring end."""
        self._ensure_space(head, rem, deadline)
        off = _HEADER_PAGE + head % self.ring_bytes
        blockcodec.HEADER.pack_into(
            self.mm, off, blockcodec.MAGIC, blockcodec.KIND_PAD, 0, seq, 0,
            rem - blockcodec.HEADER_BYTES, 0,
        )
        metrics.registry.counter("bus.shm.pad-frames").inc()
        self.set_u64(_OFF_HEAD, head + rem)
        return head + rem

    def _ensure_space(self, head: int, need: int, deadline: float) -> None:
        """Reclaim whole frames up to the slowest live consumer guard until
        ``need`` bytes fit; bounded blocking past that (backpressure —
        never a silent drop)."""
        rb = self.ring_bytes
        waited = False
        while True:
            tail = self.u64(_OFF_TAIL)
            if head + need - tail <= rb:
                return
            limit = self._min_guard()
            floor = head if limit is None else min(limit, head)
            new_tail, base = tail, None
            while new_tail < floor and head + need - new_tail > rb:
                nxt, b = self._skip_frame(new_tail, floor)
                if nxt is None:
                    break
                new_tail = nxt
                if b is not None:
                    base = b
            if new_tail != tail:
                self.set_u64(_OFF_TAIL, new_tail)
                if base is not None:
                    self.set_u64(_OFF_BASE_SEQNO, base)
                continue
            if time.monotonic() >= deadline:
                metrics.registry.counter("bus.shm.backpressure-timeouts").inc()
                raise BlockingIOError(
                    f"shm ring {self.path.name} full: a slow consumer holds "
                    f"the guard at {limit} (head {head}, ring {rb} bytes)"
                )
            if not waited:
                metrics.registry.counter("bus.shm.backpressure-waits").inc()
                waited = True
            time.sleep(0.001)

    def _skip_frame(self, tail: int, floor: int):
        """Advance tail past one frame/dead-zone. Returns (new_tail,
        new_base_seqno|None), or (None, None) when the next frame reaches
        past ``floor`` (guarded — cannot reclaim)."""
        rb = self.ring_bytes
        rem = rb - tail % rb
        if rem < blockcodec.HEADER_BYTES:
            return tail + rem, None
        off = _HEADER_PAGE + tail % rb
        magic, kind, _flags, seqno, count, length, _crc = blockcodec.HEADER.unpack_from(
            self.mm, off
        )
        if magic != blockcodec.MAGIC or blockcodec.HEADER_BYTES + length > rem:
            # unreachable unless the map was corrupted externally; resync
            return tail + 8, None
        wire = blockcodec.HEADER_BYTES + blockcodec.pad8(length)
        if tail + wire > floor:
            return None, None
        if kind == blockcodec.KIND_PAD:
            return tail + wire, None
        return tail + wire, seqno + count

    # -- fsck ----------------------------------------------------------------

    def fsck(self, repair: bool = False) -> dict:
        """Walk the published region [tail, head) validating every frame
        header and payload CRC. A break in the chain — garbled header,
        frame reaching past head, CRC mismatch — marks the durable
        frontier: everything before it is intact, everything after is
        suspect (a torn multi-byte head publish, or corruption under an
        already-published head). With ``repair=True`` the head rolls back
        to the frontier (``bus.repair.shm-head-rollback``) and impossible
        header geometry empties the ring loudly (``bus.repair.shm-reset``)
        — consumers then replay from upstream ledgers rather than decode
        garbage. Returns {"frames", "head-rollback", "reset"} where the
        action counts are 1 when taken, -1 when needed but repair=False."""
        report = {"frames": 0, "head-rollback": 0, "reset": 0}
        with _Flock(self.lock_path):
            if self._header_insane():
                if repair:
                    self._reset_empty()
                    report["reset"] = 1
                else:
                    report["reset"] = -1
                return report
            rb = self.ring_bytes
            head, pos = self.u64(_OFF_HEAD), self.u64(_OFF_TAIL)
            seq_frontier = None
            while pos < head:
                rem = rb - pos % rb
                if rem < blockcodec.HEADER_BYTES:
                    pos += rem
                    continue
                off = _HEADER_PAGE + pos % rb
                magic, kind, _flags, seqno, count, length, crc = (
                    blockcodec.HEADER.unpack_from(self.mm, off)
                )
                wire = blockcodec.HEADER_BYTES + blockcodec.pad8(length)
                if magic != blockcodec.MAGIC or wire > rem or pos + wire > head:
                    break
                if kind != blockcodec.KIND_PAD:
                    body = off + blockcodec.HEADER_BYTES
                    if zlib.crc32(self.mm[body : body + length]) != crc:
                        break
                    seq_frontier = seqno + count
                report["frames"] += 1
                pos += wire
            if pos < head:
                if repair:
                    self.set_u64(_OFF_HEAD, pos)
                    if seq_frontier is not None:
                        self.set_u64(_OFF_NEXT_SEQNO, seq_frontier)
                    report["head-rollback"] = 1
                    metrics.registry.counter("bus.repair.shm-head-rollback").inc()
                    log.warning(
                        "bus repair: rolled shm ring %s head back %d byte(s) "
                        "to the last intact frame", self.path, head - pos,
                    )
                else:
                    report["head-rollback"] = -1
        return report


class ShmBroker(Broker):
    """`shm:` scheme broker. Locator: ``shm:/dir[?ring_mb=N&...]``."""

    def __init__(
        self,
        root: str,
        ring_bytes: int | None = None,
        slots: int | None = None,
        full_block_ms: float | None = None,
        frame_records: int | None = None,
    ) -> None:
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.ring_bytes = int(
            ring_bytes
            if ring_bytes is not None
            else float(_cfg("ring-mb", _DEF_RING_MB)) * (1 << 20)
        )
        self.slots = int(slots if slots is not None else _cfg("slots", _DEF_SLOTS))
        self.full_block_ms = float(
            full_block_ms
            if full_block_ms is not None
            else _cfg("full-block-ms", _DEF_FULL_BLOCK_MS)
        )
        self.frame_records = int(
            frame_records
            if frame_records is not None
            else _cfg("frame-records", _DEF_FRAME_RECORDS)
        )
        # offsets ledger + topic-dir deletion are byte-compatible with the
        # file bus; delegate instead of re-implementing the flocked JSON
        self._files = FileBroker(str(self.root))
        self._rings: dict[tuple[str, int], _Ring] = {}

    @staticmethod
    def options_from_query(query: str) -> dict:
        out: dict = {}
        if query:
            from urllib.parse import parse_qsl

            for k, v in parse_qsl(query):
                k = k.replace("-", "_")
                if k == "ring_mb":
                    out["ring_bytes"] = int(float(v) * (1 << 20))
                elif k == "ring_bytes":
                    out["ring_bytes"] = int(v)
                elif k in ("slots", "frame_records"):
                    out[k] = int(v)
                elif k == "full_block_ms":
                    out["full_block_ms"] = float(v)
        return out

    def locator(self) -> str:
        return f"shm:{self.root}"

    # -- admin --------------------------------------------------------------

    def _topic_dir(self, topic: str) -> Path:
        return self.root / topic

    def _meta_path(self, topic: str) -> Path:
        return self._topic_dir(topic) / ".meta.json"

    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None:
        d = self._topic_dir(topic)
        d.mkdir(parents=True, exist_ok=True)
        meta = self._meta_path(topic)
        with _Flock(d / ".meta.lock"):
            if not meta.exists():
                storage.commit_text(
                    meta,
                    json.dumps(
                        {
                            "partitions": max(1, partitions),
                            "config": config or {},
                            "ring-bytes": self.ring_bytes,
                        }
                    ),
                )
        for i in range(self._num_partitions(topic)):
            self._ensure_ring_file(topic, i)

    def topic_exists(self, topic: str) -> bool:
        return self._meta_path(topic).exists()

    def delete_topic(self, topic: str) -> None:
        for key in [k for k in self._rings if k[0] == topic]:
            self._rings.pop(key).close()
        self._files.delete_topic(topic)  # rmtree + offsets ledger cleanup

    def _num_partitions(self, topic: str) -> int:
        try:
            return int(json.loads(self._meta_path(topic).read_text())["partitions"])
        except (OSError, json.JSONDecodeError, KeyError):
            return 1

    def _topic_ring_bytes(self, topic: str) -> int:
        """The ring size every process must agree on: recorded in topic
        meta at creation, not taken from each broker's own defaults."""
        try:
            return int(json.loads(self._meta_path(topic).read_text())["ring-bytes"])
        except (OSError, json.JSONDecodeError, KeyError):
            return self.ring_bytes

    def _ring_path(self, topic: str, i: int) -> Path:
        return self._topic_dir(topic) / f"partition-{i}.ring"

    def _ensure_ring_file(self, topic: str, i: int) -> None:
        path = self._ring_path(topic, i)
        try:
            if path.stat().st_size >= _HEADER_PAGE:
                return
        except OSError:
            pass
        with _Flock(path.with_suffix(".lock")):
            try:
                if path.stat().st_size >= _HEADER_PAGE:
                    return
            except OSError:
                pass
            ring_bytes = self._topic_ring_bytes(topic)
            header = bytearray(_HEADER_PAGE)
            _U64.pack_into(header, _OFF_MAGIC, RING_FILE_MAGIC)
            _U64.pack_into(header, _OFF_RING_BYTES, ring_bytes)
            tmp = path.with_suffix(f".tmp{os.getpid()}")
            with open(tmp, "wb") as f:
                f.write(header)
                f.truncate(_HEADER_PAGE + ring_bytes)  # sparse data region
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # appears fully initialized or not at all
            storage.fsync_dir(path.parent)

    def _ring(self, topic: str, i: int) -> _Ring:
        ring = self._rings.get((topic, i))
        if ring is None:
            self._ensure_ring_file(topic, i)
            ring = self._rings[(topic, i)] = _Ring(self._ring_path(topic, i))
        return ring

    def repair(self, topic: str | None = None) -> dict:
        """fsck-style sweep: every partition ring's frame chain is CRC
        validated and repaired (_Ring.fsck), an unopenable ring file —
        bad magic, garbled size word — is recreated empty from the topic
        meta (``bus.repair.shm-recreated``; the upstream layer replays),
        and the shared offset-ledger machinery is swept via the file
        broker. Returns a count report."""
        report = {
            "frames": 0, "head-rollback": 0, "reset": 0,
            "recreated": 0, "tmp-swept": 0,
        }
        topics = (
            [topic]
            if topic is not None
            else [
                d.name
                for d in sorted(self.root.iterdir())
                if d.is_dir() and (d / ".meta.json").exists()
            ]
        )
        for t in topics:
            if not self.topic_exists(t):
                continue
            report["tmp-swept"] += storage.sweep_tmp(self._topic_dir(t))
            for i in range(self._num_partitions(t)):
                path = self._ring_path(t, i)
                try:
                    sub = self._ring(t, i).fsck(repair=True)
                except OSError:
                    # unopenable ring: recreate from topic meta (loud)
                    self._rings.pop((t, i), None)
                    with _Flock(path.with_suffix(".lock")):
                        path.unlink(missing_ok=True)
                    self._ensure_ring_file(t, i)
                    report["recreated"] += 1
                    metrics.registry.counter("bus.repair.shm-recreated").inc()
                    log.warning("bus repair: recreated unopenable shm ring %s", path)
                    continue
                for k, v in sub.items():
                    report[k] += v
        return report

    # -- offsets ------------------------------------------------------------

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        return self._files.get_offsets(group, topic)

    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None:
        self._files.set_offsets(group, topic, offsets)

    def latest_offsets(self, topic: str) -> dict[int, int]:
        return {
            i: self._ring(topic, i).u64(_OFF_NEXT_SEQNO)
            for i in range(self._num_partitions(topic))
        }

    def earliest_offsets(self, topic: str) -> dict[int, int]:
        """First retained record offset per partition (the ring reclaim
        floor — the analogue of the file bus post-retention floor)."""
        return {
            i: self._ring(topic, i).u64(_OFF_BASE_SEQNO)
            for i in range(self._num_partitions(topic))
        }

    # -- produce/consume ----------------------------------------------------

    def producer(self, topic: str) -> "_ShmProducer":
        if not self.topic_exists(topic):
            self.create_topic(topic, 1)
        return _ShmProducer(self, topic)

    def consumer(
        self, topic: str, group: str | None = None, from_beginning: bool = False,
        partitions: list[int] | None = None,
    ) -> "_ShmConsumer":
        if not self.topic_exists(topic):
            self.create_topic(topic, 1)
        return _ShmConsumer(self, topic, group, from_beginning, partitions)

    def close(self) -> None:
        """Drop every process-local ring handle (file + mmap). Idempotent;
        the ring files themselves stay on disk for other processes."""
        rings, self._rings = self._rings, {}
        for ring in rings.values():
            ring.close()


class _ShmProducer(TopicProducer):
    def __init__(self, broker: ShmBroker, topic: str) -> None:
        self._broker = broker
        self._topic = topic
        self._nparts = broker._num_partitions(topic)

    @property
    def update_broker(self) -> str:
        return self._broker.locator()

    @property
    def topic(self) -> str:
        return self._topic

    def send(self, key: str | None, message: str) -> None:
        p = partition_for(key, self._nparts)
        blob = (blockcodec.encode_record(key, message) + "\n").encode("utf-8")
        self._append(p, [(blockcodec.KIND_TEXT, 0, 1, blob, None)])

    def send_many(self, records) -> int:
        if self._nparts == 1:  # no bucketing pass on single-partition topics
            per = {0: records if isinstance(records, list) else list(records)}
        else:
            per = {}
            for key, message in records:
                per.setdefault(partition_for(key, self._nparts), []).append(
                    (key, message)
                )
        n = 0
        for p, recs in per.items():
            frames = [
                (blockcodec.KIND_TEXT, 0, count, blob, None)
                for blob, count in blockcodec.encode_wire_lines(
                    recs, slice_bytes=_TEXT_FRAME_SLICE_BYTES
                )
            ]
            n += self._append(p, frames)
        return n

    def send_interactions(
        self,
        users: np.ndarray,
        items: np.ndarray,
        values: np.ndarray,
        user_prefix: bytes = b"u",
        item_prefix: bytes = b"i",
        timestamps: np.ndarray | None = None,
        partition: int = 0,
    ) -> int:
        """Publish rating events as typed columnar frames: consumers get
        int32/f32 array views, no text ever exists. Chunked to
        ``oryx.bus.shm.frame-records`` per frame."""
        # cap frames to a quarter of the ring as well as frame-records, so
        # small rings (tests, bounded-memory deployments) never trip the
        # half-ring frame limit
        rec_bytes = 12 + (8 if timestamps is not None else 0)
        ring = self._broker._ring(self._topic, partition)
        step = max(1, min(self._broker.frame_records, ring.ring_bytes // 4 // rec_bytes))
        frames = []
        # sampled ambient trace context rides as a zero-count trace frame
        # (columnar payloads have nowhere to put a text record); untraced
        # publishes — the 100K events/s bench path — emit nothing
        hdr = tracing.header_record()
        if hdr is not None:
            frames.append(
                (blockcodec.KIND_TRACE, 0, 0, hdr[1].encode("utf-8"), None)
            )
        for a in range(0, len(values), step):
            b = min(len(values), a + step)
            payload, flags, crc = blockcodec.encode_interactions_payload(
                users[a:b],
                items[a:b],
                values[a:b],
                user_prefix,
                item_prefix,
                None if timestamps is None else timestamps[a:b],
            )
            frames.append((blockcodec.KIND_COLS, flags, b - a, payload, crc))
        return self._append(partition, frames)

    def send_payload(
        self, kind: int, flags: int, count: int, payload: bytes, crc: int,
        partition: int = 0,
    ) -> int:
        """Replay a pre-encoded frame payload (with its precomputed CRC):
        per-send cost is one header pack + one memcpy — the benchmark's
        zero-per-event-format-cost producer path."""
        return self._append(partition, [(kind, flags, count, payload, crc)])

    def _append(self, p: int, frames) -> int:
        ring = self._broker._ring(self._topic, p)
        n = ring.append(frames, self._broker.full_block_ms)
        metrics.registry.counter("bus.shm.frames").inc(len(frames))
        metrics.registry.counter("bus.shm.records").inc(n)
        return n

    def close(self) -> None:
        pass


class _ShmConsumer(TopicConsumer):
    """Reads frames straight out of the mapped ring.

    Positions are record offsets (seqnos), exactly like the file bus line
    offsets, and support mid-frame values: a budget that lands inside a
    frame slices the decoded arrays/lines and the next poll resumes at
    the same frame. The guard slot auto-advances to the current read
    point at each poll entry — everything handed out by the previous poll
    is released then — unless ``pin()`` is in effect.
    """

    def __init__(
        self, broker: ShmBroker, topic: str, group: str | None,
        from_beginning: bool, partitions: list[int] | None = None,
    ) -> None:
        self._broker = broker
        self._topic = topic
        self._group = group
        self._closed = False
        self._pinned = False
        nparts = broker._num_partitions(topic)
        parts = resolve_partitions(nparts, partitions)
        stored = broker.get_offsets(group, topic) if group else {}
        self._rings = {i: broker._ring(topic, i) for i in parts}
        self._slot: dict[int, int] = {}
        self._pos: dict[int, int] = {}
        self._cursor: dict[int, int] = {}
        # per-partition trace context captured from a KIND_TRACE frame,
        # attached to the next delivered block
        self._pending_trace: dict[int, str] = {}
        try:
            for i, ring in self._rings.items():
                slot, head, tail, nseq, bseq = ring.claim_slot_and_snapshot(broker.slots)
                self._slot[i] = slot
                if stored:
                    # stored offset older than the ring retains: clamp forward
                    # (Kafka earliest-reset semantics, same as the file bus)
                    self._pos[i] = max(int(stored.get(i, 0)), bseq)
                    self._cursor[i] = tail
                elif from_beginning:
                    self._pos[i] = bseq
                    self._cursor[i] = tail
                else:
                    self._pos[i] = nseq
                    self._cursor[i] = head
                    ring.set_guard(slot, head)
        except BaseException:
            # a claim partway through the ring set failed (e.g. all slots
            # taken on a later ring): release the slots already claimed so
            # the aborted constructor doesn't strand guard positions that
            # would stall ring reclaim until pid eviction notices
            for i, slot in self._slot.items():
                try:
                    self._rings[i].release_slot(slot)
                except OSError:
                    pass
            raise
        from oryx_tpu.common import ledger

        ledger.register("consumer", self, live=lambda c: not c.closed())

    # -- guard lifetime -----------------------------------------------------

    def pin(self) -> None:
        """Freeze the guard: views stay valid across subsequent polls
        until release(). Used by multi-poll drains (the speed layer)."""
        self._pinned = True

    def release(self) -> None:
        """Release everything consumed so far and resume per-poll guard
        advance. Views handed out since pin() become invalid."""
        for i, ring in self._rings.items():
            ring.set_guard(self._slot[i], self._cursor[i])
        self._pinned = False

    # -- fetch core ---------------------------------------------------------

    def _next_block(self, i: int, budget: int):
        """One decoded block from partition i, or None: consecutive text
        frames merge into a RecordBlock; a columnar frame returns an
        InteractionBlock of zero-copy views (never mixed in one block)."""
        from oryx_tpu.common.records import InteractionBlock, RecordBlock

        ring = self._rings[i]
        mm = ring.mm
        rb = ring.ring_bytes
        head = ring.u64(_OFF_HEAD)
        tail = ring.u64(_OFF_TAIL)
        cur = self._cursor[i]
        if cur < tail:
            cur = tail  # reclaimed under us (post-seek); scan from floor
        if not self._pinned:
            ring.set_guard(self._slot[i], cur)
        pos = self._pos[i]
        lines: list[bytes] = []
        taken = 0
        resynced = False
        while cur < head and taken < budget:
            rem = rb - cur % rb
            if rem < blockcodec.HEADER_BYTES:
                cur += rem  # dead zone at the ring end
                continue
            off = _HEADER_PAGE + cur % rb
            magic, kind, flags, seqno, count, length, crc = (
                blockcodec.HEADER.unpack_from(mm, off)
            )
            if magic != blockcodec.MAGIC or blockcodec.HEADER_BYTES + length > rem:
                # lost framing (corrupted header): hunt for the next
                # aligned frame boundary
                if not resynced:
                    metrics.registry.counter("bus.shm.crc-resyncs").inc()
                    resynced = True
                cur += 8
                continue
            wire = blockcodec.HEADER_BYTES + blockcodec.pad8(length)
            if kind == blockcodec.KIND_TRACE:
                # zero-count control frame: capture the context for the
                # next delivered block (count=0 keeps seqnos untouched,
                # so the pos/seqno arithmetic below must not see it)
                body = off + blockcodec.HEADER_BYTES
                payload = memoryview(mm)[body : body + length]
                if zlib.crc32(payload) == crc:
                    self._pending_trace[i] = bytes(payload).decode(
                        "utf-8", "replace"
                    )
                else:
                    metrics.registry.counter("bus.shm.crc-resyncs").inc()
                cur += wire
                continue
            if kind == blockcodec.KIND_PAD or pos >= seqno + count:
                cur += wire  # pad, or a frame we already consumed
                continue
            body = off + blockcodec.HEADER_BYTES
            payload = memoryview(mm)[body : body + length]
            if zlib.crc32(payload) != crc:
                # torn/corrupted block: its records are unrecoverable —
                # skip the whole frame and resync at the next boundary
                metrics.registry.counter("bus.shm.crc-resyncs").inc()
                cur += wire
                pos = max(pos, seqno + count)
                continue
            if pos < seqno:
                pos = seqno  # gap aged out of the ring: clamp forward
            start = pos - seqno
            take = min(count - start, budget - taken)
            if kind == blockcodec.KIND_TEXT:
                frame_lines = bytes(payload).split(b"\n")
                if frame_lines and frame_lines[-1] == b"":
                    frame_lines.pop()
                lines.extend(frame_lines[start : start + take])
                pos += take
                taken += take
                if start + take == count:
                    cur += wire
                continue
            # KIND_COLS
            if lines:
                break  # emit the accumulated text first; frame stays unread
            users, items, values, ts, up, ip = blockcodec.columns_from_payload(
                payload, count, flags
            )
            sl = slice(start, start + take)
            block = InteractionBlock(
                users[sl],
                items[sl],
                values[sl],
                None if ts is None else ts[sl],
                up,
                ip,
            )
            pos += take
            if start + take == count:
                cur += wire
            self._pos[i] = pos
            self._cursor[i] = cur
            block.trace = self._pending_trace.pop(i, None)
            return block
        self._pos[i] = pos
        self._cursor[i] = cur
        if lines:
            block = blockcodec.lines_to_block(lines, RecordBlock)
            if block is not None and block.trace is None:
                block.trace = self._pending_trace.pop(i, None)
            return block
        return None

    # -- TopicConsumer ------------------------------------------------------

    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]:
        deadline = time.monotonic() + timeout
        out: list[KeyMessage] = []
        while True:
            for i in sorted(self._pos):
                while len(out) < max_records:
                    block = self._next_block(i, max_records - len(out))
                    if block is None:
                        break
                    out.extend(block.iter_key_messages())
            if out or self._closed or time.monotonic() >= deadline:
                return out
            time.sleep(0.0005)

    def poll_block(self, max_records: int = 1000, timeout: float = 0.1):
        """One block per call: a RecordBlock of text records, or an
        InteractionBlock whose arrays are views over the shared map (valid
        until the next poll, or release() when pinned)."""
        deadline = time.monotonic() + timeout
        while True:
            for i in sorted(self._pos):
                block = self._next_block(i, max_records)
                if block is not None and len(block):
                    return block
            if self._closed or time.monotonic() >= deadline:
                return None
            time.sleep(0.0005)

    def positions(self) -> dict[int, int]:
        return dict(self._pos)

    def seek(self, positions: dict[int, int]) -> None:
        for i, off in positions.items():
            i = int(i)
            self._pos[i] = int(off)
            # rescan from the reclaim floor; the fetch loop skips frames
            # below the target seqno arithmetically (header reads only)
            self._cursor[i] = self._rings[i].u64(_OFF_TAIL)

    def commit(self) -> None:
        if self._group:
            self._broker.set_offsets(self._group, self._topic, self._pos)

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            for i, ring in self._rings.items():
                ring.release_slot(self._slot[i])

    def closed(self) -> bool:
        return self._closed
