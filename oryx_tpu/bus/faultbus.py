"""Fault-injecting chaos bus: a Broker wrapper with seeded failure modes.

Streaming systems earn their recovery story by being tested against
broker flaps, dropped deliveries, duplicates, and slow consumers — and a
chaos test is only useful if it is *reproducible*. This module wraps any
inner broker behind a locator of the form

    fault+<inner locator>?drop=0.1&delay_ms=20&dup=0.01&fail_connect=2&seed=7

resolved by ``get_broker`` (oryx_tpu/bus/core.py). All randomness comes
from one ``numpy`` generator seeded by ``seed`` (default 0), so the same
locator over the same traffic injects the same faults.

Fault model (delivery faults, never log corruption — the at-least-once
contract of the real brokers is preserved, which is what lets the chaos
e2e assert bit-identical convergence with a fault-free run):

- ``drop``  on produce: with this probability the produce call raises a
  transient ``ConnectionError`` *before anything is written* (a dropped
  request; the caller's RetryPolicy resends). On poll: the polled batch
  is "lost in flight" — the consumer is rewound via ``seek`` and the poll
  returns empty, so the records are redelivered later.
- ``dup``   on produce: the batch is written twice. On poll: the batch is
  delivered, then delivered once more on the next poll (redelivery).
- ``delay_ms`` — added latency on every produce and every non-empty poll.
- ``fail_connect=N`` — the first N producer()/consumer() openings raise
  ``ConnectionError`` (a broker that is slow to come up).
- programmatic outage: ``set_outage(locator, True)`` makes every
  produce/poll raise until cleared — the "broker down" lever the serving
  /readyz chaos test flips.

State (RNG stream, fault counters, outage flag) is shared per locator
across ``get_broker`` calls so a multi-layer pipeline in one process sees
one coherent fault schedule; ``reset()`` clears it (test isolation).
"""

from __future__ import annotations

import threading
import time
from typing import Iterable
from urllib.parse import parse_qsl

import numpy as np

from oryx_tpu.bus.core import Broker, KeyMessage, TopicConsumer, TopicProducer, get_broker
from oryx_tpu.common import metrics

__all__ = [
    "FaultBroker",
    "FaultState",
    "get_state",
    "reset",
    "schedule_phases",
    "set_levers",
    "set_outage",
]

_FAULT_KEYS = ("drop", "delay_ms", "dup", "fail_connect", "seed")

_states: dict[str, "FaultState"] = {}
_states_lock = threading.Lock()


class FaultState:
    """Shared fault schedule + counters for one fault locator."""

    def __init__(self, drop: float, delay: float, dup: float, fail_connect: int, seed: int) -> None:
        self.drop = drop
        self.delay = delay
        self.dup = dup
        self.lock = threading.Lock()
        self.rng = np.random.default_rng(seed)
        self.connects_left_to_fail = fail_connect
        self.outage = False
        # local counters mirrored into the metrics registry
        self.dropped_records = 0
        self.duplicated_records = 0
        self.injected_errors = 0
        self.rolls = 0  # fault-schedule consultations (proof chaos ran)
        # scenario scripting: timed lever phases applied lazily on the data
        # path (schedule_phases); empty = static levers from the locator
        self._phases: list[dict] = []
        self._phase_t0: float = 0.0
        self._phase_clock = time.monotonic
        self.phases_applied = 0

    # -- scenario scripting hooks -------------------------------------------

    def set_levers(
        self,
        drop: float | None = None,
        delay_ms: float | None = None,
        dup: float | None = None,
        outage: bool | None = None,
    ) -> None:
        """Reset fault levers mid-run (the scripted-scenario control
        surface; each None leaves that lever untouched)."""
        with self.lock:
            if drop is not None:
                self.drop = float(drop)
            if delay_ms is not None:
                self.delay = float(delay_ms) / 1000.0
            if dup is not None:
                self.dup = float(dup)
        if outage is not None:
            self.outage = bool(outage)

    def schedule_phases(self, phases: list[dict], clock=time.monotonic) -> None:
        """Arm a timed fault scenario: each phase is a dict with an ``at``
        offset in seconds (relative to this call) plus any of
        ``drop`` / ``delay_ms`` / ``dup`` / ``outage``. Phases are applied
        lazily as the data path consults the fault schedule, so no extra
        thread is needed and a quiet bus advances no phases. The fleet
        harness uses this to open and close a chaos window mid-run
        (tools/fleet.py scenario actions)."""
        with self.lock:
            self._phases = sorted((dict(p) for p in phases), key=lambda p: p.get("at", 0.0))
            self._phase_clock = clock
            self._phase_t0 = clock()

    def _tick(self) -> None:
        """Apply any scheduled phases that have come due."""
        if not self._phases:
            return
        due: list[dict] = []
        with self.lock:
            elapsed = self._phase_clock() - self._phase_t0
            while self._phases and self._phases[0].get("at", 0.0) <= elapsed:
                due.append(self._phases.pop(0))
        for p in due:
            self.set_levers(
                drop=p.get("drop"),
                delay_ms=p.get("delay_ms"),
                dup=p.get("dup"),
                outage=p.get("outage"),
            )
            self.phases_applied += 1
            metrics.registry.counter("bus.fault.phases-applied").inc()

    def roll(self) -> float:
        self._tick()
        with self.lock:
            self.rolls += 1
            return float(self.rng.random())

    def take_connect_failure(self) -> bool:
        with self.lock:
            if self.connects_left_to_fail > 0:
                self.connects_left_to_fail -= 1
                return True
            return False

    def check_outage(self, what: str) -> None:
        self._tick()
        if self.outage:
            self.injected_errors += 1
            metrics.registry.counter("bus.fault.injected-errors").inc()
            raise ConnectionError(f"injected broker outage ({what})")

    def maybe_delay(self) -> None:
        if self.delay > 0.0:
            time.sleep(self.delay)


def _split_locator(locator: str) -> tuple[str, dict[str, str], str]:
    """fault+inner?query -> (inner locator, fault params, canonical key).

    Query keys that are not fault params stay on the inner locator (so
    ``fault+tcp://h:p?connect_timeout=5&drop=0.1`` forwards the timeout).
    """
    if not locator.startswith("fault+"):
        raise ValueError(f"not a fault locator: {locator!r}")
    bare, _, query = locator[len("fault+") :].partition("?")
    params: dict[str, str] = {}
    passthrough: list[str] = []
    for k, v in parse_qsl(query, keep_blank_values=True):
        if k in _FAULT_KEYS:
            params[k] = v
        else:
            passthrough.append(f"{k}={v}")
    inner = bare + ("?" + "&".join(passthrough) if passthrough else "")
    # the canonical key identifies one fault schedule: the inner endpoint
    # plus the fault params; inner-only tuning knobs (e.g. a netbus
    # connect_timeout) don't fork the shared RNG/outage state
    canon = bare + "?" + "&".join(f"{k}={params[k]}" for k in _FAULT_KEYS if k in params)
    return inner, params, canon


def get_state(locator: str) -> "FaultState":
    """The shared FaultState for a fault locator (creates it if needed)."""
    _, params, canon = _split_locator(locator)
    with _states_lock:
        state = _states.get(canon)
        if state is None:
            state = FaultState(
                drop=float(params.get("drop", 0.0)),
                delay=float(params.get("delay_ms", 0.0)) / 1000.0,
                dup=float(params.get("dup", 0.0)),
                fail_connect=int(params.get("fail_connect", 0)),
                seed=int(params.get("seed", 0)),
            )
            _states[canon] = state
    return state


def set_outage(locator: str, down: bool) -> None:
    """Flip the injected-outage lever for a fault locator."""
    get_state(locator).outage = down


def set_levers(locator: str, **levers) -> None:
    """Reset fault levers (drop / delay_ms / dup / outage) for a locator
    mid-run — the programmatic scenario control surface."""
    get_state(locator).set_levers(**levers)


def schedule_phases(locator: str, phases: list[dict], clock=time.monotonic) -> None:
    """Arm a timed chaos scenario on a locator (see
    FaultState.schedule_phases for the phase dict format)."""
    get_state(locator).schedule_phases(phases, clock=clock)


def reset() -> None:
    """Forget all fault state (test isolation; conftest calls this)."""
    with _states_lock:
        _states.clear()


class FaultBroker(Broker):
    """Broker decorator injecting the faults described in the locator."""

    def __init__(self, inner: Broker, state: FaultState) -> None:
        self._inner = inner
        self._state = state

    @classmethod
    def from_locator(cls, locator: str) -> "FaultBroker":
        inner_loc, _, _ = _split_locator(locator)
        return cls(get_broker(inner_loc), get_state(locator))

    # -- admin ops pass through untouched (chaos targets the data path) ------

    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None:
        self._inner.create_topic(topic, partitions, config)

    def topic_exists(self, topic: str) -> bool:
        return self._inner.topic_exists(topic)

    def delete_topic(self, topic: str) -> None:
        self._inner.delete_topic(topic)

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        return self._inner.get_offsets(group, topic)

    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None:
        self._inner.set_offsets(group, topic, offsets)

    def latest_offsets(self, topic: str) -> dict[int, int]:
        return self._inner.latest_offsets(topic)

    # -- faulted data path ---------------------------------------------------

    def producer(self, topic: str) -> TopicProducer:
        if self._state.take_connect_failure():
            metrics.registry.counter("bus.fault.connect-failures").inc()
            raise ConnectionError("injected connect failure (producer)")
        return _FaultProducer(self._inner.producer(topic), self._state)

    def consumer(
        self, topic: str, group: str | None = None, from_beginning: bool = False,
        partitions: list[int] | None = None,
    ) -> TopicConsumer:
        if self._state.take_connect_failure():
            metrics.registry.counter("bus.fault.connect-failures").inc()
            raise ConnectionError("injected connect failure (consumer)")
        return _FaultConsumer(
            self._inner.consumer(topic, group, from_beginning, partitions), self._state
        )


class _FaultProducer(TopicProducer):
    def __init__(self, inner: TopicProducer, state: FaultState) -> None:
        self._inner = inner
        self._state = state

    @property
    def update_broker(self) -> str:
        return self._inner.update_broker

    @property
    def topic(self) -> str:
        return self._inner.topic

    def send(self, key: str | None, message: str) -> None:
        self.send_many([(key, message)])

    def send_many(self, records: Iterable[tuple[str | None, str]]) -> int:
        state = self._state
        state.check_outage("produce")
        records = list(records)
        if not records:
            return 0
        r = state.roll()
        if r < state.drop:
            # a dropped request: nothing reached the broker, caller retries
            state.injected_errors += 1
            metrics.registry.counter("bus.fault.injected-errors").inc()
            raise ConnectionError("injected transient produce failure")
        state.maybe_delay()
        n = self._inner.send_many(records)
        if state.dup > 0.0 and r < state.drop + state.dup:
            self._inner.send_many(records)
            state.duplicated_records += len(records)
            metrics.registry.counter("bus.fault.duplicated").inc(len(records))
        return n

    def send_interactions(self, users, items, values, **kwargs) -> int:
        """Typed columnar produce (block-framed transports): the same
        drop/delay/dup levers as send_many, rolled once per call — a
        dropped request never reaches the ring, a dup re-sends the whole
        column set."""
        send = getattr(self._inner, "send_interactions", None)
        if send is None:
            raise NotImplementedError(
                f"{type(self._inner).__name__} does not support send_interactions"
            )
        state = self._state
        state.check_outage("produce")
        n = len(values)
        if n == 0:
            return 0
        r = state.roll()
        if r < state.drop:
            state.injected_errors += 1
            metrics.registry.counter("bus.fault.injected-errors").inc()
            raise ConnectionError("injected transient produce failure")
        state.maybe_delay()
        sent = send(users, items, values, **kwargs)
        if state.dup > 0.0 and r < state.drop + state.dup:
            send(users, items, values, **kwargs)
            state.duplicated_records += n
            metrics.registry.counter("bus.fault.duplicated").inc(n)
        return sent

    def close(self) -> None:
        self._inner.close()


class _FaultConsumer(TopicConsumer):
    def __init__(self, inner: TopicConsumer, state: FaultState) -> None:
        self._inner = inner
        self._state = state
        self._redeliver_block = None
        self._redeliver_records: list[KeyMessage] | None = None

    def _fault_fetch(self, fetch, rewind_positions, size_of, stash_dup):
        """Shared drop/dup/delay logic for poll and poll_block. Returns the
        fetched batch, or None/empty when it was "lost in flight"."""
        state = self._state
        state.check_outage("poll")
        batch = fetch()
        if batch is None or (size_of(batch) == 0):
            return batch
        state.maybe_delay()
        r = state.roll()
        if r < state.drop:
            # lost delivery: rewind so the records come again later
            self._inner.seek(rewind_positions)
            state.dropped_records += size_of(batch)
            metrics.registry.counter("bus.fault.dropped").inc(size_of(batch))
            return None
        if state.dup > 0.0 and r < state.drop + state.dup:
            stash_dup(batch)
            state.duplicated_records += size_of(batch)
            metrics.registry.counter("bus.fault.duplicated").inc(size_of(batch))
        return batch

    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]:
        if self._redeliver_records is not None:
            out, self._redeliver_records = self._redeliver_records, None
            return out
        pre = self._inner.positions()

        def stash(batch):
            self._redeliver_records = list(batch)

        got = self._fault_fetch(
            lambda: self._inner.poll(max_records, timeout), pre, len, stash
        )
        return got or []

    def poll_block(self, max_records: int = 1000, timeout: float = 0.1):
        if self._redeliver_block is not None:
            out, self._redeliver_block = self._redeliver_block, None
            return out
        pre = self._inner.positions()

        def stash(batch):
            # block-framed transports hand out zero-copy views whose
            # lifetime ends at the next poll; a stashed duplicate must
            # outlive that, so copy it out of the transport buffer
            if hasattr(batch, "materialize"):
                batch = batch.materialize()
            self._redeliver_block = batch

        return self._fault_fetch(
            lambda: self._inner.poll_block(max_records, timeout), pre, len, stash
        )

    def pin(self) -> None:
        """Guard-freeze passthrough for block-framed transports (no-op on
        brokers without a guard)."""
        inner_pin = getattr(self._inner, "pin", None)
        if inner_pin is not None:
            inner_pin()

    def release(self) -> None:
        inner_release = getattr(self._inner, "release", None)
        if inner_release is not None:
            inner_release()

    def positions(self) -> dict[int, int]:
        return self._inner.positions()

    def seek(self, positions: dict[int, int]) -> None:
        self._inner.seek(positions)

    def commit(self) -> None:
        self._state.check_outage("commit")
        self._inner.commit()

    def close(self) -> None:
        self._inner.close()

    def closed(self) -> bool:
        return self._inner.closed()
