"""Apache Kafka adapter behind the Broker SPI (optional extra).

The reference's transport (framework/kafka-util/src/main/java/com/
cloudera/oryx/kafka/util/KafkaUtils.java:57-152: topic admin via
AdminClient, offsets via consumer-group commits) mapped onto the
kafka-python client API. Imported lazily and only when a ``kafka://``
locator is used — the library is NOT bundled; environments without it
keep the file/tcp buses.

Semantics parity notes:
- keys/messages are UTF-8 strings on the wire (KeyMessage contract);
  a None key publishes a null Kafka key.
- consumer groups: ``group=None`` consumers get a throwaway group id and
  never commit; ``from_beginning=True`` maps to auto_offset_reset=
  "earliest" with no stored offsets (the update-topic replay path).
- get/set_offsets use the group-coordinator offset storage like
  KafkaUtils.setOffsets/fillInLatestOffsets.

Integration tests live behind the ``kafka`` pytest marker and need a
reachable broker (ORYX_KAFKA_BOOTSTRAP env var).

Requires kafka-python >= 1.4 (KafkaAdminClient); offset commit/read
adapts at runtime to both the pre-2.1 2-arg OffsetAndMetadata / raw-int
``committed()`` API and the 2.1+ 3-arg / struct-returning one.
"""

from __future__ import annotations

import logging
import uuid
from typing import Iterable

from oryx_tpu.bus.core import Broker, KeyMessage, TopicConsumer, TopicProducer

log = logging.getLogger(__name__)


def _require_kafka():
    try:
        import kafka  # noqa: F401 - availability probe

        return kafka
    except ImportError as e:  # pragma: no cover - exercised without the lib
        raise RuntimeError(
            "kafka:// locators need the kafka-python package; install it or "
            "use a file:/tcp: bus locator"
        ) from e


class _KafkaProducer(TopicProducer):
    def __init__(self, broker: "KafkaBroker", topic: str) -> None:
        kafka = _require_kafka()
        self._broker = broker
        self._topic = topic
        self._producer = kafka.KafkaProducer(
            bootstrap_servers=broker.bootstrap.split(","),
            linger_ms=1000,  # TopicProducerImpl.java:194-202 batching
            batch_size=1 << 16,
            compression_type="gzip",
            max_request_size=1 << 26,
        )

    @property
    def update_broker(self) -> str:
        return self._broker.locator()

    @property
    def topic(self) -> str:
        return self._topic

    def send(self, key: str | None, message: str) -> None:
        self._producer.send(
            self._topic,
            key=key.encode("utf-8") if key is not None else None,
            value=message.encode("utf-8"),
        )

    def send_many(self, records: Iterable[tuple[str | None, str]]) -> int:
        n = 0
        for key, message in records:
            self.send(key, message)
            n += 1
        self._producer.flush()
        return n

    def close(self) -> None:
        self._producer.flush()
        self._producer.close()


class _KafkaConsumer(TopicConsumer):
    def __init__(
        self,
        broker: "KafkaBroker",
        topic: str,
        group: str | None,
        from_beginning: bool,
    ) -> None:
        kafka = _require_kafka()
        self._topic = topic
        self._group = group
        self._closed = False
        self._consumer = kafka.KafkaConsumer(
            topic,
            bootstrap_servers=broker.bootstrap.split(","),
            group_id=group or f"oryx-anon-{uuid.uuid4().hex[:12]}",
            enable_auto_commit=False,
            auto_offset_reset="earliest" if from_beginning else "latest",
            consumer_timeout_ms=1 << 30,
        )
        if from_beginning and group is None:
            # replay-from-zero regardless of any stored position
            self._consumer.poll(timeout_ms=0)
            self._consumer.seek_to_beginning()

    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]:
        batches = self._consumer.poll(
            timeout_ms=int(timeout * 1000), max_records=max_records
        )
        out: list[KeyMessage] = []
        for recs in batches.values():
            for r in recs:
                key = r.key.decode("utf-8", "replace") if r.key is not None else None
                out.append(KeyMessage(key, r.value.decode("utf-8", "replace")))
        return out

    def positions(self) -> dict[int, int]:
        out = {}
        for tp in self._consumer.assignment():
            try:
                out[tp.partition] = self._consumer.position(tp)
            except Exception:  # noqa: BLE001 - unassigned mid-rebalance
                continue
        return out

    def commit(self) -> None:
        if self._group:
            self._consumer.commit()

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._consumer.close()

    def closed(self) -> bool:
        return self._closed


class KafkaBroker(Broker):
    """Broker SPI over an Apache Kafka cluster (kafka://host:port[,...])."""

    def __init__(self, bootstrap: str) -> None:
        _require_kafka()
        self.bootstrap = bootstrap

    def locator(self) -> str:
        return f"kafka://{self.bootstrap}"

    def _admin(self):
        from kafka.admin import KafkaAdminClient

        return KafkaAdminClient(bootstrap_servers=self.bootstrap.split(","))

    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None:
        from kafka.admin import NewTopic
        from kafka.errors import TopicAlreadyExistsError

        topic_config = {}
        if config:
            if config.get("retention-hours") is not None:
                topic_config["retention.ms"] = str(
                    int(float(config["retention-hours"]) * 3600 * 1000)
                )
            if config.get("segment-bytes") is not None:
                topic_config["segment.bytes"] = str(int(config["segment-bytes"]))
            if config.get("max-size") is not None:
                topic_config["max.message.bytes"] = str(int(config["max-size"]))
        admin = self._admin()
        try:
            admin.create_topics(
                [
                    NewTopic(
                        name=topic,
                        num_partitions=max(1, partitions),
                        replication_factor=1,
                        topic_configs=topic_config,
                    )
                ]
            )
        except TopicAlreadyExistsError:
            pass
        finally:
            admin.close()

    def topic_exists(self, topic: str) -> bool:
        admin = self._admin()
        try:
            return topic in admin.list_topics()
        finally:
            admin.close()

    def delete_topic(self, topic: str) -> None:
        admin = self._admin()
        try:
            admin.delete_topics([topic])
        finally:
            admin.close()

    def producer(self, topic: str) -> TopicProducer:
        return _KafkaProducer(self, topic)

    def consumer(
        self, topic: str, group: str | None = None, from_beginning: bool = False,
        partitions: list[int] | None = None,
    ) -> TopicConsumer:
        if partitions is not None:
            raise ValueError(
                "kafka:// consumers do not support manual partition assignment"
            )
        return _KafkaConsumer(self, topic, group, from_beginning)

    def _offset_consumer(self, group: str):
        import kafka

        return kafka.KafkaConsumer(
            bootstrap_servers=self.bootstrap.split(","),
            group_id=group,
            enable_auto_commit=False,
        )

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        import kafka
        from kafka.structs import TopicPartition

        c = self._offset_consumer(group)
        try:
            parts = c.partitions_for_topic(topic) or set()
            out = {}
            for p in sorted(parts):
                committed = c.committed(TopicPartition(topic, p))
                if committed is not None:
                    # kafka-python < 2.0 returns the raw offset int; newer
                    # versions return an OffsetAndMetadata struct
                    out[p] = int(getattr(committed, "offset", committed))
            return out
        finally:
            c.close()

    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None:
        from kafka.structs import OffsetAndMetadata, TopicPartition

        def _oam(offset: int):
            # OffsetAndMetadata grew a leader_epoch field (3 args) in
            # kafka-python 2.1; build 3-arg first, fall back to the 2-arg
            # (offset, metadata) form of 1.4-2.0
            try:
                return OffsetAndMetadata(offset, None, -1)
            except TypeError:
                return OffsetAndMetadata(offset, None)

        c = self._offset_consumer(group)
        try:
            c.commit(
                {
                    TopicPartition(topic, int(p)): _oam(int(o))
                    for p, o in offsets.items()
                }
            )
        finally:
            c.close()

    def latest_offsets(self, topic: str) -> dict[int, int]:
        import kafka
        from kafka.structs import TopicPartition

        c = kafka.KafkaConsumer(bootstrap_servers=self.bootstrap.split(","))
        try:
            parts = sorted(c.partitions_for_topic(topic) or set())
            tps = [TopicPartition(topic, p) for p in parts]
            ends = c.end_offsets(tps)
            return {tp.partition: int(off) for tp, off in ends.items()}
        finally:
            c.close()
