"""Shared wire codec for record batches: one module, two formats.

Every transport that ships record batches — the file bus's on-disk
segments, the TCP bus's length-prefixed frames, and the shared-memory
ring buffer — encodes them through here, so the formats cannot drift
between producers and consumers of different brokers.

Text format (kind=1, and the bare on-disk/netbus form): one record per
line, ``<key>\\t<message>`` with backslash escapes for ``\\ \\t \\n \\r
\\0`` and a lone NUL byte for a None key. Chosen over JSON-per-line
because framework messages are themselves JSON ("UP" deltas, MODEL PMML)
and JSON-in-JSON escapes every quote; with tab framing typical records
carry no escapes and both ends are pure byte slicing. Legacy
``{"k":...,"m":...}`` lines still decode.

Binary columnar format (kind=2): a fixed 32-byte frame header (magic,
kind, flags, seqno, count, length, crc32) followed by a short prefix
table and contiguous typed columns — user ids int32, item ids int32,
values float32, optional timestamps int64. A consumer decodes the whole
frame as numpy array *views* over the transport buffer (zero-copy): the
speed layer's parse stage becomes array arithmetic instead of text
splitting. Control messages (MODEL/MODEL-REF) travel as text frames
(kind=1) over the same framing, so one stream carries both.

Frame header layout (little-endian, 32 bytes):

    offset  size  field
    0       4     magic   0x4B4C4252 (b"RBLK")
    4       2     kind    0=pad/wrap  1=text lines  2=interaction columns
                          3=trace context (count=0: occupies no offsets)
                          4=pre-parsed HTTP request batch (native front)
    6       2     flags   bit 0: columns carry timestamps
    8       8     seqno   absolute topic offset of the first record
    16      4     count   records in the frame
    20      4     length  payload bytes (excluding header and padding)
    24      4     crc     zlib.crc32 of the payload
    28      4     (reserved/zero)

On the wire a frame occupies ``32 + pad8(length)`` bytes: payloads are
zero-padded to an 8-byte boundary so successive frames (and the int32
columns inside them) stay aligned.
"""

from __future__ import annotations

import re
import struct
import zlib

import numpy as np

MAGIC = 0x4B4C4252  # b"RBLK" little-endian
KIND_PAD = 0
KIND_TEXT = 1
KIND_COLS = 2
# trace-context carrier for the columnar shm path (count=0, so seqno /
# offset arithmetic is undisturbed); the text formats carry the same
# context as a reserved "@trc" record line instead
KIND_TRACE = 3
# pre-parsed HTTP request batch from the native serving front
# (native/httpfront.cpp hands these to serving/native_front.py); seqno
# counts requests since front start, count = records in the frame
KIND_HTTP = 4
FLAG_TIMESTAMPS = 1

# a trace control record's encoded line starts with this (the "@trc" key
# needs no escaping); common.tracing owns the key + message format
TRACE_LINE_PREFIX = b"@trc\t"

HEADER = struct.Struct("<IHHQIII4x")
HEADER_BYTES = HEADER.size  # 32
assert HEADER_BYTES == 32


def pad8(n: int) -> int:
    return (n + 7) & ~7


class FrameError(ValueError):
    """Structurally invalid frame (bad magic / insane length)."""


class FrameCrcError(FrameError):
    """Frame header parsed but the payload failed its CRC."""


# ---------------------------------------------------------------------------
# Text record codec (moved verbatim from bus/filebus.py so netbus, filebus
# and shmbus share one implementation)
# ---------------------------------------------------------------------------

_ESC_MAP = {0x5C: 0x5C, 0x74: 0x09, 0x6E: 0x0A, 0x72: 0x0D, 0x30: 0x00}
_NEEDS_ESC = re.compile(r"[\\\t\n\r\x00]")  # one C scan per field, not 5
# batch form for joined slices: \t and \n are the legitimate separators
# and \x00 the legitimate None-key marker there, so those three are
# checked by count, not by pattern
_NEEDS_ESC_BODY = re.compile(r"[\\\r]")
_NEEDS_ESC_B = re.compile(rb"[\\\t\n\r\x00]")
_SENTINEL = object()


def enc_field(s: str) -> str:
    if _NEEDS_ESC.search(s) is not None:
        s = (
            s.replace("\\", "\\\\")
            .replace("\t", "\\t")
            .replace("\n", "\\n")
            .replace("\r", "\\r")
            .replace("\x00", "\\0")
        )
    return s


def enc_field_b(b: bytes) -> bytes:
    if _NEEDS_ESC_B.search(b) is not None:
        b = (
            b.replace(b"\\", b"\\\\")
            .replace(b"\t", b"\\t")
            .replace(b"\n", b"\\n")
            .replace(b"\r", b"\\r")
            .replace(b"\x00", b"\\0")
        )
    return b


def encode_record(key: str | None, message: str) -> str:
    k = "\x00" if key is None else enc_field(key)
    return k + "\t" + enc_field(message)


def unescape(b: bytes) -> bytes:
    out = bytearray()
    i, n = 0, len(b)
    while i < n:
        c = b[i]
        if c == 0x5C and i + 1 < n:
            out.append(_ESC_MAP.get(b[i + 1], b[i + 1]))
            i += 2
        else:
            out.append(c)
            i += 1
    return bytes(out)


def decode_line(line: bytes):
    """One raw line -> KeyMessage, or None for a corrupt line (skip it)."""
    from oryx_tpu.bus.core import KeyMessage

    if line.startswith(b'{"k":'):  # legacy JSON-per-line record
        import json

        try:
            rec = json.loads(line)
            return KeyMessage(rec.get("k"), rec.get("m", ""))
        except json.JSONDecodeError:
            pass  # not legacy after all; try the tab format
    tab = line.find(b"\t")
    if tab == -1:
        return None  # corrupt complete line: skip it for good
    kf, mf = line[:tab], line[tab + 1 :]
    # the None sentinel is a LITERAL lone NUL (the encoder escapes any
    # real NUL), so test before unescaping
    if kf == b"\x00":
        key = None
    else:
        key = (unescape(kf) if b"\\" in kf else kf).decode("utf-8", "replace")
    if b"\\" in mf:
        mf = unescape(mf)
    return KeyMessage(key, mf.decode("utf-8", "replace"))


def encode_wire_lines(records, slice_bytes: int = 8 << 20):
    """Yield (blob, count) slices of tab-framed lines for an iterable of
    (key, message) pairs — the producer-side transport encoding.

    Messages are escaped per same-key run, not per record: the hot caller
    (the speed layer's UP publish, ~60K escape-free JSON messages under
    one key per micro-batch) pays one regex scan + two joins per run
    instead of 60K regex calls."""
    parts: list[str] = []  # encoded line groups for the current slice
    run: list[str] = []  # raw messages sharing the current key
    size = n = 0
    last_key: object = _SENTINEL
    ek = ""

    def close_run() -> None:
        nonlocal run
        if not run:
            return
        body = "\n".join(run)
        pref = ek + "\t"
        # membership scans, not regex: CPython's str __contains__ is a
        # memchr-speed scan per needle, ~10x re.search over the same
        # bytes. \n is checked by count, since it is the legitimate joiner
        if (
            "\\" not in body
            and "\r" not in body
            and "\t" not in body
            and "\x00" not in body
            and body.count("\n") == len(run) - 1
        ):
            parts.append(pref + ("\n" + pref).join(run))
        else:
            parts.append("\n".join(pref + enc_field(m) for m in run))
        run = []

    for key, message in records:
        if key is not last_key:
            close_run()
            ek = "\x00" if key is None else enc_field(key)
            last_key = key
        run.append(message)
        size += len(ek) + len(message) + 2
        n += 1
        if size >= slice_bytes:
            close_run()
            yield ("\n".join(parts) + "\n").encode("utf-8"), n
            parts, size, n = [], 0, 0
    close_run()
    if parts:
        yield ("\n".join(parts) + "\n").encode("utf-8"), n


def decode_wire_lines(blob: bytes):
    """Inverse of encode_wire_lines: yield (key, message) pairs."""
    for line in blob.split(b"\n"):
        if not line:
            continue
        rec = decode_line(line)
        if rec is not None:
            yield rec.key, rec.message


def encode_block_lines(block) -> bytes:
    """A RecordBlock as a tab-framed line blob (poll response transport).

    A block carrying a trace context re-emits it as a leading "@trc"
    line, so the context survives the netbus poll hop (server strips it
    into ``block.trace``, the wire re-frames it, the client's
    ``lines_to_block`` re-attaches it)."""
    head = b""
    trace = getattr(block, "trace", None)
    if trace:
        if isinstance(trace, str):
            trace = trace.encode("utf-8")
        head = TRACE_LINE_PREFIX + trace + b"\n"
    msgs = block.messages.tolist()
    if block.keys is None:
        return head + b"".join(b"\x00\t" + enc_field_b(m) + b"\n" for m in msgs)
    keys = block.keys.tolist()
    nones = (
        block.none_keys.tolist()
        if block.none_keys is not None
        else [False] * len(keys)
    )
    return head + b"".join(
        (b"\x00" if nn else enc_field_b(k)) + b"\t" + enc_field_b(m) + b"\n"
        for k, m, nn in zip(keys, msgs, nones)
    )


def lines_to_block(raw: list[bytes], RecordBlock):
    # trace control records ("@trc" lines): a producer prepends at most
    # one per batch, so the common shapes are an O(1) head check plus one
    # memchr-speed scan of the joined blob for the mid-batch case (two
    # producer batches coalesced into one poll); the per-line Python
    # filter runs only when that scan hits. The last header wins.
    trace = None
    if raw and raw[0].startswith(TRACE_LINE_PREFIX):
        trace = raw[0][len(TRACE_LINE_PREFIX):]
        raw = raw[1:]
    if not raw:
        return None
    # vectorized fast path: a batch is nearly always escape-free,
    # non-legacy (one memchr over the joined blob) and single-key
    # ("UP" runs, None-keyed input) — verify every line shares line
    # 0's key prefix, then strip it with one C-level memcpy view. No
    # per-line Python: this path carries the 100K+ events/s drain.
    blob = b"\n".join(raw)
    if b"\n" + TRACE_LINE_PREFIX in blob:
        kept = []
        for line in raw:
            if line.startswith(TRACE_LINE_PREFIX):
                trace = line[len(TRACE_LINE_PREFIX):]
            else:
                kept.append(line)
        raw = kept
        if not raw:
            return None
        blob = b"\n".join(raw)
    trace_s = trace.decode("utf-8", "replace") if trace is not None else None
    if b"\\" not in blob and b'{"k":' not in blob:
        tab = raw[0].find(b"\t")
        if tab != -1:
            pref = raw[0][: tab + 1]
            arr = np.array(raw, dtype="S")
            w = arr.dtype.itemsize
            m = w - len(pref)
            if m > 0 and bool(np.char.startswith(arr, pref).all()):
                body = arr.view("S1").reshape(len(raw), w)[:, len(pref):]
                msgs_a = np.ascontiguousarray(body).view(f"S{m}").ravel()
                key = pref[:-1]
                if key == b"\x00":
                    block = RecordBlock(None, msgs_a)  # no key column
                else:
                    block = RecordBlock(
                        np.full(len(raw), key, dtype=f"S{max(1, len(key))}"),
                        msgs_a,
                        None,
                    )
                block.trace = trace_s
                return block
    msgs: list[bytes] = []
    keys: list[bytes] = []
    nones: list[bool] = []
    any_key = False
    for line in raw:
        if b"\\" not in line and not line.startswith(b'{"k":'):
            tab = line.find(b"\t")
            if tab != -1:
                kf = line[:tab]
                if kf == b"\x00":
                    keys.append(b"")
                    nones.append(True)
                else:
                    keys.append(kf)
                    nones.append(False)
                    any_key = True
                msgs.append(line[tab + 1 :])
                continue
        rec = decode_line(line)  # legacy/escaped/corrupt: slow path
        if rec is None:
            continue
        if rec.key is None:
            keys.append(b"")
            nones.append(True)
        else:
            keys.append(rec.key.encode("utf-8"))
            nones.append(False)
            any_key = True
        msgs.append(rec.message.encode("utf-8"))
    if not msgs:
        return None
    np_msgs = np.array(msgs, dtype="S")
    if not any_key:
        block = RecordBlock(None, np_msgs)
    else:
        block = RecordBlock(
            np.array(keys, dtype="S"),
            np_msgs,
            np.array(nones, dtype=bool) if any(nones) else None,
        )
    block.trace = trace_s
    return block


# ---------------------------------------------------------------------------
# Binary frames
# ---------------------------------------------------------------------------


def encode_frame(kind: int, flags: int, seqno: int, count: int,
                 payload: bytes, crc: int | None = None) -> bytes:
    """Header + payload, zero-padded to an 8-byte boundary. Pass a
    precomputed ``crc`` to replay an identical payload with only a header
    rewrite (the benchmark's zero-per-event-cost producer path)."""
    if crc is None:
        crc = zlib.crc32(payload)
    head = HEADER.pack(MAGIC, kind, flags, seqno, count, len(payload), crc)
    tail = b"\x00" * (pad8(len(payload)) - len(payload))
    return head + payload + tail


def encode_text_frame(seqno: int, blob: bytes, count: int) -> bytes:
    """A tab-framed line blob (from encode_wire_lines/encode_block_lines)
    as one binary frame."""
    return encode_frame(KIND_TEXT, 0, seqno, count, blob)


def encode_interactions_payload(
    users: np.ndarray,
    items: np.ndarray,
    values: np.ndarray,
    user_prefix: bytes = b"u",
    item_prefix: bytes = b"i",
    timestamps: np.ndarray | None = None,
) -> tuple[bytes, int, int]:
    """Columnar payload for numeric rating events: (payload, flags, crc).

    ``users``/``items`` are int32 id codes; the short prefixes record how
    they map back to the string id space (``u123`` / ``i45``), so the
    text rendering of a frame is recoverable without carrying strings.
    Layout: u8 uplen, u8 iplen, u16 reserved, prefixes, zero padding to
    an 8-byte boundary, then users[i32], items[i32], values[f32] and
    (flagged) timestamps[i64], each contiguous.
    """
    users = np.ascontiguousarray(users, dtype=np.int32)
    items = np.ascontiguousarray(items, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float32)
    if not (len(users) == len(items) == len(values)):
        raise ValueError("column length mismatch")
    if len(user_prefix) > 255 or len(item_prefix) > 255:
        raise ValueError("id prefix longer than 255 bytes")
    sub = struct.pack("<BBH", len(user_prefix), len(item_prefix), 0)
    sub += user_prefix + item_prefix
    sub += b"\x00" * (pad8(len(sub)) - len(sub))
    parts = [sub, users.tobytes(), items.tobytes(), values.tobytes()]
    flags = 0
    if timestamps is not None:
        parts.append(np.ascontiguousarray(timestamps, dtype=np.int64).tobytes())
        flags |= FLAG_TIMESTAMPS
    payload = b"".join(parts)
    return payload, flags, zlib.crc32(payload)


def encode_interaction_frame(
    seqno: int,
    users: np.ndarray,
    items: np.ndarray,
    values: np.ndarray,
    user_prefix: bytes = b"u",
    item_prefix: bytes = b"i",
    timestamps: np.ndarray | None = None,
) -> bytes:
    payload, flags, crc = encode_interactions_payload(
        users, items, values, user_prefix, item_prefix, timestamps
    )
    return encode_frame(KIND_COLS, flags, seqno, len(values), payload, crc)


def columns_from_payload(payload, count: int, flags: int):
    """Decode a columnar payload into zero-copy array views:
    (users_i32, items_i32, values_f32, timestamps_i64|None,
    user_prefix, item_prefix). ``payload`` may be any buffer (bytes or a
    memoryview over shared memory); the views alias it."""
    buf = memoryview(payload)
    uplen, iplen, _ = struct.unpack_from("<BBH", buf, 0)
    user_prefix = bytes(buf[4 : 4 + uplen])
    item_prefix = bytes(buf[4 + uplen : 4 + uplen + iplen])
    off = pad8(4 + uplen + iplen)
    users = np.frombuffer(buf, dtype=np.int32, count=count, offset=off)
    off += 4 * count
    items = np.frombuffer(buf, dtype=np.int32, count=count, offset=off)
    off += 4 * count
    values = np.frombuffer(buf, dtype=np.float32, count=count, offset=off)
    off += 4 * count
    timestamps = None
    if flags & FLAG_TIMESTAMPS:
        timestamps = np.frombuffer(buf, dtype=np.int64, count=count, offset=off)
    return users, items, values, timestamps, user_prefix, item_prefix


# ---------------------------------------------------------------------------
# HTTP request records (KIND_HTTP): the native front's micro-batch unit
# ---------------------------------------------------------------------------

# per-record fixed header inside a KIND_HTTP payload:
#   u32 conn_id, u32 req_id, u8 method, u8 flags, u16 n_headers,
#   u32 target_len, u32 body_len, u32 rec_len (8-aligned total)
_HTTP_REC = struct.Struct("<IIBBHIII")
_HTTP_METHODS = ("GET", "POST", "DELETE", "HEAD", "OTHER")
HTTP_FLAG_HTTP10 = 1
HTTP_FLAG_CLOSE = 2


class HttpRecord:
    """One pre-parsed request from the native front. ``headers`` keeps
    the client's original name casing and order; consumers that need
    case-insensitive lookup wrap it (serving.native_front._Headers)."""

    __slots__ = ("conn_id", "req_id", "method", "flags", "target",
                 "headers", "body")

    def __init__(self, conn_id, req_id, method, flags, target, headers,
                 body) -> None:
        self.conn_id = conn_id
        self.req_id = req_id
        self.method = method
        self.flags = flags
        self.target = target
        self.headers = headers
        self.body = body


def decode_http_records(payload, count: int) -> list[HttpRecord]:
    """Decode a KIND_HTTP payload into its request records."""
    buf = memoryview(payload)
    out: list[HttpRecord] = []
    pos = 0
    for _ in range(count):
        if pos + _HTTP_REC.size > len(buf):
            raise FrameError("truncated http record header")
        (conn_id, req_id, method, flags, n_headers, target_len, body_len,
         rec_len) = _HTTP_REC.unpack_from(buf, pos)
        if pos + rec_len > len(buf) or rec_len < _HTTP_REC.size:
            raise FrameError(f"http record length {rec_len} overruns payload")
        off = pos + _HTTP_REC.size
        target = bytes(buf[off : off + target_len]).decode("latin-1")
        off += target_len
        headers: list[tuple[str, str]] = []
        for _h in range(n_headers):
            klen, vlen = struct.unpack_from("<HH", buf, off)
            off += 4
            k = bytes(buf[off : off + klen]).decode("latin-1")
            off += klen
            v = bytes(buf[off : off + vlen]).decode("latin-1")
            off += vlen
            headers.append((k, v))
        body = bytes(buf[off : off + body_len])
        out.append(
            HttpRecord(
                conn_id,
                req_id,
                _HTTP_METHODS[method] if method < 5 else "OTHER",
                flags,
                target,
                headers,
                body,
            )
        )
        pos += rec_len
    return out


class Frame:
    """A decoded frame: header fields + a payload view (NOT a copy —
    valid only as long as the underlying transport buffer is)."""

    __slots__ = ("kind", "flags", "seqno", "count", "length", "payload")

    def __init__(self, kind, flags, seqno, count, length, payload) -> None:
        self.kind = kind
        self.flags = flags
        self.seqno = seqno
        self.count = count
        self.length = length
        self.payload = payload

    def wire_bytes(self) -> int:
        return HEADER_BYTES + pad8(self.length)

    def text_lines(self) -> list[bytes]:
        lines = bytes(self.payload).split(b"\n")
        if lines and lines[-1] == b"":
            lines.pop()
        return lines

    def columns(self):
        return columns_from_payload(self.payload, self.count, self.flags)


def decode_frame(buf, pos: int = 0, check_crc: bool = True) -> Frame:
    """Parse the frame at ``buf[pos:]``. Raises FrameError on bad magic or
    an insane length, FrameCrcError when the payload fails its CRC (the
    torn/corrupted-block signal: the caller skips the frame and resyncs).
    """
    view = memoryview(buf)
    if pos + HEADER_BYTES > len(view):
        raise FrameError("truncated frame header")
    magic, kind, flags, seqno, count, length, crc = HEADER.unpack_from(view, pos)
    if magic != MAGIC:
        raise FrameError(f"bad frame magic {magic:#x} at {pos}")
    if pos + HEADER_BYTES + length > len(view):
        raise FrameError(f"frame length {length} overruns buffer at {pos}")
    payload = view[pos + HEADER_BYTES : pos + HEADER_BYTES + length]
    if check_crc and kind != KIND_PAD and zlib.crc32(payload) != crc:
        raise FrameCrcError(f"frame CRC mismatch at {pos} (seqno {seqno})")
    return Frame(kind, flags, seqno, count, length, payload)
