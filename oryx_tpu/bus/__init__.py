"""Pluggable message bus: the framework's transport between layers.

Replaces the reference's Kafka (transport) + ZooKeeper (offset/coordination)
pairing (SURVEY.md §2.2, §2.12) with a broker abstraction:

- ``inproc://<name>``  — in-process broker, the cornerstone test asset
  (analogue of the reference's embedded LocalKafkaBroker/LocalZKServer).
- ``file:/<dir>``      — file-backed broker for cross-process single-host
  deployments: append-only partition logs plus a per-group offset ledger.

Topics have partitions; messages are (key, message) string pairs routed by
key hash; consumer groups persist offsets so layers resume where they left
off (reference: KafkaUtils.getOffsets/setOffsets, KafkaUtils.java:123-162).
"""

from oryx_tpu.bus.core import (  # noqa: F401
    KeyMessage,
    TopicProducer,
    TopicConsumer,
    Broker,
    get_broker,
    maybe_create_topic,
    topic_exists,
    delete_topic,
    get_offsets,
    set_offsets,
)
