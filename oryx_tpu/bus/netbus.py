"""TCP bus: the file bus served over sockets — a real networked
transport for multi-host deployments with no shared filesystem.

The reference runs its topics on Kafka (framework/kafka-util/.../
KafkaUtils.java:57-152). This module provides the deployment shape that
matters from that: ONE host runs a bus server (`serve()`, or
``python -m oryx_tpu bus-serve``) holding the topic logs in its local
FileBroker; every layer process — on any host — speaks ``tcp://host:port``
through :class:`NetBroker`, which implements the full Broker SPI
(produce, consume-with-groups, offset ledger, admin). Offsets live in
the server's ledger, so consumer groups resume across client restarts
exactly like the file bus (and like Kafka consumer groups).

Wire protocol (deliberately minimal, length-prefixed):
  request  = u32 header_len | header JSON | u32 payload_len | payload
  response = same shape; header {"ok": bool, "error": str?, ...}
Payloads carry batched records in the file bus's tab-framed line format
(one encode shared with the on-disk segments), so the server's produce
path is a single append and the consumer's poll_block fast path is the
same vectorized splitter the file consumer uses.

A Kafka adapter proper (kafka-python client API) lives in
``oryx_tpu.bus.kafkabus`` for sites that already run Kafka.
"""

from __future__ import annotations

import json
import logging
import socket
import socketserver
import struct
import threading
import time
from typing import Iterable

from oryx_tpu.bus.core import Broker, KeyMessage, TopicConsumer, TopicProducer
from oryx_tpu.common import metrics

log = logging.getLogger(__name__)

_MAX_FRAME = 256 * 1024 * 1024


def _send_frame(sock: socket.socket, header: dict, payload: bytes = b"") -> None:
    h = json.dumps(header).encode("utf-8")
    sock.sendall(struct.pack(">II", len(h), len(payload)) + h + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> tuple[dict, bytes]:
    hlen, plen = struct.unpack(">II", _recv_exact(sock, 8))
    if hlen > _MAX_FRAME or plen > _MAX_FRAME:
        raise ConnectionError(f"oversized frame ({hlen}/{plen})")
    header = json.loads(_recv_exact(sock, hlen)) if hlen else {}
    payload = _recv_exact(sock, plen) if plen else b""
    return header, payload


# ---------------------------------------------------------------------------
# Server
# ---------------------------------------------------------------------------


class _Handler(socketserver.BaseRequestHandler):
    """One connection = one client session. Consumers opened on this
    connection are owned by it and torn down when it drops (a crashed
    client leaks nothing server-side)."""

    def handle(self) -> None:  # noqa: C901 - a flat op switch
        broker = self.server.broker  # type: ignore[attr-defined]
        consumers: dict[int, TopicConsumer] = {}
        next_cid = 0
        sock = self.request
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                try:
                    req, payload = _recv_frame(sock)
                except (ConnectionError, struct.error):
                    return
                op = req.get("op")
                try:
                    if op == "produce":
                        # payload: tab-framed lines, one per record
                        from oryx_tpu.bus.blockcodec import decode_wire_lines

                        records = decode_wire_lines(payload)
                        with broker.producer(req["topic"]) as p:
                            n = p.send_many(records)
                        _send_frame(sock, {"ok": True, "n": n})
                    elif op == "consumer_open":
                        cid = next_cid
                        next_cid += 1
                        consumers[cid] = broker.consumer(
                            req["topic"],
                            group=req.get("group"),
                            from_beginning=bool(req.get("from_beginning")),
                        )
                        _send_frame(sock, {"ok": True, "cid": cid})
                    elif op == "poll":
                        c = consumers[req["cid"]]
                        block = c.poll_block(
                            max_records=int(req.get("max_records", 1000)),
                            timeout=float(req.get("timeout", 0.1)),
                        )
                        from oryx_tpu.bus.blockcodec import encode_block_lines

                        blob = encode_block_lines(block) if block is not None else b""
                        _send_frame(
                            sock,
                            {
                                "ok": True,
                                "n": 0 if block is None else len(block),
                                # positions ride along so the client can
                                # restore this consumer after a reconnect
                                "positions": {str(k): v for k, v in c.positions().items()},
                            },
                            blob,
                        )
                    elif op == "commit":
                        consumers[req["cid"]].commit()
                        _send_frame(sock, {"ok": True})
                    elif op == "seek":
                        consumers[req["cid"]].seek(
                            {int(k): int(v) for k, v in req["positions"].items()}
                        )
                        _send_frame(sock, {"ok": True})
                    elif op == "positions":
                        pos = consumers[req["cid"]].positions()
                        _send_frame(sock, {"ok": True, "positions": {str(k): v for k, v in pos.items()}})
                    elif op == "consumer_close":
                        c = consumers.pop(req["cid"], None)
                        if c is not None:
                            c.close()
                        _send_frame(sock, {"ok": True})
                    elif op == "create_topic":
                        broker.create_topic(
                            req["topic"], int(req.get("partitions", 1)), req.get("config")
                        )
                        _send_frame(sock, {"ok": True})
                    elif op == "topic_exists":
                        _send_frame(sock, {"ok": True, "exists": broker.topic_exists(req["topic"])})
                    elif op == "delete_topic":
                        broker.delete_topic(req["topic"])
                        _send_frame(sock, {"ok": True})
                    elif op == "get_offsets":
                        offs = broker.get_offsets(req["group"], req["topic"])
                        _send_frame(sock, {"ok": True, "offsets": {str(k): v for k, v in offs.items()}})
                    elif op == "set_offsets":
                        broker.set_offsets(
                            req["group"], req["topic"],
                            {int(k): int(v) for k, v in req["offsets"].items()},
                        )
                        _send_frame(sock, {"ok": True})
                    elif op == "latest_offsets":
                        offs = broker.latest_offsets(req["topic"])
                        _send_frame(sock, {"ok": True, "offsets": {str(k): v for k, v in offs.items()}})
                    elif op == "ping":
                        _send_frame(sock, {"ok": True})
                    else:
                        _send_frame(sock, {"ok": False, "error": f"unknown op {op!r}"})
                except Exception as e:  # noqa: BLE001 - reported to client
                    log.warning("bus-serve op %s failed", op, exc_info=True)
                    try:
                        _send_frame(sock, {"ok": False, "error": f"{type(e).__name__}: {e}"})
                    except OSError:
                        return
        finally:
            for c in consumers.values():
                try:
                    c.close()
                except Exception:  # noqa: BLE001
                    pass


class BusServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, address: tuple[str, int], data_dir: str) -> None:
        super().__init__(address, _Handler)
        from oryx_tpu.bus.filebus import FileBroker

        self.broker = FileBroker(data_dir)
        self._client_socks: set = set()
        self._client_lock = threading.Lock()
        self._serve_thread: threading.Thread | None = None

    def shutdown(self):
        super().shutdown()
        # reap the background serve_forever thread started by serve();
        # shutdown() returns only after the loop exits, so this is quick
        t, self._serve_thread = self._serve_thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)

    # live-connection tracking: server_close() must sever established
    # client connections too, not just the listener — otherwise a
    # "stopped" server keeps serving through old handler threads and
    # clients never exercise their reconnect path
    def process_request(self, request, client_address):
        with self._client_lock:
            self._client_socks.add(request)
        super().process_request(request, client_address)

    def shutdown_request(self, request):
        with self._client_lock:
            self._client_socks.discard(request)
        super().shutdown_request(request)

    def server_close(self):
        super().server_close()
        with self._client_lock:
            socks = list(self._client_socks)
            self._client_socks.clear()
        for s in socks:
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass


def serve(host: str, port: int, data_dir: str) -> BusServer:
    """Start a bus server on a background thread; returns the server
    (call ``.shutdown()`` to stop). Blocking use: ``serve_forever`` on
    the returned object, which is what the CLI does."""
    server = BusServer((host, port), data_dir)
    t = threading.Thread(target=server.serve_forever, name="oryx-bus-serve", daemon=True)
    server._serve_thread = t
    t.start()
    log.info("bus server on %s:%d over %s", host, server.server_address[1], data_dir)
    return server


# ---------------------------------------------------------------------------
# Client
# ---------------------------------------------------------------------------


DEFAULT_CONNECT_TIMEOUT = 30.0


class _Conn:
    """One socket. The broker serializes requests per channel (strict
    request/response protocol) and owns reconnection, so this class is
    deliberately dumb: callers must hold the owning channel's lock."""

    def __init__(self, host: str, port: int, connect_timeout: float) -> None:
        self._host, self._port = host, port
        self._connect_timeout = connect_timeout
        self._sock: socket.socket | None = None

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def connect(self) -> None:
        self.drop()
        sock = socket.create_connection((self._host, self._port), timeout=self._connect_timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._sock = sock

    def call(self, header: dict, payload: bytes = b"") -> tuple[dict, bytes]:
        if self._sock is None:
            raise ConnectionError("not connected")
        try:
            _send_frame(self._sock, header, payload)
            resp, body = _recv_frame(self._sock)
        except (ConnectionError, OSError, struct.error):
            self.drop()
            raise
        if not resp.get("ok"):
            # a server-side op error: the connection itself is fine
            raise RuntimeError(f"bus server error: {resp.get('error')}")
        return resp, body

    def drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def close(self) -> None:
        self.drop()


class _NetProducer(TopicProducer):
    def __init__(self, broker: "NetBroker", topic: str) -> None:
        self._broker = broker
        self._topic = topic

    @property
    def update_broker(self) -> str:
        return self._broker.locator()

    @property
    def topic(self) -> str:
        return self._topic

    def send(self, key: str | None, message: str) -> None:
        self.send_many([(key, message)])

    def send_many(self, records: Iterable[tuple[str | None, str]]) -> int:
        from oryx_tpu.bus.blockcodec import encode_wire_lines

        n = 0
        # ship in bounded slices so one huge publish (a model) streams.
        # A slice retried after a reconnect may already have landed
        # server-side: at-least-once, like every broker here.
        for blob, count in encode_wire_lines(records, slice_bytes=8 << 20):
            self._broker._invoke(lambda: {"op": "produce", "topic": self._topic}, blob)
            n += count
        return n

    def close(self) -> None:
        pass


class _NetConsumer(TopicConsumer):
    """Client-side consumer handle over its own dedicated connection.

    A server-side poll blocks its connection for up to the poll timeout;
    on a shared socket that block would also stall every producer and
    admin call made through the same broker handle. Each consumer
    therefore owns a private socket and lock — its blocking polls never
    serialize against the broker's shared channel or other consumers.

    The handle remembers how it was opened and its last server-reported
    positions so the broker can transparently reopen and re-seek it after
    a reconnect of its own channel (server-side consumer sessions die
    with their connection)."""

    def __init__(
        self, broker: "NetBroker", conn: _Conn, topic: str, group: str | None,
        from_beginning: bool,
    ) -> None:
        self._broker = broker
        self._conn = conn
        self._lock = threading.RLock()
        self._cid = -1  # assigned by the first (re)open of the channel
        self._topic = topic
        self._group = group
        self._from_beginning = from_beginning
        self._last_positions: dict[int, int] | None = None
        self._closed = False
        from oryx_tpu.common import ledger

        ledger.register("consumer", self, live=lambda c: not c.closed())

    def poll(self, max_records: int = 1000, timeout: float = 0.1) -> list[KeyMessage]:
        block = self.poll_block(max_records, timeout)
        if block is None:
            return []
        return list(block.iter_key_messages())

    def poll_block(self, max_records: int = 1000, timeout: float = 0.1):
        from oryx_tpu.bus.blockcodec import lines_to_block
        from oryx_tpu.common.records import RecordBlock

        resp, blob = self._broker._invoke(
            lambda: {"op": "poll", "cid": self._cid, "max_records": max_records, "timeout": timeout},
            consumer=self,
        )
        if "positions" in resp:
            # _invoke released the channel lock on return; retake it for
            # the cache write — the broker's reopen path reads
            # _last_positions to replay the seek (audit alongside the
            # baselined lockset ORX103 on _cid)
            with self._lock:
                self._last_positions = {
                    int(k): int(v) for k, v in resp["positions"].items()
                }
        if not blob:
            return None
        return lines_to_block(blob.split(b"\n")[:-1], RecordBlock)

    def positions(self) -> dict[int, int]:
        resp, _ = self._broker._invoke(
            lambda: {"op": "positions", "cid": self._cid}, consumer=self
        )
        pos = {int(k): int(v) for k, v in resp["positions"].items()}
        with self._lock:
            self._last_positions = dict(pos)
        return pos

    def seek(self, positions: dict[int, int]) -> None:
        self._broker._invoke(
            lambda: {
                "op": "seek",
                "cid": self._cid,
                "positions": {str(k): int(v) for k, v in positions.items()},
            },
            consumer=self,
        )
        with self._lock:
            merged = dict(self._last_positions or {})
            merged.update({int(k): int(v) for k, v in positions.items()})
            self._last_positions = merged

    def commit(self) -> None:
        self._broker._invoke(lambda: {"op": "commit", "cid": self._cid}, consumer=self)

    def close(self) -> None:
        with self._lock:  # check-then-set must be one atomic step
            if self._closed:
                return
            self._closed = True
            try:
                # best-effort, no reconnect dance just to close
                if self._conn.connected:
                    self._conn.call({"op": "consumer_close", "cid": self._cid})
            except (RuntimeError, ConnectionError, OSError):
                pass
            self._conn.close()

    def closed(self) -> bool:
        with self._lock:
            return self._closed


class NetBroker(Broker):
    """Broker SPI over a ``tcp://host:port`` bus server, with
    reconnect-with-backoff and a dedicated connection per consumer.

    Producers and admin ops share one channel (socket + lock); every
    consumer owns its own, so a consumer blocked in a server-side poll
    (up to the poll timeout) never stalls produces or other consumers on
    the same broker handle. Connections are opened lazily and re-opened
    on demand: any call that hits a connection error retries under
    `retry` (a RetryPolicy), and a successful reconnect of a consumer's
    channel reopens that consumer server-side and seeks it to its last
    known positions, so consumption resumes mid-stream across a
    bus-server restart. Produce retries are at-least-once (a request that
    died in flight may have landed)."""

    def __init__(
        self,
        host: str,
        port: int,
        connect_timeout: float = DEFAULT_CONNECT_TIMEOUT,
        retry: "RetryPolicy | None" = None,
    ) -> None:
        from oryx_tpu.common.resilience import RetryPolicy

        self._host, self._port = host, port
        self._connect_timeout = connect_timeout
        self._conn = _Conn(host, port, connect_timeout)
        self._retry = retry or RetryPolicy(
            max_attempts=5, initial_backoff=0.1, max_backoff=5.0
        )
        self._io_lock = threading.RLock()

    @staticmethod
    def options_from_query(query: str) -> dict:
        """Constructor kwargs from tcp:// locator query params:
        connect_timeout (seconds), retry_max_attempts,
        retry_initial_backoff_ms, retry_max_backoff_ms."""
        from urllib.parse import parse_qsl

        from oryx_tpu.common.resilience import RetryPolicy

        params = dict(parse_qsl(query, keep_blank_values=True))
        opts: dict = {}
        if "connect_timeout" in params:
            opts["connect_timeout"] = float(params["connect_timeout"])
        retry_kw: dict = {}
        if "retry_max_attempts" in params:
            retry_kw["max_attempts"] = int(params["retry_max_attempts"])
        if "retry_initial_backoff_ms" in params:
            retry_kw["initial_backoff"] = float(params["retry_initial_backoff_ms"]) / 1000.0
        if "retry_max_backoff_ms" in params:
            retry_kw["max_backoff"] = float(params["retry_max_backoff_ms"]) / 1000.0
        if retry_kw:
            opts["retry"] = RetryPolicy(**retry_kw)
        return opts

    def locator(self) -> str:
        return f"tcp://{self._host}:{self._port}"

    # -- connection management ----------------------------------------------

    def _open_consumer_session(self, c: _NetConsumer) -> None:
        """Caller holds the consumer's lock and its connection is up.
        (Re)open the server-side session on the consumer's own channel
        and seek it back to its last known positions."""
        resp, _ = c._conn.call(
            {
                "op": "consumer_open",
                "topic": c._topic,
                "group": c._group,
                "from_beginning": c._from_beginning,
            }
        )
        c._cid = int(resp["cid"])
        if c._last_positions:
            c._conn.call(
                {
                    "op": "seek",
                    "cid": c._cid,
                    "positions": {str(k): int(v) for k, v in c._last_positions.items()},
                }
            )

    def _invoke(self, header_fn, payload: bytes = b"", consumer: _NetConsumer | None = None):
        """Run one request, transparently (re)connecting with backoff.

        Routed over the consumer's dedicated channel when `consumer` is
        given (reconnects there also reopen that one server-side
        session), else over the shared producer/admin channel.
        `header_fn` is re-evaluated per attempt so consumer ops pick up
        the cid assigned by a reconnect's reopen; ``header_fn=None`` just
        ensures the channel is connected (used for the eager first open)."""
        conn = self._conn if consumer is None else consumer._conn
        lock = self._io_lock if consumer is None else consumer._lock
        failures = 0
        with lock:
            while True:
                try:
                    if not conn.connected:
                        conn.connect()
                        if consumer is not None:
                            self._open_consumer_session(consumer)
                        if failures:
                            metrics.registry.counter("bus.net.reconnects").inc()
                    if header_fn is None:
                        return None, b""
                    return conn.call(header_fn(), payload)
                except (ConnectionError, OSError) as e:
                    conn.drop()
                    if consumer is not None and consumer.closed():
                        raise
                    failures += 1
                    delay = self._retry.backoff_or_none(failures)
                    if delay is None:
                        metrics.registry.counter("bus.net.reconnect-failures").inc()
                        raise ConnectionError(
                            f"bus server {self._host}:{self._port} unreachable "
                            f"after {failures} attempts: {e}"
                        ) from e
                    log.warning(
                        "bus connection to %s:%d failed (%s); retry %d in %.2fs",
                        self._host, self._port, e, failures, delay,
                    )
                    time.sleep(delay)

    # -- Broker SPI ----------------------------------------------------------

    def create_topic(self, topic: str, partitions: int = 1, config: dict | None = None) -> None:
        self._invoke(
            lambda: {"op": "create_topic", "topic": topic, "partitions": partitions, "config": config}
        )

    def topic_exists(self, topic: str) -> bool:
        resp, _ = self._invoke(lambda: {"op": "topic_exists", "topic": topic})
        return bool(resp["exists"])

    def delete_topic(self, topic: str) -> None:
        self._invoke(lambda: {"op": "delete_topic", "topic": topic})

    def producer(self, topic: str) -> TopicProducer:
        return _NetProducer(self, topic)

    def consumer(
        self, topic: str, group: str | None = None, from_beginning: bool = False,
        partitions: list[int] | None = None,
    ) -> TopicConsumer:
        if partitions is not None:
            raise ValueError(
                "tcp:// consumers do not support manual partition assignment"
            )
        c = _NetConsumer(
            self,
            _Conn(self._host, self._port, self._connect_timeout),
            topic, group, from_beginning,
        )
        # open the dedicated channel + server session eagerly so a bad
        # topic/server fails here (with retry/backoff), not at first poll
        self._invoke(None, consumer=c)
        return c

    def get_offsets(self, group: str, topic: str) -> dict[int, int]:
        resp, _ = self._invoke(lambda: {"op": "get_offsets", "group": group, "topic": topic})
        return {int(k): int(v) for k, v in resp["offsets"].items()}

    def set_offsets(self, group: str, topic: str, offsets: dict[int, int]) -> None:
        self._invoke(
            lambda: {"op": "set_offsets", "group": group, "topic": topic,
                     "offsets": {str(k): int(v) for k, v in offsets.items()}}
        )

    def latest_offsets(self, topic: str) -> dict[int, int]:
        resp, _ = self._invoke(lambda: {"op": "latest_offsets", "topic": topic})
        return {int(k): int(v) for k, v in resp["offsets"].items()}
