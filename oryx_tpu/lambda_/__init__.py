"""Lambda-tier runtimes: the batch and speed layer processes.

Rebuild of framework/oryx-lambda (SURVEY.md §2.4): interval-driven batch
model rebuilds over all historical data, and micro-batch incremental speed
updates, both fed from the input topic and publishing to the update topic.
"""

from oryx_tpu.lambda_.batch import BatchLayer  # noqa: F401
from oryx_tpu.lambda_.speed import SpeedLayer  # noqa: F401
