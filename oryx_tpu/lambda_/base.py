"""Shared layer base: config parsing, topic init, input consumption.

Rebuild of AbstractSparkLayer (framework/oryx-lambda/.../AbstractSparkLayer
.java:57-254): parses id/topics/interval from config, builds the input
stream starting from stored group offsets (the reference reads them from
ZooKeeper; here from the bus's offset ledger).
"""

from __future__ import annotations

import logging
import threading
from typing import Iterator

from oryx_tpu.bus.core import Broker, KeyMessage, TopicConsumer, get_broker
from oryx_tpu.common import ledger, metrics
from oryx_tpu.common.config import Config
from oryx_tpu.common.resilience import RetryPolicy, SupervisedThread

log = logging.getLogger(__name__)


class AbstractLayer:
    def __init__(self, config: Config, layer_name: str) -> None:
        self.config = config
        self.layer_name = layer_name
        self.id = config.get_optional_string("oryx.id")
        self.input_broker_loc = config.get_string("oryx.input-topic.broker")
        self.input_topic = config.get_string("oryx.input-topic.message.topic")
        self.input_partitions = config.get_optional_int("oryx.input-topic.message.partitions") or 1
        self.update_broker_loc = config.get_optional_string("oryx.update-topic.broker")
        self.update_topic = config.get_optional_string("oryx.update-topic.message.topic")
        self.update_partitions = config.get_optional_int("oryx.update-topic.message.partitions") or 1
        self.generation_interval_sec = config.get_int(
            f"oryx.{layer_name}.streaming.generation-interval-sec"
        )
        # consumer group: "OryxGroup-<LayerName>[-<id>]"
        # (AbstractSparkLayer.java:108-116); without oryx.id there is no
        # durable identity so offsets are not persisted and consumption
        # starts at latest (reference.conf:14-20 comment).
        self.group_id = f"OryxGroup-{layer_name}" + (f"-{self.id}" if self.id else "")
        self._stop_event = threading.Event()
        self._input_broker: Broker | None = None
        self._update_broker: Broker | None = None
        # resilience: every long-lived thread in a layer runs supervised
        # (restart with backoff under oryx.<layer>.retry.*, give up after
        # max-attempts consecutive failures -> the layer reports unhealthy)
        self.retry_policy = RetryPolicy.from_config(config, f"oryx.{layer_name}.retry")
        self._supervised: list[SupervisedThread] = []
        # multi-host: join the JAX multi-controller runtime before any
        # backend is touched, so jax.devices() spans the whole pod slice
        # (no-op unless oryx.batch.compute.distributed.* is configured)
        from oryx_tpu.parallel.distributed import (
            maybe_enable_compile_cache,
            maybe_initialize,
        )

        maybe_initialize(config)
        maybe_enable_compile_cache(config)

    # -- topics -------------------------------------------------------------

    def input_broker(self) -> Broker:
        # one broker handle per layer: a file broker is cheap to rebuild,
        # but tcp:// holds a live socket and kafka:// a client with
        # metadata — per-micro-batch reconstruction would churn a
        # connection (and defeat producer batching) every generation
        if self._input_broker is None:
            self._input_broker = get_broker(self.input_broker_loc)
        return self._input_broker

    def update_broker(self) -> Broker | None:
        if self.update_broker_loc and self.update_topic:
            if self._update_broker is None:
                self._update_broker = get_broker(self.update_broker_loc)
            return self._update_broker
        return None

    def init_topics(self) -> None:
        """Create topics if missing (the reference delegates this to
        `oryx-run.sh kafka-setup`; layers here do it on startup for
        operational simplicity)."""
        self.input_broker().create_topic(self.input_topic, self.input_partitions)
        ub = self.update_broker()
        if ub is not None:
            ub.create_topic(self.update_topic, self.update_partitions)

    def make_input_consumer(self, partitions: list[int] | None = None) -> TopicConsumer:
        """Input consumer resuming from stored offsets when oryx.id is set
        (AbstractSparkLayer.buildInputDStream:179-252). `partitions`
        restricts the consumer to a subset of input partitions (the sharded
        speed-pipeline path); commits of disjoint subsets merge in the
        offset ledger, so shards sharing a group never clobber each other."""
        return self.input_broker().consumer(
            self.input_topic,
            group=self.group_id if self.id else None,
            partitions=partitions,
        )

    # -- lifecycle ----------------------------------------------------------

    def maybe_start_ui(self) -> None:
        """Status/metrics HTTP endpoint on ``oryx.<layer>.ui.port`` (the
        reference exposes the Spark UI on these ports, reference.conf
        batch/speed ui.port; here it serves the metrics registry and a
        one-line status as JSON). No-op when the port is null."""
        port = self.config.get(f"oryx.{self.layer_name}.ui.port", None)
        if (
            port is None
            or getattr(self, "_ui_server", None) is not None
            or getattr(self, "_ui_thread", None) is not None
        ):
            return
        # loopback by default: the endpoint has no auth (the reference's
        # Spark UI bound 0.0.0.0 unauthenticated; metrics scrapers that
        # need remote access opt in via ui.bind-address)
        host = self.config.get(f"oryx.{self.layer_name}.ui.bind-address", None) or "127.0.0.1"
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from oryx_tpu.common import metrics as _metrics

        layer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib contract
                if self.path not in ("/", "/metrics", "/status", "/healthz"):
                    self.send_error(404)
                    return
                healthy = layer.healthy()
                if self.path == "/healthz":
                    body = {"healthy": healthy, "layer": layer.layer_name}
                    status = 200 if healthy else 503
                else:
                    if ledger.enabled():
                        ledger.ledger.refresh()
                    body = dict(_metrics.registry.snapshot())
                    body["layer"] = {
                        "type": "status",
                        "name": layer.layer_name,
                        "id": layer.id,
                        "stopped": layer.is_stopped(),
                        "healthy": healthy,
                    }
                    status = 200
                data = _json.dumps(body, indent=1).encode("utf-8")
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # quiet: it's a metrics scrape target
                pass

        srv = ThreadingHTTPServer((host, int(port)), Handler)
        self._ui_server = srv
        self.ui_port = srv.server_address[1]  # resolved (port 0 = ephemeral)
        t = threading.Thread(target=srv.serve_forever, name=f"{self.layer_name}-ui", daemon=True)
        self._ui_thread = t
        t.start()
        ledger.register("thread", t, live=threading.Thread.is_alive)

    def supervise(
        self, name: str, target, *, loop: bool = False, metrics_prefix: str | None = None,
        on_failure=None,
    ) -> SupervisedThread:
        """Start a supervised daemon thread under this layer's retry
        policy; it counts toward `healthy()`."""
        t = SupervisedThread(
            name,
            target,
            self.retry_policy,
            self._stop_event,
            loop=loop,
            metrics_prefix=metrics_prefix or f"{self.layer_name}.{name}",
            on_failure=on_failure,
        )
        self._supervised.append(t)
        t.start()
        return t

    def healthy(self) -> bool:
        """False once any supervised thread has exhausted its restart
        policy and given up."""
        return all(t.healthy for t in self._supervised)

    def is_stopped(self) -> bool:
        return self._stop_event.is_set()

    def await_termination(self, timeout: float | None = None) -> None:
        self._stop_event.wait(timeout)

    def join_or_report_leak(self, *threads, timeout: float = 10.0) -> None:
        """Join each thread; one that outlives the timeout is logged and
        counted in `layer.threads.leaked` instead of silently abandoned."""
        for t in threads:
            if t is None:
                continue
            t.join(timeout=timeout)
            if t.is_alive():
                name = getattr(t, "name", repr(t))
                log.warning(
                    "%s layer thread %r still alive after %.0fs join; leaking it",
                    self.layer_name, name, timeout,
                )
                metrics.registry.counter("layer.threads.leaked").inc()

    def close(self) -> None:
        self._stop_event.set()
        srv = getattr(self, "_ui_server", None)
        if srv is not None:
            srv.shutdown()
            srv.server_close()
            self._ui_server = None
        t = getattr(self, "_ui_thread", None)
        if t is not None:
            self._ui_thread = None
            self.join_or_report_leak(t)


def blocking_iterator(consumer: TopicConsumer, stop_event: threading.Event) -> Iterator[KeyMessage]:
    """Endless KeyMessage iterator over a consumer, ending on close/stop."""
    while not stop_event.is_set() and not consumer.closed():
        for rec in consumer.poll(timeout=0.2):
            yield rec


class GuardedBlockFeed:
    """A restartable block feed with poison-message quarantine.

    Wraps a consumer for use under a SupervisedThread: call `blocks()` for
    a FRESH generator on every (re)start, and `record_failure` from the
    supervisor's failure hook. A block that was mid-consume when the
    manager raised is retried on restart; after `max_failures` failures of
    the SAME block it is published to the dead-letter topic instead and
    the stream moves on. A failure with no block in flight (the poll
    itself raised — broker outage) is not counted against any block.
    """

    def __init__(
        self,
        consumer: TopicConsumer,
        stop_event: threading.Event,
        max_failures: int,
        dead_letter,
        on_block=None,
    ) -> None:
        self._consumer = consumer
        self._stop_event = stop_event
        self._max_failures = max(1, max_failures)
        self._dead_letter = dead_letter  # callable(block) -> None
        self._on_block = on_block  # callable(block) after each successful poll
        self._in_flight = None
        self._pending_retry = None
        self._failures = 0

    def blocks(self):
        """A fresh generator; an abandoned predecessor (after a failure)
        holds no state — everything lives on the feed object."""
        while not self._stop_event.is_set() and not self._consumer.closed():
            if self._pending_retry is not None:
                block = self._pending_retry
                self._pending_retry = None
            else:
                block = self._consumer.poll_block(max_records=10_000, timeout=0.2)
                if block is None:
                    continue
                if self._on_block is not None:
                    self._on_block(block)
            self._in_flight = block
            yield block
            # reaching here means the manager pulled the next block: the
            # previous one was fully consumed (on a failure the generator
            # is abandoned at the yield and these lines never run)
            self._in_flight = None
            self._failures = 0

    def record_failure(self, exc: BaseException) -> None:
        """Supervisor failure hook (same thread as the consume loop)."""
        block = self._in_flight
        self._in_flight = None
        if block is None:
            return  # poll-side failure; nothing to quarantine
        self._failures += 1
        if self._failures >= self._max_failures:
            self._failures = 0
            log.error(
                "block of %d update record(s) failed consume %d times (%s); dead-lettering",
                len(block), self._max_failures, exc,
            )
            try:
                self._dead_letter(block)
            except Exception:  # noqa: BLE001 - a DL failure must not kill the stream
                log.exception("dead-letter publish failed; block lost")
        else:
            self._pending_retry = block


def blocking_block_iterator(consumer: TopicConsumer, stop_event: threading.Event):
    """Endless RecordBlock iterator over a consumer (columnar poll),
    ending on close/stop. The high-rate twin of blocking_iterator: model
    consumers that can apply whole blocks at once (vectorized UP parsing)
    drain the update topic without per-record decoding."""
    while not stop_event.is_set() and not consumer.closed():
        block = consumer.poll_block(max_records=10_000, timeout=0.2)
        if block is not None:
            yield block
