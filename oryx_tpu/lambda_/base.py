"""Shared layer base: config parsing, topic init, input consumption.

Rebuild of AbstractSparkLayer (framework/oryx-lambda/.../AbstractSparkLayer
.java:57-254): parses id/topics/interval from config, builds the input
stream starting from stored group offsets (the reference reads them from
ZooKeeper; here from the bus's offset ledger).
"""

from __future__ import annotations

import logging
import threading
from typing import Iterator

from oryx_tpu.bus.core import Broker, KeyMessage, TopicConsumer, get_broker
from oryx_tpu.common.config import Config

log = logging.getLogger(__name__)


class AbstractLayer:
    def __init__(self, config: Config, layer_name: str) -> None:
        self.config = config
        self.layer_name = layer_name
        self.id = config.get_optional_string("oryx.id")
        self.input_broker_loc = config.get_string("oryx.input-topic.broker")
        self.input_topic = config.get_string("oryx.input-topic.message.topic")
        self.input_partitions = config.get_optional_int("oryx.input-topic.message.partitions") or 1
        self.update_broker_loc = config.get_optional_string("oryx.update-topic.broker")
        self.update_topic = config.get_optional_string("oryx.update-topic.message.topic")
        self.update_partitions = config.get_optional_int("oryx.update-topic.message.partitions") or 1
        self.generation_interval_sec = config.get_int(
            f"oryx.{layer_name}.streaming.generation-interval-sec"
        )
        # consumer group: "OryxGroup-<LayerName>[-<id>]"
        # (AbstractSparkLayer.java:108-116); without oryx.id there is no
        # durable identity so offsets are not persisted and consumption
        # starts at latest (reference.conf:14-20 comment).
        self.group_id = f"OryxGroup-{layer_name}" + (f"-{self.id}" if self.id else "")
        self._stop_event = threading.Event()
        self._input_broker: Broker | None = None
        self._update_broker: Broker | None = None
        # multi-host: join the JAX multi-controller runtime before any
        # backend is touched, so jax.devices() spans the whole pod slice
        # (no-op unless oryx.batch.compute.distributed.* is configured)
        from oryx_tpu.parallel.distributed import (
            maybe_enable_compile_cache,
            maybe_initialize,
        )

        maybe_initialize(config)
        maybe_enable_compile_cache(config)

    # -- topics -------------------------------------------------------------

    def input_broker(self) -> Broker:
        # one broker handle per layer: a file broker is cheap to rebuild,
        # but tcp:// holds a live socket and kafka:// a client with
        # metadata — per-micro-batch reconstruction would churn a
        # connection (and defeat producer batching) every generation
        if self._input_broker is None:
            self._input_broker = get_broker(self.input_broker_loc)
        return self._input_broker

    def update_broker(self) -> Broker | None:
        if self.update_broker_loc and self.update_topic:
            if self._update_broker is None:
                self._update_broker = get_broker(self.update_broker_loc)
            return self._update_broker
        return None

    def init_topics(self) -> None:
        """Create topics if missing (the reference delegates this to
        `oryx-run.sh kafka-setup`; layers here do it on startup for
        operational simplicity)."""
        self.input_broker().create_topic(self.input_topic, self.input_partitions)
        ub = self.update_broker()
        if ub is not None:
            ub.create_topic(self.update_topic, self.update_partitions)

    def make_input_consumer(self) -> TopicConsumer:
        """Input consumer resuming from stored offsets when oryx.id is set
        (AbstractSparkLayer.buildInputDStream:179-252)."""
        return self.input_broker().consumer(
            self.input_topic,
            group=self.group_id if self.id else None,
        )

    # -- lifecycle ----------------------------------------------------------

    def maybe_start_ui(self) -> None:
        """Status/metrics HTTP endpoint on ``oryx.<layer>.ui.port`` (the
        reference exposes the Spark UI on these ports, reference.conf
        batch/speed ui.port; here it serves the metrics registry and a
        one-line status as JSON). No-op when the port is null."""
        port = self.config.get(f"oryx.{self.layer_name}.ui.port", None)
        if port is None or getattr(self, "_ui_server", None) is not None:
            return
        # loopback by default: the endpoint has no auth (the reference's
        # Spark UI bound 0.0.0.0 unauthenticated; metrics scrapers that
        # need remote access opt in via ui.bind-address)
        host = self.config.get(f"oryx.{self.layer_name}.ui.bind-address", None) or "127.0.0.1"
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        from oryx_tpu.common import metrics as _metrics

        layer = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 - stdlib contract
                if self.path not in ("/", "/metrics", "/status"):
                    self.send_error(404)
                    return
                body = dict(_metrics.registry.snapshot())
                body["layer"] = {
                    "type": "status",
                    "name": layer.layer_name,
                    "id": layer.id,
                    "stopped": layer.is_stopped(),
                }
                data = _json.dumps(body, indent=1).encode("utf-8")
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def log_message(self, *a):  # quiet: it's a metrics scrape target
                pass

        srv = ThreadingHTTPServer((host, int(port)), Handler)
        self._ui_server = srv
        self.ui_port = srv.server_address[1]  # resolved (port 0 = ephemeral)
        t = threading.Thread(target=srv.serve_forever, name=f"{self.layer_name}-ui", daemon=True)
        t.start()

    def is_stopped(self) -> bool:
        return self._stop_event.is_set()

    def await_termination(self, timeout: float | None = None) -> None:
        self._stop_event.wait(timeout)

    def close(self) -> None:
        self._stop_event.set()
        srv = getattr(self, "_ui_server", None)
        if srv is not None:
            srv.shutdown()
            srv.server_close()
            self._ui_server = None


def blocking_iterator(consumer: TopicConsumer, stop_event: threading.Event) -> Iterator[KeyMessage]:
    """Endless KeyMessage iterator over a consumer, ending on close/stop."""
    while not stop_event.is_set() and not consumer.closed():
        for rec in consumer.poll(timeout=0.2):
            yield rec


def blocking_block_iterator(consumer: TopicConsumer, stop_event: threading.Event):
    """Endless RecordBlock iterator over a consumer (columnar poll),
    ending on close/stop. The high-rate twin of blocking_iterator: model
    consumers that can apply whole blocks at once (vectorized UP parsing)
    drain the update topic without per-record decoding."""
    while not stop_event.is_set() and not consumer.closed():
        block = consumer.poll_block(max_records=10_000, timeout=0.2)
        if block is not None:
            yield block
