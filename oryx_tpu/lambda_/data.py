"""Historical-data storage for the batch layer.

Rebuild of SaveToHDFSFunction (framework/oryx-lambda/.../batch/
SaveToHDFSFunction.java:31-77: append each non-empty micro-batch as
``dataDir/oryx-<timestampMs>.data``), the past-data re-read in
BatchUpdateFunction.java:103-130, and age-based GC in
DeleteOldDataFn.java:38-78 (timestamp parsed from the file/dir name).

Records are JSON lines ``{"k": key, "m": message}`` — the plain-file
equivalent of the reference's Hadoop SequenceFile<Text,Text>.

Directories are URIs: plain paths use the local filesystem, `gs://` /
`memory://` etc. route through the object-store backend
(oryx_tpu.common.storage) — the HDFS-parity piece that lets every host
of a multi-host deployment share one data/model store.
"""

from __future__ import annotations

import json
import re
import time
from pathlib import Path
from typing import Iterator

import numpy as np

from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common import storage
from oryx_tpu.common.records import RecordBlock, Records

_DATA_FILE_RE = re.compile(r"^oryx-(\d+)\.(data|npz)$")
_MODEL_DIR_RE = re.compile(r"^(\d+)$")


def save_micro_batch(
    data_dir: str | Path,
    timestamp_ms: int,
    records: list[KeyMessage],
    fmt: str = "npz",
) -> str | None:
    """Append one micro-batch; empty batches write nothing
    (SaveToHDFSFunction.java:60-66).

    fmt "npz" (default) stores columnar numpy byte-string arrays — the
    binary analogue of the reference's SequenceFile<Text,Text>, read back
    as whole arrays with zero per-record Python. fmt "jsonl" keeps the
    line-per-record text form (`.data`); both are read transparently."""
    if not records:
        return None
    storage.mkdirs(data_dir)
    if fmt == "npz":
        path = storage.join(data_dir, f"oryx-{timestamp_ms}.npz")
        block = RecordBlock.from_key_messages(records)
        arrays = {"messages": block.messages}
        if block.keys is not None:
            arrays["keys"] = block.keys
        if block.none_keys is not None:
            arrays["none_keys"] = block.none_keys
        with storage.open_write(path, "wb") as f:
            np.savez_compressed(f, **arrays)
        return path
    if fmt != "jsonl":
        raise ValueError(f"unknown micro-batch format {fmt!r} (want npz or jsonl)")
    path = storage.join(data_dir, f"oryx-{timestamp_ms}.data")
    with storage.open_write(path, "wb") as f:
        for rec in records:
            f.write(
                (json.dumps({"k": rec.key, "m": rec.message}, separators=(",", ":")) + "\n").encode("utf-8")
            )
    return path


def _data_file_names(data_dir: str | Path) -> list[str]:
    names = [n for n in storage.list_names(data_dir) if _DATA_FILE_RE.match(n)]
    names.sort(key=lambda n: int(_DATA_FILE_RE.match(n).group(1)))
    return names


def _read_block(path) -> RecordBlock:
    if str(path).endswith(".npz"):
        with storage.open_read(path, "rb") as f:
            with np.load(f, allow_pickle=False) as z:
                return RecordBlock(
                    z["keys"] if "keys" in z else None,
                    z["messages"],
                    z["none_keys"] if "none_keys" in z else None,
                )
    records: list[KeyMessage] = []
    with storage.open_read(path, "rb") as f:
        for line in f:
            line = line.strip()
            if line:
                rec = json.loads(line)
                records.append(KeyMessage(rec.get("k"), rec.get("m", "")))
    if not records:
        return RecordBlock(None, np.empty(0, dtype="S1"))
    return RecordBlock.from_key_messages(records)


class FileRecords(Records):
    """Lazy view over a data dir's surviving micro-batches, oldest first:
    one stored block in memory at a time (the re-read path of
    BatchUpdateFunction.java:103-130, without materializing history)."""

    def __init__(self, data_dir: str | Path) -> None:
        self._dir = data_dir

    def is_empty(self) -> bool:
        return not _data_file_names(self._dir)

    def blocks(self) -> Iterator[RecordBlock]:
        for name in _data_file_names(self._dir):
            yield _read_block(storage.join(self._dir, name))


def read_past_data(data_dir: str | Path) -> Iterator[KeyMessage]:
    """Stream all surviving historical records, oldest file first."""
    return iter(FileRecords(data_dir))


def delete_old_data(
    data_dir: str | Path, max_age_hours: int, now_ms: int | None = None
) -> list[str]:
    """Delete data files older than max_age_hours; -1 disables
    (DeleteOldDataFn.java:54-74)."""
    return _delete_old(data_dir, _DATA_FILE_RE, max_age_hours, now_ms, recursive=False)


def delete_old_models(
    model_dir: str | Path, max_age_hours: int, now_ms: int | None = None
) -> list[str]:
    """Delete versioned model dirs (named <timestampMs>) older than
    max_age_hours; -1 disables."""
    return _delete_old(model_dir, _MODEL_DIR_RE, max_age_hours, now_ms, recursive=True)


def _delete_old(
    root: str | Path,
    pattern: re.Pattern,
    max_age_hours: int,
    now_ms: int | None,
    recursive: bool,
) -> list[str]:
    if max_age_hours < 0:
        return []
    cutoff = (time.time() * 1000 if now_ms is None else now_ms) - max_age_hours * 3600_000
    deleted = []
    for name in storage.list_names(root):
        m = pattern.match(name)
        if m and int(m.group(1)) < cutoff:
            target = storage.join(root, name)
            storage.delete(target, recursive=recursive)
            deleted.append(target)
    return deleted
