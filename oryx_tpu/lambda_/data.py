"""Historical-data storage for the batch layer.

Rebuild of SaveToHDFSFunction (framework/oryx-lambda/.../batch/
SaveToHDFSFunction.java:31-77: append each non-empty micro-batch as
``dataDir/oryx-<timestampMs>.data``), the past-data re-read in
BatchUpdateFunction.java:103-130, and age-based GC in
DeleteOldDataFn.java:38-78 (timestamp parsed from the file/dir name).

Records are JSON lines ``{"k": key, "m": message}`` — the plain-file
equivalent of the reference's Hadoop SequenceFile<Text,Text>.
"""

from __future__ import annotations

import json
import re
import shutil
import time
from pathlib import Path
from typing import Iterable, Iterator

from oryx_tpu.bus.core import KeyMessage

_DATA_FILE_RE = re.compile(r"^oryx-(\d+)\.data$")
_MODEL_DIR_RE = re.compile(r"^(\d+)$")


def save_micro_batch(data_dir: str | Path, timestamp_ms: int, records: list[KeyMessage]) -> Path | None:
    """Append one micro-batch; empty batches write nothing
    (SaveToHDFSFunction.java:60-66)."""
    if not records:
        return None
    d = Path(data_dir)
    d.mkdir(parents=True, exist_ok=True)
    path = d / f"oryx-{timestamp_ms}.data"
    tmp = d / f".oryx-{timestamp_ms}.data.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        for rec in records:
            f.write(json.dumps({"k": rec.key, "m": rec.message}, separators=(",", ":")) + "\n")
    tmp.replace(path)
    return path


def read_past_data(data_dir: str | Path) -> Iterator[KeyMessage]:
    """Stream all surviving historical records, oldest file first."""
    d = Path(data_dir)
    if not d.is_dir():
        return
    files = sorted(
        (p for p in d.iterdir() if _DATA_FILE_RE.match(p.name)),
        key=lambda p: int(_DATA_FILE_RE.match(p.name).group(1)),
    )
    for path in files:
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    rec = json.loads(line)
                    yield KeyMessage(rec.get("k"), rec.get("m", ""))


def delete_old_data(data_dir: str | Path, max_age_hours: int, now_ms: int | None = None) -> list[Path]:
    """Delete data files older than max_age_hours; -1 disables
    (DeleteOldDataFn.java:54-74)."""
    return _delete_old(data_dir, _DATA_FILE_RE, max_age_hours, now_ms)


def delete_old_models(model_dir: str | Path, max_age_hours: int, now_ms: int | None = None) -> list[Path]:
    """Delete versioned model dirs (named <timestampMs>) older than
    max_age_hours; -1 disables."""
    return _delete_old(model_dir, _MODEL_DIR_RE, max_age_hours, now_ms)


def _delete_old(root: str | Path, pattern: re.Pattern, max_age_hours: int, now_ms: int | None) -> list[Path]:
    if max_age_hours < 0:
        return []
    d = Path(root)
    if not d.is_dir():
        return []
    cutoff = (time.time() * 1000 if now_ms is None else now_ms) - max_age_hours * 3600_000
    deleted = []
    for p in d.iterdir():
        m = pattern.match(p.name)
        if m and int(m.group(1)) < cutoff:
            if p.is_dir():
                shutil.rmtree(p, ignore_errors=True)
            else:
                p.unlink(missing_ok=True)
            deleted.append(p)
    return deleted
