"""Speed layer runtime.

Rebuild of SpeedLayer + SpeedLayerUpdate (framework/oryx-lambda/.../speed/
SpeedLayer.java:56-214, SpeedLayerUpdate.java:37-66; call stack §3.2):

- a dedicated thread consumes the update topic **from the beginning**
  (the replay-from-zero recovery story, SpeedLayer.java:107-121) feeding
  the configured SpeedModelManager.consume;
- every generation interval, the input micro-batch is handed to
  manager.build_updates and each returned delta is published to the update
  topic with key "UP".
"""

from __future__ import annotations

import logging
import threading
import time

from oryx_tpu.common.records import BlockRecords
from oryx_tpu.common import metrics, profiling
from oryx_tpu.common.config import Config
from oryx_tpu.common.lang import load_instance_of
from oryx_tpu.lambda_.base import AbstractLayer, blocking_block_iterator

log = logging.getLogger(__name__)


class SpeedLayer(AbstractLayer):
    def __init__(self, config: Config) -> None:
        super().__init__(config, "speed")
        self.model_manager_class = config.get_string("oryx.speed.model-manager-class")
        self.max_batch_events = config.get_int("oryx.speed.streaming.max-batch-events")
        self.manager = load_instance_of(self.model_manager_class, config)
        self._input_consumer = None
        self._update_consumer = None
        self._consume_thread: threading.Thread | None = None
        self._batch_thread: threading.Thread | None = None
        self._batch_count = 0

    def prepare_input(self) -> None:
        """Attach the input consumer; from this point input is observed."""
        if self._input_consumer is None:
            self._input_consumer = self.make_input_consumer()

    def start(self) -> None:
        self.init_topics()
        self.maybe_start_ui()
        ub = self.update_broker()
        if ub is None:
            raise ValueError("speed layer requires an update topic")
        self._update_consumer = ub.consumer(self.update_topic, from_beginning=True)
        self._consume_thread = threading.Thread(
            target=self._consume_updates, name="SpeedLayerUpdateConsumer", daemon=True
        )
        self._consume_thread.start()
        self.prepare_input()
        self._batch_thread = threading.Thread(target=self._loop, name="SpeedLayer", daemon=True)
        self._batch_thread.start()
        log.info(
            "SpeedLayer started: interval=%ss manager=%s",
            self.generation_interval_sec,
            self.model_manager_class,
        )

    def close(self) -> None:
        super().close()
        for c in (self._input_consumer, self._update_consumer):
            if c is not None:
                c.close()
        for t in (self._consume_thread, self._batch_thread):
            if t is not None:
                t.join(timeout=10)
        self.manager.close()

    @property
    def batch_count(self) -> int:
        return self._batch_count

    # -- internals ----------------------------------------------------------

    def _consume_updates(self) -> None:
        try:
            self.manager.consume_blocks(
                blocking_block_iterator(self._update_consumer, self._stop_event)
            )
        except Exception:
            if not self.is_stopped():
                log.exception("speed model consume thread failed")

    def _loop(self) -> None:
        while not self.is_stopped():
            self._stop_event.wait(self.generation_interval_sec)
            if self.is_stopped():
                break
            try:
                self.run_one_batch()
            except Exception:
                log.exception("speed micro-batch failed")

    def run_one_batch(self) -> int:
        """Process one input micro-batch; returns updates published.
        Callable directly for deterministic tests."""
        if self._input_consumer is None:
            self._input_consumer = self.make_input_consumer()
        # columnar drain: blocks of byte-string arrays, no per-record
        # object construction — the input side of the 100K events/s path
        blocks = []
        total = 0
        limit = self.max_batch_events
        while total < limit:
            block = self._input_consumer.poll_block(
                max_records=min(10_000, limit - total), timeout=0.05
            )
            if block is None:
                break
            blocks.append(block)
            total += len(block)
        if total == 0:
            return 0
        new_data = BlockRecords(blocks)
        with metrics.timed(metrics.registry.histogram("speed.batch.seconds")):
            with profiling.maybe_trace(
                profiling.profile_dir_from_config(self.config, "speed"),
                "speed-batch",
            ):
                updates = self.manager.build_updates(new_data)
            ub = self.update_broker()
            sent = 0
            if ub is not None:
                with ub.producer(self.update_topic) as producer:
                    # each delta goes out with key "UP" (SpeedLayerUpdate.java:
                    # 58-60); one batched publish per micro-batch so the bus
                    # pays one lock/write cycle, not one per delta
                    sent = producer.send_many(("UP", update) for update in updates)
            if self.id:
                self._input_consumer.commit()
        metrics.registry.counter("speed.events").inc(total)
        metrics.registry.counter("speed.updates").inc(sent)
        self._batch_count += 1
        return sent
