"""Speed layer runtime.

Rebuild of SpeedLayer + SpeedLayerUpdate (framework/oryx-lambda/.../speed/
SpeedLayer.java:56-214, SpeedLayerUpdate.java:37-66; call stack §3.2):

- a dedicated thread consumes the update topic **from the beginning**
  (the replay-from-zero recovery story, SpeedLayer.java:107-121) feeding
  the configured SpeedModelManager.consume;
- every generation interval, the input micro-batch is handed to
  manager.build_updates and each returned delta is published to the update
  topic with key "UP".

Resilience (docs/resilience.md): both threads run supervised — restart
with backoff under ``oryx.speed.retry.*``, give up after max-attempts
consecutive failures and report the layer unhealthy. An update block that
repeatedly fails ``consume_blocks`` is quarantined to the dead-letter
topic instead of killing the consume thread, and delta publishes are
retried under the same policy.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time

from oryx_tpu.common.records import BlockRecords
from oryx_tpu.common import metrics, profiling, tracing
from oryx_tpu.common.config import Config
from oryx_tpu.common.crashpoints import crashpoint
from oryx_tpu.common.lang import load_instance_of
from oryx_tpu.lambda_.base import AbstractLayer, GuardedBlockFeed

log = logging.getLogger(__name__)


def batch_origin(blocks) -> tuple[tracing.TraceContext | None, int | None]:
    """(incoming sampled trace context, earliest origin ingest ms) merged
    across a drained micro-batch's transport-carried ``@trc`` headers: the
    first sampled context continues that trace through the batch's
    parse/fold/publish spans; the earliest stamped ``ts`` becomes the
    batch's origin for the freshness chain (re-stamped on the UP publish,
    so serving can observe event-ingest -> servable-visibility)."""
    ctx = None
    earliest = None
    for b in blocks:
        info = tracing.parse_header(getattr(b, "trace", None))
        if info is None:
            continue
        if ctx is None and info.ctx is not None and info.ctx.sampled:
            ctx = info.ctx
        if info.ingest_ms is not None:
            earliest = (
                info.ingest_ms
                if earliest is None
                else min(earliest, info.ingest_ms)
            )
    return ctx, earliest


def dead_letter_topic_for(config: Config) -> str:
    """The dead-letter topic name: oryx.update-topic.dead-letter.topic, or
    '<update topic>.dead-letter' when unset."""
    explicit = config.get_optional_string("oryx.update-topic.dead-letter.topic")
    if explicit:
        return explicit
    return config.get_string("oryx.update-topic.message.topic") + ".dead-letter"


class SpeedLayer(AbstractLayer):
    def __init__(self, config: Config) -> None:
        super().__init__(config, "speed")
        self.model_manager_class = config.get_string("oryx.speed.model-manager-class")
        self.max_batch_events = config.get_int("oryx.speed.streaming.max-batch-events")
        self.dead_letter_topic = dead_letter_topic_for(config)
        self.dead_letter_max_failures = (
            config.get_optional_int("oryx.update-topic.dead-letter.max-consume-failures") or 3
        )
        self.pipeline_enabled = bool(
            config.get("oryx.speed.pipeline.enabled", None)
        )
        self.manager = load_instance_of(self.model_manager_class, config)
        # guards _input_consumer/_batch_count: the supervised batch (or
        # pipeline publish) worker attaches the consumer and bumps the
        # counter while close()/batch_count read them from the caller's
        # thread (oryxlint lockset ORX102)
        self._state_lock = threading.Lock()
        self._input_consumer = None
        self._update_consumer = None
        self._consume_thread = None
        self._batch_thread = None
        self._pipeline = None
        self._batch_count = 0
        self._closed = False

    def prepare_input(self) -> None:
        """Attach the input consumer; from this point input is observed."""
        with self._state_lock:
            if self._input_consumer is None:
                self._input_consumer = self.make_input_consumer()

    def input_consumer(self):
        """The layer's input consumer, attaching it on first use."""
        self.prepare_input()
        with self._state_lock:
            return self._input_consumer

    def start(self) -> None:
        if self._update_consumer is not None:
            raise RuntimeError(
                "SpeedLayer.start() called twice: the live update consumer "
                "and worker threads would be overwritten and leak"
            )
        self.init_topics()
        self.maybe_start_ui()
        ub = self.update_broker()
        if ub is None:
            raise ValueError("speed layer requires an update topic")
        self._update_consumer = ub.consumer(self.update_topic, from_beginning=True)
        feed = GuardedBlockFeed(
            self._update_consumer,
            self._stop_event,
            self.dead_letter_max_failures,
            self._dead_letter,
        )
        self._consume_thread = self.supervise(
            "SpeedLayerUpdateConsumer",
            lambda: self.manager.consume_blocks(feed.blocks()),
            metrics_prefix="speed.consume",
            on_failure=feed.record_failure,
        )
        if self.pipeline_enabled:
            # pipelined micro-batching: parse/fold/publish on separate
            # supervised workers with bounded hand-off queues, replicated
            # per shard when oryx.speed.pipeline.shards > 1
            from oryx_tpu.lambda_.pipeline import SpeedPipeline

            self._pipeline = SpeedPipeline(self)
            if self._pipeline.shards == 1:
                # sharded mode owns per-partition consumers instead; an
                # idle layer consumer would hold a zero-copy transport
                # guard forever and stall the ring
                self.prepare_input()
            self._pipeline.start()
        else:
            self.prepare_input()
            self._batch_thread = self.supervise(
                "SpeedLayer", self._one_interval, loop=True, metrics_prefix="speed.batch"
            )
        log.info(
            "SpeedLayer started: interval=%ss manager=%s pipeline=%s",
            self.generation_interval_sec,
            self.model_manager_class,
            self.pipeline_enabled,
        )

    def close(self) -> None:
        with self._state_lock:
            if self._closed:
                return  # idempotent: fleet drivers + atexit both call close
            self._closed = True
        super().close()
        with self._state_lock:
            input_consumer = self._input_consumer
        shard_consumers = self._pipeline.shard_consumers if self._pipeline else []
        for c in (input_consumer, self._update_consumer, *shard_consumers):
            if c is not None:
                c.close()
        pipeline_threads = self._pipeline.threads if self._pipeline else []
        self.join_or_report_leak(
            self._consume_thread, self._batch_thread, *pipeline_threads
        )
        self.manager.close()

    @property
    def batch_count(self) -> int:
        with self._state_lock:
            return self._batch_count

    def note_batch_published(self) -> None:
        """One micro-batch's updates are on the bus. Called by whichever
        worker owns the publish step — the fold loop here or the
        pipeline's publish stage — so the counter write stays under the
        layer's own lock (oryxlint caught the cross-object bare
        increment in pipeline.py as ORX103 once the attr was guarded)."""
        with self._state_lock:
            self._batch_count += 1

    # -- internals ----------------------------------------------------------

    def _dead_letter(self, block) -> None:
        """Publish a poison update block to the dead-letter topic with the
        original keys, so operators can inspect and replay it."""
        ub = self.update_broker()
        if ub is None:
            return
        ub.create_topic(self.dead_letter_topic, 1)
        records = [(km.key, km.message) for km in block.iter_key_messages()]
        with ub.producer(self.dead_letter_topic) as producer:
            n = producer.send_many(records)
        metrics.registry.counter("speed.deadletter.records").inc(n)
        log.warning("dead-lettered %d record(s) to %s", n, self.dead_letter_topic)

    def _one_interval(self) -> None:
        """One supervised micro-batch interval (wait, then batch)."""
        self._stop_event.wait(self.generation_interval_sec)
        if not self.is_stopped():
            self.run_one_batch()

    def run_one_batch(self) -> int:
        """Process one input micro-batch; returns updates published.
        Callable directly for deterministic tests."""
        try:
            return self._run_one_batch()
        except Exception:
            # operators alert on this (the loop's supervisor also logs it)
            metrics.registry.counter("speed.batch.failures").inc()
            raise

    def drain_input_blocks(
        self, limit: int, deadline: float | None = None, consumer=None
    ) -> tuple[list, int]:
        """Columnar input drain shared by the monolithic batch and the
        pipeline's parse stage: blocks of byte-string (or typed int)
        arrays, no per-record object construction — the input side of the
        100K events/s path. Without a deadline, the first empty poll ends
        the batch; with one, polling continues until the accumulation
        window closes (or ``limit`` is hit), so micro-batches stay large
        enough to amortize the fold solve. ``consumer`` overrides the
        layer-owned input consumer (the sharded pipeline drains its own
        partition-subset consumers)."""
        blocks: list = []
        total = 0
        if consumer is None:
            consumer = self.input_consumer()
        while total < limit and not self.is_stopped():
            timeout = 0.05
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                timeout = min(timeout, remaining)
            block = consumer.poll_block(
                max_records=min(10_000, limit - total), timeout=timeout
            )
            if block is None:
                if deadline is None:
                    break
                continue
            blocks.append(block)
            total += len(block)
        return blocks, total

    def _run_one_batch(self) -> int:
        consumer = self.input_consumer()
        # pin (if the transport supports it): zero-copy blocks must stay
        # valid across the multi-poll drain until build_updates has parsed
        # them; release() afterwards lets the transport reclaim
        pin = getattr(consumer, "pin", None)
        if pin is not None:
            pin()
        t0 = time.time()
        try:
            blocks, total = self.drain_input_blocks(self.max_batch_events)
            if total == 0:
                return 0
            # continue a sampled trace carried in on the input blocks, or
            # roll the sampling dice for a fresh per-micro-batch root; the
            # origin timestamp flows through to the UP publish regardless
            # of sampling (freshness is always-on)
            incoming_ctx, origin_ms = batch_origin(blocks)
            ingest_ms = origin_ms if origin_ms is not None else int(t0 * 1000)
            ctx = tracing.continue_from(incoming_ctx) or tracing.sample_root()
            if ctx is not None:
                tracing.record_span(
                    "speed.parse", ctx.child(), ctx.span_id, t0,
                    time.time() - t0,
                    {"events": total, "blocks": len(blocks)},
                )
            new_data = BlockRecords(blocks)
            with tracing.use(ctx) if ctx is not None else contextlib.nullcontext():
                with tracing.span("speed.fold", attrs={"events": total}):
                    with metrics.timed(
                        metrics.registry.histogram("speed.batch.seconds")
                    ):
                        with profiling.maybe_trace(
                            profiling.profile_dir_from_config(self.config, "speed"),
                            "speed-batch",
                        ):
                            updates = self.manager.build_updates(new_data)
        finally:
            release = getattr(consumer, "release", None)
            if release is not None:
                release()
        with tracing.use(ctx) if ctx is not None else contextlib.nullcontext():
            with metrics.timed(metrics.registry.histogram("speed.publish.seconds")):
                ub = self.update_broker()
                sent = 0
                if ub is not None:
                    with tracing.span(
                        "speed.publish", attrs={"updates": len(updates)}
                    ):
                        # each delta goes out with key "UP"
                        # (SpeedLayerUpdate.java:58-60); one batched publish
                        # per micro-batch so the bus pays one lock/write
                        # cycle, not one per delta. The publish retries
                        # under the layer policy (transient bus faults);
                        # materialized so a retry resends the same records
                        # (including the prepended "@trc" header carrying
                        # this trace + the batch's origin timestamp).
                        records = [("UP", update) for update in updates]
                        extra = 0
                        if records:
                            records, extra = tracing.with_header(
                                records, ingest_ms=ingest_ms
                            )
                        with ub.producer(self.update_topic) as producer:
                            sent = self.retry_policy.call(
                                lambda: producer.send_many(records),
                                retry_on=(ConnectionError, OSError),
                                metrics_prefix="speed.publish",
                                stop_event=self._stop_event,
                            ) - extra
                crashpoint("speed.commit.pre")
                if self.id:
                    self.input_consumer().commit()
                crashpoint("speed.commit.post")
        # the micro-batch's deltas are now servable-visible to any replica
        # that polls: event-ingest -> published, the speed half of the
        # freshness chain (serving closes it with serving.freshness.seconds)
        metrics.registry.histogram("speed.freshness.seconds").observe(
            max(0.0, time.time() - ingest_ms / 1000.0)
        )
        if ctx is not None:
            tracing.record_span(
                "speed.batch", ctx,
                incoming_ctx.span_id if incoming_ctx is not None else None,
                t0, time.time() - t0, {"events": total, "updates": sent},
            )
        metrics.registry.counter("speed.events").inc(total)
        metrics.registry.counter("speed.updates").inc(sent)
        self.note_batch_published()
        return sent
