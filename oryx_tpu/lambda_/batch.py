"""Batch layer runtime.

Rebuild of BatchLayer + BatchUpdateFunction + SaveToHDFSFunction +
UpdateOffsetsFn + DeleteOldDataFn (framework/oryx-lambda/.../batch/,
SURVEY.md §2.4, call stack §3.1). Per generation interval:

1. drain the input topic into a micro-batch,
2. read all surviving past data from the data dir,
3. invoke the configured BatchLayerUpdate (which trains on past+new and
   publishes MODEL/MODEL-REF + UP messages),
4. append the micro-batch to the data dir,
5. commit input offsets to the offset ledger (at-least-once),
6. GC data/models past their max age.

Step 3 runs before step 4 so the update sees `new_data` and `past_data`
disjoint, matching the reference's foreachRDD registration order
(BatchLayer.java:103-122).
"""

from __future__ import annotations

import logging
import threading
import time

from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common import metrics, profiling
from oryx_tpu.common.config import Config
from oryx_tpu.common.crashpoints import crashpoint
from oryx_tpu.common.lang import load_instance_of
from oryx_tpu.lambda_ import data as data_store
from oryx_tpu.lambda_.base import AbstractLayer

log = logging.getLogger(__name__)


class BatchLayer(AbstractLayer):
    def __init__(self, config: Config) -> None:
        super().__init__(config, "batch")
        self.update_class = config.get_string("oryx.batch.update-class")
        self.data_dir = config.get_string("oryx.batch.storage.data-dir")
        self.model_dir = config.get_string("oryx.batch.storage.model-dir")
        self.max_data_age_hours = config.get_int("oryx.batch.storage.max-age-data-hours")
        self.storage_format = config.get_string("oryx.batch.storage.format")
        if self.storage_format not in ("npz", "jsonl"):
            raise ValueError(
                f"oryx.batch.storage.format must be npz or jsonl, got {self.storage_format!r}"
            )
        self.max_model_age_hours = (
            config.get_optional_int("oryx.batch.storage.max-age-model-hours") or -1
        )
        self._update = load_instance_of(self.update_class, config)
        # guards _consumer/_generation_count: the supervised generation
        # thread lazily attaches the consumer and bumps the counter while
        # close()/generation_count read them from the caller's thread
        # (oryxlint lockset ORX102)
        self._state_lock = threading.Lock()
        self._consumer = None
        self._thread = None
        self._generation_count = 0

    # -- public lifecycle ---------------------------------------------------

    def prepare(self) -> None:
        """Create topics and attach the input consumer without starting the
        background loop; from this point input is observed. Useful when
        driving generations explicitly (tests, one-shot CLI runs)."""
        self.init_topics()
        self.maybe_start_ui()
        with self._state_lock:
            if self._consumer is None:
                self._consumer = self.make_input_consumer()

    def start(self) -> None:
        self.prepare()
        # supervised: a failed generation restarts the loop with backoff
        # under oryx.batch.retry.*; max-attempts consecutive failures and
        # the layer reports unhealthy (docs/resilience.md)
        self._thread = self.supervise(
            "BatchLayer", self._one_interval, loop=True, metrics_prefix="batch.loop"
        )
        log.info("BatchLayer started: interval=%ss update=%s", self.generation_interval_sec, self.update_class)

    def close(self) -> None:
        super().close()
        with self._state_lock:
            consumer = self._consumer
        if consumer is not None:
            consumer.close()
        self.join_or_report_leak(self._thread)

    @property
    def generation_count(self) -> int:
        with self._state_lock:
            return self._generation_count

    # -- generation loop ----------------------------------------------------

    def _one_interval(self) -> None:
        """One supervised generation interval (wait, then generation)."""
        self._stop_event.wait(self.generation_interval_sec)
        if not self.is_stopped():
            self.run_one_generation()

    def run_one_generation(self, timestamp_ms: int | None = None) -> None:
        """One full generation; callable directly for deterministic tests."""
        with metrics.timed(metrics.registry.histogram("batch.generation.seconds")):
            try:
                with profiling.maybe_trace(
                    profiling.profile_dir_from_config(self.config, "batch"),
                    "batch-generation",
                ):
                    self._run_one_generation(timestamp_ms)
            except Exception:
                metrics.registry.counter("batch.generations.failed").inc()
                raise
        metrics.registry.counter("batch.generations").inc()

    def _run_one_generation(self, timestamp_ms: int | None = None) -> None:
        with self._state_lock:
            if self._consumer is None:
                self._consumer = self.make_input_consumer()
            consumer = self._consumer
        timestamp_ms = int(time.time() * 1000) if timestamp_ms is None else timestamp_ms

        def phase(name):
            return metrics.timed(
                metrics.registry.histogram(f"batch.phase.{name}.seconds")
            )

        # 1. drain whatever is currently available on the input topic
        new_data: list[KeyMessage] = []
        with phase("drain"):
            while True:
                batch = consumer.poll(max_records=10_000, timeout=0.05)
                if not batch:
                    break
                new_data.extend(batch)

        # 2. past data as a lazy columnar view — blocks stream from storage
        # during the update itself (one stored micro-batch in memory at a
        # time), so the phase metric covers only discovery
        with phase("read-past"):
            past_data = data_store.FileRecords(self.data_dir)

        # 3. user update, with a producer for the update topic
        ub = self.update_broker()
        producer = ub.producer(self.update_topic) if ub is not None else None
        try:
            with phase("update"):
                self._update.run_update(
                    timestamp_ms, new_data, past_data, self.model_dir, producer
                )
        finally:
            if producer is not None:
                producer.close()

        # 4. persist the micro-batch
        crashpoint("batch.save.pre")
        with phase("save"):
            data_store.save_micro_batch(
                self.data_dir, timestamp_ms, new_data, fmt=self.storage_format
            )

        # 5. commit offsets (UpdateOffsetsFn.java:57-65)
        crashpoint("batch.commit.pre")
        if self.id:
            consumer.commit()

        # 6. age-based GC
        with phase("gc"):
            data_store.delete_old_data(self.data_dir, self.max_data_age_hours)
            data_store.delete_old_models(self.model_dir, self.max_model_age_hours)

        with self._state_lock:
            self._generation_count += 1
