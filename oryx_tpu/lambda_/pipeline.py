"""Pipelined speed-layer micro-batching: parse → fold → publish.

The monolithic ``run_one_batch`` serializes four phases — drain, parse,
fold, publish — so the fold solve (the only phase that can use the
accelerator) idles while text is split and bus bytes move, and vice
versa. This module runs the phases on three supervised workers joined by
bounded hand-off queues:

  stage 1  drain + parse    input bus → RatingMatrix (model-independent)
  stage 2  fold             RatingMatrix → update messages (the solve)
  stage 3  publish + commit update bus write, then offset commit

Backpressure is structural: each queue holds at most
``oryx.speed.pipeline.queue-depth`` batches and ``put`` blocks, so a slow
fold stalls the parse stage (and, through the consumer, the bus — the
shm ring's guard does the same one level down) instead of buffering
without bound.

At-least-once is preserved by construction: stage 1 snapshots the
consumer's positions when it finishes a drain, and ONLY stage 3 — after
the publish succeeded — writes them to the offset ledger
(``broker.set_offsets``). A crash anywhere between drain and commit
replays the batch; nothing is ever committed ahead of its updates. The
consumer itself is never ``commit()``-ed from the pipeline.

A batch whose fold raises is re-queued at the head of the parse→fold
queue (order preserved) and retried up to ``_FOLD_MAX_ATTEMPTS`` times;
then it is dropped with ``speed.pipeline.fold-dropped`` counting the
lost events — the pipelined analogue of the dead-letter quarantine.

Managers exposing the staged API (``parse_batch``/``fold_parsed``, e.g.
ALSSpeedModelManager) parse on stage 1; for anything else stage 1
materializes the drained blocks (transport views don't survive the
hand-off) and stage 2 calls plain ``build_updates``.

Sharding (``oryx.speed.pipeline.shards``, clamped to the input topic's
partition count): the pipeline is replicated into N independent
parse→fold→publish chains, shard s owning input partitions
``p % shards == s`` through a manually-assigned consumer. Each shard has
its own hand-off queues, commits ONLY its own partitions' offsets after
its own publish (the ledger merges disjoint subsets), and keeps the
retry/drop fold semantics per shard. Where the platform allows
(``pin-cores``, Linux with >1 CPU), a shard's three workers are pinned
to one core, round-robin over the allowed set, so shards scale across
cores instead of timeslicing one. With shards == 1 the behavior — thread
names, the layer-owned consumer, commit path — is exactly the unsharded
pipeline.
"""

from __future__ import annotations

import logging
import os
import threading
import time

from oryx_tpu.common import metrics, tracing
from oryx_tpu.common.records import BlockRecords

log = logging.getLogger(__name__)

_FOLD_MAX_ATTEMPTS = 3


class HandoffQueue:
    """A bounded stage-to-stage hand-off: blocking ``put`` (backpressure),
    timeout ``get``, and ``unget`` to return an item to the HEAD for an
    in-order retry. ``unget`` may exceed the bound by one — a retrying
    stage must never deadlock against its own upstream."""

    def __init__(self, depth: int) -> None:
        if depth < 1:
            raise ValueError("queue depth must be >= 1")
        self._depth = depth
        self._items: list = []
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)

    def __len__(self) -> int:
        with self._lock:
            return len(self._items)

    def put(self, item, stop_event: threading.Event | None = None) -> bool:
        """Append; blocks while full. Returns False if stopped first."""
        with self._not_full:
            while len(self._items) >= self._depth:
                if stop_event is not None and stop_event.is_set():
                    return False
                self._not_full.wait(0.1)
            self._items.append(item)
            self._not_empty.notify()
            return True

    def get(self, timeout: float = 0.2):
        """Pop the head, or None after ``timeout`` with nothing queued."""
        deadline = time.monotonic() + timeout
        with self._not_empty:
            while not self._items:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._not_empty.wait(remaining)
            item = self._items.pop(0)
            self._not_full.notify()
            return item

    def unget(self, item) -> None:
        """Put back at the head (retry without reordering)."""
        with self._not_empty:
            self._items.insert(0, item)
            self._not_empty.notify()


class _Shard:
    """One parse→fold→publish chain: its queues, its consumer (None in
    single-shard mode, where the layer-owned consumer is used), and the
    CPU its three workers pin to (None = no pinning)."""

    __slots__ = ("index", "consumer", "parsed", "folded", "cpu")

    def __init__(self, index: int, consumer, depth: int, cpu: int | None) -> None:
        self.index = index
        self.consumer = consumer
        self.parsed = HandoffQueue(depth)
        self.folded = HandoffQueue(depth)
        self.cpu = cpu


class SpeedPipeline:
    """The supervised stages, owned by a :class:`SpeedLayer`.

    Threads run under the layer's retry policy and count toward
    ``layer.healthy()``; the layer's stop event stops all of them.
    """

    def __init__(self, layer) -> None:
        self._layer = layer
        config = layer.config
        self._depth = config.get_optional_int("oryx.speed.pipeline.queue-depth") or 2
        min_batch_ms = config.get_optional_int("oryx.speed.pipeline.min-batch-ms")
        self._min_batch_sec = (200 if min_batch_ms is None else min_batch_ms) / 1000.0
        manager = layer.manager
        self._staged = hasattr(manager, "parse_batch") and hasattr(
            manager, "fold_parsed"
        )
        self._fold_takes_shard = False
        if self._staged:
            import inspect

            try:
                self._fold_takes_shard = (
                    "shard" in inspect.signature(manager.fold_parsed).parameters
                )
            except (TypeError, ValueError):
                pass
        shards = config.get_optional_int("oryx.speed.pipeline.shards") or 1
        nparts = max(1, layer.input_partitions)
        if shards > nparts:
            log.warning(
                "clamping oryx.speed.pipeline.shards=%d to the input topic's "
                "%d partition(s)", shards, nparts,
            )
            shards = nparts
        self.shards = max(1, shards)
        cpus: list[int] = []
        if self.shards > 1 and config.get_bool("oryx.speed.pipeline.pin-cores"):
            try:
                cpus = sorted(os.sched_getaffinity(0))
            except AttributeError:  # non-Linux
                cpus = []
            if len(cpus) < 2:
                cpus = []
        # tells a worker thread whether it already pinned itself (the pin
        # syscall is per-thread; doing it once beats once per loop)
        self._tls = threading.local()
        if self.shards > 1 and hasattr(manager, "configure_sharding"):
            manager.configure_sharding(self.shards)
        # consumers owned by the pipeline (sharded mode only); the layer
        # closes them alongside its own
        self.shard_consumers: list = []
        self._shards: list[_Shard] = []
        for s in range(self.shards):
            consumer = None
            if self.shards > 1:
                parts = [p for p in range(nparts) if p % self.shards == s]
                consumer = layer.make_input_consumer(partitions=parts)
                self.shard_consumers.append(consumer)
            cpu = cpus[s % len(cpus)] if cpus else None
            self._shards.append(_Shard(s, consumer, self._depth, cpu))
        self.threads: list = []

    def start(self) -> None:
        layer = self._layer
        multi = self.shards > 1
        self.threads = []
        for sh in self._shards:
            suffix = f"-{sh.index}" if multi else ""
            self.threads += [
                layer.supervise(
                    f"SpeedPipelineParse{suffix}",
                    lambda sh=sh: self._parse_step(sh), loop=True,
                    metrics_prefix="speed.pipeline.parse",
                ),
                layer.supervise(
                    f"SpeedPipelineFold{suffix}",
                    lambda sh=sh: self._fold_step(sh), loop=True,
                    metrics_prefix="speed.pipeline.fold",
                ),
                layer.supervise(
                    f"SpeedPipelinePublish{suffix}",
                    lambda sh=sh: self._publish_step(sh), loop=True,
                    metrics_prefix="speed.pipeline.publish",
                ),
            ]
        log.info(
            "speed pipeline started: shards=%d depth=%d min-batch=%.0fms "
            "staged=%s pinned=%s",
            self.shards, self._depth, self._min_batch_sec * 1000, self._staged,
            any(sh.cpu is not None for sh in self._shards),
        )

    def _pin(self, shard: _Shard) -> None:
        """Pin the calling worker to its shard's core, once per thread."""
        if shard.cpu is None or getattr(self._tls, "pinned", False):
            return
        self._tls.pinned = True
        try:
            os.sched_setaffinity(0, {shard.cpu})
        except OSError:  # cpuset changed under us; run unpinned
            log.warning("could not pin shard %d to cpu %d", shard.index, shard.cpu)

    # -- stage 1: drain + parse ---------------------------------------------

    def _parse_step(self, shard: _Shard) -> None:
        """Drain one accumulation window off the input bus and parse it.

        Transport blocks may be zero-copy views whose lifetime ends at the
        consumer's next poll; the consumer is pinned across the multi-poll
        drain and everything is copied out (parsed, or materialized) BEFORE
        the hand-off, so nothing downstream touches transport memory.
        """
        self._pin(shard)
        layer = self._layer
        consumer = shard.consumer if shard.consumer is not None else layer.input_consumer()
        limit = layer.max_batch_events
        deadline = time.monotonic() + self._min_batch_sec
        pin = getattr(consumer, "pin", None)
        if pin is not None:
            pin()
        t0 = time.time()
        try:
            blocks, total = layer.drain_input_blocks(
                limit, deadline=deadline, consumer=consumer
            )
            if total == 0:
                return
            # trace/freshness metadata rides the hand-off tuples so the
            # fold and publish stages (different threads, no ambient
            # context) can record their spans against the same trace
            from oryx_tpu.lambda_.speed import batch_origin

            incoming_ctx, origin_ms = batch_origin(blocks)
            ingest_ms = origin_ms if origin_ms is not None else int(t0 * 1000)
            ctx = tracing.continue_from(incoming_ctx) or tracing.sample_root()
            meta = (
                ctx,
                incoming_ctx.span_id if incoming_ctx is not None else None,
                ingest_ms,
                t0,
            )
            positions = dict(consumer.positions())
            if self._staged:
                payload = layer.manager.parse_batch(BlockRecords(blocks))
            else:
                payload = BlockRecords(
                    [
                        b.materialize() if hasattr(b, "materialize") else b
                        for b in blocks
                    ]
                )
        finally:
            release = getattr(consumer, "release", None)
            if release is not None:
                release()
        if ctx is not None:
            tracing.record_span(
                "speed.parse", ctx.child(), ctx.span_id, t0,
                time.time() - t0, {"events": total, "blocks": len(blocks)},
            )
        shard.parsed.put((payload, total, positions, 0, meta), layer._stop_event)

    # -- stage 2: fold -------------------------------------------------------

    def _fold_step(self, shard: _Shard) -> None:
        self._pin(shard)
        item = shard.parsed.get(timeout=0.2)
        if item is None:
            return
        payload, total, positions, attempts, meta = item
        ctx = meta[0]
        t1 = time.time()
        try:
            with metrics.timed(metrics.registry.histogram("speed.batch.seconds")):
                if self._staged:
                    if self._fold_takes_shard:
                        result = self._layer.manager.fold_parsed(
                            payload, shard=shard.index
                        )
                    else:
                        result = self._layer.manager.fold_parsed(payload)
                else:
                    result = self._layer.manager.build_updates(payload)
                updates = list(result)
        except Exception:
            attempts += 1
            if attempts >= _FOLD_MAX_ATTEMPTS:
                metrics.registry.counter("speed.pipeline.fold-dropped").inc(total)
                log.exception(
                    "dropping batch of %d event(s) after %d failed fold(s)",
                    total, attempts,
                )
                return
            metrics.registry.counter("speed.pipeline.fold-retries").inc()
            shard.parsed.unget((payload, total, positions, attempts, meta))
            raise  # the supervisor logs, counts and backs off
        if ctx is not None:
            tracing.record_span(
                "speed.fold", ctx.child(), ctx.span_id, t1,
                time.time() - t1, {"events": total},
            )
        shard.folded.put(
            (updates, total, positions, meta), self._layer._stop_event
        )

    # -- stage 3: publish + commit -------------------------------------------

    def _publish_step(self, shard: _Shard) -> None:
        self._pin(shard)
        item = shard.folded.get(timeout=0.2)
        if item is None:
            return
        updates, total, positions, meta = item
        ctx, parent_span_id, ingest_ms, t0 = meta
        layer = self._layer
        ub = layer.update_broker()
        sent = 0
        t2 = time.time()
        if ub is not None and updates:
            records = [("UP", update) for update in updates]
            # the "@trc" header carries this trace + the batch's origin
            # timestamp onto the update topic (freshness chain)
            pub_ctx = ctx.child() if ctx is not None else None
            records, extra = tracing.with_header(records, pub_ctx, ingest_ms)
            with ub.producer(layer.update_topic) as producer:
                sent = layer.retry_policy.call(
                    lambda: producer.send_many(records),
                    retry_on=(ConnectionError, OSError),
                    metrics_prefix="speed.publish",
                    stop_event=layer._stop_event,
                ) - extra
            if ctx is not None:
                tracing.record_span(
                    "speed.publish", pub_ctx, ctx.span_id, t2,
                    time.time() - t2, {"updates": len(updates)},
                )
        # the at-least-once commit point: updates are on the bus, so the
        # drained range may now be marked consumed
        if layer.id and positions:
            layer.input_broker().set_offsets(
                layer.group_id, layer.input_topic, positions
            )
        metrics.registry.histogram("speed.freshness.seconds").observe(
            max(0.0, time.time() - ingest_ms / 1000.0)
        )
        if ctx is not None:
            tracing.record_span(
                "speed.batch", ctx, parent_span_id, t0,
                time.time() - t0, {"events": total, "updates": sent},
            )
        metrics.registry.counter("speed.events").inc(total)
        metrics.registry.counter("speed.updates").inc(sent)
        metrics.registry.counter(
            f"speed.pipeline.shard.{shard.index}.events"
        ).inc(total)
        layer.note_batch_published()
