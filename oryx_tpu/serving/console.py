"""Static HTML consoles for Serving Layer applications.

Rebuilds the reference's console tier (AbstractConsoleResource.java:
header + app fragment + footer served as one HTML page at ``/`` and
``/index.html`` with ``X-Frame-Options: SAMEORIGIN`` and
``Cache-Control: public``; per-app subclasses als/Console.java:28,
kmeans/Console.java:28, rdf/Console.java:28). Instead of shipping HTML
fragment files, apps declare their endpoints as :class:`ConsoleForm`
specs and the page is generated — same header/footer framing, same
endpoint-exercising forms.
"""

from __future__ import annotations

import html as _html
from dataclasses import dataclass, field

from oryx_tpu.serving.web import Response

__all__ = ["ConsoleForm", "console_response", "render_console"]


@dataclass
class ConsoleForm:
    """One endpoint form on the console page.

    ``path`` may contain ``{placeholders}``; each becomes a text input
    whose value is substituted client-side before the request is sent.
    ``query`` names become optional query-string inputs. ``body`` adds a
    textarea posted as the request body (e.g. /ingest).
    """

    legend: str
    method: str = "GET"
    path: str = "/"
    query: tuple[str, ...] = ()
    body: bool = False
    note: str = ""


_STYLE = """
body { font-family: sans-serif; margin: 2em; color: #222; }
h1 { border-bottom: 2px solid #c60; padding-bottom: .2em; }
fieldset { margin: 1em 0; border: 1px solid #bbb; }
legend { font-weight: bold; }
code { background: #f4f4f4; padding: 0 .3em; }
input[type=text] { margin: .2em; }
pre.out { background: #f8f8f8; border: 1px solid #ddd; padding: .5em;
          max-height: 12em; overflow: auto; white-space: pre-wrap; }
.note { color: #666; font-size: .9em; }
footer { margin-top: 2em; border-top: 1px solid #bbb; color: #666;
         font-size: .85em; padding-top: .5em; }
"""

_SCRIPT = """
function contextPrefix() {
  let p = window.location.pathname;
  if (p.endsWith('index.html')) p = p.slice(0, p.length - 'index.html'.length);
  if (p.endsWith('/')) p = p.slice(0, p.length - 1);
  return p;
}
async function go(formEl, method, template, hasBody) {
  let path = template;
  const qs = [];
  for (const el of formEl.querySelectorAll('input[type=text]')) {
    const greedy = '{' + el.name + ':+}';
    const single = '{' + el.name + '}';
    if (template.includes(greedy)) {
      // greedy params are multi-segment: keep '/' as a separator, encode
      // each segment (the server splits on raw '/' before decoding)
      const enc = el.value.split('/').map(encodeURIComponent).join('/');
      path = path.replace(greedy, enc);
    } else if (template.includes(single)) {
      path = path.replace(single, encodeURIComponent(el.value));
    } else if (el.value !== '') {
      qs.push(encodeURIComponent(el.name) + '=' + encodeURIComponent(el.value));
    }
  }
  path = contextPrefix() + path;
  if (qs.length) path += '?' + qs.join('&');
  const opts = {method: method, headers: {'Accept': 'application/json'}};
  const ta = formEl.querySelector('textarea');
  if (hasBody && ta) { opts.body = ta.value; opts.headers['Content-Type'] = 'text/plain'; }
  const out = formEl.querySelector('pre.out');
  try {
    const resp = await fetch(path, opts);
    out.textContent = resp.status + ' ' + (await resp.text());
  } catch (e) {
    out.textContent = 'error: ' + e;
  }
  return false;
}
"""


def _form_html(form: ConsoleForm) -> str:
    inputs = []
    seen = set()
    path = form.path
    i = 0
    while True:
        i = path.find("{", i)
        if i < 0:
            break
        j = path.find("}", i)
        name = path[i + 1 : j].split(":")[0]
        if name not in seen:
            seen.add(name)
            inputs.append(name)
        i = j + 1
    for q in form.query:
        if q not in seen:
            seen.add(q)
            inputs.append(q)
    template = form.path
    rows = "".join(
        f'<label>{_html.escape(n)} <input type="text" name="{_html.escape(n)}"></label>'
        for n in inputs
    )
    body_area = '<br><textarea rows="3" cols="60"></textarea>' if form.body else ""
    note = f'<div class="note">{_html.escape(form.note)}</div>' if form.note else ""
    return (
        f"<fieldset><legend>{_html.escape(form.legend)}</legend>"
        f"<code>{_html.escape(form.method)} {_html.escape(form.path)}</code> {note}"
        f'<form onsubmit="go(this, {form.method!r}, {template!r}, {str(form.body).lower()}); return false">'
        f"{rows}{body_area} <input type=\"submit\" value=\"Send\">"
        '<pre class="out"></pre></form></fieldset>'
    )


def render_console(title: str, forms: list[ConsoleForm]) -> str:
    """Full console page: common header + app forms + common footer
    (the reference's console-header/app-fragment/console-footer
    concatenation, AbstractConsoleResource.java loadHTML)."""
    body = "".join(_form_html(f) for f in forms)
    return (
        "<!doctype html><html><head><meta charset=\"utf-8\">"
        f"<title>{_html.escape(title)}</title>"
        f"<style>{_STYLE}</style><script>{_SCRIPT}</script></head>"
        f"<body><h1>{_html.escape(title)}</h1>"
        "<p>Serving Layer console — exercise the application's REST "
        "endpoints below, or call them directly.</p>"
        f"{body}"
        "<footer>oryx_tpu serving layer</footer></body></html>"
    )


def console_response(html: str) -> Response:
    """Response with the reference's console headers
    (AbstractConsoleResource.java getHTML)."""
    return Response(
        200,
        html,
        content_type="text/html",
        headers={"X-Frame-Options": "SAMEORIGIN", "Cache-Control": "public"},
    )
