"""Request micro-batcher: coalesce concurrent top-N calls into batched
device submits.

The reference parallelizes a single request across a thread pool
(ALSServingModel.topN / ALSServingModel.java:289-335, one thread per LSH
partition). On TPU the economics invert: one device scan is fast but each
dispatch pays a fixed host→device→host cost, so the win comes from
batching *across* concurrent requests instead of splitting one request.

This batcher implements continuous batching, the standard accelerator
serving pattern:

- request threads enqueue (item-matrix handle, query, k, cosine) and
  block on an event;
- a dispatcher thread takes whatever is queued the moment it wakes —
  no artificial wait, so an idle server adds zero batching latency —
  groups entries by (matrix snapshot, cosine) so a model rotation
  mid-flight can never mix row indices from different snapshots, pads
  both k and the coalesced batch's row count to power-of-two buckets
  (jitted programs specialize on shape — buckets keep the compiled-
  program count logarithmic), and calls ``submit_top_k``;
- a completer thread resolves the async handles in submission order and
  wakes the request threads. While the device works on batch r+1, batch
  r's results stream back — the same overlap bench.py exploits.

Under load the queue naturally fills while the device is busy, so batch
size adapts to concurrency automatically (1 request → batch of 1,
hundreds of concurrent requests → full batches).

By default the scheduler is ADAPTIVE: instead of fixed ``max_batch`` /
``max_inflight`` knobs, the completer keeps an EWMA of dispatch latency
and sizes both from it — inflight depth targets a wall-clock latency
budget (slow dispatches → shallower pipeline, so a queued request never
sits behind seconds of device work), and the microbatch ceiling grows
while dispatches come back faster than the budget. Passing explicit
``max_batch`` / ``max_inflight`` pins the legacy fixed behavior.

The dispatcher also fixes bucket fragmentation under backpressure: when
every inflight slot is taken, draining the queue in eager dribbles would
dispatch many small power-of-two-padded groups (each mostly padding).
Instead the dispatcher keeps absorbing arrivals in 1 ms waits while it
is blocked anyway, so one full batch goes out where several fragments
would have — ``serving.batcher.coalesced`` counts the requests that
piggybacked this way, and ``serving.batcher.queue_depth`` /
``inflight`` / ``batch_size`` gauges expose the live scheduler state
through ``oryx_tpu.common.metrics``.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from dataclasses import dataclass, field

import numpy as np

from collections import deque

from oryx_tpu.common import tracing
from oryx_tpu.common.metrics import registry as _metrics
from oryx_tpu.ops import topn as topn_ops
from oryx_tpu.serving.overload import active_probe_fraction
from oryx_tpu.tenancy.context import current_tenant

log = logging.getLogger(__name__)

# Adaptive-scheduler tuning (oryx.serving.scan.* in reference.conf maps
# onto these env knobs via the serving layer).
LATENCY_BUDGET_MS = float(os.environ.get("ORYX_BATCHER_LATENCY_BUDGET_MS", 50.0))
EWMA_ALPHA = 0.25  # completer's dispatch-latency smoothing
MIN_ADAPTIVE_BATCH = 256  # one full fused-scan group
MAX_ADAPTIVE_BATCH = 4096
MIN_INFLIGHT = 2  # always enough to overlap host prep with device work
MAX_INFLIGHT = 32

# Queue-wait EWMA (the admission controller's pressure signal): smoothing
# factor per dispatch, plus an idle decay so the signal fades once the
# queue goes quiet — without it a burst's last reading would pin the shed
# ladder engaged long after the overload passed.
WAIT_EWMA_ALPHA = 0.3
WAIT_DECAY_GRACE_S = 0.25
WAIT_DECAY_HALF_LIFE_S = 0.5


class BatcherClosedError(RuntimeError):
    """Raised by ``score`` when the batcher was closed before the entry
    could be enqueued; distinguishes the benign close race from device
    errors so ``score_default`` never retries a real failure."""


class BatcherOverloadedError(RuntimeError):
    """Raised by ``score`` when the bounded queue
    (``oryx.serving.overload.max-queue``) is full at enqueue: the caller
    gets an immediate shed decision instead of the unbounded
    queued-behind-pipeline wait BENCH_r05 measured at 8.9-18 s p99.
    Deliberately NOT retried by ``score_default`` — the serving layer maps
    it to a fast 429 with Retry-After."""


@dataclass
class _Entry:
    uploaded: object
    query: np.ndarray | None  # None for index-submitted entries
    k: int
    cosine: bool
    x_dev: object | None = None  # device-resident query matrix (index entries)
    row: int | None = None  # row into x_dev
    done: threading.Event = field(default_factory=threading.Event)
    idx: np.ndarray | None = None
    vals: np.ndarray | None = None
    error: BaseException | None = None
    # tracing: the request's sampled context captured at enqueue, plus the
    # wall-clock phase stamps the completer turns into queue-wait /
    # assemble / scan spans. None/0.0 (unsampled) costs nothing.
    trace_ctx: object | None = None
    t_enqueue: float = 0.0
    t_dispatch: float = 0.0
    t_submit: float = 0.0
    # overload control: monotonic enqueue stamp feeding the queue-wait
    # EWMA (always set, unlike the tracing stamps), plus the per-request
    # reduced-probe override snapshotted from the admission contextvar on
    # the request thread — it rides the entry across to the dispatcher.
    t_q: float = 0.0
    probe_fraction: float | None = None
    nprobe_applied: int | None = None
    # multi-tenancy: the tenant identity snapshotted from the request
    # thread's contextvar at enqueue — the DRR queue services per-tenant
    # sub-queues by fair-share weight (docs/multi-tenancy.md)
    tenant: str | None = None


def _k_bucket(k: int) -> int:
    return max(16, 1 << (int(k) - 1).bit_length())


def _b_bucket(n: int) -> int:
    """Batch-row bucket: jitted programs specialize on the batch shape, so
    pad coalesced batches to power-of-two row counts (zero queries) to keep
    the number of distinct compiled programs logarithmic in max_batch."""
    return max(8, 1 << (int(n) - 1).bit_length())


def _record_entry_spans(e: _Entry, t_done: float) -> None:
    """One request's batching lifecycle as three sibling spans under the
    request span — explicit timestamps because the phases were measured by
    three different threads, none of which carries the ambient context:

        serving.queue-wait   enqueue -> dispatcher picks it up (incl. the
                             inflight-slot wait: backpressure is queueing)
        serving.assemble     grouping / padding / device submit
        serving.scan         device scan (submit -> results back); carries
                             the IVF probe count when the scanned matrix
                             is an IVF index
    """
    ctx = e.trace_ctx
    attrs = None
    if e.nprobe_applied is not None:
        attrs = {"nprobe": e.nprobe_applied, "probe_fraction": e.probe_fraction}
    else:
        resolve_nprobe = getattr(e.uploaded, "resolve_nprobe", None)
        if resolve_nprobe is not None:
            try:
                attrs = {"nprobe": int(resolve_nprobe())}
            except Exception:
                attrs = None
    tracing.record_span(
        "serving.queue-wait", ctx.child(), ctx.span_id,
        e.t_enqueue, e.t_dispatch - e.t_enqueue,
    )
    tracing.record_span(
        "serving.assemble", ctx.child(), ctx.span_id,
        e.t_dispatch, e.t_submit - e.t_dispatch,
    )
    tracing.record_span(
        "serving.scan", ctx.child(), ctx.span_id,
        e.t_submit, t_done - e.t_submit, attrs,
    )


class _FairQueue:
    """Deficit-round-robin queue over per-tenant sub-queues.

    Drop-in for the subset of :class:`queue.Queue` the batcher uses
    (``put`` / ``get`` / ``get_nowait`` / ``qsize``), plus per-tenant
    depth accounting for the admission controller. Entries without a
    tenant ride a default sub-queue at weight 1.0, so with tenancy off
    every entry lands there and service order is plain FIFO — the wired
    -but-single-tenant overhead bench measures exactly this path.

    Fairness semantics (docs/multi-tenancy.md): each tenant with queued
    entries holds a credit; the queue serves the head tenant while its
    credit lasts (one request costs 1), then rotates it to the tail with
    a fresh quantum of ``quantum * weight`` credits. A hot tenant's
    backlog therefore waits behind at most one quantum from each other
    active tenant per rotation, bounding victim queue-wait regardless of
    attacker depth.

    The close sentinel (``put(None)``) is a flag, not a queued item:
    ``get`` keeps draining real entries first and only yields ``None``
    once every sub-queue is empty — the drain-then-stop contract the
    dispatcher shutdown relies on.
    """

    _DEFAULT = ""  # sub-queue key for untenanted entries

    def __init__(
        self, weights: dict[str, float] | None = None, quantum: float = 8.0
    ) -> None:
        self._cv = threading.Condition()
        self._weights = dict(weights or {})
        self._quantum = max(1.0, float(quantum))
        self._queues: dict[str, "deque[_Entry]"] = {}
        self._rr: "deque[str]" = deque()  # tenants with queued entries
        self._credit: dict[str, float] = {}
        self._size = 0
        self._sentinel = False

    def _refill(self, key: str) -> float:
        return max(1.0, self._quantum * self._weights.get(key, 1.0))

    def put(self, e) -> None:
        with self._cv:
            if e is None:
                self._sentinel = True
                self._cv.notify_all()
                return
            key = e.tenant or self._DEFAULT
            q = self._queues.get(key)
            if q is None:
                q = self._queues[key] = deque()
            if not q:
                self._rr.append(key)
                self._credit[key] = self._refill(key)
            q.append(e)
            self._size += 1
            self._cv.notify()

    def _pop_locked(self):
        while True:
            key = self._rr[0]
            q = self._queues[key]
            if self._credit[key] >= 1.0:
                self._credit[key] -= 1.0
                e = q.popleft()
                self._size -= 1
                if not q:
                    self._rr.popleft()  # re-enters the rotation on next put
                return e
            # credit spent: rotate to the tail with a fresh quantum
            self._rr.rotate(-1)
            self._credit[key] = self._refill(key)

    def get(self, block: bool = True, timeout: float | None = None):
        with self._cv:
            if not block:
                if self._size:
                    return self._pop_locked()
                if self._sentinel:
                    return None
                raise queue.Empty
            deadline = None if timeout is None else time.monotonic() + timeout
            while not self._size and not self._sentinel:
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise queue.Empty
                self._cv.wait(remaining)
            if self._size:
                return self._pop_locked()
            return None  # sentinel, queues drained

    def get_nowait(self):
        return self.get(block=False)

    def qsize(self) -> int:
        with self._cv:
            return self._size

    def depth(self, tenant: str) -> int:
        with self._cv:
            q = self._queues.get(tenant)
            return len(q) if q else 0

    def tenant_depths(self) -> dict[str, int]:
        """Queued entries per tenant (default sub-queue excluded) — the
        admission controller's per-tenant pressure signal."""
        with self._cv:
            return {
                k: len(q) for k, q in self._queues.items() if k and len(q)
            }

    def share_limit(self, tenant: str, max_queue: int) -> int:
        """`tenant`'s slice of a bounded queue, by fair-share weight."""
        weights = self._weights
        total = sum(weights.values()) or 1.0
        share = weights.get(tenant, 1.0) / max(total, weights.get(tenant, 1.0))
        return max(1, int(max_queue * share))

    def over_share(self, tenant: str, max_queue: int) -> bool:
        """True when `tenant` has exhausted its weighted slice of the
        bounded queue WHILE other tenants are queueing too. A lone
        burster may use the whole queue — the per-tenant bound only
        bites under contention, which is exactly when isolation matters."""
        with self._cv:
            q = self._queues.get(tenant)
            if q is None or not q:
                return False
            contended = any(
                k != tenant and len(other) for k, other in self._queues.items()
            )
            if not contended:
                return False
        return len(q) >= self.share_limit(tenant, max_queue)


class TopNBatcher:
    """Coalesces concurrent ``score`` calls into batched ``submit_top_k``
    device calls. Thread-safe; one instance serves any number of models
    (entries carry their own uploaded-matrix handle)."""

    # coalesced groups past this many rows go through submit_top_k_multi:
    # one device dispatch running ceil(n/256) fused full-matrix scans,
    # paying per-dispatch cost once instead of per 256-row scan
    MULTI_THRESHOLD = 256

    def __init__(
        self,
        max_batch: int | None = None,
        max_inflight: int | None = None,
        max_queue: int | None = None,
        tenant_weights: dict[str, float] | None = None,
        fair_quantum: float = 8.0,
    ) -> None:
        # None => adaptive: the completer resizes the knob from its EWMA
        # of dispatch latency; an explicit value pins it (legacy behavior,
        # and what most unit tests use to force specific shapes)
        self._adaptive_batch = max_batch is None
        self._adaptive_inflight = max_inflight is None
        self.max_batch = MIN_ADAPTIVE_BATCH if max_batch is None else int(max_batch)
        self._inflight_cap = (
            MIN_INFLIGHT + 2 if max_inflight is None else int(max_inflight)
        )
        # bounded queue (oryx.serving.overload.max-queue): None = unbounded
        self._max_queue = None if max_queue is None else int(max_queue)
        self._ewma_ms: float | None = None
        # queue-wait EWMA (the admission controller's primary pressure
        # signal); guarded by _flight_cv like the dispatch EWMA
        self._queue_wait_ewma_ms = 0.0
        self._last_wait_obs = time.monotonic()
        # DRR service across per-tenant sub-queues; FIFO-equivalent when
        # every entry is untenanted (docs/multi-tenancy.md)
        self._queue = _FairQueue(tenant_weights, fair_quantum)
        self._pending: queue.Queue = queue.Queue()
        # inflight tracked under a Condition (not a Semaphore) so the
        # adaptive cap can move while dispatches are blocked on it
        self._flight_cv = threading.Condition()
        self._inflight_count = 0
        self._state_lock = threading.Lock()  # serializes score-enqueue vs close
        self._closed = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="TopNBatcherDispatch", daemon=True
        )
        self._completer = threading.Thread(
            target=self._complete_loop, name="TopNBatcherComplete", daemon=True
        )
        self._dispatcher.start()
        self._completer.start()

    # -- request side --------------------------------------------------------

    def score(
        self, uploaded, query: np.ndarray, k: int, cosine: bool = False
    ) -> tuple[np.ndarray, np.ndarray]:
        """(indices, scores) for one query — blocks until its batch lands.

        When ``k`` exceeds the uploaded matrix's item count the device call
        clamps it, so fewer than ``k`` rows come back — same contract as
        ``top_k_scores``. Raises ``RuntimeError`` if the batcher is closed
        (callers going through :func:`score_default` get a retry)."""
        e = _Entry(uploaded, np.asarray(query, dtype=np.float32), int(k), bool(cosine))
        return self._enqueue(e)

    def score_indexed(
        self, uploaded, x_dev, row: int, k: int, cosine: bool = False
    ) -> tuple[np.ndarray, np.ndarray]:
        """score() with the query vector already device-resident: the
        entry carries only an int32 row into ``x_dev``; coalesced groups
        dispatch via submit_top_k_multi_indexed (device-side gather, no
        vector upload)."""
        e = _Entry(uploaded, None, int(k), bool(cosine), x_dev=x_dev, row=int(row))
        return self._enqueue(e)

    def _enqueue(self, e: _Entry) -> tuple[np.ndarray, np.ndarray]:
        if tracing.enabled():
            ctx = tracing.current()
            if ctx is not None and ctx.sampled:
                e.trace_ctx = ctx
                e.t_enqueue = time.time()
        # snapshot the admission controller's reduced-probe override and
        # the tenant identity here, on the request thread that carries
        # both contextvars
        e.probe_fraction = active_probe_fraction()
        e.tenant = current_tenant()
        e.t_q = time.monotonic()
        with self._state_lock:  # an entry can never land after the sentinel
            if self._closed:
                raise BatcherClosedError("batcher is closed")
            if self._max_queue is not None and self._queue.qsize() >= self._max_queue:
                # approximate bound (qsize races concurrent enqueues by a
                # few entries) — exactness doesn't matter, unboundedness does
                _metrics.counter("serving.batcher.queue.rejected").inc()
                raise BatcherOverloadedError(
                    f"batcher queue full ({self._max_queue} entries)"
                )
            if (
                e.tenant is not None
                and self._max_queue is not None
                and self._queue.over_share(e.tenant, self._max_queue)
            ):
                # noisy-neighbor bound: under contention a tenant only
                # gets its weighted slice of the bounded queue; alone it
                # may still fill the whole thing
                _metrics.counter("serving.batcher.queue.rejected").inc()
                _metrics.counter(
                    f"serving.batcher.queue.rejected.tenant.{e.tenant}"
                ).inc()
                raise BatcherOverloadedError(
                    f"tenant {e.tenant} over fair queue share"
                )
            self._queue.put(e)
            _metrics.gauge("serving.batcher.queue.depth").set(self._queue.qsize())
        e.done.wait()
        if e.error is not None:
            raise e.error
        return e.idx, e.vals

    # -- dispatcher ----------------------------------------------------------

    def _device_busy(self) -> bool:
        with self._flight_cv:
            return self._inflight_count >= self._inflight_cap

    def _take_batch(self) -> list[_Entry] | None:
        first = self._queue.get()
        if first is None:
            return None
        batch = [first]
        coalesced = 0
        # snapshot the adaptive ceiling under _flight_cv: the completer
        # resizes it in _observe_latency while this dispatcher loop reads
        # it (oryxlint lockset ORX104); one stable cap per batch-take
        with self._flight_cv:
            max_batch = self.max_batch
        while len(batch) < max_batch:
            try:
                e = self._queue.get_nowait()
            except queue.Empty:
                # bucket-fragmentation fix: with every inflight slot taken
                # this thread is about to block anyway, so absorb arrivals
                # in bounded waits instead of dispatching a dribble now
                # and more power-of-two-padded fragments right after it
                if not self._device_busy():
                    break
                try:
                    e = self._queue.get(timeout=0.001)
                except queue.Empty:
                    continue
                coalesced += 1
            if e is None:
                self._queue.put(None)  # keep the shutdown signal visible
                break
            batch.append(e)
        if coalesced:
            _metrics.counter("serving.batcher.coalesced").inc(coalesced)
        _metrics.gauge("serving.batcher.queue_depth").set(self._queue.qsize())
        _metrics.gauge("serving.batcher.queue.depth").set(self._queue.qsize())
        _metrics.gauge("serving.batcher.batch_size").set(len(batch))
        return batch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                self._pending.put(None)
                return
            # group by (matrix snapshot, cosine, query-matrix snapshot,
            # probe override): indices are only meaningful against the
            # snapshots the caller captured, vector entries never mix with
            # index entries, and a reduced-probe request must not widen a
            # full-probe neighbour's scan (or vice versa)
            groups: dict[tuple, list[_Entry]] = {}
            for e in batch:
                xk = id(e.x_dev) if e.row is not None else None
                groups.setdefault(
                    (id(e.uploaded), e.cosine, xk, e.probe_fraction), []
                ).append(e)
            for (_, cosine, _xk, _pf), entries in groups.items():
                self._submit_group(entries, cosine)

    def _acquire_slot(self) -> None:
        with self._flight_cv:
            while self._inflight_count >= self._inflight_cap:
                self._flight_cv.wait(timeout=1.0)
            self._inflight_count += 1
            _metrics.gauge("serving.batcher.inflight").set(self._inflight_count)

    def _release_slot(self, latency_s: float | None = None) -> None:
        with self._flight_cv:
            self._inflight_count -= 1
            _metrics.gauge("serving.batcher.inflight").set(self._inflight_count)
            if latency_s is not None:
                self._observe_latency(latency_s * 1000.0)
            self._flight_cv.notify()

    def _observe_latency(self, ms: float) -> None:
        """EWMA the dispatch latency and resize the adaptive knobs from it
        (caller holds ``_flight_cv``). Inflight depth targets the latency
        budget — a queued request waits at most ``depth`` dispatches, so
        depth ~ budget / per-dispatch cost (+2 keeps the host/device
        overlap even when one dispatch blows the whole budget). The batch
        ceiling widens while dispatches stay comfortably inside the
        budget and narrows when they blow past it."""
        self._ewma_ms = (
            ms
            if self._ewma_ms is None
            else EWMA_ALPHA * ms + (1.0 - EWMA_ALPHA) * self._ewma_ms
        )
        _metrics.gauge("serving.batcher.dispatch_ewma_ms").set(self._ewma_ms)
        if self._adaptive_inflight:
            self._inflight_cap = int(
                min(max(LATENCY_BUDGET_MS / max(self._ewma_ms, 1e-3) + 2, MIN_INFLIGHT), MAX_INFLIGHT)
            )
        if self._adaptive_batch:
            if self._ewma_ms > LATENCY_BUDGET_MS and self.max_batch > MIN_ADAPTIVE_BATCH:
                self.max_batch //= 2
            elif self._ewma_ms < LATENCY_BUDGET_MS / 2 and self.max_batch < MAX_ADAPTIVE_BATCH:
                self.max_batch *= 2
            self.max_batch = max(MIN_ADAPTIVE_BATCH, min(self.max_batch, MAX_ADAPTIVE_BATCH))

    def _group_nprobe(self, entries: list[_Entry]) -> int | None:
        """Resolve a reduced-probe override into a concrete ``nprobe`` for
        one coalesced group (all entries share the same probe fraction by
        group key). None when the group runs at full quality or the handle
        is not an IVF index."""
        pf = entries[0].probe_fraction
        if pf is None:
            return None
        resolve = getattr(entries[0].uploaded, "resolve_nprobe", None)
        if resolve is None:
            return None
        try:
            nprobe = max(1, int(resolve() * pf))
        except Exception:
            return None
        for e in entries:
            e.nprobe_applied = nprobe
        return nprobe

    def _observe_queue_wait(self, entries: list[_Entry]) -> None:
        """EWMA the worst enqueue->dispatch wait of the group — the
        admission controller's primary pressure signal."""
        now = time.monotonic()
        wait_ms = max(now - e.t_q for e in entries) * 1000.0
        with self._flight_cv:
            self._queue_wait_ewma_ms = (
                WAIT_EWMA_ALPHA * wait_ms
                + (1.0 - WAIT_EWMA_ALPHA) * self._queue_wait_ewma_ms
            )
            self._last_wait_obs = now
            _metrics.gauge("serving.batcher.queue.wait-ewma-ms").set(
                self._queue_wait_ewma_ms
            )

    def queue_wait_ewma_ms(self) -> float:
        """Current queue-wait EWMA with idle decay: when no dispatches
        happen (queue went quiet) the signal halves every
        ``WAIT_DECAY_HALF_LIFE_S`` so the shed ladder can release even
        though nothing is flowing to refresh the EWMA."""
        now = time.monotonic()
        with self._flight_cv:
            idle = now - self._last_wait_obs
            ewma = self._queue_wait_ewma_ms
        if idle <= WAIT_DECAY_GRACE_S:
            return ewma
        return ewma * 0.5 ** ((idle - WAIT_DECAY_GRACE_S) / WAIT_DECAY_HALF_LIFE_S)

    def _submit_group(self, entries: list[_Entry], cosine: bool) -> None:
        self._acquire_slot()
        # queue-wait ends here: the entry has a dispatcher AND an inflight
        # slot (slot contention is backpressure, i.e. still queueing)
        self._observe_queue_wait(entries)
        for e in entries:
            if e.trace_ctx is not None:
                e.t_dispatch = time.time()
        try:
            if entries[0].row is not None:
                self._submit_indexed(entries, cosine)
                return
            nprobe = self._group_nprobe(entries)
            queries = np.stack([e.query for e in entries])
            # tiered item store: hint the cells this group will probe so
            # the store's disk->RAM promotions overlap the dispatch below
            # instead of stalling the stage-1 gather (advisory; no-op on
            # flat-plane indexes)
            prefetch = getattr(entries[0].uploaded, "prefetch_for_queries", None)
            if prefetch is not None:
                try:
                    prefetch(queries, nprobe=nprobe, cosine=cosine)
                except Exception:  # never let a hint fail a dispatch
                    pass
            kk = _k_bucket(max(e.k for e in entries))
            if len(entries) > self.MULTI_THRESHOLD:
                # fused multi-scan: pads to a multiple of scan_batch
                # internally, so compiled shapes stay one-per-K
                handle = topn_ops.submit_top_k_multi(
                    entries[0].uploaded,
                    queries,
                    kk,
                    cosine=cosine,
                    scan_batch=self.MULTI_THRESHOLD,
                    nprobe=nprobe,
                )
            else:
                pad_rows = _b_bucket(len(entries)) - len(entries)
                if pad_rows:
                    queries = np.concatenate(
                        [queries, np.zeros((pad_rows, queries.shape[1]), queries.dtype)]
                    )
                handle = topn_ops.submit_top_k(
                    entries[0].uploaded, queries, kk, cosine=cosine, nprobe=nprobe
                )
            for e in entries:
                if e.trace_ctx is not None:
                    e.t_submit = time.time()
            self._pending.put((handle, entries, time.perf_counter()))
        except BaseException as exc:  # deliver the failure to the waiters
            self._release_slot()
            for e in entries:
                e.error = exc
                e.done.set()

    def _submit_indexed(self, entries: list[_Entry], cosine: bool) -> None:
        """Dispatch one coalesced index-entry group (caller holds the
        inflight slot; errors deliver to waiters exactly like the vector
        path)."""
        try:
            nprobe = self._group_nprobe(entries)
            rows = np.asarray([e.row for e in entries], dtype=np.int32)
            kk = _k_bucket(max(e.k for e in entries))
            pad = _b_bucket(len(rows)) - len(rows)
            if pad:  # bucketed shapes: row 0 repeats, results discarded
                rows = np.concatenate([rows, np.zeros(pad, np.int32)])
            handle = topn_ops.submit_top_k_multi_indexed(
                entries[0].uploaded,
                entries[0].x_dev,
                rows,
                kk,
                cosine=cosine,
                scan_batch=self.MULTI_THRESHOLD,
                nprobe=nprobe,
            )
            for e in entries:
                if e.trace_ctx is not None:
                    e.t_submit = time.time()
            self._pending.put((handle, entries, time.perf_counter()))
        except BaseException as exc:  # deliver the failure to the waiters
            self._release_slot()
            for e in entries:
                e.error = exc
                e.done.set()

    # -- completer -----------------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            item = self._pending.get()
            if item is None:
                return
            handle, entries, t_submit = item
            latency = None
            try:
                idx, vals = handle.result()
                latency = time.perf_counter() - t_submit
                for row, e in enumerate(entries):
                    e.idx = idx[row, : e.k]
                    e.vals = vals[row, : e.k]
            except BaseException as exc:
                for e in entries:
                    e.error = exc
            finally:
                self._release_slot(latency)
                t_done = time.time()
                for e in entries:
                    if e.trace_ctx is not None:
                        _record_entry_spans(e, t_done)
                    e.done.set()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)
        self._dispatcher.join(timeout=5)
        self._completer.join(timeout=5)


_default_lock = threading.Lock()
_default: TopNBatcher | None = None
_default_init: dict = {}


_atexit_registered = False


def configure_scheduler(
    max_batch: int | None = None,
    max_inflight: int | None = None,
    latency_budget_ms: float | None = None,
    max_queue: int | None = None,
) -> None:
    """Pin the process-wide batcher's scheduler knobs (the serving layer
    maps ``oryx.serving.scan.*`` / ``oryx.serving.overload.max-queue``
    here at startup, before the default batcher spins up). ``None`` leaves
    a knob adaptive (for ``max_queue``: unbounded)."""
    global LATENCY_BUDGET_MS
    with _default_lock:
        _default_init["max_batch"] = max_batch
        _default_init["max_inflight"] = max_inflight
        _default_init["max_queue"] = max_queue
        if latency_budget_ms is not None:
            LATENCY_BUDGET_MS = float(latency_budget_ms)


def configure_fairness(
    tenant_weights: dict[str, float] | None, quantum: float = 8.0
) -> None:
    """Pin the DRR fair-share weights for the process-wide batcher (the
    serving layer maps ``oryx.tenancy.tenants.<id>.weight`` and
    ``oryx.tenancy.fair-share.quantum`` here at startup). ``None``
    weights keep tenancy-agnostic FIFO behavior."""
    with _default_lock:
        _default_init["tenant_weights"] = tenant_weights
        _default_init["fair_quantum"] = quantum


def default_batcher_signals() -> tuple[float, int]:
    """(queue_wait_ewma_ms, queue_depth) of the live default batcher, or
    zeros when none is running — the admission controller polls this on
    its control interval, so the idle fast path must stay cheap and must
    never lazily create a batcher."""
    with _default_lock:
        b = _default
    if b is None or b._closed:
        return 0.0, 0
    return b.queue_wait_ewma_ms(), b._queue.qsize()


def default_tenant_depths() -> dict[str, int]:
    """Per-tenant queued-entry counts of the live default batcher ({} when
    none is running) — the per-tenant admission ladders poll this the same
    way the global ladder polls :func:`default_batcher_signals`."""
    with _default_lock:
        b = _default
    if b is None or b._closed:
        return {}
    return b._queue.tenant_depths()


def get_default_batcher() -> TopNBatcher:
    """Process-wide batcher shared by all serving models. Lazily created
    (and re-created after a close); an atexit hook closes whatever default
    is live at interpreter shutdown so late re-creations — e.g. a request
    draining after the last serving layer released the batcher — cannot
    leak threads past process teardown."""
    global _default, _atexit_registered
    with _default_lock:
        if _default is None or _default._closed:
            _default = TopNBatcher(**_default_init)
            if not _atexit_registered:
                import atexit

                atexit.register(close_default_batcher)
                _atexit_registered = True
        return _default


def score_indexed_default(
    uploaded, x_dev, row: int, k: int, cosine: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """``score_default`` for index-submitted entries (same close-race
    retry contract)."""
    for attempt in range(4):
        try:
            return get_default_batcher().score_indexed(
                uploaded, x_dev, row, k, cosine=cosine
            )
        except BatcherClosedError:
            if attempt == 3:
                raise
    raise AssertionError("unreachable")


def score_default(
    uploaded, query: np.ndarray, k: int, cosine: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """``get_default_batcher().score(...)`` retried across close races: a
    concurrent ``close`` can flip ``_closed`` between the lookup and the
    enqueue, in which case the lookup is repeated against the replacement
    batcher. Only :class:`BatcherClosedError` is retried — device errors
    propagate immediately."""
    for attempt in range(4):
        try:
            return get_default_batcher().score(uploaded, query, k, cosine=cosine)
        except BatcherClosedError:
            if attempt == 3:
                raise
    raise AssertionError("unreachable")


_default_refs = 0


def retain_default_batcher() -> None:
    """Register a user of the process-wide batcher (serving-layer start)."""
    global _default_refs
    with _default_lock:
        _default_refs += 1


def release_default_batcher() -> None:
    """Drop a reference; the batcher is closed when the last serving layer
    in the process releases it (so one layer's close cannot kill a batcher
    another live layer is using)."""
    global _default, _default_refs
    with _default_lock:
        _default_refs = max(0, _default_refs - 1)
        if _default_refs > 0:
            return
        batcher, _default = _default, None
    if batcher is not None:
        batcher.close()


def close_default_batcher() -> None:
    """Unconditionally shut down the process-wide batcher (tests,
    process teardown)."""
    global _default, _default_refs
    with _default_lock:
        batcher, _default = _default, None
        _default_refs = 0
    if batcher is not None:
        batcher.close()
