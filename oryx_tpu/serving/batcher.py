"""Request micro-batcher: coalesce concurrent top-N calls into batched
device submits.

The reference parallelizes a single request across a thread pool
(ALSServingModel.topN / ALSServingModel.java:289-335, one thread per LSH
partition). On TPU the economics invert: one device scan is fast but each
dispatch pays a fixed host→device→host cost, so the win comes from
batching *across* concurrent requests instead of splitting one request.

This batcher implements continuous batching, the standard accelerator
serving pattern:

- request threads enqueue (item-matrix handle, query, k, cosine) and
  block on an event;
- a dispatcher thread takes whatever is queued the moment it wakes —
  no artificial wait, so an idle server adds zero batching latency —
  groups entries by (matrix snapshot, cosine) so a model rotation
  mid-flight can never mix row indices from different snapshots, pads
  both k and the coalesced batch's row count to power-of-two buckets
  (jitted programs specialize on shape — buckets keep the compiled-
  program count logarithmic), and calls ``submit_top_k``;
- a completer thread resolves the async handles in submission order and
  wakes the request threads. While the device works on batch r+1, batch
  r's results stream back — the same overlap bench.py exploits.

Under load the queue naturally fills while the device is busy, so batch
size adapts to concurrency automatically (1 request → batch of 1,
hundreds of concurrent requests → full batches).
"""

from __future__ import annotations

import logging
import queue
import threading
from dataclasses import dataclass, field

import numpy as np

from oryx_tpu.ops import topn as topn_ops

log = logging.getLogger(__name__)


class BatcherClosedError(RuntimeError):
    """Raised by ``score`` when the batcher was closed before the entry
    could be enqueued; distinguishes the benign close race from device
    errors so ``score_default`` never retries a real failure."""


@dataclass
class _Entry:
    uploaded: object
    query: np.ndarray | None  # None for index-submitted entries
    k: int
    cosine: bool
    x_dev: object | None = None  # device-resident query matrix (index entries)
    row: int | None = None  # row into x_dev
    done: threading.Event = field(default_factory=threading.Event)
    idx: np.ndarray | None = None
    vals: np.ndarray | None = None
    error: BaseException | None = None


def _k_bucket(k: int) -> int:
    return max(16, 1 << (int(k) - 1).bit_length())


def _b_bucket(n: int) -> int:
    """Batch-row bucket: jitted programs specialize on the batch shape, so
    pad coalesced batches to power-of-two row counts (zero queries) to keep
    the number of distinct compiled programs logarithmic in max_batch."""
    return max(8, 1 << (int(n) - 1).bit_length())


class TopNBatcher:
    """Coalesces concurrent ``score`` calls into batched ``submit_top_k``
    device calls. Thread-safe; one instance serves any number of models
    (entries carry their own uploaded-matrix handle)."""

    # coalesced groups past this many rows go through submit_top_k_multi:
    # one device dispatch running ceil(n/256) fused full-matrix scans,
    # paying per-dispatch cost once instead of per 256-row scan
    MULTI_THRESHOLD = 256

    def __init__(self, max_batch: int = 2048, max_inflight: int = 32) -> None:
        self.max_batch = max_batch
        self._queue: queue.Queue[_Entry | None] = queue.Queue()
        self._pending: queue.Queue = queue.Queue()
        self._inflight = threading.Semaphore(max_inflight)
        self._state_lock = threading.Lock()  # serializes score-enqueue vs close
        self._closed = False
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="TopNBatcherDispatch", daemon=True
        )
        self._completer = threading.Thread(
            target=self._complete_loop, name="TopNBatcherComplete", daemon=True
        )
        self._dispatcher.start()
        self._completer.start()

    # -- request side --------------------------------------------------------

    def score(
        self, uploaded, query: np.ndarray, k: int, cosine: bool = False
    ) -> tuple[np.ndarray, np.ndarray]:
        """(indices, scores) for one query — blocks until its batch lands.

        When ``k`` exceeds the uploaded matrix's item count the device call
        clamps it, so fewer than ``k`` rows come back — same contract as
        ``top_k_scores``. Raises ``RuntimeError`` if the batcher is closed
        (callers going through :func:`score_default` get a retry)."""
        e = _Entry(uploaded, np.asarray(query, dtype=np.float32), int(k), bool(cosine))
        return self._enqueue(e)

    def score_indexed(
        self, uploaded, x_dev, row: int, k: int, cosine: bool = False
    ) -> tuple[np.ndarray, np.ndarray]:
        """score() with the query vector already device-resident: the
        entry carries only an int32 row into ``x_dev``; coalesced groups
        dispatch via submit_top_k_multi_indexed (device-side gather, no
        vector upload)."""
        e = _Entry(uploaded, None, int(k), bool(cosine), x_dev=x_dev, row=int(row))
        return self._enqueue(e)

    def _enqueue(self, e: _Entry) -> tuple[np.ndarray, np.ndarray]:
        with self._state_lock:  # an entry can never land after the sentinel
            if self._closed:
                raise BatcherClosedError("batcher is closed")
            self._queue.put(e)
        e.done.wait()
        if e.error is not None:
            raise e.error
        return e.idx, e.vals

    # -- dispatcher ----------------------------------------------------------

    def _take_batch(self) -> list[_Entry] | None:
        first = self._queue.get()
        if first is None:
            return None
        batch = [first]
        while len(batch) < self.max_batch:
            try:
                e = self._queue.get_nowait()
            except queue.Empty:
                break
            if e is None:
                self._queue.put(None)  # keep the shutdown signal visible
                break
            batch.append(e)
        return batch

    def _dispatch_loop(self) -> None:
        while True:
            batch = self._take_batch()
            if batch is None:
                self._pending.put(None)
                return
            # group by (matrix snapshot, cosine, query-matrix snapshot):
            # indices are only meaningful against the snapshots the caller
            # captured, and vector entries never mix with index entries
            groups: dict[tuple, list[_Entry]] = {}
            for e in batch:
                xk = id(e.x_dev) if e.row is not None else None
                groups.setdefault((id(e.uploaded), e.cosine, xk), []).append(e)
            for (_, cosine, _xk), entries in groups.items():
                self._submit_group(entries, cosine)

    def _submit_group(self, entries: list[_Entry], cosine: bool) -> None:
        self._inflight.acquire()
        try:
            if entries[0].row is not None:
                self._submit_indexed(entries, cosine)
                return
            queries = np.stack([e.query for e in entries])
            kk = _k_bucket(max(e.k for e in entries))
            if len(entries) > self.MULTI_THRESHOLD:
                # fused multi-scan: pads to a multiple of scan_batch
                # internally, so compiled shapes stay one-per-K
                handle = topn_ops.submit_top_k_multi(
                    entries[0].uploaded,
                    queries,
                    kk,
                    cosine=cosine,
                    scan_batch=self.MULTI_THRESHOLD,
                )
            else:
                pad_rows = _b_bucket(len(entries)) - len(entries)
                if pad_rows:
                    queries = np.concatenate(
                        [queries, np.zeros((pad_rows, queries.shape[1]), queries.dtype)]
                    )
                handle = topn_ops.submit_top_k(
                    entries[0].uploaded, queries, kk, cosine=cosine
                )
            self._pending.put((handle, entries))
        except BaseException as exc:  # deliver the failure to the waiters
            self._inflight.release()
            for e in entries:
                e.error = exc
                e.done.set()

    def _submit_indexed(self, entries: list[_Entry], cosine: bool) -> None:
        """Dispatch one coalesced index-entry group (caller holds the
        inflight slot; errors deliver to waiters exactly like the vector
        path)."""
        try:
            rows = np.asarray([e.row for e in entries], dtype=np.int32)
            kk = _k_bucket(max(e.k for e in entries))
            pad = _b_bucket(len(rows)) - len(rows)
            if pad:  # bucketed shapes: row 0 repeats, results discarded
                rows = np.concatenate([rows, np.zeros(pad, np.int32)])
            handle = topn_ops.submit_top_k_multi_indexed(
                entries[0].uploaded,
                entries[0].x_dev,
                rows,
                kk,
                cosine=cosine,
                scan_batch=self.MULTI_THRESHOLD,
            )
            self._pending.put((handle, entries))
        except BaseException as exc:  # deliver the failure to the waiters
            self._inflight.release()
            for e in entries:
                e.error = exc
                e.done.set()

    # -- completer -----------------------------------------------------------

    def _complete_loop(self) -> None:
        while True:
            item = self._pending.get()
            if item is None:
                return
            handle, entries = item
            try:
                idx, vals = handle.result()
                for row, e in enumerate(entries):
                    e.idx = idx[row, : e.k]
                    e.vals = vals[row, : e.k]
            except BaseException as exc:
                for e in entries:
                    e.error = exc
            finally:
                self._inflight.release()
                for e in entries:
                    e.done.set()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        with self._state_lock:
            if self._closed:
                return
            self._closed = True
            self._queue.put(None)
        self._dispatcher.join(timeout=5)
        self._completer.join(timeout=5)


_default_lock = threading.Lock()
_default: TopNBatcher | None = None


_atexit_registered = False


def get_default_batcher() -> TopNBatcher:
    """Process-wide batcher shared by all serving models. Lazily created
    (and re-created after a close); an atexit hook closes whatever default
    is live at interpreter shutdown so late re-creations — e.g. a request
    draining after the last serving layer released the batcher — cannot
    leak threads past process teardown."""
    global _default, _atexit_registered
    with _default_lock:
        if _default is None or _default._closed:
            _default = TopNBatcher()
            if not _atexit_registered:
                import atexit

                atexit.register(close_default_batcher)
                _atexit_registered = True
        return _default


def score_indexed_default(
    uploaded, x_dev, row: int, k: int, cosine: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """``score_default`` for index-submitted entries (same close-race
    retry contract)."""
    for attempt in range(4):
        try:
            return get_default_batcher().score_indexed(
                uploaded, x_dev, row, k, cosine=cosine
            )
        except BatcherClosedError:
            if attempt == 3:
                raise
    raise AssertionError("unreachable")


def score_default(
    uploaded, query: np.ndarray, k: int, cosine: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """``get_default_batcher().score(...)`` retried across close races: a
    concurrent ``close`` can flip ``_closed`` between the lookup and the
    enqueue, in which case the lookup is repeated against the replacement
    batcher. Only :class:`BatcherClosedError` is retried — device errors
    propagate immediately."""
    for attempt in range(4):
        try:
            return get_default_batcher().score(uploaded, query, k, cosine=cosine)
        except BatcherClosedError:
            if attempt == 3:
                raise
    raise AssertionError("unreachable")


_default_refs = 0


def retain_default_batcher() -> None:
    """Register a user of the process-wide batcher (serving-layer start)."""
    global _default_refs
    with _default_lock:
        _default_refs += 1


def release_default_batcher() -> None:
    """Drop a reference; the batcher is closed when the last serving layer
    in the process releases it (so one layer's close cannot kill a batcher
    another live layer is using)."""
    global _default, _default_refs
    with _default_lock:
        _default_refs = max(0, _default_refs - 1)
        if _default_refs > 0:
            return
        batcher, _default = _default, None
    if batcher is not None:
        batcher.close()


def close_default_batcher() -> None:
    """Unconditionally shut down the process-wide batcher (tests,
    process teardown)."""
    global _default, _default_refs
    with _default_lock:
        batcher, _default = _default, None
        _default_refs = 0
    if batcher is not None:
        batcher.close()
