"""Predictive fleet autoscaling on multi-window burn-rate + queue-wait signals.

The policy half of the fleet control loop (the actuator half — drain-aware
replica rotation — lives in tools/fleet.py).  :class:`FleetAutoscaler`
combines two laws:

* **Predictive**: observed arrival rates are fitted to the raised-cosine
  diurnal curve the loadgen emits (``DiurnalRampProcess``:
  ``rate(t) = base + swing * (1 - cos(2*pi*(t/period + phase)))``) by
  linear least squares on the ``(1, cos wt, sin wt)`` basis — the period
  is operator-known (it's a diurnal cycle), so the fit is a 3x3 solve,
  no iteration.  Desired replicas = ceil(rate(now + lead-s) /
  per-replica-rate): the fleet is sized for where the curve will be one
  replica-startup lead ahead, so scale-out lands *before* the peak.

* **Reactive**: Google-SRE multi-window burn-rate — when BOTH the short
  and long latency-burn windows exceed ``burn-hi``, or queue wait blows
  past ``queue-wait-hi-ms``, demand one replica more than the fit asked
  for.  Two windows mean a single slow request can't trigger churn while
  a sustained breach still reacts in seconds.

Scale-in is deliberately timid: it waits ``scale-in-quiet-evals``
consecutive calm evaluations, then asks the actuator to drain — the
actuator uses the begin_drain/drain rotation, so scale-in never fails a
request; a refused drain (False) leaves the replica in place.

Lives inside the package (not tools/) so its metrics are part of the
lint-checked catalog; the harness in tools/fleet.py supplies the actuator
and signal callbacks.  `clock` is injectable: unit tests drive a scripted
trace through `step(now)` with no threads and no sleeping.
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from oryx_tpu.common import metrics


def fit_raised_cosine(
    times: list[float], rates: list[float], period_s: float
) -> Callable[[float], float] | None:
    """Least-squares fit of observed (t, rate) samples to
    ``c0 + c1*cos(wt) + c2*sin(wt)`` with known period; returns a
    non-negative rate predictor, or None when the system is singular
    (fewer than 3 samples, or samples spanning < ~2% of the period so the
    basis columns are collinear)."""
    n = len(times)
    if n < 3 or period_s <= 0:
        return None
    w = 2.0 * math.pi / period_s
    # normal equations A^T A x = A^T b for A = [1, cos(wt), sin(wt)]
    ata = [[0.0] * 3 for _ in range(3)]
    atb = [0.0] * 3
    for t, r in zip(times, rates):
        row = (1.0, math.cos(w * t), math.sin(w * t))
        for i in range(3):
            atb[i] += row[i] * r
            for j in range(3):
                ata[i][j] += row[i] * row[j]
    coef = _solve3(ata, atb)
    if coef is None:
        return None
    c0, c1, c2 = coef

    def predict(t: float) -> float:
        return max(0.0, c0 + c1 * math.cos(w * t) + c2 * math.sin(w * t))

    return predict


def _solve3(a: list[list[float]], b: list[float]) -> list[float] | None:
    """Gaussian elimination with partial pivoting for a 3x3 system."""
    m = [row[:] + [bi] for row, bi in zip(a, b)]
    for col in range(3):
        pivot = max(range(col, 3), key=lambda r: abs(m[r][col]))
        if abs(m[pivot][col]) < 1e-9:
            return None
        m[col], m[pivot] = m[pivot], m[col]
        for r in range(3):
            if r != col:
                f = m[r][col] / m[col][col]
                for c in range(col, 4):
                    m[r][c] -= f * m[col][c]
    return [m[i][3] / m[i][i] for i in range(3)]


@dataclass(frozen=True)
class AutoscaleConfig:
    """Parsed ``oryx.fleet.autoscale.*`` knobs (reference.conf defaults)."""

    enabled: bool = False
    min_replicas: int = 1
    max_replicas: int = 8
    interval_s: float = 1.0
    lead_s: float = 30.0
    period_s: float = 86400.0
    per_replica_rate: float = 100.0
    cooldown_s: float = 5.0
    burn_hi: float = 2.0
    burn_window_short_s: float = 5.0
    burn_window_long_s: float = 30.0
    queue_wait_hi_ms: float = 200.0
    scale_in_quiet_evals: int = 5
    min_fit_samples: int = 8

    @classmethod
    def from_config(cls, config) -> "AutoscaleConfig":
        p = "oryx.fleet.autoscale."
        return cls(
            enabled=config.get_bool(p + "enabled"),
            min_replicas=config.get_int(p + "min-replicas"),
            max_replicas=config.get_int(p + "max-replicas"),
            interval_s=config.get_float(p + "interval-s"),
            lead_s=config.get_float(p + "lead-s"),
            period_s=config.get_float(p + "period-s"),
            per_replica_rate=config.get_float(p + "per-replica-rate"),
            cooldown_s=config.get_float(p + "cooldown-s"),
            burn_hi=config.get_float(p + "burn-hi"),
            burn_window_short_s=config.get_float(p + "burn-window-short-s"),
            burn_window_long_s=config.get_float(p + "burn-window-long-s"),
            queue_wait_hi_ms=config.get_float(p + "queue-wait-hi-ms"),
            scale_in_quiet_evals=config.get_int(p + "scale-in-quiet-evals"),
            min_fit_samples=config.get_int(p + "min-fit-samples"),
        )


@dataclass
class AutoscaleSignals:
    """One evaluation's inputs, supplied by the harness."""

    rate: float  # observed arrival rate, req/s
    queue_wait_ms: float  # worst batcher queue-wait EWMA across replicas
    burn_short: float  # latency burn rate over the short window
    burn_long: float  # latency burn rate over the long window
    # per-tenant arrival-rate split on a multi-tenant fleet: published as
    # fleet.autoscale.rate.tenant.<tenant> gauges so capacity dashboards
    # attribute demand to tenants (sizing itself uses the aggregate rate
    # — replicas host every tenant, so capacity is fungible across them)
    tenant_rates: dict[str, float] = field(default_factory=dict)


@dataclass
class ScaleEvent:
    t: float
    direction: str  # "out" | "in"
    reason: str  # "predictive" | "reactive" | "quiet"
    replicas: int  # replica count after the event


class FleetAutoscaler:
    """Sizing policy over an actuator; call :meth:`step` once per interval.

    `actuator` needs three methods: ``replica_count() -> int``,
    ``scale_out() -> bool`` and ``scale_in() -> bool`` (scale_in drains
    first and returns False when it refuses, e.g. at min capacity or when
    the drain would strand in-flight requests).
    """

    def __init__(
        self,
        actuator,
        signals: Callable[[], AutoscaleSignals],
        cfg: AutoscaleConfig,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        self.actuator = actuator
        self._signals = signals
        self.cfg = cfg
        self._clock = clock
        self._samples: deque[tuple[float, float]] = deque(maxlen=4096)
        self._last_scale = -float("inf")
        self._quiet_evals = 0
        self.events: list[ScaleEvent] = []
        self.last_predicted_rate = 0.0

    def step(self, now: float | None = None) -> int:
        """One control evaluation; returns the replica count afterwards."""
        t = self._clock() if now is None else now
        cfg = self.cfg
        sig = self._signals()
        self._samples.append((t, sig.rate))
        current = self.actuator.replica_count()

        # predictive demand from the diurnal fit, one lead ahead
        desired = cfg.min_replicas
        predict = None
        if len(self._samples) >= cfg.min_fit_samples:
            ts = [s[0] for s in self._samples]
            rs = [s[1] for s in self._samples]
            predict = fit_raised_cosine(ts, rs, cfg.period_s)
        if predict is not None:
            predicted = predict(t + cfg.lead_s)
            self.last_predicted_rate = predicted
            desired = max(
                desired, math.ceil(predicted / max(1e-9, cfg.per_replica_rate))
            )
        else:
            # no usable fit yet: size reactively on the observed rate
            self.last_predicted_rate = sig.rate
            desired = max(
                desired, math.ceil(sig.rate / max(1e-9, cfg.per_replica_rate))
            )

        # reactive override: sustained multi-window burn or queue blow-up
        overloaded = (
            sig.burn_short > cfg.burn_hi and sig.burn_long > cfg.burn_hi
        ) or sig.queue_wait_ms > cfg.queue_wait_hi_ms
        if overloaded:
            desired = max(desired, current + 1)
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))

        if desired > current:
            self._quiet_evals = 0
            if t - self._last_scale >= cfg.cooldown_s and self.actuator.scale_out():
                self._last_scale = t
                reason = "reactive" if overloaded else "predictive"
                self._record(t, "out", reason)
        elif desired < current and not overloaded:
            self._quiet_evals += 1
            if (
                self._quiet_evals >= cfg.scale_in_quiet_evals
                and t - self._last_scale >= cfg.cooldown_s
                and self.actuator.scale_in()
            ):
                self._last_scale = t
                self._quiet_evals = 0
                self._record(t, "in", "quiet")
        else:
            self._quiet_evals = 0

        count = self.actuator.replica_count()
        metrics.registry.gauge("fleet.autoscale.replicas").set(count)
        metrics.registry.gauge("fleet.autoscale.predicted-rate").set(
            self.last_predicted_rate
        )
        for tid, tenant_rate in sig.tenant_rates.items():
            metrics.registry.gauge(f"fleet.autoscale.rate.tenant.{tid}").set(
                tenant_rate
            )
        return count

    def _record(self, t: float, direction: str, reason: str) -> None:
        count = self.actuator.replica_count()
        self.events.append(ScaleEvent(t, direction, reason, count))
        if direction == "out":
            metrics.registry.counter("fleet.autoscale.scale-outs").inc()
        else:
            metrics.registry.counter("fleet.autoscale.scale-ins").inc()


class AutoscalerThread:
    """Background driver calling ``step`` every ``interval-s``; the harness
    owns start/stop so replica mutation stays on one thread."""

    def __init__(self, policy: FleetAutoscaler) -> None:
        self.policy = policy
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="fleet-autoscaler", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.policy.cfg.interval_s):
            try:
                self.policy.step()
            except Exception:  # autoscaling must never kill the harness
                metrics.registry.counter("fleet.autoscale.errors").inc()

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
