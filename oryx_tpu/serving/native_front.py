"""ctypes binding for the native (C++) serving data plane.

``maybe_start()`` is the single entry point: the serving layer calls it
during start() and either gets a running :class:`NativeFront` (the epoll
front owns the listen socket; the stdlib server is never created) or
``None`` (any decline — disabled by config, TLS or Basic auth configured,
toolchain missing — and the layer falls back to the pooled stdlib server
with identical behavior).

The division of labor (docs/serving-native.md):

- C++ (native/httpfront.cpp) accepts, parses, and classifies every
  request without the GIL. Cheap rungs — /healthz //readyz //ready
  snapshots, overload fast-429, champion-gated stale answer-cache hits —
  are answered natively from byte templates this module pre-renders with
  the REAL Python resources, so the bytes on the wire are the Python
  front's bytes (only the Date header is stamped in C++, in the same
  IMF-fixdate format).
- Everything else crosses the boundary once, as a micro-batched RBLK
  KIND_HTTP frame (bus/blockcodec.py), and runs through the exact same
  ``layer._dispatch_parsed`` core the stdlib handler uses — tenant
  resolution, admission ladder, tracing, experiments, rendering cannot
  drift between fronts.
- A control thread pushes ladder/tenant snapshots down (overload.py
  stays the single decision-maker; C++ only applies the last pushed
  stage), mirrors answer-cache puts, re-renders liveness snapshots, and
  drains native stats/trace events back into the Python registries.

Parity contract: for every request the native front chooses to answer,
the response bytes are identical to what the Python front would have
produced (tests/serving/test_native_front.py holds the line). When in
doubt the front forwards — csv Accept negotiation, gzip-eligible bodies,
tenant-prefixed control paths, experiments (A/B arms) all route through
Python rather than risk divergence.
"""

from __future__ import annotations

import ctypes
import logging
import struct
import sys
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from email.utils import formatdate
from http.server import BaseHTTPRequestHandler

from oryx_tpu import native
from oryx_tpu.bus import blockcodec
from oryx_tpu.common import metrics, tracing
from oryx_tpu.serving import overload as _overload
from oryx_tpu.serving.web import OryxServingException, Request, render
from oryx_tpu.tenancy import context as _tenancy

log = logging.getLogger(__name__)

# mirrors BaseHTTPRequestHandler.version_string(): "oryx_tpu Python/3.x.y"
_SERVER = f"oryx_tpu Python/{sys.version.split()[0]}"

# liveness endpoints pre-rendered into C++ (post-context-strip forms)
_SNAPSHOT_PATHS = ("/healthz", "/readyz", "/ready")

# hf_stats slot names, in the exact order httpfront.cpp writes them
_SCALARS = (
    "conns_accepted", "conns_closed", "requests", "forwarded",
    "parse_errors", "ans_snapshot", "ans_shed", "ans_stale",
    "m_get", "m_post", "m_delete", "m_head", "m_other",
    "c1xx", "c2xx", "c3xx", "c4xx", "c5xx",
    "lat_count", "lat_sum_us", "events_dropped", "responses_dropped",
    "bytes_in", "bytes_out", "pending_hwm",
)
_N_BUCKETS = 29  # 28 latency buckets + overflow (metrics.Histogram mirror)
_TENANT_SLOTS = 4 + _N_BUCKETS
_TRACE_REC = 184
_TRACE_CAP = 4096  # matches kMaxEvents so one drain empties the ring

_METHOD_NAMES = ("GET", "POST", "DELETE", "HEAD", "OTHER")
_RUNG_NAMES = ("snapshot", "shed", "stale")


def _reason(status: int) -> str:
    entry = BaseHTTPRequestHandler.responses.get(status)
    return entry[0] if entry else ""


def _http_date() -> str:
    return formatdate(time.time(), usegmt=True)


class _Headers:
    """Case-insensitive ``get`` over the original-cased header pairs —
    the same contract email.Message gives ``_dispatch_parsed``."""

    __slots__ = ("_pairs",)

    def __init__(self, pairs):
        self._pairs = pairs

    def get(self, name, default=None):
        lname = name.lower()
        for k, v in self._pairs:
            if k.lower() == lname:
                return v
        return default

    def items(self):
        return list(self._pairs)


def _template_pre(status):
    """Everything up to the Date value; C++ stamps the date at send time
    in the same IMF-fixdate format formatdate(usegmt=True) emits."""
    return (
        f"HTTP/1.1 {status} {_reason(status)}\r\nServer: {_SERVER}\r\nDate: "
    ).encode("latin-1")


def _success_template(status, payload, ct, extra):
    """Template for a rendered (render()) response: mirrors
    Handler._handle_counted's write path byte for byte. The gzip rung is
    handled by C++ forwarding instead (accept_blocks_native), so the
    template always holds the identity body."""
    body = payload
    pre = _template_pre(status)
    lines = [f"Content-Type: {ct}", f"Content-Length: {len(body)}"]
    for k, v in dict(extra).items():
        lines.append(f"{k}: {v}")
    post = ("\r\n" + "\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body
    return pre, post, len(body), status


def _error_template(status, message):
    """Template for _send_error(): plain text body, written even for
    HEAD (body_len 0 disables C++ HEAD stripping to match)."""
    body = f"{status} {message}\n".encode("utf-8")
    pre = _template_pre(status)
    lines = []
    if status == 401:
        lines.append('WWW-Authenticate: Basic realm="Oryx"')
    lines.append("Content-Type: text/plain")
    lines.append(f"Content-Length: {len(body)}")
    post = ("\r\n" + "\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body
    return pre, post, 0, status


def _u8(data: bytes):
    return (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data else \
        (ctypes.c_uint8 * 1)()


def maybe_start(layer, ctx, threads):
    """Start the native front for ``layer`` or return None (fallback).

    Declines (each logged at most once, loudly only when the operator
    forced ``enabled = "true"``):

    - ``oryx.serving.native.enabled = "false"``
    - TLS or Basic auth configured: the stdlib front owns the TLS wrap
      and the 401 gate; a native snapshot answer would bypass auth
    - more tenants than the C++ table holds
    - toolchain missing / native build disabled (ORYX_NATIVE=0)
    """
    cfg = layer.config
    mode = (cfg.get_string("oryx.serving.native.enabled") or "auto").lower()
    if mode not in ("auto", "true", "false"):
        raise ValueError(
            f"oryx.serving.native.enabled must be auto/true/false, got {mode!r}"
        )
    if mode == "false":
        return None
    forced = mode == "true"
    if layer.use_tls or layer.user_name:
        if forced:
            log.warning(
                "oryx.serving.native.enabled=true but TLS/auth is configured; "
                "falling back to the Python front"
            )
        return None
    if layer.tenants is not None and len(layer.tenants.ids()) > 64:
        if forced:
            log.warning(
                "oryx.serving.native.enabled=true but >64 tenants configured; "
                "falling back to the Python front"
            )
        return None
    lib = native.get_library()
    if lib is None or not hasattr(lib, "hf_create"):
        if forced:
            log.warning(
                "oryx.serving.native.enabled=true but the native library is "
                "unavailable (no toolchain or ORYX_NATIVE=0); falling back"
            )
        return None
    max_header = cfg.get_int("oryx.serving.native.max-header-bytes")
    max_body = cfg.get_int("oryx.serving.native.max-body-bytes")
    idle_s = cfg.get_float("oryx.serving.native.idle-timeout-s")
    max_conns = cfg.get_int("oryx.serving.native.max-connections")
    handle = lib.hf_create(layer.port, 128, max_header, max_body, idle_s,
                           max_conns)
    if not handle:
        log.warning("native front failed to bind :%d; falling back",
                    layer.port)
        return None
    front = NativeFront(layer, ctx, lib, handle, threads,
                        max_header=max_header, max_body=max_body)
    front.start()
    return front


class NativeFront:
    def __init__(self, layer, ctx, lib, handle, threads, *, max_header,
                 max_body):
        self._layer = layer
        self._ctx = ctx
        self._lib = lib
        self._handle = handle
        self.port = lib.hf_port(handle)
        cfg = layer.config
        self._interval_s = max(
            0.005, cfg.get_float("oryx.serving.native.control-interval-ms")
            / 1000.0)
        dispatch = cfg.get_optional_int("oryx.serving.native.dispatch-threads")
        self._pool = ThreadPoolExecutor(
            max_workers=dispatch or threads, thread_name_prefix="NativeServe"
        )
        # one full-size record always fits: header + target + headers + body
        self._poll_cap = 64 * 1024 + int(max_header) + int(max_body) + 256
        self._poll_buf = (ctypes.c_uint8 * self._poll_cap)()
        self._trace_buf = (ctypes.c_uint8 * (_TRACE_CAP * _TRACE_REC))()
        self._tenant_names = (
            list(layer.tenants.ids()) if layer.tenants is not None else []
        )
        self._stats_need = len(_SCALARS) + _N_BUCKETS + \
            len(self._tenant_names) * _TENANT_SLOTS
        self._stats_buf = (ctypes.c_uint64 * self._stats_need)()
        # _stats_buf/_trace_buf are shared between the control tick and
        # the on-demand scrape drain in _serve_one
        self._drain_lock = threading.Lock()
        self._stop = threading.Event()
        self._respond_lock = threading.Lock()
        self._closed = False
        self._close_lock = threading.Lock()
        self._closing = False
        # answer-cache mirror: overload.AnswerCache.put -> this queue ->
        # control tick renders and pushes the template down to C++
        self._cache_queue: deque = deque()
        self._mirror_generation = None
        self.poll_thread: threading.Thread | None = None
        self._control_thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self.poll_thread is not None or self._control_thread is not None:
            raise RuntimeError("NativeFront.start() called twice")
        layer = self._layer
        ctx_path = (layer.context_path or "").encode("latin-1")
        self._lib.hf_set_context(self._handle, _u8(ctx_path), len(ctx_path))
        items = [p.encode("latin-1") for p in _overload._EXEMPT_PREFIXES]
        blob = struct.pack("<I", len(items)) + b"".join(
            struct.pack("<H", len(i)) + i for i in items
        )
        self._lib.hf_set_exempt(self._handle, _u8(blob), len(blob))
        self._lib.hf_cache_cap(self._handle,
                               layer.overload_config.cache_entries)
        self._push_shed_template()
        if layer.admission is not None:
            layer.admission.cache.listener = self._on_cache_put
        self.push_control()
        self.poll_thread = threading.Thread(
            target=self._poll_loop, name="NativePoll", daemon=True
        )
        self.poll_thread.start()
        self._control_thread = threading.Thread(
            target=self._control_loop, name="NativeControl", daemon=True
        )
        self._control_thread.start()

    def close(self) -> None:
        with self._close_lock:
            if self._closing:
                return
            self._closing = True
        self._stop.set()
        if self._control_thread is not None:
            self._control_thread.join(timeout=5)
        # two-phase teardown: shutdown unblocks hf_poll (-1) and closes
        # sockets but keeps the handle alive so in-flight hf_respond
        # calls return -1 instead of touching freed memory; hf_close
        # only runs once every thread that could hold the handle is done
        self._lib.hf_shutdown(self._handle)
        if self.poll_thread is not None:
            self.poll_thread.join(timeout=5)
        self._pool.shutdown(wait=True, cancel_futures=True)
        adm = self._layer.admission
        if adm is not None and adm.cache.listener is self._on_cache_put:
            adm.cache.listener = None
        try:
            self._drain_stats()
            self._drain_trace()
        except Exception:
            log.exception("final native stats drain failed")
        # _handle itself is never reassigned: _closed (set under the
        # respond lock) is the gate that keeps hf_respond from touching
        # the handle after hf_close frees it
        with self._respond_lock:
            self._closed = True
        self._lib.hf_close(self._handle)

    # -- forwarded-request data plane ---------------------------------------

    def _poll_loop(self) -> None:
        lib, handle = self._lib, self._handle
        buf, cap = self._poll_buf, self._poll_cap
        while True:
            n = lib.hf_poll(handle, buf, cap, 250)
            if n < 0:
                return  # shutdown
            if n == 0:
                continue
            raw = ctypes.string_at(buf, n)
            try:
                frame = blockcodec.decode_frame(raw)
                records = blockcodec.decode_http_records(
                    frame.payload, frame.count
                )
            except blockcodec.FrameError:
                log.exception("native front produced an undecodable frame")
                metrics.registry.counter("serving.http.frame.errors").inc()
                continue
            for rec in records:
                self._pool.submit(self._serve_one, rec)

    def _serve_one(self, rec) -> None:
        """Mirror of Handler._handle for one pre-parsed request."""
        layer = self._layer
        t0 = time.perf_counter()
        layer._request_began()
        try:
            path = rec.target.split("?", 1)[0]
            ctxp = layer.context_path or ""
            if ctxp and path.startswith(ctxp):
                path = path[len(ctxp):]
            if path.startswith(("/metrics", "/trace")):
                # an ops scrape must reflect every request answered so
                # far — including ones C++ answered since the last
                # control tick — so fold the native counters/spans in
                # before the handler renders the snapshot
                self._drain_stats()
                self._drain_trace()
            headers = _Headers(rec.headers)
            tenant_box = [None]
            try:
                from oryx_tpu.serving.layer import (_dispatch_parsed,
                                                    _observe_request)
                status, payload, ct, extra = _dispatch_parsed(
                    layer, self._ctx, rec.method, rec.target, headers,
                    rec.body, tenant_box,
                )
            except OryxServingException as e:
                _observe_request(rec.method, e.status, t0, layer,
                                 tenant_box[0])
                self._respond(rec, self._error_bytes(e.status, e.message))
                return
            except Exception:
                log.exception("internal error handling %s %s", rec.method,
                              rec.target)
                _observe_request(rec.method, 500, t0, layer, tenant_box[0])
                self._respond(rec, self._error_bytes(500, "internal error"))
                return
            _observe_request(rec.method, status, t0, layer, tenant_box[0])
            self._respond(
                rec,
                self._assemble(status, payload, ct, extra,
                               headers.get("Accept-Encoding", ""),
                               rec.method == "HEAD"),
            )
        finally:
            layer._request_ended()

    def _assemble(self, status, payload, ct, extra, accept_encoding,
                  is_head) -> bytes:
        """Byte-for-byte mirror of Handler._handle_counted's write path."""
        from oryx_tpu.serving.layer import gzip_compress

        body = payload
        headers = dict(extra)
        if len(body) > 1024 and "gzip" in accept_encoding:
            body = gzip_compress(body)
            headers["Content-Encoding"] = "gzip"
        lines = [
            f"HTTP/1.1 {status} {_reason(status)}",
            f"Server: {_SERVER}",
            f"Date: {_http_date()}",
            f"Content-Type: {ct}",
            f"Content-Length: {len(body)}",
        ]
        for k, v in headers.items():
            lines.append(f"{k}: {v}")
        head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
        return head if is_head else head + body

    def _error_bytes(self, status, message) -> bytes:
        """Byte-for-byte mirror of Handler._send_error (the error body is
        written even for HEAD, matching the Python front)."""
        body = f"{status} {message}\n".encode("utf-8")
        lines = [
            f"HTTP/1.1 {status} {_reason(status)}",
            f"Server: {_SERVER}",
            f"Date: {_http_date()}",
        ]
        if status == 401:
            lines.append('WWW-Authenticate: Basic realm="Oryx"')
        lines.append("Content-Type: text/plain")
        lines.append(f"Content-Length: {len(body)}")
        return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1") + body

    def _respond(self, rec, data: bytes) -> None:
        with self._respond_lock:
            if self._closed:
                return
            self._lib.hf_respond(self._handle, rec.conn_id, rec.req_id,
                                 _u8(data), len(data), 0)

    # -- control plane -------------------------------------------------------

    def _control_loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                self.push_control()
            except Exception:
                log.exception("native front control tick failed")

    def push_control(self) -> None:
        """One control tick: evaluate the ladder, push stages + tenant
        stages + fresh snapshots down, mirror cache puts, drain stats and
        trace events back up. Public so tests can force a tick."""
        adm = self._layer.admission
        if adm is not None:
            try:
                adm.evaluate()
            except Exception:
                log.exception("admission evaluate failed")
        self._push_ladder()
        self._sync_cache()
        self.push_snapshots()
        self._drain_stats()
        self._drain_trace()

    def _flags(self) -> int:
        layer = self._layer
        flags = 0
        # experiments assign sticky A/B arms and stamp ARM_HEADER on
        # data-plane responses; every native rung would skip that, so all
        # native answering is off while an experiment coordinator exists
        if layer.experiments is None:
            flags |= 1  # snapshots
            if layer.admission is not None:
                flags |= 2 | 4  # shed fast-429 + stale cache rungs
        if layer.tenants is not None:
            flags |= 8
        return flags

    def _push_ladder(self) -> None:
        layer = self._layer
        adm = layer.admission
        stage = adm.stage if adm is not None else 0
        retry = layer.overload_config.retry_after_s
        self._lib.hf_set_ladder(self._handle, stage, retry, self._flags())
        if layer.tenants is not None and adm is not None:
            names = self._tenant_names
            try:
                default_idx = names.index(layer.tenants.default_tenant)
            except ValueError:
                default_idx = -1
            parts = [struct.pack("<iI", default_idx, len(names))]
            for name in names:
                nb = name.encode("utf-8")
                parts.append(
                    struct.pack("<HBB", len(nb), adm.tenant_stage(name), 0)
                    + nb
                )
            blob = b"".join(parts)
            self._lib.hf_set_tenants(self._handle, _u8(blob), len(blob))

    def _push_shed_template(self) -> None:
        from oryx_tpu.serving.layer import _shed_response

        resp = _shed_response(self._layer.overload_config.retry_after_s)
        resp.headers[_overload.SHED_HEADER] = "shed"
        status, payload, ct, extra = render(resp, "application/json")
        pre, post, body_len, _ = _success_template(status, payload, ct, extra)
        self._lib.hf_set_shed_template(
            self._handle, _u8(pre), len(pre), _u8(post), len(post), body_len
        )

    def push_snapshots(self) -> None:
        """Re-render the liveness endpoints with the REAL resources and
        push the byte templates down. Runs every control tick so the
        native answers track health/readiness within one interval.
        Public: begin_drain() pushes immediately so /readyz flips to 503
        before the drain starts."""
        ctx_path = self._layer.context_path or ""
        for path in _SNAPSHOT_PATHS:
            pre, post, body_len, status = self._snapshot_template(path)
            raw = (ctx_path + path).encode("latin-1")
            self._lib.hf_set_snapshot(
                self._handle, _u8(raw), len(raw), _u8(pre), len(pre),
                _u8(post), len(post), body_len, status,
            )

    def _snapshot_template(self, path):
        """Dispatch ``path`` straight into the router (not through
        _dispatch_parsed: a per-tick synthetic request must not roll root
        sampling dice or bump request counters) and template the result."""
        req = Request(method="GET", path=path, params={}, query={},
                      headers={}, body=b"")
        try:
            with _tenancy.tenant_scope(None):
                response = self._layer.router.dispatch(self._ctx, req)
            status, payload, ct, extra = render(response, "application/json")
        except OryxServingException as e:
            return _error_template(e.status, e.message)
        except Exception:
            log.exception("snapshot render failed for %s", path)
            return _error_template(500, "internal error")
        return _success_template(status, payload, ct, extra)

    # -- answer-cache mirror -------------------------------------------------

    def _on_cache_put(self, key, answer) -> None:
        # called from request threads under no lock: just enqueue; the
        # control tick renders (rendering needs no request context)
        self._cache_queue.append((key, answer))

    def _sync_cache(self) -> None:
        adm = self._layer.admission
        if adm is None:
            return
        champion = adm.generation()
        if champion != self._mirror_generation:
            # promotion/rollback: the Python cache gates per-lookup, the
            # C++ mirror is wiped wholesale (same observable effect)
            self._mirror_generation = champion
            self._lib.hf_cache_clear(self._handle)
            self._cache_queue.clear()
        while True:
            try:
                key, answer = self._cache_queue.popleft()
            except IndexError:
                break
            if answer.generation != champion:
                continue
            from oryx_tpu.serving.web import Response

            resp = Response(
                answer.status, answer.payload, answer.content_type,
                headers={_overload.SHED_HEADER: "stale"},
            )
            try:
                status, payload, ct, extra = render(resp, "application/json")
            except Exception:
                log.exception("cache mirror render failed for %s", key)
                continue
            pre, post, body_len, _ = _success_template(
                status, payload, ct, extra
            )
            kb = key.encode("utf-8")
            self._lib.hf_cache_put(
                self._handle, _u8(kb), len(kb), _u8(pre), len(pre),
                _u8(post), len(post), body_len,
            )

    # -- stats / trace drains ------------------------------------------------

    def _drain_stats(self) -> None:
        with self._drain_lock:
            self._drain_stats_locked()

    def _drain_stats_locked(self) -> None:
        n_tenants = len(self._tenant_names)
        got = self._lib.hf_stats(self._handle, self._stats_buf,
                                 self._stats_need, n_tenants)
        if got != self._stats_need:
            return
        vals = list(self._stats_buf)
        if not any(vals):
            return
        s = dict(zip(_SCALARS, vals))
        buckets = vals[len(_SCALARS):len(_SCALARS) + _N_BUCKETS]
        reg = metrics.registry
        im = self._layer.instance_metrics

        def bump(name, n):
            if n:
                reg.counter(name).inc(n)

        bump("serving.http.connections", s["conns_accepted"])
        bump("serving.http.requests", s["requests"])
        bump("serving.http.forwarded", s["forwarded"])
        bump("serving.http.parse.errors", s["parse_errors"])
        bump("serving.http.read.bytes", s["bytes_in"])
        bump("serving.http.write.bytes", s["bytes_out"])
        bump("serving.http.events.dropped", s["events_dropped"])
        bump("serving.http.native-answered.snapshot", s["ans_snapshot"])
        bump("serving.http.native-answered.shed", s["ans_shed"])
        bump("serving.http.native-answered.stale", s["ans_stale"])
        im.gauge("serving.http.queue.depth").set(s["pending_hwm"])
        # natively-answered requests feed the same serving.* families the
        # Python front's _observe_request feeds, so dashboards see one
        # stream regardless of which side answered
        for i, mname in enumerate(_METHOD_NAMES[:4]):
            n = vals[8 + i]
            if n:
                reg.counter(f"serving.requests.{mname}").inc(n)
                im.counter(f"serving.requests.{mname}").inc(n)
        for cls in range(1, 6):
            n = s[f"c{cls}xx"]
            if n:
                reg.counter(f"serving.responses.{cls}xx").inc(n)
                im.counter(f"serving.responses.{cls}xx").inc(n)
        if s["lat_count"]:
            secs = s["lat_sum_us"] / 1e6
            reg.histogram("serving.request.seconds").merge_buckets(
                buckets, secs
            )
            im.histogram("serving.request.seconds").merge_buckets(
                buckets, secs
            )
            generation = self._layer.health.live_generation or "none"
            im.counter(f"serving.requests.generation.{generation}").inc(
                s["lat_count"]
            )
            im.histogram(
                f"serving.request.seconds.generation.{generation}"
            ).merge_buckets(buckets, secs)
        adm = self._layer.admission
        champion = adm.generation() if adm is not None else None
        for stage_name, n in (("shed", s["ans_shed"]),
                              ("stale", s["ans_stale"])):
            if not n:
                continue
            name = f"serving.overload.shed.{stage_name}"
            reg.counter(name).inc(n)
            im.counter(name).inc(n)
            generation = champion or self._layer.health.live_generation
            if generation:
                im.counter(f"{name}.generation.{generation}").inc(n)
        off = len(_SCALARS) + _N_BUCKETS
        for i, tenant in enumerate(self._tenant_names):
            blk = vals[off + i * _TENANT_SLOTS: off + (i + 1) * _TENANT_SLOTS]
            count, sum_us, shed_stale, shed_shed = blk[:4]
            if count:
                im.counter(f"serving.requests.tenant.{tenant}").inc(count)
                im.histogram(
                    f"serving.request.seconds.tenant.{tenant}"
                ).merge_buckets(blk[4:], sum_us / 1e6)
            if shed_shed:
                im.counter(
                    f"serving.overload.shed.shed.tenant.{tenant}"
                ).inc(shed_shed)
            if shed_stale:
                im.counter(
                    f"serving.overload.shed.stale.tenant.{tenant}"
                ).inc(shed_stale)

    def _drain_trace(self) -> None:
        with self._drain_lock:
            self._drain_trace_locked()

    def _drain_trace_locked(self) -> None:
        n = self._lib.hf_drain_trace(self._handle, self._trace_buf,
                                     len(self._trace_buf))
        if n <= 0:
            return
        buf = bytes(self._trace_buf[: n * _TRACE_REC])
        for i in range(n):
            base = i * _TRACE_REC
            (wall_ms,) = struct.unpack_from("<Q", buf, base)
            dur_us, status = struct.unpack_from("<IH", buf, base + 8)
            rung = buf[base + 14]
            method = buf[base + 15]
            tenant_idx, tp_len, path_len = struct.unpack_from(
                "<hHH", buf, base + 16
            )
            tp = buf[base + 24: base + 24 + tp_len].decode(
                "latin-1", "replace"
            )
            path = buf[base + 88: base + 88 + path_len].decode(
                "latin-1", "replace"
            )
            parent = tracing.parse_traceparent(tp)
            if parent is None or not parent.sampled:
                continue
            attrs = {
                "path": path,
                "method": _METHOD_NAMES[method] if method < 5 else "OTHER",
                "status": status,
                "native_rung": _RUNG_NAMES[rung] if rung < 3 else "?",
            }
            if 0 <= tenant_idx < len(self._tenant_names):
                attrs["tenant"] = self._tenant_names[tenant_idx]
            tracing.record_span(
                "serving.request", parent.child(), parent.span_id,
                wall_ms / 1000.0, dur_us / 1e6, attrs,
            )
