"""The serving layer process: HTTP server + model-manager lifecycle.

Rebuild of ServingLayer (framework/oryx-lambda-serving/.../ServingLayer
.java:55-339) and ModelManagerListener (.../ModelManagerListener.java:
62-238): on start, creates the input-topic producer (unless read-only),
loads the configured ServingModelManager, starts a daemon thread replaying
the update topic from the beginning into manager.consume, and serves the
registered resources over HTTP with optional Basic auth, gzip, a context
path, and /ready readiness gating (Ready.java:34-42).

Divergence from the reference, by design: Tomcat becomes a threaded
stdlib HTTP(S) server. TLS is native (ServingLayer.makeConnector:194-245
parity): configure `oryx.serving.api.keystore-file`/`key-file` (PEM) and
the server listens on `secure-port` over TLS >= 1.2. DIGEST becomes
Basic-over-TLS — Basic under TLS carries the same security as DIGEST's
challenge dance did in 2015, and credentials over plaintext are refused
at startup unless `allow-insecure-auth = true` (for deployments behind a
TLS terminator). Jersey package scanning becomes import of the modules
named in oryx.serving.application-resources.
"""

from __future__ import annotations

import base64
import gzip
import importlib
import logging
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, HTTPServer
from urllib.parse import parse_qs, urlsplit

from oryx_tpu.bus.core import get_broker
from oryx_tpu.common import metrics, profiling, tracing
from oryx_tpu.common.config import Config
from oryx_tpu.common.lang import load_instance_of
from oryx_tpu.common.resilience import RetryPolicy, SupervisedThread
from oryx_tpu.experiments import routing as _exp_routing
from oryx_tpu.serving import overload as _overload
from oryx_tpu.tenancy import context as _tenancy
from oryx_tpu.serving.web import (
    OryxServingException,
    Request,
    Response,
    Router,
    ServingContext,
    render,
    resource,
)

log = logging.getLogger(__name__)


class _PooledHTTPServer(HTTPServer):
    """HTTP server with a bounded worker pool — the Tomcat maxThreads
    analogue (ServingLayer.java:225-228 tunes 400 threads). A worker owns
    a connection for its keep-alive lifetime; beyond `threads` concurrent
    connections, accepts queue instead of spawning unbounded threads the
    way ThreadingHTTPServer does.

    TLS is wrapped per-connection on the pool worker, never on the
    listener: a client that connects and stalls mid-handshake costs one
    worker, not the accept loop (Tomcat's connector does the same).
    Accepted sockets get a read timeout so idle keep-alive connections
    cannot pin workers past shutdown, and live connections are tracked so
    server_close() can unblock every worker deterministically."""

    daemon_threads = True
    read_timeout = 30.0

    def __init__(self, addr, handler_cls, threads: int, tls_ctx=None) -> None:
        super().__init__(addr, handler_cls)
        self._tls_ctx = tls_ctx
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, threads), thread_name_prefix="ServingWorker"
        )

    def process_request(self, request, client_address):
        self._pool.submit(self._work, request, client_address)

    def _work(self, request, client_address):
        conn = request
        try:
            conn.settimeout(self.read_timeout)
            if self._tls_ctx is not None:
                try:
                    conn = self._tls_ctx.wrap_socket(conn, server_side=True)
                except Exception as e:
                    log.debug("TLS handshake failed from %s: %s", client_address, e)
                    return
            with self._conns_lock:
                self._conns.add(conn)
            try:
                self.finish_request(conn, client_address)
            except Exception:
                self.handle_error(conn, client_address)
            finally:
                with self._conns_lock:
                    self._conns.discard(conn)
        finally:
            self.shutdown_request(conn)

    def server_close(self):
        super().server_close()
        with self._conns_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        # Sockets are closed, so workers unblock promptly; waiting here keeps
        # interpreter exit from hanging on the executor's atexit join.
        self._pool.shutdown(wait=True, cancel_futures=True)


def _import_recursively(module_name: str) -> None:
    """Import a module — and, for a package, every submodule under it — so
    @resource decorators register. The OryxApplication package-scan
    analogue (OryxApplication.java:62-86 scans packages with Reflections,
    so configs may name either a module or a whole package)."""
    mod = importlib.import_module(module_name)
    path = getattr(mod, "__path__", None)
    if path is not None:
        import pkgutil

        def _fail(name: str) -> None:
            # default onerror swallows subpackage ImportErrors, which would
            # leave resources silently unregistered — fail loudly instead
            raise ImportError(f"cannot import serving resource package {name}")

        for info in pkgutil.walk_packages(path, prefix=module_name + ".", onerror=_fail):
            importlib.import_module(info.name)


class ServingHealth:
    """Liveness/readiness state for the serving layer (docs/resilience.md).

    The update-stream consumer reports in: every successful poll marks the
    stream healthy, every poll error marks it down. When the stream is
    down the layer keeps answering from the last good model — *degraded*,
    not dead — and `staleness()` says how old that model's last delta is.
    `stream_healthy` is None until the first poll (or when no update topic
    is configured), which readiness treats as "not known to be down".
    """

    def __init__(self, clock=time.time) -> None:
        self._clock = clock
        # one lock over every flag: the update-consume thread writes the
        # stream marks and generation id, the shutdown path flips
        # draining, and HTTP handler threads read all of them from
        # /ready, /healthz and /readyz (manual lockset audit riding the
        # oryxlint PR — the pass can't see this class because its thread
        # entry lives in ServingLayer)
        self._mu = threading.Lock()
        self._stream_healthy: bool | None = None
        self._last_update_time: float | None = None
        self.consume_thread: SupervisedThread | None = None
        self._draining: bool = False
        self._live_generation: str | None = None
        self._challenger_generation: str | None = None

    @property
    def stream_healthy(self) -> bool | None:
        with self._mu:
            return self._stream_healthy

    @property
    def last_update_time(self) -> float | None:
        with self._mu:
            return self._last_update_time

    # drain-aware shutdown: once True, /ready and /readyz answer 503 so
    # load balancers stop routing here, while in-flight requests (and
    # any still arriving from stale routing tables) complete normally
    @property
    def draining(self) -> bool:
        with self._mu:
            return self._draining

    @draining.setter
    def draining(self, value: bool) -> None:
        with self._mu:
            self._draining = bool(value)

    # generation id of the live model (set by the GenerationTracker as
    # MODEL/MODEL-REF records flow past); None until one arrives or
    # when models carry no generation identity
    @property
    def live_generation(self) -> str | None:
        with self._mu:
            return self._live_generation

    @live_generation.setter
    def live_generation(self, value: str | None) -> None:
        with self._mu:
            self._live_generation = value

    # generation id of the challenger arm while an online experiment is
    # active (docs/experiments.md); None otherwise
    @property
    def challenger_generation(self) -> str | None:
        with self._mu:
            return self._challenger_generation

    @challenger_generation.setter
    def challenger_generation(self, value: str | None) -> None:
        with self._mu:
            self._challenger_generation = value

    def mark_stream_ok(self) -> None:
        with self._mu:
            self._stream_healthy = True
        metrics.registry.gauge("serving.update-stream.healthy").set(1)

    def mark_stream_down(self) -> None:
        with self._mu:
            self._stream_healthy = False
        metrics.registry.gauge("serving.update-stream.healthy").set(0)

    def mark_update(self) -> None:
        with self._mu:
            self._last_update_time = self._clock()

    def staleness(self) -> float | None:
        """Seconds since the last model update was applied, or None if no
        update has ever arrived. Also published as a gauge."""
        with self._mu:
            last = self._last_update_time
        if last is None:
            return None
        s = self._clock() - last
        metrics.registry.gauge("serving.model.staleness-seconds").set(s)
        return s

    @property
    def alive(self) -> bool:
        """False only once the supervised consume thread exhausted its
        restart policy — the layer can no longer recover by itself."""
        t = self.consume_thread
        return t is None or not t.gave_up

    @property
    def degraded(self) -> bool:
        return self.stream_healthy is False


@resource("GET", "/ready")
def _ready(ctx: ServingContext, req: Request) -> Response:
    """503 until the model is sufficiently loaded (Ready.java:34-42) — and
    again once the instance is draining for shutdown."""
    if ctx.health is not None and ctx.health.draining:
        return Response(503, None)
    if _model_ready(ctx):
        return Response(200, None)
    return Response(503, None)


@resource("GET", "/healthz")
def _healthz(ctx: ServingContext, req: Request) -> Response:
    """Liveness + degraded-mode report. 200 while the process can serve —
    including degraded (update stream down, answering from the last good
    model); 503 only when the update consumer has given up for good.

    The ``status`` field unifies the two degraded-mode notions (last-good
    -model serving per reference.conf's degraded contract, and the shed
    ladder's reduced-quality stages) into one operator-facing word:
    down > draining > degraded > ok; ``shed_stage`` names the ladder rung
    currently serving answers. ``cli health`` renders exactly this."""
    health = ctx.health
    if health is None:
        return Response(200, {"alive": True}, content_type="application/json")
    stage = ctx.admission.stage if ctx.admission is not None else _overload.STAGE_FULL
    if not health.alive:
        status = "down"
    elif health.draining:
        status = "draining"
    elif health.degraded or stage > _overload.STAGE_FULL:
        status = "degraded"
    else:
        status = "ok"
    body = {
        "alive": health.alive,
        "degraded": health.degraded or stage > _overload.STAGE_FULL,
        "status": status,
        "shed_stage": _overload.STAGE_NAMES[stage],
        "stream_healthy": health.stream_healthy,
        "staleness_seconds": health.staleness(),
        "live_generation": health.live_generation,
        "challenger_generation": health.challenger_generation,
    }
    # multi-tenant serving: the model manager is a TenantServingMux and
    # each tenant has its own live generation (cli health renders the
    # per-tenant skew line from exactly this)
    live_generations = getattr(ctx.model_manager, "live_generations", None)
    if callable(live_generations):
        body["tenants"] = live_generations()
    return Response(200 if health.alive else 503, body, content_type="application/json")


@resource("GET", "/readyz")
def _readyz(ctx: ServingContext, req: Request) -> Response:
    """Strict readiness for load balancers: the model must be loaded AND
    the update stream must not be known-down AND the instance must not be
    draining. Degraded/draining instances keep /healthz green but drop
    out of /readyz rotation."""
    ready = _model_ready(ctx)
    stream_ok = ctx.health is None or ctx.health.stream_healthy is not False
    draining = ctx.health is not None and ctx.health.draining
    body = {"model_ready": ready, "stream_ok": stream_ok, "draining": draining}
    ok = ready and stream_ok and not draining
    return Response(200 if ok else 503, body, content_type="application/json")


@resource("GET", "/metrics")
def _metrics(ctx: ServingContext, req: Request) -> Response:
    """Request QPS/latency histograms and model state, as JSON — the
    observability the reference lacks (SURVEY.md §5). Request-path metrics
    come from this instance's own registry when one is attached, so N
    replicas in one process each report their *own* traffic (the fleet
    harness computes per-replica SLO burn rates from exactly this)."""
    from oryx_tpu.common import ledger

    if ledger.enabled():
        # resources.<kind>.live gauges: the leak alarm for week-long runs
        ledger.ledger.refresh()
    snap = metrics.registry.snapshot()
    if ctx.instance_metrics is not None:
        # instance-scoped values shadow the process-global ones: in a
        # multi-replica process the shared registry aggregates all
        # replicas, the instance registry is this replica alone
        snap.update(ctx.instance_metrics.snapshot())
    manager = ctx.model_manager
    model = manager.get_model() if manager is not None else None
    if model is not None:
        snap["serving.model.fraction_loaded"] = {
            "type": "gauge",
            "value": getattr(model, "get_fraction_loaded", lambda: 1.0)(),
        }
    if ctx.health is not None and ctx.health.live_generation is not None:
        snap["serving.model.live_generation"] = {
            "type": "gauge",
            "value": ctx.health.live_generation,
        }
    accept = next(
        (v for k, v in req.headers.items() if k.lower() == "accept"), ""
    )
    if (
        req.q1("format") == "prometheus"
        or "text/plain" in accept
        or "openmetrics" in accept
    ) and req.q1("format") != "json":
        # standard-scraper exposition (Prometheus sends
        # `Accept: text/plain;version=0.0.4`); live_generation may be a
        # non-numeric id, which the renderer would choke on — drop it
        # from the text form (scrapers read the per-generation request
        # counters instead)
        prom = {
            k: v
            for k, v in snap.items()
            if not (k == "serving.model.live_generation" and _non_numeric(v))
        }
        return Response(
            200,
            metrics.render_prometheus(prom),
            content_type=metrics.PROMETHEUS_CONTENT_TYPE,
        )
    return Response(200, snap, content_type="application/json")


def _non_numeric(entry) -> bool:
    try:
        float(entry.get("value"))
        return False
    except (TypeError, ValueError):
        return True


@resource("GET", "/trace")
def _trace(ctx: ServingContext, req: Request) -> Response:
    """This process's recorded spans: Chrome-trace/Perfetto JSON by
    default (load in chrome://tracing or ui.perfetto.dev), or the raw
    span list with parent links under ?format=spans. ?trace=<32hex>
    filters to one trace id — the loadgen client records the ids it
    sent, so a request's server-side breakdown is one GET away."""
    trace_id = req.q1("trace")
    if req.q1("format") == "spans":
        body = {"spans": tracing.spans(trace_id), **tracing.stats()}
    else:
        body = tracing.export_chrome(trace_id)
    return Response(200, body, content_type="application/json")


@resource("POST", "/debug/profile")
def _debug_profile(ctx: ServingContext, req: Request) -> Response:
    """On-demand JAX profiler capture: trace this process's devices for
    ?seconds=N (default 1, capped at 30), write the xprof trace under
    oryx.serving.compute.profile-dir, return the path. 503 when no
    profile dir is configured or the profiler cannot start."""
    profile_dir = profiling.profile_dir_from_config(ctx.config, "serving")
    if not profile_dir:
        raise OryxServingException(
            503, "oryx.serving.compute.profile-dir is not configured"
        )
    seconds = min(30.0, max(0.0, req.q_float("seconds", 1.0)))
    try:
        target = profiling.capture(profile_dir, "serving-ondemand", seconds)
    except RuntimeError as e:
        raise OryxServingException(503, str(e))
    metrics.registry.counter("serving.debug.profiles").inc()
    return Response(
        200, {"path": target, "seconds": seconds}, content_type="application/json"
    )


@resource("GET", "/model/generations")
def _model_generations(ctx: ServingContext, req: Request) -> Response:
    """The registry's view of the model dir plus what this instance is
    actually serving — the skew between the two is what the `health` CLI
    probe alerts on (docs/model-registry.md)."""
    registry = ctx.registry
    if registry is None:
        raise OryxServingException(404, "no model registry configured")
    generations = []
    for gen_id in registry.list_generations():
        manifest = registry.read_manifest(gen_id)
        entry = {"generation_id": gen_id}
        if manifest is not None:
            entry.update(
                status=manifest.status,
                parent_id=manifest.parent_id,
                eval_metric=manifest.eval_metric,
                created_at_ms=manifest.created_at_ms,
            )
        generations.append(entry)
    body = {
        "live_generation": ctx.health.live_generation if ctx.health else None,
        "champion": registry.champion_id(),
        "generations": generations,
    }
    return Response(200, body, content_type="application/json")


@resource("POST", "/model/rollback/{generationID}")
def _model_rollback(ctx: ServingContext, req: Request) -> Response:
    """Republish an archived generation onto the update topic so every
    consumer (this instance, other serving replicas, the speed layer)
    converges on it, and move the CHAMPION pointer so subsequent batch
    runs gate/warm-start against the rolled-back generation."""
    registry = ctx.registry
    if registry is None:
        raise OryxServingException(404, "no model registry configured")
    if ctx.config.get_bool("oryx.serving.api.read-only"):
        raise OryxServingException(403, "serving layer is read-only")
    if ctx.rollback_publisher is None:
        raise OryxServingException(503, "no update topic configured")
    generation_id = req.params["generationID"]
    if not registry.has_generation(generation_id):
        raise OryxServingException(404, f"no such generation {generation_id}")
    key = ctx.rollback_publisher(generation_id)
    registry.set_champion(generation_id)
    metrics.registry.counter("serving.model.rollbacks").inc()
    log.warning("rollback: republished generation %s as %s", generation_id, key)
    body = {"generation_id": generation_id, "published_as": key}
    return Response(200, body, content_type="application/json")


@resource("GET", "/experiments")
def _experiments_report(ctx: ServingContext, req: Request) -> Response:
    """Online-experiment report (docs/experiments.md): arm assignment
    config, champion/challenger generations, per-arm online metrics and
    the standing online-gate decision. Always answers — with experiments
    disabled the body just says so, which keeps `cli experiments` and
    fleet dashboards probe-safe."""
    if ctx.experiments is None:
        return Response(
            200,
            {"enabled": False, "active": False},
            content_type="application/json",
        )
    return Response(200, ctx.experiments.report(), content_type="application/json")


def _observe_request(
    method: str, status: int, t0: float, layer=None, tenant: str | None = None
) -> None:
    dt = time.perf_counter() - t0
    metrics.registry.counter(f"serving.requests.{method}").inc()
    metrics.registry.counter(f"serving.responses.{status // 100}xx").inc()
    metrics.registry.histogram("serving.request.seconds").observe(dt)
    if layer is None:
        return
    # instance-scoped mirrors (per-replica truth in a multi-replica
    # process) plus the per-generation counter that makes a rotation
    # observable: the live generation at response time is stamped on the
    # request, so a rotation shows up as traffic moving between
    # serving.requests.generation.<gen> counters, not as a gap
    im = layer.instance_metrics
    im.counter(f"serving.requests.{method}").inc()
    im.counter(f"serving.responses.{status // 100}xx").inc()
    im.histogram("serving.request.seconds").observe(dt)
    generation = layer.health.live_generation or "none"
    im.counter(f"serving.requests.generation.{generation}").inc()
    # generation-labeled latency: per-generation dashboards (and the
    # per-arm comparison while an experiment runs) need the latency
    # distribution split the same way the request counter is
    im.histogram(f"serving.request.seconds.generation.{generation}").observe(dt)
    if tenant is not None:
        # tenant-labeled twins: per-tenant SLO burn and rate are computed
        # from these on a shared multi-tenant fleet (docs/multi-tenancy.md)
        im.counter(f"serving.requests.tenant.{tenant}").inc()
        im.histogram(f"serving.request.seconds.tenant.{tenant}").observe(dt)


def observe_block_freshness(raw_trace, instance_metrics=None):
    """Parse an update block's transport-carried ``@trc`` header and feed
    the freshness histogram: seconds from the origin timestamp the
    publisher stamped (earliest event-ingest time for speed updates,
    publish time for model publishes) to visibility on this replica.
    Returns the parsed :class:`tracing.BlockTrace` (or None) so the
    caller can continue the publisher's trace."""
    info = tracing.parse_header(raw_trace)
    if info is None:
        return None
    if info.ingest_ms is not None:
        age_s = max(0.0, time.time() - info.ingest_ms / 1000.0)
        metrics.registry.histogram("serving.freshness.seconds").observe(age_s)
        if instance_metrics is not None:
            instance_metrics.histogram("serving.freshness.seconds").observe(
                age_s
            )
    return info


def _block_has_model(block) -> bool:
    keys = getattr(block, "keys", None)
    if keys is None:
        return False
    return bool((keys == b"MODEL").any() or (keys == b"MODEL-REF").any())


def _model_ready(ctx: ServingContext) -> bool:
    manager = ctx.model_manager
    if manager is None:
        return False
    min_fraction = ctx.config.get_float("oryx.serving.min-model-load-fraction")
    tenant_models = getattr(manager, "tenant_models", None)
    if tenant_models is not None:
        # multi-tenant mux: the replica is ready when EVERY tenant's
        # model is loaded past the threshold — readiness gates fleet
        # rotation, and rotating onto a replica missing one tenant's
        # model would 503 that tenant's traffic
        models = tenant_models()
        if not models:
            return False
        return all(
            m is not None
            and getattr(m, "get_fraction_loaded", lambda: 1.0)() >= min_fraction
            for m in models.values()
        )
    model = manager.get_model()
    if model is None:
        return False
    fraction = getattr(model, "get_fraction_loaded", lambda: 1.0)()
    return fraction >= min_fraction


class ServingLayer:
    def __init__(self, config: Config) -> None:
        self.config = config
        from oryx_tpu.parallel.distributed import maybe_enable_compile_cache

        maybe_enable_compile_cache(config)  # device scans cache like training
        tracing.configure_from(config)
        self.port = config.get_int("oryx.serving.api.port")
        self.context_path = config.get_string("oryx.serving.api.context-path").rstrip("/")
        self.read_only = config.get_bool("oryx.serving.api.read-only")
        self.user_name = config.get_optional_string("oryx.serving.api.user-name")
        self.password = config.get_optional_string("oryx.serving.api.password")
        if self.user_name and not self.password:
            # auth requires BOTH set (reference.conf contract); a missing
            # password must not silently degrade to a guessable credential
            raise ValueError("oryx.serving.api.user-name set without password")
        self.keystore_file = config.get_optional_string("oryx.serving.api.keystore-file")
        self.key_file = config.get_optional_string("oryx.serving.api.key-file")
        self.keystore_password = config.get_optional_string(
            "oryx.serving.api.keystore-password"
        )
        if bool(self.keystore_file) != bool(self.key_file):
            raise ValueError(
                "oryx.serving.api.keystore-file and key-file must be set together"
            )
        self.use_tls = bool(self.keystore_file)
        if self.use_tls:
            self.port = config.get_int("oryx.serving.api.secure-port")
        if self.user_name and not self.use_tls:
            # Basic credentials in cleartext are a downgrade the reference
            # never allows (its DIGEST realm runs under a TLS constraint,
            # ServingLayer.java:290-321); require explicit opt-in
            if not (config.get_optional_bool("oryx.serving.api.allow-insecure-auth") or False):
                raise ValueError(
                    "oryx.serving.api.user-name is set but TLS is not configured; "
                    "set keystore-file/key-file, or allow-insecure-auth = true "
                    "behind a TLS terminator"
                )
        self.no_init_topics = config.get_optional_bool("oryx.serving.no-init-topics") or False
        self.model_manager_class = config.get_optional_string("oryx.serving.model-manager-class")
        self.app_resources = config.get_optional_strings("oryx.serving.application-resources")

        # multi-tenant mode (docs/multi-tenancy.md): the oryx.tenancy
        # block declares N tenants this one replica serves — None keeps
        # the classic single-tenant wiring byte-for-byte
        from oryx_tpu.tenancy.spec import TenantRegistry

        self.tenants = TenantRegistry.from_config(config)
        self.tenant_mux = None
        if self.tenants is not None:
            # one router hosts every tenant's app endpoints
            merged = list(self.app_resources or [])
            for mod in self.tenants.resource_modules():
                if mod not in merged:
                    merged.append(mod)
            self.app_resources = merged

        # quantized pipelined scan engine: push oryx.serving.scan.* into
        # the micro-batcher scheduler and the scan kernels before either
        # compiles/spins up (jitted programs bake the knobs in at trace
        # time; the default batcher is created on first use)
        from oryx_tpu.ops.pallas_topn import configure_scan
        from oryx_tpu.serving.batcher import configure_fairness, configure_scheduler

        if self.tenants is not None and self.tenants.fair_share:
            # DRR fair scheduling in the adaptive batcher: each tenant's
            # entries drain from a private queue at its weighted share
            configure_fairness(self.tenants.weights(), self.tenants.quantum)
        configure_scheduler(
            max_batch=config.get_optional_int("oryx.serving.scan.max-batch"),
            max_inflight=config.get_optional_int("oryx.serving.scan.max-inflight"),
            latency_budget_ms=config.get_optional_float(
                "oryx.serving.scan.latency-budget-ms"
            ),
            # bounded queue: full queue => immediate shed decision instead
            # of the unbounded queued-behind-pipeline wait (BENCH_r05)
            max_queue=config.get_optional_int("oryx.serving.overload.max-queue"),
        )
        configure_scan(
            oversample=config.get_optional_int("oryx.serving.scan.oversample"),
            chunk=config.get_optional_int("oryx.serving.scan.chunk"),
            block=config.get_optional_int("oryx.serving.scan.block"),
        )
        from oryx_tpu.ops.ivf import configure_ann

        configure_ann(
            enabled=config.get_optional_bool("oryx.serving.scan.ann.enabled"),
            cells=config.get_optional_int("oryx.serving.scan.ann.cells"),
            nprobe=config.get_optional_int("oryx.serving.scan.ann.nprobe"),
            probe_fraction=config.get_optional_float(
                "oryx.serving.scan.ann.probe-fraction"
            ),
            min_items=config.get_optional_int("oryx.serving.scan.ann.min-items"),
            overlay_capacity=config.get_optional_int(
                "oryx.serving.scan.ann.overlay-capacity"
            ),
            query_block=config.get_optional_int("oryx.serving.scan.ann.query-block"),
            tile_chunks=config.get_optional_int("oryx.serving.scan.ann.tile-chunks"),
            host_stage1={"true": True, "false": False}.get(
                str(
                    config.get_optional_string("oryx.serving.scan.ann.host-stage1")
                ).lower()
            ),
        )
        # background ANN maintenance loop (docs/serving-scan.md): the
        # incremental overlay->clustered compaction + index-generation
        # publication knobs ride the same ann config block
        from oryx_tpu.serving.maintain import configure_maintain

        configure_maintain(
            enabled=config.get_optional_bool("oryx.serving.scan.ann.maintain.enabled"),
            interval_sec=config.get_optional_float(
                "oryx.serving.scan.ann.maintain.interval-sec"
            ),
            watermark=config.get_optional_float(
                "oryx.serving.scan.ann.maintain.watermark"
            ),
            split_max_items=config.get_optional_int(
                "oryx.serving.scan.ann.maintain.split-max-items"
            ),
            merge_min_items=config.get_optional_int(
                "oryx.serving.scan.ann.maintain.merge-min-items"
            ),
            publish=config.get_optional_bool("oryx.serving.scan.ann.maintain.publish"),
        )
        # tiered HBM->RAM->disk item store (native/store.py): catalogs
        # bigger than RAM keep serving out of the cell store
        from oryx_tpu.native.store import configure_tier

        tier_ram_mb = config.get_optional_int("oryx.serving.store.tier.ram-mb")
        configure_tier(
            enabled=config.get_optional_bool("oryx.serving.store.tier.enabled"),
            hot_cells=config.get_optional_int("oryx.serving.store.tier.hot-cells"),
            ram_bytes=None if tier_ram_mb is None else int(tier_ram_mb) << 20,
            spill_dir=config.get_optional_string("oryx.serving.store.tier.spill-dir"),
        )

        self.model_manager = None
        self._index_maintainer = None
        self.input_producer = None
        self._update_consumer = None
        self._consume_thread: SupervisedThread | None = None
        self._server: HTTPServer | None = None
        self._server_thread: threading.Thread | None = None
        self._native_front = None  # serving/native_front.NativeFront | None
        self._stop_event = threading.Event()
        self.health = ServingHealth()
        self.retry_policy = RetryPolicy.from_config(config, "oryx.serving.retry")
        # instance-scoped metrics: in a multi-replica process (tools/fleet.py)
        # the module-global registry aggregates every replica; this registry
        # is this replica alone, and /metrics serves it shadowing the global
        self.instance_metrics = metrics.MetricsRegistry()
        self._inflight = 0
        self._inflight_cond = threading.Condition()
        # close() can race between the fleet driver and atexit/signal
        # paths; the flag flip must be one atomic check-then-set
        self._close_lock = threading.Lock()
        self._close_done = False

        # model registry over the batch model dir: /model/generations +
        # rollback, and live-generation tracking with duplicate-MODEL
        # suppression on the update stream
        from oryx_tpu.registry.store import RegistryStore
        from oryx_tpu.registry.tracking import GenerationTracker

        model_dir = config.get_optional_string("oryx.batch.storage.model-dir")
        self.registry_store = RegistryStore(model_dir) if model_dir else None

        # MODEL-REF restage cache (docs/durability.md): referenced
        # generation dirs download locally through an atomic temp-dir +
        # rename, so a crash mid-download never leaves a half-staged
        # model. Registered process-wide; replicas sharing a process
        # (tools/fleet.py) share one staged copy per generation.
        self.model_stager = None
        restage_dir = config.get_optional_string("oryx.serving.restage-dir")
        if restage_dir:
            from oryx_tpu.serving import restage

            self.model_stager = restage.ModelStager(restage_dir)
            restage.set_active(self.model_stager)

        # online experiments (docs/experiments.md): arm router + online
        # evaluator + evidence-gated promotion loop. Built only when
        # oryx.serving.ab.fraction > 0 AND a registry is configured (the
        # CHAMPION pointer is what classifies challenger publishes), so
        # the request path pays nothing with experiments off.
        self.experiments = None
        if (
            self.registry_store is not None
            and config.get_float("oryx.serving.ab.fraction") > 0
        ):
            from oryx_tpu.experiments.coordinator import ExperimentCoordinator

            self.experiments = ExperimentCoordinator(
                config, self.registry_store, instance_metrics=self.instance_metrics
            )
        self.generation_tracker = GenerationTracker(
            self.health, experiments=self.experiments
        )
        if self.experiments is not None:
            self.experiments.attach_tracker(self.generation_tracker)
        self._rollback_producer = None
        self._rollback_lock = threading.Lock()

        # adaptive overload control: the admission controller watches the
        # batcher's queue-wait EWMA / queue depth / HTTP inflight against
        # the oryx.serving.overload.* budget and walks the shed ladder
        # (docs/overload.md); None when disabled, so the request fast path
        # pays nothing
        self.overload_config = _overload.OverloadConfig.from_config(config)
        self.admission = (
            _overload.AdmissionController(
                self.overload_config,
                signals=self._overload_signals,
                instance_metrics=self.instance_metrics,
                generation_fn=lambda: self.health.live_generation,
            )
            if self.overload_config.enabled
            else None
        )
        if self.admission is not None and self.tenants is not None:
            from oryx_tpu.serving.batcher import default_tenant_depths

            # per-tenant shed ladders: a noisy neighbor's own queue depth
            # (vs its weighted share) walks its private ladder while the
            # global one — every other tenant's floor — stays low
            self.admission.configure_tenants(
                self.tenants.weights(), default_tenant_depths
            )

        self.router = Router()
        if self.app_resources:
            for mod in self.app_resources:
                _import_recursively(mod)
        # framework resources (this module) + configured app resources only —
        # never whatever else happens to be imported in this interpreter
        self.router.add_from_registry([__name__] + list(self.app_resources or []))

    # -- lifecycle (ModelManagerListener.contextInitialized analogue) -------

    def start(self) -> None:
        from oryx_tpu.serving.batcher import retain_default_batcher

        if (
            self._server is not None
            or self._server_thread is not None
            or self._native_front is not None
            or self._update_consumer is not None
        ):
            raise RuntimeError(
                "ServingLayer.start() called twice (or retried after a "
                "partial start): the live HTTP server, update consumer, "
                "and consume thread would be overwritten and leak"
            )
        retain_default_batcher()
        self._batcher_retained = True
        cfg = self.config
        input_broker_loc = cfg.get_optional_string("oryx.input-topic.broker")
        input_topic = cfg.get_optional_string("oryx.input-topic.message.topic")
        update_broker_loc = cfg.get_optional_string("oryx.update-topic.broker")
        update_topic = cfg.get_optional_string("oryx.update-topic.message.topic")

        if self.tenants is None and input_broker_loc and input_topic and not self.read_only:
            broker = get_broker(input_broker_loc)
            if not self.no_init_topics:
                broker.create_topic(
                    input_topic, cfg.get_optional_int("oryx.input-topic.message.partitions") or 1
                )
            self.input_producer = broker.producer(input_topic)

        if self.experiments is not None and input_broker_loc and input_topic:
            # online evaluator: follow the input topic live (new events
            # only — historical interactions can't join future serves)
            broker = get_broker(input_broker_loc)
            if not self.no_init_topics:
                broker.create_topic(
                    input_topic, cfg.get_optional_int("oryx.input-topic.message.partitions") or 1
                )
            self.experiments.start(broker.consumer(input_topic))

        if self.tenants is not None:
            # multi-tenant wiring replaces the single manager/consumer
            # pair with one runtime per tenant behind the mux facades
            self._start_tenants(cfg, input_broker_loc, update_broker_loc)
        elif self.model_manager_class:
            self.model_manager = load_instance_of(self.model_manager_class, cfg)
            if update_broker_loc and update_topic:
                broker = get_broker(update_broker_loc)
                if not self.no_init_topics:
                    broker.create_topic(
                        update_topic,
                        cfg.get_optional_int("oryx.update-topic.message.partitions") or 1,
                    )
                # replay the update topic from offset 0 on every start
                # (ModelManagerListener.java:118-132). Supervised: a poll
                # failure marks the stream down (degraded mode — keep
                # serving the last good model) and the thread restarts
                # with backoff under oryx.serving.retry.*; only after
                # max-attempts consecutive failures does /healthz go red.
                self._update_consumer = broker.consumer(update_topic, from_beginning=True)
                self._consume_thread = SupervisedThread(
                    "ServingUpdateConsumer",
                    self._consume_updates,
                    self.retry_policy,
                    self._stop_event,
                    metrics_prefix="serving.consume",
                )
                self.health.consume_thread = self._consume_thread
                self._consume_thread.start()

        # background ANN index maintenance: compaction loop + (optional)
        # index-generation publication over the update topic. Duck-typed
        # on get_model so any manager whose models speak the maintenance
        # protocol (app/als) gets the loop; others are left alone.
        from oryx_tpu.serving import maintain as maintain_mod

        if (
            self.model_manager is not None
            and maintain_mod.maintain_enabled()
            and hasattr(self.model_manager, "get_model")
        ):
            publish_fn = None
            if (
                maintain_mod.MAINTAIN_PUBLISH
                and self.registry_store is not None
                and update_broker_loc
                and update_topic
            ):

                def publish_fn(index, stats):
                    ref = maintain_mod.write_index_generation(
                        self.registry_store.model_dir, index, stats=stats
                    )
                    # shares the rollback path's lazy update-topic producer
                    # (and its lock: publications serialize with rollbacks)
                    with self._rollback_lock:
                        if self._rollback_producer is None:
                            self._rollback_producer = get_broker(
                                update_broker_loc
                            ).producer(update_topic)
                        self._rollback_producer.send(maintain_mod.INDEX_REF_KEY, ref)
                    return ref

            self._index_maintainer = maintain_mod.IndexMaintainer(
                self.model_manager.get_model, publish_fn=publish_fn
            )
            self._index_maintainer.start()

        rollback_publisher = None
        if self.registry_store is not None and update_broker_loc and update_topic:
            max_size = cfg.get_int("oryx.update-topic.message.max-size")

            def rollback_publisher(generation_id: str) -> str:
                from oryx_tpu.registry.store import publish_generation

                # lazy producer: rollbacks are rare, no point holding an
                # update-topic producer open on every serving instance.
                # The lock covers the WHOLE publish, not just producer
                # creation: concurrent rollback requests serialize, so two
                # racing rollbacks can never interleave their MODEL bytes
                # on the topic — the last one to publish wins cleanly.
                with self._rollback_lock:
                    if self._rollback_producer is None:
                        self._rollback_producer = get_broker(update_broker_loc).producer(
                            update_topic
                        )
                    return publish_generation(
                        self.registry_store,
                        generation_id,
                        self._rollback_producer,
                        max_size,
                        retry_policy=self.retry_policy,
                    )

        ctx = ServingContext(
            self.model_manager,
            self.input_producer,
            self.config,
            self.health,
            registry=self.registry_store,
            rollback_publisher=rollback_publisher,
            instance_metrics=self.instance_metrics,
            admission=self.admission,
            experiments=self.experiments,
        )
        handler_cls = _make_handler(self, ctx)
        threads = self.config.get_optional_int("oryx.serving.api.threads") or 64
        tls_ctx = None
        if self.use_tls:
            # HTTPS connector analogue (ServingLayer.makeConnector:194-245).
            # The listener stays plaintext; each accepted socket is wrapped
            # on a pool worker so a stalled handshake can't starve accept().
            import ssl

            tls_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            tls_ctx.minimum_version = ssl.TLSVersion.TLSv1_2
            tls_ctx.load_cert_chain(
                certfile=self.keystore_file,
                keyfile=self.key_file,
                password=self.keystore_password,
            )
        # native data plane (docs/serving-native.md): when the toolchain
        # is present and oryx.serving.native.* allows it, the epoll C++
        # front replaces the pooled stdlib server; it answers the cheap
        # rungs in C++ and forwards everything else through the same
        # _dispatch_parsed core. maybe_start() returns None on any
        # decline (TLS, auth, disabled, no g++) and the stdlib server
        # below serves identically — the bit-compatible fallback.
        from oryx_tpu.serving import native_front as _native_mod

        self._native_front = _native_mod.maybe_start(self, ctx, threads)
        from oryx_tpu.common import ledger

        if self._native_front is not None:
            self.port = self._native_front.port
            ledger.register(
                "thread",
                self._native_front.poll_thread,
                live=threading.Thread.is_alive,
            )
        else:
            self._server = _PooledHTTPServer(
                ("0.0.0.0", self.port), handler_cls, threads, tls_ctx=tls_ctx
            )
            if self.port == 0:
                self.port = self._server.server_address[1]
            self._server_thread = threading.Thread(
                target=self._server.serve_forever, name="ServingHTTP", daemon=True
            )
            self._server_thread.start()
            ledger.register(
                "thread", self._server_thread, live=threading.Thread.is_alive
            )
        log.info(
            "ServingLayer listening on :%d%s%s",
            self.port,
            self.context_path or "/",
            " (native front)" if self._native_front is not None else "",
        )

    def _consume_updates(self) -> None:
        self.model_manager.consume_blocks(self._health_blocks())

    def _health_blocks(self):
        """blocking_block_iterator with a health reporter: every poll that
        returns marks the update stream healthy, a poll that raises marks
        it down (degraded mode) and propagates to the supervisor, and each
        applied block timestamps the staleness clock.

        Observability rides here too: a block carrying a ``@trc`` header
        feeds the freshness histogram (origin timestamp -> visible on
        this replica) and, when the publisher's trace was sampled, the
        apply is recorded as a span of that trace — the consumer side of
        the publish->apply propagation pair. A redelivered duplicate
        carries the same header, so it shows up as the same trace id with
        a fresh span id per delivery."""
        consumer = self._update_consumer
        while not self._stop_event.is_set() and not consumer.closed():
            try:
                block = consumer.poll_block(max_records=10_000, timeout=0.2)
            except Exception:
                self.health.mark_stream_down()
                raise
            self.health.mark_stream_ok()
            raw_trace = getattr(block, "trace", None)
            # track live generation + suppress duplicate deliveries of the
            # live generation's MODEL before the manager sees the block
            block = self.generation_tracker.filter_block(block)
            if block is not None and len(block) > 0:
                # generation-aware managers read this during consume to
                # load a challenger model without swapping it live
                challenger_ctx = _exp_routing.consume_challenger(
                    self.generation_tracker.challenger_generation
                )
                info = observe_block_freshness(
                    raw_trace, self.instance_metrics
                )
                apply_ctx = (
                    tracing.continue_from(info.ctx)
                    if info is not None and info.ctx is not None
                    else None
                )
                if apply_ctx is None:
                    with challenger_ctx:
                        yield block
                else:
                    name = (
                        "serving.model.apply"
                        if _block_has_model(block)
                        else "serving.apply"
                    )
                    # parent = the publisher's span (info.ctx); the span
                    # covers the manager's processing of the block (the
                    # time between yield and resume)
                    with tracing.use(info.ctx):
                        with tracing.span(
                            name,
                            attrs={
                                "instance": self.port,
                                "records": len(block),
                            },
                        ) as sp:
                            if info.ingest_ms is not None:
                                sp.set(
                                    "skew_ms",
                                    round(
                                        time.time() * 1000 - info.ingest_ms, 3
                                    ),
                                )
                            with challenger_ctx:
                                yield block
                            if self.health.live_generation is not None:
                                sp.set(
                                    "generation", self.health.live_generation
                                )
                self.health.mark_update()
                if self._native_front is not None and _block_has_model(block):
                    # a MODEL apply flips readiness / live_generation NOW;
                    # callers that watch convergence in-process (fleet
                    # wait_converged) probe /readyz immediately after, so
                    # the native snapshots cannot wait for the next
                    # control tick (push_snapshots is safe off the
                    # control thread — begin_drain relies on that too)
                    self._native_front.push_snapshots()

    # -- multi-tenant wiring (docs/multi-tenancy.md) ------------------------

    def _start_tenants(self, cfg, input_broker_loc, update_broker_loc) -> None:
        """One serving runtime per tenant — private model manager,
        health, generation tracker, registry store, and a namespaced
        update-topic consumer replaying from offset 0 — multiplexed
        behind the single ``ServingContext`` surface the resource
        handlers already use."""
        from functools import partial

        from oryx_tpu.registry.store import RegistryStore
        from oryx_tpu.registry.tracking import GenerationTracker
        from oryx_tpu.tenancy.mux import (
            TenantInputMux,
            TenantRuntime,
            TenantServingMux,
        )
        from oryx_tpu.tenancy.spec import tenant_config

        runtimes: dict[str, TenantRuntime] = {}
        producers: dict = {}
        for spec in self.tenants:
            tid = spec.tenant_id
            tcfg = tenant_config(cfg, spec)
            manager = load_instance_of(spec.wiring("serving-manager"), tcfg)
            health = ServingHealth()
            tracker = GenerationTracker(health)
            model_dir = tcfg.get_optional_string("oryx.batch.storage.model-dir")
            rt = TenantRuntime(
                spec,
                tcfg,
                manager,
                health,
                tracker,
                store=RegistryStore(model_dir) if model_dir else None,
            )
            tenant_input = tcfg.get_optional_string("oryx.input-topic.message.topic")
            if input_broker_loc and tenant_input and not self.read_only:
                broker = get_broker(input_broker_loc)
                if not self.no_init_topics:
                    broker.create_topic(
                        tenant_input,
                        tcfg.get_optional_int("oryx.input-topic.message.partitions")
                        or 1,
                    )
                rt.producer = broker.producer(tenant_input)
                producers[tid] = rt.producer
            tenant_update = tcfg.get_optional_string(
                "oryx.update-topic.message.topic"
            )
            if update_broker_loc and tenant_update:
                broker = get_broker(update_broker_loc)
                if not self.no_init_topics:
                    broker.create_topic(
                        tenant_update,
                        tcfg.get_optional_int("oryx.update-topic.message.partitions")
                        or 1,
                    )
                rt.consumer = broker.consumer(tenant_update, from_beginning=True)
                rt.thread = SupervisedThread(
                    f"ServingUpdateConsumer-{tid}",
                    partial(self._consume_tenant_updates, rt),
                    self.retry_policy,
                    self._stop_event,
                    metrics_prefix="serving.consume",
                )
                health.consume_thread = rt.thread
                rt.thread.start()
            runtimes[tid] = rt
        self.tenant_mux = TenantServingMux(runtimes, self.tenants.default_tenant)
        self.model_manager = self.tenant_mux
        if producers:
            self.input_producer = TenantInputMux(
                producers, self.tenants.default_tenant
            )

    def _consume_tenant_updates(self, rt) -> None:
        rt.manager.consume_blocks(self._tenant_blocks(rt))

    def _tenant_blocks(self, rt):
        """The per-tenant twin of :meth:`_health_blocks`: same stream
        health marks, duplicate-MODEL suppression, freshness accounting
        and publish->apply span propagation, against the tenant's own
        consumer/tracker/health — and every apply span carries the
        tenant id."""
        consumer = rt.consumer
        while not self._stop_event.is_set() and not consumer.closed():
            try:
                block = consumer.poll_block(max_records=10_000, timeout=0.2)
            except Exception:
                rt.health.mark_stream_down()
                raise
            rt.health.mark_stream_ok()
            raw_trace = getattr(block, "trace", None)
            block = rt.tracker.filter_block(block)
            if block is not None and len(block) > 0:
                info = observe_block_freshness(raw_trace, self.instance_metrics)
                if info is not None and info.ctx is not None:
                    name = (
                        "serving.model.apply"
                        if _block_has_model(block)
                        else "serving.apply"
                    )
                    with tracing.use(info.ctx):
                        with tracing.span(
                            name,
                            attrs={
                                "instance": self.port,
                                "records": len(block),
                                "tenant": rt.spec.tenant_id,
                            },
                        ) as sp:
                            yield block
                            if rt.health.live_generation is not None:
                                sp.set("generation", rt.health.live_generation)
                else:
                    yield block
                rt.health.mark_update()

    def await_termination(self, timeout: float | None = None) -> None:
        if self._server_thread is not None:
            self._server_thread.join(timeout)

    # -- drain-aware shutdown -----------------------------------------------

    def _request_began(self) -> None:
        with self._inflight_cond:
            self._inflight += 1
            n = self._inflight
        self.instance_metrics.gauge("serving.requests.in-flight").set(n)

    def _request_ended(self) -> None:
        with self._inflight_cond:
            self._inflight -= 1
            n = self._inflight
            if n <= 0:
                self._inflight_cond.notify_all()
        self.instance_metrics.gauge("serving.requests.in-flight").set(n)

    @property
    def inflight_requests(self) -> int:
        with self._inflight_cond:
            return self._inflight

    def _overload_signals(self) -> tuple[float, int, int]:
        """(queue_wait_ewma_ms, queue_depth, http_inflight) for the
        admission controller — the batcher half reads the process-wide
        default batcher without ever creating one."""
        from oryx_tpu.serving.batcher import default_batcher_signals

        queue_wait_ms, depth = default_batcher_signals()
        return queue_wait_ms, depth, self.inflight_requests

    def begin_drain(self) -> None:
        """Start refusing NEW traffic at the readiness level: /ready and
        /readyz flip to 503 so load balancers (and the open-loop engine's
        readiness router) stop sending here, while requests already in
        flight — or still arriving from stale routing tables — complete
        normally. The first half of a zero-downtime rolling restart."""
        self.health.draining = True
        self.instance_metrics.gauge("serving.draining").set(1)
        if self._native_front is not None:
            # the native /readyz snapshot must flip to 503 NOW, not at
            # the next control tick — load balancers poll readiness to
            # decide where new traffic goes during a rolling restart
            self._native_front.push_snapshots()
        log.info("ServingLayer :%d draining (readiness now 503)", self.port)

    def drain(self, timeout: float = 10.0) -> bool:
        """Block until no requests are in flight (or timeout). Returns
        True when the instance is idle and safe to close."""
        deadline = time.monotonic() + timeout
        with self._inflight_cond:
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cond.wait(remaining)
        return True

    def close(self, drain_seconds: float = 0.0) -> None:
        with self._close_lock:
            if self._close_done:
                return
            self._close_done = True
        if drain_seconds > 0:
            self.begin_drain()
            if not self.drain(drain_seconds):
                log.warning(
                    "close: %d request(s) still in flight after %.1fs drain",
                    self.inflight_requests,
                    drain_seconds,
                )
        if self._native_front is not None:
            self._native_front.close()
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
        self._stop_event.set()
        if self._update_consumer is not None:
            self._update_consumer.close()
        if self._consume_thread is not None:
            self._consume_thread.join(timeout=5)
            if self._consume_thread.is_alive():
                log.warning(
                    "serving thread %r still alive after 5s join; leaking it",
                    self._consume_thread.name,
                )
                metrics.registry.counter("layer.threads.leaked").inc()
        if self.tenant_mux is not None:
            # close every tenant consumer first (unblocks the polls),
            # then join the consume threads
            runtimes = self.tenant_mux.runtimes()
            for rt in runtimes.values():
                if rt.consumer is not None:
                    rt.consumer.close()
            for rt in runtimes.values():
                if rt.thread is not None:
                    rt.thread.join(timeout=5)
                    if rt.thread.is_alive():
                        log.warning(
                            "serving thread %r still alive after 5s join; "
                            "leaking it",
                            rt.thread.name,
                        )
                        metrics.registry.counter("layer.threads.leaked").inc()
        if self._index_maintainer is not None:
            # before the manager: the loop snapshots through get_model
            self._index_maintainer.close()
        if self.model_manager is not None:
            self.model_manager.close()
        if self.experiments is not None:
            self.experiments.close()
        if self.input_producer is not None:
            self.input_producer.close()
        if self._rollback_producer is not None:
            self._rollback_producer.close()
        if getattr(self, "_batcher_retained", False):
            self._batcher_retained = False
            from oryx_tpu.serving.batcher import release_default_batcher

            release_default_batcher()
        if self.model_stager is not None:
            from oryx_tpu.serving import restage

            # only clear the process-wide hook if it is still ours — a
            # replica started after us may have re-registered it
            if restage.active() is self.model_stager:
                restage.set_active(None)

    def __enter__(self) -> "ServingLayer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _shed_response(retry_after_s: int) -> Response:
    """Fast-429 for the top ladder rung: tiny JSON body, Retry-After so
    well-behaved clients back off instead of hammering the retry path."""
    return Response(
        429,
        {"error": "overloaded", "retry_after_s": retry_after_s},
        content_type="application/json",
        headers={"Retry-After": str(retry_after_s)},
    )


def _admit_and_route(layer: ServingLayer, ctx: ServingContext, req, cache_key, sp):
    """Route one request through the shed ladder (docs/overload.md).

    The admission decision picks the *intended* stage; this function
    reports the stage the request was *actually* served at — a stale-rung
    request that misses the answer cache falls through to a reduced-probe
    scan, and a full-quality request that finds the batcher queue full is
    shed at the door. The served stage is stamped on the response header,
    the request span, and the per-stage counters, so loadgen's achieved-
    quality accounting always reflects reality, not intent.

    While an online experiment is active (docs/experiments.md) the
    request is first assigned an arm: challenger-arm dispatch runs under
    a generation override so generation-aware managers serve the
    challenger model, the arm lands on the X-Oryx-Experiment-Arm header
    and the request span, and the serve is recorded with the evaluator
    for the interaction-event join."""
    from oryx_tpu.serving.batcher import BatcherOverloadedError

    t_arrive = time.perf_counter()
    experiments = layer.experiments
    assignment = (
        experiments.assign_request(req.path, req.headers)
        if experiments is not None
        else None
    )

    def _dispatch():
        if experiments is not None:
            # pin every request to the tracker's generation for its arm
            # (challenger for the challenger arm, live for everything
            # else). With a generation-aware manager this keeps the
            # champion default intact while a challenger is loaded, and
            # flips all traffic the moment a promotion swaps the tracker;
            # managers without per-generation retention ignore it.
            generation = (
                assignment[1]
                if assignment is not None
                else layer.health.live_generation
            )
            with _exp_routing.serve_generation(generation):
                return layer.router.dispatch(ctx, req)
        return layer.router.dispatch(ctx, req)

    tenant = _tenancy.current_tenant()
    admission = layer.admission
    decision = (
        admission.decide(req.method, req.path, tenant=tenant)
        if admission is not None
        else None
    )

    def _champion_generation():
        # the generation stale-cache entries are stamped with / validated
        # against: the tenant's own champion on a multi-tenant fleet
        # (each tenant has a private lineage), the tracker's otherwise
        if tenant is not None and layer.tenant_mux is not None:
            rt = layer.tenant_mux.runtime(tenant)
            return rt.health.live_generation if rt is not None else None
        return admission.generation() if admission is not None else None
    served = None  # stage name actually used; None = full quality
    response = None
    if decision is not None and decision.stage >= _overload.STAGE_SHED:
        served = "shed"
        response = _shed_response(decision.retry_after_s)
    elif (
        decision is not None
        and decision.stage >= _overload.STAGE_STALE
        and req.method == "GET"
    ):
        cached = admission.cache.get(cache_key, _champion_generation())
        if cached is not None:
            served = "stale"
            response = Response(cached.status, cached.payload, cached.content_type)
    if response is None:
        try:
            if decision is not None and decision.probe_fraction is not None:
                with _overload.probe_override(decision.probe_fraction):
                    response = _dispatch()
                if getattr(response, "status", 200) == 200:
                    served = "reduced-probe"
            else:
                response = _dispatch()
        except BatcherOverloadedError:
            # bounded-queue rejection (oryx.serving.overload.max-queue):
            # an immediate shed decision instead of unbounded queueing,
            # taken even when the admission controller is disabled
            served = "shed"
            retry_after = (
                layer.overload_config.retry_after_s
                if layer.overload_config is not None
                else 1
            )
            response = _shed_response(retry_after)
        else:
            if (
                decision is not None
                and decision.stage == _overload.STAGE_FULL
                and req.method == "GET"
                and getattr(response, "status", 200) == 200
                and _champion_generation() is not None
                # challenger answers must never enter the stale cache:
                # it is stamped with the champion generation
                and (assignment is None or assignment[0] != _exp_routing.ARM_CHALLENGER)
            ):
                # feed the stale-answer cache with full-quality answers
                # only, stamped with the champion generation
                admission.cache.put(
                    cache_key,
                    _overload.CachedAnswer(
                        _champion_generation(),
                        response.status,
                        response.body,
                        response.content_type,
                    ),
                )
    if served is not None:
        _overload.count_shed(
            served,
            layer.instance_metrics,
            generation=(
                assignment[1]
                if assignment is not None
                else (_champion_generation() or layer.health.live_generation)
            ),
            tenant=tenant,
        )
        headers = getattr(response, "headers", None)
        if headers is not None:
            headers[_overload.SHED_HEADER] = served
        if sp is not None:
            sp.set("shed_stage", served)
    if assignment is not None:
        arm, generation, user = assignment
        headers = getattr(response, "headers", None)
        if headers is not None:
            headers[_exp_routing.ARM_HEADER] = arm
        if sp is not None:
            sp.set("experiment_arm", arm)
            if generation is not None:
                sp.set("experiment_generation", generation)
        items = (
            _served_items(getattr(response, "body", None))
            if getattr(response, "status", 200) == 200
            else ()
        )
        experiments.observe_request(
            user,
            arm,
            generation,
            items,
            latency_s=time.perf_counter() - t_arrive,
            shed_stage=served,
        )
    return response


def _served_items(body):
    """Item ids in a recommendation response body, in rank order, for
    the online join. Understands the two shapes the app endpoints
    produce: a dict with an ``items`` list, and a ranked list of
    item / (item, score) entries."""
    if isinstance(body, dict):
        items = body.get("items")
        if isinstance(items, list):
            return [str(i) for i in items]
        return ()
    if isinstance(body, list):
        out = []
        for entry in body:
            if isinstance(entry, (list, tuple)) and entry:
                out.append(str(entry[0]))
            elif isinstance(entry, (str, int)):
                out.append(str(entry))
        return out
    return ()


def _check_auth(layer: ServingLayer, headers) -> None:
    """Basic-auth gate shared by both fronts; raises 401 on failure."""
    if not layer.user_name:
        return
    auth = headers.get("Authorization", "") or ""
    if not auth.startswith("Basic "):
        raise OryxServingException(401, "unauthorized")
    try:
        userpass = base64.b64decode(auth[6:]).decode("utf-8")
    except Exception:
        raise OryxServingException(401, "unauthorized")
    import hmac

    if not hmac.compare_digest(userpass, f"{layer.user_name}:{layer.password}"):
        raise OryxServingException(401, "unauthorized")


def gzip_compress(body: bytes) -> bytes:
    """Deterministic response gzip (mtime pinned): the same body always
    produces the same bytes, which is what lets the native/Python fronts
    hold their byte-parity contract across the gzip rung."""
    return gzip.compress(body, mtime=0)


def _dispatch_parsed(layer, ctx, method: str, raw_path: str, headers, body,
                     tenant_box):
    """The front-agnostic request core: everything between "a parsed
    request" and "a rendered (status, payload, content-type, extras)
    tuple". Both the Python handler and the native front's dispatch
    workers (serving/native_front.py) call this, so tenant resolution,
    admission, tracing, experiments, and rendering cannot drift between
    fronts. `headers` needs case-insensitive ``get`` plus ``items()``
    with original casing (email.Message and native_front._Headers both
    qualify); ``tenant_box[0]`` receives the resolved tenant even when
    dispatch later raises."""
    _check_auth(layer, headers)
    split = urlsplit(raw_path)
    path = split.path
    if layer.context_path:
        if not path.startswith(layer.context_path):
            raise OryxServingException(404, "outside context path")
        path = path[len(layer.context_path) :] or "/"
    # tenant resolution (docs/multi-tenancy.md): the /t/<tenant>/
    # prefix wins over the X-Oryx-Tenant header; untenanted
    # data-plane requests fall to the default tenant. Resolved
    # before routing so the stripped path matches the resources,
    # and scoped over the dispatch so the batcher / admission /
    # mux all see it.
    tenant = None
    if layer.tenants is not None:
        tenant, path = _tenancy.split_tenant_path(path)
        if tenant is None:
            tenant = headers.get(_tenancy.TENANT_HEADER)
        if tenant is None and not _overload.exempt(path):
            tenant = layer.tenants.default_tenant
        if tenant is not None and tenant not in layer.tenants:
            raise OryxServingException(404, f"unknown tenant {tenant!r}")
        tenant_box[0] = tenant
    if headers.get("Content-Encoding") == "gzip":
        body = gzip.decompress(body)
    req = Request(
        # HEAD routes like GET; the body is suppressed at send time
        method="GET" if method == "HEAD" else method,
        path=path,
        params={},
        query=parse_qs(split.query),
        headers={k: v for k, v in headers.items()},
        body=body,
    )
    # answer-cache key: path + raw query, i.e. the full request
    # identity for the GET data plane the stale rung serves — the
    # tenant rides in front so two tenants' answers for the same
    # path can never alias in the cache
    cache_key = path + ("?" + split.query if split.query else "")
    if tenant is not None:
        cache_key = f"/t/{tenant}{cache_key}"
    attrs = {"path": path, "method": req.method}
    if tenant is not None:
        attrs["tenant"] = tenant
    # request-lifecycle span: a sampled incoming traceparent is
    # honored (the loadgen client's span becomes this span's
    # parent, joined by trace id); header-less requests roll the
    # root sampling dice. Untraced requests skip all of it.
    incoming = tracing.parse_traceparent(headers.get("traceparent"))
    with _tenancy.tenant_scope(tenant):
        if incoming is not None and incoming.sampled:
            with tracing.use(incoming):
                with tracing.span("serving.request", attrs=attrs) as sp:
                    response = _admit_and_route(layer, ctx, req, cache_key, sp)
                    sp.set("status", getattr(response, "status", 200))
        else:
            with tracing.span("serving.request", attrs=attrs, root=True) as sp:
                response = _admit_and_route(layer, ctx, req, cache_key, sp)
                sp.set("status", getattr(response, "status", 200))
    return render(response, headers.get("Accept", "application/json"))


def _make_handler(layer: ServingLayer, ctx: ServingContext):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        server_version = "oryx_tpu"
        # keep-alive clients see Nagle + delayed-ACK stack into ~40 ms
        # per-request stalls without this; the native front (httpfront.cpp)
        # sets TCP_NODELAY on every accepted socket for the same reason
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):  # route to logging, not stderr
            log.debug("%s " + fmt, self.address_string(), *args)

        def _handle(self, method: str) -> None:
            t0 = time.perf_counter()
            layer._request_began()
            try:
                self._handle_counted(method, t0)
            finally:
                layer._request_ended()

        def _handle_counted(self, method: str, t0: float) -> None:
            try:
                status, payload, ct, extra = self._dispatch(method)
            except OryxServingException as e:
                _observe_request(
                    method, e.status, t0, layer, getattr(self, "_tenant", None)
                )
                self._send_error(e.status, e.message)
                return
            except Exception:
                log.exception("internal error handling %s %s", method, self.path)
                _observe_request(
                    method, 500, t0, layer, getattr(self, "_tenant", None)
                )
                self._send_error(500, "internal error")
                return
            _observe_request(
                method, status, t0, layer, getattr(self, "_tenant", None)
            )
            body = payload
            headers = dict(extra)
            if len(body) > 1024 and "gzip" in self.headers.get("Accept-Encoding", ""):
                body = gzip_compress(body)
                headers["Content-Encoding"] = "gzip"
            self.send_response(status)
            self.send_header("Content-Type", ct)
            self.send_header("Content-Length", str(len(body)))
            for k, v in headers.items():
                self.send_header(k, v)
            self.end_headers()
            if method != "HEAD":
                self.wfile.write(body)

        def _dispatch(self, method: str):
            self._tenant = None
            length = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(length) if length else b""
            tenant_box = [None]
            try:
                return _dispatch_parsed(
                    layer, ctx, method, self.path, self.headers, body, tenant_box
                )
            finally:
                self._tenant = tenant_box[0]

        def _send_error(self, status: int, message: str) -> None:
            # plain error body (ErrorResource.java renders status + message)
            body = f"{status} {message}\n".encode("utf-8")
            self.send_response(status)
            if status == 401:
                self.send_header("WWW-Authenticate", 'Basic realm="Oryx"')
            self.send_header("Content-Type", "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except BrokenPipeError:
                pass

        def do_GET(self):
            self._handle("GET")

        def do_POST(self):
            self._handle("POST")

        def do_DELETE(self):
            self._handle("DELETE")

        def do_HEAD(self):
            self._handle("HEAD")

    return Handler
