"""Adaptive overload control: admission controller + staged quality shedding.

Production serving tiers that only queue under overload convert a traffic
spike into an unbounded latency tail (BENCH_r05: 8.9-18 s p99 queued behind
the pipeline).  Following DAGOR-style admission control (Zhou et al.,
SoCC'18, "Overload Control for Scaling WeChat Microservices") this module
degrades answer *quality* in stages instead of degrading *latency*
unboundedly.  A per-replica :class:`AdmissionController` watches the
adaptive batcher's queue-wait EWMA, queue depth, and HTTP in-flight count
against the ``oryx.serving.overload.*`` budget, folds them into a single
smoothed pressure ratio, and walks a shed ladder one rung at a time:

    stage 0  full           exact / full-nprobe ANN scan
    stage 1  reduced-probe  ANN with ``nprobe`` scaled down per request
    stage 2  stale          cached top-N from the champion generation
    stage 3  shed           fast 429 with Retry-After

Hysteresis prevents flapping: a rung engages when smoothed pressure crosses
its engage threshold, releases only when pressure drops below
``engage * release-fraction``, and both directions dwell ``hold-s`` seconds
between moves.  Every shed decision is counted per stage, carried on the
response as the ``X-Oryx-Shed-Stage`` header, and recorded as a trace
attribute so loadgen can report achieved quality alongside latency
(docs/overload.md).

This module deliberately imports only the metrics registry — the batcher
imports it for the queue-full shed path, so it must never import the
batcher back.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass, field
from typing import Callable

from oryx_tpu.common import metrics

# Ladder stages, in engagement order. Indexes are meaningful: the
# controller only ever moves one rung at a time.
STAGE_FULL = 0
STAGE_REDUCED_PROBE = 1
STAGE_STALE = 2
STAGE_SHED = 3
STAGE_NAMES = ("full", "reduced-probe", "stale", "shed")

# Response header carrying the stage a request was actually served at.
SHED_HEADER = "X-Oryx-Shed-Stage"

# Control-plane paths are exempt from shedding: health and drain signals
# must stay accurate precisely when the data plane is overloaded.
_EXEMPT_PREFIXES = (
    "/healthz",
    "/readyz",
    "/ready",
    "/metrics",
    "/trace",
    "/model/",
    "/debug/",
    "/experiments",
)


def exempt(path: str) -> bool:
    """True when `path` is control-plane and must never be shed."""
    return any(path == p.rstrip("/") or path.startswith(p) for p in _EXEMPT_PREFIXES)


# -- per-request probe override ---------------------------------------------
#
# The admission decision is taken on the HTTP worker thread; the same
# thread calls into the batcher's enqueue path, so a ContextVar carries
# the reduced probe fraction from the controller to the batcher without
# widening every scoring signature in between (the batcher snapshots it
# into the entry before handing off to the dispatcher thread).

_probe_override: ContextVar[float | None] = ContextVar("oryx_probe_override", default=None)


def active_probe_fraction() -> float | None:
    """The probe fraction the current request should scan with, if reduced."""
    return _probe_override.get()


@contextmanager
def probe_override(fraction: float | None):
    """Scope a reduced probe fraction over a router dispatch."""
    token = _probe_override.set(fraction)
    try:
        yield
    finally:
        _probe_override.reset(token)


# -- configuration -----------------------------------------------------------


@dataclass(frozen=True)
class OverloadConfig:
    """Parsed ``oryx.serving.overload.*`` budget (reference.conf defaults)."""

    enabled: bool = True
    target_queue_wait_ms: float = 50.0
    inflight_target: int = 64
    max_queue: int | None = 2048
    engage_reduced: float = 0.7
    engage_stale: float = 1.0
    engage_shed: float = 1.3
    release_fraction: float = 0.75
    hold_s: float = 1.0
    alpha: float = 0.3
    probe_fraction: float = 0.25
    cache_entries: int = 256
    retry_after_s: int = 1
    control_interval_ms: float = 100.0

    @classmethod
    def from_config(cls, config) -> "OverloadConfig":
        p = "oryx.serving.overload."
        return cls(
            enabled=config.get_bool(p + "enabled"),
            target_queue_wait_ms=config.get_float(p + "target-queue-wait-ms"),
            inflight_target=config.get_int(p + "inflight-target"),
            max_queue=config.get_optional_int(p + "max-queue"),
            engage_reduced=config.get_float(p + "engage-reduced"),
            engage_stale=config.get_float(p + "engage-stale"),
            engage_shed=config.get_float(p + "engage-shed"),
            release_fraction=config.get_float(p + "release-fraction"),
            hold_s=config.get_float(p + "hold-s"),
            alpha=config.get_float(p + "alpha"),
            probe_fraction=config.get_float(p + "probe-fraction"),
            cache_entries=config.get_int(p + "cache-entries"),
            retry_after_s=config.get_int(p + "retry-after-s"),
            control_interval_ms=config.get_float(p + "control-interval-ms"),
        )

    def engage_threshold(self, stage: int) -> float:
        return (self.engage_reduced, self.engage_stale, self.engage_shed)[stage - 1]


# -- shed accounting ---------------------------------------------------------

# Registered here so the literal names live next to the catalog entries in
# docs/observability.md; the family is docs-cataloged as
# serving.overload.shed.<stage>.
_SHED_COUNTER_PREFIX = "serving.overload.shed."


def count_shed(
    stage_name: str, instance_metrics=None, generation=None, tenant=None
) -> None:
    """Count one answer served below full quality at `stage_name`.

    When the generation that would have served the request is known, a
    generation-labeled twin is counted alongside, so per-generation (and
    per-experiment-arm) dashboards see *which* model's traffic was
    degraded; likewise a tenant-labeled twin
    (``serving.overload.shed.<stage>.tenant.<tenant>``) attributes the
    degradation to the tenant that absorbed it."""
    name = _SHED_COUNTER_PREFIX + stage_name
    metrics.registry.counter(name).inc()
    if instance_metrics is not None:
        instance_metrics.counter(name).inc()
        if generation is not None:
            instance_metrics.counter(f"{name}.generation.{generation}").inc()
        if tenant is not None:
            instance_metrics.counter(f"{name}.tenant.{tenant}").inc()


# -- stale-answer cache ------------------------------------------------------


@dataclass
class CachedAnswer:
    generation: str
    status: int
    payload: object  # the un-rendered Response body; re-rendered per Accept
    content_type: str | None


class AnswerCache:
    """Bounded LRU of last-good answers keyed by request path+query.

    Entries are stamped with the generation that produced them; lookups
    only hit when the stamped generation still equals the tracked champion
    — a rollback or promotion implicitly invalidates the whole cache, so
    the stale rung can never serve answers from an abandoned candidate
    generation. Only full-quality (stage 0) 200s are cached, so "stale"
    means *older* full answers, never degraded ones.
    """

    def __init__(self, max_entries: int = 256) -> None:
        self._max = max(1, int(max_entries))
        self._lock = threading.Lock()
        self._entries: OrderedDict[str, CachedAnswer] = OrderedDict()
        self.hits = 0
        self.misses = 0
        # optional mirror hook (serving/native_front.py): called after
        # each put, off the lock, so the native front can mirror the
        # entry into its C++ answer cache. Must be cheap and
        # non-blocking — the native front just enqueues and renders on
        # its control tick, never on this (request) thread.
        self.listener: Callable[[str, CachedAnswer], None] | None = None

    def put(self, key: str, answer: CachedAnswer) -> None:
        with self._lock:
            self._entries[key] = answer
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)
        listener = self.listener
        if listener is not None:
            listener(key, answer)

    def get(self, key: str, champion_generation: str | None) -> CachedAnswer | None:
        with self._lock:
            entry = self._entries.get(key)
            if (
                entry is None
                or champion_generation is None
                or entry.generation != champion_generation
            ):
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


# -- admission controller ----------------------------------------------------


@dataclass
class _TenantLadder:
    """One tenant's private shed ladder (same control law, scoped signal).

    A tenant's pressure is its *own* queue depth against its *weighted
    share* of the bounded queue, so a noisy neighbor climbs its ladder —
    and gets shed — while the global ladder (which all tenants inherit as
    a floor) stays low and victims keep full quality."""

    weight: float = 1.0
    stage: int = STAGE_FULL
    pressure: float = 0.0
    last_move: float = -float("inf")


@dataclass(frozen=True)
class Decision:
    """One admission decision: the stage to serve the request at."""

    stage: int
    probe_fraction: float | None = None
    retry_after_s: int = 1

    @property
    def name(self) -> str:
        return STAGE_NAMES[self.stage]


class AdmissionController:
    """Per-replica shed-ladder controller with hysteresis.

    `signals` returns ``(queue_wait_ms, queue_depth, inflight)``; the
    controller normalises each against its budget, takes the max (the
    bottleneck dominates, per DAGOR), and EWMA-smooths it into a single
    pressure ratio.  1.0 means "exactly at budget".  Rung moves are rate
    limited to one per `hold-s` in either direction; evaluation itself is
    rate limited to `control-interval-ms` so the idle fast path is one
    monotonic read + compare.  `clock` is injectable for deterministic
    tests.
    """

    def __init__(
        self,
        cfg: OverloadConfig,
        signals: Callable[[], tuple[float, int, int]],
        clock: Callable[[], float] = time.monotonic,
        instance_metrics=None,
        generation_fn: Callable[[], str | None] | None = None,
    ) -> None:
        self.cfg = cfg
        self._signals = signals
        self._clock = clock
        self._instance_metrics = instance_metrics
        self._generation_fn = generation_fn
        self.cache = AnswerCache(cfg.cache_entries)
        self._lock = threading.Lock()
        self._stage = STAGE_FULL
        self._pressure = 0.0
        self._last_eval = -float("inf")
        self._last_move = -float("inf")
        self.transitions: list[tuple[float, int, int, float]] = []
        # per-tenant ladders (configure_tenants); empty = tenancy off
        self._tenants: dict[str, _TenantLadder] = {}
        self._tenant_depths: Callable[[], dict[str, int]] | None = None

    def configure_tenants(
        self,
        weights: dict[str, float],
        depths_fn: Callable[[], dict[str, int]],
    ) -> None:
        """Attach per-tenant shed ladders (serving layer, at startup).

        `depths_fn` returns the batcher's per-tenant queued-entry counts;
        each tenant's ladder normalises its own depth against its weighted
        share of ``max-queue`` and walks the same hysteresis rungs as the
        global ladder."""
        with self._lock:
            self._tenants = {
                tid: _TenantLadder(weight=w) for tid, w in weights.items()
            }
            self._tenant_depths = depths_fn

    # -- signal plumbing --

    def generation(self) -> str | None:
        """The tracked champion generation (None before the first model)."""
        return self._generation_fn() if self._generation_fn is not None else None

    @property
    def stage(self) -> int:
        return self._stage

    @property
    def pressure(self) -> float:
        return self._pressure

    def _raw_pressure(self) -> float:
        queue_wait_ms, queue_depth, inflight = self._signals()
        ratios = [
            queue_wait_ms / self.cfg.target_queue_wait_ms,
            inflight / max(1, self.cfg.inflight_target),
        ]
        if self.cfg.max_queue:
            ratios.append(queue_depth / self.cfg.max_queue)
        return max(ratios)

    # -- control law --

    def evaluate(self, now: float | None = None) -> int:
        """Fold signals into smoothed pressure and move at most one rung."""
        t = self._clock() if now is None else now
        with self._lock:
            self._last_eval = t
            raw = self._raw_pressure()
            a = self.cfg.alpha
            self._pressure = a * raw + (1.0 - a) * self._pressure
            stage = self._stage
            if t - self._last_move >= self.cfg.hold_s:
                if (
                    stage < STAGE_SHED
                    and self._pressure >= self.cfg.engage_threshold(stage + 1)
                ):
                    self._move(stage + 1, t)
                elif (
                    stage > STAGE_FULL
                    and self._pressure
                    <= self.cfg.engage_threshold(stage) * self.cfg.release_fraction
                ):
                    self._move(stage - 1, t)
            if self._tenants:
                self._evaluate_tenants(t)
            metrics.registry.gauge("serving.overload.stage").set(self._stage)
            metrics.registry.gauge("serving.overload.pressure").set(self._pressure)
            if self._instance_metrics is not None:
                self._instance_metrics.gauge("serving.overload.stage").set(self._stage)
                self._instance_metrics.gauge("serving.overload.pressure").set(
                    self._pressure
                )
            return self._stage

    def _evaluate_tenants(self, t: float) -> None:
        """Walk each tenant ladder one step (caller holds the lock)."""
        depths = self._tenant_depths() if self._tenant_depths else {}
        total_weight = sum(l.weight for l in self._tenants.values())
        max_queue = self.cfg.max_queue
        for tid, ladder in self._tenants.items():
            if not max_queue:
                break  # unbounded queue: per-tenant shares are undefined
            share = max(1.0, max_queue * ladder.weight / max(total_weight, 1e-9))
            raw = depths.get(tid, 0) / share
            a = self.cfg.alpha
            ladder.pressure = a * raw + (1.0 - a) * ladder.pressure
            if t - ladder.last_move >= self.cfg.hold_s:
                if (
                    ladder.stage < STAGE_SHED
                    and ladder.pressure
                    >= self.cfg.engage_threshold(ladder.stage + 1)
                ):
                    ladder.stage += 1
                    ladder.last_move = t
                elif (
                    ladder.stage > STAGE_FULL
                    and ladder.pressure
                    <= self.cfg.engage_threshold(ladder.stage)
                    * self.cfg.release_fraction
                ):
                    ladder.stage -= 1
                    ladder.last_move = t
            if self._instance_metrics is not None:
                self._instance_metrics.gauge(
                    f"serving.overload.stage.tenant.{tid}"
                ).set(ladder.stage)
                self._instance_metrics.gauge(
                    f"serving.overload.pressure.tenant.{tid}"
                ).set(ladder.pressure)

    def tenant_stage(self, tenant: str | None) -> int:
        """The tenant's own ladder stage (STAGE_FULL when untracked)."""
        if tenant is None:
            return STAGE_FULL
        ladder = self._tenants.get(tenant)
        return ladder.stage if ladder is not None else STAGE_FULL

    def _move(self, to_stage: int, t: float) -> None:
        self.transitions.append((t, self._stage, to_stage, self._pressure))
        self._stage = to_stage
        self._last_move = t
        metrics.registry.counter("serving.overload.transitions").inc()
        if self._instance_metrics is not None:
            self._instance_metrics.counter("serving.overload.transitions").inc()

    def decide(
        self, method: str, path: str, tenant: str | None = None
    ) -> Decision | None:
        """Admission decision for one request; None = exempt, serve normally.

        With tenancy on, the effective stage is the *max* of the global
        ladder and the tenant's own — global pressure degrades everyone,
        a noisy neighbor additionally degrades only itself."""
        if exempt(path):
            return None
        t = self._clock()
        if t - self._last_eval >= self.cfg.control_interval_ms / 1000.0:
            self.evaluate(t)
        stage = max(self._stage, self.tenant_stage(tenant))
        if stage == STAGE_FULL:
            return Decision(STAGE_FULL)
        if stage == STAGE_REDUCED_PROBE:
            return Decision(
                STAGE_REDUCED_PROBE, probe_fraction=self.cfg.probe_fraction
            )
        if stage == STAGE_STALE:
            # stale only helps GETs; mutations fall through at reduced probe
            return Decision(
                STAGE_STALE,
                probe_fraction=self.cfg.probe_fraction,
                retry_after_s=self.cfg.retry_after_s,
            )
        return Decision(STAGE_SHED, retry_after_s=self.cfg.retry_after_s)
