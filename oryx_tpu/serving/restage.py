"""Atomic local restage of MODEL-REF generation dirs.

A MODEL-REF update message names a *generation dir* in the registry
store (local path or ``gs://...``). Replicas that want the artifacts on
local disk — repeated resolves of a large model, side artifacts the
scan engine mmaps — restage the dir into a local cache. The restage is
a commit sequence of its own and must be crash-faithful: artifacts copy
into a hidden ``.stage-<generation>-<pid>`` temp dir (model.pmml last,
mirroring ``storage.upload_dir`` so a visible model.pmml implies its
siblings are complete, each file fsynced), and one atomic rename makes
the staged generation appear whole or not at all. A replica SIGKILLed
mid-download leaves only temp litter that ``repair()`` sweeps on the
next start — never a half-written model dir the server could load.

Enabled per layer with ``oryx.serving.restage-dir``; the serving layer
registers its stager process-wide (``set_active``) and
``app/pmml.read_pmml_from_update_message`` resolves MODEL-REFs through
it. The cache is keyed by generation id, so every replica in a process
(tools/fleet.py) shares one staged copy.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from pathlib import Path

from oryx_tpu.common import metrics, storage
from oryx_tpu.common.crashpoints import crashpoint
from oryx_tpu.registry.store import MODEL_FILE_NAME, generation_id_from_ref

log = logging.getLogger(__name__)

__all__ = ["ModelStager", "active", "set_active"]

_STAGE_MARKER = ".stage-"


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - pid exists, other user
        return True
    return True


class ModelStager:
    """Downloads generation dirs into a local cache, atomically."""

    def __init__(self, stage_dir: str | Path) -> None:
        self.root = Path(stage_dir)
        self.root.mkdir(parents=True, exist_ok=True)
        self._lock = threading.Lock()
        self.swept_on_open = self.repair()

    # -- cache ---------------------------------------------------------------

    def staged_path(self, generation_id: str) -> Path:
        return self.root / generation_id

    def is_staged(self, generation_id: str) -> bool:
        # the stage commit is atomic, so the presence of the dir (always
        # renamed complete, model.pmml included) is the whole check
        return (self.staged_path(generation_id) / MODEL_FILE_NAME).is_file()

    def stage(self, ref: str) -> Path | None:
        """Restage a MODEL-REF generation dir into the cache; returns the
        local dir, or None when the ref isn't registry-shaped / vanished
        (callers fall back to direct resolution). Idempotent and cheap
        once staged. Thread-safe within the process; cross-process races
        are benign (both writers stage identical bytes, last rename wins
        atomically)."""
        gen = generation_id_from_ref(ref)
        if gen is None:
            return None
        with self._lock:
            if self.is_staged(gen):
                metrics.registry.counter("serving.restage.hits").inc()
                return self.staged_path(gen)
            names = self._artifact_files(ref)
            if names is None:
                return None
            tmp = self.root / f"{_STAGE_MARKER}{gen}-{os.getpid()}"
            if tmp.exists():
                shutil.rmtree(tmp)
            try:
                # model.pmml LAST (upload_dir's ordering contract): a
                # kill mid-copy can never leave a readable model whose
                # side artifacts are missing
                names.sort(key=lambda rel: (rel.split("/")[-1] == MODEL_FILE_NAME, rel))
                for k, rel in enumerate(names):
                    if rel.split("/")[-1] == MODEL_FILE_NAME:
                        crashpoint("serving.restage.mid")
                    dst = tmp.joinpath(*rel.split("/"))
                    dst.parent.mkdir(parents=True, exist_ok=True)
                    with storage.open_read(storage.join(ref, rel), "rb") as src, open(
                        dst, "wb"
                    ) as out:
                        shutil.copyfileobj(src, out)
                        out.flush()
                        os.fsync(out.fileno())
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            crashpoint("serving.restage.pre-commit")
            final = self.staged_path(gen)
            if final.exists():  # lost a cross-process race; theirs is whole
                shutil.rmtree(tmp, ignore_errors=True)
            else:
                os.rename(tmp, final)
            storage.fsync_dir(self.root)
            metrics.registry.counter("serving.restage.staged").inc()
            log.info("restaged generation %s (%d files) from %s", gen, len(names), ref)
            return final

    def _artifact_files(self, ref: str) -> list[str] | None:
        """Relative paths of every file under the generation dir."""
        if storage.is_remote(ref):
            import fsspec

            fs, path = fsspec.core.url_to_fs(ref)
            if not fs.exists(path):
                return None
            base = path.rstrip("/")
            return [
                p[len(base) :].lstrip("/")
                for p in fs.find(base)
            ]
        d = storage.local_path(ref)
        if not d.is_dir():
            return None
        return [p.relative_to(d).as_posix() for p in d.rglob("*") if p.is_file()]

    # -- repair --------------------------------------------------------------

    def repair(self) -> int:
        """Sweep ``.stage-*`` temp dirs left by dead stagers (kill mid-
        download). Counted on ``serving.restage.swept``."""
        removed = 0
        for p in self.root.iterdir():
            if not (p.is_dir() and p.name.startswith(_STAGE_MARKER)):
                continue
            try:
                pid = int(p.name.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                continue
            if pid == os.getpid() or _pid_alive(pid):
                continue
            shutil.rmtree(p, ignore_errors=True)
            removed += 1
            log.warning("restage repair: swept dead staging dir %s", p)
        if removed:
            metrics.registry.counter("serving.restage.swept").inc(removed)
        return removed


# -- process-wide hook (read by app/pmml.read_pmml_from_update_message) ------

_active: ModelStager | None = None


def active() -> ModelStager | None:
    return _active


def set_active(stager: ModelStager | None) -> None:
    global _active
    _active = stager
