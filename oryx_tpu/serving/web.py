"""HTTP routing: path templates, content negotiation, error mapping.

Rebuilds the JAX-RS surface the reference gets from Jersey: @Path-style
templates with single-segment ``{name}`` and greedy ``{name:+}`` params
(the reference's ``{userID : .+}`` idiom for multi-value paths, e.g.
RecommendToMany.java:57), CSV/JSON content negotiation
(CSVMessageBodyWriter.java:38-87), and OryxServingException →
HTTP-status mapping (OryxExceptionMapper.java:28).
"""

from __future__ import annotations

import inspect
import json
import re
from dataclasses import dataclass, field
from typing import Any, Callable
from urllib.parse import parse_qs, unquote

__all__ = [
    "OryxServingException",
    "Request",
    "Response",
    "Router",
    "ServingContext",
    "resource",
    "global_registry",
]


class OryxServingException(Exception):
    """Maps to an HTTP error status (OryxServingException.java)."""

    def __init__(self, status: int, message: str = "") -> None:
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


@dataclass
class Request:
    method: str
    path: str
    params: dict[str, Any]  # path template params ({x:+} values are lists)
    query: dict[str, list[str]]
    headers: dict[str, str]
    body: bytes = b""

    def q1(self, name: str, default: str | None = None) -> str | None:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def q_int(self, name: str, default: int) -> int:
        v = self.q1(name)
        if v is None:
            return default
        try:
            return int(v)
        except ValueError:
            raise OryxServingException(400, f"bad value for {name}: {v!r}")

    def q_float(self, name: str, default: float) -> float:
        v = self.q1(name)
        if v is None:
            return default
        try:
            return float(v)
        except ValueError:
            raise OryxServingException(400, f"bad value for {name}: {v!r}")

    def q_bool(self, name: str, default: bool = False) -> bool:
        v = self.q1(name)
        if v is None:
            return default
        return v.lower() == "true"

    def q_list(self, name: str) -> list[str]:
        return self.query.get(name, [])

    def text(self) -> str:
        return self.body.decode("utf-8")

    def json(self) -> Any:
        try:
            return json.loads(self.text())
        except json.JSONDecodeError as e:
            raise OryxServingException(400, f"bad JSON body: {e}")


@dataclass
class Response:
    status: int = 200
    body: Any = None
    content_type: str | None = None  # None = negotiate
    headers: dict[str, str] = field(default_factory=dict)


class ServingContext:
    """What resources get besides the request: the model manager, the input
    producer, and config (the reference stores these in servlet-context
    attributes, OryxResource.java:11-36 / AbstractOryxResource.java:54-73)."""

    def __init__(
        self,
        model_manager,
        input_producer,
        config,
        health=None,
        registry=None,
        rollback_publisher=None,
        instance_metrics=None,
        admission=None,
        experiments=None,
    ) -> None:
        self.model_manager = model_manager
        self.input_producer = input_producer
        self.config = config
        # ServingHealth (oryx_tpu/serving/layer.py) when run under a full
        # ServingLayer; None in bare router tests
        self.health = health
        # RegistryStore over the batch model dir (oryx_tpu/registry/store.py)
        # when one is configured; backs /model/generations and rollback
        self.registry = registry
        # callable(generation_id) -> publish key, provided by ServingLayer
        # (republishes an archived generation onto the update topic)
        self.rollback_publisher = rollback_publisher
        # this replica's own MetricsRegistry (per-replica truth when many
        # ServingLayers share one process); None in bare router tests
        self.instance_metrics = instance_metrics
        # AdmissionController (oryx_tpu/serving/overload.py) when overload
        # control is enabled under a full ServingLayer; None otherwise
        self.admission = admission
        # ExperimentCoordinator (oryx_tpu/experiments/coordinator.py)
        # when online experiments are enabled; backs GET /experiments
        self.experiments = experiments


# ---------------------------------------------------------------------------
# Resource registry
# ---------------------------------------------------------------------------

_REGISTRY: list[tuple[str, str, str, Callable]] = []  # (module, method, template, fn)


def resource(method: str, template: str):
    """Register a handler: @resource("GET", "/recommend/{userID}").

    Handlers may take (ctx, req) or just (req). Return value may be a
    Response, or any JSON-serializable object (negotiated to CSV when the
    client prefers text/csv)."""

    def deco(fn: Callable) -> Callable:
        _REGISTRY.append((fn.__module__, method.upper(), template, fn))
        return fn

    return deco


def global_registry() -> list[tuple[str, str, str, Callable]]:
    return list(_REGISTRY)


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

_PARAM_RE = re.compile(r"\{([A-Za-z_][A-Za-z0-9_]*)(:\+)?\}")


def _compile_template(template: str) -> re.Pattern:
    pattern = "^"
    pos = 0
    for m in _PARAM_RE.finditer(template):
        pattern += re.escape(template[pos : m.start()])
        name, greedy = m.group(1), m.group(2)
        pattern += f"(?P<{name}>.+)" if greedy else f"(?P<{name}>[^/]+)"
        pos = m.end()
    pattern += re.escape(template[pos:]) + "$"
    return re.compile(pattern)


class _Route:
    def __init__(self, method: str, template: str, fn: Callable) -> None:
        self.method = method
        self.template = template
        self.fn = fn
        self.pattern = _compile_template(template)
        self.greedy_names = {m.group(1) for m in _PARAM_RE.finditer(template) if m.group(2)}
        # longer literal prefixes match first
        self.specificity = (template.count("/"), -len(self.greedy_names), len(template))

    def match(self, path: str) -> dict[str, Any] | None:
        m = self.pattern.match(path)
        if not m:
            return None
        params: dict[str, Any] = {}
        for name, value in m.groupdict().items():
            if name in self.greedy_names:
                params[name] = [unquote(seg) for seg in value.split("/") if seg]
            else:
                params[name] = unquote(value)
        return params


class Router:
    def __init__(self) -> None:
        self._routes: list[_Route] = []

    def add(self, method: str, template: str, fn: Callable) -> None:
        self._routes.append(_Route(method.upper(), template, fn))
        self._routes.sort(key=lambda r: r.specificity, reverse=True)

    def add_from_registry(self, packages: list[str] | None) -> int:
        """Register resources whose defining module falls under one of
        `packages` (None = all registered). The OryxApplication package-scan
        analogue (OryxApplication.java:62-86)."""
        count = 0
        for module, method, template, fn in global_registry():
            if packages is None or any(
                module == p or module.startswith(p + ".") for p in packages
            ):
                self.add(method, template, fn)
                count += 1
        return count

    def dispatch(self, ctx: ServingContext, req: Request) -> Response:
        path_matched = False
        for route in self._routes:
            params = route.match(req.path)
            if params is None:
                continue
            path_matched = True
            if route.method != req.method:
                continue
            req.params = params
            result = _invoke(route.fn, ctx, req)
            if isinstance(result, Response):
                return result
            return Response(200, result)
        if path_matched:
            raise OryxServingException(405, f"method {req.method} not allowed for {req.path}")
        raise OryxServingException(404, f"no resource for {req.path}")


def _invoke(fn: Callable, ctx: ServingContext, req: Request) -> Any:
    sig = inspect.signature(fn)
    if len(sig.parameters) >= 2:
        return fn(ctx, req)
    return fn(req)


# ---------------------------------------------------------------------------
# Serialization / negotiation
# ---------------------------------------------------------------------------


def _csv_line(item: Any) -> str:
    from oryx_tpu.common import text as text_utils

    if callable(getattr(item, "to_csv", None)):  # HasCSV, structurally
        return item.to_csv()
    if isinstance(item, (list, tuple)):
        return text_utils.join_csv(list(item))
    if isinstance(item, dict):
        return text_utils.join_csv(list(item.values()))
    return str(item)


def _jsonable(obj: Any) -> Any:
    if hasattr(obj, "to_json"):
        return obj.to_json()
    if isinstance(obj, dict):
        return {k: _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    try:
        import numpy as np

        if isinstance(obj, np.ndarray):
            return obj.tolist()
        if isinstance(obj, np.generic):
            return obj.item()
    except ImportError:  # pragma: no cover
        pass
    return obj


def render(response: Response, accept: str) -> tuple[int, bytes, str, dict[str, str]]:
    """Serialize a Response per the Accept header: text/csv renders one CSV
    line per item (CSVMessageBodyWriter semantics); JSON otherwise."""
    if response.body is None:
        return response.status, b"", "text/plain", response.headers
    ct = response.content_type
    if ct is None:
        wants_csv = "text/csv" in accept and "application/json" not in accept.split(",")[0]
        ct = "text/csv" if wants_csv else "application/json"
    if ct == "application/json":
        payload = json.dumps(_jsonable(response.body)).encode("utf-8")
    elif ct == "text/csv":
        body = response.body
        if isinstance(body, (list, tuple)):
            payload = ("\n".join(_csv_line(x) for x in body) + "\n").encode("utf-8")
        else:
            payload = (_csv_line(body) + "\n").encode("utf-8")
    else:
        payload = body_bytes(response.body)
    return response.status, payload, ct, response.headers


def body_bytes(body: Any) -> bytes:
    if isinstance(body, bytes):
        return body
    return str(body).encode("utf-8")
