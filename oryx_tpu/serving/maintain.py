"""Always-fresh ANN index maintenance (docs/serving-scan.md).

The speed layer folds item updates into the IVF index's pending overlay;
overflow spills the oldest entries to ``pending_spill`` where they go
invisible until compacted. Before this loop existed the only way back to
a clustered layout was the refresh tick's full re-cluster — a stop-the-
world rebuild that could land on a request's watch. ``IndexMaintainer``
makes maintenance a first-class background production: it snapshots the
overlay + spill under the model's cache lock, runs the incremental cell
split/merge compaction (``ivf.compact_ivf`` — SPFresh-style LIRE, no
k-means retraining) OFF the request path, and installs the result with a
single pointer swap, replaying any fold-ins that raced the compaction.

When a registry + update-topic producer are attached, each compaction
also publishes an **index generation** — ``<model-dir>/index/<gid>/``
holding the clustering manifest + centroids — as an ``INDEX-REF`` record
on the update topic, exactly like models publish MODEL/MODEL-REF.
Replicas consume it through the same ``GenerationTracker`` (duplicate
suppression, ``serving.index.generation`` gauge) and rebuild their local
layout seeded with the published centroids, so a whole fleet converges
on one clustering with zero downtime: each replica builds off-lock and
swaps under its cache lock.

Config: ``oryx.serving.scan.ann.maintain.*`` (interval, watermark,
split/merge thresholds, publish switch), wired through
``ServingLayer.configure_ann``'s config block like every other ANN knob.
"""

from __future__ import annotations

import json
import logging
import threading
import time

import numpy as np

from oryx_tpu.common import ledger, metrics, storage

log = logging.getLogger(__name__)

# fold-in -> clustered-layout visibility lag, observed at each
# compaction for the oldest entry folded (worst case over the batch)
FRESHNESS_GAUGE = "serving.ann.freshness.seconds"

INDEX_REF_KEY = "INDEX-REF"
INDEX_DIR_NAME = "index"  # non-numeric: invisible to model-generation GC
INDEX_MANIFEST_NAME = "index.json"
INDEX_CENTROIDS_NAME = "centroids.npy"

# module knobs (oryx.serving.scan.ann.maintain.*), mirroring
# ops.ivf.configure_ann's style: set before the layer starts
MAINTAIN_ENABLED = False
MAINTAIN_INTERVAL_SEC = 5.0
MAINTAIN_WATERMARK = 0.5
MAINTAIN_SPLIT_MAX_ITEMS = 0  # 0 = auto (mean * 4)
MAINTAIN_MERGE_MIN_ITEMS = 0  # 0 = auto (mean / 8)
MAINTAIN_PUBLISH = False


def configure_maintain(
    enabled=None,
    interval_sec=None,
    watermark=None,
    split_max_items=None,
    merge_min_items=None,
    publish=None,
):
    """Set the maintenance-loop defaults (config:
    oryx.serving.scan.ann.maintain.*); None leaves a knob unchanged."""
    global MAINTAIN_ENABLED, MAINTAIN_INTERVAL_SEC, MAINTAIN_WATERMARK
    global MAINTAIN_SPLIT_MAX_ITEMS, MAINTAIN_MERGE_MIN_ITEMS, MAINTAIN_PUBLISH
    if enabled is not None:
        MAINTAIN_ENABLED = bool(enabled)
    if interval_sec is not None:
        MAINTAIN_INTERVAL_SEC = float(interval_sec)
    if watermark is not None:
        MAINTAIN_WATERMARK = float(watermark)
    if split_max_items is not None:
        MAINTAIN_SPLIT_MAX_ITEMS = int(split_max_items)
    if merge_min_items is not None:
        MAINTAIN_MERGE_MIN_ITEMS = int(merge_min_items)
    if publish is not None:
        MAINTAIN_PUBLISH = bool(publish)


def maintain_enabled() -> bool:
    return MAINTAIN_ENABLED


# -- index generations --------------------------------------------------------


def index_generation_dir(model_dir: str, generation_id: str) -> str:
    return storage.join(model_dir, INDEX_DIR_NAME, str(generation_id))


def write_index_generation(
    model_dir: str,
    index,
    *,
    generation_id: str | None = None,
    stats: dict | None = None,
) -> str:
    """Archive one compacted clustering under
    ``<model-dir>/index/<gid>/``: a JSON manifest + the centroid matrix
    ([n_cells, features] f32). The centroids ARE the clustering — a
    replica seeds ``build_ivf(mat, centroids=...)`` with them and gets
    the identical cell geometry over its own (replayed-to-parity) item
    store, so the artifact stays KBs even for 100M-item catalogs.
    Returns the generation dir path (the INDEX-REF payload)."""
    gid = str(generation_id) if generation_id else str(int(time.time() * 1000))
    d = index_generation_dir(model_dir, gid)
    storage.mkdirs(d)
    feat = int(index.features)
    cents = np.ascontiguousarray(
        np.asarray(index.centroids_t, dtype=np.float32).T[:, :feat]
    )
    import io

    buf = io.BytesIO()
    np.save(buf, cents)
    storage.commit_bytes(storage.join(d, INDEX_CENTROIDS_NAME), buf.getvalue())
    manifest = {
        "generation_id": gid,
        "created_at": time.time(),
        "n_cells": int(cents.shape[0]),
        "features": feat,
        "n_items": int(index.n_items),
    }
    if stats:
        manifest["compaction"] = {
            k: int(stats[k])
            for k in ("folded", "live", "splits", "merges")
            if k in stats
        }
    storage.commit_text(storage.join(d, INDEX_MANIFEST_NAME), json.dumps(manifest))
    return d


def read_index_generation(ref: str) -> tuple[str, dict, np.ndarray] | None:
    """(generation_id, manifest, centroids) from an INDEX-REF dir, or
    None when the ref is unreadable / malformed."""
    try:
        manifest = json.loads(storage.read_text(storage.join(ref, INDEX_MANIFEST_NAME)))
        with storage.open_read(storage.join(ref, INDEX_CENTROIDS_NAME)) as f:
            cents = np.load(f)
        gid = str(manifest.get("generation_id") or ref.rstrip("/").split("/")[-1])
        return gid, manifest, np.ascontiguousarray(cents, np.float32)
    except Exception:
        log.warning("unreadable index generation at %r", ref, exc_info=True)
        return None


class IndexMaintainer:
    """Background incremental ANN compaction for one serving model.

    The owning side (the serving layer, or a test driving ``run_once``)
    wires it to any model exposing the maintenance protocol:

    - ``maintenance_snapshot(watermark, force)`` -> ``(index, snapshot)``
      or None when there is nothing to do
    - ``install_compacted(new_index, stats)`` -> bool (False = a full
      rebuild superseded the snapshot; the result is discarded)
    - ``set_index_pressure_callback(cb)`` (optional): called when a
      fold-in batch crosses the overlay watermark or spills, waking the
      loop ahead of its interval — the degrade path's freshness bound

    Compaction runs entirely off the request path: the snapshot and the
    install are brief critical sections; the split/merge clustering work
    happens between them on this thread.
    """

    def __init__(
        self,
        model_source,
        *,
        interval_sec: float | None = None,
        watermark: float | None = None,
        split_max_items: int | None = None,
        merge_min_items: int | None = None,
        publish_fn=None,
        seed: int = 0,
    ) -> None:
        # model_source: zero-arg callable returning the current model (or
        # None) — rotation swaps models, the maintainer follows along
        self._model_source = model_source
        self.interval_sec = (
            MAINTAIN_INTERVAL_SEC if interval_sec is None else float(interval_sec)
        )
        self.watermark = MAINTAIN_WATERMARK if watermark is None else float(watermark)
        self.split_max_items = (
            MAINTAIN_SPLIT_MAX_ITEMS if split_max_items is None else int(split_max_items)
        )
        self.merge_min_items = (
            MAINTAIN_MERGE_MIN_ITEMS if merge_min_items is None else int(merge_min_items)
        )
        # publish_fn(index, stats) -> generation dir: archives + sends the
        # INDEX-REF (serving layer wires this to its registry + producer)
        self._publish_fn = publish_fn
        self._seed = int(seed)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._attached: set[int] = set()  # models already given the callback
        self.compactions = 0
        self.published = 0
        self.last_stats: dict | None = None

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="ann-index-maintainer", daemon=True
        )
        self._thread.start()
        ledger.register("thread", self._thread, live=threading.Thread.is_alive)

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        self._thread = None
        if t is not None:
            t.join(timeout=10.0)

    def note_pressure(self) -> None:
        """Fold-in pressure signal: wake the loop ahead of the interval
        (called by the model under its cache lock — just an Event set)."""
        self._wake.set()

    # -- the loop -------------------------------------------------------------

    def _run(self) -> None:
        while not self._stop.is_set():
            # hook the CURRENT model before sleeping so fold-in pressure
            # can wake us ahead of the interval from the very first batch
            # (and again after every rotation swap)
            try:
                model = self._model_source()
                if model is not None:
                    self._hook_model(model)
            except Exception:  # pragma: no cover - defensive
                pass
            self._wake.wait(timeout=self.interval_sec)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.run_once()
            except Exception:
                # maintenance must never take serving down; the next tick
                # retries against fresh state
                log.warning("index maintenance pass failed", exc_info=True)
                metrics.registry.counter("serving.ann.maintain.errors").inc()

    def _hook_model(self, model) -> None:
        cb = getattr(model, "set_index_pressure_callback", None)
        if cb is None:
            return
        key = id(model)
        if key in self._attached:
            return
        cb(self.note_pressure)
        self._attached.add(key)
        if len(self._attached) > 64:  # rotation churn: ids are not stable
            self._attached = {key}

    def run_once(self, force: bool = False) -> dict | None:
        """One maintenance pass: snapshot -> compact -> install ->
        publish. Returns the compaction stats dict when a pass ran and
        installed, None otherwise (nothing pending, or a full rebuild
        raced the snapshot and won). Tests drive this directly."""
        model = self._model_source()
        if model is None:
            return None
        self._hook_model(model)
        snap_fn = getattr(model, "maintenance_snapshot", None)
        if snap_fn is None:
            return None
        work = snap_fn(watermark=self.watermark, force=force)
        if work is None:
            return None
        index, snapshot = work
        from oryx_tpu.ops import ivf as ivf_ops

        t0 = time.monotonic()
        new_index, stats = ivf_ops.compact_ivf(
            index,
            snapshot,
            seed=self._seed + self.compactions,
            split_max_items=self.split_max_items,
            merge_min_items=self.merge_min_items,
        )
        new_index = ivf_ops.attach_tiered_plane(new_index)
        stats["compact_seconds"] = time.monotonic() - t0
        if not model.install_compacted(new_index, stats):
            log.info("compaction discarded: a full rebuild superseded the snapshot")
            return None
        self.compactions += 1
        self.last_stats = stats
        born = stats.get("born") or {}
        if born:
            # worst-case fold-in -> clustered-visibility lag this pass
            lag = max(0.0, time.time() - min(born.values()))
            metrics.registry.gauge(FRESHNESS_GAUGE).set(lag)
        metrics.registry.counter("serving.ann.maintain.compactions").inc()
        if self._publish_fn is not None and stats.get("folded", 0):
            try:
                ref = self._publish_fn(new_index, stats)
                self.published += 1
                # the publisher consumes its own INDEX-REF off the topic;
                # marking the generation here dedups that self-delivery
                note = getattr(model, "note_published_index", None)
                if note is not None and ref:
                    note(str(ref).rstrip("/").split("/")[-1])
            except Exception:
                log.warning("index generation publish failed", exc_info=True)
                metrics.registry.counter("serving.ann.maintain.publish-errors").inc()
        return stats
