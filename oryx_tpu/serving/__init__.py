"""Serving-layer runtime: embedded HTTP server + model manager lifecycle.

Rebuild of framework/oryx-lambda-serving (SURVEY.md §2.5): the reference
embeds Tomcat + Jersey and discovers JAX-RS resources by package scan
(OryxApplication.java:42-98); here an embedded threaded HTTP server routes
to resources registered with the @resource decorator from the modules
listed in oryx.serving.application-resources.
"""

from oryx_tpu.serving.web import (  # noqa: F401
    OryxServingException,
    Request,
    Response,
    ServingContext,
    resource,
)
from oryx_tpu.serving.layer import ServingLayer  # noqa: F401
