"""Example speed model manager (reference: app/example/.../speed/
ExampleSpeedModelManager.java)."""

from __future__ import annotations

import json
import threading
from typing import Iterable, Iterator

from oryx_tpu.api.speed import SpeedModelManager
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.example.batch import count_distinct_other_words


class ExampleSpeedModelManager(SpeedModelManager):
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for km in update_iterator:
            if km.key == "MODEL":
                model = json.loads(km.message)
                with self._lock:
                    for stale in set(self._counts) - set(model):
                        del self._counts[stale]
                    self._counts.update(model)
            elif km.key == "UP":
                pass  # this manager's own updates; nothing to do
            else:
                raise ValueError(f"unknown key {km.key}")

    def build_updates(self, new_data: Iterable[KeyMessage]) -> Iterable[str]:
        out = []
        for word, count in count_distinct_other_words(new_data).items():
            with self._lock:
                new_count = self._counts.get(word, 0) + count
                self._counts[word] = new_count
            out.append(f"{word},{new_count}")
        return out
