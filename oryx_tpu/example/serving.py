"""Example serving model manager + endpoints (reference: app/example/...
/serving/{ExampleServingModelManager,ExampleServingModel,Add,Distinct}
.java)."""

from __future__ import annotations

import json
import threading
from typing import Iterator

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.serving.web import OryxServingException, Request, Response, ServingContext, resource


class ExampleServingModel(ServingModel):
    def __init__(self, counts: dict[str, int]) -> None:
        self._counts = counts

    def get_fraction_loaded(self) -> float:
        return 1.0

    def get_words(self) -> dict[str, int]:
        return self._counts


class ExampleServingModelManager(AbstractServingModelManager):
    def __init__(self, config) -> None:
        super().__init__(config)
        self._lock = threading.Lock()
        self._counts: dict[str, int] = {}
        self._have_model = False

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        for km in update_iterator:
            if km.key == "MODEL":
                model = json.loads(km.message)
                with self._lock:
                    for stale in set(self._counts) - set(model):
                        del self._counts[stale]
                    self._counts.update(model)
                    self._have_model = True
            elif km.key == "UP":
                word, count = km.message.split(",", 1)
                with self._lock:
                    self._counts[word] = int(count)
                    self._have_model = True
            else:
                raise ValueError(f"unknown key {km.key}")

    def get_model(self) -> ExampleServingModel | None:
        with self._lock:
            if not self._have_model:
                return None
            return ExampleServingModel(dict(self._counts))


@resource("GET", "/distinct")
def distinct(ctx: ServingContext, req: Request):
    model = ctx.model_manager.get_model() if ctx.model_manager else None
    if model is None:
        raise OryxServingException(503, "model not yet available")
    return model.get_words()


@resource("POST", "/add")
def add(ctx: ServingContext, req: Request) -> Response:
    if ctx.model_manager is not None and ctx.model_manager.is_read_only():
        raise OryxServingException(403, "read-only")
    if ctx.input_producer is None:
        raise OryxServingException(503, "no input topic configured")
    for line in req.text().splitlines():
        if line.strip():
            ctx.input_producer.send(None, line.strip())
    return Response(204)
