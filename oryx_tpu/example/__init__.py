"""Word-count example app: the bare SPI without the ML tier.

Rebuild of app/example (SURVEY.md §2.11): counts, for each word, the
number of distinct other words it co-occurs with on input lines; serves
the counts over /distinct and accepts new lines over /add.
"""
