"""Example batch update (reference: app/example/.../batch/
ExampleBatchLayerUpdate.java:28-56)."""

from __future__ import annotations

import json
from typing import Iterable

from oryx_tpu.api.batch import BatchLayerUpdate
from oryx_tpu.bus.core import KeyMessage, TopicProducer


def count_distinct_other_words(data: Iterable[KeyMessage]) -> dict[str, int]:
    """For each word, the number of distinct other words it has ever
    co-occurred with on a line (countDistinctOtherWords semantics)."""
    pairs: set[tuple[str, str]] = set()
    for rec in data:
        tokens = set(rec.message.split(" "))
        for a in tokens:
            for b in tokens:
                if a != b:
                    pairs.add((a, b))
    counts: dict[str, int] = {}
    for a, _ in pairs:
        counts[a] = counts.get(a, 0) + 1
    return counts


class ExampleBatchLayerUpdate(BatchLayerUpdate):
    def run_update(
        self,
        timestamp_ms: int,
        new_data: Iterable[KeyMessage],
        past_data: Iterable[KeyMessage],
        model_dir: str,
        model_update_topic: TopicProducer | None,
    ) -> None:
        all_data = list(new_data) + list(past_data)
        model = count_distinct_other_words(all_data)
        if model_update_topic is not None:
            model_update_topic.send("MODEL", json.dumps(model))
