"""python -m oryx_tpu — see oryx_tpu.cli."""

from oryx_tpu.cli import main

raise SystemExit(main())
