"""Mesh construction and sharding specs.

The framework's standard mesh has one axis, ``data``, over which examples
(users, points, ratings) are sharded; factor/parameter matrices are either
replicated or row-sharded over the same axis. Multi-axis meshes (e.g.
{data, model}) are supported by config: oryx.batch.compute.mesh is an
object of axis-name -> size.
"""

from __future__ import annotations

import contextlib
import math
import threading
from typing import Mapping

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"

# Per-thread device-subset override: hyperparameter candidates train
# concurrently on disjoint sub-meshes (MLUpdate.java:256-288 runs them as
# parallel Spark jobs; here each candidate thread scopes its own devices).
_scope = threading.local()


@contextlib.contextmanager
def device_scope(devices):
    """Restrict mesh construction in this thread to `devices`."""
    prev = getattr(_scope, "devices", None)
    _scope.devices = list(devices)
    try:
        yield
    finally:
        _scope.devices = prev


def scoped_devices() -> list:
    """Devices visible to mesh construction in this thread."""
    devs = getattr(_scope, "devices", None)
    return list(devs) if devs is not None else list(jax.devices())


def partition_devices(groups: int) -> list[list]:
    """Split the local devices into `groups` disjoint contiguous subsets
    (empty-safe: at most one group per device). Contiguity keeps each
    sub-mesh on neighboring ICI links."""
    devices = scoped_devices()
    groups = max(1, min(groups, len(devices)))
    per = len(devices) // groups
    return [devices[g * per : (g + 1) * per] for g in range(groups)]


def get_mesh(spec: Mapping[str, int] | None = None, devices=None) -> Mesh:
    """Build a Mesh over the thread's scoped devices (all local devices
    unless a device_scope is active). Default: one 'data' axis."""
    devices = scoped_devices() if devices is None else devices
    if not spec:
        return Mesh(np.asarray(devices), (DATA_AXIS,))
    names = tuple(spec.keys())
    sizes = tuple(int(s) for s in spec.values())
    want = math.prod(sizes)
    if want > len(devices):
        raise ValueError(f"mesh {dict(spec)} needs {want} devices, have {len(devices)}")
    arr = np.asarray(devices[:want]).reshape(sizes)
    return Mesh(arr, names)


def shard_rows(mesh: Mesh, axis: str = DATA_AXIS) -> NamedSharding:
    """First array dim sharded over `axis`, rest replicated."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def data_sharding(mesh: Mesh, ndim: int, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard dim 0 over `axis` for an ndim-dim array."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def pad_to_multiple(n: int, multiple: int) -> int:
    """Smallest m >= n with m % multiple == 0 (shard-evenly helper)."""
    return ((n + multiple - 1) // multiple) * multiple


def mesh_from_config(config) -> Mesh | None:
    """Mesh per oryx.batch.compute.mesh: explicit axis spec, or all local
    devices on one 'data' axis when several are present, else None
    (single device: skip sharding machinery entirely)."""
    spec = config.get("oryx.batch.compute.mesh", None)
    if spec is None:
        if len(scoped_devices()) > 1:
            return get_mesh()
        return None
    return get_mesh(spec)
