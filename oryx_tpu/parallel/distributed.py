"""Multi-host (multi-process) JAX initialization.

The reference scales out via YARN containers coordinated by Spark; the
TPU-native equivalent is JAX's multi-controller runtime: every host in a
pod slice runs the same layer process, calls
``jax.distributed.initialize``, and from then on ``jax.devices()`` spans
the whole slice — the trainers' ``shard_map``/``NamedSharding`` programs
then run collectives over ICI/DCN with no further coordination code.

Configuration (all optional — absent means single-process):

- ``oryx.batch.compute.distributed.coordinator-address`` — host:port of
  process 0; also honored from $ORYX_COORDINATOR.
- ``oryx.batch.compute.distributed.num-processes`` / $ORYX_NUM_PROCESSES
- ``oryx.batch.compute.distributed.process-id`` / $ORYX_PROCESS_ID

On TPU pods, all three can be omitted when the environment provides
them (jax.distributed.initialize() auto-detects on Cloud TPU); setting
just ``auto = true`` opts into that detection.
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger(__name__)

_initialized = False
_cache_enabled = False


def maybe_enable_compile_cache(config) -> None:
    """Point XLA's persistent compilation cache at
    ``oryx.compute.compile-cache-dir`` (no-op when null). Layers call this
    before touching a backend, so a restarted process — or generation N+1
    after a redeploy — reloads the programs generation N compiled instead
    of paying tens of seconds of recompiles per bucketed shape. (Spark
    has no analogue; JVM JIT state dies with the process. Here compiled
    XLA executables are a pure function of HLO + backend, so they cache
    like any artifact.)"""
    global _cache_enabled
    if _cache_enabled:
        return
    d = config.get("oryx.compute.compile-cache-dir", None)
    if not d:
        return
    import jax

    os.makedirs(d, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", str(d))
    # bucketed training shapes compile in ~1-40s each; cache all of them
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    _cache_enabled = True
    log.info("persistent XLA compilation cache at %s", d)


def maybe_initialize(config) -> bool:
    """Initialize jax.distributed when configured; returns True if this
    process is now (or already was) part of a multi-process runtime."""
    global _initialized
    if _initialized:
        return True
    coord = (
        config.get("oryx.batch.compute.distributed.coordinator-address", None)
        or os.environ.get("ORYX_COORDINATOR")
    )
    nproc = (
        config.get("oryx.batch.compute.distributed.num-processes", None)
        or os.environ.get("ORYX_NUM_PROCESSES")
    )
    pid = config.get("oryx.batch.compute.distributed.process-id", None)
    if pid is None:
        pid = os.environ.get("ORYX_PROCESS_ID")
    auto = bool(config.get("oryx.batch.compute.distributed.auto", False))
    if coord is None and not auto:
        return False

    if coord is not None:
        missing = [
            name
            for name, val in (
                ("num-processes ($ORYX_NUM_PROCESSES)", nproc),
                ("process-id ($ORYX_PROCESS_ID)", pid),
            )
            if val is None
        ]
        if missing:
            raise ValueError(
                "oryx.batch.compute.distributed.coordinator-address is set but "
                + " and ".join(missing)
                + " is missing; all three are required for explicit multi-process init"
            )

    import jax

    if coord is None:
        jax.distributed.initialize()  # Cloud TPU auto-detection
    else:
        jax.distributed.initialize(
            coordinator_address=str(coord),
            num_processes=int(nproc),
            process_id=int(pid),
        )
    _initialized = True
    log.info(
        "jax.distributed initialized: process %d/%d, %d global devices",
        jax.process_index(),
        jax.process_count(),
        len(jax.devices()),
    )
    return True
