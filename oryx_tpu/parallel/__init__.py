"""Device mesh + sharding helpers (the Spark-cluster analogue).

Where the reference distributes work as Spark RDD partitions over YARN
executors (SURVEY.md §2.12), this framework shards arrays over a
jax.sharding.Mesh and lets XLA insert ICI/DCN collectives.
"""

from oryx_tpu.parallel.mesh import (  # noqa: F401
    get_mesh,
    data_sharding,
    replicated,
    shard_rows,
)
