"""oryx_tpu — a TPU-native lambda-architecture ML framework.

A from-scratch rebuild of the capabilities of Oryx 2 (reference:
/root/reference, see SURVEY.md): a batch layer that periodically rebuilds
models from all historical data, a speed layer that produces incremental
model updates within seconds, and a horizontally scalable REST serving
layer — shipping end-to-end applications for ALS collaborative filtering,
k-means clustering, and random-decision-forest classification/regression.

Where the reference composes Spark + Kafka + HDFS + Tomcat on the JVM,
this framework is JAX/XLA-native: trainers are jit/shard_map programs over
a TPU device mesh, incremental updates (ALS fold-in, centroid drift, leaf
refresh) run on-device, and models flow between layers over a pluggable
message bus speaking the same MODEL / MODEL-REF / UP protocol with
PMML-compatible artifacts.
"""

__version__ = "0.1.0"

import os as _os
import sys as _sys


def honor_platform_env() -> None:
    """Make $JAX_PLATFORMS authoritative when a site-installed accelerator
    plugin already imported jax at interpreter startup and pinned
    jax_platforms (the pin would otherwise silently override the env var,
    making e.g. a CPU-only run hang trying to reach an unavailable
    accelerator). Empty string means "unpin" (restore JAX's default
    platform selection). No-op when jax hasn't been imported yet — its
    own env handling honors the variable. Runs at package import; call
    it explicitly from entry points that touch jax before importing this
    package (bench.py, __graft_entry__)."""
    if "JAX_PLATFORMS" not in _os.environ or "jax" not in _sys.modules:
        return
    _jax = _sys.modules["jax"]
    try:
        current = _jax.config.jax_platforms
    except AttributeError:  # pragma: no cover - config renamed
        current = None
    desired = _os.environ["JAX_PLATFORMS"] or None
    if current != desired:
        try:
            _jax.config.update("jax_platforms", desired)
            import logging as _logging

            _logging.getLogger(__name__).info(
                "overriding jax_platforms=%r with $JAX_PLATFORMS=%r", current, desired
            )
        except AttributeError:  # pragma: no cover - config renamed
            pass


honor_platform_env()
