"""Command-line launcher — the oryx-run.sh equivalent.

Rebuilds the operator surface of deploy/bin/oryx-run.sh:18-371 and the three
deploy Mains (deploy/oryx-batch/.../Main.java:31-37 etc.) as one Python entry
point:

    python -m oryx_tpu batch   --conf oryx.conf
    python -m oryx_tpu speed   --conf oryx.conf
    python -m oryx_tpu serving --conf oryx.conf
    python -m oryx_tpu bus-setup --conf oryx.conf     (kafka-setup analogue)
    python -m oryx_tpu bus-tail  --conf oryx.conf     (kafka-tail analogue)
    python -m oryx_tpu bus-input --conf oryx.conf --input-file data.csv
    python -m oryx_tpu config    --conf oryx.conf     (ConfigToProperties)

Where the reference wires user code with --app-jar, user app code here is a
Python import path named in config; --app-dir prepends directories to
sys.path so an app package outside the working dir resolves.
"""

from __future__ import annotations

import argparse
import hashlib
import logging
import os
import signal
import sys

from oryx_tpu.common import config as config_utils
from oryx_tpu.common.config import Config
from oryx_tpu.common.lang import close_at_shutdown

log = logging.getLogger(__name__)

COMMANDS = (
    "batch", "speed", "serving", "bus-setup", "bus-serve", "bus-tail",
    "bus-input", "config", "health", "models", "trace", "experiments", "lint",
    "repair", "tenants",
)

MODELS_SUBCOMMANDS = ("list", "show", "rollback", "gc")

TENANTS_SUBCOMMANDS = ("list", "show")


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="oryx_tpu",
        description="TPU-native lambda-architecture ML framework launcher",
    )
    p.add_argument("command", choices=COMMANDS, help="which layer or utility to run")
    p.add_argument(
        "subcommand",
        nargs="?",
        default=None,
        help="models: list | show <generation> | rollback <generation> | gc; "
        "tenants: list | show <tenant>; trace: optional trace id to filter by",
    )
    p.add_argument(
        "generation",
        nargs="?",
        default=None,
        help="models show/rollback: the generation id (a <timestampMs> dir "
        "name); tenants show: the tenant id",
    )
    p.add_argument(
        "--conf",
        default=None,
        help="configuration file (HOCON); defaults to ./oryx.conf when present",
    )
    p.add_argument(
        "--app-dir",
        action="append",
        default=[],
        help="directory added to sys.path so config-named app classes import "
        "(the --app-jar analogue); repeatable",
    )
    p.add_argument(
        "--set",
        action="append",
        default=[],
        metavar="KEY=VALUE",
        help="config override, e.g. --set oryx.serving.api.port=9090; repeatable",
    )
    p.add_argument("--input-file", default=None, help="bus-input: file to send line-by-line")
    p.add_argument(
        "--bind", default="0.0.0.0:6378",
        help="bus-serve: host:port to listen on (default 0.0.0.0:6378)",
    )
    p.add_argument(
        "--data-dir", default=None,
        help="bus-serve: directory for the served topic logs "
        "(default: the path of the config's file: input-topic broker)",
    )
    p.add_argument(
        "--from-beginning",
        action="store_true",
        help="bus-tail: start from offset 0 instead of latest",
    )
    p.add_argument("--log-level", default="INFO", help="python logging level")
    return p


def load_config(conf: str | None, overrides: list[str]) -> Config:
    """Layered config: packaged defaults <- --conf file <- --set overrides
    (ConfigUtils.getDefault + -Dconfig.file semantics, oryx-run.sh:146-147)."""
    if conf is None and os.path.exists("oryx.conf"):
        conf = "oryx.conf"
    if conf is not None:
        if not os.path.exists(conf):
            raise SystemExit(f"Config file {conf} does not exist")
        os.environ["ORYX_CONF"] = conf
    cfg = config_utils.get_default()
    if overrides:
        lines = []
        for kv in overrides:
            if "=" not in kv:
                raise SystemExit(f"bad --set {kv!r}: expected KEY=VALUE")
            key, _, value = kv.partition("=")
            lines.append(f"{key} = {value}")
        cfg = cfg.with_overlay("\n".join(lines))
    return cfg


def _install_signal_handlers(layer) -> None:
    def handler(signum, frame):  # noqa: ARG001
        log.info("signal %s: shutting down", signum)
        layer.close()

    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            signal.signal(sig, handler)
        except ValueError:  # pragma: no cover - non-main thread
            pass


def run_batch(cfg: Config) -> None:
    """deploy/oryx-batch Main.java:31-37 analogue."""
    from oryx_tpu.lambda_.batch import BatchLayer

    layer = BatchLayer(cfg)
    close_at_shutdown(layer)
    _install_signal_handlers(layer)
    layer.start()
    layer.await_termination()


def run_speed(cfg: Config) -> None:
    from oryx_tpu.lambda_.speed import SpeedLayer

    layer = SpeedLayer(cfg)
    close_at_shutdown(layer)
    _install_signal_handlers(layer)
    layer.start()
    layer.await_termination()


def run_serving(cfg: Config) -> None:
    from oryx_tpu.serving.layer import ServingLayer

    layer = ServingLayer(cfg)
    close_at_shutdown(layer)
    _install_signal_handlers(layer)
    layer.start()
    layer.await_termination()


def run_bus_setup(cfg: Config) -> None:
    """kafka-setup analogue (oryx-run.sh:319-351): create input topic with
    N partitions and the single-partition update topic, then report."""
    from oryx_tpu.bus import core as bus

    input_broker = cfg.get_string("oryx.input-topic.broker")
    input_topic = cfg.get_string("oryx.input-topic.message.topic")
    input_parts = cfg.get_optional_int("oryx.input-topic.message.partitions") or 1
    bus.maybe_create_topic(input_broker, input_topic, input_parts)
    print(f"created (or found) input topic {input_topic} "
          f"({input_parts} partitions) on {input_broker}")

    update_broker = cfg.get_optional_string("oryx.update-topic.broker")
    update_topic = cfg.get_optional_string("oryx.update-topic.message.topic")
    if update_broker and update_topic:
        update_parts = cfg.get_optional_int("oryx.update-topic.message.partitions") or 1
        max_size = cfg.get_optional_int("oryx.update-topic.message.max-size")
        bus.maybe_create_topic(
            update_broker, update_topic, update_parts,
            {"max-size": max_size} if max_size else None,
        )
        print(f"created (or found) update topic {update_topic} "
              f"({update_parts} partitions) on {update_broker}")


def run_bus_tail(cfg: Config, from_beginning: bool = False, out=None, stop_after: int | None = None) -> None:
    """kafka-tail analogue: follow input + update topics, one line per
    message as '<topic>\t<key>\t<message>'."""
    from oryx_tpu.bus.core import get_broker

    out = out or sys.stdout
    pairs = [(cfg.get_string("oryx.input-topic.broker"),
              cfg.get_string("oryx.input-topic.message.topic"))]
    ub = cfg.get_optional_string("oryx.update-topic.broker")
    ut = cfg.get_optional_string("oryx.update-topic.message.topic")
    if ub and ut:
        pairs.append((ub, ut))
    consumers = [
        (topic, get_broker(loc).consumer(topic, from_beginning=from_beginning))
        for loc, topic in pairs
    ]
    printed = 0
    try:
        while True:
            idle = True
            for topic, consumer in consumers:
                for rec in consumer.poll(timeout=0.2):
                    print(f"{topic}\t{rec.key}\t{rec.message}", file=out)
                    idle = False
                    printed += 1
                    if stop_after is not None and printed >= stop_after:
                        return
            if idle:
                out.flush()
    except KeyboardInterrupt:  # pragma: no cover
        pass
    finally:
        for _, consumer in consumers:
            consumer.close()


def run_bus_input(cfg: Config, input_file: str | None) -> int:
    """kafka-input analogue: push lines to the input topic, keyed by a hex
    hash of the line so they spread over partitions (the serving layer's
    sendInput idiom, AbstractOryxResource.java:65-69)."""
    from oryx_tpu.bus.core import get_broker

    broker = get_broker(cfg.get_string("oryx.input-topic.broker"))
    topic = cfg.get_string("oryx.input-topic.message.topic")
    parts = cfg.get_optional_int("oryx.input-topic.message.partitions") or 1
    broker.create_topic(topic, parts)

    if input_file:
        if not os.path.exists(input_file):
            raise SystemExit(f"Input file {input_file} does not exist")
        f = open(input_file, "r", encoding="utf-8")
    else:
        f = sys.stdin
    sent = 0
    try:
        with broker.producer(topic) as producer:
            for line in f:
                line = line.rstrip("\n")
                if not line:
                    continue
                key = hashlib.md5(line.encode("utf-8")).hexdigest()
                producer.send(key, line)
                sent += 1
    finally:
        if f is not sys.stdin:
            f.close()
    print(f"sent {sent} messages to {topic}")
    return sent


def run_health(cfg: Config, out=None) -> int:
    """Probe the serving layer's /healthz and /readyz (docs/resilience.md)
    and print one line per endpoint, then compare the live generation
    /healthz reports against the registry's CHAMPION pointer — serving
    answering from a generation the registry no longer endorses is the
    skew this probe exists to catch. Exit 0 only when everything is green
    and in sync."""
    import json
    from urllib.error import URLError
    from urllib.request import urlopen

    out = out or sys.stdout
    scheme = "https" if cfg.get_optional_string("oryx.serving.api.keystore-file") else "http"
    port = cfg.get_int(
        "oryx.serving.api.secure-port" if scheme == "https" else "oryx.serving.api.port"
    )
    ctx_path = cfg.get_string("oryx.serving.api.context-path").rstrip("/")
    ok = True
    live_generation = None
    tenant_generations: dict | None = None
    for endpoint in ("/healthz", "/readyz"):
        url = f"{scheme}://localhost:{port}{ctx_path}{endpoint}"
        try:
            with urlopen(url, timeout=5) as resp:
                status, body = resp.status, resp.read()
        except URLError as e:
            resp = getattr(e, "fp", None)
            if resp is None:
                print(f"{endpoint}: unreachable ({e})", file=out)
                ok = False
                continue
            status, body = e.code, resp.read()
        try:
            detail = json.loads(body)
        except ValueError:
            detail = None
        if endpoint == "/healthz" and isinstance(detail, dict):
            live_generation = detail.get("live_generation")
            tenants = detail.get("tenants")
            if isinstance(tenants, dict):
                tenant_generations = tenants
            # unified operator verdict (ok/degraded/draining/down) plus the
            # overload ladder's current rung when it is shedding quality
            unified = detail.get("status")
            if unified is not None:
                shed = detail.get("shed_stage")
                summary = f"status={unified}"
                if shed and shed != "full":
                    summary += f" shed_stage={shed}"
                print(f"{endpoint}: {summary}", file=out)
        print(f"{endpoint}: {status}" + (f" {detail}" if detail is not None else ""), file=out)
        ok = ok and status == 200

    model_dir = cfg.get_optional_string("oryx.batch.storage.model-dir")
    if model_dir:
        from oryx_tpu.registry.store import RegistryStore

        champion = RegistryStore(model_dir).champion_id()
        if live_generation is not None and champion is not None:
            if live_generation == champion:
                print(f"generations: live={live_generation} champion={champion} (in sync)", file=out)
            else:
                print(f"generations: live={live_generation} champion={champion} SKEW", file=out)
                ok = False
        else:
            print(f"generations: live={live_generation} champion={champion}", file=out)
    # per-tenant skew: each tenant's live generation (from /healthz's
    # tenants map) against that tenant's OWN registry champion — one
    # lagging tenant is skew even when every other tenant is in sync
    if tenant_generations is not None:
        from oryx_tpu.registry.store import RegistryStore
        from oryx_tpu.tenancy import TenantRegistry, tenant_config

        registry = TenantRegistry.from_config(cfg)
        for tid in sorted(tenant_generations):
            live = tenant_generations[tid]
            spec = registry.get(tid) if registry is not None else None
            champion = None
            if spec is not None:
                tenant_model_dir = tenant_config(cfg, spec).get_optional_string(
                    "oryx.batch.storage.model-dir"
                )
                if tenant_model_dir and os.path.isdir(tenant_model_dir):
                    champion = RegistryStore(tenant_model_dir).champion_id()
            if champion is None:
                print(f"tenant {tid}: live={live}", file=out)
            elif live == champion:
                print(f"tenant {tid}: live={live} champion={champion} (in sync)", file=out)
            else:
                print(f"tenant {tid}: live={live} champion={champion} SKEW", file=out)
                ok = False
    return 0 if ok else 1


def run_tenants(cfg: Config, subcommand: str | None, tenant_id: str | None, out=None) -> int:
    """Tenancy operator surface (docs/multi-tenancy.md):

        tenants list          one line per declared tenant: app, weight,
                              quota, SLO p99 (the fair-share inputs)
        tenants show <id>     the tenant's full derived identity as JSON —
                              namespaced topics, registry root, wired
                              classes — plus its registry's champion when
                              the model dir exists
    """
    import json

    from oryx_tpu.tenancy import TenantRegistry, tenant_config

    out = out or sys.stdout
    registry = TenantRegistry.from_config(cfg)
    if registry is None:
        print("tenancy disabled (oryx.tenancy.enabled = false or no tenants declared)", file=out)
        return 1
    if subcommand not in TENANTS_SUBCOMMANDS:
        raise SystemExit(
            f"tenants requires a subcommand: {' | '.join(TENANTS_SUBCOMMANDS)}"
        )

    if subcommand == "list":
        for spec in registry:
            marker = " *default*" if spec.tenant_id == registry.default_tenant else ""
            quota = f"{spec.quota_qps:g}qps" if spec.quota_qps else "-"
            print(
                f"{spec.tenant_id}\tapp={spec.app}\tweight={spec.weight:g}\t"
                f"quota={quota}\tslo_p99={spec.slo_p99_ms:g}ms{marker}",
                file=out,
            )
        return 0

    if tenant_id is None:
        raise SystemExit("tenants show requires a tenant id")
    spec = registry.get(tenant_id)
    if spec is None:
        print(f"no such tenant {tenant_id!r} (declared: {', '.join(registry.ids())})", file=out)
        return 1
    tcfg = tenant_config(cfg, spec)
    model_dir = tcfg.get_optional_string("oryx.batch.storage.model-dir")
    view = {
        "tenant": spec.tenant_id,
        "app": spec.app,
        "weight": spec.weight,
        "quota_qps": spec.quota_qps,
        "slo": {
            "p99_ms": spec.slo_p99_ms,
            "error_rate": spec.slo_error_rate,
            "min_full_quality": spec.slo_min_full_quality,
        },
        "input_topic": tcfg.get_optional_string("oryx.input-topic.message.topic"),
        "update_topic": tcfg.get_optional_string("oryx.update-topic.message.topic"),
        "model_dir": model_dir,
        "wiring": {
            "update_class": spec.wiring("update-class"),
            "speed_manager": spec.wiring("speed-manager"),
            "serving_manager": spec.wiring("serving-manager"),
            "resources": spec.resource_modules(),
        },
    }
    if model_dir and os.path.isdir(model_dir):
        from oryx_tpu.registry.store import RegistryStore

        store = RegistryStore(model_dir)
        view["champion"] = store.champion_id()
        view["generations"] = store.list_generations()
    print(json.dumps(view, indent=2), file=out)
    return 0


def run_lint(cfg: Config, out=None) -> int:
    """Run the unified static-analysis suite (docs/static-analysis.md)
    over the default targets with the checked-in baseline — the same
    gate tier-1 runs, as an operator command next to ``health``. Exit 0
    only when the tree is clean."""
    from oryx_tpu.analysis import run_passes

    out = out or sys.stdout
    res = run_passes()
    for f in res.findings:
        print(f.render(), file=out)
    for key in sorted(res.stale_baseline):
        print(f"note: stale baseline entry (no longer fires): {key}", file=out)
    verdict = (
        "clean"
        if res.rc == 0
        else f"{len(res.findings)} finding(s)"
    )
    print(f"oryxlint: {verdict} ({len(res.suppressed)} baselined)", file=out)
    return res.rc


def run_repair(cfg: Config, out=None) -> int:
    """Offline fsck across every durable store the config names
    (docs/durability.md): bus topic logs (torn tails, unreadable offset
    ledgers, garbled shm frames), the model registry layout (stale
    commit temps, half-written generations, an unusable CHAMPION), and
    the serving restage cache. The same audits run automatically on
    consumer open / MLUpdate start / stager construction; this command
    runs them all at once, with everything down, and prints what was
    repaired. Run it with the layers stopped — a registry fsck racing an
    in-flight promote mistakes a generation mid-upload for a torn one.

    Exit 0 when every store is clean or repaired; repairs are also
    visible on the bus.repair.* / registry.repair.* counters."""
    out = out or sys.stdout
    repaired_anything = False

    seen: set[str] = set()
    for key in ("oryx.input-topic.broker", "oryx.update-topic.broker"):
        loc = cfg.get_optional_string(key)
        if not loc or loc in seen:
            continue
        seen.add(loc)
        from oryx_tpu.bus.core import get_broker

        broker = get_broker(loc)
        if not hasattr(broker, "repair"):
            print(f"bus {loc}: no repairable on-disk state ({type(broker).__name__})", file=out)
            continue
        report = broker.repair()
        # "frames" counts intact frames walked, not repairs
        repaired_anything |= any(v for k, v in report.items() if k != "frames")
        summary = ", ".join(f"{k}={v}" for k, v in sorted(report.items()) if v)
        print(f"bus {loc}: {summary or 'clean'}", file=out)

    model_dir = cfg.get_optional_string("oryx.batch.storage.model-dir")
    if model_dir:
        from oryx_tpu.registry.store import RegistryStore

        report = RegistryStore(model_dir).fsck(repair=True)
        repaired_anything |= any(report.values())
        summary = ", ".join(f"{k}={v}" for k, v in sorted(report.items()) if v)
        print(f"registry {model_dir}: {summary or 'clean'}", file=out)

    restage_dir = cfg.get_optional_string("oryx.serving.restage-dir")
    if restage_dir and os.path.isdir(restage_dir):
        from oryx_tpu.serving.restage import ModelStager

        swept = ModelStager(restage_dir).swept_on_open
        repaired_anything |= swept > 0
        print(f"restage {restage_dir}: " + (f"swept={swept}" if swept else "clean"), file=out)

    print("repair: " + ("repairs applied" if repaired_anything else "all stores clean"), file=out)
    return 0


def run_models(cfg: Config, subcommand: str | None, generation: str | None, out=None) -> int:
    """Registry operator surface (docs/model-registry.md):

        models list             one line per generation + the champion
        models show <gen>       the generation's manifest, as JSON
        models rollback <gen>   republish an archived generation onto the
                                update topic and move the CHAMPION pointer
        models gc               apply oryx.ml.retention.max-generations now
    """
    from oryx_tpu.registry.store import RegistryStore, publish_generation

    out = out or sys.stdout
    if subcommand not in MODELS_SUBCOMMANDS:
        raise SystemExit(
            f"models requires a subcommand: {' | '.join(MODELS_SUBCOMMANDS)}"
        )
    model_dir = cfg.get_string("oryx.batch.storage.model-dir")
    store = RegistryStore(model_dir)

    if subcommand == "list":
        champion = store.champion_id()
        gens = store.list_generations()
        if not gens:
            print(f"no generations under {model_dir}", file=out)
            return 0
        for gen in gens:
            manifest = store.read_manifest(gen)
            status = manifest.status if manifest else "?"
            metric = manifest.eval_metric if manifest else None
            marker = " *champion*" if gen == champion else ""
            print(f"{gen}\t{status}\teval={metric}{marker}", file=out)
        return 0

    if subcommand == "gc":
        deleted = store.gc(cfg.get_int("oryx.ml.retention.max-generations"))
        print(f"deleted {len(deleted)} generation(s): {deleted}", file=out)
        return 0

    if generation is None:
        raise SystemExit(f"models {subcommand} requires a generation id")
    if not store.has_generation(generation):
        print(f"no such generation {generation} under {model_dir}", file=out)
        return 1

    if subcommand == "show":
        manifest = store.read_manifest(generation)
        if manifest is None:
            print(f"generation {generation} has no manifest", file=out)
            return 1
        print(manifest.to_json(), file=out)
        return 0

    # rollback: same path the serving endpoint takes — republish, then
    # move the champion so batch gates/warm-starts against it
    from oryx_tpu.bus.core import get_broker

    broker_loc = cfg.get_optional_string("oryx.update-topic.broker")
    topic = cfg.get_optional_string("oryx.update-topic.message.topic")
    if not broker_loc or not topic:
        raise SystemExit("models rollback requires an update topic in config")
    with get_broker(broker_loc).producer(topic) as producer:
        key = publish_generation(
            store, generation, producer,
            cfg.get_int("oryx.update-topic.message.max-size"),
        )
    store.set_champion(generation)
    print(f"republished generation {generation} as {key}; champion moved", file=out)
    return 0


def run_trace(cfg: Config, trace_id: str | None = None, out=None) -> int:
    """Dump the serving layer's recorded spans as Chrome-trace JSON
    (docs/observability.md): fetch GET /trace from the configured serving
    port — optionally filtered to one trace id via ``trace <trace-id>`` —
    and print it. Pipe to a file and load in chrome://tracing or
    ui.perfetto.dev."""
    from urllib.error import URLError
    from urllib.request import urlopen

    out = out or sys.stdout
    scheme = "https" if cfg.get_optional_string("oryx.serving.api.keystore-file") else "http"
    port = cfg.get_int(
        "oryx.serving.api.secure-port" if scheme == "https" else "oryx.serving.api.port"
    )
    ctx_path = cfg.get_string("oryx.serving.api.context-path").rstrip("/")
    url = f"{scheme}://localhost:{port}{ctx_path}/trace"
    if trace_id:
        url += f"?trace={trace_id}"
    try:
        with urlopen(url, timeout=10) as resp:
            body = resp.read().decode("utf-8", "replace")
    except URLError as e:
        print(f"/trace: unreachable ({e})", file=out)
        return 1
    print(body, file=out)
    return 0


def run_experiments(cfg: Config, out=None) -> int:
    """Fetch and pretty-print the serving layer's GET /experiments body
    (docs/experiments.md): arm split config, champion/challenger
    generations, per-arm online metrics, and the standing online-gate
    decision. Exit 0 when the endpoint answered, 1 when unreachable."""
    import json
    from urllib.error import URLError
    from urllib.request import urlopen

    out = out or sys.stdout
    scheme = "https" if cfg.get_optional_string("oryx.serving.api.keystore-file") else "http"
    port = cfg.get_int(
        "oryx.serving.api.secure-port" if scheme == "https" else "oryx.serving.api.port"
    )
    ctx_path = cfg.get_string("oryx.serving.api.context-path").rstrip("/")
    url = f"{scheme}://localhost:{port}{ctx_path}/experiments"
    try:
        with urlopen(url, timeout=10) as resp:
            body = resp.read().decode("utf-8", "replace")
    except URLError as e:
        print(f"/experiments: unreachable ({e})", file=out)
        return 1
    try:
        print(json.dumps(json.loads(body), indent=2, sort_keys=True), file=out)
    except ValueError:
        print(body, file=out)
    return 0


def run_config_dump(cfg: Config, out=None) -> None:
    """ConfigToProperties analogue: dump the resolved oryx.* tree as
    key=value lines for shell consumption (used at oryx-run.sh:87)."""
    out = out or sys.stdout
    props = cfg.get_config("oryx").to_properties(prefix="oryx")
    for key in sorted(props):
        print(f"{key}={props[key]}", file=out)


def run_bus_serve(cfg: Config, bind: str, data_dir: str | None) -> None:
    """Serve a bus over TCP (oryx_tpu.bus.netbus): topic logs live in
    data_dir on THIS host; every layer on any host reaches them via a
    tcp://host:port locator — the multi-host transport when no shared
    filesystem (and no Kafka) is available."""
    host, _, port = bind.partition(":")
    if data_dir is None:
        loc = cfg.get_string("oryx.input-topic.broker")
        if not loc.startswith("file:"):
            raise SystemExit(
                "--data-dir required (input-topic broker is not a file: path)"
            )
        # normalize exactly like get_broker: strip leading '//' pairs so
        # file:///var/x serves the same /var/x a co-located layer opens
        data_dir = loc[len("file:"):]
        while data_dir.startswith("//"):
            data_dir = data_dir[1:]
    from oryx_tpu.bus.netbus import BusServer

    server = BusServer((host or "0.0.0.0", int(port or 6378)), data_dir)
    log.info("bus-serve: tcp://%s:%s over %s", host, server.server_address[1], data_dir)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.shutdown()


def main(argv: list[str] | None = None) -> int:
    args = _build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)-5s %(name)s: %(message)s",
    )
    for d in args.app_dir:
        sys.path.insert(0, os.path.abspath(d))

    cfg = load_config(args.conf, args.set)

    if args.command == "batch":
        run_batch(cfg)
    elif args.command == "speed":
        run_speed(cfg)
    elif args.command == "serving":
        run_serving(cfg)
    elif args.command == "bus-setup":
        run_bus_setup(cfg)
    elif args.command == "bus-serve":
        run_bus_serve(cfg, args.bind, args.data_dir)
    elif args.command == "bus-tail":
        run_bus_tail(cfg, from_beginning=args.from_beginning)
    elif args.command == "bus-input":
        run_bus_input(cfg, args.input_file)
    elif args.command == "config":
        run_config_dump(cfg)
    elif args.command == "health":
        return run_health(cfg)
    elif args.command == "models":
        return run_models(cfg, args.subcommand, args.generation)
    elif args.command == "tenants":
        return run_tenants(cfg, args.subcommand, args.generation)
    elif args.command == "trace":
        return run_trace(cfg, args.subcommand)
    elif args.command == "experiments":
        return run_experiments(cfg)
    elif args.command == "lint":
        return run_lint(cfg)
    elif args.command == "repair":
        return run_repair(cfg)
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
