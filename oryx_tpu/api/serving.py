"""Serving layer SPI.

Rebuild of framework/oryx-api .../serving/ServingModelManager.java:35-75,
ServingModel.java, AbstractServingModelManager.java:39-53 and the
HasCSV marker used for text/csv content negotiation.
"""

from __future__ import annotations

import abc
from typing import Iterator

from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common.config import Config


class ServingModel(abc.ABC):
    @abc.abstractmethod
    def get_fraction_loaded(self) -> float:
        """Approximate fraction (0..1) of the model loaded so far."""


class HasCSV(abc.ABC):
    """Objects that can render themselves as a CSV line (HasCSV.java)."""

    @abc.abstractmethod
    def to_csv(self) -> str: ...


class ServingModelManager(abc.ABC):
    """Consumes models/updates from the update topic and serves the current
    model to REST resources."""

    @abc.abstractmethod
    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        """Blocking loop reading (MODEL|MODEL-REF|UP) messages; runs on a
        daemon thread started by the serving runtime
        (ModelManagerListener.java:134-145)."""

    def consume_blocks(self, block_iterator) -> None:
        """Columnar form of consume (iterator of RecordBlocks). Default
        adapts to the per-record consume(); managers with heavy replay
        traffic (ALS factor publishes are one UP per vector) override it
        to parse whole blocks vectorized."""
        self.consume(
            km for block in block_iterator for km in block.iter_key_messages()
        )

    @abc.abstractmethod
    def get_config(self) -> Config: ...

    @abc.abstractmethod
    def get_model(self) -> object | None: ...

    @abc.abstractmethod
    def is_read_only(self) -> bool: ...

    def close(self) -> None:
        """Release resources (idempotent)."""


class AbstractServingModelManager(ServingModelManager):
    """Convenience base: holds config, answers read-only from
    oryx.serving.api.read-only (AbstractServingModelManager.java:39-53)."""

    def __init__(self, config: Config) -> None:
        self._config = config
        self._read_only = config.get_bool("oryx.serving.api.read-only")

    def get_config(self) -> Config:
        return self._config

    def is_read_only(self) -> bool:
        return self._read_only
