"""Speed layer SPI.

Rebuild of framework/oryx-api .../speed/SpeedModelManager.java:37-66 and
SpeedModel.java.
"""

from __future__ import annotations

import abc
from typing import Iterable, Iterator

from oryx_tpu.bus.core import KeyMessage


class SpeedModel(abc.ABC):
    """In-memory speed model with incremental-load accounting."""

    @abc.abstractmethod
    def get_fraction_loaded(self) -> float:
        """Approximate fraction (0..1) of the model loaded so far."""


class SpeedModelManager(abc.ABC):
    """Consumes models/updates from the update topic and produces deltas
    from new input micro-batches."""

    @abc.abstractmethod
    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        """Blocking loop reading (MODEL|MODEL-REF|UP) messages and updating
        in-memory model state; runs on a dedicated thread
        (SpeedLayer.java:107-131)."""

    def consume_blocks(self, block_iterator) -> None:
        """Columnar form of consume: an iterator of RecordBlocks. The
        default adapts to the per-record consume(); managers on the hot
        self-consume path (ALS at 100K+ deltas/s) override this to parse
        whole blocks vectorized."""
        self.consume(
            km for block in block_iterator for km in block.iter_key_messages()
        )

    @abc.abstractmethod
    def build_updates(self, new_data: Iterable[KeyMessage]) -> Iterable[str]:
        """Given one micro-batch of input, return serialized model updates;
        each is published to the update topic with key "UP"
        (SpeedLayerUpdate.java:52-64)."""

    def close(self) -> None:
        """Release resources (idempotent)."""
