"""Batch layer SPI.

Rebuild of framework/oryx-api/src/main/java/com/cloudera/oryx/api/batch/
BatchLayerUpdate.java:38-59 — the entire batch contract is one method.
"""

from __future__ import annotations

import abc
from typing import Iterable

from oryx_tpu.bus.core import KeyMessage, TopicProducer


class BatchLayerUpdate(abc.ABC):
    """Implementations specify what is done with current and historical data
    to update a model. Constructed with the app Config when the constructor
    accepts one (ClassUtils-style instantiation)."""

    @abc.abstractmethod
    def run_update(
        self,
        timestamp_ms: int,
        new_data: Iterable[KeyMessage],
        past_data: Iterable[KeyMessage],
        model_dir: str,
        model_update_topic: TopicProducer | None,
    ) -> None:
        """Run one batch-model update: `new_data` is the input that arrived
        in this generation interval, `past_data` is all surviving earlier
        input re-read from the data dir (empty iterable if none), and models
        / updates are published to `model_update_topic` (None when the
        update topic is disabled).

        Mirrors BatchLayerUpdate.runUpdate(sparkContext, timestamp, newData,
        pastData, modelDirString, modelUpdateTopic); there is no Spark
        context — implementations run JAX programs directly.
        """
