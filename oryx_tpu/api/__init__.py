"""User-facing SPI: the three interfaces apps implement.

Rebuild of framework/oryx-api (SURVEY.md §2.3): `BatchLayerUpdate`,
`SpeedModelManager`, `ServingModelManager` plus the model readiness
contract. User implementations are named in config
(oryx.batch.update-class, oryx.speed.model-manager-class,
oryx.serving.model-manager-class) and loaded reflectively by the layer
runtimes, exactly as the reference does (BatchLayer.java:152-184).
"""

from oryx_tpu.api.batch import BatchLayerUpdate  # noqa: F401
from oryx_tpu.api.speed import SpeedModel, SpeedModelManager  # noqa: F401
from oryx_tpu.api.serving import (  # noqa: F401
    AbstractServingModelManager,
    ServingModel,
    ServingModelManager,
)
