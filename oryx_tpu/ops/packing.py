"""Host-side neighbor-bucket packing for ALS ingest.

This module owns the COO -> degree-bucketed neighbor layout transform that
feeds the device sweeps in :mod:`oryx_tpu.ops.als` (the analogue of the
reference's Spark-side block partitioning in ``ALSUpdate.java``). Two
implementations produce **bit-identical** buckets:

``build_neighbor_buckets_reference``
    The original single-process composite-key path: one stable argsort by
    ``(width_code << 40) | row`` over all entries. Kept as the equivalence
    oracle and as a fallback; its int64 comparison sort is the scaling
    wall (~3M entries/s on one core at 50M ratings).

``pack_neighbor_buckets``
    The sharded engine. Rows are split into contiguous ranges; each range
    is packed independently and writes directly into a preallocated
    arena, either in-process (1 worker) or from forked worker processes
    through ``multiprocessing.shared_memory`` (zero-copy handoff — no
    rating block is ever pickled; inputs reach workers by fork
    copy-on-write, outputs come back as the parent's own mapping of the
    shared arena). Input is streamed in bounded chunks (``chunk_rows``
    COO entries at a time) during counting and shard selection so peak
    RSS stays flat relative to the working set as the dataset grows.

    The restructure is also the single-core win: sorting by 16-bit keys
    (block id, then row-within-block) hits numpy's radix sort instead of
    the int64 timsort (~7x on the sort), and the final placement is one
    flat scatter through a per-row precomputed destination base instead
    of per-bucket masked passes.

Determinism contract: packing consumes no RNG, and the bucket layout is a
pure function of ``(row_idx, col_idx, values, num_rows, num_shards,
min_width, workspace_elems, features, stable_shapes)`` — the shard count,
worker count and chunk size never change a byte of the output. Within a
bucket, rows are ordered by ascending row id (the rank of the row among
same-width rows) and each row's entries keep input arrival order, exactly
the order the reference path's stable composite-key sort produces.
"""

from __future__ import annotations

import contextlib
import logging
import os
import time
import weakref
from dataclasses import dataclass
from multiprocessing import get_context, shared_memory
from typing import Optional

import numpy as np

logger = logging.getLogger(__name__)

# Rows per radix block: keys within a block fit uint16, numpy's stable
# sort dispatches to radix for <=16-bit integers.
_BLOCK_BITS = 16
_BLOCK = 1 << _BLOCK_BITS

# Multiprocess packing only pays for itself beyond this many entries;
# below it "auto" resolves to the in-process path.
_MIN_PARALLEL_NNZ = 2_000_000

# wall seconds of the most recent pack_neighbor_buckets call, split by
# phase, plus the resolved worker count. Read by ops/als.py (which folds
# the totals into its last_phase_seconds) and by tools/. Overwritten per
# call, never merged.
last_pack_stats: dict[str, float] = {}


def pad_to_multiple(n: int, multiple: int) -> int:
    """Smallest m >= n with m % multiple == 0 (shard-evenly helper)."""
    return ((n + multiple - 1) // multiple) * multiple


def _pow2_at_least(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


@dataclass
class NeighborBucket:
    """Rows whose degree rounds up to the same power-of-two width.

    ``rows`` holds global row ids per slot (``-1`` for pad slots added to
    make the slot count divisible by the sharding/chunking granule)."""

    rows: np.ndarray  # [n] int32 global row ids, -1 = pad slot
    idx: np.ndarray  # [n, D] int32 col indices into the other side
    val: np.ndarray  # [n, D] float32 rating values (0 where padded)
    deg: np.ndarray  # [n] int32 real entries per slot (0 for pad slots);
    #   entries fill positions 0..deg-1, so the [n, D] validity mask is
    #   exactly (iota < deg) and never needs to be materialized — a third
    #   of the bucket bytes on host AND device at scale
    chunk: int  # rows per lax.map step (n is a multiple of chunk*shards)

    @property
    def width(self) -> int:
        return self.idx.shape[1]

    @property
    def num_slots(self) -> int:
        return self.idx.shape[0]


@dataclass(frozen=True)
class PackingOptions:
    """Knobs for the sharded packing engine (``oryx.ml.als.packing.*``).

    ``workers``: ``"auto"`` (one worker per core, capped at 8, in-process
    when the input is small or the host has one core) or an explicit
    count; ``<= 1`` forces the in-process path.
    ``chunk_rows``: COO entries per streamed chunk during counting and
    shard selection — bounds the transient footprint of a pass over the
    input.
    ``shm_budget_mb``: ceiling on the shared-memory arena for the
    multi-process path; a pack whose output arena would exceed it falls
    back to the in-process path with a warning instead of failing (or
    filling a small /dev/shm).
    ``worker_timeout_sec``: per-pack deadline for the worker pool; on
    expiry workers are terminated and the pack raises instead of hanging.
    """

    workers: "int | str" = "auto"
    chunk_rows: int = 8_000_000
    shm_budget_mb: int = 8192
    worker_timeout_sec: float = 900.0

    def resolve_workers(self, nnz: int, num_rows: int) -> int:
        if isinstance(self.workers, str):
            if self.workers != "auto":
                raise ValueError(
                    f"packing workers must be 'auto' or an int, got {self.workers!r}"
                )
            w = min(os.cpu_count() or 1, 8)
            if nnz < _MIN_PARALLEL_NNZ:
                w = 1
        else:
            w = int(self.workers)
        # one worker per row at most; empty shards are legal but useless
        return max(1, min(w, max(1, num_rows)))


def bucket_geometry(
    num_real_rows: int,
    width: int,
    num_shards: int,
    workspace_elems: int,
    features: int,
    stable_shapes: bool,
) -> tuple[int, int]:
    """(padded slot count, chunk) for one bucket — shared by both packing
    paths so shape signatures (and the compile cache they key) never
    depend on which path packed the bucket.

    The chunk size keeps the [chunk, D, k] gather workspace under
    ``workspace_elems`` elements; ``stable_shapes`` rounds the slot count
    to a power of two so consecutive generations of a growing
    factorization reuse the compiled sweep (see ops/als.py)."""
    chunk = max(1, workspace_elems // (width * max(features, 1)))
    chunk = 1 << (chunk.bit_length() - 1)  # floor to power of two
    chunk = min(chunk, 1 << 16)
    if stable_shapes and num_shards & (num_shards - 1) == 0:
        # pow2 slot count: a multiple of chunk*num_shards for free
        # (all three are powers of two and n >= num_shards*chunk')
        n = _pow2_at_least(max(num_real_rows, num_shards))
        chunk = min(chunk, n // num_shards)
    else:
        granule = chunk * num_shards
        n = pad_to_multiple(num_real_rows, granule)
        # shrink chunk when padding to the granule would more than
        # double the bucket (tiny buckets shouldn't pay a 65536-row pad)
        while chunk > 1 and n >= 2 * max(1, num_real_rows):
            chunk //= 2
            granule = chunk * num_shards
            n = pad_to_multiple(num_real_rows, granule)
    return n, chunk


def row_widths(counts: np.ndarray, min_width: int) -> np.ndarray:
    """Power-of-two bucket width per row (>= min_width); log2 of an exact
    power of two is exact in float64, so the ceil is safe."""
    safe = np.maximum(counts, 1)
    return np.maximum(
        min_width, (2 ** np.ceil(np.log2(safe)).astype(np.int64)).astype(np.int64)
    )


def build_neighbor_buckets_reference(
    row_idx: np.ndarray,
    col_idx: np.ndarray,
    values: np.ndarray,
    num_rows: int,
    num_shards: int = 1,
    min_width: int = 8,
    workspace_elems: int = 1 << 27,
    features: int = 50,
    stable_shapes: bool = True,
) -> list[NeighborBucket]:
    """Single-process composite-key pack (the original path).

    Rows with no ratings appear in no bucket (their factors stay zero,
    matching the rectangle path where an all-masked row solves to the
    zero vector). One stable sort by (bucket width, row) makes every
    bucket a contiguous slice of the sorted arrays; the stable sort also
    preserves arrival order within each row. Kept verbatim as the
    equivalence oracle for the sharded engine."""
    row_idx = np.asarray(row_idx)
    col_idx = np.asarray(col_idx)
    values = np.asarray(values)
    nnz = len(row_idx)
    if not num_rows or not nnz:
        return []
    counts = np.bincount(row_idx, minlength=num_rows)
    widths = row_widths(counts, min_width)

    wcode = np.log2(widths).astype(np.int64)  # [num_rows], values < 40
    key = (wcode[row_idx] << 40) | row_idx.astype(np.int64)
    order = np.argsort(key, kind="stable")
    del key
    r = row_idx[order]
    c = col_idx[order]
    v = values[order]
    del order

    # row-run boundaries in sorted order -> per-entry position within row
    bounds = np.flatnonzero(np.r_[True, r[1:] != r[:-1]]).astype(np.int64)
    row_start = np.zeros(nnz, dtype=np.int64)
    row_start[bounds] = bounds
    np.maximum.accumulate(row_start, out=row_start)
    pos = (np.arange(nnz, dtype=np.int64) - row_start).astype(np.int32)
    del row_start

    # bucket slice boundaries: wcode is non-decreasing along the sort
    codes_present = np.unique(wcode[r[bounds]])
    code_of_bound = wcode[r[bounds]]
    buckets: list[NeighborBucket] = []
    for code in codes_present.tolist():
        w = 1 << int(code)
        b_lo, b_hi = np.searchsorted(code_of_bound, [code, code + 1])
        first_bounds = bounds[b_lo:b_hi]  # entry offset of each row's run
        lo = int(first_bounds[0])
        hi = int(bounds[b_hi]) if b_hi < len(bounds) else nnz
        rows_w = r[first_bounds].astype(np.int32)
        counts_w = np.diff(np.r_[first_bounds, hi]).astype(np.int32)
        n, chunk = bucket_geometry(
            len(rows_w), w, num_shards, workspace_elems, features, stable_shapes
        )
        rows = np.full(n, -1, dtype=np.int32)
        rows[: len(rows_w)] = rows_w
        deg = np.zeros(n, dtype=np.int32)
        deg[: len(rows_w)] = counts_w
        # slot index per entry: which row-run of this bucket it belongs to
        slot = np.repeat(
            np.arange(len(rows_w), dtype=np.int64), counts_w.astype(np.int64)
        )
        flat = slot * w + pos[lo:hi]
        del slot
        idx = np.zeros(n * w, dtype=np.int32)
        idx[flat] = c[lo:hi]
        val = np.zeros(n * w, dtype=np.float32)
        val[flat] = v[lo:hi]
        del flat
        buckets.append(
            NeighborBucket(rows, idx.reshape(n, w), val.reshape(n, w), deg, chunk)
        )
    return buckets


# ---------------------------------------------------------------------------
# Sharded engine
# ---------------------------------------------------------------------------


# Segments whose close() failed because numpy views still referenced the
# buffer when their arena was collected (gc order within a cycle is
# arbitrary). Holding them here silences SharedMemory.__del__ (which
# would re-raise the BufferError as an unraisable warning); the next pack
# call — or interpreter exit — sweeps them once the views are gone. The
# names are already unlinked, so at worst the mapping lives until exit.
_pending_close: list[shared_memory.SharedMemory] = []


def _sweep_pending_segments():
    still = []
    for shm in _pending_close:
        try:
            shm.close()
        except (BufferError, OSError):
            still.append(shm)
    _pending_close[:] = still


import atexit  # noqa: E402

atexit.register(_sweep_pending_segments)


class _ShmArena:
    """Owns the shared-memory segments backing one pack's bucket arrays.

    The segments are unlinked as soon as the workers have joined (the
    name disappears from /dev/shm; the parent's mapping — and therefore
    every bucket view — stays valid), and closed when the arena is
    garbage collected. Buckets keep a reference to their arena, so the
    mapping lives exactly as long as the buckets built from it; a segment
    whose views are still live at that point (collection order is not
    ours to pick) parks in ``_pending_close`` for the next sweep."""

    def __init__(self, segments: list[shared_memory.SharedMemory]):
        self._segments = segments
        self._finalizer = weakref.finalize(self, _ShmArena._close_all, segments)

    @staticmethod
    def _close_all(segments):
        for shm in segments:
            try:
                shm.close()
            except (BufferError, OSError):
                _pending_close.append(shm)

    def unlink(self):
        for shm in self._segments:
            with contextlib.suppress(FileNotFoundError):
                shm.unlink()


def _streamed_counts(row_idx, num_rows, chunk_rows):
    counts = np.zeros(num_rows, dtype=np.int64)
    for a in range(0, len(row_idx), chunk_rows):
        counts += np.bincount(row_idx[a : a + chunk_rows], minlength=num_rows)
    return counts


def _plan(row_idx, num_rows, num_shards, min_width, workspace_elems, features,
          stable_shapes, chunk_rows):
    """Row-level plan: per-row destination bases plus per-bucket geometry.

    Everything here is O(num_rows) (plus one streamed counting pass over
    the entries) and runs in the parent; workers only ever touch
    entry-level work."""
    counts = _streamed_counts(row_idx, num_rows, chunk_rows)
    widths = row_widths(counts, min_width)
    wcode = np.log2(widths).astype(np.int64)
    nz_rows = np.flatnonzero(counts > 0).astype(np.int64)
    codes = np.unique(wcode[nz_rows])
    cidx = np.searchsorted(codes, wcode).astype(np.uint8)  # [num_rows]
    # slot of a row = its rank among same-code rows, row-ascending —
    # exactly the order the reference path's (code, row) sort yields
    order_c = np.argsort(cidx[nz_rows], kind="stable")
    rows_per_code = np.bincount(cidx[nz_rows], minlength=len(codes)).astype(np.int64)
    starts = np.concatenate([[0], np.cumsum(rows_per_code)[:-1]])
    slot = np.full(num_rows, -1, dtype=np.int64)
    slot[nz_rows[order_c]] = np.arange(len(nz_rows), dtype=np.int64) - np.repeat(
        starts, rows_per_code
    )
    del order_c

    geos = [
        bucket_geometry(
            int(rows_per_code[ci]), 1 << int(code), num_shards,
            workspace_elems, features, stable_shapes,
        )
        for ci, code in enumerate(codes.tolist())
    ]
    elems = np.array(
        [n * (1 << int(code)) for (n, _), code in zip(geos, codes.tolist())],
        dtype=np.int64,
    )
    bases = np.concatenate([[0], np.cumsum(elems)[:-1]]).astype(np.int64)
    # flat arena destination of each row's first entry; entry j of the
    # row lands at dest0[row] + j
    dest0 = bases[cidx] + slot * widths
    return counts, cidx, nz_rows, codes, slot, geos, bases, int(elems.sum()), dest0


def _shard_bounds(counts, workers):
    """Contiguous row ranges balanced by entry count (prefix-sum cuts)."""
    num_rows = len(counts)
    if workers <= 1 or num_rows <= 1:
        return np.array([0, num_rows], dtype=np.int64)
    csum = np.cumsum(counts)
    total = int(csum[-1])
    targets = (np.arange(1, workers, dtype=np.int64) * total) // workers
    cuts = np.searchsorted(csum, targets, side="left") + 1
    return np.unique(np.concatenate([[0], cuts, [num_rows]])).astype(np.int64)


def _pack_range(
    row_idx, col_idx, values, lo, hi, dest0, idx_flat, val_flat, chunk_rows,
    select, stats,
):
    """Pack every entry whose row falls in [lo, hi) into the arena.

    Entry-level core shared by the in-process and worker paths. Sorts the
    range's entries by row with radix-friendly 16-bit keys (global block
    id, then row-within-block), computes per-entry arrival positions from
    the row runs, and scatters column ids / values to
    ``dest0[row] + position`` in one flat pass. ``select=False`` skips
    the membership scan when the range covers every row."""
    nnz = len(row_idx)
    t0 = time.perf_counter()
    if select:
        parts = []
        for a in range(0, nnz, chunk_rows):
            r = row_idx[a : a + chunk_rows]
            m = (r >= lo) & (r < hi)
            parts.append((np.flatnonzero(m) + a).astype(np.int64))
        sel64 = np.concatenate(parts) if parts else np.empty(0, np.int64)
        del parts
        sel = sel64.astype(np.int32) if nnz < 2**31 else sel64
        del sel64
        if not len(sel):
            stats += [time.perf_counter() - t0, 0.0, 0.0, 0.0]
            return
        loc = row_idx[sel]
        t1 = time.perf_counter()
        hi16 = (loc >> _BLOCK_BITS).astype(np.uint16)
        order1 = np.argsort(hi16, kind="stable")
        del hi16
        sel = sel[order1]
        loc = loc[order1]
        del order1
    else:
        t1 = t0
        hi16 = (row_idx >> _BLOCK_BITS).astype(np.uint16)
        order1 = np.argsort(hi16, kind="stable")
        del hi16
        sel = order1.astype(np.int32) if nnz < 2**31 else order1
        del order1
        loc = row_idx[sel]
    m = len(sel)
    # refine within each 65536-row block: keys fit uint16 -> radix
    first_block = int(loc[0]) >> _BLOCK_BITS
    last_block = int(loc[-1]) >> _BLOCK_BITS
    if last_block > first_block:
        marks = np.arange(first_block + 1, last_block + 1, dtype=np.int64) << _BLOCK_BITS
        edges = np.searchsorted(loc, marks)
        edges = np.concatenate([[0], edges, [m]])
    else:
        edges = np.array([0, m], dtype=np.int64)
    for b in range(len(edges) - 1):
        s0, s1 = int(edges[b]), int(edges[b + 1])
        if s1 - s0 <= 1:
            continue
        low = (loc[s0:s1] & (_BLOCK - 1)).astype(np.uint16)
        o2 = np.argsort(low, kind="stable")
        del low
        sel[s0:s1] = sel[s0:s1][o2]
        loc[s0:s1] = loc[s0:s1][o2]
        del o2
    t2 = time.perf_counter()

    # per-entry arrival position within its row, from run boundaries
    bnd = np.flatnonzero(np.r_[True, loc[1:] != loc[:-1]])
    run_start = np.zeros(m, dtype=np.int64 if m >= 2**31 else np.int32)
    run_start[bnd] = bnd.astype(run_start.dtype)
    np.maximum.accumulate(run_start, out=run_start)
    del bnd
    dest = dest0[loc]
    dest += np.arange(m, dtype=np.int64)
    dest -= run_start.astype(np.int64)
    del run_start, loc
    t3 = time.perf_counter()

    idx_flat[dest] = col_idx[sel]
    val_flat[dest] = values[sel]
    del dest, sel
    t4 = time.perf_counter()
    stats += [t1 - t0, t2 - t1, t3 - t2, t4 - t3]


def _worker_main(shard, lo, hi, row_idx, col_idx, values, dest0, idx_flat,
                 val_flat, chunk_rows, stats_arr):
    """Worker process entry point (fork: all array args are inherited
    copy-on-write; idx/val/stats views are shared mappings)."""
    stats: list[float] = []
    _pack_range(
        row_idx, col_idx, values, lo, hi, dest0, idx_flat, val_flat,
        chunk_rows, True, stats,
    )
    stats_arr[shard, : len(stats)] = stats


def _assemble(codes, geos, bases, counts, cidx, nz_rows, slot, idx_flat,
              val_flat, arena):
    buckets = []
    for ci in range(len(codes)):
        n, chunk = geos[ci]
        w = 1 << int(codes[ci])
        rows_c = np.full(n, -1, dtype=np.int32)
        deg_c = np.zeros(n, dtype=np.int32)
        rc = nz_rows[cidx[nz_rows] == ci]
        s = slot[rc]
        rows_c[s] = rc.astype(np.int32)
        deg_c[s] = counts[rc].astype(np.int32)
        b0 = int(bases[ci])
        bucket = NeighborBucket(
            rows_c,
            idx_flat[b0 : b0 + n * w].reshape(n, w),
            val_flat[b0 : b0 + n * w].reshape(n, w),
            deg_c,
            chunk,
        )
        if arena is not None:
            # keep the shared mapping alive exactly as long as its views
            bucket._arena = arena  # type: ignore[attr-defined]
        buckets.append(bucket)
    return buckets


def pack_neighbor_buckets(
    row_idx: np.ndarray,
    col_idx: np.ndarray,
    values: np.ndarray,
    num_rows: int,
    num_shards: int = 1,
    min_width: int = 8,
    workspace_elems: int = 1 << 27,
    features: int = 50,
    stable_shapes: bool = True,
    options: Optional[PackingOptions] = None,
) -> list[NeighborBucket]:
    """Sharded packing engine; bit-identical to the reference path.

    Resolves the worker count from ``options`` (in-process below
    ``_MIN_PARALLEL_NNZ`` entries or on one core), packs each contiguous
    row range into a preallocated flat arena, and assembles buckets as
    zero-copy views. See the module docstring for the layout/determinism
    contract and ``last_pack_stats`` for per-phase wall seconds."""
    row_idx = np.asarray(row_idx)
    col_idx = np.asarray(col_idx)
    values = np.asarray(values)
    nnz = len(row_idx)
    last_pack_stats.clear()
    _sweep_pending_segments()
    if not num_rows or not nnz:
        return []
    opts = options or PackingOptions()
    workers = opts.resolve_workers(nnz, num_rows)

    t0 = time.perf_counter()
    counts, cidx, nz_rows, codes, slot, geos, bases, total_elems, dest0 = _plan(
        row_idx, num_rows, num_shards, min_width, workspace_elems, features,
        stable_shapes, opts.chunk_rows,
    )
    t_plan = time.perf_counter() - t0

    arena_bytes = total_elems * 8  # int32 idx + float32 val
    if workers > 1 and arena_bytes > opts.shm_budget_mb * (1 << 20):
        logger.warning(
            "packing arena (%.0f MB) exceeds oryx.ml.als.packing shared-mem "
            "budget (%d MB); falling back to in-process packing",
            arena_bytes / (1 << 20), opts.shm_budget_mb,
        )
        workers = 1

    arena = None
    t0 = time.perf_counter()
    if workers > 1:
        try:
            seg_idx = shared_memory.SharedMemory(create=True, size=max(1, total_elems * 4))
            seg_val = shared_memory.SharedMemory(create=True, size=max(1, total_elems * 4))
            seg_stats = shared_memory.SharedMemory(create=True, size=max(1, workers * 4 * 8))
        except OSError as e:
            logger.warning(
                "shared-memory allocation failed (%s); falling back to "
                "in-process packing", e,
            )
            workers = 1
        else:
            arena = _ShmArena([seg_idx, seg_val, seg_stats])
            idx_flat = np.frombuffer(seg_idx.buf, dtype=np.int32, count=total_elems)
            val_flat = np.frombuffer(seg_val.buf, dtype=np.float32, count=total_elems)
            stats_arr = np.frombuffer(seg_stats.buf, dtype=np.float64).reshape(workers, 4)
    if workers == 1:
        idx_flat = np.zeros(total_elems, dtype=np.int32)
        val_flat = np.zeros(total_elems, dtype=np.float32)
        stats_arr = np.zeros((1, 4), dtype=np.float64)
    t_alloc = time.perf_counter() - t0

    t0 = time.perf_counter()
    if workers == 1:
        stats: list[float] = []
        _pack_range(
            row_idx, col_idx, values, 0, num_rows, dest0, idx_flat, val_flat,
            opts.chunk_rows, False, stats,
        )
        stats_arr[0, : len(stats)] = stats
    else:
        bounds = _shard_bounds(counts, workers)
        ctx = get_context("fork")
        procs = []
        for s in range(len(bounds) - 1):
            p = ctx.Process(
                target=_worker_main,
                args=(
                    s, int(bounds[s]), int(bounds[s + 1]), row_idx, col_idx,
                    values, dest0, idx_flat, val_flat, opts.chunk_rows,
                    stats_arr,
                ),
                daemon=True,
            )
            p.start()
            procs.append(p)
        deadline = time.monotonic() + opts.worker_timeout_sec
        failed = None
        try:
            pending = list(enumerate(procs))
            while pending and failed is None:
                still = []
                for s, p in pending:
                    p.join(timeout=0.05)
                    if p.exitcode is None:
                        still.append((s, p))
                    elif p.exitcode != 0:
                        failed = (s, p.exitcode)
                        break
                pending = still
                if pending and time.monotonic() > deadline:
                    failed = (pending[0][0], "timeout")
                    break
        finally:
            if failed is not None:
                for p in procs:
                    if p.exitcode is None:
                        p.terminate()
            for p in procs:
                p.join(timeout=5.0)
        if arena is not None:
            arena.unlink()
        if failed is not None:
            s, what = failed
            lo, hi = int(bounds[s]), int(bounds[s + 1])
            raise RuntimeError(
                f"packing worker {s} (rows [{lo}, {hi})) "
                + (
                    "timed out"
                    if what == "timeout"
                    else f"exited with code {what}"
                )
                + "; all workers terminated"
            )
    t_pack = time.perf_counter() - t0

    t0 = time.perf_counter()
    buckets = _assemble(
        codes, geos, bases, counts, cidx, nz_rows, slot, idx_flat, val_flat,
        arena,
    )
    t_fill = time.perf_counter() - t0

    sel_s, sort_s, pos_s, scat_s = (float(x) for x in stats_arr.sum(axis=0))
    last_pack_stats.update(
        workers=float(workers),
        plan=t_plan,
        alloc=t_alloc,
        select=sel_s,
        sort=sort_s,
        position=pos_s,
        scatter=scat_s,
        pack_wall=t_pack,
        fill=t_fill,
        total=t_plan + t_alloc + t_pack + t_fill,
    )
    return buckets
