"""Random-forest training on TPU: histogram-based level-wise growth.

The TPU-native replacement for Spark MLlib's RandomForest.trainClassifier/
trainRegressor used by the reference's RDFUpdate (app/oryx-app-mllib/...
/rdf/RDFUpdate.java:143-165). Decision-tree induction is branchy and
pointer-chasing in its classic form; the TPU formulation (XGBoost-style,
SURVEY.md §7 step 5) grows all nodes of one depth at once:

- inputs are pre-binned feature matrices ([n, p] small-int bin ids, the
  binning/bin-edge mapping lives in the app tier),
- one level = ONE fused pass producing the full [p, nodes, bins, stats]
  histogram tensor, from which cumulative sums over bins give every
  candidate split's left/right statistics, impurity gains are evaluated
  for all (node, feature, bin) candidates simultaneously, and argmax
  picks each node's split,
- per-node feature subsampling (mtry) is a random mask over the gain
  tensor, bootstrap resampling is Poisson(1) example weights,
- trees come out as flat heap arrays (node i's children at 2i+1/2i+2)
  that the app tier converts to portable DecisionTree objects.

Histogram formulations (docs/batch-trainers.md):

- **matmul** — one dense contraction ``A.T @ onehot(bins)`` with
  ``A[n, L*S] = onehot(node) ⊗ (w * chan)``: all features × nodes × bins
  batched through the MXU. Used when the level's FLOP/one-hot footprint
  fits a budget (shallow levels, where nodes are few).
- **scalar** — classification folds the class channel INTO the segment
  id (``seg = (node*B + bin)*C + class``) so the scatter moves one
  scalar weight per (row, feature) instead of a C-wide stat vector.
- **reference** — the original per-feature vector segment-sum scan,
  kept as the equivalence baseline for tests.

All formulations produce the same [p, L, B, S] tensor and stay
psum-compatible under the existing shard_map: each device computes local
histograms and a single psum produces the global ones; split selection is
then replicated math and example routing stays local.

On the CPU backend with no mesh, ``train_forest`` takes a host fast path:
per-(tree, level) ``np.bincount`` histograms (5-10x the throughput of
XLA:CPU scatter) over only the **live** nodes of the level — children of
the previous level's splits — with the split selection running through
the same jitted gain kernel the device path uses, so both paths pick
identical splits. Stats channels: per-class weighted counts for
classification, (w, w*y, w*y^2) for regression.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


# cap on one tree chunk's per-tree device-resident rows (weights+routing)
_TREE_CHUNK_BUDGET_BYTES = 1 << 30

# dense-matmul histogram budget: FLOPs of the one contraction, and
# elements of the materialized one-hots (the [n, p*B] bin one-hot is the
# big one). Above either bound the scalar/vector segment path takes over.
_MM_FLOP_BUDGET = float(1 << 32)
_MM_ELEM_BUDGET = float(1 << 28)

# wall seconds of the most recent train_forest call, split by phase
# ({"init": s, "iterate": s}); read by tools/train_benchmark.py for
# bench.py's per-phase rows. Overwritten per call, never merged.
last_phase_seconds: dict[str, float] = {}


@dataclass
class ForestArrays:
    """Flat heap-layout forest. -1 split_feature = leaf."""

    split_feature: np.ndarray  # [T, max_nodes] int32
    split_bin: np.ndarray  # [T, max_nodes] int32 (negative branch: bin <= split_bin)
    node_stats: np.ndarray  # [T, max_nodes, S] per-node class counts / (w, wy, wyy)
    node_counts: np.ndarray  # [T, max_nodes] weighted example counts
    gains: np.ndarray  # [T, max_nodes] impurity decrease of each split
    num_classes: int | None  # None = regression

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]


def _impurity(stats: jnp.ndarray, total: jnp.ndarray, kind: str) -> jnp.ndarray:
    """stats [..., S], total [...] -> impurity [...]."""
    if kind == "variance":
        w, wy, wyy = stats[..., 0], stats[..., 1], stats[..., 2]
        mean = wy / jnp.maximum(w, 1e-12)
        return jnp.maximum(wyy / jnp.maximum(w, 1e-12) - mean * mean, 0.0)
    p = stats / jnp.maximum(total[..., None], 1e-12)
    if kind == "gini":
        return 1.0 - jnp.sum(p * p, axis=-1)
    # entropy in nats (reference: min-info-gain-nats)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), axis=-1)


def _level_histograms(
    binned,  # [n, p] int32
    w_act,  # [n] float32 example weights, 0 for inactive rows
    y_cls,  # [n] int32 class ids (zeros for regression)
    chan,  # [n, S] float32 per-example stat basis (onehot(y) / (1, y, y^2))
    pos_c,  # [n] int32 clamped node position within the level
    num_level_nodes: int,
    num_bins: int,
    impurity: str,
    hist_mode: str,
):
    """All (feature, node, bin, stat) sums for one level: [p, L, B, S]."""
    n, p = binned.shape
    s = chan.shape[1]
    L, B = num_level_nodes, num_bins

    if hist_mode != "reference":
        mm_flops = 2.0 * n * L * s * p * B
        mm_elems = float(n) * (p * B + L * s)
        if hist_mode == "matmul" or (
            hist_mode == "auto"
            and mm_flops <= _MM_FLOP_BUDGET
            and mm_elems <= _MM_ELEM_BUDGET
        ):
            # ONE dense contraction on the MXU: A[n, L*S] carries each
            # row's weighted stat channels at its node's slot, the bin
            # one-hot [n, p*B] carries its bin slot per feature, and
            # A.T @ onehot yields every (node, stat, feature, bin) sum.
            nh = jax.nn.one_hot(pos_c, L, dtype=jnp.float32) * w_act[:, None]
            a = (nh[:, :, None] * chan[:, None, :]).reshape(n, L * s)
            ohb = jax.nn.one_hot(binned, B, dtype=jnp.float32).reshape(n, p * B)
            h = jnp.dot(a.T, ohb, preferred_element_type=jnp.float32)
            return h.reshape(L, s, p, B).transpose(2, 0, 3, 1)  # [p, L, B, S]
        if impurity != "variance":
            # classification: fold the class channel into the segment id
            # so each (row, feature) scatters ONE scalar, not an S-vector
            base = (pos_c * B) * s + y_cls

            def hist_scalar(carry, f):
                seg = base + binned[:, f] * s
                h = jax.ops.segment_sum(w_act, seg, num_segments=L * B * s)
                return carry, h.reshape(L, B, s)

            _, hists = jax.lax.scan(hist_scalar, 0, jnp.arange(p))
            return hists

    w_stats = chan * w_act[:, None]  # [n, S]

    def hist_vector(carry, f):
        seg = pos_c * B + binned[:, f]
        h = jax.ops.segment_sum(w_stats, seg, num_segments=L * B)
        return carry, h.reshape(L, B, s)

    _, hists = jax.lax.scan(hist_vector, 0, jnp.arange(p))
    return hists


def _candidate_gains(
    hists,  # [p, L, B, S] histograms; B may be trimmed below num_bins_total
    node_tot,  # [L, S] per-node totals (shared across feature groups)
    impurity: str,
    min_node_size,  # float32
    num_bins_total: int,  # GLOBAL bin count: candidate bin num_bins-1 is
    # "everything left" and never a real split, even when B is trimmed
):
    """Impurity gain of every (feature, node, bin) candidate: [p, L, B],
    -inf where the candidate is invalid (child below min_node_size, or
    the all-left last bin)."""
    num_bins = hists.shape[2]

    # weighted example count: regression carries it in channel 0; for
    # classification it is the sum of the per-class channels
    def _count(stats):
        if impurity == "variance":
            return stats[..., 0]
        return stats.sum(axis=-1)

    left = jnp.cumsum(hists, axis=2)  # [p, L, B, S] stats for bin <= b
    right = node_tot[None, :, None, :] - left
    tot_cnt = _count(node_tot)  # [L]
    l_cnt = _count(left)
    r_cnt = _count(right)

    parent_imp = _impurity(node_tot, tot_cnt, impurity)  # [L]
    l_imp = _impurity(left, l_cnt, impurity)
    r_imp = _impurity(right, r_cnt, impurity)
    tot_safe = jnp.maximum(tot_cnt, 1e-12)
    gain = parent_imp[None, :, None] - (l_cnt * l_imp + r_cnt * r_imp) / tot_safe[None, :, None]

    valid = (l_cnt >= min_node_size) & (r_cnt >= min_node_size)
    # last candidate bin (B-1) sends everything left: never a real split
    valid = valid & (jnp.arange(num_bins)[None, None, :] < num_bins_total - 1)
    return jnp.where(valid, gain, -jnp.inf)


def _best_of(g):
    """argmax over the (feature, bin) candidate axes: g [p, L, B] ->
    (flat index [L], gain [L]); flat = f_local * B + bin."""
    p, num_level_nodes, num_bins = g.shape
    flat = g.transpose(1, 0, 2).reshape(num_level_nodes, p * num_bins)
    best = jnp.argmax(flat, axis=1)
    return best, jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]


def _level_splits_from_hists(
    hists,  # [p, L, B, S] histogram tensor (already psum'd if sharded)
    feat_mask,  # [L, p] float32 1/0 mtry mask
    allowed_mask,  # [p] float32 1/0: features splits may EVER use
    impurity: str,
    min_node_size,  # float32
    min_info_gain,  # float32
    is_last_level: bool,
):
    """Split selection for one level from its histograms: returns
    (split_feature [L], split_bin [L], gain [L], node_tot [L, S])."""
    p, num_level_nodes, num_bins, _s = hists.shape
    node_tot = hists[0].sum(axis=1)  # [L, S] (same for every feature)

    gain = _candidate_gains(hists, node_tot, impurity, min_node_size, num_bins)
    # excluded features (id/ignored/target columns) are out of bounds for
    # the mtry-widening fallback too, not just for the sampled mask
    gain_all = jnp.where(allowed_mask[:, None, None] > 0, gain, -jnp.inf)
    gain_masked = jnp.where(feat_mask.T[:, :, None] > 0, gain_all, -jnp.inf)

    # prefer the mtry-sampled features; when none of them admits a valid
    # split, keep looking among all features (sklearn max_features
    # semantics: the search widens until a valid partition is found)
    best_m, gain_m = _best_of(gain_masked)
    best_a, gain_a = _best_of(gain_all)
    use_masked = gain_m > min_info_gain
    best = jnp.where(use_masked, best_m, best_a)
    best_gain = jnp.where(use_masked, gain_m, gain_a)
    best_feat = (best // num_bins).astype(jnp.int32)
    best_bin = (best % num_bins).astype(jnp.int32)

    do_split = (best_gain > min_info_gain) & jnp.isfinite(best_gain)
    if is_last_level:
        do_split = jnp.zeros_like(do_split)
    split_feature = jnp.where(do_split, best_feat, -1)
    split_bin = jnp.where(do_split, best_bin, -1)
    return split_feature, split_bin, jnp.where(do_split, best_gain, 0.0), node_tot


def _grow_level_impl(
    binned,  # [n, p] int32 (local rows under shard_map)
    y_cls,  # [n] int32 class ids (zeros for regression)
    chan,  # [n, S] float32 per-example stat basis (shared by every tree)
    w_ex,  # [n] float32 per-tree example weights
    node_of,  # [n] int32 heap index or -1 (inactive)
    feat_mask,  # [L, p] float32 1/0 mtry mask for this level
    allowed_mask,  # [p] float32 1/0: features splits may EVER use
    level_start: int,  # heap index of first node at this depth (2^d - 1)
    num_level_nodes: int,  # L = 2^d
    num_bins: int,  # B
    impurity: str,
    min_node_size,  # float32
    min_info_gain,  # float32
    is_last_level: bool,
    hist_mode: str = "auto",
    axis_name: str | None = None,  # psum histograms over this mesh axis
):
    """Returns (split_feature [L], split_bin [L], gain [L], node_tot [L,S],
    new_node_of [n])."""
    n, p = binned.shape
    pos = node_of - level_start  # position within level; <0 or >=L = inactive
    active = (pos >= 0) & (pos < num_level_nodes)
    pos_c = jnp.where(active, pos, 0)
    w_act = jnp.where(active, w_ex, 0.0)

    hists = _level_histograms(
        binned, w_act, y_cls, chan, pos_c, num_level_nodes, num_bins,
        impurity, hist_mode,
    )
    if axis_name is not None:
        # rows are sharded over the mesh: local histograms psum into the
        # global ones; everything after this line is replicated math
        hists = jax.lax.psum(hists, axis_name)

    split_feature, split_bin, gains, node_tot = _level_splits_from_hists(
        hists, feat_mask, allowed_mask, impurity,
        min_node_size, min_info_gain, is_last_level,
    )
    do_split = split_feature >= 0

    # route examples: children heap indices; leaves freeze at -1
    node_heap = pos_c + level_start
    ex_feat = split_feature[pos_c]
    ex_bin = split_bin[pos_c]
    ex_split = do_split[pos_c] & active
    goes_pos = binned[jnp.arange(n), jnp.maximum(ex_feat, 0)] > ex_bin
    child = 2 * node_heap + 1 + goes_pos.astype(jnp.int32)
    new_node_of = jnp.where(ex_split, child, jnp.where(active, -node_heap - 2, node_of))
    # inactive-but-was-active encode as -(heap+2) so final leaf is recoverable
    return split_feature, split_bin, gains, node_tot, new_node_of


def _grow_level_trees_impl(
    binned,  # [n, p] int32 (shared by every tree)
    y_cls,  # [n] int32 (shared)
    chan,  # [n, S] float32 (shared)
    w_t,  # [T, n] per-tree example weights
    node_t,  # [T, n] per-tree heap index or -1
    mask_t,  # [T, L, p] per-tree mtry masks for this level
    allowed_mask,  # [p] float32, shared by every tree
    level_start: int,
    num_level_nodes: int,
    num_bins: int,
    impurity: str,
    min_node_size,
    min_info_gain,
    is_last_level: bool,
    hist_mode: str = "auto",
    axis_name: str | None = None,
):
    """Whole-forest level pass: lax.scan over the tree axis around the
    single-tree level kernel, so ALL trees advance one depth in ONE
    device dispatch (the per-(tree, level) dispatch grid — 20 trees x 11
    levels of ~round-trip latency each — dominated wall-clock on remote
    devices). The scan keeps peak histogram memory at one tree's
    [p, L, B, S] tensor; the [T, n] weights, [T, n] routing, and [T, L]
    split results are resident for the whole call — train_forest bounds
    T per call so they stay under a fixed budget."""

    def one_tree(carry, args):
        w, no, fm = args
        out = _grow_level_impl(
            binned, y_cls, chan, w, no, fm, allowed_mask, level_start,
            num_level_nodes, num_bins, impurity, min_node_size,
            min_info_gain, is_last_level, hist_mode, axis_name,
        )
        return carry, out

    _, outs = jax.lax.scan(one_tree, 0, (w_t, node_t, mask_t))
    return outs  # (sf [T,L], sb [T,L], gain [T,L], node_tot [T,L,S], node_of [T,n])


_grow_level_trees = functools.partial(jax.jit, static_argnums=(7, 8, 9, 10, 13, 14))(
    _grow_level_trees_impl
)


# jitted candidate scoring for the host-histogram fast path: the SAME
# gain kernel the device path runs, so both paths pick identical splits
# (host log/argmax would differ from XLA by ulps and flip near-tie
# candidates). Evaluated per feature GROUP — features of equal bin width
# share a trimmed [pg, L, width, S] tensor, so a mostly-binary feature
# set (e.g. one-hot categoricals next to a few 32-bin numerics) skips
# the ~75% of the dense candidate grid that is structurally empty.
@functools.partial(jax.jit, static_argnums=(5, 6))
def _eval_group_hists(hists, node_tot, feat_mask, allowed_mask, mins,
                      impurity, num_bins_total):
    gain = _candidate_gains(hists, node_tot, impurity, mins[0], num_bins_total)
    gain_all = jnp.where(allowed_mask[:, None, None] > 0, gain, -jnp.inf)
    gain_masked = jnp.where(feat_mask.T[:, :, None] > 0, gain_all, -jnp.inf)
    best_m, gain_m = _best_of(gain_masked)
    best_a, gain_a = _best_of(gain_all)
    return best_m, gain_m, best_a, gain_a


@functools.lru_cache(maxsize=8)
def _grow_level_trees_mesh(mesh, axis_name: str):
    """shard_map'd whole-forest level pass: rows sharded over ``axis_name``
    (tree axis replicated in layout, scanned in compute), histograms
    psum'd per tree inside the scan."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    rows = P(axis_name, None)
    row1 = P(axis_name)
    trow1 = P(None, axis_name)
    repl = P()

    def wrapped(binned, y_cls, chan, w_t, node_t, mask_t, allowed_mask,
                level_start, num_level_nodes, num_bins, impurity,
                min_node_size, min_info_gain, is_last_level, hist_mode):
        fn = functools.partial(
            _grow_level_trees_impl,
            level_start=level_start,
            num_level_nodes=num_level_nodes,
            num_bins=num_bins,
            impurity=impurity,
            min_node_size=min_node_size,
            min_info_gain=min_info_gain,
            is_last_level=is_last_level,
            hist_mode=hist_mode,
            axis_name=axis_name,
        )
        return shard_map(
            fn,
            mesh=mesh,
            in_specs=(rows, row1, rows, trow1, trow1, repl, repl),
            out_specs=(repl, repl, repl, repl, trow1),
        )(binned, y_cls, chan, w_t, node_t, mask_t, allowed_mask)

    return functools.partial(
        jax.jit, static_argnums=(7, 8, 9, 10, 13, 14)
    )(wrapped)


def _host_level_hists(
    binned_T,  # [p, n] int32 (row-major per feature)
    w,  # [n] float32 weights with inactive rows zeroed is NOT required:
    #   inactive rows are routed to the trash slot below instead
    y_cls,  # [n] int32 (classification) — class folded into the bin id
    ybase,  # (y, y*y) float arrays for regression, else None
    compact,  # [n] int64 live-node slot per row; == trash for dead rows
    num_slots: int,  # live slots incl. pow2 padding (trash slot excluded)
    num_bins: int,
    s: int,
) -> np.ndarray:
    """np.bincount histograms [p, num_slots, B, S] for one (tree, level).

    One weighted bincount per (feature, channel): 5-10x the throughput of
    an XLA:CPU scatter for the same sums, and exact for classification
    (integer Poisson weights accumulate exactly in float64)."""
    p = binned_T.shape[0]
    b = num_bins
    size = (num_slots + 1) * b  # +1 = trash slot for dead/frozen rows
    if ybase is None:
        base = compact * (b * s) + y_cls
        out = np.empty((p, num_slots + 1, b, s), np.float32)
        for f in range(p):
            seg = base + binned_T[f] * s
            out[f] = np.bincount(seg, weights=w, minlength=size * s).reshape(
                num_slots + 1, b, s
            )
    else:
        base = compact * b
        out = np.empty((p, num_slots + 1, b, s), np.float32)
        chans = (w, w * ybase[0], w * ybase[1])
        for f in range(p):
            seg = base + binned_T[f]
            for c in range(3):
                out[f, :, :, c] = np.bincount(
                    seg, weights=chans[c], minlength=size
                ).reshape(num_slots + 1, b)
    return out[:, :num_slots]


def _pow2_at_least(n: int) -> int:
    return 1 << max(0, int(n - 1).bit_length())


def train_forest(
    binned: np.ndarray,
    targets: np.ndarray,
    num_bins: int,
    num_classes: int | None,
    num_trees: int = 20,
    max_depth: int = 8,
    min_node_size: float = 1.0,
    min_info_gain: float = 0.0,
    impurity: str = "entropy",
    mtry: int | None = None,
    seed: int | None = None,
    exclude_features: set[int] | None = None,
    mesh=None,
    hist_mode: str = "auto",
    host_hist: bool | None = None,
) -> ForestArrays:
    """Train `num_trees` trees over pre-binned features. Columns in
    `exclude_features` (e.g. the target's predictor slot) are never
    sampled for splitting. With ``mesh``, example rows shard over the
    'data' axis and per-level histograms psum across devices.

    ``hist_mode`` picks the device histogram formulation: "auto" (dense
    one-hot matmul when the level fits the FLOP budget, else the scalar/
    vector segment path), "matmul", "scalar", or "reference" (the
    original per-feature vector scan, kept for equivalence tests).
    ``host_hist`` forces the host np.bincount fast path on or off;
    default None enables it on the CPU backend with no mesh. Both paths
    consume the identical RNG stream and run split selection through the
    same jitted gain kernel, so they grow identical forests."""
    import time as _time

    from oryx_tpu.common import rng as rng_mod

    t_init = _time.perf_counter()
    binned = np.asarray(binned, dtype=np.int32)
    n, p = binned.shape
    allowed = np.asarray(
        sorted(set(range(p)) - (exclude_features or set())), dtype=np.int64
    )
    if len(allowed) == 0:
        raise ValueError("no usable features")
    allowed_vec = np.zeros(p, dtype=np.float32)
    allowed_vec[allowed] = 1.0
    if num_classes is None:
        y = np.asarray(targets, dtype=np.float32)
        chan_base = np.stack([np.ones(n, np.float32), y, y * y], axis=1)
        y_cls = np.zeros(n, dtype=np.int32)
        imp_kind = "variance"
    else:
        y_cls = np.asarray(targets, dtype=np.int32)
        chan_base = np.eye(num_classes, dtype=np.float32)[y_cls]
        imp_kind = impurity
    s_chan = chan_base.shape[1]
    pa = len(allowed)
    if mtry is None:
        mtry = max(1, int(np.sqrt(pa)) if num_classes is not None else max(1, pa // 3))

    max_nodes = 2 ** (max_depth + 1) - 1
    gen = np.random.default_rng(rng_mod.next_seed() if seed is None else seed)

    t_feat = np.full((num_trees, max_nodes), -1, dtype=np.int32)
    t_bin = np.full((num_trees, max_nodes), -1, dtype=np.int32)
    t_stats = np.zeros((num_trees, max_nodes, s_chan), dtype=np.float64)
    t_counts = np.zeros((num_trees, max_nodes), dtype=np.float64)
    t_gains = np.zeros((num_trees, max_nodes), dtype=np.float64)

    if host_hist is None:
        host_hist = mesh is None and jax.default_backend() == "cpu"

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from oryx_tpu.parallel.mesh import DATA_AXIS, pad_to_multiple

        num_shards = int(np.prod(mesh.devices.shape))
        n_pad = pad_to_multiple(n, num_shards)
        if n_pad != n:  # pad rows arrive inactive (node_of = -1, weight 0)
            binned = np.concatenate([binned, np.zeros((n_pad - n, p), np.int32)])
            chan_base = np.concatenate(
                [chan_base, np.zeros((n_pad - n, s_chan), np.float32)]
            )
            y_cls = np.concatenate([y_cls, np.zeros(n_pad - n, np.int32)])
        rows_sh = NamedSharding(mesh, P(DATA_AXIS, None))
        row1_sh = NamedSharding(mesh, P(DATA_AXIS))
        trow1_sh = NamedSharding(mesh, P(None, DATA_AXIS))
        grow = _grow_level_trees_mesh(mesh, DATA_AXIS)
        binned_dev = jax.device_put(binned, rows_sh)
        y_dev = jax.device_put(y_cls, row1_sh)
        chan_dev = jax.device_put(chan_base, rows_sh)
    elif not host_hist:
        grow = _grow_level_trees
        binned_dev = jnp.asarray(binned)  # uploaded once, reused every level
        y_dev = jnp.asarray(y_cls)
        chan_dev = jnp.asarray(chan_base)

    n_rows = binned.shape[0]  # == n unless mesh-padded

    # Trees batch into chunks whose per-tree [tc, n_rows] weight/routing
    # tensors stay under a fixed budget. One chunk covers every packaged
    # config.
    budget = int(_TREE_CHUNK_BUDGET_BYTES)
    tc = max(1, min(num_trees, budget // max(1, n_rows * 8)))

    def chunk_weights(t0: int, t1: int) -> np.ndarray:
        # drawn per chunk (in order, so the sequence matches an up-front
        # [num_trees, n] draw) to keep peak weight memory chunk-bounded
        if num_trees > 1:
            w = gen.poisson(1.0, (t1 - t0, n)).astype(np.float32)
        else:
            w = np.ones((1, n), np.float32)
        if n_rows != n:  # pad rows arrive inactive (node_of = -1, weight 0)
            w = np.concatenate(
                [w, np.zeros((t1 - t0, n_rows - n), np.float32)], axis=1
            )
        return w

    def level_masks(t0: int, t1: int, num_level: int) -> np.ndarray:
        # per-node mtry masks, vectorized: one uniform key per allowed
        # column, smallest-m keys win — a uniform random m-subset per
        # node in one pass (the per-node gen.choice loop was ~0.5s of
        # host time for a 20-tree depth-10 training)
        m = min(mtry, pa)
        mask_t = np.zeros((t1 - t0, num_level, p), dtype=np.float32)
        if m >= pa:
            mask_t[:, :, allowed] = 1.0
        else:
            keys = gen.random((t1 - t0, num_level, pa), dtype=np.float32)
            pick = np.argpartition(keys, m, axis=2)[:, :, :m]
            np.put_along_axis(
                mask_t.reshape((t1 - t0) * num_level, p),
                allowed[pick].reshape((t1 - t0) * num_level, m),
                1.0,
                axis=1,
            )
        return mask_t

    t_iter = _time.perf_counter()
    if host_hist:
        _train_host_chunks(
            binned, y_cls, chan_base, num_classes, allowed_vec, num_bins,
            imp_kind, min_node_size, min_info_gain, max_depth, num_trees, tc,
            chunk_weights, level_masks,
            t_feat, t_bin, t_stats, t_counts, t_gains,
        )
        last_phase_seconds.clear()
        last_phase_seconds.update(
            init=t_iter - t_init, iterate=_time.perf_counter() - t_iter
        )
        return ForestArrays(t_feat, t_bin, t_stats, t_counts, t_gains, num_classes)

    # The chunk's whole forest advances one depth per dispatch (lax.scan
    # over trees inside the level kernel). The level loop syncs each
    # level's splits (one small [T, L] array) and exits as soon as no
    # node anywhere can still split — an all-leaf level is never
    # dispatched.
    for t0 in range(0, num_trees, tc):
        t1 = min(t0 + tc, num_trees)
        w_c = chunk_weights(t0, t1)
        node_c = np.where(w_c > 0, 0, -1).astype(np.int32)  # [tc, n_rows]
        if mesh is not None:
            w_dev = jax.device_put(w_c, trow1_sh)
            node_dev = jax.device_put(node_c, trow1_sh)
        else:
            w_dev = jnp.asarray(w_c)
            node_dev = jnp.asarray(node_c)
        level_out = []
        for depth in range(max_depth + 1):
            level_start = 2**depth - 1
            num_level = 2**depth
            mask_t = level_masks(t0, t1, num_level)
            sf, sb, gains, node_tot, node_dev = grow(
                binned_dev,
                y_dev,
                chan_dev,
                w_dev,
                node_dev,
                jnp.asarray(mask_t),
                allowed_vec,
                level_start,
                num_level,
                num_bins,
                imp_kind,
                np.float32(min_node_size),
                np.float32(min_info_gain),
                depth == max_depth,
                hist_mode,
            )
            for a in (sb, gains, node_tot):
                try:
                    a.copy_to_host_async()
                except AttributeError:  # pragma: no cover - older array types
                    pass
            level_out.append((level_start, num_level, sf, sb, gains, node_tot))
            # exact level-wise early exit: no split at this level means
            # every deeper level is all-leaf — don't dispatch it
            if np.all(np.asarray(sf) < 0):
                break
        for level_start, num_level, sf, sb, gains, node_tot in level_out:
            sl = slice(level_start, level_start + num_level)
            node_tot = np.asarray(node_tot)  # [tc, L, S]
            t_feat[t0:t1, sl] = np.asarray(sf)
            t_bin[t0:t1, sl] = np.asarray(sb)
            t_stats[t0:t1, sl] = node_tot
            t_counts[t0:t1, sl] = (
                node_tot[..., 0] if num_classes is None else node_tot.sum(axis=2)
            )
            t_gains[t0:t1, sl] = np.asarray(gains)
    last_phase_seconds.clear()
    last_phase_seconds.update(
        init=t_iter - t_init, iterate=_time.perf_counter() - t_iter
    )
    return ForestArrays(t_feat, t_bin, t_stats, t_counts, t_gains, num_classes)


def _train_host_chunks(
    binned, y_cls, chan_base, num_classes, allowed_vec, num_bins,
    imp_kind, min_node_size, min_info_gain, max_depth, num_trees, tc,
    chunk_weights, level_masks,
    t_feat, t_bin, t_stats, t_counts, t_gains,
):
    """Host fast-path level loop (CPU backend, no mesh): np.bincount
    histograms restricted to each tree's LIVE nodes — the children of the
    previous level's splits — with split selection through the same
    jitted gain kernel as the device path. Mirrors the device path's RNG
    consumption exactly (same weight/mask draw schedule, same chunk-wide
    level-loop exit), so both paths grow identical forests on a seed."""
    n, p = binned.shape
    s = chan_base.shape[1]
    if num_classes is None:
        ybase = (chan_base[:, 1].astype(np.float64), chan_base[:, 2].astype(np.float64))
        y64 = None
    else:
        ybase = None
        y64 = y_cls.astype(np.int64)
    mins = (np.float32(min_node_size), np.float32(min_info_gain))

    # group features by occupied bin width (rounded up to a power of two
    # to bound the jit shape set): binary/one-hot columns score over a
    # 2-bin candidate axis instead of the full num_bins one
    nb_f = binned.max(axis=0).astype(np.int64) + 1
    pow2 = 2 ** np.ceil(np.log2(np.maximum(nb_f, 2))).astype(np.int64)
    widths = np.minimum(pow2, num_bins)
    groups = []  # (width, feats ascending, [pg, n] binned.T slice)
    for width in sorted(set(widths.tolist())):
        feats = np.nonzero(widths == width)[0]
        groups.append((int(width), feats, np.ascontiguousarray(binned[:, feats].T)))
    # node totals come from feature 0's histogram (first slot of its group:
    # feats are ascending, so feature 0 is slot 0 when present)
    g0 = next(i for i, (wd, _, _) in enumerate(groups) if wd == widths[0])

    for t0 in range(0, num_trees, tc):
        t1 = min(t0 + tc, num_trees)
        w_c = chunk_weights(t0, t1)
        node_c = np.where(w_c > 0, 0, -1).astype(np.int32)  # [tc, n]
        w64 = w_c.astype(np.float64)
        # per-tree live-node heap positions for the CURRENT level
        alive = [np.array([0], dtype=np.int64) for _ in range(t1 - t0)]
        for depth in range(max_depth + 1):
            level_start = 2**depth - 1
            num_level = 2**depth
            mask_t = level_masks(t0, t1, num_level)
            any_split = False
            for ti in range(t1 - t0):
                alive_pos = alive[ti]
                la = len(alive_pos)
                if la == 0:
                    continue
                lp = _pow2_at_least(la)  # pad slots: bounded compile set
                inv = np.full(num_level, lp, dtype=np.int64)
                inv[alive_pos] = np.arange(la)
                pos = node_c[ti].astype(np.int64) - level_start
                in_level = (pos >= 0) & (pos < num_level)
                compact = np.where(in_level, inv[np.where(in_level, pos, 0)], lp)
                group_hists = [
                    _host_level_hists(bt, w64[ti], y64, ybase, compact, lp, wd, s)
                    for wd, _, bt in groups
                ]
                node_tot = group_hists[g0][0].sum(axis=1)  # [lp, S]
                # score each group's trimmed candidate grid on the shared
                # gain kernel, then merge: max gain wins, ties go to the
                # lowest (feature * num_bins + bin) flat index — exactly
                # the device kernel's single flat argmax
                cand = []  # (gain_m, flat_m, gain_a, flat_a) per group
                for (wd, feats, _), gh in zip(groups, group_hists):
                    fm = np.zeros((lp, len(feats)), np.float32)
                    fm[:la] = mask_t[ti, alive_pos][:, feats]
                    bm, gm, ba, ga = _eval_group_hists(
                        gh, node_tot, fm, allowed_vec[feats], mins,
                        imp_kind, num_bins,
                    )
                    bm, gm, ba, ga = (np.asarray(a) for a in (bm, gm, ba, ga))
                    flat_m = feats[bm // wd] * num_bins + bm % wd
                    flat_a = feats[ba // wd] * num_bins + ba % wd
                    cand.append((gm, flat_m, ga, flat_a))

                def _merge(gs, flats):
                    g = np.stack(gs)  # [G, lp]
                    f = np.stack(flats)
                    top = g.max(axis=0)
                    return top, np.where(g == top, f, np.iinfo(np.int64).max).min(axis=0)

                gain_m, flat_m = _merge([c[0] for c in cand], [c[1] for c in cand])
                gain_a, flat_a = _merge([c[2] for c in cand], [c[3] for c in cand])
                use_masked = gain_m > mins[1]
                best_gain = np.where(use_masked, gain_m, gain_a)
                best_flat = np.where(use_masked, flat_m, flat_a)
                do_split = (best_gain > mins[1]) & np.isfinite(best_gain)
                if depth == max_depth:  # device kernel's is_last_level
                    do_split[:] = False
                sf = np.where(do_split, best_flat // num_bins, -1).astype(np.int32)
                sb = np.where(do_split, best_flat % num_bins, -1).astype(np.int32)
                gains = np.where(do_split, best_gain, 0.0)
                heap = level_start + alive_pos
                t_feat[t0 + ti, heap] = sf[:la]
                t_bin[t0 + ti, heap] = sb[:la]
                t_stats[t0 + ti, heap] = node_tot[:la]
                t_counts[t0 + ti, heap] = (
                    node_tot[:la, 0] if num_classes is None else node_tot[:la].sum(axis=1)
                )
                t_gains[t0 + ti, heap] = gains[:la]
                # route rows: split rows descend, the rest freeze
                full_sf = np.full(num_level, -1, np.int32)
                full_sf[alive_pos] = sf[:la]
                full_sb = np.full(num_level, -1, np.int32)
                full_sb[alive_pos] = sb[:la]
                pos_c = np.where(in_level, pos, 0)
                ex_feat = full_sf[pos_c]
                ex_bin = full_sb[pos_c]
                ex_split = (ex_feat >= 0) & in_level
                node_heap = (pos_c + level_start).astype(np.int32)
                goes_pos = binned[np.arange(n), np.maximum(ex_feat, 0)] > ex_bin
                child = 2 * node_heap + 1 + goes_pos.astype(np.int32)
                node_c[ti] = np.where(
                    ex_split, child, np.where(in_level, -node_heap - 2, node_c[ti])
                )
                split_heap = heap[sf[:la] >= 0]
                if len(split_heap):
                    any_split = True
                    alive[ti] = np.sort(
                        np.concatenate([2 * split_heap + 1, 2 * split_heap + 2])
                    ) - (2 ** (depth + 1) - 1)
                else:
                    alive[ti] = np.empty(0, dtype=np.int64)
            if not any_split:
                break


def feature_importances(forest: ForestArrays, num_features: int) -> np.ndarray:
    """Total weighted impurity decrease per feature, normalized to max 1
    (DecisionForest feature-importance semantics)."""
    imp = np.zeros(num_features)
    feat = forest.split_feature
    weight = forest.node_counts * forest.gains
    for t in range(forest.num_trees):
        mask = feat[t] >= 0
        np.add.at(imp, feat[t][mask], weight[t][mask])
    m = imp.max()
    return imp / m if m > 0 else imp


def predict_forest_binned(forest: ForestArrays, binned: np.ndarray) -> np.ndarray:
    """Vectorized inference over the flat heap arrays (device-friendly):
    returns [n, C] summed class counts or [n, 2] (sum, count) pooled."""
    binned = jnp.asarray(binned, dtype=jnp.int32)
    sf = jnp.asarray(forest.split_feature)
    sb = jnp.asarray(forest.split_bin)
    stats = jnp.asarray(forest.node_stats, dtype=jnp.float32)
    max_depth = int(np.log2(forest.split_feature.shape[1] + 1)) - 1

    @jax.jit
    def run(x):
        n = x.shape[0]

        def one_tree(carry, ti):
            node = jnp.zeros(n, dtype=jnp.int32)

            def step(_, node_):
                f = sf[ti][node_]
                b = sb[ti][node_]
                is_split = f >= 0
                goes_pos = x[jnp.arange(n), jnp.maximum(f, 0)] > b
                child = 2 * node_ + 1 + goes_pos.astype(jnp.int32)
                return jnp.where(is_split, child, node_)

            node = jax.lax.fori_loop(0, max_depth + 1, step, node)
            return carry + stats[ti][node], None

        acc, _ = jax.lax.scan(one_tree, jnp.zeros((n, stats.shape[2])), jnp.arange(sf.shape[0]))
        return acc

    return np.asarray(run(binned))
