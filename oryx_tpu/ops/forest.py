"""Random-forest training on TPU: histogram-based level-wise growth.

The TPU-native replacement for Spark MLlib's RandomForest.trainClassifier/
trainRegressor used by the reference's RDFUpdate (app/oryx-app-mllib/...
/rdf/RDFUpdate.java:143-165). Decision-tree induction is branchy and
pointer-chasing in its classic form; the TPU formulation (XGBoost-style,
SURVEY.md §7 step 5) grows all nodes of one depth at once:

- inputs are pre-binned feature matrices ([n, p] small-int bin ids, the
  binning/bin-edge mapping lives in the app tier),
- one level = ONE fused pass: a lax.scan over features of segment-sum
  histograms [nodes*bins, stats], then cumulative sums over bins give
  every candidate split's left/right statistics, impurity gains are
  evaluated for all (node, feature, bin) candidates simultaneously, and
  argmax picks each node's split,
- per-node feature subsampling (mtry) is a random mask over the gain
  tensor, bootstrap resampling is Poisson(1) example weights,
- trees come out as flat heap arrays (node i's children at 2i+1/2i+2)
  that the app tier converts to portable DecisionTree objects.

Stats channels: per-class weighted counts for classification,
(w, w*y, w*y^2) for regression. With ``mesh=``, example rows shard over
the 'data' axis under shard_map: each device computes local histograms
and a single psum produces the global ones; split selection is then
replicated math and example routing stays local — the level pass is
still one fused program per device.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


# cap on one tree chunk's [tc, n, S] example-stats tensor (host + device)
_TREE_CHUNK_BUDGET_BYTES = 1 << 30


@dataclass
class ForestArrays:
    """Flat heap-layout forest. -1 split_feature = leaf."""

    split_feature: np.ndarray  # [T, max_nodes] int32
    split_bin: np.ndarray  # [T, max_nodes] int32 (negative branch: bin <= split_bin)
    node_stats: np.ndarray  # [T, max_nodes, S] per-node class counts / (w, wy, wyy)
    node_counts: np.ndarray  # [T, max_nodes] weighted example counts
    gains: np.ndarray  # [T, max_nodes] impurity decrease of each split
    num_classes: int | None  # None = regression

    @property
    def num_trees(self) -> int:
        return self.split_feature.shape[0]


def _impurity(stats: jnp.ndarray, total: jnp.ndarray, kind: str) -> jnp.ndarray:
    """stats [..., S], total [...] -> impurity [...]."""
    if kind == "variance":
        w, wy, wyy = stats[..., 0], stats[..., 1], stats[..., 2]
        mean = wy / jnp.maximum(w, 1e-12)
        return jnp.maximum(wyy / jnp.maximum(w, 1e-12) - mean * mean, 0.0)
    p = stats / jnp.maximum(total[..., None], 1e-12)
    if kind == "gini":
        return 1.0 - jnp.sum(p * p, axis=-1)
    # entropy in nats (reference: min-info-gain-nats)
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), axis=-1)


def _grow_level_impl(
    binned,  # [n, p] int32 (local rows under shard_map)
    stats_chan,  # [n, S] float32 per-example stat channels (w-weighted)
    node_of,  # [n] int32 heap index or -1 (inactive)
    feat_mask,  # [L, p] float32 1/0 mtry mask for this level
    allowed_mask,  # [p] float32 1/0: features splits may EVER use
    level_start: int,  # heap index of first node at this depth (2^d - 1)
    num_level_nodes: int,  # L = 2^d
    num_bins: int,  # B
    impurity: str,
    min_node_size,  # float32
    min_info_gain,  # float32
    is_last_level: bool,
    axis_name: str | None = None,  # psum histograms over this mesh axis
):
    """Returns (split_feature [L], split_bin [L], gain [L], node_tot [L,S],
    new_node_of [n])."""
    n, p = binned.shape
    s = stats_chan.shape[1]
    pos = node_of - level_start  # position within level; <0 or >=L = inactive
    active = (pos >= 0) & (pos < num_level_nodes)
    pos_c = jnp.where(active, pos, 0)
    w_stats = jnp.where(active[:, None], stats_chan, 0.0)

    def hist_one_feature(carry, f):
        seg = pos_c * num_bins + binned[:, f]
        h = jax.ops.segment_sum(w_stats, seg, num_segments=num_level_nodes * num_bins)
        return carry, h.reshape(num_level_nodes, num_bins, s)

    _, hists = jax.lax.scan(hist_one_feature, 0, jnp.arange(p))  # [p, L, B, S]
    if axis_name is not None:
        # rows are sharded over the mesh: local histograms psum into the
        # global ones; everything after this line is replicated math
        hists = jax.lax.psum(hists, axis_name)

    node_tot = hists[0].sum(axis=1)  # [L, S] (same for every feature)

    # weighted example count: regression carries it in channel 0; for
    # classification it is the sum of the per-class channels
    def _count(stats):
        if impurity == "variance":
            return stats[..., 0]
        return stats.sum(axis=-1)

    left = jnp.cumsum(hists, axis=2)  # [p, L, B, S] stats for bin <= b
    right = node_tot[None, :, None, :] - left
    tot_cnt = _count(node_tot)  # [L]
    l_cnt = _count(left)
    r_cnt = _count(right)

    parent_imp = _impurity(node_tot, tot_cnt, impurity)  # [L]
    l_imp = _impurity(left, l_cnt, impurity)
    r_imp = _impurity(right, r_cnt, impurity)
    tot_safe = jnp.maximum(tot_cnt, 1e-12)
    gain = parent_imp[None, :, None] - (l_cnt * l_imp + r_cnt * r_imp) / tot_safe[None, :, None]

    valid = (l_cnt >= min_node_size) & (r_cnt >= min_node_size)
    # last candidate bin (B-1) sends everything left: never a real split
    valid = valid & (jnp.arange(num_bins)[None, None, :] < num_bins - 1)
    # excluded features (id/ignored/target columns) are out of bounds for
    # the mtry-widening fallback too, not just for the sampled mask
    gain_all = jnp.where(valid, gain, -jnp.inf)
    gain_all = jnp.where(allowed_mask[:, None, None] > 0, gain_all, -jnp.inf)
    gain_masked = jnp.where(feat_mask.T[:, :, None] > 0, gain_all, -jnp.inf)

    def best_of(g):
        flat = g.transpose(1, 0, 2).reshape(num_level_nodes, p * num_bins)  # [L, p*B]
        best = jnp.argmax(flat, axis=1)
        return best, jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]

    # prefer the mtry-sampled features; when none of them admits a valid
    # split, keep looking among all features (sklearn max_features
    # semantics: the search widens until a valid partition is found)
    best_m, gain_m = best_of(gain_masked)
    best_a, gain_a = best_of(gain_all)
    use_masked = gain_m > min_info_gain
    best = jnp.where(use_masked, best_m, best_a)
    best_gain = jnp.where(use_masked, gain_m, gain_a)
    best_feat = (best // num_bins).astype(jnp.int32)
    best_bin = (best % num_bins).astype(jnp.int32)

    do_split = (best_gain > min_info_gain) & jnp.isfinite(best_gain)
    if is_last_level:
        do_split = jnp.zeros_like(do_split)
    split_feature = jnp.where(do_split, best_feat, -1)
    split_bin = jnp.where(do_split, best_bin, -1)

    # route examples: children heap indices; leaves freeze at -1
    node_heap = pos_c + level_start
    ex_feat = split_feature[pos_c]
    ex_bin = split_bin[pos_c]
    ex_split = do_split[pos_c] & active
    goes_pos = binned[jnp.arange(n), jnp.maximum(ex_feat, 0)] > ex_bin
    child = 2 * node_heap + 1 + goes_pos.astype(jnp.int32)
    new_node_of = jnp.where(ex_split, child, jnp.where(active, -node_heap - 2, node_of))
    # inactive-but-was-active encode as -(heap+2) so final leaf is recoverable
    return split_feature, split_bin, jnp.where(do_split, best_gain, 0.0), node_tot, new_node_of


def _grow_level_trees_impl(
    binned,  # [n, p] int32 (shared by every tree)
    stats_t,  # [T, n, S] per-tree weighted stat channels
    node_t,  # [T, n] per-tree heap index or -1
    mask_t,  # [T, L, p] per-tree mtry masks for this level
    allowed_mask,  # [p] float32, shared by every tree
    level_start: int,
    num_level_nodes: int,
    num_bins: int,
    impurity: str,
    min_node_size,
    min_info_gain,
    is_last_level: bool,
    axis_name: str | None = None,
):
    """Whole-forest level pass: lax.scan over the tree axis around the
    single-tree level kernel, so ALL trees advance one depth in ONE
    device dispatch (the per-(tree, level) dispatch grid — 20 trees x 11
    levels of ~round-trip latency each — dominated wall-clock on remote
    devices). The scan keeps peak histogram memory at one tree's
    [p, L, B, S] tensor; the [T, n, S] stats input, [T, n] routing, and
    [T, L] split results are resident for the whole call — train_forest
    bounds T per call so stats stay under a fixed budget."""

    def one_tree(carry, args):
        sc, no, fm = args
        out = _grow_level_impl(
            binned, sc, no, fm, allowed_mask, level_start, num_level_nodes,
            num_bins, impurity, min_node_size, min_info_gain, is_last_level,
            axis_name,
        )
        return carry, out

    _, outs = jax.lax.scan(one_tree, 0, (stats_t, node_t, mask_t))
    return outs  # (sf [T,L], sb [T,L], gain [T,L], node_tot [T,L,S], node_of [T,n])


_grow_level_trees = functools.partial(jax.jit, static_argnums=(5, 6, 7, 8, 11))(
    _grow_level_trees_impl
)


@functools.lru_cache(maxsize=8)
def _grow_level_trees_mesh(mesh, axis_name: str):
    """shard_map'd whole-forest level pass: rows sharded over ``axis_name``
    (tree axis replicated in layout, scanned in compute), histograms
    psum'd per tree inside the scan."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    rows = P(axis_name, None)
    trows = P(None, axis_name, None)
    trow1 = P(None, axis_name)
    repl = P()

    def wrapped(binned, stats_t, node_t, mask_t, allowed_mask, level_start,
                num_level_nodes, num_bins, impurity, min_node_size,
                min_info_gain, is_last_level):
        fn = functools.partial(
            _grow_level_trees_impl,
            level_start=level_start,
            num_level_nodes=num_level_nodes,
            num_bins=num_bins,
            impurity=impurity,
            min_node_size=min_node_size,
            min_info_gain=min_info_gain,
            is_last_level=is_last_level,
            axis_name=axis_name,
        )
        return shard_map(
            fn,
            mesh=mesh,
            in_specs=(rows, trows, trow1, repl, repl),
            out_specs=(repl, repl, repl, repl, trow1),
        )(binned, stats_t, node_t, mask_t, allowed_mask)

    return functools.partial(jax.jit, static_argnums=(5, 6, 7, 8, 9, 10, 11))(wrapped)


def train_forest(
    binned: np.ndarray,
    targets: np.ndarray,
    num_bins: int,
    num_classes: int | None,
    num_trees: int = 20,
    max_depth: int = 8,
    min_node_size: float = 1.0,
    min_info_gain: float = 0.0,
    impurity: str = "entropy",
    mtry: int | None = None,
    seed: int | None = None,
    exclude_features: set[int] | None = None,
    mesh=None,
) -> ForestArrays:
    """Train `num_trees` trees over pre-binned features. Columns in
    `exclude_features` (e.g. the target's predictor slot) are never
    sampled for splitting. With ``mesh``, example rows shard over the
    'data' axis and per-level histograms psum across devices."""
    from oryx_tpu.common import rng as rng_mod

    binned = np.asarray(binned, dtype=np.int32)
    n, p = binned.shape
    allowed = np.asarray(
        sorted(set(range(p)) - (exclude_features or set())), dtype=np.int64
    )
    if len(allowed) == 0:
        raise ValueError("no usable features")
    allowed_vec = np.zeros(p, dtype=np.float32)
    allowed_vec[allowed] = 1.0
    if num_classes is None:
        y = np.asarray(targets, dtype=np.float32)
        stats_base = np.stack([np.ones(n, np.float32), y, y * y], axis=1)
        imp_kind = "variance"
    else:
        y = np.asarray(targets, dtype=np.int32)
        stats_base = np.eye(num_classes, dtype=np.float32)[y]
        imp_kind = impurity
    pa = len(allowed)
    if mtry is None:
        mtry = max(1, int(np.sqrt(pa)) if num_classes is not None else max(1, pa // 3))

    max_nodes = 2 ** (max_depth + 1) - 1
    gen = np.random.default_rng(rng_mod.next_seed() if seed is None else seed)

    t_feat = np.full((num_trees, max_nodes), -1, dtype=np.int32)
    t_bin = np.full((num_trees, max_nodes), -1, dtype=np.int32)
    t_stats = np.zeros((num_trees, max_nodes, stats_base.shape[1]), dtype=np.float64)
    t_counts = np.zeros((num_trees, max_nodes), dtype=np.float64)
    t_gains = np.zeros((num_trees, max_nodes), dtype=np.float64)

    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from oryx_tpu.parallel.mesh import DATA_AXIS, pad_to_multiple

        num_shards = int(np.prod(mesh.devices.shape))
        n_pad = pad_to_multiple(n, num_shards)
        if n_pad != n:  # pad rows arrive inactive (node_of = -1, weight 0)
            binned = np.concatenate([binned, np.zeros((n_pad - n, p), np.int32)])
            stats_base = np.concatenate(
                [stats_base, np.zeros((n_pad - n, stats_base.shape[1]), np.float32)]
            )
        rows_sh = NamedSharding(mesh, P(DATA_AXIS, None))
        trows_sh = NamedSharding(mesh, P(None, DATA_AXIS, None))
        trow1_sh = NamedSharding(mesh, P(None, DATA_AXIS))
        grow = _grow_level_trees_mesh(mesh, DATA_AXIS)
        binned_dev = jax.device_put(binned, rows_sh)
    else:
        grow = _grow_level_trees
        binned_dev = jnp.asarray(binned)  # uploaded once, reused every level

    n_rows = binned.shape[0]  # == n unless mesh-padded

    # Trees batch into chunks whose [tc, n_rows, S] stats tensor stays
    # under a fixed budget: the whole-forest level pass would otherwise
    # hold num_trees full stats copies resident at once (a 10M x 100-class
    # run is ~4 GB per tree). One chunk covers every packaged config.
    s_chan = stats_base.shape[1]
    budget = int(_TREE_CHUNK_BUDGET_BYTES)
    tc = max(1, min(num_trees, budget // max(1, n_rows * s_chan * 4)))

    def chunk_weights(t0: int, t1: int) -> np.ndarray:
        # drawn per chunk (in order, so the sequence matches an up-front
        # [num_trees, n] draw) to keep peak weight memory chunk-bounded
        if num_trees > 1:
            w = gen.poisson(1.0, (t1 - t0, n)).astype(np.float32)
        else:
            w = np.ones((1, n), np.float32)
        if n_rows != n:  # pad rows arrive inactive (node_of = -1, weight 0)
            w = np.concatenate(
                [w, np.zeros((t1 - t0, n_rows - n), np.float32)], axis=1
            )
        return w

    # The chunk's whole forest advances one depth per dispatch (lax.scan
    # over trees inside the level kernel), and levels dispatch
    # asynchronously: each level's grow consumes the previous level's
    # device-resident routing, so a chunk trains in max_depth+1
    # dispatches with no host sync between them — the per-(tree, level)
    # dispatch grid of ~round-trip latency each dominated wall-clock on
    # remote devices. The grown-to-leaves early exit checks the PREVIOUS
    # level's splits: one level may dispatch redundantly, but an all-leaf
    # level writes the same -1/zero values the output arrays start with.
    for t0 in range(0, num_trees, tc):
        t1 = min(t0 + tc, num_trees)
        w_c = chunk_weights(t0, t1)
        stats_c = stats_base[None, :, :] * w_c[:, :, None]  # [tc, n_rows, S]
        node_c = np.where(w_c > 0, 0, -1).astype(np.int32)  # [tc, n_rows]
        if mesh is not None:
            stats_dev = jax.device_put(stats_c, trows_sh)
            node_dev = jax.device_put(node_c, trow1_sh)
        else:
            stats_dev = jnp.asarray(stats_c)
            node_dev = jnp.asarray(node_c)
        level_out = []
        prev_sf = None
        for depth in range(max_depth + 1):
            level_start = 2**depth - 1
            num_level = 2**depth
            # per-node mtry masks, vectorized: one uniform key per allowed
            # column, smallest-m keys win — a uniform random m-subset per
            # node in one pass (the per-node gen.choice loop was ~0.5s of
            # host time for a 20-tree depth-10 training)
            m = min(mtry, pa)
            mask_t = np.zeros((t1 - t0, num_level, p), dtype=np.float32)
            if m >= pa:
                mask_t[:, :, allowed] = 1.0
            else:
                keys = gen.random((t1 - t0, num_level, pa), dtype=np.float32)
                pick = np.argpartition(keys, m, axis=2)[:, :, :m]
                np.put_along_axis(
                    mask_t.reshape((t1 - t0) * num_level, p),
                    allowed[pick].reshape((t1 - t0) * num_level, m),
                    1.0,
                    axis=1,
                )
            sf, sb, gains, node_tot, node_dev = grow(
                binned_dev,
                stats_dev,
                node_dev,
                jnp.asarray(mask_t),
                allowed_vec,
                level_start,
                num_level,
                num_bins,
                imp_kind,
                np.float32(min_node_size),
                np.float32(min_info_gain),
                depth == max_depth,
            )
            for a in (sf, sb, gains, node_tot):
                try:
                    a.copy_to_host_async()
                except AttributeError:  # pragma: no cover - older array types
                    pass
            level_out.append((level_start, num_level, sf, sb, gains, node_tot))
            if prev_sf is not None and np.all(np.asarray(prev_sf) < 0):
                break
            prev_sf = sf
        for level_start, num_level, sf, sb, gains, node_tot in level_out:
            sl = slice(level_start, level_start + num_level)
            node_tot = np.asarray(node_tot)  # [tc, L, S]
            t_feat[t0:t1, sl] = np.asarray(sf)
            t_bin[t0:t1, sl] = np.asarray(sb)
            t_stats[t0:t1, sl] = node_tot
            t_counts[t0:t1, sl] = (
                node_tot[..., 0] if num_classes is None else node_tot.sum(axis=2)
            )
            t_gains[t0:t1, sl] = np.asarray(gains)
    return ForestArrays(t_feat, t_bin, t_stats, t_counts, t_gains, num_classes)


def feature_importances(forest: ForestArrays, num_features: int) -> np.ndarray:
    """Total weighted impurity decrease per feature, normalized to max 1
    (DecisionForest feature-importance semantics)."""
    imp = np.zeros(num_features)
    feat = forest.split_feature
    weight = forest.node_counts * forest.gains
    for t in range(forest.num_trees):
        mask = feat[t] >= 0
        np.add.at(imp, feat[t][mask], weight[t][mask])
    m = imp.max()
    return imp / m if m > 0 else imp


def predict_forest_binned(forest: ForestArrays, binned: np.ndarray) -> np.ndarray:
    """Vectorized inference over the flat heap arrays (device-friendly):
    returns [n, C] summed class counts or [n, 2] (sum, count) pooled."""
    binned = jnp.asarray(binned, dtype=jnp.int32)
    sf = jnp.asarray(forest.split_feature)
    sb = jnp.asarray(forest.split_bin)
    stats = jnp.asarray(forest.node_stats, dtype=jnp.float32)
    max_depth = int(np.log2(forest.split_feature.shape[1] + 1)) - 1

    @jax.jit
    def run(x):
        n = x.shape[0]

        def one_tree(carry, ti):
            node = jnp.zeros(n, dtype=jnp.int32)

            def step(_, node_):
                f = sf[ti][node_]
                b = sb[ti][node_]
                is_split = f >= 0
                goes_pos = x[jnp.arange(n), jnp.maximum(f, 0)] > b
                child = 2 * node_ + 1 + goes_pos.astype(jnp.int32)
                return jnp.where(is_split, child, node_)

            node = jax.lax.fori_loop(0, max_depth + 1, step, node)
            return carry + stats[ti][node], None

        acc, _ = jax.lax.scan(one_tree, jnp.zeros((n, stats.shape[2])), jnp.arange(sf.shape[0]))
        return acc

    return np.asarray(run(binned))
