"""K-means on TPU: Lloyd iterations and internal evaluation metrics.

The TPU-native replacement for Spark MLlib's KMeans.train used by the
reference's KMeansUpdate (app/oryx-app-mllib/.../kmeans/KMeansUpdate.java:
116-117): one Lloyd iteration is a distance matmul ([n,d] @ [d,k] on the
MXU), an argmin, and segment-sum reductions — points row-sharded over the
mesh's 'data' axis, centers replicated, XLA reducing partial sums across
shards. Initialization: "random" or "k-means||" (Bahmani et al.;
MLlib's default init, oversample then weighted k-means++ on candidates).

Also the four internal clustering quality metrics the reference computes
as Spark map-reduces (SumSquaredError/DaviesBouldinIndex/DunnIndex/
SilhouetteCoefficient.java, SURVEY.md §2.8), vectorized.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oryx_tpu.parallel.mesh import DATA_AXIS, pad_to_multiple


# one-hot [n, k] element cap for the matmul centroid update: beyond it
# (huge n*k) the memory-lean scatter update takes over
_ONEHOT_ELEM_BUDGET = 1 << 27

# wall seconds of the most recent train_kmeans call, split by phase
# ({"init": s, "iterate": s}); read by tools/train_benchmark.py for
# bench.py's per-phase rows. Overwritten per call, never merged.
last_phase_seconds: dict[str, float] = {}


def _assign(points_, centers_, mask_):
    # HIGHEST: the TPU default would compute distances in bf16 passes,
    # flipping borderline argmin assignments vs the Pallas sweep (which
    # accumulates in f32) and drifting the centers apart
    d2 = (
        jnp.sum(points_ * points_, axis=1, keepdims=True)
        - 2.0
        * jnp.dot(
            points_,
            centers_.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        + jnp.sum(centers_ * centers_, axis=1)[None, :]
    )
    a = jnp.argmin(d2, axis=1)
    mind2 = jnp.min(d2, axis=1)
    return a, jnp.where(mask_, mind2, 0.0)


def _centroid_sums(points, a, w, k):
    """Per-cluster (sums [k, d], counts [k]) of `w`-weighted points. The
    one-hot matmul form keeps the reduction on the MXU (and is several
    times faster than an XLA:CPU scatter); the segment-sum form is the
    fallback when the [n, k] one-hot would be too large."""
    if points.shape[0] * k <= _ONEHOT_ELEM_BUDGET:
        oh = jax.nn.one_hot(a, k, dtype=points.dtype) * w[:, None]
        sums = jnp.dot(oh.T, points, preferred_element_type=jnp.float32)
        counts = jnp.sum(oh, axis=0)
    else:
        sums = jax.ops.segment_sum(points * w[:, None], a, num_segments=k)
        counts = jax.ops.segment_sum(w, a, num_segments=k)
    return sums, counts


@functools.partial(jax.jit, static_argnums=3)
def _lloyd_run(points, centers0, mask, iterations):
    """points [n, d], centers0 [k, d], mask [n] bool (False = padding row)."""

    def body(_, centers_):
        a, _d = _assign(points, centers_, mask)
        k = centers_.shape[0]
        w = mask.astype(points.dtype)
        sums, counts = _centroid_sums(points, a, w, k)
        new_centers = jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], centers_
        )
        return new_centers

    centers = jax.lax.fori_loop(0, iterations, body, centers0)
    a, d2 = _assign(points, centers, mask)
    w = mask.astype(points.dtype)
    counts = jax.ops.segment_sum(w, a, num_segments=centers.shape[0])
    return centers, counts, jnp.sum(d2)


def _sq_to(points, c):
    """Squared distances [n] from each point to one center [d]."""
    diff = points - c[None, :]
    return jnp.sum(diff * diff, axis=1)


@functools.partial(jax.jit, static_argnums=(3, 4))
def _kmeans_parallel_init_device(points, mask, key, k, rounds):
    """k-means|| (Bahmani et al.) entirely on device: oversampling rounds,
    candidate weighting, and the weighted k-means++ reduction all run as
    one jitted program over fixed shapes — no host<->device churn, and the
    init overlaps the points upload instead of serializing against Lloyd.

    Fixed-shape formulation: each round Bernoulli-samples points with
    prob min(2k * d2/total, 1) (expected ~2k picks) and keeps up to
    4k of them (smallest drawn uniforms win — the draw is still a
    uniform random subset of the sampled points); candidates live in a
    [1 + rounds*4k, d] buffer with a validity mask. The final weighted
    k-means++ picks sequentially by the Gumbel-max trick, so categorical
    sampling needs no host round-trip either. Same distribution family
    as the host path, not the same RNG stream: quality equivalence (SSE)
    is the contract, asserted in tests/ops/test_trainers.py."""
    n, d = points.shape
    cap_round = min(4 * k, n)  # top_k cannot exceed the row count
    cap_t = 1 + rounds * cap_round
    maskf = mask.astype(jnp.float32)
    logmask = jnp.where(mask, 0.0, -jnp.inf)

    key, k0 = jax.random.split(key)
    i0 = jnp.argmax(jax.random.gumbel(k0, (n,)) + logmask)  # uniform valid row
    cand = jnp.zeros((cap_t, d), jnp.float32).at[0].set(points[i0])
    cvalid = jnp.zeros(cap_t, bool).at[0].set(True)
    d2 = jnp.where(mask, _sq_to(points, points[i0]), 0.0)
    # nearest-candidate id per point, tracked incrementally across rounds
    # so no final [n, cap_t] assignment pass is needed for the weights
    amin = jnp.zeros(n, jnp.int32)

    def round_body(r, carry):
        cand, cvalid, d2, amin, key = carry
        key, ku = jax.random.split(key)
        total = jnp.maximum(jnp.sum(d2), 1e-30)
        probs = jnp.minimum((2.0 * k) * d2 / total, 1.0)
        u = jax.random.uniform(ku, (n,))
        picked = (u < probs) & mask
        _, idx = jax.lax.top_k(-jnp.where(picked, u, jnp.inf), cap_round)
        newv = picked[idx]
        newpts = jnp.where(newv[:, None], points[idx], 0.0)
        base = 1 + r * cap_round
        cand = jax.lax.dynamic_update_slice(cand, newpts, (base, 0))
        cvalid = jax.lax.dynamic_update_slice(cvalid, newv, (base,))
        dn = (
            jnp.sum(points * points, axis=1, keepdims=True)
            - 2.0 * jnp.dot(points, newpts.T, preferred_element_type=jnp.float32)
            + jnp.sum(newpts * newpts, axis=1)[None, :]
        )
        dn = jnp.where(newv[None, :], dn, jnp.inf)
        dn_min = jnp.maximum(dn.min(axis=1), 0.0)
        closer = dn_min < d2
        amin = jnp.where(closer, base + jnp.argmin(dn, axis=1).astype(jnp.int32), amin)
        d2 = jnp.where(mask & closer, dn_min, d2)
        return cand, cvalid, d2, amin, key

    cand, cvalid, d2, amin, key = jax.lax.fori_loop(
        0, rounds, round_body, (cand, cvalid, d2, amin, key)
    )

    # weight candidates by how many points they attract
    w = jax.ops.segment_sum(maskf, amin, num_segments=cap_t)

    # weighted k-means++ over the candidates (Gumbel-max categorical:
    # argmax(log score + Gumbel) samples proportionally to score; an
    # already-chosen candidate has d2 = 0 -> score 0 -> never re-picked)
    key, kp0 = jax.random.split(key)
    lw = jnp.log(jnp.where(cvalid, w, 0.0))
    i0 = jnp.argmax(lw + jax.random.gumbel(kp0, (cap_t,)))
    centers = jnp.zeros((k, d), jnp.float32).at[0].set(cand[i0])
    mind2 = jnp.maximum(_sq_to(cand, cand[i0]), 0.0)

    def pp_body(i, carry):
        centers, mind2, key = carry
        key, kg = jax.random.split(key)
        score = jnp.where(cvalid, mind2 * w, 0.0)
        idx = jnp.argmax(jnp.log(score) + jax.random.gumbel(kg, (cap_t,)))
        c = cand[idx]
        centers = centers.at[i].set(c)
        mind2 = jnp.minimum(mind2, jnp.maximum(_sq_to(cand, c), 0.0))
        return centers, mind2, key

    centers, _, _ = jax.lax.fori_loop(1, k, pp_body, (centers, mind2, key))
    return centers


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _minibatch_run(points, centers0, key, iterations, batch, n_items):
    """Mini-batch k-means (Sculley 2010): each iteration assigns a random
    `batch`-point sample and moves each touched center toward the batch
    mean with a per-center learning rate 1/v_c (v_c = cumulative assigned
    count), so the steady-state cost scales with the batch size, not n.
    Returns the final centers only — callers finish with one full
    assignment pass for counts/cost."""
    n, d = points.shape
    k = centers0.shape[0]

    def body(_, carry):
        centers, v, key = carry
        key, ks = jax.random.split(key)
        idx = jax.random.randint(ks, (batch,), 0, n_items)
        xb = points[idx]
        a, _ = _assign(xb, centers, jnp.ones(batch, bool))
        sums, cnt = _centroid_sums(xb, a, jnp.ones(batch, jnp.float32), k)
        v = v + cnt
        centers = centers + (sums - cnt[:, None] * centers) / jnp.maximum(v, 1.0)[:, None]
        return centers, v, key

    centers, _, _ = jax.lax.fori_loop(
        0, iterations, body, (centers0, jnp.zeros(k, jnp.float32), key)
    )
    return centers


def train_kmeans(
    points: np.ndarray,
    k: int,
    iterations: int = 30,
    init: str = "k-means||",
    mesh: Optional[Mesh] = None,
    seed: int | None = None,
    initial_centers: np.ndarray | None = None,
    minibatch_size: int | None = None,
    init_backend: str = "auto",
) -> tuple[np.ndarray, np.ndarray, float]:
    """Returns (centers [k,d], counts [k], cost). Padded internally so the
    point rows shard evenly over the mesh. ``initial_centers`` [k, d]
    seeds Lloyd directly (warm-start from a previous generation's
    centers); a shape mismatch silently falls back to the configured
    ``init`` so a changed k or feature dim cold-starts.

    ``minibatch_size`` switches the iterations to mini-batch k-means
    (Sculley 2010; config knob oryx.ml.kmeans.minibatch-size): each
    iteration updates centers from a random sample of that many points,
    then ONE full pass produces the reported counts/cost. n at or below
    the batch size (or a mesh) runs full-batch Lloyd as before.

    ``init_backend``: "device" runs k-means|| init as one jitted program
    on the accelerator, "host" keeps the NumPy passes, "auto" = device
    except under a mesh (where points are row-sharded and the init's
    candidate set is cheapest to build on the host)."""
    import time as _time

    from oryx_tpu.common import rng as rng_mod

    points = np.asarray(points, dtype=np.float32)
    n, d = points.shape
    if n == 0:
        raise ValueError("no points")
    k = min(k, n)
    gen = np.random.default_rng(rng_mod.next_seed() if seed is None else seed)
    minibatch = minibatch_size is not None and 0 < minibatch_size < n and mesh is None
    device_init = init_backend == "device" or (init_backend == "auto" and mesh is None)

    def pick_init(pts_dev=None, n_items=None):
        # pts_dev: pre-uploaded (possibly row-padded) device points; lets
        # the device init consume the in-flight upload directly
        if initial_centers is not None:
            warm = np.asarray(initial_centers, dtype=np.float32)
            if warm.shape == (k, d):
                return warm.copy()
        if init == "random":
            return points[gen.choice(n, size=k, replace=False)]
        if device_init:
            if pts_dev is None:
                pts_dev, n_items = jnp.asarray(points), n
            pad_mask = jnp.arange(pts_dev.shape[0]) < n_items
            key = jax.random.PRNGKey(int(gen.integers(2**31)))
            return _kmeans_parallel_init_device(pts_dev, pad_mask, key, k, 2)
        return _kmeans_parallel_init(points, k, gen)

    if mesh is None and jax.default_backend() == "tpu":
        # single-device TPU: the fused Pallas sweep reads the points once
        # per iteration (no [n, k] distance matrix in HBM); huge k*d whose
        # working set would overflow VMEM falls back to the XLA path
        from oryx_tpu.ops.pallas_kmeans import (
            fits_vmem,
            lloyd_pallas,
            minibatch_lloyd_pallas,
            pad_to_block,
        )

        if fits_vmem(k, d):
            # start the H->D transfer first: jnp.asarray enqueues the copy
            # asynchronously, so the k-means|| init (device or host) runs
            # while the points stream over the link (both were serialized
            # before, and at bench scale each is a double-digit-% slice
            # of total wall)
            t_init = _time.perf_counter()
            pts_dev = jnp.asarray(pad_to_block(points))
            centers0 = np.asarray(pick_init(pts_dev, n), dtype=np.float32)
            t_iter = _time.perf_counter()
            if minibatch:
                key = jax.random.PRNGKey(int(gen.integers(2**31)))
                # every mini-batch step AND the final full pass run the
                # fused sweep kernel (one dispatch for the whole schedule)
                out = minibatch_lloyd_pallas(
                    pts_dev, centers0, iterations, int(minibatch_size), key,
                    n_items=n,
                )
            else:
                out = lloyd_pallas(pts_dev, centers0, iterations, n_items=n)
            last_phase_seconds.clear()
            last_phase_seconds.update(
                init=t_iter - t_init, iterate=_time.perf_counter() - t_iter
            )
            return out

    num_shards = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    n_pad = pad_to_multiple(n, num_shards)
    if n_pad != n:
        points = np.concatenate([points, np.zeros((n_pad - n, d), dtype=np.float32)])
    mask = np.arange(n_pad) < n  # explicit: origin points are real data

    t_init = _time.perf_counter()
    if mesh is not None:
        centers0 = pick_init()
        rows = NamedSharding(mesh, P(DATA_AXIS, None))
        row1 = NamedSharding(mesh, P(DATA_AXIS))
        repl = NamedSharding(mesh, P())
        points_dev = jax.device_put(points, rows)
        mask_dev = jax.device_put(mask, row1)
        centers_dev = jax.device_put(np.asarray(centers0, np.float32), repl)
        t_iter = _time.perf_counter()
        centers, counts, cost = _lloyd_run(points_dev, centers_dev, mask_dev, iterations)
    else:
        pts_dev = jnp.asarray(points)
        centers0 = jnp.asarray(pick_init(pts_dev, n), dtype=jnp.float32)
        centers0.block_until_ready()
        t_iter = _time.perf_counter()
        if minibatch:
            key = jax.random.PRNGKey(int(gen.integers(2**31)))
            centers0 = _minibatch_run(
                pts_dev, centers0, key, iterations, int(minibatch_size), n
            )
            centers, counts, cost = _lloyd_run(pts_dev, centers0, mask, 0)
        else:
            centers, counts, cost = _lloyd_run(pts_dev, centers0, mask, iterations)
    centers, counts, cost = np.asarray(centers), np.asarray(counts), float(cost)
    last_phase_seconds.clear()
    last_phase_seconds.update(
        init=t_iter - t_init, iterate=_time.perf_counter() - t_iter
    )
    return centers, counts, cost


def _kmeans_parallel_init(points: np.ndarray, k: int, gen: np.random.Generator, rounds: int = 2):
    """k-means|| oversampling init then weighted k-means++ over candidates."""
    n = points.shape[0]
    centers = [points[gen.integers(n)]]
    oversample = 2 * k
    for _ in range(rounds):
        c = np.stack(centers)
        d2 = _min_sq_dists(points, c)
        total = d2.sum()
        if total <= 0:
            break
        probs = np.minimum(oversample * d2 / total, 1.0)
        picked = np.nonzero(gen.random(n) < probs)[0]
        centers.extend(points[i] for i in picked)
    cand = np.stack(centers)
    if len(cand) <= k:
        # oversampling came up short: top up with random points (keeping
        # the sampled candidates first; duplicates are harmless — Lloyd
        # leaves an empty cluster's center in place)
        extra = points[gen.choice(n, size=k, replace=n < k)]
        return np.concatenate([cand, extra])[:k]
    # weight candidates by how many points they attract, then k-means++
    assign = np.argmin(_sq_dist_matrix(points, cand), axis=1)
    weights = np.bincount(assign, minlength=len(cand)).astype(np.float64)
    return _weighted_kmeans_pp(cand, weights, k, gen)


def _weighted_kmeans_pp(cand: np.ndarray, weights: np.ndarray, k: int, gen) -> np.ndarray:
    chosen = [int(gen.choice(len(cand), p=weights / weights.sum()))]
    for _ in range(k - 1):
        d2 = _min_sq_dists(cand, cand[chosen])
        score = d2 * weights
        total = score.sum()
        if total <= 0:
            remaining = [i for i in range(len(cand)) if i not in chosen]
            chosen.append(int(gen.choice(remaining)))
            continue
        chosen.append(int(gen.choice(len(cand), p=score / total)))
    return cand[chosen]


def _sq_dist_matrix(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return (
        np.sum(a * a, axis=1, keepdims=True)
        - 2.0 * a @ b.T
        + np.sum(b * b, axis=1)[None, :]
    )


def _min_sq_dists(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    return np.maximum(_sq_dist_matrix(a, b).min(axis=1), 0.0)


# ---------------------------------------------------------------------------
# Assignment + internal evaluation metrics
# ---------------------------------------------------------------------------


def assign_clusters(points: np.ndarray, centers: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(cluster ids, distances) for each point (Euclidean)."""
    d2 = _sq_dist_matrix(np.asarray(points, np.float64), np.asarray(centers, np.float64))
    a = np.argmin(d2, axis=1)
    return a, np.sqrt(np.maximum(d2[np.arange(len(a)), a], 0.0))


def sum_squared_error(points: np.ndarray, centers: np.ndarray) -> float:
    """SSE: lower is better (SumSquaredError.java)."""
    _, dist = assign_clusters(points, centers)
    return float(np.sum(dist**2))


def _cluster_mean_dists(points: np.ndarray, centers: np.ndarray) -> np.ndarray:
    a, dist = assign_clusters(points, centers)
    k = centers.shape[0]
    sums = np.bincount(a, weights=dist, minlength=k)
    counts = np.maximum(np.bincount(a, minlength=k), 1)
    return sums / counts


def davies_bouldin_index(points: np.ndarray, centers: np.ndarray) -> float:
    """Mean over clusters i of max_j != i (S_i + S_j) / d(c_i, c_j);
    lower is better (DaviesBouldinIndex.java)."""
    s = _cluster_mean_dists(points, centers)
    k = centers.shape[0]
    if k < 2:
        return 0.0
    cd = np.sqrt(np.maximum(_sq_dist_matrix(centers.astype(np.float64), centers.astype(np.float64)), 0))
    ratios = (s[:, None] + s[None, :]) / np.where(cd > 0, cd, np.inf)
    np.fill_diagonal(ratios, 0.0)
    return float(np.mean(ratios.max(axis=1)))


def dunn_index(points: np.ndarray, centers: np.ndarray) -> float:
    """Min centroid separation / max mean intra-cluster distance; higher
    is better (DunnIndex.java)."""
    s = _cluster_mean_dists(points, centers)
    k = centers.shape[0]
    if k < 2:
        return 0.0
    cd = np.sqrt(np.maximum(_sq_dist_matrix(centers.astype(np.float64), centers.astype(np.float64)), 0))
    cd[np.eye(k, dtype=bool)] = np.inf
    max_intra = s.max()
    if max_intra <= 0:
        return 0.0
    return float(cd.min() / max_intra)


def silhouette_coefficient(
    points: np.ndarray, centers: np.ndarray, max_sample: int = 100_000, gen=None
) -> float:
    """Mean silhouette over a sample; singleton clusters contribute 0
    (SilhouetteCoefficient.java, MAX_SAMPLE_SIZE=100000)."""
    points = np.asarray(points, dtype=np.float64)
    if gen is None:
        from oryx_tpu.common import rng as rng_mod

        gen = rng_mod.get_random()
    if len(points) > max_sample:
        points = points[gen.choice(len(points), size=max_sample, replace=False)]
    a, _ = assign_clusters(points, centers)
    k = centers.shape[0]
    total = 0.0
    count = len(points)
    if count == 0:
        return 0.0
    # Mean distance from each point to each cluster's points, computed in
    # row blocks so peak memory is O(block x largest-cluster) rather than
    # O(|cluster| x sample) — at the 100k default sample a dense per-pair
    # matrix would be tens of GB.
    by_cluster = [points[a == c] for c in range(k)]
    sizes = np.asarray([len(p) for p in by_cluster])
    block = 256
    for c in range(k):
        pts = by_cluster[c]
        if len(pts) <= 1:
            continue  # contributes 0
        for start in range(0, len(pts), block):
            blk = pts[start : start + block]
            intra = np.zeros(len(blk))
            inter = np.full(len(blk), np.inf)
            for o in range(k):
                if not sizes[o]:
                    continue
                d = np.sqrt(np.maximum(_sq_dist_matrix(blk, by_cluster[o]), 0))
                if o == c:
                    intra = d.sum(axis=1) / (sizes[c] - 1)  # exclude self (d=0)
                else:
                    inter = np.minimum(inter, d.mean(axis=1))
            valid = np.isfinite(inter)
            s = np.where(
                valid, (inter - intra) / np.maximum(np.maximum(intra, inter), 1e-300), 0.0
            )
            total += float(s.sum())
    return total / count
