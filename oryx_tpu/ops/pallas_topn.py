"""Pallas TPU kernel: fused item-scoring + top-k for ALS serving.

The serving hot loop is "score every item against a user vector, keep the
best k" (reference: ALSServingModel.topN / TopNConsumer.java scanning LSH
partitions on a thread pool, VectorMath.dot per item). On TPU the exact
scan is one matmul — but the naive XLA program (``scores = Q @ Y.T`` then
``lax.top_k``) writes the full [b, n_items] score matrix to HBM and reads
it back for the top-k, which at 1M+ items costs more bandwidth than
reading the item matrix itself. This module fuses the two:

- the item matrix is laid out feature-major ``[k_feat, n_items]`` so each
  grid step streams a contiguous ``[k_feat, BLOCK_N]`` block of items
  through VMEM (Mosaic double-buffers blocks across the grid);
- each step computes ``[b, BLOCK_N]`` scores on the MXU with float32
  accumulation (items may be stored bfloat16 or row-quantized int8,
  halving / quartering HBM traffic);
- each block reduces to its own top-k candidates on-chip — either into a
  running VMEM scratch (small scan batches, with a threshold gate that
  skips selection for blocks that cannot enter the top-k) or as
  block-local ``[b, k]`` candidate tiles (large scan batches, merged by
  one tiny ``lax.top_k`` over ``[b, num_blocks * k]`` afterwards);
- only candidates ever reach HBM — the full score matrix never does.

HBM traffic per batch drops from ``n*k_feat*4 + 2*b*n*4`` bytes to
``n*k_feat*{1|2|4}`` — a 2-12x win for the bandwidth-bound scan.

int8 handles store one f32 dequantization scale per item row
(``absmax/127``); scores dequantize by a single post-dot multiply, and
cosine scoring folds the cached item norms into that same multiplier so
the kernel never rescales twice.

On non-TPU backends the public entry points run an XLA twin of the same
blocked scan (``lax.scan`` over feature-major item blocks, block-local
``lax.top_k``, final candidate merge) instead of materializing [b, n]
scores; ``interpret=True`` forces the Pallas kernel under the interpreter
(used by the CPU parity tests).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some CPU-only builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Score-tile width. [b=256, 4096] f32 scores + the iota/mask temps fit
# the 16 MB scoped-VMEM limit of a v5e; 8192 does not (measured 20.7 MB).
import os as _os

SCORE_TILE = int(_os.environ.get("ORYX_TOPN_BLOCK", 4096))
# Sub-tiles streamed per grid step: the item block per step is
# [k_feat, SCORE_TILE * SUBTILES] (bf16, ~1.6 MB at 4) while the
# score/iota tiles stay SCORE_TILE wide — grid-step orchestration costs
# ~20us on a v5e, so fewer, fatter steps is most of the kernel's speed
# (measured 5.5 ms -> 0.17 ms per 1M x 50 scan going 1 -> 4). 8 exceeds
# the 16 MB scoped-VMEM limit at b=256.
SUBTILES = int(_os.environ.get("ORYX_TOPN_SUBTILES", 4))
BLOCK_N = SCORE_TILE * SUBTILES  # items consumed per grid step

# Scan batches past this row count switch the compiled kernel to the
# block-local candidates form: the running-scratch kernel needs the full
# [b, SCORE_TILE] score tile resident, which stops fitting scoped VMEM
# past ~256 rows, while the candidates kernel shrinks its tile instead.
LOCAL_TOPK_BATCH = int(_os.environ.get("ORYX_TOPN_LOCAL_TOPK_BATCH", 256))

# Items per lax.scan step of the XLA (non-TPU) blocked scan. Rounded down
# to a BLOCK_N multiple that divides the padded item count. 16K keeps the
# [b, block] score tile inside L2/L3 so the block-local top-k reads cache,
# not DRAM (measured best of 4K..128K on the 1-core cpu bench host).
XLA_SCAN_BLOCK = int(_os.environ.get("ORYX_XLA_SCAN_BLOCK", 16384))

# Oversampling factor for quantized scans: the int8 plane ranks the scan,
# then the top (RESCORE_OVERSAMPLE * k) candidates are re-scored against
# the residual plane (int8 codes of what the first plane dropped) before
# the final top-k. 0 disables rescoring (raw int8 ranks).
RESCORE_OVERSAMPLE = int(_os.environ.get("ORYX_TOPN_RESCORE", 4))

# Chunk width of the quantized XLA scan's candidate selection: the scan
# reduces scores to per-chunk maxes (a reduce that fuses into the GEMM's
# epilogue — wide lax.top_k inside the scan body does not), the top-m
# chunks by max provably contain the top-m items, and only those chunks'
# columns are gathered and scored exactly afterwards.
_CHUNK = int(_os.environ.get("ORYX_TOPN_CHUNK", 32))

# How many chunks that selection keeps: the top-k chunks by primary-plane
# max already provably contain the primary top-k items, and every kept
# chunk drags in its _CHUNK-1 neighbors, so a modest factor over k yields
# a ~30x item-level oversample for the exact two-plane rescore. The tail
# (gather + rescore) is linear in this count — keep it lean.
CHUNK_OVERSAMPLE = float(_os.environ.get("ORYX_TOPN_CHUNK_OVERSAMPLE", 1.25))


def _chunk_k(k: int, chunks: int) -> int:
    return min(max(int(round(CHUNK_OVERSAMPLE * k)), k + 2), chunks)


def configure_scan(
    *,
    oversample: int | None = None,
    chunk: int | None = None,
    block: int | None = None,
) -> None:
    """Apply ``oryx.serving.scan.*`` tuning (serving-layer startup). Must
    run before the first dispatch: jitted scan programs bake these in at
    trace time and are cached by shape, not by knob value."""
    global RESCORE_OVERSAMPLE, _CHUNK, XLA_SCAN_BLOCK
    if oversample is not None:
        RESCORE_OVERSAMPLE = int(oversample)
    if chunk is not None:
        _CHUNK = int(chunk)
    if block is not None:
        XLA_SCAN_BLOCK = int(block)

# int8 operand tiles are (32 sublanes, 128 lanes): the feature dim of a
# quantized matrix pads to a 32 multiple (zero-filled; queries pad alike)
_INT8_FEAT_MULTIPLE = 32


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def _is_int8(dtype) -> bool:
    if dtype is None:
        return False
    try:
        return np.dtype(dtype) == np.dtype(np.int8)
    except TypeError:  # pragma: no cover - exotic dtype objects
        return False


@dataclass(frozen=True)
class StreamingItemMatrix:
    """Device-resident item factors in the kernel's feature-major layout."""

    mat_t: jax.Array  # [k_feat(_pad), n_padded]; f32, bf16, or row-quantized int8
    norms: jax.Array  # [1, n_padded] f32 (L2 norms of the ORIGINAL f32 rows)
    n_items: int
    # int8 handles only: per-item dequantization scale (absmax/127, f32,
    # 1.0 for all-zero rows so dequantizing is always a plain multiply)
    scales: jax.Array | None = None
    # true feature count before int8 sublane padding (None = no padding)
    features: int | None = None
    # int8 handles only: residual plane — int8 codes of (row - codes * s),
    # with its own per-row scale. Never scanned: only the top-(~4k)
    # candidates per query gather it for a ~14-bit-effective rescore, so
    # scan traffic stays 1 B/feature while recall matches f32.
    resid: jax.Array | None = None
    resid_scales: jax.Array | None = None

    @property
    def num_features(self) -> int:
        return self.features if self.features is not None else self.mat_t.shape[0]

    @property
    def quantized(self) -> bool:
        return self.scales is not None


def _quantize_rows(mat: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Row-wise symmetric int8: q = rint(row / s), s = absmax/127 (1.0 for
    all-zero rows). Same rule as the device-side requantize in
    ``topn.update_rows`` so a scatter round-trips bit-exactly."""
    absmax = np.max(np.abs(mat), axis=1)
    s = np.where(absmax > 0, absmax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(mat / s[:, None]), -127, 127).astype(np.int8)
    return q, s


def _quantize_residual(
    mat: np.ndarray, q: np.ndarray, s: np.ndarray
) -> tuple[np.ndarray, np.ndarray]:
    """Second int8 plane: quantize what the first plane dropped
    (``row - q * s``, at most s/2 per element) with its own per-row
    absmax/127 scale — together the planes carry ~14 significant bits,
    enough for candidate rescoring to match f32 ranking."""
    r = mat - q.astype(np.float32) * s[:, None]
    am = np.max(np.abs(r), axis=1)
    s2 = np.where(am > 0, am / 127.0, 1.0).astype(np.float32)
    q2 = np.clip(np.rint(r / s2[:, None]), -127, 127).astype(np.int8)
    return q2, s2


def upload_streaming(matrix: np.ndarray, dtype=jnp.float32) -> StreamingItemMatrix:
    """Pad items up to a BLOCK_N multiple and move [k, n] to device.

    ``dtype=jnp.int8`` row-quantizes: each item row stores int8 codes plus
    one f32 scale, cutting the scan's HBM traffic 4x vs f32 while keeping
    per-row dynamic range (a global scale would clip hot rows)."""
    n, k_feat = matrix.shape
    n_pad = max(BLOCK_N, _ceil_to(n, BLOCK_N))
    mat = np.asarray(matrix, dtype=np.float32)
    norms = np.zeros((1, n_pad), dtype=np.float32)
    norms[0, :n] = np.linalg.norm(mat, axis=1)
    if _is_int8(dtype):
        q, s = _quantize_rows(mat)
        q2, s2 = _quantize_residual(mat, q, s)
        kf_pad = _ceil_to(k_feat, _INT8_FEAT_MULTIPLE)
        mat_t = np.zeros((kf_pad, n_pad), dtype=np.int8)
        mat_t[:k_feat, :n] = q.T
        resid = np.zeros((kf_pad, n_pad), dtype=np.int8)
        resid[:k_feat, :n] = q2.T
        scales = np.ones((1, n_pad), dtype=np.float32)
        scales[0, :n] = s
        rscales = np.ones((1, n_pad), dtype=np.float32)
        rscales[0, :n] = s2
        return StreamingItemMatrix(
            mat_t=jnp.asarray(mat_t),
            norms=jnp.asarray(norms),
            n_items=n,
            scales=jnp.asarray(scales),
            features=k_feat if kf_pad != k_feat else None,
            resid=jnp.asarray(resid),
            resid_scales=jnp.asarray(rscales),
        )
    mat_t = np.zeros((k_feat, n_pad), dtype=np.float32)
    mat_t[:, :n] = mat.T
    return StreamingItemMatrix(
        mat_t=jnp.asarray(mat_t, dtype=dtype),
        norms=jnp.asarray(norms),
        n_items=n,
    )


def _dot_precision_for(q, quantized: bool):
    # f32 items get true f32 accumulation (TPU default would silently drop
    # to bf16 passes); bf16 items are the intentional fast path. int8
    # items upcast in-register and take bf16 MXU passes: the quantization
    # step (~0.4% of row absmax) dominates the accumulation error, and
    # DEFAULT runs the MXU at 6x the f32-HIGHEST rate.
    if quantized or q.dtype != jnp.float32:
        return jax.lax.Precision.DEFAULT
    return jax.lax.Precision.HIGHEST


def _score_tile(q, mat_s, aux_s, qn, *, cosine, quantized):
    """[b, tile] scores for one item sub-tile. ``aux_s`` is the item-norm
    tile (unquantized) or the folded dequant multiplier (quantized; cosine
    norms already divided in outside the kernel)."""
    if quantized:
        mat_s = mat_s.astype(jnp.float32)
    scores = jnp.dot(
        q,
        mat_s,
        preferred_element_type=jnp.float32,
        precision=_dot_precision_for(q, quantized),
    )
    if quantized:
        scores = scores * aux_s
        if cosine:
            scores = scores / jnp.maximum(qn, 1e-12)
    elif cosine:
        scores = scores / jnp.maximum(aux_s * qn, 1e-12)
    return scores


def _tile_topk(sc, local_cols, base, k, int_max, neg_inf):
    """Unrolled iterative max: the tile's top-k as k [b, 1] column lists
    (ties -> lowest item id, like a stable host scan)."""
    vals_cols = []
    idx_cols = []
    for _ in range(k):
        m = jnp.max(sc, axis=1, keepdims=True)  # [b, 1]
        at = jnp.min(jnp.where(sc == m, local_cols, int_max), axis=1, keepdims=True)
        vals_cols.append(m)
        idx_cols.append(at + base)
        sc = jnp.where(local_cols == at, neg_inf, sc)
    return vals_cols, idx_cols


def _merge_topk(cur_v, cur_i, vals_cols, idx_cols, k, int_max, neg_inf):
    """Merge a tile's top-k column lists into the running [b, k] state:
    k passes over [b, 2k] (tiny). Ties prefer the smaller item index,
    which is always the earlier tile — same result as a stable global
    merge."""
    cat_v = jnp.concatenate([cur_v] + vals_cols, axis=1)
    cat_i = jnp.concatenate([cur_i] + idx_cols, axis=1)
    new_v = []
    new_i = []
    for _ in range(k):
        m = jnp.max(cat_v, axis=1, keepdims=True)
        sel = jnp.min(jnp.where(cat_v == m, cat_i, int_max), axis=1, keepdims=True)
        new_v.append(m)
        new_i.append(sel)
        cat_v = jnp.where((cat_v == m) & (cat_i == sel), neg_inf, cat_v)
    return jnp.concatenate(new_v, axis=1), jnp.concatenate(new_i, axis=1)


def _topn_kernel(
    q_ref, mat_ref, aux_ref, vals_ref, idx_ref, vstate, istate, *,
    k, n_items, cosine, quantized, grid, subtiles
):
    """One grid step: score a [k_feat, BLOCK_N] item block and fold it
    into the running top-k carried in VMEM scratch across grid steps.

    The k-pass selection is ~40 VPU ops per score — 10x the cost of the
    matmul that produced them — so the kernel keeps the running k-th-best
    as a threshold and SKIPS selection for blocks whose max cannot enter
    the top-k. With a randomly ordered item matrix only O(k log grid) of
    the blocks pass the gate, which turns the scan from selection-bound
    (~4ms at 1M x 50) into matmul/HBM-bound."""
    block = pl.program_id(0)
    b = q_ref.shape[0]
    neg_inf = jnp.float32(-jnp.inf)
    int_max = jnp.int32(2**31 - 1)

    @pl.when(block == 0)
    def _():
        vstate[...] = jnp.full((b, k), neg_inf, jnp.float32)
        istate[...] = jnp.zeros((b, k), jnp.int32)

    q = q_ref[:]  # [b, k_feat]
    qn = None
    if cosine:
        qn = jnp.sqrt(
            jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32), axis=1, keepdims=True)
        )
    # local (per-tile) column ids: one [b, SCORE_TILE] iota reused by every
    # sub-tile keeps VMEM at two tiles regardless of how many sub-tiles a
    # grid step streams; the global item id is base + local.
    local_cols = jax.lax.broadcasted_iota(jnp.int32, (b, SCORE_TILE), 1)
    for s in range(subtiles):  # unrolled: static sub-tile slices
        base = block * (SCORE_TILE * subtiles) + s * SCORE_TILE
        scores = _score_tile(
            q,
            mat_ref[:, s * SCORE_TILE : (s + 1) * SCORE_TILE],
            aux_ref[:, s * SCORE_TILE : (s + 1) * SCORE_TILE],
            qn,
            cosine=cosine,
            quantized=quantized,
        )
        scores = jnp.where(local_cols < n_items - base, scores, neg_inf)
        kth = vstate[...][:, k - 1 : k]  # worst of the running top-k, [b, 1]
        need = jnp.any(jnp.max(scores, axis=1, keepdims=True) > kth)

        @pl.when(need)
        def _(scores=scores, base=base):
            vals_cols, idx_cols = _tile_topk(
                scores, local_cols, base, k, int_max, neg_inf
            )
            v, i = _merge_topk(
                vstate[...], istate[...], vals_cols, idx_cols, k, int_max, neg_inf
            )
            vstate[...] = v
            istate[...] = i

    @pl.when(block == grid - 1)
    def _():
        vals_ref[...] = vstate[...]
        idx_ref[...] = istate[...]


def _topn_candidates_kernel(
    q_ref, mat_ref, aux_ref, vals_ref, idx_ref, *,
    k, n_items, cosine, quantized, subtiles, tile
):
    """Block-local top-k: each grid step reduces its own item block to
    [b, k] candidates written straight to its output slot — no cross-step
    scratch and no threshold gate, so the score tile can narrow as the
    scan batch grows (the running-scratch kernel is pinned to
    [b, SCORE_TILE] and stops fitting VMEM past ~256 rows). A final
    [b, grid * k] lax.top_k outside the kernel merges the blocks; the
    candidate traffic is k/tile of the score matrix, so HBM stays
    item-bound."""
    block = pl.program_id(0)
    b = q_ref.shape[0]
    neg_inf = jnp.float32(-jnp.inf)
    int_max = jnp.int32(2**31 - 1)
    q = q_ref[:]
    qn = None
    if cosine:
        qn = jnp.sqrt(
            jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32), axis=1, keepdims=True)
        )
    local_cols = jax.lax.broadcasted_iota(jnp.int32, (b, tile), 1)
    best_v = jnp.full((b, k), neg_inf, jnp.float32)
    best_i = jnp.zeros((b, k), jnp.int32)
    for s in range(subtiles):
        base = block * (tile * subtiles) + s * tile
        scores = _score_tile(
            q,
            mat_ref[:, s * tile : (s + 1) * tile],
            aux_ref[:, s * tile : (s + 1) * tile],
            qn,
            cosine=cosine,
            quantized=quantized,
        )
        scores = jnp.where(local_cols < n_items - base, scores, neg_inf)
        vals_cols, idx_cols = _tile_topk(scores, local_cols, base, k, int_max, neg_inf)
        best_v, best_i = _merge_topk(
            best_v, best_i, vals_cols, idx_cols, k, int_max, neg_inf
        )
    vals_ref[...] = best_v[None]
    idx_ref[...] = best_i[None]


def _scan_k(k: int, n_items: int, resid) -> int:
    """Candidates the scan keeps per query before the residual rescore
    trims back to k. Capped at MAX_KERNEL_K so the oversampled scan stays
    on the kernel paths."""
    if resid is None or RESCORE_OVERSAMPLE <= 1:
        return k
    m = min(max(RESCORE_OVERSAMPLE * k, 32), MAX_KERNEL_K, n_items)
    return max(m, k)


def _rescore_topk(vals, idxs, q, qn, resid, resid_scales, norms, *, k, cosine):
    """Trim oversampled int8 candidates to the final top-k by adding the
    residual plane's contribution: gather the candidates' residual codes
    (a few KB — never the whole plane), one tiny batched dot, re-rank.
    Candidates are re-sorted by item id first so the stable top_k keeps
    breaking ties toward the lowest index."""
    order = jnp.argsort(idxs, axis=1)
    ii = jnp.take_along_axis(idxs, order, axis=1)  # [b, m] ascending ids
    vv = jnp.take_along_axis(vals, order, axis=1)
    cand = jnp.take(resid, ii, axis=1).astype(jnp.float32)  # [kf, b, m]
    corr = jnp.einsum(
        "bf,fbm->bm", q, cand, precision=jax.lax.Precision.HIGHEST
    )
    aux2 = resid_scales[0]
    if cosine:
        aux2 = aux2 / jnp.maximum(norms[0], 1e-12)
    corr = corr * aux2[ii]
    if cosine:
        corr = corr / jnp.maximum(qn, 1e-12)
    # padding candidates carry -inf from the scan; keep them out
    sc = jnp.where(jnp.isfinite(vv), vv + corr, -jnp.inf)
    v, pos = jax.lax.top_k(sc, k)
    return v, jnp.take_along_axis(ii, pos, axis=1)




@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "interpret", "download_dtype")
)
def _streaming_topk_multi(
    mat_t, norms, scales, resid, resid_scales, queries_kb, *,
    k, n_items, cosine, interpret, download_dtype=None,
):
    """K full-matrix scans in ONE dispatch: lax.map runs the pallas scan
    sequentially over [K, b, feat] query groups inside a single jitted
    program. Host dispatch + tunnel round-trip are paid once per K scans
    instead of once per scan — the difference between dispatch-bound
    hundreds of scans/s and bandwidth-bound thousands on a remote chip.
    Returns (vals [K, b, k], idxs [K, b, k]); ``download_dtype`` rounds
    the returned scores (selection itself always runs in f32) so a
    result-byte-bound link ships 6 B/hit instead of 8."""

    def one(q):
        return _streaming_topk_impl(
            mat_t, norms, scales, resid, resid_scales, q,
            k=k, n_items=n_items, cosine=cosine, interpret=interpret,
        )

    vals, idxs = jax.lax.map(one, queries_kb)
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "interpret", "download_dtype")
)
def _streaming_topk(
    mat_t, norms, scales, resid, resid_scales, queries, *,
    k, n_items, cosine, interpret, download_dtype=None,
):
    vals, idxs = _streaming_topk_impl(
        mat_t, norms, scales, resid, resid_scales, queries,
        k=k, n_items=n_items, cosine=cosine, interpret=interpret,
    )
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


_VMEM_BUDGET = 16 * 2**20  # v5e scoped-vmem limit (measured)


def _subtiles_for(k_feat: int, b: int, dtype_bytes: int) -> int:
    """Largest power-of-two sub-tile count (<= SUBTILES, divides BLOCK_N)
    whose working set fits scoped VMEM. Calibrated against measured
    compile outcomes: ~ b*TILE*8 (score+iota tiles) + 2*k_feat*TILE*s*
    dtype (double-buffered item block) + ~4MB of temps."""
    s = SUBTILES
    while s > 1 and (
        b * SCORE_TILE * 8 + 2 * k_feat * SCORE_TILE * s * dtype_bytes + 4 * 2**20
        > _VMEM_BUDGET - 256 * 1024  # headroom: the calibration is +/- a few %
    ):
        s //= 2
    return s


def _candidates_tile_for(k_feat: int, b: int, dtype_bytes: int) -> int:
    """Score-tile width for the block-local candidates kernel: halve from
    SCORE_TILE until the [b, tile] score + iota tiles and the item block
    fit scoped VMEM (same calibration as ``_subtiles_for``). Power-of-two
    halving keeps tile * SUBTILES a divisor of BLOCK_N, so the grid stays
    exact for any padded item count."""
    tile = SCORE_TILE
    while tile > 256 and (
        b * tile * 8 + 2 * k_feat * tile * SUBTILES * dtype_bytes + 4 * 2**20
        > _VMEM_BUDGET - 256 * 1024
    ):
        tile //= 2
    return tile


def _fold_aux(norms, scales, cosine: bool):
    """The kernel's third operand: item norms (unquantized) or the folded
    dequant multiplier (quantized — cosine divides the cached norms into
    the per-row scale here, outside the kernel, so scoring is one
    multiply either way)."""
    if scales is None:
        return norms
    if cosine:
        return scales / jnp.maximum(norms, 1e-12)
    return scales


def _pad_queries(q, k_feat: int):
    # int8 sublane padding: the handle's feature dim is a 32-multiple;
    # zero-pad queries to match (zero features cannot change any score)
    if q.shape[1] < k_feat:
        q = jnp.pad(q, ((0, 0), (0, k_feat - q.shape[1])))
    return q


def _streaming_topk_impl(
    mat_t, norms, scales, resid, resid_scales, queries, *,
    k, n_items, cosine, interpret,
):
    k_feat, n_pad = mat_t.shape
    b = queries.shape[0]
    quantized = scales is not None
    q = _pad_queries(queries.astype(jnp.float32 if quantized else mat_t.dtype), k_feat)
    aux = _fold_aux(norms, scales, cosine)
    m = _scan_k(k, n_items, resid)

    def finish(vals, idxs):
        if resid is None or RESCORE_OVERSAMPLE <= 1:
            return vals, idxs
        qn = (
            jnp.linalg.norm(q.astype(jnp.float32), axis=1, keepdims=True)
            if cosine
            else None
        )
        return _rescore_topk(
            vals, idxs, q.astype(jnp.float32), qn, resid, resid_scales, norms,
            k=k, cosine=cosine,
        )
    common = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    if pltpu is None:  # pragma: no cover - jax builds without pallas-tpu
        raise RuntimeError(
            "streaming top-k needs jax.experimental.pallas.tpu (scratch "
            "state); use the XLA handle (upload(streaming=False)) instead"
        )
    if b > LOCAL_TOPK_BATCH:
        # block-local candidates: per-block [b, k] tiles + one final merge
        tile = _candidates_tile_for(k_feat, b, mat_t.dtype.itemsize)
        step = tile * SUBTILES
        grid = n_pad // step
        kernel = functools.partial(
            _topn_candidates_kernel, k=m, n_items=n_items, cosine=cosine,
            quantized=quantized, subtiles=SUBTILES, tile=tile,
        )
        vals_c, idx_c = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((b, k_feat), lambda i: (0, 0), **common),
                pl.BlockSpec((k_feat, step), lambda i: (0, i), **common),
                pl.BlockSpec((1, step), lambda i: (0, i), **common),
            ],
            out_specs=[
                pl.BlockSpec((1, b, m), lambda i: (i, 0, 0), **common),
                pl.BlockSpec((1, b, m), lambda i: (i, 0, 0), **common),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((grid, b, m), jnp.float32),
                jax.ShapeDtypeStruct((grid, b, m), jnp.int32),
            ],
            interpret=interpret,
        )(q, mat_t, aux)
        allv = jnp.moveaxis(vals_c, 0, 1).reshape(b, grid * m)
        alli = jnp.moveaxis(idx_c, 0, 1).reshape(b, grid * m)
        vals, pos = jax.lax.top_k(allv, m)
        return finish(vals, jnp.take_along_axis(alli, pos, axis=1))
    # adapt sub-tiles to the feature width so wide models (250-feat) still
    # fit scoped VMEM; n_pad is a BLOCK_N multiple, so any power-of-two
    # divisor of SUBTILES keeps the grid exact
    subtiles = _subtiles_for(k_feat, b, mat_t.dtype.itemsize)
    step = SCORE_TILE * subtiles
    grid = n_pad // step
    kernel = functools.partial(
        _topn_kernel, k=m, n_items=n_items, cosine=cosine, quantized=quantized,
        grid=grid, subtiles=subtiles,
    )
    scratch = [pltpu.VMEM((b, m), jnp.float32), pltpu.VMEM((b, m), jnp.int32)]
    vals, idxs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((b, k_feat), lambda i: (0, 0), **common),
            pl.BlockSpec((k_feat, step), lambda i: (0, i), **common),
            pl.BlockSpec((1, step), lambda i: (0, i), **common),
        ],
        out_specs=[
            pl.BlockSpec((b, m), lambda i: (0, 0), **common),
            pl.BlockSpec((b, m), lambda i: (0, 0), **common),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, m), jnp.float32),
            jax.ShapeDtypeStruct((b, m), jnp.int32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(q, mat_t, aux)
    return finish(vals, idxs)


# -- XLA twin of the blocked scan (non-TPU backends) --------------------------


def _xla_scan_step(n_pad: int) -> int:
    """Largest BLOCK_N multiple that divides ``n_pad``, capped at
    XLA_SCAN_BLOCK — keeps the lax.scan grid exact without re-padding."""
    m = n_pad // BLOCK_N
    d = max(1, min(XLA_SCAN_BLOCK // BLOCK_N, m))
    while m % d:
        d -= 1
    return BLOCK_N * d


def _xla_streaming_topk_impl(
    mat_t, norms, scales, resid, resid_scales, queries, *, k, n_items, cosine
):
    """Fused XLA blocked scan over the feature-major layout: lax.scan
    streams [k_feat, block] item slices and reduces each block on the
    spot, so the [b, n] score matrix never materializes — which is what
    lets scan batches grow past the memory of the naive matmul+top_k
    path. f32/bf16 handles top-k each block exactly and merge the
    [b, grid * k] candidates with one tiny lax.top_k. int8 handles
    (upcast to f32 before the dot — XLA CPU int8 matmul is ~3x slower
    than upcast + f32 GEMM, measured) reduce each block to per-_CHUNK
    maxes instead: the max fuses into the GEMM's epilogue where a wide
    in-scan lax.top_k does not (measured ~2x the scan time), the top-m
    chunks by max provably contain the top-m items, and only those
    chunks' columns gather both int8 planes for an exact ~14-bit rescore
    after the scan. HIGHEST precision keeps the f32 GEMM on the fast CPU
    path (the DEFAULT-precision CPU kernel is ~2x slower, measured)."""
    k_feat, n_pad = mat_t.shape
    b = queries.shape[0]
    quantized = scales is not None
    q = _pad_queries(queries.astype(jnp.float32), k_feat)
    qn = jnp.linalg.norm(q, axis=1, keepdims=True) if cosine else None
    mult = _fold_aux(norms, scales, cosine) if quantized else None
    block = _xla_scan_step(n_pad)
    grid = n_pad // block
    m = _scan_k(k, n_items, resid)
    chunked = (
        quantized
        and resid is not None
        and RESCORE_OVERSAMPLE > 1
        and block % _CHUNK == 0
        and block // _CHUNK >= _chunk_k(k, block // _CHUNK)
    )
    # padding mask as an ADDITIVE bias, not a per-element where: the
    # iota-compare-select breaks the GEMM epilogue fusion and costs ~3x
    # the GEMM itself (measured: +1.1 s/dispatch at 1M x 50); a broadcast
    # add of a constant-folded [-inf over padded cols] row fuses like the
    # scale multiply does. Padded columns are all-zero so their dot is
    # finite (0) and 0 + -inf = -inf, never NaN.
    bias = jnp.where(
        jnp.arange(n_pad, dtype=jnp.int32) < n_items, 0.0, -jnp.inf
    )[None, :].astype(jnp.float32)

    def scores_for(i):
        base = i * block
        blk = jax.lax.dynamic_slice(mat_t, (0, base), (k_feat, block))
        scores = jnp.dot(
            q,
            blk.astype(jnp.float32),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if quantized:
            scores = scores * jax.lax.dynamic_slice(mult, (0, base), (1, block))
            if cosine:
                scores = scores / jnp.maximum(qn, 1e-12)
        elif cosine:
            nrm = jax.lax.dynamic_slice(norms, (0, base), (1, block))
            scores = scores / jnp.maximum(nrm * qn, 1e-12)
        return scores + jax.lax.dynamic_slice(bias, (0, base), (1, block))

    if not chunked:
        kk = min(k, block)

        def step(carry, i):
            v, p = jax.lax.top_k(scores_for(i), kk)
            return carry, (v, p + i * block)

        _, (vs, idxs) = jax.lax.scan(step, 0, jnp.arange(grid, dtype=jnp.int32))
        # candidates are ordered (block, rank): for equal scores the
        # earlier position is the earlier block / lower item id, and
        # lax.top_k is stable — so ties break by lowest index, same as
        # the kernel
        allv = jnp.moveaxis(vs, 0, 1).reshape(b, grid * kk)
        alli = jnp.moveaxis(idxs, 0, 1).reshape(b, grid * kk)
        vals, pos = jax.lax.top_k(allv, min(k, allv.shape[1]))
        return vals, jnp.take_along_axis(alli, pos, axis=1)

    chunks = block // _CHUNK
    kc = _chunk_k(k, chunks)

    def step(carry, i):
        cm = jnp.max(scores_for(i).reshape(b, chunks, _CHUNK), axis=2)
        v, p = jax.lax.top_k(cm, kc)
        return carry, (v, p + i * chunks)

    _, (vs, cps) = jax.lax.scan(step, 0, jnp.arange(grid, dtype=jnp.int32))
    poolv = jnp.moveaxis(vs, 0, 1).reshape(b, grid * kc)
    pooli = jnp.moveaxis(cps, 0, 1).reshape(b, grid * kc)
    return _chunk_tail(
        mat_t, resid, scales, resid_scales, norms, q, qn, poolv, pooli,
        k=k, kc=kc, n_items=n_items, cosine=cosine,
    )


def _gathered_pair_scores(
    mat_t, resid, scales, resid_scales, norms, q, qn, iid, *, cosine
):
    """Exact ~14-bit two-plane scores for an explicit candidate column set
    ``iid`` [b, m]: gather BOTH int8 planes for just those columns and
    combine ``d1*s1 + d2*s2``. Shared by the chunked scan's candidate tail
    and the IVF tier's probed-cell scan (ops/ivf.py) — sharing the exact
    arithmetic (same gather layout, same einsum contraction) is what lets
    a full-probe IVF scan reproduce the exact path's scores bit-for-bit."""
    c1 = jnp.take(mat_t, iid, axis=1).astype(jnp.float32)  # [kf, b, m]
    c2 = jnp.take(resid, iid, axis=1).astype(jnp.float32)
    d1 = jnp.einsum("bf,fbm->bm", q, c1, precision=jax.lax.Precision.HIGHEST)
    d2 = jnp.einsum("bf,fbm->bm", q, c2, precision=jax.lax.Precision.HIGHEST)
    sc = d1 * scales[0][iid] + d2 * resid_scales[0][iid]
    if cosine:
        sc = sc / jnp.maximum(norms[0][iid] * qn, 1e-12)
    return sc


def _chunk_tail(
    mat_t, resid, scales, resid_scales, norms, q, qn, poolv, pooli, *,
    k, kc, n_items, cosine,
):
    """Candidate stage of the chunked scan: keep the globally best chunks
    from the pooled per-block chunk maxes, gather BOTH int8 planes for
    just their columns, and pick the final top-k from exact ~14-bit
    two-plane scores."""
    b = q.shape[0]
    mc = min(kc, poolv.shape[1])
    _, sel = jax.lax.top_k(poolv, mc)
    # ascending chunk ids -> ascending item ids, so the stable final
    # top_k keeps breaking ties toward the lowest item id
    cid = jnp.sort(jnp.take_along_axis(pooli, sel, axis=1), axis=1)
    iid = (
        cid[:, :, None] * _CHUNK + jnp.arange(_CHUNK, dtype=jnp.int32)[None, None, :]
    ).reshape(b, mc * _CHUNK)
    sc = _gathered_pair_scores(
        mat_t, resid, scales, resid_scales, norms, q, qn, iid, cosine=cosine
    )
    sc = jnp.where(iid < n_items, sc, -jnp.inf)
    v, pos = jax.lax.top_k(sc, k)
    return v, jnp.take_along_axis(iid, pos, axis=1)


def _xla_streaming_topk_multi_impl(
    mat_t, norms, scales, resid, resid_scales, q_kbf, *, k, n_items, cosine
):
    """K fused scans sharing ONE pass of int8->f32 block conversion. The
    naive multi path (lax.map of the single impl) re-converts every item
    block once per query group, and at wide features that conversion is
    ~50% on top of the pure f32 GEMM (measured per-block 15.5 ms mixed
    vs 10.4 ms f32 x f32 at 256x16384) — so the loops invert here: the
    lax.scan over blocks is OUTSIDE and the K group GEMMs unroll INSIDE
    the step, all reading the same materialized f32 block. Per-group
    score tiles stay [b, block] (the merged [K*b, block] tile blows the
    LLC — measured 3x slowdown at 512 rows), and the candidate tails
    stay per-group after the scan. Non-chunked handles (f32/bf16, tiny
    matrices) keep the exact lax.map path."""
    kg, b, _ = q_kbf.shape
    k_feat, n_pad = mat_t.shape
    block = _xla_scan_step(n_pad)
    grid = n_pad // block
    chunks = block // _CHUNK
    chunked = (
        scales is not None
        and resid is not None
        and RESCORE_OVERSAMPLE > 1
        and block % _CHUNK == 0
        and chunks >= _chunk_k(k, chunks)
    )
    if not chunked:
        def one(q):
            return _xla_streaming_topk_impl(
                mat_t, norms, scales, resid, resid_scales, q,
                k=k, n_items=n_items, cosine=cosine,
            )

        return jax.lax.map(one, q_kbf)

    kc = _chunk_k(k, chunks)
    q_k = _pad_queries(
        q_kbf.astype(jnp.float32).reshape(kg * b, -1), k_feat
    ).reshape(kg, b, k_feat)
    qn_k = (
        jnp.linalg.norm(q_k, axis=2, keepdims=True) if cosine else [None] * kg
    )
    mult = _fold_aux(norms, scales, cosine)
    bias = jnp.where(
        jnp.arange(n_pad, dtype=jnp.int32) < n_items, 0.0, -jnp.inf
    )[None, :].astype(jnp.float32)

    def step(carry, i):
        base = i * block
        blk = jax.lax.dynamic_slice(
            mat_t, (0, base), (k_feat, block)
        ).astype(jnp.float32)
        m_b = jax.lax.dynamic_slice(mult, (0, base), (1, block))
        bia = jax.lax.dynamic_slice(bias, (0, base), (1, block))
        vs, ps = [], []
        for g in range(kg):  # static unroll: kg GEMMs share blk
            sc = (
                jnp.dot(
                    q_k[g], blk,
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST,
                )
                * m_b
            )
            if cosine:
                sc = sc / jnp.maximum(qn_k[g], 1e-12)
            cm = jnp.max((sc + bia).reshape(b, chunks, _CHUNK), axis=2)
            v, p = jax.lax.top_k(cm, kc)
            vs.append(v)
            ps.append(p + i * chunks)
        return carry, (jnp.stack(vs), jnp.stack(ps))

    _, (vs, cps) = jax.lax.scan(step, 0, jnp.arange(grid, dtype=jnp.int32))
    poolv = jnp.transpose(vs, (1, 2, 0, 3)).reshape(kg, b, grid * kc)
    pooli = jnp.transpose(cps, (1, 2, 0, 3)).reshape(kg, b, grid * kc)
    outs = [
        _chunk_tail(
            mat_t, resid, scales, resid_scales, norms, q_k[g], qn_k[g],
            poolv[g], pooli[g], k=k, kc=kc, n_items=n_items, cosine=cosine,
        )
        for g in range(kg)
    ]
    return jnp.stack([v for v, _ in outs]), jnp.stack([i for _, i in outs])


@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "download_dtype")
)
def _xla_streaming_topk(
    mat_t, norms, scales, resid, resid_scales, queries, *,
    k, n_items, cosine, download_dtype=None,
):
    vals, idxs = _xla_streaming_topk_impl(
        mat_t, norms, scales, resid, resid_scales, queries,
        k=k, n_items=n_items, cosine=cosine,
    )
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "download_dtype")
)
def _xla_streaming_topk_multi(
    mat_t, norms, scales, resid, resid_scales, queries_kb, *,
    k, n_items, cosine, download_dtype=None,
):
    vals, idxs = _xla_streaming_topk_multi_impl(
        mat_t, norms, scales, resid, resid_scales, queries_kb,
        k=k, n_items=n_items, cosine=cosine,
    )
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "download_dtype")
)
def _xla_streaming_topk_multi_indexed(
    mat_t, norms, scales, resid, resid_scales, x_dev, idx_kb, *,
    k, n_items, cosine, download_dtype=None,
):
    vals, idxs = _xla_streaming_topk_multi_impl(
        mat_t, norms, scales, resid, resid_scales,
        x_dev[idx_kb].astype(jnp.float32),
        k=k, n_items=n_items, cosine=cosine,
    )
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


# above this k the kernel's unrolled per-block selection stops paying for
# itself (and compile time grows with k); fall back to one XLA top_k
MAX_KERNEL_K = 128


@functools.partial(jax.jit, static_argnames=("k", "n_items", "cosine"))
def _materialized_topk(
    mat_t, norms, scales, resid, resid_scales, queries, *, k, n_items, cosine
):
    """Large-k fallback over the same feature-major layout: materialize
    [b, n] scores once and let XLA's top_k handle the wide selection.
    Quantized handles sum both planes in full here — at k > MAX_KERNEL_K
    the oversample-then-rescore shape stops paying for itself."""
    quantized = scales is not None
    q = _pad_queries(
        queries.astype(jnp.float32 if quantized else mat_t.dtype), mat_t.shape[0]
    )
    mat = mat_t.astype(jnp.float32) if quantized else mat_t
    scores = jnp.dot(
        q, mat, preferred_element_type=jnp.float32,
        precision=_dot_precision_for(q, quantized),
    )
    if quantized:
        scores = scores * scales
        if resid is not None:
            scores = scores + jnp.dot(
                q, resid.astype(jnp.float32),
                preferred_element_type=jnp.float32,
                precision=_dot_precision_for(q, quantized),
            ) * resid_scales
    if cosine:
        qn = jnp.linalg.norm(queries.astype(jnp.float32), axis=1, keepdims=True)
        scores = scores / jnp.maximum(norms * qn, 1e-12)
    cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(cols < n_items, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def _use_xla_scan(interpret) -> bool:
    """Non-TPU backends with no explicit interpret request run the XLA
    twin of the blocked scan; ``interpret=True`` always forces the Pallas
    interpreter (the parity test suite), and TPU compiles the kernel."""
    return interpret is None and jax.default_backend() != "tpu"


def top_k_streaming_device(
    up: StreamingItemMatrix,
    queries: np.ndarray,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
    download_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [b, k], indices [b, k]) as device arrays — the async
    building block. ``interpret=None`` picks per backend: the compiled
    kernel on TPU, the fused XLA blocked scan elsewhere."""
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    k = max(1, min(int(k), up.n_items))
    if k > MAX_KERNEL_K:
        vals, idxs = _materialized_topk(
            up.mat_t, up.norms, up.scales, up.resid, up.resid_scales,
            jnp.asarray(q), k=k, n_items=up.n_items, cosine=cosine,
        )
        return (vals.astype(download_dtype) if download_dtype is not None else vals), idxs
    if _use_xla_scan(interpret):
        return _xla_streaming_topk(
            up.mat_t, up.norms, up.scales, up.resid, up.resid_scales,
            jnp.asarray(q),
            k=k, n_items=up.n_items, cosine=cosine, download_dtype=download_dtype,
        )
    return _streaming_topk(
        up.mat_t,
        up.norms,
        up.scales,
        up.resid,
        up.resid_scales,
        jnp.asarray(q),
        k=k,
        n_items=up.n_items,
        cosine=cosine,
        interpret=bool(interpret),
        download_dtype=download_dtype,
    )


def top_k_streaming_device_multi(
    up: StreamingItemMatrix,
    queries_kb: jax.Array,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
    download_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [K, b, k], indices [K, b, k]) for [K, b, feat] query
    groups — K full-matrix scans fused into one dispatch."""
    k = max(1, min(int(k), up.n_items))
    if _use_xla_scan(interpret):
        return _xla_streaming_topk_multi(
            up.mat_t, up.norms, up.scales, up.resid, up.resid_scales, queries_kb,
            k=k, n_items=up.n_items, cosine=cosine, download_dtype=download_dtype,
        )
    return _streaming_topk_multi(
        up.mat_t,
        up.norms,
        up.scales,
        up.resid,
        up.resid_scales,
        queries_kb,
        k=k,
        n_items=up.n_items,
        cosine=cosine,
        interpret=bool(interpret),
        download_dtype=download_dtype,
    )


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_items", "cosine", "interpret", "download_dtype"),
)
def _streaming_topk_multi_indexed(
    mat_t, norms, scales, resid, resid_scales, x_dev, idx_kb, *,
    k, n_items, cosine, interpret, download_dtype=None,
):
    """Index-submitted fused multi-scan: gather the [K, b, feat] query
    group from the device-resident ``x_dev`` inside the dispatch, then
    run the same per-group pallas scan."""

    def one(idx_b):
        q = x_dev[idx_b].astype(jnp.float32)
        return _streaming_topk_impl(
            mat_t, norms, scales, resid, resid_scales, q,
            k=k, n_items=n_items, cosine=cosine, interpret=interpret,
        )

    vals, idxs = jax.lax.map(one, idx_kb)
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


def top_k_streaming_device_multi_indexed(
    up: StreamingItemMatrix,
    x_dev: jax.Array,
    idx_kb: jax.Array,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
    download_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [K, b, k], indices [K, b, k]) for [K, b] int32 row indices
    into the device-resident query matrix ``x_dev`` — the uplink carries
    4 B/query instead of a full vector."""
    k = max(1, min(int(k), up.n_items))
    if _use_xla_scan(interpret):
        return _xla_streaming_topk_multi_indexed(
            up.mat_t, up.norms, up.scales, up.resid, up.resid_scales, x_dev, idx_kb,
            k=k, n_items=up.n_items, cosine=cosine, download_dtype=download_dtype,
        )
    return _streaming_topk_multi_indexed(
        up.mat_t,
        up.norms,
        up.scales,
        up.resid,
        up.resid_scales,
        x_dev,
        idx_kb,
        k=k,
        n_items=up.n_items,
        cosine=cosine,
        interpret=bool(interpret),
        download_dtype=download_dtype,
    )


def top_k_streaming(
    up: StreamingItemMatrix,
    queries: np.ndarray,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """(indices [b, k], scores [b, k]) of the best items per query row."""
    vals, idxs = top_k_streaming_device(up, queries, k, cosine=cosine, interpret=interpret)
    return np.asarray(idxs), np.asarray(vals)
