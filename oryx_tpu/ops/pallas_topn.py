"""Pallas TPU kernel: fused item-scoring + top-k for ALS serving.

The serving hot loop is "score every item against a user vector, keep the
best k" (reference: ALSServingModel.topN / TopNConsumer.java scanning LSH
partitions on a thread pool, VectorMath.dot per item). On TPU the exact
scan is one matmul — but the naive XLA program (``scores = Q @ Y.T`` then
``lax.top_k``) writes the full [b, n_items] score matrix to HBM and reads
it back for the top-k, which at 1M+ items costs more bandwidth than
reading the item matrix itself. This kernel fuses the two:

- the item matrix is laid out feature-major ``[k_feat, n_items]`` so each
  grid step streams a contiguous ``[k_feat, BLOCK_N]`` block of items
  through VMEM (Mosaic double-buffers blocks across the grid);
- each step computes ``[b, BLOCK_N]`` scores on the MXU with float32
  accumulation (items may be stored bfloat16, halving HBM traffic);
- a statically-unrolled iterative max reduces the block to its local
  top-k (k is small: 10..a few hundred) entirely in VMEM;
- only ``[num_blocks, b, k]`` candidates ever reach HBM; a final tiny
  ``lax.top_k`` over ``num_blocks * k`` merges them.

HBM traffic per batch drops from ``n*k_feat*4 + 2*b*n*4`` bytes to
``n*k_feat*{2|4}`` — a 2-6x win for the bandwidth-bound scan.

Cosine scoring divides by cached item norms in-kernel (an extra
``[1, BLOCK_N]`` f32 stream, ~2% overhead) so ranking happens on the
normalized scores, matching CosineAverageFunction.java semantics.

On non-TPU backends the public entry points fall back to plain XLA ops;
``interpret=True`` runs the kernel under the Pallas interpreter (used by
the CPU test suite).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some CPU-only builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

# Score-tile width. [b=256, 4096] f32 scores + the iota/mask temps fit
# the 16 MB scoped-VMEM limit of a v5e; 8192 does not (measured 20.7 MB).
import os as _os

SCORE_TILE = int(_os.environ.get("ORYX_TOPN_BLOCK", 4096))
# Sub-tiles streamed per grid step: the item block per step is
# [k_feat, SCORE_TILE * SUBTILES] (bf16, ~1.6 MB at 4) while the
# score/iota tiles stay SCORE_TILE wide — grid-step orchestration costs
# ~20us on a v5e, so fewer, fatter steps is most of the kernel's speed
# (measured 5.5 ms -> 0.17 ms per 1M x 50 scan going 1 -> 4). 8 exceeds
# the 16 MB scoped-VMEM limit at b=256.
SUBTILES = int(_os.environ.get("ORYX_TOPN_SUBTILES", 4))
BLOCK_N = SCORE_TILE * SUBTILES  # items consumed per grid step


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclass(frozen=True)
class StreamingItemMatrix:
    """Device-resident item factors in the kernel's feature-major layout."""

    mat_t: jax.Array  # [k_feat, n_padded], f32 or bf16
    norms: jax.Array  # [1, n_padded] f32 (row L2 norms, 0-padded)
    n_items: int

    @property
    def num_features(self) -> int:
        return self.mat_t.shape[0]


def upload_streaming(matrix: np.ndarray, dtype=jnp.float32) -> StreamingItemMatrix:
    """Pad items up to a BLOCK_N multiple and move [k, n] to device."""
    n, _k = matrix.shape
    n_pad = max(BLOCK_N, _ceil_to(n, BLOCK_N))
    mat = np.asarray(matrix, dtype=np.float32)
    norms = np.zeros((1, n_pad), dtype=np.float32)
    norms[0, :n] = np.linalg.norm(mat, axis=1)
    mat_t = np.zeros((matrix.shape[1], n_pad), dtype=np.float32)
    mat_t[:, :n] = mat.T
    return StreamingItemMatrix(
        mat_t=jnp.asarray(mat_t, dtype=dtype),
        norms=jnp.asarray(norms),
        n_items=n,
    )


def _topn_kernel(
    q_ref, mat_ref, norms_ref, vals_ref, idx_ref, vstate, istate, *,
    k, n_items, cosine, grid, subtiles
):
    """One grid step: score a [k_feat, BLOCK_N] item block and fold it
    into the running top-k carried in VMEM scratch across grid steps.

    The k-pass selection is ~40 VPU ops per score — 10x the cost of the
    matmul that produced them — so the kernel keeps the running k-th-best
    as a threshold and SKIPS selection for blocks whose max cannot enter
    the top-k. With a randomly ordered item matrix only O(k log grid) of
    the blocks pass the gate, which turns the scan from selection-bound
    (~4ms at 1M x 50) into matmul/HBM-bound."""
    block = pl.program_id(0)
    b = q_ref.shape[0]
    neg_inf = jnp.float32(-jnp.inf)
    int_max = jnp.int32(2**31 - 1)

    @pl.when(block == 0)
    def _():
        vstate[...] = jnp.full((b, k), neg_inf, jnp.float32)
        istate[...] = jnp.zeros((b, k), jnp.int32)

    q = q_ref[:]  # [b, k_feat]
    # f32 items get true f32 accumulation (TPU default would silently drop
    # to bf16 passes); bf16 items are the intentional fast path
    precision = (
        jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else jax.lax.Precision.DEFAULT
    )
    qn = None
    if cosine:
        qn = jnp.sqrt(
            jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32), axis=1, keepdims=True)
        )
    # local (per-tile) column ids: one [b, SCORE_TILE] iota reused by every
    # sub-tile keeps VMEM at two tiles regardless of how many sub-tiles a
    # grid step streams; the global item id is base + local.
    local_cols = jax.lax.broadcasted_iota(jnp.int32, (b, SCORE_TILE), 1)
    for s in range(subtiles):  # unrolled: static sub-tile slices
        base = block * (SCORE_TILE * subtiles) + s * SCORE_TILE
        scores = jnp.dot(
            q,
            mat_ref[:, s * SCORE_TILE : (s + 1) * SCORE_TILE],
            preferred_element_type=jnp.float32,
            precision=precision,
        )  # [b, SCORE_TILE]
        if cosine:
            norms_s = norms_ref[:, s * SCORE_TILE : (s + 1) * SCORE_TILE]
            scores = scores / jnp.maximum(norms_s * qn, 1e-12)
        scores = jnp.where(local_cols < n_items - base, scores, neg_inf)
        kth = vstate[...][:, k - 1 : k]  # worst of the running top-k, [b, 1]
        need = jnp.any(jnp.max(scores, axis=1, keepdims=True) > kth)

        @pl.when(need)
        def _(scores=scores, base=base):
            sc = scores
            vals_cols = []
            idx_cols = []
            for _ in range(k):  # k is small and static: unrolled iterative max
                m = jnp.max(sc, axis=1, keepdims=True)  # [b, 1]
                # first column index attaining the max (ties -> lowest id,
                # like a stable host scan)
                at = jnp.min(
                    jnp.where(sc == m, local_cols, int_max), axis=1, keepdims=True
                )
                vals_cols.append(m)
                idx_cols.append(at + base)
                sc = jnp.where(local_cols == at, neg_inf, sc)
            # merge the tile's top-k into the running state: k passes over
            # [b, 2k] (tiny). Ties prefer the smaller item index, which is
            # always the earlier tile — same result as a stable global merge.
            cat_v = jnp.concatenate([vstate[...]] + vals_cols, axis=1)
            cat_i = jnp.concatenate([istate[...]] + idx_cols, axis=1)
            new_v = []
            new_i = []
            for _ in range(k):
                m = jnp.max(cat_v, axis=1, keepdims=True)
                sel = jnp.min(
                    jnp.where(cat_v == m, cat_i, int_max), axis=1, keepdims=True
                )
                new_v.append(m)
                new_i.append(sel)
                cat_v = jnp.where((cat_v == m) & (cat_i == sel), neg_inf, cat_v)
            vstate[...] = jnp.concatenate(new_v, axis=1)
            istate[...] = jnp.concatenate(new_i, axis=1)

    @pl.when(block == grid - 1)
    def _():
        vals_ref[...] = vstate[...]
        idx_ref[...] = istate[...]


@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "interpret", "download_dtype")
)
def _streaming_topk_multi(
    mat_t, norms, queries_kb, *, k, n_items, cosine, interpret, download_dtype=None
):
    """K full-matrix scans in ONE dispatch: lax.map runs the pallas scan
    sequentially over [K, b, feat] query groups inside a single jitted
    program. Host dispatch + tunnel round-trip are paid once per K scans
    instead of once per scan — the difference between dispatch-bound
    hundreds of scans/s and bandwidth-bound thousands on a remote chip.
    Returns (vals [K, b, k], idxs [K, b, k]); ``download_dtype`` rounds
    the returned scores (selection itself always runs in f32) so a
    result-byte-bound link ships 6 B/hit instead of 8."""

    def one(q):
        return _streaming_topk_impl(
            mat_t, norms, q, k=k, n_items=n_items, cosine=cosine, interpret=interpret
        )

    vals, idxs = jax.lax.map(one, queries_kb)
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "interpret", "download_dtype")
)
def _streaming_topk(
    mat_t, norms, queries, *, k, n_items, cosine, interpret, download_dtype=None
):
    vals, idxs = _streaming_topk_impl(
        mat_t, norms, queries, k=k, n_items=n_items, cosine=cosine, interpret=interpret
    )
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


_VMEM_BUDGET = 16 * 2**20  # v5e scoped-vmem limit (measured)


def _subtiles_for(k_feat: int, b: int, dtype_bytes: int) -> int:
    """Largest power-of-two sub-tile count (<= SUBTILES, divides BLOCK_N)
    whose working set fits scoped VMEM. Calibrated against measured
    compile outcomes: ~ b*TILE*8 (score+iota tiles) + 2*k_feat*TILE*s*
    dtype (double-buffered item block) + ~4MB of temps."""
    s = SUBTILES
    while s > 1 and (
        b * SCORE_TILE * 8 + 2 * k_feat * SCORE_TILE * s * dtype_bytes + 4 * 2**20
        > _VMEM_BUDGET - 256 * 1024  # headroom: the calibration is +/- a few %
    ):
        s //= 2
    return s


def _streaming_topk_impl(mat_t, norms, queries, *, k, n_items, cosine, interpret):
    k_feat, n_pad = mat_t.shape
    b = queries.shape[0]
    # adapt sub-tiles to the feature width so wide models (250-feat) still
    # fit scoped VMEM; n_pad is a BLOCK_N multiple, so any power-of-two
    # divisor of SUBTILES keeps the grid exact
    subtiles = _subtiles_for(k_feat, b, mat_t.dtype.itemsize)
    step = SCORE_TILE * subtiles
    grid = n_pad // step
    kernel = functools.partial(
        _topn_kernel, k=k, n_items=n_items, cosine=cosine, grid=grid, subtiles=subtiles
    )
    common = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    if pltpu is None:  # pragma: no cover - jax builds without pallas-tpu
        raise RuntimeError(
            "streaming top-k needs jax.experimental.pallas.tpu (scratch "
            "state); use the XLA handle (upload(streaming=False)) instead"
        )
    scratch = [pltpu.VMEM((b, k), jnp.float32), pltpu.VMEM((b, k), jnp.int32)]
    vals, idxs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((b, k_feat), lambda i: (0, 0), **common),
            pl.BlockSpec((k_feat, step), lambda i: (0, i), **common),
            pl.BlockSpec((1, step), lambda i: (0, i), **common),
        ],
        out_specs=[
            pl.BlockSpec((b, k), lambda i: (0, 0), **common),
            pl.BlockSpec((b, k), lambda i: (0, 0), **common),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, k), jnp.float32),
            jax.ShapeDtypeStruct((b, k), jnp.int32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(queries.astype(mat_t.dtype), mat_t, norms)
    return vals, idxs


# above this k the kernel's unrolled per-block selection stops paying for
# itself (and compile time grows with k); fall back to one XLA top_k
MAX_KERNEL_K = 128


@functools.partial(jax.jit, static_argnames=("k", "n_items", "cosine"))
def _materialized_topk(mat_t, norms, queries, *, k, n_items, cosine):
    """Large-k fallback over the same feature-major layout: materialize
    [b, n] scores once and let XLA's top_k handle the wide selection."""
    q = queries.astype(mat_t.dtype)
    precision = (
        jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else jax.lax.Precision.DEFAULT
    )
    scores = jnp.dot(q, mat_t, preferred_element_type=jnp.float32, precision=precision)
    if cosine:
        qn = jnp.linalg.norm(queries.astype(jnp.float32), axis=1, keepdims=True)
        scores = scores / jnp.maximum(norms * qn, 1e-12)
    cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(cols < n_items, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def top_k_streaming_device(
    up: StreamingItemMatrix,
    queries: np.ndarray,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
    download_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [b, k], indices [b, k]) as device arrays — the async
    building block. ``interpret`` defaults to the Pallas interpreter on
    non-TPU backends so the same handle works everywhere."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    k = max(1, min(int(k), up.n_items))
    if k > MAX_KERNEL_K:
        vals, idxs = _materialized_topk(
            up.mat_t, up.norms, jnp.asarray(q), k=k, n_items=up.n_items, cosine=cosine
        )
        return (vals.astype(download_dtype) if download_dtype is not None else vals), idxs
    return _streaming_topk(
        up.mat_t,
        up.norms,
        jnp.asarray(q),
        k=k,
        n_items=up.n_items,
        cosine=cosine,
        interpret=interpret,
        download_dtype=download_dtype,
    )


def top_k_streaming_device_multi(
    up: StreamingItemMatrix,
    queries_kb: jax.Array,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
    download_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [K, b, k], indices [K, b, k]) for [K, b, feat] query
    groups — K full-matrix scans fused into one dispatch."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = max(1, min(int(k), up.n_items))
    return _streaming_topk_multi(
        up.mat_t,
        up.norms,
        queries_kb,
        k=k,
        n_items=up.n_items,
        cosine=cosine,
        interpret=interpret,
        download_dtype=download_dtype,
    )


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_items", "cosine", "interpret", "download_dtype"),
)
def _streaming_topk_multi_indexed(
    mat_t, norms, x_dev, idx_kb, *, k, n_items, cosine, interpret, download_dtype=None
):
    """Index-submitted fused multi-scan: gather the [K, b, feat] query
    group from the device-resident ``x_dev`` inside the dispatch, then
    run the same per-group pallas scan."""

    def one(idx_b):
        q = x_dev[idx_b].astype(jnp.float32)
        return _streaming_topk_impl(
            mat_t, norms, q, k=k, n_items=n_items, cosine=cosine, interpret=interpret
        )

    vals, idxs = jax.lax.map(one, idx_kb)
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


def top_k_streaming_device_multi_indexed(
    up: StreamingItemMatrix,
    x_dev: jax.Array,
    idx_kb: jax.Array,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
    download_dtype=None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [K, b, k], indices [K, b, k]) for [K, b] int32 row indices
    into the device-resident query matrix ``x_dev`` — the uplink carries
    4 B/query instead of a full vector."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = max(1, min(int(k), up.n_items))
    return _streaming_topk_multi_indexed(
        up.mat_t,
        up.norms,
        x_dev,
        idx_kb,
        k=k,
        n_items=up.n_items,
        cosine=cosine,
        interpret=interpret,
        download_dtype=download_dtype,
    )


def top_k_streaming(
    up: StreamingItemMatrix,
    queries: np.ndarray,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """(indices [b, k], scores [b, k]) of the best items per query row."""
    vals, idxs = top_k_streaming_device(up, queries, k, cosine=cosine, interpret=interpret)
    return np.asarray(idxs), np.asarray(vals)
