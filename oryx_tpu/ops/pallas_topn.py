"""Pallas TPU kernel: fused item-scoring + top-k for ALS serving.

The serving hot loop is "score every item against a user vector, keep the
best k" (reference: ALSServingModel.topN / TopNConsumer.java scanning LSH
partitions on a thread pool, VectorMath.dot per item). On TPU the exact
scan is one matmul — but the naive XLA program (``scores = Q @ Y.T`` then
``lax.top_k``) writes the full [b, n_items] score matrix to HBM and reads
it back for the top-k, which at 1M+ items costs more bandwidth than
reading the item matrix itself. This kernel fuses the two:

- the item matrix is laid out feature-major ``[k_feat, n_items]`` so each
  grid step streams a contiguous ``[k_feat, BLOCK_N]`` block of items
  through VMEM (Mosaic double-buffers blocks across the grid);
- each step computes ``[b, BLOCK_N]`` scores on the MXU with float32
  accumulation (items may be stored bfloat16, halving HBM traffic);
- a statically-unrolled iterative max reduces the block to its local
  top-k (k is small: 10..a few hundred) entirely in VMEM;
- only ``[num_blocks, b, k]`` candidates ever reach HBM; a final tiny
  ``lax.top_k`` over ``num_blocks * k`` merges them.

HBM traffic per batch drops from ``n*k_feat*4 + 2*b*n*4`` bytes to
``n*k_feat*{2|4}`` — a 2-6x win for the bandwidth-bound scan.

Cosine scoring divides by cached item norms in-kernel (an extra
``[1, BLOCK_N]`` f32 stream, ~2% overhead) so ranking happens on the
normalized scores, matching CosineAverageFunction.java semantics.

On non-TPU backends the public entry points fall back to plain XLA ops;
``interpret=True`` runs the kernel under the Pallas interpreter (used by
the CPU test suite).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports fail on some CPU-only builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

BLOCK_N = 4096  # items per grid step; [k_feat<=256, 4096] f32 block = 4 MB VMEM


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclass(frozen=True)
class StreamingItemMatrix:
    """Device-resident item factors in the kernel's feature-major layout."""

    mat_t: jax.Array  # [k_feat, n_padded], f32 or bf16
    norms: jax.Array  # [1, n_padded] f32 (row L2 norms, 0-padded)
    n_items: int

    @property
    def num_features(self) -> int:
        return self.mat_t.shape[0]


def upload_streaming(matrix: np.ndarray, dtype=jnp.float32) -> StreamingItemMatrix:
    """Pad items up to a BLOCK_N multiple and move [k, n] to device."""
    n, _k = matrix.shape
    n_pad = max(BLOCK_N, _ceil_to(n, BLOCK_N))
    mat = np.asarray(matrix, dtype=np.float32)
    norms = np.zeros((1, n_pad), dtype=np.float32)
    norms[0, :n] = np.linalg.norm(mat, axis=1)
    mat_t = np.zeros((matrix.shape[1], n_pad), dtype=np.float32)
    mat_t[:, :n] = mat.T
    return StreamingItemMatrix(
        mat_t=jnp.asarray(mat_t, dtype=dtype),
        norms=jnp.asarray(norms),
        n_items=n,
    )


def _topn_kernel(q_ref, mat_ref, norms_ref, vals_ref, idx_ref, *, k, n_items, cosine):
    """One grid step: score a [k_feat, BLOCK_N] item block, keep its top-k."""
    block = pl.program_id(0)
    q = q_ref[:]  # [b, k_feat]
    # f32 items get true f32 accumulation (TPU default would silently drop
    # to bf16 passes); bf16 items are the intentional fast path
    precision = (
        jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else jax.lax.Precision.DEFAULT
    )
    scores = jnp.dot(
        q, mat_ref[:], preferred_element_type=jnp.float32, precision=precision
    )  # [b, BLOCK_N]
    b = scores.shape[0]
    cols = jax.lax.broadcasted_iota(jnp.int32, (b, BLOCK_N), 1) + block * BLOCK_N
    if cosine:
        qn = jnp.sqrt(
            jnp.sum(q.astype(jnp.float32) * q.astype(jnp.float32), axis=1, keepdims=True)
        )
        denom = jnp.maximum(norms_ref[:] * qn, 1e-12)  # [b, BLOCK_N] via broadcast
        scores = scores / denom
    neg_inf = jnp.float32(-jnp.inf)
    scores = jnp.where(cols < n_items, scores, neg_inf)
    vals_cols = []
    idx_cols = []
    for _ in range(k):  # k is small and static: unrolled iterative max
        m = jnp.max(scores, axis=1, keepdims=True)  # [b, 1]
        # first column index attaining the max (ties -> lowest id, like a
        # stable host scan)
        at = jnp.min(jnp.where(scores == m, cols, jnp.int32(2**31 - 1)), axis=1, keepdims=True)
        vals_cols.append(m)
        idx_cols.append(at)
        scores = jnp.where(cols == at, neg_inf, scores)
    vals_ref[0] = jnp.concatenate(vals_cols, axis=1)  # [b, k]
    idx_ref[0] = jnp.concatenate(idx_cols, axis=1)


@functools.partial(
    jax.jit, static_argnames=("k", "n_items", "cosine", "interpret")
)
def _streaming_topk(mat_t, norms, queries, *, k, n_items, cosine, interpret):
    k_feat, n_pad = mat_t.shape
    b = queries.shape[0]
    grid = n_pad // BLOCK_N
    kernel = functools.partial(_topn_kernel, k=k, n_items=n_items, cosine=cosine)
    common = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    vals, idxs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((b, k_feat), lambda i: (0, 0), **common),
            pl.BlockSpec((k_feat, BLOCK_N), lambda i: (0, i), **common),
            pl.BlockSpec((1, BLOCK_N), lambda i: (0, i), **common),
        ],
        out_specs=[
            pl.BlockSpec((1, b, k), lambda i: (i, 0, 0), **common),
            pl.BlockSpec((1, b, k), lambda i: (i, 0, 0), **common),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid, b, k), jnp.float32),
            jax.ShapeDtypeStruct((grid, b, k), jnp.int32),
        ],
        interpret=interpret,
    )(queries.astype(mat_t.dtype), mat_t, norms)
    # merge the per-block candidates: [b, grid * k] is tiny
    flat_v = jnp.transpose(vals, (1, 0, 2)).reshape(b, grid * k)
    flat_i = jnp.transpose(idxs, (1, 0, 2)).reshape(b, grid * k)
    top_v, pos = jax.lax.top_k(flat_v, k)
    top_i = jnp.take_along_axis(flat_i, pos, axis=1)
    return top_v, top_i


# above this k the kernel's unrolled per-block selection stops paying for
# itself (and compile time grows with k); fall back to one XLA top_k
MAX_KERNEL_K = 128


@functools.partial(jax.jit, static_argnames=("k", "n_items", "cosine"))
def _materialized_topk(mat_t, norms, queries, *, k, n_items, cosine):
    """Large-k fallback over the same feature-major layout: materialize
    [b, n] scores once and let XLA's top_k handle the wide selection."""
    q = queries.astype(mat_t.dtype)
    precision = (
        jax.lax.Precision.HIGHEST if q.dtype == jnp.float32 else jax.lax.Precision.DEFAULT
    )
    scores = jnp.dot(q, mat_t, preferred_element_type=jnp.float32, precision=precision)
    if cosine:
        qn = jnp.linalg.norm(queries.astype(jnp.float32), axis=1, keepdims=True)
        scores = scores / jnp.maximum(norms * qn, 1e-12)
    cols = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 1)
    scores = jnp.where(cols < n_items, scores, -jnp.inf)
    return jax.lax.top_k(scores, k)


def top_k_streaming_device(
    up: StreamingItemMatrix,
    queries: np.ndarray,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(scores [b, k], indices [b, k]) as device arrays — the async
    building block. ``interpret`` defaults to the Pallas interpreter on
    non-TPU backends so the same handle works everywhere."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    k = max(1, min(int(k), up.n_items))
    if k > MAX_KERNEL_K:
        return _materialized_topk(
            up.mat_t, up.norms, jnp.asarray(q), k=k, n_items=up.n_items, cosine=cosine
        )
    return _streaming_topk(
        up.mat_t,
        up.norms,
        jnp.asarray(q),
        k=k,
        n_items=up.n_items,
        cosine=cosine,
        interpret=interpret,
    )


def top_k_streaming(
    up: StreamingItemMatrix,
    queries: np.ndarray,
    k: int,
    cosine: bool = False,
    interpret: bool | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """(indices [b, k], scores [b, k]) of the best items per query row."""
    vals, idxs = top_k_streaming_device(up, queries, k, cosine=cosine, interpret=interpret)
    return np.asarray(idxs), np.asarray(vals)
