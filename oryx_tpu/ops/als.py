"""ALS matrix factorization on TPU.

The TPU-native replacement for Spark MLlib's ALS (the hot loop of the
reference's ALSUpdate, app/oryx-app-mllib/.../als/ALSUpdate.java:116-124):
alternating normal-equation sweeps solved as batched k x k systems on
device.

Design (TPU-first, not a port):
- Ratings arrive as COO (user_idx, item_idx, value). Host-side they are
  grouped per-row and packed into **degree buckets**: rows whose rating
  count rounds up to the same power-of-two width D share a padded
  [N_b, D] rectangle of neighbor indices + values + mask. Fixed shapes
  mean XLA compiles one program per (bucket width, chunk) pair —
  logarithmically many — while a power-law degree distribution no longer
  forces every row to the max degree (a single 10k-rating user used to
  inflate the gather workspace for all rows; now it sits alone in a wide
  bucket and everyone else stays narrow).
- One half-sweep solves all users at once:
    implicit (Hu/Koren/Volinsky, MLlib semantics):
        c_ui = 1 + alpha*|r|, p_ui = 1 if r > 0 else 0
        A_u = YtY + sum_i (c-1) y_i y_i^T + lambda*I ;  b_u = sum_i c*p*y_i
    explicit (ALS-WR weighted-lambda):
        A_u = sum_i y_i y_i^T + lambda*n_u*I        ;  b_u = sum_i r y_i
  built with gathers + einsum (MXU work) and solved with batched
  jnp.linalg.solve. Rows are processed in chunks sized so the [C, D, k]
  gather workspace stays under a fixed HBM budget regardless of D.
- Replicated mode (default): neighbor buckets are sharded over rows on
  the mesh's 'data' axis; factor matrices live replicated, so YtY needs
  no collective and the per-row gather is local. XLA inserts the
  all-gather of the updated factors between half-sweeps.
- Sharded-factor mode (``shard_factors=True``): X and Y live sharded
  over the mesh (rows never replicated) so factorizations larger than
  one device's HBM fit a slice — the capability MLlib gets from block-
  partitioning (ALSUpdate.java:116-124, SURVEY.md §5). Each half-sweep
  runs under ``shard_map``: the implicit-feedback Gramian YtY is a
  ``psum`` of local Gramians, and the neighbor gather becomes a **ring
  exchange** — at ring step s each device holds item-factor shard
  (d+s) mod S (moved with ``ppermute`` over ICI) and fills the slots of
  its local [C, D, k] workspace whose item lives in that shard. After S
  steps the workspace is complete and the normal-equation solve is
  purely local. Factors are stored in bucket-permuted layout on device;
  the host keeps the permutation and restores natural order on export.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oryx_tpu.parallel.mesh import DATA_AXIS, pad_to_multiple


@dataclass
class NeighborBlock:
    """Padded per-row neighbor structure for one side of the factorization."""

    idx: np.ndarray  # [N, D] int32 indices into the other side's factors
    val: np.ndarray  # [N, D] float32 rating values (0 where padded)
    mask: np.ndarray  # [N, D] float32 1/0 validity

    @property
    def num_rows(self) -> int:
        return self.idx.shape[0]


def build_neighbor_block(
    row_idx: np.ndarray,
    col_idx: np.ndarray,
    values: np.ndarray,
    num_rows: int,
    pad_rows_to: int = 1,
) -> NeighborBlock:
    """Group COO entries by row and pad to [N, Dmax] rectangles.

    Retained for small problems and tests; ``train_als`` uses the
    degree-bucketed :func:`build_neighbor_buckets` (a max-degree
    rectangle explodes on power-law data — VERDICT r1 #2)."""
    order = np.argsort(row_idx, kind="stable")
    r, c, v = row_idx[order], col_idx[order], values[order]
    counts = np.bincount(r, minlength=num_rows)
    dmax = max(1, int(counts.max()) if counts.size else 1)
    n = pad_to_multiple(max(num_rows, 1), pad_rows_to)
    idx = np.zeros((n, dmax), dtype=np.int32)
    val = np.zeros((n, dmax), dtype=np.float32)
    mask = np.zeros((n, dmax), dtype=np.float32)
    # vectorized scatter: position of each entry within its row
    starts = np.concatenate([[0], np.cumsum(counts)])
    pos = np.arange(len(r)) - starts[r]
    idx[r, pos] = c
    val[r, pos] = v
    mask[r, pos] = 1.0
    return NeighborBlock(idx, val, mask)


# NeighborBucket and both packing implementations live in ops/packing.py
# (numpy + stdlib only, so forked packing workers never import jax);
# re-exported here for API compatibility.
from oryx_tpu.ops.packing import (  # noqa: E402  (re-export)
    NeighborBucket,
    PackingOptions,
    _pow2_at_least,
    build_neighbor_buckets_reference,
    pack_neighbor_buckets,
)

# wall seconds of the most recent train_als call (replicated path), split
# by phase ({"pack": s, "init": s, "iterate": s}: neighbor-bucket packing
# vs the rest of setup (factor init, device_put) vs the compiled sweep
# run); read by tools/train_benchmark.py for bench.py's per-phase rows.
# Overwritten per call, never merged.
last_phase_seconds: dict[str, float] = {}


def _pcast_varying(x):
    """Mark an array device-varying inside shard_map where the running
    jax has varying types (>= 0.6 ``jax.lax.pcast``); identity on older
    versions, whose shard_map has no varying-type system and needs no
    annotation for the scan carries to line up."""
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, (DATA_AXIS,), to="varying")
    return x


def _mask_from_deg(shape, deg):
    """[C, D] f32 validity mask from per-slot degrees: bucket entries
    occupy positions 0..deg-1, so the mask is a comparison against an
    iota — computed in-register on device instead of stored in HBM."""
    return (
        jax.lax.broadcasted_iota(jnp.int32, shape, len(shape) - 1) < deg[..., None]
    ).astype(jnp.float32)


def build_neighbor_buckets(
    row_idx: np.ndarray,
    col_idx: np.ndarray,
    values: np.ndarray,
    num_rows: int,
    num_shards: int = 1,
    min_width: int = 8,
    workspace_elems: int = 1 << 27,
    features: int = 50,
    stable_shapes: bool = True,
    options: PackingOptions | None = None,
) -> list[NeighborBucket]:
    """Group COO entries by row into power-of-two degree buckets.

    Rows with no ratings appear in no bucket (their factors stay zero,
    matching the rectangle path where an all-masked row solves to the
    zero vector). Each bucket's chunk size is chosen so the [chunk, D, k]
    gather workspace stays under ``workspace_elems`` elements, and its
    slot count is padded (rows = -1) to a multiple of chunk*num_shards so
    every device runs the same number of full-width lax.map steps.

    ``stable_shapes`` (default) additionally rounds each bucket's slot
    count up to a power of two, so the (num_slots, width, chunk) shape
    signature takes log-many values as the dataset grows: consecutive
    generations of a growing factorization land on the same signature and
    reuse the compiled sweep instead of retracing. Pad slots are
    zero-degree and solve to the zero vector into the sacrificial row, so
    the padding is numerically free; the pow2 round-up also never more
    than doubles a bucket, same bound as the granule heuristic it
    replaces. Falls back to exact-granule padding when num_shards is not
    a power of two.

    Delegates to the sharded engine in :mod:`oryx_tpu.ops.packing`
    (``options`` selects worker count / chunking / shm budget), whose
    layout is bit-identical to :func:`build_neighbor_buckets_reference`
    for every option value — callers and the compile cache never see
    which path packed a bucket.
    """
    return pack_neighbor_buckets(
        row_idx, col_idx, values, num_rows, num_shards, min_width,
        workspace_elems, features, stable_shapes, options,
    )


def _normal_equations(v, cval, cmask, yty, lam, alpha, implicit, k, matmul_dtype=None):
    """A [C,k,k], b [C,k] of the per-row normal equations given the
    gathered neighbor workspace v [C,D,k] (zeros at masked slots).

    ``matmul_dtype=bfloat16`` runs the Gramian-building einsums with bf16
    operands and f32 accumulation (halved HBM traffic, full-rate MXU);
    the k x k systems and their solves stay f32. Per-row confidence
    weights fold into one operand in f32 BEFORE the cast so the bf16
    rounding applies once per factor entry, not per product term."""
    md = matmul_dtype or jnp.float32
    eye = jnp.eye(k, dtype=jnp.float32)
    pet = dict(preferred_element_type=jnp.float32)
    if implicit:
        conf_m1 = alpha * jnp.abs(cval) * cmask  # c - 1
        vw = (v * conf_m1[..., None]).astype(md)
        a = yty[None] + jnp.einsum("cdk,cdl->ckl", vw, v.astype(md), **pet) + lam * eye[None]
        p = (cval > 0).astype(jnp.float32) * cmask
        bw = ((1.0 + alpha * jnp.abs(cval)) * p).astype(md)
        b = jnp.einsum("cdk,cd->ck", v.astype(md), bw, **pet)
    else:
        n_u = cmask.sum(axis=1)  # ratings per row (ALS-WR lambda scaling)
        vm = v.astype(md)
        a = (
            jnp.einsum("cdk,cdl->ckl", vm, vm, **pet)
            + (lam * jnp.maximum(n_u, 1.0))[:, None, None] * eye[None]
        )
        b = jnp.einsum("cdk,cd->ck", vm, (cval * cmask).astype(md), **pet)
    return a, b


def _sweep_buckets(
    other: jnp.ndarray,  # [M(+1), k] factors of the other side (full copy)
    out_shape: int,  # rows in the output factor matrix (incl. pad slot)
    bucket_args: list[tuple],  # per bucket: (rows, idx, val, deg, chunk)
    lam: float,
    alpha: float,
    implicit: bool,
    matmul_dtype=None,
) -> jnp.ndarray:
    """One half-sweep in replicated-factor mode: solve every bucket and
    scatter results into a fresh [out_shape, k] factor matrix. Rows in no
    bucket (degree 0) stay zero; pad slots (row -1) scatter to the last
    (sacrificial) row, which callers slice off."""
    k = other.shape[1]
    md = matmul_dtype or jnp.float32
    yty = (
        jnp.dot(other.astype(md).T, other.astype(md), preferred_element_type=jnp.float32)
        if implicit
        else None
    )

    def solve_chunk(args):
        cidx, cval, cdeg = args
        cmask = _mask_from_deg(cval.shape, cdeg)
        v = other[cidx] * cmask[..., None]  # [C, D, k]
        a, b = _normal_equations(v, cval, cmask, yty, lam, alpha, implicit, k, md)
        return jnp.linalg.solve(a, b[..., None])[..., 0]

    out = jnp.zeros((out_shape, k), dtype=jnp.float32)
    for rows, idx, val, deg, chunk in bucket_args:
        n, d = idx.shape
        num_chunks = n // chunk
        if num_chunks <= 1:
            solved = solve_chunk((idx, val, deg))
        else:
            solved = jax.lax.map(
                solve_chunk,
                (
                    idx.reshape(num_chunks, chunk, d),
                    val.reshape(num_chunks, chunk, d),
                    deg.reshape(num_chunks, chunk),
                ),
            ).reshape(n, k)
        # pad slots carry row -1 -> scatter to the sacrificial last row
        target = jnp.where(rows < 0, out_shape - 1, rows)
        out = out.at[target].set(solved)
    return out


@dataclass
class ALSModel:
    """Factorization result: row-major float32 factor matrices."""

    x: np.ndarray  # [num_users, k]
    y: np.ndarray  # [num_items, k]


@functools.lru_cache(maxsize=64)
def _compiled_run(
    u_sig: tuple,  # per user-bucket (num_slots, width, chunk)
    i_sig: tuple,  # per item-bucket (num_slots, width, chunk)
    users_pad: int,  # factor rows incl. sacrificial/pow2 pad
    items_pad: int,
    features: int,
    iterations: int,
    implicit: bool,
    matmul_dtype: Optional[str],
    mesh: Optional[Mesh],
):
    """Persistent compiled ALS run, keyed on the static shape signature.

    Everything shape-like is in the cache key; everything value-like
    (bucket contents, init factors, lam, alpha) is a traced argument. A
    warm-started generation whose buckets land on the same pow2 shape
    signature (the common case under ``stable_shapes``) re-enters the
    exact jit wrapper and pays zero tracing and zero XLA compilation —
    previously every ``train_als`` call jitted a fresh closure, so every
    generation recompiled the whole sweep. ``y_init`` is donated: the
    warm-start factors' buffer is reused for the fori_loop carry instead
    of being held live next to it for the whole run.
    """
    md = jnp.bfloat16 if matmul_dtype == "bfloat16" else None
    u_chunks = [c for _, _, c in u_sig]
    i_chunks = [c for _, _, c in i_sig]

    def run(u_arrs, i_arrs, y_init, lam, alpha):
        # chunk sizes are static (from the cache key); arrays + the two
        # hyperparameters are traced, so a lam/alpha sweep is free too
        u_args = [(*a, c) for a, c in zip(u_arrs, u_chunks)]
        i_args = [(*a, c) for a, c in zip(i_arrs, i_chunks)]
        x = jnp.zeros((users_pad, features), dtype=jnp.float32)

        def body(_, carry):
            x_, y_ = carry
            x_ = _sweep_buckets(y_, users_pad, u_args, lam, alpha, implicit, md)
            y_ = _sweep_buckets(x_, items_pad, i_args, lam, alpha, implicit, md)
            return x_, y_

        return jax.lax.fori_loop(0, iterations, body, (x, y_init))

    if mesh is not None:
        repl = NamedSharding(mesh, P())
        return jax.jit(run, out_shardings=(repl, repl), donate_argnums=(2,))
    return jax.jit(run, donate_argnums=(2,))


def compiled_run_cache_info():
    """(hits, misses, ...) of the persistent ALS run cache — exposed for
    the recompile-count regression test and ops introspection."""
    return _compiled_run.cache_info()


def train_als(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    num_users: int,
    num_items: int,
    features: int,
    lam: float,
    alpha: float = 1.0,
    implicit: bool = True,
    iterations: int = 10,
    mesh: Optional[Mesh] = None,
    seed: int | None = None,
    workspace_elems: int = 1 << 27,
    shard_factors: bool = False,
    matmul_dtype: str | None = None,
    init_y: np.ndarray | None = None,
    packing: PackingOptions | None = None,
) -> ALSModel:
    """Full ALS training run.

    ``init_y`` [num_items, features] warm-starts the item factors from a
    previous generation (the first half-sweep then solves X against
    near-converged Y instead of noise); rows default to the usual random
    init where the caller has no previous factor (new items). A shape
    mismatch silently falls back to cold init. Replicated-factor path
    only — the sharded path's permuted layout cold-starts.

    COO inputs are int32/float32 numpy arrays. With ``mesh``, neighbor
    buckets are row-sharded over the 'data' axis; factors are replicated
    (default) or, with ``shard_factors=True``, sharded over the mesh so
    factorizations larger than one device's HBM fit the slice (ring-
    exchange half-sweeps; see module docstring). ``workspace_elems``
    bounds the per-chunk gather workspace (elements, not bytes).
    ``matmul_dtype="bfloat16"`` (oryx.batch.compute.matmul-dtype) runs
    the Gramian-building matmuls with bf16 operands and f32 accumulation
    — halved HBM traffic and full-rate MXU on TPU; solves stay f32.
    """
    import time as _time

    from oryx_tpu.common import rng as rng_mod

    t_init = _time.perf_counter()

    if matmul_dtype not in (None, "float32", "bfloat16"):
        # a typo'd dtype silently training full-f32 would corrupt capacity
        # planning; fail at startup like the serving score-dtype check
        raise ValueError(
            f"matmul_dtype must be float32 or bfloat16, got {matmul_dtype!r}"
        )
    md = jnp.bfloat16 if matmul_dtype == "bfloat16" else None
    seed_val = rng_mod.next_seed() if seed is None else seed
    if shard_factors:
        if mesh is None:
            raise ValueError("shard_factors=True requires a mesh")
        return _train_als_sharded(
            user_idx, item_idx, values, num_users, num_items, features,
            lam, alpha, implicit, iterations, mesh, seed_val, workspace_elems,
            md, packing,
        )

    num_shards = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    t_pack0 = _time.perf_counter()
    u_buckets = build_neighbor_buckets(
        user_idx, item_idx, values, num_users, num_shards,
        workspace_elems=workspace_elems, features=features, options=packing,
    )
    i_buckets = build_neighbor_buckets(
        item_idx, user_idx, values, num_items, num_shards,
        workspace_elems=workspace_elems, features=features, options=packing,
    )
    t_pack = _time.perf_counter() - t_pack0

    # MLlib-style init: small random normal factors (+1 sacrificial pad
    # row, then pow2 row padding so the compiled run's shape signature is
    # stable as the item universe grows; pad rows are zero, enter YtY as
    # zero, and are sliced off on export — numerically free). Host RNG in
    # natural row order so the sharded-factor mode (which permutes the
    # same init) is step-identical with this path.
    users_pad = _pow2_at_least(num_users + 1)
    items_pad = _pow2_at_least(num_items + 1)
    y0 = np.zeros((items_pad, features), np.float32)
    if init_y is not None and np.shape(init_y) == (num_items, features):
        y0[:num_items] = np.asarray(init_y, dtype=np.float32)
    else:
        if init_y is not None:
            # feature count or item universe changed under us: warm-start
            # is an optimization, never a correctness dependency
            import logging

            logging.getLogger(__name__).info(
                "init_y shape %s != (%d, %d); cold-starting",
                np.shape(init_y), num_items, features,
            )
        y0[:num_items] = 0.1 * np.random.default_rng(seed_val).standard_normal(
            (num_items, features)
        ).astype(np.float32)

    u_sig = tuple((b.num_slots, b.width, b.chunk) for b in u_buckets)
    i_sig = tuple((b.num_slots, b.width, b.chunk) for b in i_buckets)
    run_c = _compiled_run(
        u_sig, i_sig, users_pad, items_pad, features, iterations, implicit,
        matmul_dtype, mesh,
    )

    def to_arrs(buckets, row_sh=None, row_sh2=None):
        out = []
        for b in buckets:
            if row_sh is None:
                out.append((jnp.asarray(b.rows), jnp.asarray(b.idx), jnp.asarray(b.val), jnp.asarray(b.deg)))
            else:
                out.append(
                    (
                        jax.device_put(b.rows, row_sh),
                        jax.device_put(b.idx, row_sh2),
                        jax.device_put(b.val, row_sh2),
                        jax.device_put(b.deg, row_sh),
                    )
                )
        return out

    lam_t = jnp.float32(lam)
    alpha_t = jnp.float32(alpha)
    t_iter = _time.perf_counter()
    if mesh is not None:
        row_sharded = NamedSharding(mesh, P(DATA_AXIS))
        row_sharded2 = NamedSharding(mesh, P(DATA_AXIS, None))
        repl = NamedSharding(mesh, P())
        u_arrs = to_arrs(u_buckets, row_sharded, row_sharded2)
        i_arrs = to_arrs(i_buckets, row_sharded, row_sharded2)
        y0 = jax.device_put(np.asarray(y0), repl)
        x, y = run_c(u_arrs, i_arrs, y0, lam_t, alpha_t)
    else:
        x, y = run_c(
            to_arrs(u_buckets), to_arrs(i_buckets), jnp.asarray(y0), lam_t, alpha_t
        )

    x = np.asarray(x)[:num_users]
    y = np.asarray(y)[:num_items]
    last_phase_seconds.clear()
    last_phase_seconds.update(
        pack=t_pack,
        init=t_iter - t_init - t_pack,
        iterate=_time.perf_counter() - t_iter,
    )
    return ALSModel(x=x, y=y)


# ---------------------------------------------------------------------------
# Sharded-factor training: ring-exchange half-sweeps under shard_map
# ---------------------------------------------------------------------------


def _sharded_layout(buckets: list[NeighborBucket], num_rows: int, s: int):
    """Device-major slot layout for sharded factors.

    Global slot order is device-major, bucket-minor: device d's block is
    the concatenation of every bucket's d-th shard slice. Returns
    (perm_rows [T] global row id per slot (-1 pad), pos [num_rows] slot
    position per row (-1 if degree 0), loc = slots per device)."""
    loc = sum(b.num_slots // s for b in buckets)
    total = loc * s
    perm_rows = np.full(total, -1, dtype=np.int64)
    pos = np.full(num_rows, -1, dtype=np.int64)
    offset = 0
    for b in buckets:
        n_b = b.num_slots
        n_loc = n_b // s
        i = np.arange(n_b)
        d, j = i // n_loc, i % n_loc
        gp = d * loc + offset + j
        perm_rows[gp] = b.rows
        valid = b.rows >= 0
        pos[b.rows[valid]] = gp[valid]
        offset += n_loc
    return perm_rows, pos, loc


def _translate_to_shards(idx: np.ndarray, pos_other: np.ndarray, other_loc: int):
    """Map col ids to (owner shard, local row) in the other side's layout.

    Entries whose col has no slot (only possible for mask-0 padding, idx
    0) get shard -1 — matched by no ring step, contributing zero."""
    p = pos_other[idx]
    ish = np.where(p < 0, -1, p // other_loc).astype(np.int32)
    ilo = np.where(p < 0, 0, p % other_loc).astype(np.int32)
    return ish, ilo


def _train_als_sharded(
    user_idx, item_idx, values, num_users, num_items, features,
    lam, alpha, implicit, iterations, mesh, seed_val, workspace_elems,
    matmul_dtype=None, packing=None,
) -> ALSModel:
    """shard_map ALS with factors sharded over the mesh (see module doc)."""
    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    s = int(np.prod(mesh.devices.shape))
    u_buckets = build_neighbor_buckets(
        user_idx, item_idx, values, num_users, s,
        workspace_elems=workspace_elems, features=features, options=packing,
    )
    i_buckets = build_neighbor_buckets(
        item_idx, user_idx, values, num_items, s,
        workspace_elems=workspace_elems, features=features, options=packing,
    )
    if not u_buckets or not i_buckets:
        return ALSModel(
            x=np.zeros((num_users, features), np.float32),
            y=np.zeros((num_items, features), np.float32),
        )

    perm_x, pos_x, u_loc = _sharded_layout(u_buckets, num_users, s)
    perm_y, pos_y, i_loc = _sharded_layout(i_buckets, num_items, s)

    u_arrs = []
    for b in u_buckets:
        ish, ilo = _translate_to_shards(b.idx, pos_y, i_loc)
        u_arrs.append((ish, ilo, b.val, b.deg))
    i_arrs = []
    for b in i_buckets:
        ish, ilo = _translate_to_shards(b.idx, pos_x, u_loc)
        i_arrs.append((ish, ilo, b.val, b.deg))
    u_chunks = [b.chunk for b in u_buckets]
    i_chunks = [b.chunk for b in i_buckets]

    # same natural-order init as the replicated path, permuted into the
    # sharded layout (pad slots zero — they enter the psum'd YtY)
    y_nat = 0.1 * np.random.default_rng(seed_val).standard_normal(
        (num_items, features)
    ).astype(np.float32)
    y0 = np.zeros((i_loc * s, features), np.float32)
    yv0 = perm_y >= 0
    y0[yv0] = y_nat[perm_y[yv0]]

    ring = [(i, (i - 1) % s) for i in range(s)]
    k = features

    def ring_fill(other_loc, ish_c, ilo_c):
        """[C, D, k] workspace: at ring step t this device holds the other
        side's shard (my+t) mod S and fills the slots that shard owns."""
        my = jax.lax.axis_index(DATA_AXIS)
        v0 = jnp.zeros(ish_c.shape + (other_loc.shape[1],), jnp.float32)
        # the accumulator varies per device (ppermute output feeds it):
        # mark it device-varying so the scan carry types line up
        v0 = _pcast_varying(v0)

        def step(carry, t):
            cur, v = carry
            shard_id = jax.lax.rem(my + t, s)
            g = cur[ilo_c]
            v = v + jnp.where((ish_c == shard_id)[..., None], g, 0.0)
            cur = jax.lax.ppermute(cur, DATA_AXIS, ring)
            return (cur, v), None

        (_, v), _ = jax.lax.scan(step, (other_loc, v0), jnp.arange(s, dtype=jnp.int32))
        return v

    def half_sweep(other_loc, arrs, chunks):
        md = matmul_dtype or jnp.float32
        yty = (
            jax.lax.psum(
                jnp.dot(
                    other_loc.astype(md).T,
                    other_loc.astype(md),
                    preferred_element_type=jnp.float32,
                ),
                DATA_AXIS,
            )
            if implicit
            else None
        )
        outs = []
        for (ish, ilo, val, deg), chunk in zip(arrs, chunks):
            n_loc, d = ish.shape

            def solve_chunk(args):
                ish_c, ilo_c, cval, cdeg = args
                cmask = _mask_from_deg(cval.shape, cdeg)
                v = ring_fill(other_loc, ish_c, ilo_c) * cmask[..., None]
                a, b = _normal_equations(v, cval, cmask, yty, lam, alpha, implicit, k, md)
                return jnp.linalg.solve(a, b[..., None])[..., 0]

            nch = n_loc // chunk
            if nch <= 1:
                solved = solve_chunk((ish, ilo, val, deg))
            else:
                solved = jax.lax.map(
                    solve_chunk,
                    (
                        ish.reshape(nch, chunk, d),
                        ilo.reshape(nch, chunk, d),
                        val.reshape(nch, chunk, d),
                        deg.reshape(nch, chunk),
                    ),
                ).reshape(n_loc, k)
            outs.append(solved)
        return jnp.concatenate(outs, axis=0)

    def run(u_in, i_in, y_loc0):
        def body(_, carry):
            x_loc, y_loc = carry
            x_loc = half_sweep(y_loc, u_in, u_chunks)
            y_loc = half_sweep(x_loc, i_in, i_chunks)
            return x_loc, y_loc

        x_loc = _pcast_varying(jnp.zeros((u_loc, features), jnp.float32))
        return jax.lax.fori_loop(0, iterations, body, (x_loc, y_loc0))

    spec2 = P(DATA_AXIS, None)
    spec1 = P(DATA_AXIS)  # the rank-1 per-slot degree column
    arr_specs_u = [(spec2, spec2, spec2, spec1) for _ in u_arrs]
    arr_specs_i = [(spec2, spec2, spec2, spec1) for _ in i_arrs]
    run_c = jax.jit(
        shard_map(
            run,
            mesh=mesh,
            in_specs=(arr_specs_u, arr_specs_i, spec2),
            out_specs=(spec2, spec2),
        )
    )

    sh2 = NamedSharding(mesh, spec2)
    sh1 = NamedSharding(mesh, spec1)
    u_dev = [
        tuple(jax.device_put(a, sh1 if a.ndim == 1 else sh2) for a in t)
        for t in u_arrs
    ]
    i_dev = [
        tuple(jax.device_put(a, sh1 if a.ndim == 1 else sh2) for a in t)
        for t in i_arrs
    ]
    x_p, y_p = run_c(u_dev, i_dev, jax.device_put(y0, sh2))

    x = np.zeros((num_users, features), np.float32)
    y = np.zeros((num_items, features), np.float32)
    xv = perm_x >= 0
    yv = perm_y >= 0
    x[perm_x[xv]] = np.asarray(x_p)[xv]
    y[perm_y[yv]] = np.asarray(y_p)[yv]
    return ALSModel(x=x, y=y)


# -- evaluation --------------------------------------------------------------


def predict_pairs(x: np.ndarray, y: np.ndarray, user_idx: np.ndarray, item_idx: np.ndarray) -> np.ndarray:
    """Predicted strengths for (user, item) pairs (on device, batched)."""

    @jax.jit
    def _pred(xa, ya, ui, ii):
        return jnp.sum(xa[ui] * ya[ii], axis=-1)

    return np.asarray(_pred(x, y, user_idx, item_idx))


def rmse(x: np.ndarray, y: np.ndarray, user_idx, item_idx, values) -> float:
    """Root mean squared error over test pairs (Evaluation.rmse analogue,
    app/oryx-app-mllib/.../als/Evaluation.java:49-63)."""
    if len(values) == 0:
        return float("nan")
    pred = predict_pairs(x, y, user_idx, item_idx)
    return float(np.sqrt(np.mean((pred - values) ** 2)))


@functools.partial(jax.jit, static_argnames=("chunk",))
def _auc_bucket_jit(x, y, uids, pos, posm, neg, negm, chunk):
    """Per-user AUC for one degree bucket: [N, P] padded positive and
    sampled-negative item ids + masks. Scores on the MXU, pairwise
    comparison [C, P, P] chunked to bound memory."""

    def per_chunk(args):
        cu, cp, cpm, cn, cnm = args
        xu = x[cu]  # [C, k]
        sp = jnp.einsum("cpk,ck->cp", y[cp], xu)
        sn = jnp.einsum("cnk,ck->cn", y[cn], xu)
        gt = (
            (sp[:, :, None] > sn[:, None, :])
            & cpm[:, :, None]
            & cnm[:, None, :]
        ).sum(axis=(1, 2))
        pairs = cpm.sum(axis=1) * cnm.sum(axis=1)
        return gt / jnp.maximum(pairs, 1), pairs > 0

    n = uids.shape[0]
    if n <= chunk:
        return per_chunk((uids, pos, posm, neg, negm))
    nch = n // chunk
    a, v = jax.lax.map(
        per_chunk,
        (
            uids.reshape(nch, chunk),
            pos.reshape(nch, chunk, -1),
            posm.reshape(nch, chunk, -1),
            neg.reshape(nch, chunk, -1),
            negm.reshape(nch, chunk, -1),
        ),
    )
    return a.reshape(n), v.reshape(n)


def mean_auc(
    x: np.ndarray,
    y: np.ndarray,
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rng: np.random.Generator,
) -> float:
    """Mean per-user AUC with about as many sampled negatives as positives
    per user (Evaluation.areaUnderCurve, Evaluation.java:70-136).

    Fully vectorized (VERDICT r1 #8): users are grouped into power-of-two
    positive-count buckets; negative sampling (4x candidates, positives
    rejected) happens with one sort + searchsorted pass per bucket on
    host, and the score/pairwise-comparison work runs on device with
    chunked [C, P, P] comparisons — no Python per-user loop."""
    if len(user_idx) == 0:
        return float("nan")
    all_items = np.unique(item_idx)
    order = np.argsort(user_idx, kind="stable")
    uu, ii = user_idx[order], item_idx[order]
    uniq_users, starts = np.unique(uu, return_index=True)
    ends = np.concatenate([starts[1:], [len(uu)]])
    counts = ends - starts

    xd = jnp.asarray(x, dtype=jnp.float32)
    yd = jnp.asarray(y, dtype=jnp.float32)

    # per-entry user ordinal and position within the user's run
    entry_user = np.repeat(np.arange(len(uniq_users)), counts)
    entry_pos = np.arange(len(ii)) - np.repeat(starts, counts)

    aucs: list[np.ndarray] = []
    valids: list[np.ndarray] = []
    widths = np.maximum(1, 2 ** np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64))
    for w in sorted(set(widths.tolist())):
        sel = np.flatnonzero(widths == w)
        nu = len(sel)
        p = int(w)
        pos = np.zeros((nu, p), dtype=np.int64)
        posm = np.zeros((nu, p), dtype=bool)
        slot_of = np.full(len(uniq_users), -1, dtype=np.int64)
        slot_of[sel] = np.arange(nu)
        esel = slot_of[entry_user] >= 0
        pos[slot_of[entry_user[esel]], entry_pos[esel]] = ii[esel]
        posm[slot_of[entry_user[esel]], entry_pos[esel]] = True
        # sample 4x candidates, reject positives via disjoint-range keys:
        # row r's sorted positives become keys in [r*M, (r+1)*M) so one
        # global searchsorted answers rowwise membership
        m = int(all_items.max()) + 2
        cand = rng.choice(all_items, size=(nu, 4 * p))
        keys = np.sort(np.where(posm, pos, m - 1) + np.arange(nu)[:, None] * m, axis=1)
        ckeys = cand + np.arange(nu)[:, None] * m
        loc = np.searchsorted(keys.ravel(), ckeys.ravel())
        hit = np.zeros(loc.shape, dtype=bool)
        in_range = loc < keys.size
        hit[in_range] = keys.ravel()[loc[in_range]] == ckeys.ravel()[in_range]
        ok = ~hit.reshape(nu, 4 * p)
        rank = np.cumsum(ok, axis=1) - 1
        want = counts[sel][:, None]  # as many negatives as positives
        take = ok & (rank < want) & (rank < p)
        neg = np.zeros((nu, p), dtype=np.int64)
        negm = np.zeros((nu, p), dtype=bool)
        rows, cols = np.nonzero(take)
        neg[rows, rank[rows, cols]] = cand[rows, cols]
        negm[rows, rank[rows, cols]] = True

        chunk = max(1, min(nu, (1 << 24) // max(p * p, 1)))
        pad = -nu % chunk
        if pad:
            z2 = np.zeros((pad, p), dtype=np.int64)
            zb = np.zeros((pad, p), dtype=bool)
            pos, posm = np.concatenate([pos, z2]), np.concatenate([posm, zb])
            neg, negm = np.concatenate([neg, z2]), np.concatenate([negm, zb])
        uids = np.concatenate([uniq_users[sel], np.zeros(pad, uniq_users.dtype)])
        a, v = _auc_bucket_jit(
            xd, yd, jnp.asarray(uids), jnp.asarray(pos), jnp.asarray(posm),
            jnp.asarray(neg), jnp.asarray(negm), chunk,
        )
        aucs.append(np.asarray(a)[:nu])
        valids.append(np.asarray(v)[:nu])
    auc = np.concatenate(aucs)
    valid = np.concatenate(valids)
    return float(auc[valid].mean()) if valid.any() else float("nan")


# ---------------------------------------------------------------------------
# Speed-layer fold-in, batched on device
# ---------------------------------------------------------------------------
#
# The reference folds in one event at a time (ALSUtils.computeUpdatedXu:
# 74-106 inside ALSSpeedModelManager.buildUpdates' parallelStream). All
# events in a micro-batch read the PRE-batch model state (updates travel
# via the update topic, not in-place), so the whole batch is one
# data-parallel computation: a single [k,k] Cholesky factorization per
# side reused against an [n,k] right-hand-side block on the MXU.


def _batch_target_qui(implicit: bool, values, current):
    """Vectorized ALSUtils.computeTargetQui:37-59; NaN = no update."""
    if not implicit:
        return values
    pos = (values > 0.0) & (current < 1.0)
    t_pos = current + (values / (1.0 + values)) * (1.0 - jnp.maximum(0.0, current))
    neg = (values < 0.0) & (current > 0.0)
    t_neg = current + (values / (values - 1.0)) * (0.0 - jnp.minimum(1.0, current))
    return jnp.where(pos, t_pos, jnp.where(neg, t_neg, jnp.nan))


def _fold_half(ata, vecs_own, own_valid, vecs_other, other_valid, values, implicit):
    """New own-side vectors after events against the other side's vectors.

    vecs_own[n,k] current vectors (zeros where own_valid is False — a
    brand-new row starts from a "don't know" prior of 0.5), vecs_other
    the interacting vectors. Returns (new_vecs[n,k], updated[n])."""
    qui = jnp.where(own_valid, jnp.sum(vecs_own * vecs_other, axis=1), 0.0)
    current = jnp.where(own_valid, qui, 0.5)
    target = _batch_target_qui(implicit, values, current)
    d_qui = target - qui
    rhs = d_qui[:, None] * vecs_other  # [n, k]
    chol = jax.scipy.linalg.cho_factor(ata)
    d_vec = jax.scipy.linalg.cho_solve(chol, rhs.T).T
    # Cholesky of a near-singular AtA yields NaNs in float32 (the host
    # Solver's QR threshold/lstsq fallback has no device analogue), so
    # whole rows that came out non-finite are re-solved via pseudo-inverse
    # rather than published corrupted. lax.cond keeps the SVD off the hot
    # path when the factorization was healthy (the common case).
    row_ok = jnp.all(jnp.isfinite(d_vec), axis=1, keepdims=True)
    d_vec = jax.lax.cond(
        jnp.all(row_ok),
        lambda d, _a, _r: d,
        lambda d, a, r: jnp.where(row_ok, d, (jnp.linalg.pinv(a, rcond=1e-5) @ r.T).T),
        d_vec,
        ata,
        rhs,
    )
    new_vecs = jnp.where(own_valid[:, None], vecs_own, 0.0) + d_vec
    updated = other_valid & ~jnp.isnan(target) & jnp.all(jnp.isfinite(d_vec), axis=1)
    return jnp.where(updated[:, None], new_vecs, 0.0), updated


@functools.partial(jax.jit, static_argnames=("implicit",))
def _fold_in_batch_jit(yty, xtx, xu, xu_valid, yi, yi_valid, values, implicit):
    new_xu, x_upd = _fold_half(yty, xu, xu_valid, yi, yi_valid, values, implicit)
    new_yi, y_upd = _fold_half(xtx, yi, yi_valid, xu, xu_valid, values, implicit)
    return new_xu, x_upd, new_yi, y_upd


def _fold_half_host(ata, vecs_own, own_valid, vecs_other, other_valid, values, implicit):
    """Host (BLAS) twin of _fold_half: float32 vectors/solves (same
    precision as the device path), float64 target math (scalar parity)."""
    vo = np.asarray(vecs_own, dtype=np.float32)
    vt = np.asarray(vecs_other, dtype=np.float32)
    values = values.astype(np.float64)
    qui = np.where(own_valid, np.einsum("nk,nk->n", vo, vt, dtype=np.float64), 0.0)
    current = np.where(own_valid, qui, 0.5)
    if implicit:
        with np.errstate(divide="ignore", invalid="ignore"):
            t_pos = current + (values / (1.0 + values)) * (1.0 - np.maximum(0.0, current))
            t_neg = current + (values / (values - 1.0)) * (0.0 - np.minimum(1.0, current))
        target = np.where(
            (values > 0.0) & (current < 1.0),
            t_pos,
            np.where((values < 0.0) & (current > 0.0), t_neg, np.nan),
        )
    else:
        target = values
    d_qui = np.nan_to_num(target - qui).astype(np.float32)
    rhs = d_qui[:, None] * vt
    ata32 = np.asarray(ata, dtype=np.float32)
    try:
        # AtA is SPD and k x k (tiny): invert it ONCE via Cholesky (in
        # float64 for the inversion's sake), then apply to all n right-hand
        # sides as a single GEMM. One n*k^2 GEMM is ~2x the two BLAS
        # triangular solves cho_solve costs over the same n — this is the
        # speed layer's per-event floor at 100K events/s. The pinv
        # fallback below still catches ill-conditioned Gramians.
        import scipy.linalg as sla

        chol = sla.cho_factor(ata32.astype(np.float64), lower=True, check_finite=False)
        ainv = sla.cho_solve(
            chol, np.eye(ata32.shape[0], dtype=np.float64), check_finite=False
        ).astype(np.float32)
        d_vec = rhs @ ainv  # ainv symmetric: no transpose needed
    except Exception:
        d_vec = np.full_like(rhs, np.nan)
    # same safety net as the device path: singular/ill-conditioned AtA
    # falls back to a pseudo-inverse solve, and rows that still come out
    # non-finite are dropped instead of published
    finite = np.isfinite(d_vec).all(axis=1)
    if not finite.all():
        d_lstsq = (np.linalg.pinv(ata32, rcond=1e-5) @ rhs.T).T
        d_vec = np.where(~finite[:, None], d_lstsq, d_vec)
        finite = np.isfinite(d_vec).all(axis=1)
    new = np.where(own_valid[:, None], vo, 0.0)
    new += d_vec  # in-place: [n,k] temp saved, bits unchanged
    updated = other_valid & ~np.isnan(target) & finite
    if not updated.all():  # zero dropped rows in place of a full where-copy
        new[~updated] = 0.0
    return new.astype(np.float32, copy=False), updated


def _bucket(n: int) -> int:
    """Pad batch sizes to power-of-two buckets so the jitted fold-in
    compiles once per bucket, not once per micro-batch size."""
    return max(256, 1 << (n - 1).bit_length())


_auto_fold_choice: str | None = None


def _calibrate_fold_backend(yty, xtx, xu, xu_valid, yi, yi_valid, values, implicit):
    """Time host vs device on this real batch, lock in the winner, return
    the host result (already computed — no work wasted). The device is
    timed on a second call so compile time doesn't poison the measurement."""
    global _auto_fold_choice
    import logging
    import time as _time

    t0 = _time.perf_counter()
    host_result = fold_in_batch(
        yty, xtx, xu, xu_valid, yi, yi_valid, values, implicit, backend="host"
    )
    t_host = _time.perf_counter() - t0
    try:
        fold_in_batch(  # compile + first dispatch, untimed
            yty, xtx, xu, xu_valid, yi, yi_valid, values, implicit, backend="device"
        )
        t0 = _time.perf_counter()
        fold_in_batch(
            yty, xtx, xu, xu_valid, yi, yi_valid, values, implicit, backend="device"
        )
        t_device = _time.perf_counter() - t0
    except Exception:  # device backend unusable: host it is
        t_device = float("inf")
    _auto_fold_choice = "device" if t_device < t_host else "host"
    logging.getLogger(__name__).info(
        "fold-in auto backend: host %.3fs vs device %.3fs at n=%d -> %s",
        t_host, t_device, len(values), _auto_fold_choice,
    )
    return host_result


def fold_in_batch(
    yty: np.ndarray,
    xtx: np.ndarray,
    xu: np.ndarray,
    xu_valid: np.ndarray,
    yi: np.ndarray,
    yi_valid: np.ndarray,
    values: np.ndarray,
    implicit: bool,
    backend: str = "auto",
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Fold a micro-batch of n (user, item, value) events into both factor
    sides at once. xu/yi are the events' current vectors ([n,k], zero rows
    where the id is new, flagged by the valid masks). Returns
    (new_xu[n,k], x_updated[n], new_yi[n,k], y_updated[n]) — rows where
    the updated flag is False carry no update (reference: None returns of
    ALSUtils.computeUpdatedXu).

    backend: 'device' (jit, batch padded to power-of-two buckets),
    'host' (float64 BLAS), or 'auto' — measured, not guessed: the first
    large enough batch runs both backends once, times them, and locks in
    the winner for the process. A size heuristic cannot know the
    deployment's dispatch latency — a locally-attached TPU and a
    tunneled one differ by ~100x per call, and guessing wrong costs 2-3x
    sustained speed-layer throughput."""
    n, k = xu.shape
    if backend == "auto":
        if _auto_fold_choice is not None:
            backend = _auto_fold_choice
        elif n * max(k, 1) < 500_000:
            backend = "host"  # too small to learn from; host wins when tiny
        else:
            return _calibrate_fold_backend(
                yty, xtx, xu, xu_valid, yi, yi_valid, values, implicit
            )
    if backend == "host":
        new_xu, x_upd = _fold_half_host(yty, xu, xu_valid, yi, yi_valid, values, implicit)
        new_yi, y_upd = _fold_half_host(xtx, yi, yi_valid, xu, xu_valid, values, implicit)
        return new_xu, x_upd, new_yi, y_upd
    m = _bucket(n)
    if m != n:
        pad = m - n
        xu = np.concatenate([xu, np.zeros((pad, k), xu.dtype)])
        yi = np.concatenate([yi, np.zeros((pad, k), yi.dtype)])
        xu_valid = np.concatenate([xu_valid, np.zeros(pad, bool)])
        yi_valid = np.concatenate([yi_valid, np.zeros(pad, bool)])
        values = np.concatenate([values, np.zeros(pad, values.dtype)])
    out = _fold_in_batch_jit(
        jnp.asarray(yty, dtype=jnp.float32),
        jnp.asarray(xtx, dtype=jnp.float32),
        jnp.asarray(xu, dtype=jnp.float32),
        jnp.asarray(xu_valid),
        jnp.asarray(yi, dtype=jnp.float32),
        jnp.asarray(yi_valid),
        jnp.asarray(values, dtype=jnp.float32),
        implicit,
    )
    new_xu, x_upd, new_yi, y_upd = (np.asarray(o)[:n] for o in out)
    return new_xu, x_upd, new_yi, y_upd


def device_gramian(mat: np.ndarray):
    """Upload a [k,k] Gramian once as a float32 device array. Callers
    cache the result on the owning Solver instance: the solver cache is
    invalidated exactly when the Gramian changes (vector writes, model
    rotation), so a fresh Solver — not every micro-batch — is the only
    event that pays the host->device round-trip again."""
    return jnp.asarray(np.asarray(mat), dtype=jnp.float32)


class FoldInSession:
    """Accumulate fold-in delta blocks and solve them as one micro-batch.

    The pipelined speed layer parses the input stream into several event
    blocks per micro-batch (one per transport frame). Folding each block
    separately would pay a Cholesky + dispatch per block; a session
    accumulates the gathered vector blocks as they arrive — eagerly
    placed on device when the fold backend is the device, so the
    host->device copies overlap the parse stage — and issues ONE solve
    over the concatenation per micro-batch.

    ``yty``/``xtx`` may be numpy arrays or device arrays from
    :func:`device_gramian`; device-resident Gramians flow into the jitted
    solve with no per-batch transfer. Results are computed by the exact
    same code as :func:`fold_in_batch` (the host path literally calls
    it), so a session is bit-identical to the unbatched fold at f32.
    """

    def __init__(self, yty, xtx, implicit: bool, backend: str = "auto") -> None:
        self.yty = yty
        self.xtx = xtx
        self.implicit = implicit
        self.backend = backend
        self._blocks: list[tuple] = []
        self._pending = 0
        from oryx_tpu.common import ledger

        # released by reference drop (the device Gramians/blocks live as
        # long as the session) — no probe, live while strongly referenced
        ledger.register("session", self)

    def _resolved_backend(self, n: int, k: int) -> str:
        if self.backend != "auto":
            return self.backend
        if _auto_fold_choice is not None:
            return _auto_fold_choice
        return "host" if n * max(k, 1) < 500_000 else "auto"

    def resolved_backend(self, n: int, k: int) -> str:
        """The backend this session would pick for an [n,k] micro-batch.
        Callers use it to decide whether device-resident Gramians are
        worth handing in: the host path wants the float64 originals (its
        Cholesky runs in f64), the device path casts to f32 regardless."""
        return self._resolved_backend(n, k)

    def add_block(self, xu, xu_valid, yi, yi_valid, values) -> None:
        n, k = xu.shape
        if self._resolved_backend(max(self._pending + n, n), k) == "device":
            block = (
                jnp.asarray(xu, dtype=jnp.float32),
                jnp.asarray(xu_valid),
                jnp.asarray(yi, dtype=jnp.float32),
                jnp.asarray(yi_valid),
                jnp.asarray(values, dtype=jnp.float32),
            )
        else:
            block = (xu, xu_valid, yi, yi_valid, values)
        self._blocks.append(block)
        self._pending += n

    @property
    def pending(self) -> int:
        return self._pending

    def solve(self):
        """One fold over everything accumulated; clears the session.
        Returns (new_xu, x_updated, new_yi, y_updated) like fold_in_batch,
        or None when nothing is pending."""
        if not self._blocks:
            return None
        blocks, self._blocks = self._blocks, []
        n, self._pending = self._pending, 0
        k = blocks[0][0].shape[1]
        backend = self._resolved_backend(n, k)
        if backend == "device" and all(
            isinstance(b[0], jnp.ndarray) for b in blocks
        ):
            # all-device micro-batch: concatenate + pad on device and call
            # the jitted kernel with the resident Gramians directly — the
            # only host traffic is the [n,k] results coming back
            xu, xu_valid, yi, yi_valid, values = (
                b[0] if len(blocks) == 1 else jnp.concatenate([blk[i] for blk in blocks])
                for i, b in enumerate(zip(*blocks))
            )
            m = _bucket(n)
            if m != n:
                pad = m - n
                xu = jnp.concatenate([xu, jnp.zeros((pad, k), xu.dtype)])
                yi = jnp.concatenate([yi, jnp.zeros((pad, k), yi.dtype)])
                xu_valid = jnp.concatenate([xu_valid, jnp.zeros(pad, bool)])
                yi_valid = jnp.concatenate([yi_valid, jnp.zeros(pad, bool)])
                values = jnp.concatenate([values, jnp.zeros(pad, values.dtype)])
            out = _fold_in_batch_jit(
                jnp.asarray(self.yty, dtype=jnp.float32),
                jnp.asarray(self.xtx, dtype=jnp.float32),
                xu, xu_valid, yi, yi_valid, values, self.implicit,
            )
            new_xu, x_upd, new_yi, y_upd = (np.asarray(o)[:n] for o in out)
            return new_xu, x_upd, new_yi, y_upd
        cat = [
            b[0] if len(blocks) == 1 else np.concatenate([np.asarray(blk[i]) for blk in blocks])
            for i, b in enumerate(zip(*blocks))
        ]
        return fold_in_batch(
            np.asarray(self.yty),
            np.asarray(self.xtx),
            *cat,
            self.implicit,
            backend=backend,
        )


class PartitionedFoldInSession:
    """Sharded fold-in: K disjoint accumulator slices over ONE shared
    Gramian pair.

    The sharded speed pipeline runs K independent parse->fold->publish
    chains; each chain folds only its own partitions' events. A naive
    per-shard :class:`FoldInSession` would re-upload the Gramians per
    shard per micro-batch; here every slice shares the same ``yty``/
    ``xtx`` references (device-resident via :func:`device_gramian` when
    the backend resolves there — uploaded ONCE for all K shards), and
    each shard's blocks accumulate in its own slice so concurrent
    ``add_block``/``solve_shard`` calls never touch shared state.

    Bit-identity: the fold math is row-wise independent — each event row
    gets its own einsum/target/GEMM against the same fixed Gramians (see
    ``_fold_half_host`` / ``_fold_half``) — so folding a shard's slice
    alone, or merging all slices into one solve (:meth:`solve`, shard
    order), produces EXACTLY the f32 bits a single session fed the same
    events would. Tests assert both forms against ``FoldInSession``.
    """

    def __init__(self, yty, xtx, implicit: bool, shards: int, backend: str = "auto") -> None:
        if shards < 1:
            raise ValueError("shards must be >= 1")
        self.implicit = implicit
        self.backend = backend
        self._slices = [
            FoldInSession(yty, xtx, implicit, backend) for _ in range(shards)
        ]

    @property
    def shards(self) -> int:
        return len(self._slices)

    @property
    def pending(self) -> int:
        return sum(s.pending for s in self._slices)

    def set_gramians(self, yty, xtx) -> None:
        """Swap in (typically device-resident) Gramians for every slice —
        one upload serves all K shards for the life of the Solver pair."""
        for s in self._slices:
            s.yty = yty
            s.xtx = xtx

    def session(self, shard: int) -> FoldInSession:
        """Shard ``shard``'s private slice. Distinct shards may use their
        slices concurrently; one shard's slice is single-threaded."""
        return self._slices[shard % len(self._slices)]

    def resolved_backend(self, n: int, k: int) -> str:
        return self._slices[0].resolved_backend(n, k)

    def add_block(self, shard: int, xu, xu_valid, yi, yi_valid, values) -> None:
        self.session(shard).add_block(xu, xu_valid, yi, yi_valid, values)

    def solve_shard(self, shard: int):
        """Fold shard ``shard``'s accumulated slice alone (its micro-batch
        boundary); other shards' slices are untouched."""
        return self.session(shard).solve()

    def solve(self):
        """The merge step: reconcile ALL slices in shard order into one
        solve — the cheap cross-shard synchronization point (list moves
        only; the concatenation happens inside the single solve)."""
        merged = self._slices[0]
        for s in self._slices[1:]:
            merged._blocks.extend(s._blocks)
            merged._pending += s._pending
            s._blocks = []
            s._pending = 0
        return merged.solve()
