"""ALS matrix factorization on TPU.

The TPU-native replacement for Spark MLlib's ALS (the hot loop of the
reference's ALSUpdate, app/oryx-app-mllib/.../als/ALSUpdate.java:116-124):
alternating normal-equation sweeps solved as batched k x k systems on
device.

Design (TPU-first, not a port):
- Ratings arrive as COO (user_idx, item_idx, value). Host-side they are
  grouped per-row and padded to a rectangle [N, D] of neighbor indices +
  values + mask — fixed shapes so XLA compiles one program per sweep.
- One half-sweep solves all users at once:
    implicit (Hu/Koren/Volinsky, MLlib semantics):
        c_ui = 1 + alpha*|r|, p_ui = 1 if r > 0 else 0
        A_u = YtY + sum_i (c-1) y_i y_i^T + lambda*I ;  b_u = sum_i c*p*y_i
    explicit (ALS-WR weighted-lambda):
        A_u = sum_i y_i y_i^T + lambda*n_u*I        ;  b_u = sum_i r y_i
  built with gathers + einsum (MXU work) and solved with batched
  jnp.linalg.solve. Users are processed in fixed-size chunks via lax.map
  to bound the [chunk, D, k] gather workspace in HBM.
- Sharding: neighbor structures are sharded over rows (users for the X
  half-sweep, items for the Y half-sweep) on the mesh's 'data' axis;
  factor matrices live replicated, so YtY needs no collective and the
  per-row gather is local. XLA inserts the all-gather of the updated
  factors between half-sweeps. This mirrors how the reference's MLlib
  block-partitions the rating matrix (SURVEY.md §2.12) but with the
  collectives compiled by XLA instead of hand-rolled shuffles.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from oryx_tpu.parallel.mesh import DATA_AXIS, pad_to_multiple


@dataclass
class NeighborBlock:
    """Padded per-row neighbor structure for one side of the factorization."""

    idx: np.ndarray  # [N, D] int32 indices into the other side's factors
    val: np.ndarray  # [N, D] float32 rating values (0 where padded)
    mask: np.ndarray  # [N, D] float32 1/0 validity

    @property
    def num_rows(self) -> int:
        return self.idx.shape[0]


def build_neighbor_block(
    row_idx: np.ndarray,
    col_idx: np.ndarray,
    values: np.ndarray,
    num_rows: int,
    pad_rows_to: int = 1,
) -> NeighborBlock:
    """Group COO entries by row and pad to [N, Dmax] rectangles."""
    order = np.argsort(row_idx, kind="stable")
    r, c, v = row_idx[order], col_idx[order], values[order]
    counts = np.bincount(r, minlength=num_rows)
    dmax = max(1, int(counts.max()) if counts.size else 1)
    n = pad_to_multiple(max(num_rows, 1), pad_rows_to)
    idx = np.zeros((n, dmax), dtype=np.int32)
    val = np.zeros((n, dmax), dtype=np.float32)
    mask = np.zeros((n, dmax), dtype=np.float32)
    # vectorized scatter: position of each entry within its row
    starts = np.concatenate([[0], np.cumsum(counts)])
    pos = np.arange(len(r)) - starts[r]
    idx[r, pos] = c
    val[r, pos] = v
    mask[r, pos] = 1.0
    return NeighborBlock(idx, val, mask)


def _solve_half_sweep(
    other: jnp.ndarray,  # [M, k] factors of the other side
    idx: jnp.ndarray,  # [N, D]
    val: jnp.ndarray,  # [N, D]
    mask: jnp.ndarray,  # [N, D]
    lam: float,
    alpha: float,
    implicit: bool,
    chunk: int,
) -> jnp.ndarray:
    k = other.shape[1]
    eye = jnp.eye(k, dtype=jnp.float32)
    yty = other.T @ other if implicit else None  # [k, k], free of the chunk loop

    def solve_chunk(args):
        cidx, cval, cmask = args  # [C, D]
        v = other[cidx] * cmask[..., None]  # [C, D, k]
        if implicit:
            conf_m1 = alpha * jnp.abs(cval) * cmask  # c - 1
            a = (
                yty[None]
                + jnp.einsum("cdk,cd,cdl->ckl", v, conf_m1, v)
                + lam * eye[None]
            )
            p = (cval > 0).astype(jnp.float32) * cmask
            b = jnp.einsum("cdk,cd->ck", v, (1.0 + alpha * jnp.abs(cval)) * p)
        else:
            n_u = cmask.sum(axis=1)  # ratings per row (ALS-WR lambda scaling)
            a = (
                jnp.einsum("cdk,cdl->ckl", v, v)
                + (lam * jnp.maximum(n_u, 1.0))[:, None, None] * eye[None]
            )
            b = jnp.einsum("cdk,cd->ck", v, cval * cmask)
        return jnp.linalg.solve(a, b[..., None])[..., 0]

    n = idx.shape[0]
    if n <= chunk:
        return solve_chunk((idx, val, mask))
    # bound HBM: process rows in fixed-size chunks sequentially
    num_chunks = n // chunk
    main = jax.lax.map(
        solve_chunk,
        (
            idx[: num_chunks * chunk].reshape(num_chunks, chunk, -1),
            val[: num_chunks * chunk].reshape(num_chunks, chunk, -1),
            mask[: num_chunks * chunk].reshape(num_chunks, chunk, -1),
        ),
    ).reshape(num_chunks * chunk, k)
    rem = n - num_chunks * chunk
    if rem:
        tail = solve_chunk((idx[-rem:], val[-rem:], mask[-rem:]))
        return jnp.concatenate([main, tail], axis=0)
    return main


@dataclass
class ALSModel:
    """Factorization result: row-major float32 factor matrices."""

    x: np.ndarray  # [num_users, k]
    y: np.ndarray  # [num_items, k]


def train_als(
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    values: np.ndarray,
    num_users: int,
    num_items: int,
    features: int,
    lam: float,
    alpha: float = 1.0,
    implicit: bool = True,
    iterations: int = 10,
    mesh: Optional[Mesh] = None,
    seed: int | None = None,
    chunk: int = 4096,
) -> ALSModel:
    """Full ALS training run.

    COO inputs are int32/float32 numpy arrays. With `mesh`, neighbor
    structures are row-sharded over the 'data' axis and factors replicated;
    single-device otherwise.
    """
    from oryx_tpu.common import rng as rng_mod

    num_shards = int(np.prod(mesh.devices.shape)) if mesh is not None else 1
    users = build_neighbor_block(user_idx, item_idx, values, num_users, num_shards)
    items = build_neighbor_block(item_idx, user_idx, values, num_items, num_shards)

    key = jax.random.key(rng_mod.next_seed() if seed is None else seed)
    # MLlib-style init: small random normal factors
    y0 = 0.1 * jax.random.normal(key, (items.num_rows, features), dtype=jnp.float32)

    sweep = functools.partial(
        _solve_half_sweep, lam=lam, alpha=alpha, implicit=implicit, chunk=chunk
    )

    def run(u_idx_, u_val_, u_mask_, i_idx_, i_val_, i_mask_, y_init):
        x = jnp.zeros((u_idx_.shape[0], features), dtype=jnp.float32)
        y = y_init

        def body(_, carry):
            x_, y_ = carry
            x_ = sweep(y_, u_idx_, u_val_, u_mask_)
            y_ = sweep(x_, i_idx_, i_val_, i_mask_)
            return x_, y_

        return jax.lax.fori_loop(0, iterations, body, (x, y))

    if mesh is not None:
        row_sharded = NamedSharding(mesh, P(DATA_AXIS, None))
        repl = NamedSharding(mesh, P())
        u_args = [jax.device_put(a, row_sharded) for a in (users.idx, users.val, users.mask)]
        i_args = [jax.device_put(a, row_sharded) for a in (items.idx, items.val, items.mask)]
        y0 = jax.device_put(np.asarray(y0), repl)
        run_c = jax.jit(
            run,
            in_shardings=(row_sharded,) * 3 + (row_sharded,) * 3 + (repl,),
            out_shardings=(row_sharded, row_sharded),
        )
        x, y = run_c(*u_args, *i_args, y0)
    else:
        run_c = jax.jit(run)
        x, y = run_c(users.idx, users.val, users.mask, items.idx, items.val, items.mask, y0)

    x = np.asarray(x)[:num_users]
    y = np.asarray(y)[:num_items]
    return ALSModel(x=x, y=y)


# -- evaluation --------------------------------------------------------------


def predict_pairs(x: np.ndarray, y: np.ndarray, user_idx: np.ndarray, item_idx: np.ndarray) -> np.ndarray:
    """Predicted strengths for (user, item) pairs (on device, batched)."""

    @jax.jit
    def _pred(xa, ya, ui, ii):
        return jnp.sum(xa[ui] * ya[ii], axis=-1)

    return np.asarray(_pred(x, y, user_idx, item_idx))


def rmse(x: np.ndarray, y: np.ndarray, user_idx, item_idx, values) -> float:
    """Root mean squared error over test pairs (Evaluation.rmse analogue,
    app/oryx-app-mllib/.../als/Evaluation.java:49-63)."""
    if len(values) == 0:
        return float("nan")
    pred = predict_pairs(x, y, user_idx, item_idx)
    return float(np.sqrt(np.mean((pred - values) ** 2)))


def mean_auc(
    x: np.ndarray,
    y: np.ndarray,
    user_idx: np.ndarray,
    item_idx: np.ndarray,
    rng: np.random.Generator,
) -> float:
    """Mean per-user AUC with about as many sampled negatives as positives
    per user (Evaluation.areaUnderCurve, Evaluation.java:70-136)."""
    if len(user_idx) == 0:
        return float("nan")
    all_items = np.unique(item_idx)
    order = np.argsort(user_idx, kind="stable")
    uu, ii = user_idx[order], item_idx[order]
    uniq_users = np.unique(uu)
    starts = np.searchsorted(uu, uniq_users, side="left")
    ends = np.searchsorted(uu, uniq_users, side="right")
    aucs = []
    for u, s, e in zip(uniq_users, starts, ends):
        pos = ii[s:e]
        pos_set = set(pos.tolist())
        num_pos = len(pos)
        # sample negatives: bounded tries like the reference (numItems tries)
        cand = rng.choice(all_items, size=min(len(all_items), 4 * num_pos))
        neg = np.asarray([c for c in cand if c not in pos_set][:num_pos], dtype=np.int64)
        if len(neg) == 0:
            continue
        pos_scores = y[pos] @ x[u]
        neg_scores = y[neg] @ x[u]
        correct = (pos_scores[:, None] > neg_scores[None, :]).sum()
        aucs.append(correct / (len(pos_scores) * len(neg_scores)))
    return float(np.mean(aucs)) if aucs else float("nan")


# ---------------------------------------------------------------------------
# Speed-layer fold-in, batched on device
# ---------------------------------------------------------------------------
#
# The reference folds in one event at a time (ALSUtils.computeUpdatedXu:
# 74-106 inside ALSSpeedModelManager.buildUpdates' parallelStream). All
# events in a micro-batch read the PRE-batch model state (updates travel
# via the update topic, not in-place), so the whole batch is one
# data-parallel computation: a single [k,k] Cholesky factorization per
# side reused against an [n,k] right-hand-side block on the MXU.


def _batch_target_qui(implicit: bool, values, current):
    """Vectorized ALSUtils.computeTargetQui:37-59; NaN = no update."""
    if not implicit:
        return values
    pos = (values > 0.0) & (current < 1.0)
    t_pos = current + (values / (1.0 + values)) * (1.0 - jnp.maximum(0.0, current))
    neg = (values < 0.0) & (current > 0.0)
    t_neg = current + (values / (values - 1.0)) * (0.0 - jnp.minimum(1.0, current))
    return jnp.where(pos, t_pos, jnp.where(neg, t_neg, jnp.nan))


def _fold_half(ata, vecs_own, own_valid, vecs_other, other_valid, values, implicit):
    """New own-side vectors after events against the other side's vectors.

    vecs_own[n,k] current vectors (zeros where own_valid is False — a
    brand-new row starts from a "don't know" prior of 0.5), vecs_other
    the interacting vectors. Returns (new_vecs[n,k], updated[n])."""
    qui = jnp.where(own_valid, jnp.sum(vecs_own * vecs_other, axis=1), 0.0)
    current = jnp.where(own_valid, qui, 0.5)
    target = _batch_target_qui(implicit, values, current)
    d_qui = target - qui
    rhs = d_qui[:, None] * vecs_other  # [n, k]
    chol = jax.scipy.linalg.cho_factor(ata)
    d_vec = jax.scipy.linalg.cho_solve(chol, rhs.T).T
    # Cholesky of a near-singular AtA yields NaNs in float32 (the host
    # Solver's QR threshold/lstsq fallback has no device analogue), so
    # whole rows that came out non-finite are re-solved via pseudo-inverse
    # rather than published corrupted. lax.cond keeps the SVD off the hot
    # path when the factorization was healthy (the common case).
    row_ok = jnp.all(jnp.isfinite(d_vec), axis=1, keepdims=True)
    d_vec = jax.lax.cond(
        jnp.all(row_ok),
        lambda d, _a, _r: d,
        lambda d, a, r: jnp.where(row_ok, d, (jnp.linalg.pinv(a, rcond=1e-5) @ r.T).T),
        d_vec,
        ata,
        rhs,
    )
    new_vecs = jnp.where(own_valid[:, None], vecs_own, 0.0) + d_vec
    updated = other_valid & ~jnp.isnan(target) & jnp.all(jnp.isfinite(d_vec), axis=1)
    return jnp.where(updated[:, None], new_vecs, 0.0), updated


@functools.partial(jax.jit, static_argnames=("implicit",))
def _fold_in_batch_jit(yty, xtx, xu, xu_valid, yi, yi_valid, values, implicit):
    new_xu, x_upd = _fold_half(yty, xu, xu_valid, yi, yi_valid, values, implicit)
    new_yi, y_upd = _fold_half(xtx, yi, yi_valid, xu, xu_valid, values, implicit)
    return new_xu, x_upd, new_yi, y_upd


def _fold_half_host(ata, vecs_own, own_valid, vecs_other, other_valid, values, implicit):
    """Host (BLAS) twin of _fold_half: float32 vectors/solves (same
    precision as the device path), float64 target math (scalar parity)."""
    vo = np.asarray(vecs_own, dtype=np.float32)
    vt = np.asarray(vecs_other, dtype=np.float32)
    values = values.astype(np.float64)
    qui = np.where(own_valid, np.einsum("nk,nk->n", vo, vt, dtype=np.float64), 0.0)
    current = np.where(own_valid, qui, 0.5)
    if implicit:
        with np.errstate(divide="ignore", invalid="ignore"):
            t_pos = current + (values / (1.0 + values)) * (1.0 - np.maximum(0.0, current))
            t_neg = current + (values / (values - 1.0)) * (0.0 - np.minimum(1.0, current))
        target = np.where(
            (values > 0.0) & (current < 1.0),
            t_pos,
            np.where((values < 0.0) & (current > 0.0), t_neg, np.nan),
        )
    else:
        target = values
    d_qui = np.nan_to_num(target - qui).astype(np.float32)
    rhs = d_qui[:, None] * vt
    ata32 = np.asarray(ata, dtype=np.float32)
    try:
        # AtA is SPD: Cholesky factor once, then one BLAS triangular solve
        # over all n right-hand sides (~3x the general-LU path np.linalg
        # .solve takes, which dominated the 100k-event micro-batch profile)
        import scipy.linalg as sla

        chol = sla.cho_factor(ata32, lower=True, check_finite=False)
        d_vec = sla.cho_solve(chol, rhs.T, check_finite=False).T
    except Exception:
        d_vec = np.full_like(rhs, np.nan)
    # same safety net as the device path: singular/ill-conditioned AtA
    # falls back to a pseudo-inverse solve, and rows that still come out
    # non-finite are dropped instead of published
    bad = ~np.isfinite(d_vec).all(axis=1)
    if bad.any():
        d_lstsq = (np.linalg.pinv(ata32, rcond=1e-5) @ rhs.T).T
        d_vec = np.where(bad[:, None], d_lstsq, d_vec)
    new = np.where(own_valid[:, None], vo, 0.0) + d_vec
    updated = other_valid & ~np.isnan(target) & np.isfinite(d_vec).all(axis=1)
    return np.where(updated[:, None], new, 0.0).astype(np.float32, copy=False), updated


def _bucket(n: int) -> int:
    """Pad batch sizes to power-of-two buckets so the jitted fold-in
    compiles once per bucket, not once per micro-batch size."""
    return max(256, 1 << (n - 1).bit_length())


def fold_in_batch(
    yty: np.ndarray,
    xtx: np.ndarray,
    xu: np.ndarray,
    xu_valid: np.ndarray,
    yi: np.ndarray,
    yi_valid: np.ndarray,
    values: np.ndarray,
    implicit: bool,
    backend: str = "auto",
) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Fold a micro-batch of n (user, item, value) events into both factor
    sides at once. xu/yi are the events' current vectors ([n,k], zero rows
    where the id is new, flagged by the valid masks). Returns
    (new_xu[n,k], x_updated[n], new_yi[n,k], y_updated[n]) — rows where
    the updated flag is False carry no update (reference: None returns of
    ALSUtils.computeUpdatedXu).

    backend: 'device' (jit, batch padded to power-of-two buckets),
    'host' (float64 BLAS), or 'auto' — device once the batch is big
    enough that the k x k solves dominate host<->device transfer."""
    n, k = xu.shape
    if backend == "auto":
        # the k x k solves are tiny; device only pays off once the batch is
        # large enough that MXU throughput beats host BLAS plus transfer
        backend = "device" if n * max(k, 1) >= 8_000_000 else "host"
    if backend == "host":
        new_xu, x_upd = _fold_half_host(yty, xu, xu_valid, yi, yi_valid, values, implicit)
        new_yi, y_upd = _fold_half_host(xtx, yi, yi_valid, xu, xu_valid, values, implicit)
        return new_xu, x_upd, new_yi, y_upd
    m = _bucket(n)
    if m != n:
        pad = m - n
        xu = np.concatenate([xu, np.zeros((pad, k), xu.dtype)])
        yi = np.concatenate([yi, np.zeros((pad, k), yi.dtype)])
        xu_valid = np.concatenate([xu_valid, np.zeros(pad, bool)])
        yi_valid = np.concatenate([yi_valid, np.zeros(pad, bool)])
        values = np.concatenate([values, np.zeros(pad, values.dtype)])
    out = _fold_in_batch_jit(
        jnp.asarray(yty, dtype=jnp.float32),
        jnp.asarray(xtx, dtype=jnp.float32),
        jnp.asarray(xu, dtype=jnp.float32),
        jnp.asarray(xu_valid),
        jnp.asarray(yi, dtype=jnp.float32),
        jnp.asarray(yi_valid),
        jnp.asarray(values, dtype=jnp.float32),
        implicit,
    )
    new_xu, x_upd, new_yi, y_upd = (np.asarray(o)[:n] for o in out)
    return new_xu, x_upd, new_yi, y_upd
