"""TPU compute kernels: the numerical engines the reference outsources to
Spark MLlib / Commons Math (SURVEY.md intro). ALS normal-equation sweeps,
k-means Lloyd iterations, forest training, top-N scoring — all as
JAX/XLA programs over a device mesh.
"""
