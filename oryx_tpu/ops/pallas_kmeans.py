"""Pallas TPU kernel: one fused Lloyd sweep for k-means.

The XLA formulation of a Lloyd iteration (ops/kmeans.py:_lloyd_run —
distance matmul, argmin, then segment_sum) walks the points array twice
and materializes the [n, k] distance matrix in HBM. This kernel fuses the
whole sweep into a single pass:

- grid over point blocks; centers stay resident in VMEM across steps;
- each step computes the block's squared distances on the MXU, takes the
  per-point argmin, and immediately reduces the block into partial
  centroid sums via a one-hot matmul ``onehot(assign).T @ points`` (MXU
  again) plus per-cluster counts and the block's cost;
- partials accumulate into the kernel outputs across sequential grid
  steps (TPU grids execute in order on a core), so HBM sees the points
  exactly once per sweep and only [k, d] + [k] + [1] results ever come
  back.

The reference delegates this loop to Spark MLlib's KMeans
(app/oryx-app-mllib/.../kmeans/KMeansUpdate.java:116-117), where each
iteration is a cluster-wide map-reduce; here an iteration is one kernel
launch. Used by train_kmeans on TPU; the XLA path remains for meshes
(auto-sharded) and non-TPU backends, and tests run this kernel under the
Pallas interpreter.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

BLOCK_N = 1024


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def fits_vmem(k: int, d: int, budget_bytes: int = 12 * 1024 * 1024) -> bool:
    """Whether one sweep's block working set (points block + centers/sums
    + distance and one-hot blocks, double-buffered) fits the VMEM budget.
    Lives here so the estimate tracks the kernel's actual shapes."""
    kp = max(8, _ceil_to(k, 8))
    working = 4 * 2 * (BLOCK_N * d + 2 * kp * d + 2 * BLOCK_N * kp + kp)
    return working <= budget_bytes


def _sweep_kernel(pts_ref, ctr_ref, sums_ref, counts_ref, cost_ref, *, n_items, k_real):
    i = pl.program_id(0)
    pts = pts_ref[:]  # [B, d]
    ctr = ctr_ref[:]  # [kp, d]
    b = pts.shape[0]
    kp = ctr.shape[0]
    precision = jax.lax.Precision.HIGHEST
    d2 = (
        jnp.sum(pts * pts, axis=1, keepdims=True)
        - 2.0 * jnp.dot(pts, ctr.T, preferred_element_type=jnp.float32, precision=precision)
        + jnp.sum(ctr * ctr, axis=1)[None, :]
    )  # [B, kp]
    col = jax.lax.broadcasted_iota(jnp.int32, (b, kp), 1)
    d2 = jnp.where(col < k_real, d2, jnp.float32(jnp.inf))  # padded centers lose
    row_global = jax.lax.broadcasted_iota(jnp.int32, (b, 1), 0) + i * b
    valid = row_global < n_items  # [B, 1] padding rows contribute nothing
    mind2 = jnp.min(d2, axis=1, keepdims=True)  # [B, 1]
    # first center attaining the min (stable tie-break, like jnp.argmin)
    amin = jnp.min(jnp.where(d2 == mind2, col, jnp.int32(2**31 - 1)), axis=1, keepdims=True)
    onehot = ((col == amin) & valid).astype(jnp.float32)  # [B, kp]
    psums = jnp.dot(onehot.T, pts, preferred_element_type=jnp.float32, precision=precision)
    pcounts = jnp.sum(onehot, axis=0)[None, :]  # [1, kp]
    pcost = jnp.sum(jnp.where(valid, jnp.maximum(mind2, 0.0), 0.0))

    # Mosaic can't store a bare scalar into VMEM ("Cannot store scalars to
    # VMEM" on hardware; the interpreter accepts it) — keep the cost as a
    # (1, 1) tile end to end.
    pcost_tile = jnp.reshape(pcost, (1, 1))

    @pl.when(i == 0)
    def _():
        sums_ref[:] = psums
        counts_ref[:] = pcounts
        cost_ref[:, :] = pcost_tile

    @pl.when(i > 0)
    def _():
        sums_ref[:] += psums
        counts_ref[:] += pcounts
        cost_ref[:, :] += pcost_tile


@functools.partial(jax.jit, static_argnames=("n_items", "k_real", "interpret"))
def _sweep(points, centers, *, n_items, k_real, interpret):
    return _sweep_impl(points, centers, n_items=n_items, k_real=k_real, interpret=interpret)


@functools.partial(
    jax.jit, static_argnames=("iterations", "n_items", "k_real", "interpret")
)
def _lloyd_fused(points, centers0, *, iterations, n_items, k_real, interpret):
    """All Lloyd iterations in ONE dispatch: lax.fori_loop over the fused
    sweep kernel, centers updated on device between sweeps. Per-iteration
    host dispatch (one round-trip each on a remote/tunneled chip) was the
    dominant cost of the unfused loop at bench scale."""

    def body(_, ctr):
        sums, counts, _cost = _sweep_impl(
            points, ctr, n_items=n_items, k_real=k_real, interpret=interpret
        )
        return jnp.where(
            counts[:, None] > 0, sums / jnp.maximum(counts, 1.0)[:, None], ctr
        )

    ctr = jax.lax.fori_loop(0, iterations, body, centers0)
    sums, counts, cost = _sweep_impl(
        points, ctr, n_items=n_items, k_real=k_real, interpret=interpret
    )
    return ctr, counts, cost


def _sweep_impl(points, centers, *, n_items, k_real, interpret):
    """One fused assignment+reduction pass. points [n_pad, d] (rows beyond
    n_items are padding), centers [kp, d] (rows beyond k_real are padding).
    Returns (sums [kp, d], counts [kp], cost)."""
    n_pad, d = points.shape
    kp = centers.shape[0]
    grid = n_pad // BLOCK_N
    kernel = functools.partial(_sweep_kernel, n_items=n_items, k_real=k_real)
    common = dict(memory_space=_VMEM) if (_VMEM is not None and not interpret) else {}
    sums, counts, cost = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((BLOCK_N, d), lambda i: (i, 0), **common),
            pl.BlockSpec((kp, d), lambda i: (0, 0), **common),
        ],
        out_specs=[
            pl.BlockSpec((kp, d), lambda i: (0, 0), **common),
            pl.BlockSpec((1, kp), lambda i: (0, 0), **common),
            pl.BlockSpec((1, 1), lambda i: (0, 0), **common),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kp, d), jnp.float32),
            jax.ShapeDtypeStruct((1, kp), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ],
        interpret=interpret,
    )(points, centers)
    return sums, counts[0], cost[0, 0]


@functools.partial(
    jax.jit,
    static_argnames=("iterations", "batch", "n_items", "k_real", "interpret"),
)
def _minibatch_fused(
    points, centers0, key, *, iterations, batch, n_items, k_real, interpret
):
    """Mini-batch k-means (Sculley 2010) with every pass through the fused
    sweep kernel: each iteration gathers a random `batch`-point sample,
    runs ONE sweep over it (assignment + per-center sums/counts in a
    single kernel), and moves each touched center toward the batch mean
    with learning rate 1/v_c (v_c = cumulative assigned count). The
    whole schedule is one dispatch; a final full-data sweep yields the
    reported counts/cost."""
    bpad = max(BLOCK_N, _ceil_to(batch, BLOCK_N))
    kp = centers0.shape[0]

    def body(_, carry):
        ctr, v, key = carry
        key, ks = jax.random.split(key)
        # gather bpad rows, of which the sweep counts only the first
        # `batch` (rows past n_items-bounded indices never occur; rows
        # past `batch` are masked off by the kernel's n_items guard)
        idx = jax.random.randint(ks, (bpad,), 0, n_items)
        xb = points[idx]
        sums, counts, _ = _sweep_impl(
            xb, ctr, n_items=batch, k_real=k_real, interpret=interpret
        )
        v = v + counts
        ctr = ctr + (sums - counts[:, None] * ctr) / jnp.maximum(v, 1.0)[:, None]
        return ctr, v, key

    ctr, _, _ = jax.lax.fori_loop(
        0, iterations, body, (centers0, jnp.zeros(kp, jnp.float32), key)
    )
    sums, counts, cost = _sweep_impl(
        points, ctr, n_items=n_items, k_real=k_real, interpret=interpret
    )
    return ctr, counts, cost


def minibatch_lloyd_pallas(
    points,
    centers0: np.ndarray,
    iterations: int,
    batch: int,
    key,
    interpret: bool | None = None,
    n_items: int | None = None,
) -> tuple[np.ndarray, np.ndarray, float]:
    """Mini-batch counterpart of lloyd_pallas: same (centers, counts, cost)
    contract, but iterations touch `batch` sampled points each instead of
    all n — steady-state cost scales with the batch size. `key` is a JAX
    PRNG key driving the per-iteration samples."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = centers0.shape[0]
    kp = max(8, _ceil_to(k, 8))
    if isinstance(points, jax.Array):
        if n_items is None:
            raise ValueError("n_items is required for pre-uploaded points")
        pts_dev = points
        n, d = n_items, points.shape[1]
    else:
        n = np.asarray(points).shape[0]
        pts_dev = jnp.asarray(pad_to_block(np.asarray(points, dtype=np.float32)))
        d = pts_dev.shape[1]
    ctr = np.zeros((kp, d), np.float32)
    ctr[:k] = np.asarray(centers0, np.float32)
    ctr_dev, counts, cost = _minibatch_fused(
        pts_dev,
        jnp.asarray(ctr),
        key,
        iterations=iterations,
        batch=min(batch, n),
        n_items=n,
        k_real=k,
        interpret=interpret,
    )
    return np.asarray(ctr_dev[:k]), np.asarray(counts[:k]), float(cost)


def pad_to_block(points: np.ndarray) -> np.ndarray:
    """Points padded with zero rows to a BLOCK_N multiple (the kernel's
    grid granule)."""
    n, d = points.shape
    n_pad = max(BLOCK_N, _ceil_to(n, BLOCK_N))
    if n_pad == n:
        return points
    return np.concatenate([points, np.zeros((n_pad - n, d), np.float32)])


def lloyd_pallas(
    points,
    centers0: np.ndarray,
    iterations: int,
    interpret: bool | None = None,
    n_items: int | None = None,
) -> tuple[np.ndarray, np.ndarray, float]:
    """Lloyd iterations via the fused sweep; returns (centers, counts, cost)
    with the same semantics as ops.kmeans._lloyd_run (final counts/cost
    measured against the final centers). ``points`` may be a device array
    already padded to a BLOCK_N multiple (pass ``n_items`` = real row
    count) — that lets callers start the upload before host-side init."""
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k = centers0.shape[0]
    kp = max(8, _ceil_to(k, 8))
    if isinstance(points, jax.Array):
        if n_items is None:
            raise ValueError("n_items is required for pre-uploaded points")
        if points.shape[0] % BLOCK_N:
            raise ValueError("pre-uploaded points must be padded to BLOCK_N")
        if points.dtype != jnp.float32:
            raise ValueError("pre-uploaded points must be float32")
        n, d = n_items, points.shape[1]
        pts_dev = points
    else:
        n = np.asarray(points).shape[0]
        points = pad_to_block(np.asarray(points, dtype=np.float32))
        d = points.shape[1]
        pts_dev = jnp.asarray(points)
    ctr = np.zeros((kp, d), np.float32)
    ctr[:k] = centers0
    ctr_dev = jnp.asarray(ctr)
    ctr_dev, counts, cost = _lloyd_fused(
        pts_dev, ctr_dev, iterations=iterations, n_items=n, k_real=k, interpret=interpret
    )
    return (
        np.asarray(ctr_dev[:k]),
        np.asarray(counts[:k]),
        float(cost),
    )
