"""IVF approximate-retrieval tier for the serving scan.

The exact quantized scan (docs/serving-scan.md) streams every item row per
query, which caps single-chip serving near 1M items. This module turns
that ceiling into a 10-100M-item story with the classic inverted-file
(Faiss-style) two-stage retrieval, built entirely from machinery already
in the repo:

1. **Coarse quantizer** — the item matrix is clustered into ~sqrt(n)
   cells with ``ops/kmeans.py`` (k-means|| init + mini-batch Lloyd); each
   item is assigned to its nearest centroid.
2. **Cell-contiguous layout** — items are permuted so every cell occupies
   a contiguous, tile-aligned run of the same two-plane int8 codes the
   exact scan uses (``StreamingItemMatrix``'s per-row quantization rules
   verbatim, so each item's codes are bit-identical to a fresh
   ``upload``). The primary plane is additionally stored ITEM-major: a
   probed run is then a contiguous byte range, which is what makes the
   cell scan a dense GEMM instead of a strided gather (a feature-major
   gather pulls one cacheline per byte — measured 25x slower).
3. **Routing** — a query dots against the [feat, n_cells] centroid matrix
   and keeps the top ``nprobe`` cells.
4. **Probed scan + exact rescore** — a query group's probed cells union
   into a tile list; each tile is one contiguous ``dynamic_slice`` +
   plane-1 GEMM reduced to per-chunk maxes (the same chunk-max ranking
   the exact scan uses), and the top chunks then rescore through the
   same ``pallas_topn._gathered_pair_scores`` two-plane epilogue as the
   exact path's candidate tail. Scanning the group UNION means every
   query sees a superset of its own probed cells — recall only goes up —
   while the int8->f32 tile conversion amortizes across the group.

Speed-layer visibility: ``update_rows`` keeps fold-ins visible through
the ANN path with a **pending-overlay list** — touched rows leave the
cell structure (their slot id is tombstoned) and land in a small
device-resident overlay of dequantized rows that every query scans
exactly and merges before the final top-k. The overlay holds the rows'
two-plane DEQUANTIZED values, so overlay scores match a fresh upload's
quantized scores to f32 rounding. A full overlay raises
:class:`IVFOverlayFull`; callers rebuild the index (the serving model's
full-rebuild path).

Exactness contract: with ``nprobe >= n_cells`` every cell is probed, the
candidate set is the whole catalog ordered by ascending item id, and the
scores come from the shared epilogue on the SAME feature-major planes —
the result reproduces the exact int8 scan's top-N bit-for-bit (tested in
tests/ops/test_ivf_scan.py; the item-major plane exists only for stage-1
ranking, whose rounding never touches the returned scores).
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.ops import pallas_topn as pt

# -- knobs (oryx.serving.scan.ann.*, pushed by ServingLayer) ------------------

# master switch for the serving tier (ops-level entry points work either way)
ANN_ENABLED = False
# coarse cells; 0 = auto round(sqrt(n))
N_CELLS = 0
# cells probed per query; 0 = derive from PROBE_FRACTION
NPROBE = 0
# fraction of items a query should scan when NPROBE is 0 (nprobe =
# round(fraction * n_cells)); the knob tools/load_benchmark.py maps the
# reference harness's LSH sampleRate onto. 1% probes measure recall@10
# ~0.997 on clustered catalogs at 200k-1M items (see docs/serving-scan.md
# for the recall/latency trade-off and the data-model caveat)
PROBE_FRACTION = 0.01
# catalogs below this stay on the exact scan (clustering overhead isn't
# worth it when one GEMM streams the whole matrix)
MIN_ITEMS = 100_000
# pending-overlay rows (speed-layer updates between index rebuilds)
OVERLAY_CAPACITY = 4096
# queries per scan group: the probed-cell UNION of a group shares one
# pass of tile gather + GEMM, so bigger groups amortize memory traffic
# but inflate the union (more cells scanned per query); 4-8 measures
# best on the host stage-1 path, where the take is already memcpy-fast
QUERY_BLOCK = 8
# chunks per scan tile: tiles are the dynamic_slice granularity of the
# probed scan, and cells pad to a tile multiple — bigger tiles mean
# fewer, beefier GEMM steps but more padding per cell
TILE_CHUNKS = 8
# None = auto (on for the CPU backend): keep a host-resident dequantized
# f32 copy of the item planes and run the probed scan through numpy
# block-take + BLAS. XLA:CPU gathers byte-at-a-time (~0.4 GB/s measured)
# and converts int8->f32 at ~0.5 Gelem/s, so the device probed path
# loses its sublinearity to data movement; numpy block-take runs at
# memcpy speed and the f32 plane never converts at query time. Costs
# 4x the primary plane's bytes in HOST memory (10 GB at 10M x 256).
HOST_STAGE1 = None

# rows assigned to centroids per jitted block during build
_ASSIGN_BLOCK = 65536


def configure_ann(
    enabled=None,
    cells=None,
    nprobe=None,
    probe_fraction=None,
    min_items=None,
    overlay_capacity=None,
    query_block=None,
    tile_chunks=None,
    host_stage1=None,
):
    """Set the IVF defaults (config: oryx.serving.scan.ann.*). Like
    ``configure_scan``, call before the first dispatch — jitted programs
    bake the derived static shapes in at trace time, and the host stage-1
    plane only materializes at build time."""
    global ANN_ENABLED, N_CELLS, NPROBE, PROBE_FRACTION
    global MIN_ITEMS, OVERLAY_CAPACITY, QUERY_BLOCK, TILE_CHUNKS, HOST_STAGE1
    if enabled is not None:
        ANN_ENABLED = bool(enabled)
    if cells is not None:
        N_CELLS = int(cells)
    if nprobe is not None:
        NPROBE = int(nprobe)
    if probe_fraction is not None:
        PROBE_FRACTION = float(probe_fraction)
    if min_items is not None:
        MIN_ITEMS = int(min_items)
    if overlay_capacity is not None:
        OVERLAY_CAPACITY = int(overlay_capacity)
    if query_block is not None:
        QUERY_BLOCK = int(query_block)
    if tile_chunks is not None:
        TILE_CHUNKS = int(tile_chunks)
    if host_stage1 is not None:
        HOST_STAGE1 = bool(host_stage1)


def _host_stage1_active() -> bool:
    if HOST_STAGE1 is not None:
        return HOST_STAGE1
    return jax.default_backend() == "cpu"


def ann_active(n_items: int) -> bool:
    """Should the serving tier route this catalog through IVF?"""
    return ANN_ENABLED and n_items >= MIN_ITEMS


class IVFOverlayFull(RuntimeError):
    """The pending-overlay list is out of slots: rebuild the index."""


@dataclasses.dataclass(frozen=True, eq=False)
class IVFIndex:
    """Cell-contiguous two-plane int8 item matrix + routing table.

    Device arrays are immutable; ``update_rows`` returns a new handle
    (sharing unchanged planes). The host-side routing tables
    (``id_to_slot``, ``ov_map``) are bookkeeping for the update path and
    are mutated in place under the caller's serialization (the serving
    model updates under its cache lock), never read at query time.

    The slot space ends with one all-padding guard tile (slot ids -1,
    zero codes): tile/chunk selections that have nothing real to point
    at aim there, so downstream gathers always hit masked slots instead
    of a neighbouring cell's items (which would duplicate results).
    """

    # permuted, per-cell tile-padded planes in the exact scan's
    # feature-major layout; padding slots carry scale 1 / codes 0
    mat_t: jax.Array  # [kf_pad, n_slots] int8
    resid: jax.Array  # [kf_pad, n_slots] int8
    # item-major copy of the PRIMARY plane for the dense probed scan
    mat_rows: jax.Array  # [n_slots, kf_pad] int8
    scales: jax.Array  # [1, n_slots] f32
    resid_scales: jax.Array  # [1, n_slots] f32
    norms: jax.Array  # [1, n_slots] f32 (original f32 row norms)
    # slot -> original item id; -1 = padding or superseded by the overlay
    slot_ids: jax.Array  # [n_slots] int32
    # routing table
    centroids_t: jax.Array  # [kf_pad, n_cells] f32
    centroid_norms: jax.Array  # [n_cells] f32
    chunk_start: jax.Array  # [n_cells] int32, in chunk units
    chunk_count: jax.Array  # [n_cells] int32 (occupied chunks only)
    # pending overlay: dequantized rows of updated items, scanned exactly
    ov_rows: jax.Array  # [cap, kf_pad] f32
    ov_ids: jax.Array  # [cap] int32, -1 = empty
    ov_norms: jax.Array  # [cap] f32
    n_items: int
    features: int  # true feature count before int8 sublane padding
    chunk: int  # items per candidate chunk (layout constant)
    tile_chunks: int  # chunks per scan tile (layout constant)
    # host-side routing/update bookkeeping
    chunk_count_host: np.ndarray  # [n_cells] int64
    tile_start_host: np.ndarray  # [n_cells] int64, in tile units
    tile_count_host: np.ndarray  # [n_cells] int64
    id_to_slot: np.ndarray  # [n_items at build] int32, -1 = overlay/none
    ov_map: dict  # item id -> overlay slot
    ov_used: int
    # host stage-1 mirrors (None when HOST_STAGE1 resolves off): the
    # dequantized two-plane f32 item rows (q1*s1 + q2*s2), scanned by
    # numpy block-take + BLAS on the CPU backend; same quantized values
    # as the device planes, so recall and scores match to f32 rounding
    host_plane: np.ndarray | None = None  # [n_slots, kf_pad] f32
    slot_ids_host: np.ndarray | None = None  # [n_slots] int32
    norms_host: np.ndarray | None = None  # [n_slots] f32
    ov_rows_host: np.ndarray | None = None  # [cap, kf_pad] f32
    ov_ids_host: np.ndarray | None = None  # [cap] int32
    ov_norms_host: np.ndarray | None = None  # [cap] f32

    @property
    def n_cells(self) -> int:
        return self.centroids_t.shape[1]

    @property
    def n_slots(self) -> int:
        return self.mat_t.shape[1]

    @property
    def num_features(self) -> int:
        return self.features

    @property
    def quantized(self) -> bool:
        return True

    def resolve_nprobe(self, nprobe: int | None = None) -> int:
        """Probed cells per query: explicit arg > NPROBE knob > fraction."""
        p = nprobe if nprobe is not None else NPROBE
        if not p:
            p = int(round(PROBE_FRACTION * self.n_cells))
        return max(1, min(int(p), self.n_cells))


# -- build --------------------------------------------------------------------


@jax.jit
def _assign_block_dev(blk, cent_t, half_c2):
    # nearest centroid by L2 == argmax(y.c - ||c||^2/2); HIGHEST so
    # borderline assignments match the kmeans trainer's f32 distances
    s = (
        jnp.dot(
            blk,
            cent_t,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        - half_c2
    )
    return jnp.argmin(-s, axis=1).astype(jnp.int32)


def _assign_cells(mat: np.ndarray, centers: np.ndarray) -> np.ndarray:
    """Nearest-centroid id per item row, in fixed-shape device blocks."""
    n = len(mat)
    cent_t = jnp.asarray(centers.T)
    half = jnp.asarray(0.5 * np.einsum("kd,kd->k", centers, centers)[None, :])
    out = np.empty(n, np.int32)
    block = min(_ASSIGN_BLOCK, n)
    for beg in range(0, n, block):
        sub = np.asarray(mat[beg : beg + block], dtype=np.float32)
        real = len(sub)
        if real < block:  # pad the tail so two shapes compile, not many
            sub = np.concatenate([sub, np.zeros((block - real, sub.shape[1]), np.float32)])
        out[beg : beg + real] = np.asarray(
            _assign_block_dev(jnp.asarray(sub), cent_t, half)
        )[:real]
    return out


def _pow2_ceil(x: int) -> int:
    return 1 << max(0, int(x) - 1).bit_length()


def build_ivf(
    matrix: np.ndarray,
    *,
    n_cells: int | None = None,
    seed: int = 0,
    train_sample: int = 200_000,
    iterations: int = 8,
    overlay_capacity: int | None = None,
) -> IVFIndex:
    """Cluster, permute cell-contiguous, quantize, and ship to device.

    The coarse quantizer trains on a uniform sample (mini-batch Lloyd
    over k-means|| seeds); the full catalog then assigns to the trained
    centroids in device blocks. Rows quantize with the exact scan's
    per-row rules, streamed in million-row slices so the host transient
    stays bounded at 10M+ items.
    """
    mat = np.asarray(matrix, dtype=np.float32)
    n, feat = mat.shape
    if n == 0:
        raise ValueError("cannot build an IVF index over zero items")
    chunk = max(8, int(pt._CHUNK))
    tile_chunks = max(1, TILE_CHUNKS)
    tile_slots = tile_chunks * chunk
    cells = int(n_cells if n_cells is not None else (N_CELLS or round(math.sqrt(n))))
    cells = max(1, min(cells, n))

    from oryx_tpu.ops.kmeans import train_kmeans

    rng = np.random.default_rng(seed)
    sample = (
        mat[rng.choice(n, train_sample, replace=False)] if n > train_sample else mat
    )
    minibatch = 32_768 if len(sample) > 65_536 else None
    centers, _counts, _cost = train_kmeans(
        sample,
        cells,
        iterations=iterations,
        init="k-means||",
        seed=seed,
        minibatch_size=minibatch,
    )
    centers = np.asarray(centers, dtype=np.float32)

    assign = _assign_cells(mat, centers)
    order = np.argsort(assign, kind="stable")  # within-cell: ascending id
    counts = np.bincount(assign, minlength=cells).astype(np.int64)
    chunk_counts = -(-counts // chunk)  # occupied chunks; empty cells keep 0
    tile_counts = -(-chunk_counts // tile_chunks)
    spans = tile_counts * tile_slots  # per-cell slot span, tile-aligned
    item_starts = np.zeros(cells + 1, np.int64)
    np.cumsum(counts, out=item_starts[1:])
    slot_base = np.zeros(cells + 1, np.int64)
    np.cumsum(spans, out=slot_base[1:])
    # +1 guard tile at the end: the all-padding landing zone for starved
    # tile/chunk selections
    n_slots = int(slot_base[-1]) + tile_slots
    # slot of the i-th cell-sorted item: its cell's base + rank in cell
    pos_in_cell = np.arange(n, dtype=np.int64) - np.repeat(item_starts[:-1], counts)
    slots_sorted = np.repeat(slot_base[:-1], counts) + pos_in_cell

    kf_pad = pt._ceil_to(feat, pt._INT8_FEAT_MULTIPLE)
    mat_t = np.zeros((kf_pad, n_slots), np.int8)
    resid = np.zeros((kf_pad, n_slots), np.int8)
    mat_rows = np.zeros((n_slots, kf_pad), np.int8)
    scales = np.ones((1, n_slots), np.float32)  # 1.0: padding dequant is a no-op
    rscales = np.ones((1, n_slots), np.float32)
    norms = np.zeros((1, n_slots), np.float32)
    slot_ids = np.full(n_slots, -1, np.int32)
    slot_ids[slots_sorted] = order
    id_to_slot = np.empty(n, np.int32)
    id_to_slot[order] = slots_sorted.astype(np.int32)
    host1 = _host_stage1_active()
    host_plane = np.zeros((n_slots, kf_pad), np.float32) if host1 else None
    slice_rows = 1_000_000  # bounds the quantize transient at 10M+ items
    for beg in range(0, n, slice_rows):
        rows = order[beg : beg + slice_rows]
        sl = slots_sorted[beg : beg + slice_rows]
        sub = mat[rows]
        q, s = pt._quantize_rows(sub)
        q2, s2 = pt._quantize_residual(sub, q, s)
        mat_t[:feat, sl] = q.T
        resid[:feat, sl] = q2.T
        mat_rows[sl, :feat] = q
        scales[0, sl] = s
        rscales[0, sl] = s2
        norms[0, sl] = np.linalg.norm(sub, axis=1)
        if host_plane is not None:
            host_plane[sl, :feat] = (
                q.astype(np.float32) * s[:, None]
                + q2.astype(np.float32) * s2[:, None]
            )

    cent_t = np.zeros((kf_pad, cells), np.float32)
    cent_t[:feat] = centers.T
    cap = _pow2_ceil(overlay_capacity or OVERLAY_CAPACITY)

    return IVFIndex(
        mat_t=jnp.asarray(mat_t),
        resid=jnp.asarray(resid),
        mat_rows=jnp.asarray(mat_rows),
        scales=jnp.asarray(scales),
        resid_scales=jnp.asarray(rscales),
        norms=jnp.asarray(norms),
        slot_ids=jnp.asarray(slot_ids),
        centroids_t=jnp.asarray(cent_t),
        centroid_norms=jnp.asarray(np.linalg.norm(centers, axis=1)),
        chunk_start=jnp.asarray((slot_base[:-1] // chunk).astype(np.int32)),
        chunk_count=jnp.asarray(chunk_counts.astype(np.int32)),
        ov_rows=jnp.zeros((cap, kf_pad), jnp.float32),
        ov_ids=jnp.full((cap,), -1, jnp.int32),
        ov_norms=jnp.zeros((cap,), jnp.float32),
        n_items=n,
        features=feat,
        chunk=chunk,
        tile_chunks=tile_chunks,
        chunk_count_host=chunk_counts,
        tile_start_host=slot_base[:-1] // tile_slots,
        tile_count_host=tile_counts,
        id_to_slot=id_to_slot,
        ov_map={},
        ov_used=0,
        host_plane=host_plane,
        slot_ids_host=slot_ids.copy() if host1 else None,
        norms_host=norms[0].copy() if host1 else None,
        ov_rows_host=np.zeros((cap, kf_pad), np.float32) if host1 else None,
        ov_ids_host=np.full((cap,), -1, np.int32) if host1 else None,
        ov_norms_host=np.zeros((cap,), np.float32) if host1 else None,
    )


# -- query: routing -----------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("nprobe", "cosine"))
def _route_cells(cent_t, cnorms, q_bf, *, nprobe, cosine):
    route = jnp.dot(
        q_bf,
        cent_t,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    if cosine:
        # ||q|| is constant per row: dividing by centroid norms alone
        # preserves the per-query cosine routing order
        route = route / jnp.maximum(cnorms[None, :], 1e-12)
    _, cells = jax.lax.top_k(route, nprobe)
    return cells  # [b, nprobe]


def _group_tile_lists(index: IVFIndex, cells_np: np.ndarray, g: int):
    """Union each query group's probed cells into ragged tile lists.

    Scanning the union instead of per-query lists keeps the scan dense
    and uniform — a query only ever sees EXTRA cells, never fewer.
    """
    b = cells_np.shape[0]
    groups = -(-b // g)
    per_group = []
    for gi in range(groups):
        uc = np.unique(cells_np[gi * g : (gi + 1) * g].ravel())
        cnt = index.tile_count_host[uc]
        uc = uc[cnt > 0]  # empty cells contribute no tiles
        cnt = index.tile_count_host[uc]
        starts = index.tile_start_host[uc]
        total = int(cnt.sum())
        if total == 0:
            per_group.append(np.empty(0, np.int64))
            continue
        # ragged [start, start+cnt) ranges flattened in one vector op
        base = np.repeat(starts, cnt)
        cum = np.zeros(len(uc) + 1, np.int64)
        np.cumsum(cnt, out=cum[1:])
        per_group.append(base + (np.arange(total) - np.repeat(cum[:-1], cnt)))
    return per_group


def _pack_tiles(index: IVFIndex, lists, e: int):
    """Stack ragged tile lists into a [len(lists), e] device array; short
    lists pad with the guard tile, whose slots are all masked."""
    guard = index.n_slots // (index.tile_chunks * index.chunk) - 1
    tiles = np.full((len(lists), e), guard, np.int64)
    for gi, t in enumerate(lists):
        tiles[gi, : len(t)] = t
    return jnp.asarray(tiles.astype(np.int32))


# -- query: host stage-1 path (CPU backend) -----------------------------------


def _host_topk(index: IVFIndex, qpad: np.ndarray, cells: np.ndarray, k: int, cosine: bool):
    """Probed scan over the host-resident dequantized f32 plane.

    One numpy pass per query group: block-take the group's probed tiles
    (memcpy-speed, unlike XLA:CPU's elementwise gather), one BLAS GEMM
    against the group's queries, then a per-query partition + (score
    desc, id asc) ordering — the same tie direction as the exact scan's
    ascending-id stable top_k. Because the plane holds the two-plane
    DEQUANTIZED values, the ranking scores ARE final-precision scores:
    the CPU path collapses the rescore stage instead of re-gathering
    candidates through XLA. Returns host (vals [n, k] f32, ids [n, k]
    int32); the overlay merges from its host mirror.
    """
    n, kf = qpad.shape
    kk = max(1, int(k))
    g = max(1, min(QUERY_BLOCK, n))
    # probe-locality sort (see top_k_device): shared cells collapse in
    # the group union
    order = np.argsort(cells[:, 0], kind="stable")
    lists = _group_tile_lists(index, cells[order], g)
    ts = index.tile_chunks * index.chunk
    n_tiles = index.n_slots // ts
    plane3 = index.host_plane.reshape(n_tiles, ts, kf)
    sids3 = index.slot_ids_host.reshape(n_tiles, ts)
    norms3 = index.norms_host.reshape(n_tiles, ts)
    used = index.ov_used
    qn = np.linalg.norm(qpad, axis=1) if cosine else None
    if used:
        ov_sc = qpad @ index.ov_rows_host[:used].T  # [n, used] exact
        if cosine:
            ov_sc = ov_sc / np.maximum(
                index.ov_norms_host[None, :used] * qn[:, None], 1e-12
            )
        ov_ids = index.ov_ids_host[:used].astype(np.int64)
    out_v = np.full((n, kk), -np.inf, np.float32)
    out_i = np.full((n, kk), -1, np.int32)
    for gi, tl in enumerate(lists):
        rows = order[gi * g : (gi + 1) * g]
        qg = qpad[rows]
        if len(tl):
            slab = plane3[tl].reshape(-1, kf)  # contiguous block take
            sc = slab @ qg.T  # [S, group] final-precision scores
            ssid = sids3[tl].reshape(-1).astype(np.int64)
            if cosine:
                nr = norms3[tl].reshape(-1)
                sc = sc / np.maximum(nr[:, None] * qn[rows][None, :], 1e-12)
            sc[ssid < 0, :] = -np.inf  # padding + tombstoned slots
        else:  # every probed cell was empty: overlay-only candidates
            sc = np.empty((0, len(rows)), np.float32)
            ssid = np.empty(0, np.int64)
        kp = min(kk, sc.shape[0])
        if kp and sc.shape[0] > kp:
            part = np.argpartition(-sc, kp - 1, axis=0)[:kp]  # [kp, group]
        else:
            part = np.broadcast_to(
                np.arange(sc.shape[0])[:, None], (sc.shape[0], len(rows))
            )
        for j, qi in enumerate(rows):
            pv = sc[part[:, j], j]
            pi = ssid[part[:, j]]
            if used:
                pv = np.concatenate([pv, ov_sc[qi]])
                pi = np.concatenate([pi, ov_ids])
            if not len(pv):
                continue
            # score desc, item id asc — the exact path's tie direction
            o = np.lexsort((pi, -pv))[:kk]
            pv, pi = pv[o], pi[o]
            fin = np.isfinite(pv)
            out_v[qi, : len(pv)] = np.where(fin, pv, -np.inf)
            out_i[qi, : len(pv)] = np.where(fin, pi, -1).astype(np.int32)
    return out_v, out_i


# -- query: probed scan + exact rescore ---------------------------------------


@functools.partial(
    jax.jit, static_argnames=("k", "kc", "tile", "chunk", "cosine")
)
def _probe_topk(
    mat_rows,
    mat_t,
    resid,
    scales,
    resid_scales,
    norms,
    slot_ids,
    ov_rows,
    ov_ids,
    ov_norms,
    q_gbf,
    tiles_ge,
    *,
    k,
    kc,
    tile,
    chunk,
    cosine,
):
    """[G, g, kf] query groups x [G, E] probed tiles -> (vals, ids) [G, g, k].

    Stage 1 is the exact scan's chunk-max ranking restricted to the
    probed tiles: a group's tile list gathers as one contiguous-block
    slab of the item-major primary plane, so the whole probed region is
    ONE int8->f32 conversion + GEMM shared by the query group, reduced
    to per-chunk maxes in the epilogue. (One big step per group, not one
    small step per tile — XLA:CPU charges ~100us of dispatch per scan
    step, which at thousands of tiles costs more than the math.)
    Stage 2 takes each query's top ``kc`` chunks and rescores their items
    through the same two-plane gather epilogue as the exact path's
    candidate tail, then merges the pending overlay's exact scores."""
    n_slots = mat_rows.shape[0]
    kf = mat_rows.shape[1]
    guard_chunk = n_slots // chunk - 1  # inside the guard tile: all masked
    tile_slots = tile * chunk
    n_tiles = n_slots // tile_slots
    # tile-blocked views: row-major reshapes, no data movement
    rows3 = mat_rows.reshape(n_tiles, tile_slots, kf)
    scales_t = scales.reshape(n_tiles, tile_slots)
    sids_t = slot_ids.reshape(n_tiles, tile_slots)
    norms_t = norms.reshape(n_tiles, tile_slots)

    def one(args):
        q, tl = args  # [g, kf], [E]
        g = q.shape[0]
        e = tl.shape[0]
        qn = jnp.linalg.norm(q, axis=1, keepdims=True) if cosine else None
        qt = q.T  # [kf, g]

        # contiguous-block gather of the probed tiles (each tile is one
        # memcpy-able run), then a single dense GEMM over the union
        slab = jnp.take(rows3, tl, axis=0).reshape(e * tile_slots, kf)
        s1 = jnp.take(scales_t, tl, axis=0).reshape(e * tile_slots)
        sid1 = jnp.take(sids_t, tl, axis=0).reshape(e * tile_slots)
        sc = (
            jnp.dot(
                slab.astype(jnp.float32),
                qt,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            * s1[:, None]
        )  # [e*tile_slots, g] plane-1 ranking scores
        if cosine:
            nr = jnp.take(norms_t, tl, axis=0).reshape(e * tile_slots)
            sc = sc / jnp.maximum(nr[:, None] * qn[None, :, 0], 1e-12)
        sc = jnp.where(sid1[:, None] >= 0, sc, -jnp.inf)
        cms = jnp.max(sc.reshape(e, tile, chunk, g), axis=2)  # [E, tile, g]
        allc = jnp.moveaxis(cms, 2, 0).reshape(g, -1)  # [g, E*tile]
        cv, cpos = jax.lax.top_k(allc, min(kc, allc.shape[1]))
        tchunk = tl[cpos // tile] * tile + cpos % tile  # global chunk ids
        # starved selections (-inf chunk max) land on the guard chunk so
        # the gather below cannot touch an unprobed cell's items
        tchunk = jnp.where(jnp.isfinite(cv), tchunk, guard_chunk)
        iid = (
            tchunk[:, :, None] * chunk
            + jnp.arange(chunk, dtype=jnp.int32)[None, None, :]
        ).reshape(g, -1)
        sid = slot_ids[iid]
        sc = pt._gathered_pair_scores(
            mat_t, resid, scales, resid_scales, norms, q, qn, iid, cosine=cosine
        )
        sc = jnp.where(sid >= 0, sc, -jnp.inf)
        # pending overlay: exact f32 scan of the updated rows
        osc = jnp.dot(
            q,
            ov_rows.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if cosine:
            osc = osc / jnp.maximum(ov_norms[None, :] * qn, 1e-12)
        osc = jnp.where(ov_ids[None, :] >= 0, osc, -jnp.inf)
        allv = jnp.concatenate([sc, osc], axis=1)
        alli = jnp.concatenate(
            [sid, jnp.broadcast_to(ov_ids[None, :], osc.shape)], axis=1
        )
        ke = min(k, allv.shape[1])
        v, p = jax.lax.top_k(allv, ke)
        out_ids = jnp.take_along_axis(alli, p, axis=1)
        # starved windows (k > finite candidates) pad with id -1, not a
        # garbage gather target — callers skip negatives
        out_ids = jnp.where(jnp.isfinite(v), out_ids, -1)
        if ke < k:
            v = jnp.pad(v, ((0, 0), (0, k - ke)), constant_values=-jnp.inf)
            out_ids = jnp.pad(out_ids, ((0, 0), (0, k - ke)), constant_values=-1)
        return v, out_ids

    if q_gbf.shape[0] == 1:
        v, i = one((q_gbf[0], tiles_ge[0]))
        return v[None], i[None]
    return jax.lax.map(one, (q_gbf, tiles_ge))


# -- query: full-probe exact mode ---------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("k", "n_seg", "seg", "cosine", "chunk")
)
def _full_topk(
    mat_t,
    resid,
    scales,
    resid_scales,
    norms,
    slot_ids,
    chunk_start,
    chunk_count,
    ov_rows,
    ov_ids,
    ov_norms,
    queries_gbf,
    *,
    k,
    n_seg,
    seg,
    cosine,
    chunk,
):
    """nprobe == n_cells: every occupied chunk is a candidate and every
    candidate rescores through the shared two-plane epilogue — the
    ascending-item-id candidate order makes the stable top_k break score
    ties toward the lowest id, exactly like the exact scan. O(n) gather:
    this mode exists for the bit-for-bit contract (and tiny catalogs),
    not for speed — the probed path above is the serving path."""
    int_max = jnp.iinfo(jnp.int32).max
    n_cells = chunk_start.shape[0]
    q_chunks = n_seg * seg

    def one(q):
        g = q.shape[0]
        qn = jnp.linalg.norm(q, axis=1, keepdims=True) if cosine else None
        lens = jnp.broadcast_to(chunk_count[None, :], (g, n_cells))
        cum = jnp.cumsum(lens, axis=1)
        j = jnp.broadcast_to(
            jnp.arange(q_chunks, dtype=jnp.int32)[None, :], (g, q_chunks)
        )
        # which cell does global candidate-chunk j fall into
        pos = jax.vmap(lambda c, jj: jnp.searchsorted(c, jj, side="right"))(cum, j)
        valid = pos < n_cells
        posc = jnp.minimum(pos, n_cells - 1)
        prev = cum - lens
        within = j - jnp.take_along_axis(prev, posc, axis=1)
        chk = jnp.where(valid, chunk_start[posc] + within, 0)
        iid = (
            chk[:, :, None] * chunk
            + jnp.arange(chunk, dtype=jnp.int32)[None, None, :]
        ).reshape(g, q_chunks * chunk)
        sid = slot_ids[iid]  # [g, m] original ids; -1 = padding/tombstone
        ok = jnp.repeat(valid, chunk, axis=1) & (sid >= 0)
        # ascending item id, padding last — the stable per-segment + final
        # top_k then tie-breaks toward the lowest item id
        key = jnp.where(ok, sid, int_max)
        ordr = jnp.argsort(key, axis=1)
        iid = jnp.take_along_axis(iid, ordr, axis=1)
        sid = jnp.take_along_axis(sid, ordr, axis=1)
        ok = jnp.take_along_axis(ok, ordr, axis=1)
        seg_items = seg * chunk
        kk = max(1, min(k, seg_items))
        iid_s = jnp.moveaxis(iid.reshape(g, n_seg, seg_items), 1, 0)
        sid_s = jnp.moveaxis(sid.reshape(g, n_seg, seg_items), 1, 0)
        ok_s = jnp.moveaxis(ok.reshape(g, n_seg, seg_items), 1, 0)

        def seg_step(carry, xs):
            ii, ss, oo = xs
            sc = pt._gathered_pair_scores(
                mat_t, resid, scales, resid_scales, norms, q, qn, ii,
                cosine=cosine,
            )
            sc = jnp.where(oo, sc, -jnp.inf)
            v, p = jax.lax.top_k(sc, kk)
            return carry, (v, jnp.take_along_axis(ss, p, axis=1))

        if n_seg == 1:
            _, (vs, ids) = seg_step(0, (iid_s[0], sid_s[0], ok_s[0]))
            allv, alli = vs, ids
        else:
            _, (vs, ids) = jax.lax.scan(seg_step, 0, (iid_s, sid_s, ok_s))
            allv = jnp.moveaxis(vs, 0, 1).reshape(g, n_seg * kk)
            alli = jnp.moveaxis(ids, 0, 1).reshape(g, n_seg * kk)
        # pending overlay: exact f32 scan of the updated rows
        osc = jnp.dot(
            q,
            ov_rows.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if cosine:
            osc = osc / jnp.maximum(ov_norms[None, :] * qn, 1e-12)
        osc = jnp.where(ov_ids[None, :] >= 0, osc, -jnp.inf)
        allv = jnp.concatenate([allv, osc], axis=1)
        alli = jnp.concatenate(
            [alli, jnp.broadcast_to(ov_ids[None, :], osc.shape)], axis=1
        )
        ke = min(k, allv.shape[1])
        v, p = jax.lax.top_k(allv, ke)
        out_ids = jnp.take_along_axis(alli, p, axis=1)
        out_ids = jnp.where(jnp.isfinite(v), out_ids, -1)
        if ke < k:
            v = jnp.pad(v, ((0, 0), (0, k - ke)), constant_values=-jnp.inf)
            out_ids = jnp.pad(out_ids, ((0, 0), (0, k - ke)), constant_values=-1)
        return v, out_ids

    if queries_gbf.shape[0] == 1:
        v, i = one(queries_gbf[0])
        return v[None], i[None]
    return jax.lax.map(one, queries_gbf)


# -- query: entry points ------------------------------------------------------

# per-segment items for the full-probe gather (bounds the [kf, g, seg]
# f32 candidate planes to a few MB regardless of catalog size)
_SEG_ITEMS = 8192


def _group_queries(index: IVFIndex, queries: np.ndarray, order=None):
    """[n, feat] -> ([G, g, kf_pad] device f32, n, g). ``order`` permutes
    the queries before grouping (probe-locality sort)."""
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    n = q.shape[0]
    if order is not None:
        q = q[order]
    kf_pad = index.mat_t.shape[0]
    g = max(1, min(QUERY_BLOCK, n))
    groups = -(-n // g)
    padded = np.zeros((groups * g, kf_pad), np.float32)
    padded[:n, : q.shape[1]] = q
    return jnp.asarray(padded.reshape(groups, g, kf_pad)), n, g


def top_k_device(
    index: IVFIndex,
    queries: np.ndarray,
    k: int,
    *,
    nprobe: int | None = None,
    cosine: bool = False,
):
    """(vals [n, k], ids [n, k]) device arrays; ids are ORIGINAL item
    row indices (-1 pads starved windows)."""
    np_ = index.resolve_nprobe(nprobe)
    kk = max(1, int(k))
    # an empty overlay shrinks to one masked dummy row: the overlay GEMM
    # against the full capacity (default 4096 rows) would otherwise cost
    # more than the probed scan itself
    if index.ov_used == 0:
        ov_rows, ov_ids, ov_norms = (
            index.ov_rows[:1],
            index.ov_ids[:1],
            index.ov_norms[:1],
        )
    else:
        ov_rows, ov_ids, ov_norms = index.ov_rows, index.ov_ids, index.ov_norms
    if np_ >= index.n_cells:
        q_gbf, n, g = _group_queries(index, queries)
        total_chunks = max(1, int(index.chunk_count_host.sum()))
        seg = max(1, _SEG_ITEMS // index.chunk)
        n_seg = -(-total_chunks // seg)
        if n_seg == 1:
            seg = total_chunks
        vals, ids = _full_topk(
            index.mat_t,
            index.resid,
            index.scales,
            index.resid_scales,
            index.norms,
            index.slot_ids,
            index.chunk_start,
            index.chunk_count,
            ov_rows,
            ov_ids,
            ov_norms,
            q_gbf,
            k=kk,
            n_seg=n_seg,
            seg=seg,
            cosine=cosine,
            chunk=index.chunk,
        )
        out_k = vals.shape[-1]
        return vals.reshape(-1, out_k)[:n], ids.reshape(-1, out_k)[:n]
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    n = q.shape[0]
    kf_pad = index.mat_t.shape[0]
    qpad = np.zeros((n, kf_pad), np.float32)
    qpad[:, : q.shape[1]] = q
    cells = np.asarray(
        _route_cells(
            index.centroids_t,
            index.centroid_norms,
            jnp.asarray(qpad),
            nprobe=np_,
            cosine=cosine,
        )
    )
    if index.host_plane is not None:
        vals_np, ids_np = _host_topk(index, qpad, cells, kk, cosine)
        return jnp.asarray(vals_np), jnp.asarray(ids_np)
    # probe-locality sort: queries sharing a best cell land in the same
    # scan group, shrinking each group's cell union (the scan covers the
    # union, so overlap is pure savings); results unsort at the end
    order = np.argsort(cells[:, 0], kind="stable")
    g = max(1, min(QUERY_BLOCK, n))
    groups = -(-n // g)
    lists = _group_tile_lists(index, cells[order], g)
    qs = np.zeros((groups * g, kf_pad), np.float32)
    qs[:n] = qpad[order]
    qs = qs.reshape(groups, g, kf_pad)
    # bucket groups by pow2(union size): each bucket pads only to ITS
    # widest member, so one pathological union doesn't tax every group
    buckets: dict[int, list[int]] = {}
    for gi, t in enumerate(lists):
        buckets.setdefault(_pow2_ceil(max(1, len(t))), []).append(gi)
    row_src = []  # sorted-query row ranges, in bucket emission order
    parts_v, parts_i = [], []
    for e, gis in sorted(buckets.items()):
        tiles = _pack_tiles(index, [lists[gi] for gi in gis], e)
        v, i = _probe_topk(
            index.mat_rows,
            index.mat_t,
            index.resid,
            index.scales,
            index.resid_scales,
            index.norms,
            index.slot_ids,
            ov_rows,
            ov_ids,
            ov_norms,
            jnp.asarray(qs[gis]),
            tiles,
            k=kk,
            kc=pt._chunk_k(kk, e * index.tile_chunks),
            tile=index.tile_chunks,
            chunk=index.chunk,
            cosine=cosine,
        )
        parts_v.append(v.reshape(-1, v.shape[-1]))
        parts_i.append(i.reshape(-1, i.shape[-1]))
        for gi in gis:
            row_src.append(np.arange(gi * g, (gi + 1) * g, dtype=np.int64))
    stacked_v = parts_v[0] if len(parts_v) == 1 else jnp.concatenate(parts_v)
    stacked_i = parts_i[0] if len(parts_i) == 1 else jnp.concatenate(parts_i)
    # stacked row j holds sorted-query row_src[j]; compose with the
    # locality unsort so one device gather restores caller order
    where = np.empty(groups * g, np.int64)
    where[np.concatenate(row_src)] = np.arange(groups * g)
    inv = np.argsort(order)
    sel = jnp.asarray(where[inv].astype(np.int32))
    return stacked_v[sel], stacked_i[sel]


def top_k(
    index: IVFIndex,
    queries: np.ndarray,
    k: int,
    *,
    nprobe: int | None = None,
    cosine: bool = False,
):
    """Blocking host-side form: (ids [n, k] int32, vals [n, k] f32)."""
    vals, ids = top_k_device(index, queries, k, nprobe=nprobe, cosine=cosine)
    return np.asarray(ids), np.asarray(vals)


def top_k_device_indexed(
    index: IVFIndex,
    x_dev: jax.Array,
    indices: np.ndarray,
    k: int,
    *,
    nprobe: int | None = None,
    cosine: bool = False,
):
    """Index-submit twin: queries are rows of the device-resident X."""
    idx = np.atleast_1d(np.asarray(indices, dtype=np.int32))
    q = np.asarray(x_dev[jnp.asarray(idx)])  # device gather, tiny download
    return top_k_device(index, q, k, nprobe=nprobe, cosine=cosine)


# -- update path (speed-layer fold-ins) ---------------------------------------


@jax.jit
def _apply_overlay(slot_ids, ov_rows, ov_ids, ov_norms, dead, pos, rows, ids, nrm):
    # dead slots repeat their last entry when bucketed — set(-1) is
    # idempotent, so duplicates are harmless
    slot_ids = slot_ids.at[dead].set(-1)
    ov_rows = ov_rows.at[pos].set(rows)
    ov_ids = ov_ids.at[pos].set(ids)
    ov_norms = ov_norms.at[pos].set(nrm)
    return slot_ids, ov_rows, ov_ids, ov_norms


def update_rows(
    index: IVFIndex,
    rows: np.ndarray,
    values: np.ndarray,
    n_items: int | None = None,
) -> IVFIndex:
    """Fold updated item rows into the index via the pending overlay.

    Each touched row's cell slot is tombstoned (slot id -> -1) and its
    fresh vector lands in the overlay, which queries scan exactly — so a
    speed-layer fold-in is visible on the very next request regardless of
    which cells it routes to. Overlay rows store the two-plane
    DEQUANTIZED values (q1*s1 + q2*s2), so their scores match what a full
    rebuild would serve to f32 rounding. Raises :class:`IVFOverlayFull`
    when the overlay is out of slots (callers rebuild)."""
    rows = np.asarray(rows, dtype=np.int64)
    values = np.ascontiguousarray(np.atleast_2d(values), dtype=np.float32)
    if len(rows) == 0:
        return index
    count = int(index.n_items if n_items is None else n_items)
    # last write wins for duplicate ids in one batch
    last = {}
    for i, r in enumerate(rows):
        last[int(r)] = i
    ids = np.fromiter(last.keys(), dtype=np.int64, count=len(last))
    vals = values[np.fromiter(last.values(), dtype=np.int64, count=len(last))]

    cap = index.ov_rows.shape[0]
    ov_map = index.ov_map
    used = index.ov_used
    pos = np.empty(len(ids), np.int32)
    fresh = 0
    for i, item in enumerate(ids):
        item = int(item)
        if item in ov_map:
            pos[i] = ov_map[item]
        else:
            if used + fresh >= cap:
                raise IVFOverlayFull(
                    f"pending overlay full ({cap} rows): rebuild the IVF index"
                )
            pos[i] = used + fresh
            fresh += 1
    dead = np.array(
        [
            index.id_to_slot[item]
            for item in ids
            if item < len(index.id_to_slot) and index.id_to_slot[item] >= 0
        ],
        dtype=np.int32,
    )

    q, s = pt._quantize_rows(vals)
    q2, s2 = pt._quantize_residual(vals, q, s)
    deq = q.astype(np.float32) * s[:, None] + q2.astype(np.float32) * s2[:, None]
    kf_pad = index.mat_t.shape[0]
    deq_pad = np.zeros((len(ids), kf_pad), np.float32)
    deq_pad[:, : vals.shape[1]] = deq
    nrm = np.linalg.norm(vals, axis=1)

    # bucket the scatter shapes like topn.update_rows (pad repeats the
    # last entry; rewriting the same overlay slot with the same row is
    # a no-op) so jit retraces O(log n) shapes
    def bucket(arr):
        m = len(arr)
        b = _pow2_ceil(m)
        if b == m:
            return arr
        return np.concatenate([arr, np.repeat(arr[-1:], b - m, axis=0)], axis=0)

    slot_ids, ov_rows, ov_ids, ov_norms = (
        index.slot_ids,
        index.ov_rows,
        index.ov_ids,
        index.ov_norms,
    )
    if len(dead):
        slot_ids, ov_rows, ov_ids, ov_norms = _apply_overlay(
            slot_ids,
            ov_rows,
            ov_ids,
            ov_norms,
            jnp.asarray(bucket(dead)),
            jnp.asarray(bucket(pos)),
            jnp.asarray(bucket(deq_pad)),
            jnp.asarray(bucket(ids.astype(np.int32))),
            jnp.asarray(bucket(nrm.astype(np.float32))),
        )
    else:
        pos_b = jnp.asarray(bucket(pos))
        ov_rows = ov_rows.at[pos_b].set(jnp.asarray(bucket(deq_pad)))
        ov_ids = ov_ids.at[pos_b].set(jnp.asarray(bucket(ids.astype(np.int32))))
        ov_norms = ov_norms.at[pos_b].set(jnp.asarray(bucket(nrm.astype(np.float32))))

    # host bookkeeping (see class docstring: serialized by the caller)
    if index.host_plane is not None:
        if len(dead):
            index.slot_ids_host[dead] = -1  # tombstone in the host mirror
        index.ov_rows_host[pos] = deq_pad
        index.ov_ids_host[pos] = ids.astype(np.int32)
        index.ov_norms_host[pos] = nrm.astype(np.float32)
    for i, item in enumerate(ids):
        item = int(item)
        ov_map[item] = int(pos[i])
        if item < len(index.id_to_slot):
            index.id_to_slot[item] = -1
    return dataclasses.replace(
        index,
        slot_ids=slot_ids,
        ov_rows=ov_rows,
        ov_ids=ov_ids,
        ov_norms=ov_norms,
        n_items=max(count, index.n_items),
        ov_used=used + fresh,
    )


def capacity(index: IVFIndex) -> int:
    """Rows the handle can represent without a rebuild: the built catalog
    plus whatever overlay slots remain for appended items."""
    return index.n_items + (index.ov_rows.shape[0] - index.ov_used)
