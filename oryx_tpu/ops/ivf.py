"""IVF approximate-retrieval tier for the serving scan.

The exact quantized scan (docs/serving-scan.md) streams every item row per
query, which caps single-chip serving near 1M items. This module turns
that ceiling into a 10-100M-item story with the classic inverted-file
(Faiss-style) two-stage retrieval, built entirely from machinery already
in the repo:

1. **Coarse quantizer** — the item matrix is clustered into ~sqrt(n)
   cells with ``ops/kmeans.py`` (k-means|| init + mini-batch Lloyd); each
   item is assigned to its nearest centroid.
2. **Cell-contiguous layout** — items are permuted so every cell occupies
   a contiguous, tile-aligned run of the same two-plane int8 codes the
   exact scan uses (``StreamingItemMatrix``'s per-row quantization rules
   verbatim, so each item's codes are bit-identical to a fresh
   ``upload``). The primary plane is additionally stored ITEM-major: a
   probed run is then a contiguous byte range, which is what makes the
   cell scan a dense GEMM instead of a strided gather (a feature-major
   gather pulls one cacheline per byte — measured 25x slower).
3. **Routing** — a query dots against the [feat, n_cells] centroid matrix
   and keeps the top ``nprobe`` cells.
4. **Probed scan + exact rescore** — a query group's probed cells union
   into a tile list; each tile is one contiguous ``dynamic_slice`` +
   plane-1 GEMM reduced to per-chunk maxes (the same chunk-max ranking
   the exact scan uses), and the top chunks then rescore through the
   same ``pallas_topn._gathered_pair_scores`` two-plane epilogue as the
   exact path's candidate tail. Scanning the group UNION means every
   query sees a superset of its own probed cells — recall only goes up —
   while the int8->f32 tile conversion amortizes across the group.

Speed-layer visibility: ``update_rows`` keeps fold-ins visible through
the ANN path with a **pending-overlay list** — touched rows leave the
cell structure (their slot id is tombstoned) and land in a small
device-resident overlay of dequantized rows that every query scans
exactly and merges before the final top-k. The overlay holds the rows'
two-plane DEQUANTIZED values, so overlay scores match a fresh upload's
quantized scores to f32 rounding. A full overlay never stalls the
request path: the OLDEST overlay entries spill to a host-side pending
queue (``pending_spill``) and their slots are reused — spilled rows go
invisible until the next compaction folds them back into the clustered
layout, a bounded-freshness trade instead of the old synchronous
full re-cluster (:class:`IVFOverlayFull` is kept for compatibility but
no longer raised here).

Maintenance: ``compact_ivf`` folds the overlay + spill queue back into
the cell-contiguous layout WITHOUT retraining the coarse quantizer —
retained rows keep their quantized codes verbatim (per-row quantization
is deterministic, so the compacted planes are bit-identical to a
from-scratch build over the same item set), tombstoned slots are
garbage-collected, oversized cells split via a local 2-means and
undersized cells merge into their nearest surviving neighbour
(SPFresh-style LIRE rebalancing, DiskANN-style background rebuild).
``oryx_tpu/serving/maintain.py`` drives it off the request path.

Exactness contract: with ``nprobe >= n_cells`` every cell is probed, the
candidate set is the whole catalog ordered by ascending item id, and the
scores come from the shared epilogue on the SAME feature-major planes —
the result reproduces the exact int8 scan's top-N bit-for-bit (tested in
tests/ops/test_ivf_scan.py; the item-major plane exists only for stage-1
ranking, whose rounding never touches the returned scores).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import time

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.ops import pallas_topn as pt

# -- knobs (oryx.serving.scan.ann.*, pushed by ServingLayer) ------------------

# master switch for the serving tier (ops-level entry points work either way)
ANN_ENABLED = False
# coarse cells; 0 = auto round(sqrt(n))
N_CELLS = 0
# cells probed per query; 0 = derive from PROBE_FRACTION
NPROBE = 0
# fraction of items a query should scan when NPROBE is 0 (nprobe =
# round(fraction * n_cells)); the knob tools/load_benchmark.py maps the
# reference harness's LSH sampleRate onto. 1% probes measure recall@10
# ~0.997 on clustered catalogs at 200k-1M items (see docs/serving-scan.md
# for the recall/latency trade-off and the data-model caveat)
PROBE_FRACTION = 0.01
# catalogs below this stay on the exact scan (clustering overhead isn't
# worth it when one GEMM streams the whole matrix)
MIN_ITEMS = 100_000
# pending-overlay rows (speed-layer updates between index rebuilds)
OVERLAY_CAPACITY = 4096
# queries per scan group: the probed-cell UNION of a group shares one
# pass of tile gather + GEMM, so bigger groups amortize memory traffic
# but inflate the union (more cells scanned per query); 4-8 measures
# best on the host stage-1 path, where the take is already memcpy-fast
QUERY_BLOCK = 8
# chunks per scan tile: tiles are the dynamic_slice granularity of the
# probed scan, and cells pad to a tile multiple — bigger tiles mean
# fewer, beefier GEMM steps but more padding per cell
TILE_CHUNKS = 8
# None = auto (on for the CPU backend): keep a host-resident dequantized
# f32 copy of the item planes and run the probed scan through numpy
# block-take + BLAS. XLA:CPU gathers byte-at-a-time (~0.4 GB/s measured)
# and converts int8->f32 at ~0.5 Gelem/s, so the device probed path
# loses its sublinearity to data movement; numpy block-take runs at
# memcpy speed and the f32 plane never converts at query time. Costs
# 4x the primary plane's bytes in HOST memory (10 GB at 10M x 256).
HOST_STAGE1 = None

# rows assigned to centroids per jitted block during build
_ASSIGN_BLOCK = 65536


def configure_ann(
    enabled=None,
    cells=None,
    nprobe=None,
    probe_fraction=None,
    min_items=None,
    overlay_capacity=None,
    query_block=None,
    tile_chunks=None,
    host_stage1=None,
):
    """Set the IVF defaults (config: oryx.serving.scan.ann.*). Like
    ``configure_scan``, call before the first dispatch — jitted programs
    bake the derived static shapes in at trace time, and the host stage-1
    plane only materializes at build time."""
    global ANN_ENABLED, N_CELLS, NPROBE, PROBE_FRACTION
    global MIN_ITEMS, OVERLAY_CAPACITY, QUERY_BLOCK, TILE_CHUNKS, HOST_STAGE1
    if enabled is not None:
        ANN_ENABLED = bool(enabled)
    if cells is not None:
        N_CELLS = int(cells)
    if nprobe is not None:
        NPROBE = int(nprobe)
    if probe_fraction is not None:
        PROBE_FRACTION = float(probe_fraction)
    if min_items is not None:
        MIN_ITEMS = int(min_items)
    if overlay_capacity is not None:
        OVERLAY_CAPACITY = int(overlay_capacity)
    if query_block is not None:
        QUERY_BLOCK = int(query_block)
    if tile_chunks is not None:
        TILE_CHUNKS = int(tile_chunks)
    if host_stage1 is not None:
        HOST_STAGE1 = bool(host_stage1)


def _host_stage1_active() -> bool:
    if HOST_STAGE1 is not None:
        return HOST_STAGE1
    return jax.default_backend() == "cpu"


def ann_active(n_items: int) -> bool:
    """Should the serving tier route this catalog through IVF?"""
    return ANN_ENABLED and n_items >= MIN_ITEMS


class IVFOverlayFull(RuntimeError):
    """The pending-overlay list is out of slots.

    Kept for API compatibility: since the spill queue landed,
    ``update_rows`` degrades by spilling the oldest overlay entries to
    ``pending_spill`` instead of raising — no caller sees this on the
    request path anymore. Compaction (``compact_ivf``) drains the queue.
    """


@dataclasses.dataclass(frozen=True, eq=False)
class IVFIndex:
    """Cell-contiguous two-plane int8 item matrix + routing table.

    Device arrays are immutable; ``update_rows`` returns a new handle
    (sharing unchanged planes). The host-side routing tables
    (``id_to_slot``, ``ov_map``) are bookkeeping for the update path and
    are mutated in place under the caller's serialization (the serving
    model updates under its cache lock), never read at query time.

    The slot space ends with one all-padding guard tile (slot ids -1,
    zero codes): tile/chunk selections that have nothing real to point
    at aim there, so downstream gathers always hit masked slots instead
    of a neighbouring cell's items (which would duplicate results).
    """

    # permuted, per-cell tile-padded planes in the exact scan's
    # feature-major layout; padding slots carry scale 1 / codes 0
    mat_t: jax.Array  # [kf_pad, n_slots] int8
    resid: jax.Array  # [kf_pad, n_slots] int8
    # item-major copy of the PRIMARY plane for the dense probed scan
    mat_rows: jax.Array  # [n_slots, kf_pad] int8
    scales: jax.Array  # [1, n_slots] f32
    resid_scales: jax.Array  # [1, n_slots] f32
    norms: jax.Array  # [1, n_slots] f32 (original f32 row norms)
    # slot -> original item id; -1 = padding or superseded by the overlay
    slot_ids: jax.Array  # [n_slots] int32
    # routing table
    centroids_t: jax.Array  # [kf_pad, n_cells] f32
    centroid_norms: jax.Array  # [n_cells] f32
    chunk_start: jax.Array  # [n_cells] int32, in chunk units
    chunk_count: jax.Array  # [n_cells] int32 (occupied chunks only)
    # pending overlay: dequantized rows of updated items, scanned exactly
    ov_rows: jax.Array  # [cap, kf_pad] f32
    ov_ids: jax.Array  # [cap] int32, -1 = empty
    ov_norms: jax.Array  # [cap] f32
    n_items: int
    features: int  # true feature count before int8 sublane padding
    chunk: int  # items per candidate chunk (layout constant)
    tile_chunks: int  # chunks per scan tile (layout constant)
    # host-side routing/update bookkeeping
    chunk_count_host: np.ndarray  # [n_cells] int64
    tile_start_host: np.ndarray  # [n_cells] int64, in tile units
    tile_count_host: np.ndarray  # [n_cells] int64
    id_to_slot: np.ndarray  # [n_items at build] int32, -1 = overlay/none
    ov_map: dict  # item id -> overlay slot
    ov_used: int
    # host stage-1 mirrors (None when HOST_STAGE1 resolves off): the
    # dequantized two-plane f32 item rows (q1*s1 + q2*s2), scanned by
    # numpy block-take + BLAS on the CPU backend; same quantized values
    # as the device planes, so recall and scores match to f32 rounding
    host_plane: np.ndarray | None = None  # [n_slots, kf_pad] f32
    slot_ids_host: np.ndarray | None = None  # [n_slots] int32
    norms_host: np.ndarray | None = None  # [n_slots] f32
    ov_rows_host: np.ndarray | None = None  # [cap, kf_pad] f32
    ov_ids_host: np.ndarray | None = None  # [cap] int32
    ov_norms_host: np.ndarray | None = None  # [cap] f32
    # maintenance bookkeeping (host-side, mutated in place under the
    # caller's serialization, like ov_map):
    # RAW pre-quantization values of each overlay slot — compaction
    # requantizes from these so the compacted codes are bit-identical to
    # a from-scratch build over the same item set (requantizing the
    # DEQUANTIZED overlay values would shift the per-row scale)
    ov_raw_host: np.ndarray | None = None  # [cap, kf_pad] f32
    # item id -> fold-in wall-clock seconds (freshness accounting)
    ov_born: dict | None = None
    # overlay-overflow spill queue: item id -> (raw row [kf_pad] f32,
    # born seconds). Spilled rows are INVISIBLE to queries until the
    # next compaction folds them back in — the bounded-freshness degrade
    # that replaced the request-path full re-cluster.
    pending_spill: dict | None = None
    # optional tiered host plane (native/store.py TieredHostPlane): when
    # set, host stage-1 gathers probed tiles through the HBM->RAM->disk
    # cell store instead of the flat host_plane array
    tier: object | None = None

    @property
    def n_cells(self) -> int:
        return self.centroids_t.shape[1]

    @property
    def n_slots(self) -> int:
        return self.mat_t.shape[1]

    @property
    def num_features(self) -> int:
        return self.features

    @property
    def quantized(self) -> bool:
        return True

    def resolve_nprobe(self, nprobe: int | None = None) -> int:
        """Probed cells per query: explicit arg > NPROBE knob > fraction."""
        p = nprobe if nprobe is not None else NPROBE
        if not p:
            p = int(round(PROBE_FRACTION * self.n_cells))
        return max(1, min(int(p), self.n_cells))

    def prefetch_for_queries(
        self, queries, nprobe: int | None = None, cosine: bool = False
    ) -> int:
        """Advisory async prefetch of the cells these queries will probe.

        The batcher calls this while a scan group assembles (ahead of the
        actual dispatch), so the tier store's disk->RAM copies overlap
        with batching + routing instead of stalling the scan. Routing
        here is a host-side numpy dot (exactness is irrelevant for a
        prefetch hint; the scan re-routes on device). No-op without an
        attached tier. Returns the number of cells hinted."""
        tier = self.tier
        if tier is None:
            return 0
        np_ = self.resolve_nprobe(nprobe)
        if np_ >= self.n_cells:
            return 0  # full probe touches everything; nothing to target
        q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
        cent, cnorms = tier.routing_arrays()
        qpad = np.zeros((q.shape[0], cent.shape[0]), np.float32)
        qpad[:, : q.shape[1]] = q
        sc = qpad @ cent
        if cosine:
            sc = sc / np.maximum(cnorms[None, :], 1e-12)
        if np_ < sc.shape[1]:
            part = np.argpartition(-sc, np_ - 1, axis=1)[:, :np_]
        else:
            part = np.broadcast_to(np.arange(sc.shape[1]), sc.shape)
        hinted = np.unique(part)
        tier.prefetch_cells(hinted)
        return int(len(hinted))


# -- build --------------------------------------------------------------------


@jax.jit
def _assign_block_dev(blk, cent_t, half_c2):
    # nearest centroid by L2 == argmax(y.c - ||c||^2/2); HIGHEST so
    # borderline assignments match the kmeans trainer's f32 distances
    s = (
        jnp.dot(
            blk,
            cent_t,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        - half_c2
    )
    return jnp.argmin(-s, axis=1).astype(jnp.int32)


def _assign_cells(mat: np.ndarray, centers: np.ndarray) -> np.ndarray:
    """Nearest-centroid id per item row, in fixed-shape device blocks."""
    n = len(mat)
    cent_t = jnp.asarray(centers.T)
    half = jnp.asarray(0.5 * np.einsum("kd,kd->k", centers, centers)[None, :])
    out = np.empty(n, np.int32)
    block = min(_ASSIGN_BLOCK, n)
    for beg in range(0, n, block):
        sub = np.asarray(mat[beg : beg + block], dtype=np.float32)
        real = len(sub)
        if real < block:  # pad the tail so two shapes compile, not many
            sub = np.concatenate([sub, np.zeros((block - real, sub.shape[1]), np.float32)])
        out[beg : beg + real] = np.asarray(
            _assign_block_dev(jnp.asarray(sub), cent_t, half)
        )[:real]
    return out


def _pow2_ceil(x: int) -> int:
    return 1 << max(0, int(x) - 1).bit_length()


def build_ivf(
    matrix: np.ndarray,
    *,
    n_cells: int | None = None,
    seed: int = 0,
    train_sample: int = 200_000,
    iterations: int = 8,
    overlay_capacity: int | None = None,
    centroids: np.ndarray | None = None,
) -> IVFIndex:
    """Cluster, permute cell-contiguous, quantize, and ship to device.

    The coarse quantizer trains on a uniform sample (mini-batch Lloyd
    over k-means|| seeds); the full catalog then assigns to the trained
    centroids in device blocks. Rows quantize with the exact scan's
    per-row rules, streamed in million-row slices so the host transient
    stays bounded at 10M+ items.

    ``centroids`` short-circuits the training: the catalog lays out onto
    the GIVEN [cells, feat] coarse quantizer (assignment + layout only,
    no Lloyd iterations). This is how a replica swaps onto a published
    index generation — every replica reproduces the maintainer's
    clustering over its own item store without re-running kmeans.
    """
    mat = np.asarray(matrix, dtype=np.float32)
    n, feat = mat.shape
    if n == 0:
        raise ValueError("cannot build an IVF index over zero items")
    chunk = max(8, int(pt._CHUNK))
    tile_chunks = max(1, TILE_CHUNKS)
    tile_slots = tile_chunks * chunk
    if centroids is not None:
        centers = np.ascontiguousarray(centroids, dtype=np.float32)[:, :feat]
        cells = len(centers)
    else:
        cells = int(
            n_cells if n_cells is not None else (N_CELLS or round(math.sqrt(n)))
        )
        cells = max(1, min(cells, n))

        from oryx_tpu.ops.kmeans import train_kmeans

        rng = np.random.default_rng(seed)
        sample = (
            mat[rng.choice(n, train_sample, replace=False)]
            if n > train_sample
            else mat
        )
        minibatch = 32_768 if len(sample) > 65_536 else None
        centers, _counts, _cost = train_kmeans(
            sample,
            cells,
            iterations=iterations,
            init="k-means||",
            seed=seed,
            minibatch_size=minibatch,
        )
        centers = np.asarray(centers, dtype=np.float32)

    assign = _assign_cells(mat, centers)
    order = np.argsort(assign, kind="stable")  # within-cell: ascending id
    counts = np.bincount(assign, minlength=cells).astype(np.int64)
    chunk_counts = -(-counts // chunk)  # occupied chunks; empty cells keep 0
    tile_counts = -(-chunk_counts // tile_chunks)
    spans = tile_counts * tile_slots  # per-cell slot span, tile-aligned
    item_starts = np.zeros(cells + 1, np.int64)
    np.cumsum(counts, out=item_starts[1:])
    slot_base = np.zeros(cells + 1, np.int64)
    np.cumsum(spans, out=slot_base[1:])
    # +1 guard tile at the end: the all-padding landing zone for starved
    # tile/chunk selections
    n_slots = int(slot_base[-1]) + tile_slots
    # slot of the i-th cell-sorted item: its cell's base + rank in cell
    pos_in_cell = np.arange(n, dtype=np.int64) - np.repeat(item_starts[:-1], counts)
    slots_sorted = np.repeat(slot_base[:-1], counts) + pos_in_cell

    kf_pad = pt._ceil_to(feat, pt._INT8_FEAT_MULTIPLE)
    mat_t = np.zeros((kf_pad, n_slots), np.int8)
    resid = np.zeros((kf_pad, n_slots), np.int8)
    mat_rows = np.zeros((n_slots, kf_pad), np.int8)
    scales = np.ones((1, n_slots), np.float32)  # 1.0: padding dequant is a no-op
    rscales = np.ones((1, n_slots), np.float32)
    norms = np.zeros((1, n_slots), np.float32)
    slot_ids = np.full(n_slots, -1, np.int32)
    slot_ids[slots_sorted] = order
    id_to_slot = np.empty(n, np.int32)
    id_to_slot[order] = slots_sorted.astype(np.int32)
    host1 = _host_stage1_active()
    host_plane = np.zeros((n_slots, kf_pad), np.float32) if host1 else None
    slice_rows = 1_000_000  # bounds the quantize transient at 10M+ items
    for beg in range(0, n, slice_rows):
        rows = order[beg : beg + slice_rows]
        sl = slots_sorted[beg : beg + slice_rows]
        sub = mat[rows]
        q, s = pt._quantize_rows(sub)
        q2, s2 = pt._quantize_residual(sub, q, s)
        mat_t[:feat, sl] = q.T
        resid[:feat, sl] = q2.T
        mat_rows[sl, :feat] = q
        scales[0, sl] = s
        rscales[0, sl] = s2
        norms[0, sl] = np.linalg.norm(sub, axis=1)
        if host_plane is not None:
            host_plane[sl, :feat] = (
                q.astype(np.float32) * s[:, None]
                + q2.astype(np.float32) * s2[:, None]
            )

    cent_t = np.zeros((kf_pad, cells), np.float32)
    cent_t[:feat] = centers.T
    cap = _pow2_ceil(overlay_capacity or OVERLAY_CAPACITY)

    return IVFIndex(
        mat_t=jnp.asarray(mat_t),
        resid=jnp.asarray(resid),
        mat_rows=jnp.asarray(mat_rows),
        scales=jnp.asarray(scales),
        resid_scales=jnp.asarray(rscales),
        norms=jnp.asarray(norms),
        slot_ids=jnp.asarray(slot_ids),
        centroids_t=jnp.asarray(cent_t),
        centroid_norms=jnp.asarray(np.linalg.norm(centers, axis=1)),
        chunk_start=jnp.asarray((slot_base[:-1] // chunk).astype(np.int32)),
        chunk_count=jnp.asarray(chunk_counts.astype(np.int32)),
        ov_rows=jnp.zeros((cap, kf_pad), jnp.float32),
        ov_ids=jnp.full((cap,), -1, jnp.int32),
        ov_norms=jnp.zeros((cap,), jnp.float32),
        n_items=n,
        features=feat,
        chunk=chunk,
        tile_chunks=tile_chunks,
        chunk_count_host=chunk_counts,
        tile_start_host=slot_base[:-1] // tile_slots,
        tile_count_host=tile_counts,
        id_to_slot=id_to_slot,
        ov_map={},
        ov_used=0,
        host_plane=host_plane,
        slot_ids_host=slot_ids.copy() if host1 else None,
        norms_host=norms[0].copy() if host1 else None,
        ov_rows_host=np.zeros((cap, kf_pad), np.float32) if host1 else None,
        ov_ids_host=np.full((cap,), -1, np.int32) if host1 else None,
        ov_norms_host=np.zeros((cap,), np.float32) if host1 else None,
        ov_raw_host=np.zeros((cap, kf_pad), np.float32),
        ov_born={},
        pending_spill={},
    )


# -- query: routing -----------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("nprobe", "cosine"))
def _route_cells(cent_t, cnorms, q_bf, *, nprobe, cosine):
    route = jnp.dot(
        q_bf,
        cent_t,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )
    if cosine:
        # ||q|| is constant per row: dividing by centroid norms alone
        # preserves the per-query cosine routing order
        route = route / jnp.maximum(cnorms[None, :], 1e-12)
    _, cells = jax.lax.top_k(route, nprobe)
    return cells  # [b, nprobe]


def _group_tile_lists(index: IVFIndex, cells_np: np.ndarray, g: int):
    """Union each query group's probed cells into ragged tile lists.

    Scanning the union instead of per-query lists keeps the scan dense
    and uniform — a query only ever sees EXTRA cells, never fewer.
    """
    b = cells_np.shape[0]
    groups = -(-b // g)
    per_group = []
    for gi in range(groups):
        uc = np.unique(cells_np[gi * g : (gi + 1) * g].ravel())
        cnt = index.tile_count_host[uc]
        uc = uc[cnt > 0]  # empty cells contribute no tiles
        cnt = index.tile_count_host[uc]
        starts = index.tile_start_host[uc]
        total = int(cnt.sum())
        if total == 0:
            per_group.append(np.empty(0, np.int64))
            continue
        # ragged [start, start+cnt) ranges flattened in one vector op
        base = np.repeat(starts, cnt)
        cum = np.zeros(len(uc) + 1, np.int64)
        np.cumsum(cnt, out=cum[1:])
        per_group.append(base + (np.arange(total) - np.repeat(cum[:-1], cnt)))
    return per_group


def _pack_tiles(index: IVFIndex, lists, e: int):
    """Stack ragged tile lists into a [len(lists), e] device array; short
    lists pad with the guard tile, whose slots are all masked."""
    guard = index.n_slots // (index.tile_chunks * index.chunk) - 1
    tiles = np.full((len(lists), e), guard, np.int64)
    for gi, t in enumerate(lists):
        tiles[gi, : len(t)] = t
    return jnp.asarray(tiles.astype(np.int32))


# -- query: host stage-1 path (CPU backend) -----------------------------------


def _host_topk(index: IVFIndex, qpad: np.ndarray, cells: np.ndarray, k: int, cosine: bool):
    """Probed scan over the host-resident dequantized f32 plane.

    One numpy pass per query group: block-take the group's probed tiles
    (memcpy-speed, unlike XLA:CPU's elementwise gather), one BLAS GEMM
    against the group's queries, then a per-query partition + (score
    desc, id asc) ordering — the same tie direction as the exact scan's
    ascending-id stable top_k. Because the plane holds the two-plane
    DEQUANTIZED values, the ranking scores ARE final-precision scores:
    the CPU path collapses the rescore stage instead of re-gathering
    candidates through XLA. Returns host (vals [n, k] f32, ids [n, k]
    int32); the overlay merges from its host mirror.
    """
    n, kf = qpad.shape
    kk = max(1, int(k))
    g = max(1, min(QUERY_BLOCK, n))
    # probe-locality sort (see top_k_device): shared cells collapse in
    # the group union
    order = np.argsort(cells[:, 0], kind="stable")
    lists = _group_tile_lists(index, cells[order], g)
    ts = index.tile_chunks * index.chunk
    n_tiles = index.n_slots // ts
    # tiered plane: probed tiles gather through the HBM->RAM->disk cell
    # store (promotion + residency tracked there); the flat array path
    # stays the default. slot ids / norms are 8 B/slot — always RAM.
    tier = index.tier
    plane3 = None if tier is not None else index.host_plane.reshape(n_tiles, ts, kf)
    sids3 = index.slot_ids_host.reshape(n_tiles, ts)
    norms3 = index.norms_host.reshape(n_tiles, ts)
    used = index.ov_used
    qn = np.linalg.norm(qpad, axis=1) if cosine else None
    if used:
        ov_sc = qpad @ index.ov_rows_host[:used].T  # [n, used] exact
        if cosine:
            ov_sc = ov_sc / np.maximum(
                index.ov_norms_host[None, :used] * qn[:, None], 1e-12
            )
        ov_ids = index.ov_ids_host[:used].astype(np.int64)
    out_v = np.full((n, kk), -np.inf, np.float32)
    out_i = np.full((n, kk), -1, np.int32)
    for gi, tl in enumerate(lists):
        rows = order[gi * g : (gi + 1) * g]
        qg = qpad[rows]
        if len(tl):
            if tier is not None:
                slab = tier.gather_tiles(tl)  # [len(tl)*ts, kf] f32
            else:
                slab = plane3[tl].reshape(-1, kf)  # contiguous block take
            sc = slab @ qg.T  # [S, group] final-precision scores
            ssid = sids3[tl].reshape(-1).astype(np.int64)
            if cosine:
                nr = norms3[tl].reshape(-1)
                sc = sc / np.maximum(nr[:, None] * qn[rows][None, :], 1e-12)
            sc[ssid < 0, :] = -np.inf  # padding + tombstoned slots
        else:  # every probed cell was empty: overlay-only candidates
            sc = np.empty((0, len(rows)), np.float32)
            ssid = np.empty(0, np.int64)
        kp = min(kk, sc.shape[0])
        if kp and sc.shape[0] > kp:
            part = np.argpartition(-sc, kp - 1, axis=0)[:kp]  # [kp, group]
        else:
            part = np.broadcast_to(
                np.arange(sc.shape[0])[:, None], (sc.shape[0], len(rows))
            )
        for j, qi in enumerate(rows):
            pv = sc[part[:, j], j]
            pi = ssid[part[:, j]]
            if used:
                pv = np.concatenate([pv, ov_sc[qi]])
                pi = np.concatenate([pi, ov_ids])
            if not len(pv):
                continue
            # score desc, item id asc — the exact path's tie direction
            o = np.lexsort((pi, -pv))[:kk]
            pv, pi = pv[o], pi[o]
            fin = np.isfinite(pv)
            out_v[qi, : len(pv)] = np.where(fin, pv, -np.inf)
            out_i[qi, : len(pv)] = np.where(fin, pi, -1).astype(np.int32)
    return out_v, out_i


# -- query: probed scan + exact rescore ---------------------------------------


@functools.partial(
    jax.jit, static_argnames=("k", "kc", "tile", "chunk", "cosine")
)
def _probe_topk(
    mat_rows,
    mat_t,
    resid,
    scales,
    resid_scales,
    norms,
    slot_ids,
    ov_rows,
    ov_ids,
    ov_norms,
    q_gbf,
    tiles_ge,
    *,
    k,
    kc,
    tile,
    chunk,
    cosine,
):
    """[G, g, kf] query groups x [G, E] probed tiles -> (vals, ids) [G, g, k].

    Stage 1 is the exact scan's chunk-max ranking restricted to the
    probed tiles: a group's tile list gathers as one contiguous-block
    slab of the item-major primary plane, so the whole probed region is
    ONE int8->f32 conversion + GEMM shared by the query group, reduced
    to per-chunk maxes in the epilogue. (One big step per group, not one
    small step per tile — XLA:CPU charges ~100us of dispatch per scan
    step, which at thousands of tiles costs more than the math.)
    Stage 2 takes each query's top ``kc`` chunks and rescores their items
    through the same two-plane gather epilogue as the exact path's
    candidate tail, then merges the pending overlay's exact scores."""
    n_slots = mat_rows.shape[0]
    kf = mat_rows.shape[1]
    guard_chunk = n_slots // chunk - 1  # inside the guard tile: all masked
    tile_slots = tile * chunk
    n_tiles = n_slots // tile_slots
    # tile-blocked views: row-major reshapes, no data movement
    rows3 = mat_rows.reshape(n_tiles, tile_slots, kf)
    scales_t = scales.reshape(n_tiles, tile_slots)
    sids_t = slot_ids.reshape(n_tiles, tile_slots)
    norms_t = norms.reshape(n_tiles, tile_slots)

    def one(args):
        q, tl = args  # [g, kf], [E]
        g = q.shape[0]
        e = tl.shape[0]
        qn = jnp.linalg.norm(q, axis=1, keepdims=True) if cosine else None
        qt = q.T  # [kf, g]

        # contiguous-block gather of the probed tiles (each tile is one
        # memcpy-able run), then a single dense GEMM over the union
        slab = jnp.take(rows3, tl, axis=0).reshape(e * tile_slots, kf)
        s1 = jnp.take(scales_t, tl, axis=0).reshape(e * tile_slots)
        sid1 = jnp.take(sids_t, tl, axis=0).reshape(e * tile_slots)
        sc = (
            jnp.dot(
                slab.astype(jnp.float32),
                qt,
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
            * s1[:, None]
        )  # [e*tile_slots, g] plane-1 ranking scores
        if cosine:
            nr = jnp.take(norms_t, tl, axis=0).reshape(e * tile_slots)
            sc = sc / jnp.maximum(nr[:, None] * qn[None, :, 0], 1e-12)
        sc = jnp.where(sid1[:, None] >= 0, sc, -jnp.inf)
        cms = jnp.max(sc.reshape(e, tile, chunk, g), axis=2)  # [E, tile, g]
        allc = jnp.moveaxis(cms, 2, 0).reshape(g, -1)  # [g, E*tile]
        cv, cpos = jax.lax.top_k(allc, min(kc, allc.shape[1]))
        tchunk = tl[cpos // tile] * tile + cpos % tile  # global chunk ids
        # starved selections (-inf chunk max) land on the guard chunk so
        # the gather below cannot touch an unprobed cell's items
        tchunk = jnp.where(jnp.isfinite(cv), tchunk, guard_chunk)
        iid = (
            tchunk[:, :, None] * chunk
            + jnp.arange(chunk, dtype=jnp.int32)[None, None, :]
        ).reshape(g, -1)
        sid = slot_ids[iid]
        sc = pt._gathered_pair_scores(
            mat_t, resid, scales, resid_scales, norms, q, qn, iid, cosine=cosine
        )
        sc = jnp.where(sid >= 0, sc, -jnp.inf)
        # pending overlay: exact f32 scan of the updated rows
        osc = jnp.dot(
            q,
            ov_rows.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if cosine:
            osc = osc / jnp.maximum(ov_norms[None, :] * qn, 1e-12)
        osc = jnp.where(ov_ids[None, :] >= 0, osc, -jnp.inf)
        allv = jnp.concatenate([sc, osc], axis=1)
        alli = jnp.concatenate(
            [sid, jnp.broadcast_to(ov_ids[None, :], osc.shape)], axis=1
        )
        ke = min(k, allv.shape[1])
        v, p = jax.lax.top_k(allv, ke)
        out_ids = jnp.take_along_axis(alli, p, axis=1)
        # starved windows (k > finite candidates) pad with id -1, not a
        # garbage gather target — callers skip negatives
        out_ids = jnp.where(jnp.isfinite(v), out_ids, -1)
        if ke < k:
            v = jnp.pad(v, ((0, 0), (0, k - ke)), constant_values=-jnp.inf)
            out_ids = jnp.pad(out_ids, ((0, 0), (0, k - ke)), constant_values=-1)
        return v, out_ids

    if q_gbf.shape[0] == 1:
        v, i = one((q_gbf[0], tiles_ge[0]))
        return v[None], i[None]
    return jax.lax.map(one, (q_gbf, tiles_ge))


# -- query: full-probe exact mode ---------------------------------------------


@functools.partial(
    jax.jit, static_argnames=("k", "n_seg", "seg", "cosine", "chunk")
)
def _full_topk(
    mat_t,
    resid,
    scales,
    resid_scales,
    norms,
    slot_ids,
    chunk_start,
    chunk_count,
    ov_rows,
    ov_ids,
    ov_norms,
    queries_gbf,
    *,
    k,
    n_seg,
    seg,
    cosine,
    chunk,
):
    """nprobe == n_cells: every occupied chunk is a candidate and every
    candidate rescores through the shared two-plane epilogue — the
    ascending-item-id candidate order makes the stable top_k break score
    ties toward the lowest id, exactly like the exact scan. O(n) gather:
    this mode exists for the bit-for-bit contract (and tiny catalogs),
    not for speed — the probed path above is the serving path."""
    int_max = jnp.iinfo(jnp.int32).max
    n_cells = chunk_start.shape[0]
    q_chunks = n_seg * seg

    def one(q):
        g = q.shape[0]
        qn = jnp.linalg.norm(q, axis=1, keepdims=True) if cosine else None
        lens = jnp.broadcast_to(chunk_count[None, :], (g, n_cells))
        cum = jnp.cumsum(lens, axis=1)
        j = jnp.broadcast_to(
            jnp.arange(q_chunks, dtype=jnp.int32)[None, :], (g, q_chunks)
        )
        # which cell does global candidate-chunk j fall into
        pos = jax.vmap(lambda c, jj: jnp.searchsorted(c, jj, side="right"))(cum, j)
        valid = pos < n_cells
        posc = jnp.minimum(pos, n_cells - 1)
        prev = cum - lens
        within = j - jnp.take_along_axis(prev, posc, axis=1)
        chk = jnp.where(valid, chunk_start[posc] + within, 0)
        iid = (
            chk[:, :, None] * chunk
            + jnp.arange(chunk, dtype=jnp.int32)[None, None, :]
        ).reshape(g, q_chunks * chunk)
        sid = slot_ids[iid]  # [g, m] original ids; -1 = padding/tombstone
        ok = jnp.repeat(valid, chunk, axis=1) & (sid >= 0)
        # ascending item id, padding last — the stable per-segment + final
        # top_k then tie-breaks toward the lowest item id
        key = jnp.where(ok, sid, int_max)
        ordr = jnp.argsort(key, axis=1)
        iid = jnp.take_along_axis(iid, ordr, axis=1)
        sid = jnp.take_along_axis(sid, ordr, axis=1)
        ok = jnp.take_along_axis(ok, ordr, axis=1)
        seg_items = seg * chunk
        kk = max(1, min(k, seg_items))
        iid_s = jnp.moveaxis(iid.reshape(g, n_seg, seg_items), 1, 0)
        sid_s = jnp.moveaxis(sid.reshape(g, n_seg, seg_items), 1, 0)
        ok_s = jnp.moveaxis(ok.reshape(g, n_seg, seg_items), 1, 0)

        def seg_step(carry, xs):
            ii, ss, oo = xs
            sc = pt._gathered_pair_scores(
                mat_t, resid, scales, resid_scales, norms, q, qn, ii,
                cosine=cosine,
            )
            sc = jnp.where(oo, sc, -jnp.inf)
            v, p = jax.lax.top_k(sc, kk)
            return carry, (v, jnp.take_along_axis(ss, p, axis=1))

        if n_seg == 1:
            _, (vs, ids) = seg_step(0, (iid_s[0], sid_s[0], ok_s[0]))
            allv, alli = vs, ids
        else:
            _, (vs, ids) = jax.lax.scan(seg_step, 0, (iid_s, sid_s, ok_s))
            allv = jnp.moveaxis(vs, 0, 1).reshape(g, n_seg * kk)
            alli = jnp.moveaxis(ids, 0, 1).reshape(g, n_seg * kk)
        # pending overlay: exact f32 scan of the updated rows
        osc = jnp.dot(
            q,
            ov_rows.T,
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST,
        )
        if cosine:
            osc = osc / jnp.maximum(ov_norms[None, :] * qn, 1e-12)
        osc = jnp.where(ov_ids[None, :] >= 0, osc, -jnp.inf)
        allv = jnp.concatenate([allv, osc], axis=1)
        alli = jnp.concatenate(
            [alli, jnp.broadcast_to(ov_ids[None, :], osc.shape)], axis=1
        )
        ke = min(k, allv.shape[1])
        v, p = jax.lax.top_k(allv, ke)
        out_ids = jnp.take_along_axis(alli, p, axis=1)
        out_ids = jnp.where(jnp.isfinite(v), out_ids, -1)
        if ke < k:
            v = jnp.pad(v, ((0, 0), (0, k - ke)), constant_values=-jnp.inf)
            out_ids = jnp.pad(out_ids, ((0, 0), (0, k - ke)), constant_values=-1)
        return v, out_ids

    if queries_gbf.shape[0] == 1:
        v, i = one(queries_gbf[0])
        return v[None], i[None]
    return jax.lax.map(one, queries_gbf)


# -- query: entry points ------------------------------------------------------

# per-segment items for the full-probe gather (bounds the [kf, g, seg]
# f32 candidate planes to a few MB regardless of catalog size)
_SEG_ITEMS = 8192


def _group_queries(index: IVFIndex, queries: np.ndarray, order=None):
    """[n, feat] -> ([G, g, kf_pad] device f32, n, g). ``order`` permutes
    the queries before grouping (probe-locality sort)."""
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    n = q.shape[0]
    if order is not None:
        q = q[order]
    kf_pad = index.mat_t.shape[0]
    g = max(1, min(QUERY_BLOCK, n))
    groups = -(-n // g)
    padded = np.zeros((groups * g, kf_pad), np.float32)
    padded[:n, : q.shape[1]] = q
    return jnp.asarray(padded.reshape(groups, g, kf_pad)), n, g


def top_k_device(
    index: IVFIndex,
    queries: np.ndarray,
    k: int,
    *,
    nprobe: int | None = None,
    cosine: bool = False,
):
    """(vals [n, k], ids [n, k]) device arrays; ids are ORIGINAL item
    row indices (-1 pads starved windows)."""
    np_ = index.resolve_nprobe(nprobe)
    kk = max(1, int(k))
    # an empty overlay shrinks to one masked dummy row: the overlay GEMM
    # against the full capacity (default 4096 rows) would otherwise cost
    # more than the probed scan itself
    if index.ov_used == 0:
        ov_rows, ov_ids, ov_norms = (
            index.ov_rows[:1],
            index.ov_ids[:1],
            index.ov_norms[:1],
        )
    else:
        ov_rows, ov_ids, ov_norms = index.ov_rows, index.ov_ids, index.ov_norms
    if np_ >= index.n_cells:
        q_gbf, n, g = _group_queries(index, queries)
        total_chunks = max(1, int(index.chunk_count_host.sum()))
        seg = max(1, _SEG_ITEMS // index.chunk)
        n_seg = -(-total_chunks // seg)
        if n_seg == 1:
            seg = total_chunks
        vals, ids = _full_topk(
            index.mat_t,
            index.resid,
            index.scales,
            index.resid_scales,
            index.norms,
            index.slot_ids,
            index.chunk_start,
            index.chunk_count,
            ov_rows,
            ov_ids,
            ov_norms,
            q_gbf,
            k=kk,
            n_seg=n_seg,
            seg=seg,
            cosine=cosine,
            chunk=index.chunk,
        )
        out_k = vals.shape[-1]
        return vals.reshape(-1, out_k)[:n], ids.reshape(-1, out_k)[:n]
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    n = q.shape[0]
    kf_pad = index.mat_t.shape[0]
    qpad = np.zeros((n, kf_pad), np.float32)
    qpad[:, : q.shape[1]] = q
    cells = np.asarray(
        _route_cells(
            index.centroids_t,
            index.centroid_norms,
            jnp.asarray(qpad),
            nprobe=np_,
            cosine=cosine,
        )
    )
    if index.host_plane is not None or index.tier is not None:
        if index.tier is not None:
            # issue the async disk->RAM copies for every probed cell
            # before the group loop scans them in sequence
            index.tier.prefetch_cells(np.unique(cells))
        vals_np, ids_np = _host_topk(index, qpad, cells, kk, cosine)
        return jnp.asarray(vals_np), jnp.asarray(ids_np)
    # probe-locality sort: queries sharing a best cell land in the same
    # scan group, shrinking each group's cell union (the scan covers the
    # union, so overlap is pure savings); results unsort at the end
    order = np.argsort(cells[:, 0], kind="stable")
    g = max(1, min(QUERY_BLOCK, n))
    groups = -(-n // g)
    lists = _group_tile_lists(index, cells[order], g)
    qs = np.zeros((groups * g, kf_pad), np.float32)
    qs[:n] = qpad[order]
    qs = qs.reshape(groups, g, kf_pad)
    # bucket groups by pow2(union size): each bucket pads only to ITS
    # widest member, so one pathological union doesn't tax every group
    buckets: dict[int, list[int]] = {}
    for gi, t in enumerate(lists):
        buckets.setdefault(_pow2_ceil(max(1, len(t))), []).append(gi)
    row_src = []  # sorted-query row ranges, in bucket emission order
    parts_v, parts_i = [], []
    for e, gis in sorted(buckets.items()):
        tiles = _pack_tiles(index, [lists[gi] for gi in gis], e)
        v, i = _probe_topk(
            index.mat_rows,
            index.mat_t,
            index.resid,
            index.scales,
            index.resid_scales,
            index.norms,
            index.slot_ids,
            ov_rows,
            ov_ids,
            ov_norms,
            jnp.asarray(qs[gis]),
            tiles,
            k=kk,
            kc=pt._chunk_k(kk, e * index.tile_chunks),
            tile=index.tile_chunks,
            chunk=index.chunk,
            cosine=cosine,
        )
        parts_v.append(v.reshape(-1, v.shape[-1]))
        parts_i.append(i.reshape(-1, i.shape[-1]))
        for gi in gis:
            row_src.append(np.arange(gi * g, (gi + 1) * g, dtype=np.int64))
    stacked_v = parts_v[0] if len(parts_v) == 1 else jnp.concatenate(parts_v)
    stacked_i = parts_i[0] if len(parts_i) == 1 else jnp.concatenate(parts_i)
    # stacked row j holds sorted-query row_src[j]; compose with the
    # locality unsort so one device gather restores caller order
    where = np.empty(groups * g, np.int64)
    where[np.concatenate(row_src)] = np.arange(groups * g)
    inv = np.argsort(order)
    sel = jnp.asarray(where[inv].astype(np.int32))
    return stacked_v[sel], stacked_i[sel]


def top_k(
    index: IVFIndex,
    queries: np.ndarray,
    k: int,
    *,
    nprobe: int | None = None,
    cosine: bool = False,
):
    """Blocking host-side form: (ids [n, k] int32, vals [n, k] f32)."""
    vals, ids = top_k_device(index, queries, k, nprobe=nprobe, cosine=cosine)
    return np.asarray(ids), np.asarray(vals)


def top_k_device_indexed(
    index: IVFIndex,
    x_dev: jax.Array,
    indices: np.ndarray,
    k: int,
    *,
    nprobe: int | None = None,
    cosine: bool = False,
):
    """Index-submit twin: queries are rows of the device-resident X."""
    idx = np.atleast_1d(np.asarray(indices, dtype=np.int32))
    q = np.asarray(x_dev[jnp.asarray(idx)])  # device gather, tiny download
    return top_k_device(index, q, k, nprobe=nprobe, cosine=cosine)


# -- update path (speed-layer fold-ins) ---------------------------------------


@jax.jit
def _apply_overlay(slot_ids, ov_rows, ov_ids, ov_norms, dead, pos, rows, ids, nrm):
    # dead slots repeat their last entry when bucketed — set(-1) is
    # idempotent, so duplicates are harmless
    slot_ids = slot_ids.at[dead].set(-1)
    ov_rows = ov_rows.at[pos].set(rows)
    ov_ids = ov_ids.at[pos].set(ids)
    ov_norms = ov_norms.at[pos].set(nrm)
    return slot_ids, ov_rows, ov_ids, ov_norms


def update_rows(
    index: IVFIndex,
    rows: np.ndarray,
    values: np.ndarray,
    n_items: int | None = None,
) -> IVFIndex:
    """Fold updated item rows into the index via the pending overlay.

    Each touched row's cell slot is tombstoned (slot id -> -1) and its
    fresh vector lands in the overlay, which queries scan exactly — so a
    speed-layer fold-in is visible on the very next request regardless of
    which cells it routes to. Overlay rows store the two-plane
    DEQUANTIZED values (q1*s1 + q2*s2), so their scores match what a full
    rebuild would serve to f32 rounding.

    A full overlay DEGRADES instead of raising: the oldest overlay
    entries are evicted to ``index.pending_spill`` (raw values + fold-in
    time) and their slots reused, so the fold-in path stays O(batch)
    regardless of pressure — the spilled rows go invisible until
    ``compact_ivf`` folds them back. Re-updating an overlaid item
    refreshes its recency (and its spill entry, if any, is superseded).
    """
    rows = np.asarray(rows, dtype=np.int64)
    values = np.ascontiguousarray(np.atleast_2d(values), dtype=np.float32)
    if len(rows) == 0:
        return index
    count = int(index.n_items if n_items is None else n_items)
    # last write wins for duplicate ids in one batch
    last = {}
    for i, r in enumerate(rows):
        last[int(r)] = i
    ids = np.fromiter(last.keys(), dtype=np.int64, count=len(last))
    vals = values[np.fromiter(last.values(), dtype=np.int64, count=len(last))]

    cap = index.ov_rows.shape[0]
    ov_map = index.ov_map
    spill = index.pending_spill if index.pending_spill is not None else {}
    born = index.ov_born if index.ov_born is not None else {}
    now = time.time()
    used = index.ov_used
    pos = np.empty(len(ids), np.int32)
    fresh = 0
    for i, item in enumerate(ids):
        item = int(item)
        spill.pop(item, None)  # a fresh value supersedes any spilled one
        if item in ov_map:
            # keep the slot but refresh recency (dict order = age order)
            pos[i] = ov_map.pop(item)
            ov_map[item] = int(pos[i])
        elif used + fresh >= cap:
            if ov_map:
                # overlay full: evict the OLDEST entry to the spill queue
                # and reuse its slot (the scatter below overwrites it)
                old_id, old_slot = next(iter(ov_map.items()))
                ov_map.pop(old_id)
                if index.ov_raw_host is not None:
                    spill[old_id] = (
                        index.ov_raw_host[old_slot].copy(),
                        born.pop(old_id, now),
                    )
                else:
                    born.pop(old_id, None)
                pos[i] = old_slot
                ov_map[item] = int(old_slot)
            else:
                # every slot already belongs to THIS batch's fresh
                # entries (they join ov_map only after the scatter): the
                # incoming row spills directly — its raw value is right
                # here in vals, no slot round-trip needed
                if index.ov_raw_host is not None:
                    raw = np.zeros(index.mat_t.shape[0], np.float32)
                    raw[: vals.shape[1]] = vals[i]
                    spill[item] = (raw, now)
                born.pop(item, None)
                pos[i] = -1
        else:
            pos[i] = used + fresh
            fresh += 1
    dead = np.array(
        [
            index.id_to_slot[item]
            for item in ids
            if item < len(index.id_to_slot) and index.id_to_slot[item] >= 0
        ],
        dtype=np.int32,
    )

    keep = pos >= 0
    if not keep.all():
        # direct-spilled rows skip the overlay scatter, but any base-slot
        # versions of them still die (dead above covers them) and the
        # host mirror forgets the base mapping so lookups go to the spill
        for i in np.flatnonzero(~keep):
            item = int(ids[i])
            if item < len(index.id_to_slot):
                index.id_to_slot[item] = -1
        ids, vals, pos = ids[keep], vals[keep], pos[keep]
        if len(ids) == 0:
            if len(dead) and index.slot_ids_host is not None:
                index.slot_ids_host[dead] = -1
            slot_ids = index.slot_ids
            if len(dead):
                slot_ids = slot_ids.at[jnp.asarray(dead)].set(-1)
            return dataclasses.replace(
                index,
                slot_ids=slot_ids,
                n_items=max(count, index.n_items),
                ov_used=used + fresh,
            )

    q, s = pt._quantize_rows(vals)
    q2, s2 = pt._quantize_residual(vals, q, s)
    deq = q.astype(np.float32) * s[:, None] + q2.astype(np.float32) * s2[:, None]
    kf_pad = index.mat_t.shape[0]
    deq_pad = np.zeros((len(ids), kf_pad), np.float32)
    deq_pad[:, : vals.shape[1]] = deq
    nrm = np.linalg.norm(vals, axis=1)

    # bucket the scatter shapes like topn.update_rows (pad repeats the
    # last entry; rewriting the same overlay slot with the same row is
    # a no-op) so jit retraces O(log n) shapes
    def bucket(arr):
        m = len(arr)
        b = _pow2_ceil(m)
        if b == m:
            return arr
        return np.concatenate([arr, np.repeat(arr[-1:], b - m, axis=0)], axis=0)

    slot_ids, ov_rows, ov_ids, ov_norms = (
        index.slot_ids,
        index.ov_rows,
        index.ov_ids,
        index.ov_norms,
    )
    if len(dead):
        slot_ids, ov_rows, ov_ids, ov_norms = _apply_overlay(
            slot_ids,
            ov_rows,
            ov_ids,
            ov_norms,
            jnp.asarray(bucket(dead)),
            jnp.asarray(bucket(pos)),
            jnp.asarray(bucket(deq_pad)),
            jnp.asarray(bucket(ids.astype(np.int32))),
            jnp.asarray(bucket(nrm.astype(np.float32))),
        )
    else:
        pos_b = jnp.asarray(bucket(pos))
        ov_rows = ov_rows.at[pos_b].set(jnp.asarray(bucket(deq_pad)))
        ov_ids = ov_ids.at[pos_b].set(jnp.asarray(bucket(ids.astype(np.int32))))
        ov_norms = ov_norms.at[pos_b].set(jnp.asarray(bucket(nrm.astype(np.float32))))

    # host bookkeeping (see class docstring: serialized by the caller)
    if index.slot_ids_host is not None:
        if len(dead):
            index.slot_ids_host[dead] = -1  # tombstone in the host mirror
        index.ov_rows_host[pos] = deq_pad
        index.ov_ids_host[pos] = ids.astype(np.int32)
        index.ov_norms_host[pos] = nrm.astype(np.float32)
    if index.ov_raw_host is not None:
        raw_pad = np.zeros((len(ids), kf_pad), np.float32)
        raw_pad[:, : vals.shape[1]] = vals
        index.ov_raw_host[pos] = raw_pad
    for i, item in enumerate(ids):
        item = int(item)
        ov_map[item] = int(pos[i])
        born[item] = now
        if item < len(index.id_to_slot):
            index.id_to_slot[item] = -1
    return dataclasses.replace(
        index,
        slot_ids=slot_ids,
        ov_rows=ov_rows,
        ov_ids=ov_ids,
        ov_norms=ov_norms,
        n_items=max(count, index.n_items),
        ov_used=used + fresh,
    )


def capacity(index: IVFIndex) -> int:
    """Rows the handle can represent without a rebuild: the built catalog
    plus whatever overlay slots remain for appended items. (With a
    maintainer attached callers may exceed this — the overlay spills and
    compaction absorbs the growth — but absent one this is the honest
    always-visible bound.)"""
    return index.n_items + (index.ov_rows.shape[0] - index.ov_used)


# -- maintenance (background compaction; serving/maintain.py drives) ----------


@dataclasses.dataclass
class PendingSnapshot:
    """A consistent copy of everything compaction folds in: the overlay's
    raw rows plus the spill queue, with per-item fold-in times."""

    ids: np.ndarray  # [m] int64 item ids
    raw: np.ndarray  # [m, kf_pad] f32 RAW (pre-quantization) values
    born: dict  # item id -> fold-in wall-clock seconds
    taken_at: float  # wall-clock seconds at snapshot


def snapshot_pending(index: IVFIndex) -> PendingSnapshot:
    """Copy the overlay + spill queue out of the index.

    Call under the OWNER's serialization (the serving model's cache
    lock): ``compact_ivf`` then runs entirely on immutable device arrays
    plus these copies, so concurrent fold-ins mutating the live host
    bookkeeping (``ov_map``/``ov_raw_host``/``pending_spill``) never race
    the background compaction."""
    ids: list[int] = []
    rows: list[np.ndarray] = []
    born: dict[int, float] = {}
    src_born = index.ov_born or {}
    now = time.time()
    for item, slot in index.ov_map.items():
        ids.append(item)
        rows.append(index.ov_raw_host[slot].copy())
        born[item] = src_born.get(item, now)
    for item, (raw, b) in (index.pending_spill or {}).items():
        ids.append(item)
        rows.append(np.asarray(raw, dtype=np.float32))
        born[item] = float(b)
    kf_pad = index.mat_t.shape[0]
    raw = (
        np.vstack(rows).astype(np.float32, copy=False)
        if rows
        else np.zeros((0, kf_pad), np.float32)
    )
    return PendingSnapshot(np.asarray(ids, np.int64), raw, born, now)


def needs_maintenance(index, watermark: float = 0.5) -> bool:
    """Is it time to compact? True once anything spilled (those rows are
    invisible until compaction) or the overlay passed the watermark."""
    if not isinstance(index, IVFIndex):
        return False
    if index.pending_spill:
        return True
    cap = index.ov_rows.shape[0]
    return index.ov_used >= max(1, int(float(watermark) * cap))


def compact_ivf(
    index: IVFIndex,
    pending: PendingSnapshot | None = None,
    *,
    seed: int = 0,
    split_max_items: int = 0,
    merge_min_items: int = 0,
) -> tuple[IVFIndex, dict]:
    """Fold the overlay + spill queue into a fresh cell-contiguous layout
    WITHOUT retraining the coarse quantizer (the no-stop-the-world
    rebuild: SPFresh's LIRE rebalancing applied to the IVF tier).

    - retained rows keep their quantized codes/scales/norms VERBATIM —
      per-row quantization is deterministic, so the compacted planes are
      bit-identical to a from-scratch ``build_ivf`` over the same item
      set (the full-probe exactness contract transfers);
    - pending rows quantize fresh from their RAW values and assign to
      their nearest centroid;
    - tombstoned slots are garbage-collected by omission;
    - cells grown past ``split_max_items`` split via a local 2-means
      (children replace the parent centroid); cells starved below
      ``merge_min_items`` dissolve into their members' nearest surviving
      centroid. Zero thresholds auto-derive from the mean cell load
      (4x mean splits, mean/8 merges).

    Returns ``(new_index, stats)``; the new index starts with an empty
    overlay and spill queue. Runs on the caller's thread — the maintainer
    calls it OFF the request path and swaps the result in under the
    model's lock."""
    if pending is None:
        pending = snapshot_pending(index)
    feat = index.features
    chunk = index.chunk
    tile_chunks = index.tile_chunks
    ts = tile_chunks * chunk
    cells0 = index.n_cells

    # slot -> cell from the tile spans (cells are laid out contiguously
    # from slot 0 in cell order; the trailing guard tile maps to no cell)
    spans = (index.tile_count_host * ts).astype(np.int64)
    slot_cell = np.full(index.n_slots, -1, np.int64)
    slot_cell[: int(spans.sum())] = np.repeat(
        np.arange(cells0, dtype=np.int64), spans
    )

    sids = np.asarray(index.slot_ids)
    live = np.flatnonzero(sids >= 0)
    r_ids = sids[live].astype(np.int64)
    r_cell = slot_cell[live]
    r_q = np.asarray(index.mat_rows)[live][:, :feat]
    r_q2 = np.ascontiguousarray(np.asarray(index.resid)[:, live].T)[:, :feat]
    r_s = np.asarray(index.scales)[0, live]
    r_s2 = np.asarray(index.resid_scales)[0, live]
    r_n = np.asarray(index.norms)[0, live]

    centers = np.ascontiguousarray(np.asarray(index.centroids_t).T[:, :feat])
    p_ids = pending.ids
    if len(p_ids):
        p_raw = np.ascontiguousarray(pending.raw[:, :feat])
        p_cell = _assign_cells(p_raw, centers).astype(np.int64)
        p_q, p_s = pt._quantize_rows(p_raw)
        p_q2, p_s2 = pt._quantize_residual(p_raw, p_q, p_s)
        p_n = np.linalg.norm(p_raw, axis=1)
        ids = np.concatenate([r_ids, p_ids])
        cell = np.concatenate([r_cell, p_cell])
        q = np.vstack([r_q, p_q])
        q2 = np.vstack([r_q2, p_q2])
        s = np.concatenate([r_s, p_s])
        s2 = np.concatenate([r_s2, p_s2])
        nv = np.concatenate([r_n, p_n])
    else:
        ids, cell, q, q2, s, s2, nv = r_ids, r_cell, r_q, r_q2, r_s, r_s2, r_n
    n = len(ids)
    if n == 0:
        raise ValueError("compaction would produce an empty index")

    mean = max(1, n // max(1, cells0))
    merge_min = int(merge_min_items) or max(1, mean // 8)
    split_max = int(split_max_items) or max(mean * 4, merge_min + 1)

    # -- merges: starved cells dissolve into their nearest survivor ------
    counts = np.bincount(cell, minlength=cells0)
    victims = np.flatnonzero(counts < merge_min)
    merges = 0
    if len(victims) == cells0:  # keep the fattest cell alive
        victims = victims[victims != int(np.argmax(counts))]
    if len(victims):
        surv = np.setdiff1d(np.arange(cells0), victims, assume_unique=True)
        remap = np.full(cells0, -1, np.int64)
        remap[surv] = np.arange(len(surv))
        cell = remap[cell]
        mov = np.flatnonzero(cell < 0)
        if len(mov):
            deq = (
                q[mov].astype(np.float32) * s[mov, None]
                + q2[mov].astype(np.float32) * s2[mov, None]
            )
            cell[mov] = _assign_cells(deq, centers[surv]).astype(np.int64)
        centers = np.ascontiguousarray(centers[surv])
        merges = int(len(victims))

    # -- splits: overloaded cells split via a local 2-means --------------
    splits = 0
    counts = np.bincount(cell, minlength=len(centers))
    big = np.flatnonzero(counts > split_max)
    if len(big):
        from oryx_tpu.ops.kmeans import train_kmeans

        order_c = np.argsort(cell, kind="stable")
        bounds = np.zeros(len(centers) + 1, np.int64)
        np.cumsum(counts, out=bounds[1:])
        extra: list[np.ndarray] = []
        for c in big:
            mem = order_c[bounds[c] : bounds[c + 1]]
            deq = (
                q[mem].astype(np.float32) * s[mem, None]
                + q2[mem].astype(np.float32) * s2[mem, None]
            )
            minibatch = 32_768 if len(deq) > 65_536 else None
            sub_c, _cnt, _cost = train_kmeans(
                deq,
                2,
                iterations=4,
                init="k-means||",
                seed=seed + 17 * int(c),
                minibatch_size=minibatch,
            )
            sub_c = np.asarray(sub_c, dtype=np.float32)
            half = _assign_cells(deq, sub_c)
            if half.min() == half.max():
                continue  # degenerate split (all rows one side): skip
            centers[c] = sub_c[0]
            cell[mem[half == 1]] = len(centers) + len(extra)
            extra.append(sub_c[1])
            splits += 1
        if extra:
            centers = np.vstack([centers] + [e[None, :] for e in extra])

    n_items = max(index.n_items, int(ids.max()) + 1)
    new_index = _assemble_layout(
        ids,
        cell,
        centers,
        q,
        q2,
        s,
        s2,
        nv,
        feat=feat,
        chunk=chunk,
        tile_chunks=tile_chunks,
        cap=index.ov_rows.shape[0],
        n_items=n_items,
        host1=index.slot_ids_host is not None,
    )
    stats = {
        "folded": int(len(p_ids)),
        "live": int(len(r_ids)),
        "cells": int(len(centers)),
        "splits": int(splits),
        "merges": merges,
        "born": dict(pending.born),
        "taken_at": pending.taken_at,
    }
    return new_index, stats


def _assemble_layout(
    ids: np.ndarray,
    cell: np.ndarray,
    centers: np.ndarray,
    q: np.ndarray,
    q2: np.ndarray,
    s: np.ndarray,
    s2: np.ndarray,
    norms_v: np.ndarray,
    *,
    feat: int,
    chunk: int,
    tile_chunks: int,
    cap: int,
    n_items: int,
    host1: bool,
) -> IVFIndex:
    """Lay (ids, cell assignment, codes) out cell-contiguous and
    tile-aligned — ``build_ivf``'s layout stage over PRE-QUANTIZED rows.
    Within a cell items order by ascending id, preserving the exact
    path's tie direction."""
    n = len(ids)
    cells = len(centers)
    tile_slots = tile_chunks * chunk
    order = np.lexsort((ids, cell))  # cell-major, ascending id within
    counts = np.bincount(cell, minlength=cells).astype(np.int64)
    chunk_counts = -(-counts // chunk)
    tile_counts = -(-chunk_counts // tile_chunks)
    spans = tile_counts * tile_slots
    item_starts = np.zeros(cells + 1, np.int64)
    np.cumsum(counts, out=item_starts[1:])
    slot_base = np.zeros(cells + 1, np.int64)
    np.cumsum(spans, out=slot_base[1:])
    n_slots = int(slot_base[-1]) + tile_slots  # +1 guard tile
    pos_in_cell = np.arange(n, dtype=np.int64) - np.repeat(
        item_starts[:-1], counts
    )
    slots_sorted = np.repeat(slot_base[:-1], counts) + pos_in_cell

    kf_pad = pt._ceil_to(feat, pt._INT8_FEAT_MULTIPLE)
    mat_t = np.zeros((kf_pad, n_slots), np.int8)
    resid = np.zeros((kf_pad, n_slots), np.int8)
    mat_rows = np.zeros((n_slots, kf_pad), np.int8)
    scales = np.ones((1, n_slots), np.float32)
    rscales = np.ones((1, n_slots), np.float32)
    norms = np.zeros((1, n_slots), np.float32)
    slot_ids = np.full(n_slots, -1, np.int32)
    slot_ids[slots_sorted] = ids[order].astype(np.int32)
    id_to_slot = np.full(n_items, -1, np.int32)
    id_to_slot[ids[order]] = slots_sorted.astype(np.int32)
    host_plane = np.zeros((n_slots, kf_pad), np.float32) if host1 else None
    slice_rows = 1_000_000  # bounds the host transient like build_ivf
    for beg in range(0, n, slice_rows):
        rows = order[beg : beg + slice_rows]
        sl = slots_sorted[beg : beg + slice_rows]
        qs_ = q[rows][:, :feat]
        q2s_ = q2[rows][:, :feat]
        ss_ = s[rows]
        s2s_ = s2[rows]
        mat_t[:feat, sl] = qs_.T
        resid[:feat, sl] = q2s_.T
        mat_rows[sl, :feat] = qs_
        scales[0, sl] = ss_
        rscales[0, sl] = s2s_
        norms[0, sl] = norms_v[rows]
        if host_plane is not None:
            host_plane[sl, :feat] = (
                qs_.astype(np.float32) * ss_[:, None]
                + q2s_.astype(np.float32) * s2s_[:, None]
            )

    cent_t = np.zeros((kf_pad, cells), np.float32)
    cent_t[:feat] = centers.T

    return IVFIndex(
        mat_t=jnp.asarray(mat_t),
        resid=jnp.asarray(resid),
        mat_rows=jnp.asarray(mat_rows),
        scales=jnp.asarray(scales),
        resid_scales=jnp.asarray(rscales),
        norms=jnp.asarray(norms),
        slot_ids=jnp.asarray(slot_ids),
        centroids_t=jnp.asarray(cent_t),
        centroid_norms=jnp.asarray(np.linalg.norm(centers, axis=1)),
        chunk_start=jnp.asarray((slot_base[:-1] // chunk).astype(np.int32)),
        chunk_count=jnp.asarray(chunk_counts.astype(np.int32)),
        ov_rows=jnp.zeros((cap, kf_pad), jnp.float32),
        ov_ids=jnp.full((cap,), -1, jnp.int32),
        ov_norms=jnp.zeros((cap,), jnp.float32),
        n_items=n_items,
        features=feat,
        chunk=chunk,
        tile_chunks=tile_chunks,
        chunk_count_host=chunk_counts,
        tile_start_host=slot_base[:-1] // tile_slots,
        tile_count_host=tile_counts,
        id_to_slot=id_to_slot,
        ov_map={},
        ov_used=0,
        host_plane=host_plane,
        slot_ids_host=slot_ids.copy() if host1 else None,
        norms_host=norms[0].copy() if host1 else None,
        ov_rows_host=np.zeros((cap, kf_pad), np.float32) if host1 else None,
        ov_ids_host=np.full((cap,), -1, np.int32) if host1 else None,
        ov_norms_host=np.zeros((cap,), np.float32) if host1 else None,
        ov_raw_host=np.zeros((cap, kf_pad), np.float32),
        ov_born={},
        pending_spill={},
    )


# -- tiered host plane (native/store.py) --------------------------------------


def attach_tiered_plane(index: IVFIndex, plane=None) -> IVFIndex:
    """Move the host stage-1 plane into the tiered HBM->RAM->disk cell
    store. Returns a new handle with ``tier`` set and the flat
    ``host_plane`` dropped (the hot tier's working set replaces it);
    a no-op when tiering is off or the index has no host plane. Pass a
    prebuilt ``plane`` to adopt one (tests)."""
    if index.host_plane is None or index.tier is not None:
        return index
    if plane is None:
        from oryx_tpu.native import store as fstore

        if not fstore.tier_active():
            return index
        plane = fstore.TieredHostPlane.build(
            index.host_plane,
            tile_start=np.asarray(index.tile_start_host, np.int64),
            tile_count=np.asarray(index.tile_count_host, np.int64),
            tile_slots=index.tile_chunks * index.chunk,
            centroids=np.ascontiguousarray(np.asarray(index.centroids_t)),
            centroid_norms=np.asarray(index.centroid_norms),
        )
    return dataclasses.replace(index, tier=plane, host_plane=None)
