"""Top-N scoring: batched matvec + top_k on device.

Replaces the reference's per-request thread-pool scan over LSH partitions
(ALSServingModel.topN / TopNConsumer.java, VectorMath.dot in the hot
loop): dot scores for ALL items are computed on the MXU and top-k
selected on device. Two device backends share one public API:

- ``xla``: plain ``scores = Q @ Y.T`` + ``lax.top_k`` — simple, fine for
  small/medium item matrices;
- ``pallas`` (TPU): the fused streaming kernel in
  :mod:`oryx_tpu.ops.pallas_topn`, which never materializes the [b, n]
  score matrix in HBM and can hold items in bfloat16 — 2-6x less HBM
  traffic at 1M+ items.

``upload`` picks the backend (pallas when running on TPU, xla
otherwise); ``top_k_scores`` / ``top_k_scores_batch`` dispatch on the
uploaded handle's type.

``submit_top_k`` is the async form: it enqueues the device computation
and a non-blocking device→host copy, returning a handle whose
``result()`` materializes the answer. Callers that keep several requests
in flight (the serving layer's request pipeline, bench.py) overlap
device compute and PCIe/tunnel transfers instead of paying a full
round-trip per request.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from oryx_tpu.ops import ivf as ivf_ops
from oryx_tpu.ops.ivf import IVFIndex
from oryx_tpu.ops.pallas_topn import (
    StreamingItemMatrix,
    _is_int8,
    _quantize_residual,
    _quantize_rows,
    top_k_streaming,
    top_k_streaming_device,
    top_k_streaming_device_multi,
    upload_streaming,
)


def _default_streaming() -> bool:
    return jax.default_backend() == "tpu"


def upload(
    matrix: np.ndarray,
    dtype=None,
    streaming: bool | None = None,
):
    """Move a packed [n, k] float32 item matrix to device.

    Returns an opaque handle for the top-k functions. On TPU the handle
    is a :class:`StreamingItemMatrix` (feature-major layout for the
    Pallas kernel, optionally bfloat16); elsewhere it is the plain
    ``(matrix, norms)`` device pair for the XLA path.

    ``dtype=int8`` returns the streaming (feature-major, row-quantized)
    handle on EVERY backend: the quantized scan engine owns that layout,
    and non-TPU backends scan it with the fused XLA twin of the kernel
    rather than materializing [b, n] scores.
    """
    if _is_int8(dtype):
        return upload_streaming(matrix, dtype=jnp.int8)
    if streaming is None:
        streaming = _default_streaming()
    if streaming:
        return upload_streaming(matrix, dtype=dtype or jnp.float32)
    mat = jnp.asarray(matrix, dtype=dtype or jnp.float32)
    norms = jnp.linalg.norm(mat.astype(jnp.float32), axis=1)
    return mat, norms


def _dot_precision(dtype):
    """f32 scoring gets true f32 MXU accumulation — the TPU default would
    silently drop f32 matmuls to bf16 passes, making the "exact" XLA path
    *less* precise than the Pallas kernel it is the reference twin for.
    bf16 inputs stay on the intentional fast path."""
    return jax.lax.Precision.HIGHEST if dtype == jnp.float32 else jax.lax.Precision.DEFAULT


@functools.partial(jax.jit, static_argnums=2)
def _dot_topk(mat, query, k):
    scores = jnp.dot(
        mat, query, preferred_element_type=jnp.float32, precision=_dot_precision(mat.dtype)
    )
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnums=3)
def _cosine_topk(mat, norms, query, k):
    qn = jnp.linalg.norm(query.astype(jnp.float32))
    scores = jnp.dot(
        mat, query, preferred_element_type=jnp.float32, precision=_dot_precision(mat.dtype)
    ) / jnp.maximum(norms * qn, 1e-12)
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _dot_topk_batch(mat, norms, queries, k, cosine, download_dtype=None):
    scores = jnp.dot(
        queries, mat.T, preferred_element_type=jnp.float32, precision=_dot_precision(mat.dtype)
    )  # [b, n]
    if cosine:
        qn = jnp.linalg.norm(queries.astype(jnp.float32), axis=1, keepdims=True)
        scores = scores / jnp.maximum(norms[None, :] * qn, 1e-12)
    vals, idxs = jax.lax.top_k(scores, k)
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


def top_k_scores(uploaded, query: np.ndarray, k: int, cosine: bool = False):
    """(indices, scores) of the k best items for one query vector."""
    if isinstance(uploaded, IVFIndex):
        idx, vals = ivf_ops.top_k(uploaded, query, k, cosine=cosine)
        return idx[0], vals[0]
    if isinstance(uploaded, StreamingItemMatrix):
        idx, vals = top_k_streaming(uploaded, query, k, cosine=cosine)
        return idx[0], vals[0]
    mat, norms = uploaded
    k = max(1, min(int(k), mat.shape[0]))
    q = jnp.asarray(query, dtype=mat.dtype)
    if cosine:
        s, i = _cosine_topk(mat, norms, q, k)
    else:
        s, i = _dot_topk(mat, q, k)
    return np.asarray(i), np.asarray(s)


def top_k_scores_batch(uploaded, queries: np.ndarray, k: int, cosine: bool = False):
    """Batched top-k for [b, k] query vectors (concurrent requests)."""
    if isinstance(uploaded, IVFIndex):
        return ivf_ops.top_k(uploaded, queries, k, cosine=cosine)
    if isinstance(uploaded, StreamingItemMatrix):
        return top_k_streaming(uploaded, queries, k, cosine=cosine)
    mat, norms = uploaded
    k = max(1, min(int(k), mat.shape[0]))
    q = jnp.asarray(queries, dtype=mat.dtype)
    s, i = _dot_topk_batch(mat, norms, q, k, cosine)
    return np.asarray(i), np.asarray(s)


# -- mesh-sharded scan --------------------------------------------------------


@dataclass
class ShardedItemMatrix:
    """Item matrix row-sharded over a device mesh: each device holds an
    [n/d, k] slice plus its norms. The multi-chip serving layout — a
    40M x 200 f32 model is 32 GB replicated but 2 GB/chip on a v5e-16
    (SURVEY §2.12 request parallelism; the reference shards the same way
    across LSH thread partitions on one host)."""

    mat: jax.Array  # [n_pad, k], rows sharded over 'data'; f32/bf16/int8
    norms: jax.Array  # [n_pad], sharded alike
    n_items: int
    mesh: object
    scales: jax.Array | None = None  # [n_pad] per-row int8 dequant scale
    resid: jax.Array | None = None  # [n_pad, k] int8 residual plane
    resid_scales: jax.Array | None = None  # [n_pad] residual dequant scale


def upload_sharded(matrix: np.ndarray, mesh, dtype=None) -> ShardedItemMatrix:
    """Shard a packed [n, k] item matrix row-wise over `mesh`'s devices
    (padded so every device gets an equal slice). ``dtype=int8``
    row-quantizes exactly like the streaming handle: int8 codes sharded
    with the rows, one f32 scale per row riding next to the norms."""
    from oryx_tpu.parallel.mesh import data_sharding, pad_to_multiple, shard_rows

    n, k = matrix.shape
    d = mesh.devices.size
    n_pad = pad_to_multiple(max(n, d), d)
    mat = np.zeros((n_pad, k), dtype=np.float32)
    mat[:n] = matrix
    norms = np.linalg.norm(mat, axis=1)
    if _is_int8(dtype):
        q, s = _quantize_rows(mat)  # pad rows are all-zero -> scale 1.0
        q2, s2 = _quantize_residual(mat, q, s)
        return ShardedItemMatrix(
            mat=jax.device_put(jnp.asarray(q), data_sharding(mesh, 2)),
            norms=jax.device_put(jnp.asarray(norms), shard_rows(mesh)),
            n_items=n,
            mesh=mesh,
            scales=jax.device_put(jnp.asarray(s), shard_rows(mesh)),
            resid=jax.device_put(jnp.asarray(q2), data_sharding(mesh, 2)),
            resid_scales=jax.device_put(jnp.asarray(s2), shard_rows(mesh)),
        )
    return ShardedItemMatrix(
        mat=jax.device_put(jnp.asarray(mat, dtype=dtype or jnp.float32), data_sharding(mesh, 2)),
        norms=jax.device_put(jnp.asarray(norms), shard_rows(mesh)),
        n_items=n,
        mesh=mesh,
    )


def _sharded_topk_fn(mesh, k: int, cosine: bool, quantized: bool = False):
    """shard_map'd scan: each device scores and top-k's its row shard,
    then the tiny [b, k]-per-device candidates all-gather and a final
    top-k merges them — the [b, n] score matrix never materializes
    globally and no full-matrix collective ever runs. Quantized shards
    upcast their int8 slice in-register and dequantize by the sharded
    per-row scale after the dot."""
    from jax.sharding import PartitionSpec as P

    try:
        from jax import shard_map
    except ImportError:  # pragma: no cover - older jax
        from jax.experimental.shard_map import shard_map

    from oryx_tpu.parallel.mesh import DATA_AXIS

    def local(mat, norms, scales, resid, resid_scales, queries, qn, shard_base, n_items_arr):
        # mat: [n_local, k_feat]; shard_base: [1] global row offset;
        # scales/resid/resid_scales: per-row dequant multipliers and the
        # int8 residual plane (norms/mat dummies with the same sharding
        # when not quantized, ignored below). Sharded scans sum both int8
        # planes in full — per-shard candidate gathers aren't worth the
        # collective plumbing, and the shards split the extra GEMM anyway.
        m = mat.astype(jnp.float32) if quantized else mat
        scores = jnp.dot(
            queries, m.T, preferred_element_type=jnp.float32,
            precision=_dot_precision(m.dtype),
        )  # [b, n_local]
        if quantized:
            scores = scores * scales[None, :]
            scores = scores + jnp.dot(
                queries, resid.astype(jnp.float32).T,
                preferred_element_type=jnp.float32,
                precision=_dot_precision(mat.dtype),
            ) * resid_scales[None, :]
        if cosine:
            scores = scores / jnp.maximum(norms[None, :] * qn, 1e-12)
        # mask padding by global row position — NOT by zero norms, which
        # would also drop genuine zero-vector items (cold rows score 0,
        # same as the single-device path)
        gcol = shard_base[0] + jnp.arange(mat.shape[0], dtype=jnp.int32)
        scores = jnp.where(gcol[None, :] < n_items_arr[0], scores, -jnp.inf)
        kk = min(k, mat.shape[0])
        v, i = jax.lax.top_k(scores, kk)
        i = i + shard_base[0]
        # gather every device's candidates and merge: [b, d*kk] is tiny
        v_all = jax.lax.all_gather(v, DATA_AXIS, axis=1, tiled=True)
        i_all = jax.lax.all_gather(i, DATA_AXIS, axis=1, tiled=True)
        vm, pos = jax.lax.top_k(v_all, min(k, v_all.shape[1]))
        im = jnp.take_along_axis(i_all, pos, axis=1)
        return vm, im

    in_specs = (
        P(DATA_AXIS, None),
        P(DATA_AXIS),
        P(DATA_AXIS),  # per-row scales (or the norms dummy)
        P(DATA_AXIS, None),  # residual plane (or the mat dummy)
        P(DATA_AXIS),  # residual scales (or the norms dummy)
        P(),  # queries replicated
        P(),
        P(DATA_AXIS),
        P(),  # n_items replicated
    )
    out_specs = (P(), P())
    # after the all_gather every device computes the same merge, but the
    # replication checker can't infer that through top_k — disable it
    # (kwarg renamed check_rep -> check_vma across jax versions)
    try:
        smapped = shard_map(
            local, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:  # pragma: no cover - older jax
        smapped = shard_map(
            local, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )
    return jax.jit(smapped)


def top_k_sharded(
    up: ShardedItemMatrix, queries: np.ndarray, k: int, cosine: bool = False
) -> tuple[np.ndarray, np.ndarray]:
    """(indices [b, k], scores [b, k]) over the mesh-sharded matrix."""
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    k = max(1, min(int(k), up.n_items))
    qn = np.linalg.norm(q, axis=1, keepdims=True).astype(np.float32)
    d = up.mesh.devices.size
    per = up.mat.shape[0] // d
    shard_base = jnp.arange(d, dtype=jnp.int32) * per
    quantized = up.scales is not None
    fn = _sharded_topk_cache(up.mesh, k, bool(cosine), quantized)
    vals, idxs = fn(
        up.mat,
        up.norms,
        up.scales if quantized else up.norms,
        up.resid if quantized else up.mat,
        up.resid_scales if quantized else up.norms,
        jnp.asarray(q, dtype=jnp.float32 if quantized else up.mat.dtype),
        jnp.asarray(qn),
        shard_base,
        jnp.asarray([up.n_items], dtype=jnp.int32),
    )
    return np.asarray(idxs), np.asarray(vals)


_sharded_fns: dict = {}


def _sharded_topk_cache(mesh, k: int, cosine: bool, quantized: bool = False):
    key = (id(mesh), k, cosine, quantized)
    fn = _sharded_fns.get(key)
    if fn is None:
        fn = _sharded_fns[key] = _sharded_topk_fn(mesh, k, cosine, quantized)
    return fn


# -- incremental updates ------------------------------------------------------


# No donation: in-flight top-k requests may still hold the previous
# handle, and donating would delete their buffers mid-request. The
# device-side copy this costs is HBM-internal (no host transfer — the
# thing incremental refresh exists to avoid) and transient.
@jax.jit
def _scatter_rows_t(mat_t, norms, rows, vals, new_norms):
    """Feature-major scatter: mat_t[:, rows] <- vals.T, norms[0, rows] <- n."""
    mat_t = mat_t.at[:, rows].set(vals.T.astype(mat_t.dtype))
    norms = norms.at[0, rows].set(new_norms)
    return mat_t, norms


@jax.jit
def _scatter_rows(mat, norms, rows, vals, new_norms):
    mat = mat.at[rows].set(vals.astype(mat.dtype))
    norms = norms.at[rows].set(new_norms)
    return mat, norms


@jax.jit
def _scatter_rows_t_q(
    mat_t, norms, scales, resid, resid_scales, rows, q, s, q2, s2, new_norms
):
    """int8 feature-major scatter of pre-quantized rows: codes + residual
    codes + norms + both per-row scales in one call. Quantization happens
    on the HOST (``_quantize_rows``/``_quantize_residual``, the same
    functions upload uses) so a speed-layer fold-in that touches a row
    leaves it bit-identical to a fresh upload of the same values — under
    jit, XLA fuses the requantize arithmetic into FMAs and drifts a few
    ulps from the host result."""
    kf_pad = mat_t.shape[0]

    def pad_t(codes):
        codes = codes.T
        if codes.shape[0] < kf_pad:  # int8 sublane padding on the handle
            codes = jnp.pad(codes, ((0, kf_pad - codes.shape[0]), (0, 0)))
        return codes

    mat_t = mat_t.at[:, rows].set(pad_t(q))
    resid = resid.at[:, rows].set(pad_t(q2))
    norms = norms.at[0, rows].set(new_norms)
    scales = scales.at[0, rows].set(s)
    resid_scales = resid_scales.at[0, rows].set(s2)
    return mat_t, norms, scales, resid, resid_scales


def capacity(uploaded) -> int:
    """Row capacity of the handle (padding included); rows beyond
    ``n_items`` can be appended in place on the streaming layout. For an
    IVF handle it is the built catalog plus the free overlay slots —
    overflow forces a rebuild, which is exactly when the routing table
    should be refreshed anyway."""
    if isinstance(uploaded, IVFIndex):
        return ivf_ops.capacity(uploaded)
    if isinstance(uploaded, StreamingItemMatrix):
        return uploaded.mat_t.shape[1]
    mat, _ = uploaded
    return mat.shape[0]


def update_rows(uploaded, rows: np.ndarray, values: np.ndarray, n_items: int | None = None):
    """Scatter-update `rows` of an uploaded item matrix with `values`
    [len(rows), k] — the incremental-refresh path (SURVEY §7
    'incremental serving state vs immutable device arrays'): a handful
    of dirty vectors ship a few KB host->device instead of the whole
    matrix. For the streaming layout, `n_items` may grow into the padded
    capacity (append of new items without realloc).

    The row-count is bucketed to a power of two (padding repeats the last
    row) so jit retraces O(log n) scatter shapes, not one per batch size.
    """
    if isinstance(uploaded, IVFIndex):
        # IVF fold-ins route through the pending overlay (scanned exactly
        # by every query); IVFOverlayFull propagates so the caller can
        # fall back to a full rebuild
        return ivf_ops.update_rows(uploaded, rows, values, n_items=n_items)
    rows = np.asarray(rows, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float32)
    m = len(rows)
    if m == 0:
        return uploaded
    bucket = 1 << (m - 1).bit_length()
    if bucket != m:
        pad = bucket - m
        rows = np.concatenate([rows, np.repeat(rows[-1:], pad)])
        values = np.concatenate([values, np.repeat(values[-1:], pad, axis=0)])
    new_norms = np.linalg.norm(values, axis=1)
    if isinstance(uploaded, StreamingItemMatrix):
        count = uploaded.n_items if n_items is None else n_items
        if uploaded.scales is not None:
            # quantized handle: touched rows requantize in place — the
            # speed-layer fold-in path never falls back to a full upload
            qr, sr = _quantize_rows(values)
            q2r, s2r = _quantize_residual(values, qr, sr)
            mat_t, norms, scales, resid, resid_scales = _scatter_rows_t_q(
                uploaded.mat_t, uploaded.norms, uploaded.scales,
                uploaded.resid, uploaded.resid_scales, rows,
                qr, sr, q2r, s2r, new_norms,
            )
            return StreamingItemMatrix(
                mat_t=mat_t, norms=norms, n_items=count,
                scales=scales, features=uploaded.features,
                resid=resid, resid_scales=resid_scales,
            )
        mat_t, norms = _scatter_rows_t(
            uploaded.mat_t, uploaded.norms, rows, values, new_norms
        )
        return StreamingItemMatrix(mat_t=mat_t, norms=norms, n_items=count)
    mat, norms = uploaded
    return _scatter_rows(mat, norms, rows, values, new_norms)


@dataclass
class TopNHandle:
    """In-flight async top-k request; ``result()`` blocks and returns
    (indices [b, k], scores [b, k]) as numpy arrays."""

    _vals: jax.Array
    _idxs: jax.Array

    def result(self) -> tuple[np.ndarray, np.ndarray]:
        # scores may travel as bf16 (download_dtype); callers always see f32
        return np.asarray(self._idxs), np.asarray(self._vals).astype(np.float32, copy=False)


@dataclass
class MultiTopNHandle:
    """In-flight fused multi-scan request; ``result()`` returns
    (indices [n, k], scores [n, k]) for the original n queries."""

    _vals: jax.Array  # [K, b, k]
    _idxs: jax.Array
    _n: int

    def result(self) -> tuple[np.ndarray, np.ndarray]:
        k = self._vals.shape[-1]
        idxs = np.asarray(self._idxs).reshape(-1, k)[: self._n]
        vals = (
            np.asarray(self._vals)
            .astype(np.float32, copy=False)  # bf16-on-the-wire -> f32 for callers
            .reshape(-1, k)[: self._n]
        )
        return idxs, vals


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _dot_topk_batch_multi(mat, norms, queries_kb, k, cosine, download_dtype=None):
    """XLA twin of the fused multi-scan: lax.map over query groups keeps
    peak memory at one [b, n] score block instead of [K*b, n]."""

    def one(q):
        return _dot_topk_batch(mat, norms, q, k, cosine)

    vals, idxs = jax.lax.map(one, queries_kb)
    if download_dtype is not None:
        vals = vals.astype(download_dtype)
    return vals, idxs


def _auto_download_dtype(uploaded) -> object | None:
    """Scores of a bf16 item matrix carry ~bf16 information even though
    selection accumulates in f32 — shipping them back over a result-byte-
    bound link as bf16 cuts the per-hit payload from 8 B to 6 B without
    changing the on-device ranking. f32 matrices keep f32 results."""
    mat = uploaded.mat_t if isinstance(uploaded, StreamingItemMatrix) else uploaded[0]
    # int8 scores carry ~0.4% quantization error already — bf16 wire dtype
    # loses nothing that selection kept
    return jnp.bfloat16 if mat.dtype in (jnp.bfloat16, jnp.int8) else None


def _group_pad(arr: np.ndarray, scan_batch: int) -> tuple[np.ndarray, int]:
    """Zero-pad rows to a multiple of the per-scan batch and reshape to
    [groups, b, ...]; returns (grouped, real row count)."""
    n = arr.shape[0]
    b = max(1, min(scan_batch, n))
    groups = (n + b - 1) // b
    if groups * b != n:
        pad = np.zeros((groups * b - n,) + arr.shape[1:], arr.dtype)
        arr = np.concatenate([arr, pad])
    return arr.reshape((groups, b) + arr.shape[1:]), n


def _async_multi_handle(vals, idxs, n: int) -> MultiTopNHandle:
    """Enqueue the device→host copies without blocking and wrap."""
    try:
        vals.copy_to_host_async()
        idxs.copy_to_host_async()
    except AttributeError:  # pragma: no cover - older array types
        pass
    return MultiTopNHandle(vals, idxs, n)


def submit_top_k_multi(
    uploaded,
    queries: np.ndarray,
    k: int,
    cosine: bool = False,
    scan_batch: int = 256,
    nprobe: int | None = None,
) -> MultiTopNHandle:
    """Fused form of submit_top_k: ceil(n / scan_batch) full-matrix scans
    run inside ONE device dispatch (lax.map), so per-dispatch host work
    and device round-trip latency amortize across the whole query group.
    This is what converts a dispatch-bound serving pipeline (~hundreds of
    scans/s regardless of batch size) into a bandwidth/MXU-bound one.
    scan_batch bounds per-scan VMEM ([scan_batch, BLOCK_N] f32 scores)."""
    q = np.atleast_2d(np.asarray(queries, dtype=np.float32))
    if isinstance(uploaded, IVFIndex):
        # the IVF program does its own QUERY_BLOCK grouping (lax.map over
        # groups inside one dispatch), so the whole batch submits at once.
        # `nprobe` overrides the index default per call (overload control's
        # reduced-probe rung); ignored for non-IVF handles below, which
        # have no probe concept.
        vals, ids = ivf_ops.top_k_device(uploaded, q, k, cosine=cosine, nprobe=nprobe)
        return _async_multi_handle(vals[None], ids[None], q.shape[0])
    q_kb, n = _group_pad(q, scan_batch)
    dl = _auto_download_dtype(uploaded)
    if isinstance(uploaded, StreamingItemMatrix):
        vals, idxs = top_k_streaming_device_multi(
            uploaded, jnp.asarray(q_kb), k, cosine=cosine, download_dtype=dl
        )
    else:
        mat, norms = uploaded
        kk = max(1, min(int(k), mat.shape[0]))
        vals, idxs = _dot_topk_batch_multi(
            mat, norms, jnp.asarray(q_kb, dtype=mat.dtype), kk, cosine, dl
        )
    return _async_multi_handle(vals, idxs, n)


def upload_queries(queries: np.ndarray) -> jax.Array:
    """Stage a [m, feat] query-vector matrix on device (float32), for
    index-submitted scans."""
    return jnp.asarray(np.atleast_2d(np.asarray(queries, np.float32)))


def upload_random(
    n_items: int,
    num_features: int,
    dtype=None,
    seed: int = 0,
    streaming: bool | None = None,
):
    """Benchmark helper: a random item matrix generated ON DEVICE, in the
    same handle form as :func:`upload`. A 20M x 250 bf16 matrix is 10 GB;
    generating it device-side means those bytes never cross the
    host<->device link (minutes of tunnel upload in the load-test setups
    of docs/performance.md's 5M/20M-item rows) and never cost host RAM."""
    if streaming is None:
        streaming = _default_streaming()
    dtype = dtype or jnp.float32
    key = jax.random.PRNGKey(seed)
    if _is_int8(dtype):
        # int8 is always the streaming layout: generate f32 feature-major
        # on device, then quantize per column (= per item row) in place
        from oryx_tpu.ops.pallas_topn import _INT8_FEAT_MULTIPLE, BLOCK_N, _ceil_to

        n_pad = max(BLOCK_N, ((n_items + BLOCK_N - 1) // BLOCK_N) * BLOCK_N)
        mat_t, norms = _gen_streaming_random(
            key, num_features, n_pad, n_items, jnp.float32
        )
        mat_q, scales, mat_r, rscales = _quantize_cols_t(mat_t)
        kf_pad = _ceil_to(num_features, _INT8_FEAT_MULTIPLE)
        if kf_pad != num_features:
            mat_q = jnp.pad(mat_q, ((0, kf_pad - num_features), (0, 0)))
            mat_r = jnp.pad(mat_r, ((0, kf_pad - num_features), (0, 0)))
        return StreamingItemMatrix(
            mat_t=mat_q, norms=norms, n_items=n_items, scales=scales,
            features=num_features if kf_pad != num_features else None,
            resid=mat_r, resid_scales=rscales,
        )
    if streaming:
        from oryx_tpu.ops.pallas_topn import BLOCK_N

        n_pad = max(BLOCK_N, ((n_items + BLOCK_N - 1) // BLOCK_N) * BLOCK_N)
        mat_t, norms = _gen_streaming_random(key, num_features, n_pad, n_items, dtype)
        return StreamingItemMatrix(mat_t=mat_t, norms=norms, n_items=n_items)
    mat, norms = _gen_plain_random(key, n_items, num_features, dtype)
    return mat, norms


@functools.partial(jax.jit, donate_argnums=0, static_argnums=3)
def _fill_normal_block(buf, key, start, width):
    blk = jax.random.normal(key, (buf.shape[0], width), dtype=buf.dtype)
    return jax.lax.dynamic_update_slice(buf, blk, (0, start))


@functools.partial(jax.jit, donate_argnums=0, static_argnums=2)
def _mask_and_norms(mat_t, n_items_arr, n_pad):
    mask = (jnp.arange(n_pad) < n_items_arr)[None, :]
    mat_t = jnp.where(mask, mat_t, jnp.zeros((), dtype=mat_t.dtype))
    norms = jnp.sqrt(
        jnp.sum(jnp.square(mat_t.astype(jnp.float32)), axis=0, keepdims=True)
    )
    return mat_t, norms


def _gen_streaming_random(key, num_features, n_pad, n_items, dtype):
    # Chunked fill with buffer donation: generating a [250, 20M] matrix in
    # one call would materialize the RNG bit tensor next to the output
    # (2x peak); 2M-column blocks bound the transient to ~1 GB while the
    # donated buffer stays in place.
    chunk = min(n_pad, 2_000_000)
    buf = jnp.zeros((num_features, n_pad), dtype=dtype)
    starts = list(range(0, n_pad, chunk))
    keys = jax.random.split(key, len(starts))
    for i, start in enumerate(starts):
        # keep the block width static for one compiled fill: clamp the
        # last start back so the block fits (the overlap is re-randomized,
        # which is harmless for benchmark data)
        buf = _fill_normal_block(buf, keys[i], min(start, n_pad - chunk), chunk)
    return _mask_and_norms(buf, jnp.int32(n_items), n_pad)


@jax.jit
def _quantize_cols_t(mat_t):
    """Column-wise (= per item row in the feature-major layout) symmetric
    int8 quantization on device — same absmax/127 rule as the host path,
    so padding columns (all-zero) get scale 1.0 and codes 0. Returns both
    planes (codes + residual codes) and their per-column scales."""

    def requant(v):
        absmax = jnp.max(jnp.abs(v), axis=0, keepdims=True)
        s = jnp.where(absmax > 0, absmax / 127.0, 1.0)
        q = jnp.clip(jnp.round(v / s), -127, 127)
        return q, s

    q, s = requant(mat_t)
    q2, s2 = requant(mat_t - q * s)
    return q.astype(jnp.int8), s, q2.astype(jnp.int8), s2


@functools.partial(jax.jit, static_argnums=(1, 2, 3))
def _gen_plain_random(key, n_items, num_features, dtype):
    mat = jax.random.normal(key, (n_items, num_features), dtype=dtype)
    norms = jnp.linalg.norm(mat.astype(jnp.float32), axis=1)
    return mat, norms


@jax.jit
def _scatter_query_rows(x_dev, rows, vals):
    return x_dev.at[rows].set(vals)


def update_query_rows(x_dev: jax.Array, rows: np.ndarray, values: np.ndarray) -> jax.Array:
    """Scatter-update rows of a staged query matrix (the incremental
    refresh for device-resident X — same idea as update_rows for Y).
    Row counts bucket to powers of two (padding repeats the last row) so
    jit retraces O(log n) scatter shapes, not one per dirty-batch size."""
    rows = np.asarray(rows, dtype=np.int32)
    values = np.ascontiguousarray(values, dtype=np.float32)
    m = len(rows)
    if m == 0:
        return x_dev
    bucket = 1 << (m - 1).bit_length()
    if bucket != m:
        pad = bucket - m
        rows = np.concatenate([rows, np.repeat(rows[-1:], pad)])
        values = np.concatenate([values, np.repeat(values[-1:], pad, axis=0)])
    return _scatter_query_rows(x_dev, jnp.asarray(rows), jnp.asarray(values))


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _indexed_multi_xla(mat, norms, x_dev, idx_kb, k, cosine, download_dtype):
    q_kb = x_dev[idx_kb].astype(mat.dtype)  # [K, b, feat] gathered on device
    return _dot_topk_batch_multi(mat, norms, q_kb, k, cosine, download_dtype)


def submit_top_k_multi_indexed(
    uploaded,
    x_dev: jax.Array,
    indices: np.ndarray,
    k: int,
    cosine: bool = False,
    scan_batch: int = 256,
    nprobe: int | None = None,
) -> MultiTopNHandle:
    """submit_top_k_multi with the query VECTORS already device-resident:
    the host ships only int32 row indices into ``x_dev`` (4 B/query vs
    4*feat B — 50-200x less uplink on a wire-bound link) and the gather
    happens on device inside the same dispatch as the fused scans.

    This is the serving shape where the user-factor matrix X lives on
    device next to Y (e.g. refreshed by the same scatter-update path);
    /recommend then resolves the user id to a row index and never uploads
    a vector at all."""
    idx = np.atleast_1d(np.asarray(indices, dtype=np.int32))
    if isinstance(uploaded, IVFIndex):
        vals, ids = ivf_ops.top_k_device_indexed(
            uploaded, x_dev, idx, k, cosine=cosine, nprobe=nprobe
        )
        return _async_multi_handle(vals[None], ids[None], len(idx))
    idx_kb_np, n = _group_pad(idx, scan_batch)
    idx_kb = jnp.asarray(idx_kb_np)
    dl = _auto_download_dtype(uploaded)
    if isinstance(uploaded, StreamingItemMatrix):
        from oryx_tpu.ops.pallas_topn import top_k_streaming_device_multi_indexed

        vals, idxs = top_k_streaming_device_multi_indexed(
            uploaded, x_dev, idx_kb, k, cosine=cosine, download_dtype=dl
        )
    else:
        mat, norms = uploaded
        kk = max(1, min(int(k), mat.shape[0]))
        vals, idxs = _indexed_multi_xla(mat, norms, x_dev, idx_kb, kk, cosine, dl)
    return _async_multi_handle(vals, idxs, n)


def submit_top_k(
    uploaded, queries: np.ndarray, k: int, cosine: bool = False,
    nprobe: int | None = None,
) -> TopNHandle:
    """Enqueue a batched top-k without waiting: device compute and the
    device→host copy both run asynchronously. Keeping a window of
    handles in flight pipelines transfers behind compute. ``nprobe``
    overrides the IVF index's default probe count per call (the overload
    controller's reduced-probe rung); ignored for non-IVF handles."""
    if isinstance(uploaded, IVFIndex):
        vals, ids = ivf_ops.top_k_device(
            uploaded, np.atleast_2d(queries), k, cosine=cosine, nprobe=nprobe
        )
        try:
            vals.copy_to_host_async()
            ids.copy_to_host_async()
        except AttributeError:  # pragma: no cover - older array types
            pass
        return TopNHandle(vals, ids)
    dl = _auto_download_dtype(uploaded)
    if isinstance(uploaded, StreamingItemMatrix):
        vals, idxs = top_k_streaming_device(
            uploaded, queries, k, cosine=cosine, download_dtype=dl
        )
    else:
        mat, norms = uploaded
        kk = max(1, min(int(k), mat.shape[0]))
        q = jnp.asarray(np.atleast_2d(queries), dtype=mat.dtype)
        vals, idxs = _dot_topk_batch(mat, norms, q, kk, cosine, dl)
    try:
        vals.copy_to_host_async()
        idxs.copy_to_host_async()
    except AttributeError:  # pragma: no cover - older array types
        pass
    return TopNHandle(vals, idxs)
