"""Top-N scoring: one batched matvec + top_k on device.

Replaces the reference's per-request thread-pool scan over LSH partitions
(ALSServingModel.topN / TopNConsumer.java, VectorMath.dot in the hot
loop): dot scores for ALL items are one [n, k] @ [k] matvec on the MXU,
cosine scores normalize by cached row norms, and jax.lax.top_k returns
the best candidates. Queries can also be batched [b, k] for concurrent
requests.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np


def upload(matrix: np.ndarray):
    """Move a packed [n, k] float32 matrix to device, with cached norms."""
    mat = jnp.asarray(matrix, dtype=jnp.float32)
    norms = jnp.linalg.norm(mat, axis=1)
    return mat, norms


@functools.partial(jax.jit, static_argnums=2)
def _dot_topk(mat, query, k):
    scores = mat @ query
    return jax.lax.top_k(scores, k)


@functools.partial(jax.jit, static_argnums=3)
def _cosine_topk(mat, norms, query, k):
    qn = jnp.linalg.norm(query)
    scores = (mat @ query) / jnp.maximum(norms * qn, 1e-12)
    return jax.lax.top_k(scores, k)


def top_k_scores(uploaded, query: np.ndarray, k: int, cosine: bool = False):
    """(indices, scores) of the k best items for one query vector."""
    mat, norms = uploaded
    k = max(1, min(int(k), mat.shape[0]))
    q = jnp.asarray(query, dtype=jnp.float32)
    if cosine:
        s, i = _cosine_topk(mat, norms, q, k)
    else:
        s, i = _dot_topk(mat, q, k)
    return np.asarray(i), np.asarray(s)


@functools.partial(jax.jit, static_argnums=2)
def _dot_topk_batch(mat, queries, k):
    scores = queries @ mat.T  # [b, n]
    return jax.lax.top_k(scores, k)


def top_k_scores_batch(uploaded, queries: np.ndarray, k: int):
    """Batched top-k for [b, k] query vectors (concurrent requests)."""
    mat, _ = uploaded
    k = max(1, min(int(k), mat.shape[0]))
    q = jnp.asarray(queries, dtype=jnp.float32)
    s, i = _dot_topk_batch(mat, q, k)
    return np.asarray(i), np.asarray(s)
