"""Per-replica experiment coordinator: owns the arm router, the online
evaluator, and the evidence-gated promotion loop.

One coordinator lives inside each ServingLayer when ``oryx.serving.ab``
is enabled and a model registry is configured. It is wired to the
layer's :class:`~oryx_tpu.registry.tracking.GenerationTracker` (which
classifies incoming MODEL records into live vs challenger) and runs a
consumer thread over the *input* topic so interaction events join back
to the serves this replica made.

Promotion is coordinated through the registry, not the bus: evidence is
per-replica, and the first replica whose evidence clears the online
gate's bars applies the decision — ``set_champion`` for a promote, an
``online_status = refused`` manifest annotation for a refuse. Every
other replica polls the CHAMPION pointer and the challenger's manifest
on its gate-check interval and adopts the externally-recorded decision,
so a fleet converges without any new record types on the update topic.
"""

from __future__ import annotations

import logging
import threading
import time

from oryx_tpu.common import metrics
from oryx_tpu.experiments.evaluator import ExperimentEvaluator
from oryx_tpu.experiments.routing import (
    ABConfig,
    ARM_CHALLENGER,
    ARM_CHAMPION,
    ArmRouter,
)
from oryx_tpu.registry import manifest as manifest_mod
from oryx_tpu.registry.gate import ChampionGate, OnlineDecision

log = logging.getLogger(__name__)

CONSUME_ERRORS_COUNTER = "serving.experiment.consume-errors"
_POLL_TIMEOUT_S = 0.2


class ExperimentCoordinator:
    def __init__(
        self,
        config,
        store,
        instance_metrics=None,
        clock=time.monotonic,
    ) -> None:
        self.ab = ABConfig.from_config(config)
        self.gate = ChampionGate(config)
        self.store = store
        self.router = ArmRouter(self.ab)
        self.evaluator = ExperimentEvaluator(self.ab)
        self.instance_metrics = instance_metrics
        self._clock = clock
        self._tracker = None
        self._lock = threading.Lock()
        self._decision: OnlineDecision | None = None
        self._last_check = 0.0
        self._consumer = None
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- wiring ----------------------------------------------------------------

    def attach_tracker(self, tracker) -> None:
        self._tracker = tracker

    @property
    def challenger_generation(self) -> str | None:
        return self._tracker.challenger_generation if self._tracker else None

    @property
    def live_generation(self) -> str | None:
        return self._tracker.live_generation if self._tracker else None

    @property
    def active(self) -> bool:
        """True while a challenger is receiving experiment traffic."""
        return self.ab.enabled and self.challenger_generation is not None

    # -- tracker callbacks -----------------------------------------------------

    def wants_challenger(self, generation: str) -> bool:
        """Should a new-generation MODEL record be tracked as the
        challenger? Yes when the registry's CHAMPION pointer names a
        *different* generation — the online gate published this one
        without moving the pointer. A pointer match (rollback republish,
        offline promotion) or a registry without a pointer stays a live
        swap."""
        if not self.ab.enabled or self.store is None:
            return False
        champion = self.store.champion_id()
        return champion is not None and generation != champion

    def on_challenger(self, generation: str | None) -> None:
        """Tracker callback: the challenger id changed."""
        if generation is not None:
            with self._lock:
                self._decision = None
            self.evaluator.reset()
            log.info("experiment started: challenger generation %s", generation)
        self._publish_gauges()

    # -- request path ----------------------------------------------------------

    def assign_request(self, path: str, headers=None):
        """(arm, generation, user) for an attributed request while an
        experiment is active; None otherwise (request proceeds exactly
        as without experiments)."""
        if not self.active:
            return None
        user = self.router.user_of(path, headers)
        if user is None:
            return None
        arm = self.router.assign(user)
        generation = (
            self.challenger_generation if arm == ARM_CHALLENGER else self.live_generation
        )
        return arm, generation, user

    def observe_request(
        self,
        user: str,
        arm: str,
        generation: str | None,
        items,
        latency_s: float | None,
        shed_stage: str | None,
    ) -> None:
        """Record an attributed serve: evaluator join state + per-arm
        instance metrics."""
        self.evaluator.observe_serve(
            user, arm, generation, items, latency_s=latency_s, shed_stage=shed_stage
        )
        im = self.instance_metrics
        if im is None:
            return
        im.counter(f"serving.experiment.requests.{arm}").inc()
        if latency_s is not None:
            im.histogram(f"serving.experiment.request.seconds.{arm}").observe(latency_s)
        if shed_stage:
            im.counter(f"serving.experiment.shed.{arm}.{shed_stage}").inc()

    # -- evaluation loop -------------------------------------------------------

    def start(self, consumer) -> None:
        """Start the input-topic consumer thread (owns `consumer`)."""
        if self._thread is not None:
            raise RuntimeError("experiment coordinator already started")
        self._consumer = consumer
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="oryx-experiment-evaluator", daemon=True
        )
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        consumer = self._consumer
        if consumer is not None:
            try:
                consumer.close()
            except Exception:
                log.debug("experiment consumer close failed", exc_info=True)
        thread = self._thread
        if thread is not None:
            thread.join(timeout=5.0)
        self._thread = None
        self._consumer = None

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                records = self._consumer.poll(max_records=1000, timeout=_POLL_TIMEOUT_S)
                for record in records:
                    self.evaluator.observe_event(record.message)
                self.evaluator.tick()
                now = self._clock()
                if now - self._last_check >= self.gate.online.check_interval_s:
                    self._last_check = now
                    self.check_gate()
            except Exception:
                if self._stop.is_set():
                    return
                metrics.registry.counter(CONSUME_ERRORS_COUNTER).inc()
                log.warning("experiment evaluator loop error", exc_info=True)
                self._stop.wait(_POLL_TIMEOUT_S)

    # -- gate ------------------------------------------------------------------

    def check_gate(self) -> OnlineDecision | None:
        """One gate evaluation: adopt an externally-recorded decision if
        another replica concluded first, else evaluate local evidence
        and apply the outcome. Returns the standing decision."""
        challenger = self.challenger_generation
        if challenger is None or not self.gate.online.enabled:
            self._publish_gauges()
            with self._lock:
                return self._decision
        external = self._external_decision(challenger)
        if external is not None:
            self._conclude(challenger, external, record=False)
            return external
        snap = self.evaluator.snapshot()
        champion_arm = snap["arms"][ARM_CHAMPION]
        challenger_arm = snap["arms"][ARM_CHALLENGER]
        pairs = snap["pairs"]
        decision = self.gate.decide_online(
            champion_samples=champion_arm["resolved"],
            challenger_samples=challenger_arm["resolved"],
            champion_hit_rate=champion_arm["hit_rate"],
            challenger_hit_rate=challenger_arm["hit_rate"],
            challenger_wins=pairs["challenger_wins"],
            champion_wins=pairs["champion_wins"],
        )
        with self._lock:
            self._decision = decision
        if decision.concluded:
            self._conclude(challenger, decision, record=True)
        self._publish_gauges()
        return decision

    def _external_decision(self, challenger: str) -> OnlineDecision | None:
        """A decision another replica already recorded in the registry."""
        try:
            if self.store.champion_id() == challenger:
                return OnlineDecision(
                    verdict="promote", reason="champion pointer moved (peer decision)"
                )
            manifest = self.store.read_manifest(challenger)
        except Exception:
            log.debug("registry poll failed", exc_info=True)
            return None
        if manifest is not None and manifest.online_status == manifest_mod.ONLINE_REFUSED:
            return OnlineDecision(
                verdict="refuse",
                reason=manifest.online_reason or "refused (peer decision)",
            )
        return None

    def _conclude(self, challenger: str, decision: OnlineDecision, record: bool) -> None:
        with self._lock:
            self._decision = decision
        if record:
            self._record_decision(challenger, decision)
        if self._tracker is not None:
            if decision.verdict == "promote":
                self._tracker.promote_challenger()
            else:
                self._tracker.drop_challenger()
        log.info(
            "experiment concluded for generation %s: %s (%s)",
            challenger,
            decision.verdict,
            decision.reason,
        )
        self._publish_gauges()

    def _record_decision(self, challenger: str, decision: OnlineDecision) -> None:
        """First-concluder path: write the decision into the registry so
        the rest of the fleet converges on it."""
        try:
            if decision.verdict == "promote":
                self.store.set_champion(challenger)
            manifest = self.store.read_manifest(challenger)
            if manifest is not None:
                manifest.online_status = (
                    manifest_mod.ONLINE_PROMOTED
                    if decision.verdict == "promote"
                    else manifest_mod.ONLINE_REFUSED
                )
                manifest.online_reason = decision.reason
                manifest.online_samples = {
                    ARM_CHAMPION: decision.champion_samples,
                    ARM_CHALLENGER: decision.challenger_samples,
                }
                manifest.online_lift = decision.lift
                manifest.online_confidence = decision.confidence
                self.store.write_manifest(manifest)
        except Exception:
            log.warning(
                "failed to record online decision for %s", challenger, exc_info=True
            )

    # -- reporting -------------------------------------------------------------

    def _publish_gauges(self) -> None:
        im = self.instance_metrics
        if im is None:
            return
        im.gauge("serving.experiment.active").set(1 if self.active else 0)
        snap = self.evaluator.snapshot()
        for arm in (ARM_CHAMPION, ARM_CHALLENGER):
            stats = snap["arms"][arm]
            im.gauge(f"serving.experiment.resolved.{arm}").set(stats["resolved"])
            if stats["hit_rate"] is not None:
                im.gauge(f"serving.experiment.hit-rate.{arm}").set(stats["hit_rate"])
            if stats["mrr"] is not None:
                im.gauge(f"serving.experiment.mrr.{arm}").set(stats["mrr"])
        pairs = snap["pairs"]
        im.gauge("serving.experiment.pairs").set(
            pairs["challenger_wins"] + pairs["champion_wins"] + pairs["ties"]
        )
        with self._lock:
            decision = self._decision
        if decision is not None:
            if decision.lift is not None:
                im.gauge("serving.experiment.lift").set(decision.lift)
            if decision.confidence is not None:
                im.gauge("serving.experiment.confidence").set(decision.confidence)

    def report(self) -> dict:
        """The serializable ExperimentReport served on GET /experiments
        and by `cli experiments`."""
        with self._lock:
            decision = self._decision
        return {
            "enabled": self.ab.enabled,
            "fraction": self.ab.fraction,
            "active": self.active,
            "champion": self.live_generation,
            "challenger": self.challenger_generation,
            "online_gate": {
                "enabled": self.gate.online.enabled,
                "min_samples": self.gate.online.min_samples,
                "min_lift": self.gate.online.min_lift,
                "max_harm": self.gate.online.max_harm,
                "confidence": self.gate.online.confidence,
            },
            "decision": (
                {
                    "verdict": decision.verdict,
                    "reason": decision.reason,
                    "champion_samples": decision.champion_samples,
                    "challenger_samples": decision.challenger_samples,
                    "lift": decision.lift,
                    "confidence": decision.confidence,
                }
                if decision is not None
                else None
            ),
            "report": self.evaluator.snapshot(),
        }
