"""Interleaved online evaluation: join interaction events back to served
recommendations and accumulate per-arm evidence.

Every attributed serve is recorded as *pending* for its user. A
subsequent interaction event (the ``user,item[,value]`` CSV lines the
speed layer folds in) within ``oryx.serving.ab.join-window-s`` resolves
the oldest pending serve for that user into an *outcome*: the reciprocal
of the interacted item's observed rank in the served list (1.0 for a
top-1 hit), or 0.0 when the item was not in the list. Pending serves
that outlive the window resolve to a 0.0 miss. Outcomes are paired
across arms in resolution order — the i-th resolved champion outcome
against the i-th resolved challenger outcome, Radlinski & Joachims
style — which is what the online gate's sign test consumes.

All methods are thread-safe: serves arrive from request-handler threads
while events arrive from the evaluator's input-topic consumer.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field

from oryx_tpu.experiments.routing import ABConfig, ARM_CHALLENGER, ARM_CHAMPION

#: Hard bound on the per-arm outcome streams used for pairing.
_MAX_OUTCOMES = 100_000
#: Latency samples retained per arm for the report quantiles.
_LATENCY_RESERVOIR = 2048


def parse_event(line: str) -> tuple[str, str] | None:
    """Parse a ``user,item[,value]`` interaction line; None when the
    line is not event-shaped (the input topic also carries free text)."""
    parts = line.strip().split(",")
    if len(parts) < 2:
        return None
    user, item = parts[0].strip(), parts[1].strip()
    if not user or not item:
        return None
    return user, item


def _quantile(sorted_values: list[float], q: float) -> float | None:
    if not sorted_values:
        return None
    idx = min(len(sorted_values) - 1, int(q * len(sorted_values)))
    return sorted_values[idx]


@dataclass
class _PendingServe:
    t: float
    arm: str
    generation: str | None
    items: tuple[str, ...]


@dataclass
class ArmStats:
    """Accumulated per-arm evidence."""

    serves: int = 0
    resolved: int = 0
    hits: int = 0
    rank_reciprocal_sum: float = 0.0
    shed: dict = field(default_factory=dict)
    latencies: deque = field(default_factory=lambda: deque(maxlen=_LATENCY_RESERVOIR))

    @property
    def hit_rate(self) -> float | None:
        return (self.hits / self.resolved) if self.resolved else None

    @property
    def mrr(self) -> float | None:
        """Mean observed-rank reciprocal rank over resolved serves
        (misses contribute 0)."""
        return (self.rank_reciprocal_sum / self.resolved) if self.resolved else None

    def latency_quantiles(self) -> dict:
        values = sorted(self.latencies)
        return {
            "p50_s": _quantile(values, 0.50),
            "p99_s": _quantile(values, 0.99),
            "samples": len(values),
        }

    def to_dict(self) -> dict:
        return {
            "serves": self.serves,
            "resolved": self.resolved,
            "hits": self.hits,
            "hit_rate": self.hit_rate,
            "mrr": self.mrr,
            "latency": self.latency_quantiles(),
            "shed": dict(self.shed),
        }


class ExperimentEvaluator:
    """Joins served recommendations to interaction events and keeps the
    per-arm evidence the online gate decides on."""

    def __init__(self, cfg: ABConfig, clock=time.monotonic) -> None:
        self.cfg = cfg
        self._clock = clock
        self._lock = threading.Lock()
        # user -> deque[_PendingServe], LRU-ordered by last serve
        self._pending_serves: OrderedDict[str, deque] = OrderedDict()
        self.arms: dict[str, ArmStats] = {
            ARM_CHAMPION: ArmStats(),
            ARM_CHALLENGER: ArmStats(),
        }
        self._outcomes: dict[str, list] = {ARM_CHAMPION: [], ARM_CHALLENGER: []}
        self.events_seen = 0
        self.events_joined = 0
        self.started_at = time.time()

    # -- serve side ----------------------------------------------------------

    def observe_serve(
        self,
        user: str,
        arm: str,
        generation: str | None,
        items,
        latency_s: float | None = None,
        shed_stage: str | None = None,
    ) -> None:
        """Record an attributed serve (called from the request path)."""
        now = self._clock()
        with self._lock:
            stats = self.arms[arm]
            stats.serves += 1
            if latency_s is not None:
                stats.latencies.append(latency_s)
            if shed_stage:
                stats.shed[shed_stage] = stats.shed.get(shed_stage, 0) + 1
            if not items:
                # nothing was recommended (non-recommendation endpoint or
                # an error body): per-arm traffic counted above, but there
                # is no serve to join an interaction against
                self._expire_locked(now)
                return
            queue = self._pending_serves.get(user)
            if queue is None:
                queue = deque()
                self._pending_serves[user] = queue
            queue.append(
                _PendingServe(now, arm, generation, tuple(str(i) for i in items or ()))
            )
            self._pending_serves.move_to_end(user)
            self._expire_locked(now)
            while len(self._pending_serves) > self.cfg.max_tracked_users:
                _, evicted = self._pending_serves.popitem(last=False)
                for serve in evicted:
                    self._resolve_locked(serve, outcome=0.0, hit=False)

    # -- event side ----------------------------------------------------------

    def observe_event(self, line: str) -> bool:
        """Consume one input-topic line; True when it joined a serve."""
        parsed = parse_event(line)
        now = self._clock()
        with self._lock:
            self.events_seen += 1
            self._expire_locked(now)
            if parsed is None:
                return False
            user, item = parsed
            queue = self._pending_serves.get(user)
            while queue:
                serve = queue.popleft()
                if now - serve.t > self.cfg.join_window_s:
                    self._resolve_locked(serve, outcome=0.0, hit=False)
                    continue
                self.events_joined += 1
                if item in serve.items:
                    rank = serve.items.index(item) + 1
                    self._resolve_locked(serve, outcome=1.0 / rank, hit=True)
                else:
                    self._resolve_locked(serve, outcome=0.0, hit=False)
                if not queue:
                    self._pending_serves.pop(user, None)
                return True
            self._pending_serves.pop(user, None)
            return False

    def tick(self) -> None:
        """Resolve pending serves whose join window has expired (called
        periodically by the coordinator's consumer loop)."""
        with self._lock:
            self._expire_locked(self._clock())

    # -- internals -----------------------------------------------------------

    def _expire_locked(self, now: float) -> None:
        window = self.cfg.join_window_s
        for user in list(self._pending_serves):
            queue = self._pending_serves[user]
            while queue and now - queue[0].t > window:
                self._resolve_locked(queue.popleft(), outcome=0.0, hit=False)
            if not queue:
                del self._pending_serves[user]

    def _resolve_locked(self, serve: _PendingServe, outcome: float, hit: bool) -> None:
        stats = self.arms[serve.arm]
        stats.resolved += 1
        if hit:
            stats.hits += 1
            stats.rank_reciprocal_sum += outcome
        stream = self._outcomes[serve.arm]
        if len(stream) < _MAX_OUTCOMES:
            stream.append(outcome)

    # -- gate/report side ----------------------------------------------------

    def pair_counts(self) -> tuple[int, int, int]:
        """(challenger-wins, champion-wins, ties) over index-paired
        resolved outcomes."""
        with self._lock:
            champion = self._outcomes[ARM_CHAMPION]
            challenger = self._outcomes[ARM_CHALLENGER]
            n = min(len(champion), len(challenger))
            pos = neg = ties = 0
            for i in range(n):
                if challenger[i] > champion[i]:
                    pos += 1
                elif challenger[i] < champion[i]:
                    neg += 1
                else:
                    ties += 1
            return pos, neg, ties

    def snapshot(self) -> dict:
        """Serializable per-arm evidence (the ExperimentReport body)."""
        with self._lock:
            pending = sum(len(q) for q in self._pending_serves.values())
            arms = {arm: stats.to_dict() for arm, stats in self.arms.items()}
        pos, neg, ties = self.pair_counts()
        return {
            "arms": arms,
            "pairs": {"challenger_wins": pos, "champion_wins": neg, "ties": ties},
            "events_seen": self.events_seen,
            "events_joined": self.events_joined,
            "pending_serves": pending,
            "join_window_s": self.cfg.join_window_s,
            "started_at": self.started_at,
        }

    def reset(self) -> None:
        """Drop all evidence (a new experiment is starting)."""
        with self._lock:
            self._pending_serves.clear()
            self.arms = {ARM_CHAMPION: ArmStats(), ARM_CHALLENGER: ArmStats()}
            self._outcomes = {ARM_CHAMPION: [], ARM_CHALLENGER: []}
            self.events_seen = 0
            self.events_joined = 0
            self.started_at = time.time()
