"""Deterministic champion/challenger arm assignment.

Users are bucketed by a salted blake2b hash of their id mapped into
[0, 1): a user lands in the challenger arm iff their bucket falls below
``oryx.serving.ab.fraction``. The hash is keyed only on (salt, user), so
assignment is sticky for the lifetime of an experiment and identical on
every replica — no coordination, no assignment state.

The module also carries the per-request generation override: the serving
layer wraps challenger-arm dispatch in :func:`serve_generation` and
generation-aware model managers consult :func:`requested_generation`
inside ``get_model()``. This mirrors the ``probe_override`` ContextVar in
``serving/overload.py`` — per-request values thread through dispatch
without widening every signature on the path.
"""

from __future__ import annotations

import hashlib
import re
from contextlib import contextmanager
from contextvars import ContextVar
from dataclasses import dataclass

ARM_CHAMPION = "champion"
ARM_CHALLENGER = "challenger"
#: Response header naming the arm that served the request.
ARM_HEADER = "X-Oryx-Experiment-Arm"

_requested_generation: ContextVar[str | None] = ContextVar(
    "oryx_requested_generation", default=None
)


def requested_generation() -> str | None:
    """The generation the current request should be served from, when an
    experiment routed it to a non-live arm (None otherwise)."""
    return _requested_generation.get()


@contextmanager
def serve_generation(generation_id: str | None):
    """Scope a generation override to the current request."""
    token = _requested_generation.set(generation_id)
    try:
        yield
    finally:
        _requested_generation.reset(token)


_consuming_challenger: ContextVar[str | None] = ContextVar(
    "oryx_consuming_challenger", default=None
)


def consuming_challenger() -> str | None:
    """The generation id the tracker currently classifies as challenger,
    visible while the serving layer feeds an update block to the model
    manager. Generation-aware managers consult this in ``consume()`` to
    retain the challenger's model WITHOUT swapping it in as the default —
    only the arm router (via :func:`serve_generation`) may route requests
    to it. None outside experiment mode, so managers that ignore it keep
    the plain swap-on-arrival behavior."""
    return _consuming_challenger.get()


@contextmanager
def consume_challenger(generation_id: str | None):
    """Scope the tracked challenger id around one block consume."""
    token = _consuming_challenger.set(generation_id)
    try:
        yield
    finally:
        _consuming_challenger.reset(token)


def bucket_of(user: str, salt: str) -> float:
    """Deterministic bucket for `user` in [0, 1). Stable across
    processes and runs (Python's builtin ``hash`` is per-process
    salted, so it is useless here)."""
    digest = hashlib.blake2b(
        f"{salt}:{user}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 2.0**64


@dataclass(frozen=True)
class ABConfig:
    """``oryx.serving.ab`` knob block."""

    fraction: float = 0.0
    salt: str = "oryx-ab"
    user_header: str = "X-Oryx-User"
    user_pattern: str = r"(?:^|/)recommend[A-Za-z]*/([^/]+)"
    join_window_s: float = 300.0
    max_tracked_users: int = 10000

    @classmethod
    def from_config(cls, config) -> "ABConfig":
        block = config.get_config("oryx.serving.ab")
        return cls(
            fraction=block.get_float("fraction"),
            salt=block.get_string("salt"),
            user_header=block.get_string("user-header"),
            user_pattern=block.get_string("user-pattern"),
            join_window_s=block.get_float("join-window-s"),
            max_tracked_users=block.get_int("max-tracked-users"),
        )

    @property
    def enabled(self) -> bool:
        return self.fraction > 0.0


class ArmRouter:
    """Stateless arm assignment: extract the experiment unit (user) from
    a request, hash it into an arm."""

    def __init__(self, cfg: ABConfig) -> None:
        self.cfg = cfg
        self._pattern = re.compile(cfg.user_pattern) if cfg.user_pattern else None
        self._header_key = cfg.user_header.lower()

    def user_of(self, path: str, headers=None) -> str | None:
        """The experiment unit for a request: the user header when
        present, else the first capture of the path pattern, else None
        (unattributed — served by the champion)."""
        if headers:
            for k in headers:
                if k.lower() == self._header_key:
                    value = headers[k]
                    if value:
                        return str(value)
                    break
        if self._pattern is not None:
            m = self._pattern.search(path.split("?", 1)[0])
            if m:
                return m.group(1)
        return None

    def assign(self, user: str) -> str:
        """Sticky arm for `user`."""
        if bucket_of(user, self.cfg.salt) < self.cfg.fraction:
            return ARM_CHALLENGER
        return ARM_CHAMPION
