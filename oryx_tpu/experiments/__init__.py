"""Online experiment observability (docs/experiments.md).

Champion/challenger traffic splitting, interleaved online evaluation of
served recommendations against subsequent interaction events, and the
evidence feed for the online promotion gate (``oryx.ml.gate.online``).

The package is stdlib-only and import-light on purpose: the serving
request path touches it on every request while an experiment is active,
and the tracker imports it at module load.
"""

from oryx_tpu.experiments.routing import (
    ARM_CHALLENGER,
    ARM_CHAMPION,
    ARM_HEADER,
    ABConfig,
    ArmRouter,
    bucket_of,
    requested_generation,
    serve_generation,
)
from oryx_tpu.experiments.evaluator import ArmStats, ExperimentEvaluator
from oryx_tpu.experiments.coordinator import ExperimentCoordinator

__all__ = [
    "ABConfig",
    "ARM_CHALLENGER",
    "ARM_CHAMPION",
    "ARM_HEADER",
    "ArmRouter",
    "ArmStats",
    "ExperimentCoordinator",
    "ExperimentEvaluator",
    "bucket_of",
    "requested_generation",
    "serve_generation",
]
