"""Model registry: generation lineage, champion/challenger gating,
warm-start surfacing, serving rollback, and retention GC.

The reference's model lifecycle ends at MLUpdate's temp->rename promotion
into ``model_dir/<timestampMs>/`` plus a fire-and-forget publish
(MLUpdate.java:192-241). This package is the model-validation/lineage
layer production pipelines put between training and serving (the TFX
Evaluator/Pusher pattern):

- ``manifest``  — one JSON manifest per generation, written atomically
  next to ``model.pmml`` at promotion time (lineage, hyperparams, eval
  metric, record counts, wall time, content hash).
- ``store``     — lists/reads generations locally or remotely over
  ``common/storage`` and maintains the ``CHAMPION`` pointer file
  (atomic rename), plus count-based retention GC.
- ``gate``      — champion/challenger gate: a candidate that regresses
  the champion's eval metric beyond ``oryx.ml.gate.max-regression`` is
  archived but not published.
- ``tracking``  — serving-side live-generation tracking + duplicate
  MODEL suppression (dedupe by generation id).

See docs/model-registry.md for schema, gate semantics, and the rollback
runbook.
"""

from oryx_tpu.registry.gate import ChampionGate, GateDecision
from oryx_tpu.registry.manifest import (
    GENERATION_EXTENSION,
    MANIFEST_FILE_NAME,
    PARENT_EXTENSION,
    GenerationManifest,
)
from oryx_tpu.registry.store import CHAMPION_FILE_NAME, RegistryStore, publish_generation
from oryx_tpu.registry.tracking import GenerationTracker

__all__ = [
    "CHAMPION_FILE_NAME",
    "ChampionGate",
    "GENERATION_EXTENSION",
    "GateDecision",
    "GenerationManifest",
    "GenerationTracker",
    "MANIFEST_FILE_NAME",
    "PARENT_EXTENSION",
    "RegistryStore",
    "publish_generation",
]
