"""Champion/challenger gate: should a new candidate be published?

The MLUpdate evaluation contract is higher-is-better (MLUpdate.java's
evaluate). The gate compares the freshly-trained challenger's eval metric
against the current champion's (read from its manifest) and blocks the
publish when the challenger regresses by more than
``oryx.ml.gate.max-regression`` — an *absolute* tolerance in the metric's
own units (negated RMSE for ALS, silhouette-like score for k-means, ...).
A gated generation is still promoted to the model dir with
``status = "gated"`` in its manifest — archived for forensics, invisible
to serving.

The gate is deliberately permissive on missing evidence: no champion yet,
an unreadable champion manifest, a champion with no recorded metric, or a
NaN challenger metric (test-fraction = 0 trains have nothing to evaluate
against) all publish. Gating on absent data would wedge a pipeline that
never evaluates.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass

from oryx_tpu.common import metrics
from oryx_tpu.common.config import Config
from oryx_tpu.registry.store import RegistryStore

log = logging.getLogger(__name__)

GATED_COUNTER = "ml.gate.gated"
PASSED_COUNTER = "ml.gate.passed"


@dataclass
class GateDecision:
    publish: bool
    reason: str | None = None
    champion_id: str | None = None
    champion_metric: float | None = None
    candidate_metric: float | None = None


class ChampionGate:
    def __init__(self, config: Config) -> None:
        self.max_regression = config.get_optional_float("oryx.ml.gate.max-regression")

    @property
    def enabled(self) -> bool:
        return self.max_regression is not None

    def decide(self, store: RegistryStore, candidate_metric: float | None) -> GateDecision:
        if not self.enabled:
            return GateDecision(publish=True, reason="gate disabled")
        champion_id = store.champion_id()
        if champion_id is None:
            return self._passed(GateDecision(publish=True, reason="no champion yet"))
        manifest = store.read_manifest(champion_id)
        champion_metric = manifest.eval_metric if manifest is not None else None
        if champion_metric is None or math.isnan(champion_metric):
            return self._passed(
                GateDecision(
                    publish=True,
                    reason="champion has no recorded eval metric",
                    champion_id=champion_id,
                )
            )
        if candidate_metric is None or math.isnan(candidate_metric):
            return self._passed(
                GateDecision(
                    publish=True,
                    reason="candidate has no eval metric (nothing to compare)",
                    champion_id=champion_id,
                    champion_metric=champion_metric,
                )
            )
        regression = champion_metric - candidate_metric
        decision = GateDecision(
            publish=regression <= self.max_regression,
            champion_id=champion_id,
            champion_metric=champion_metric,
            candidate_metric=candidate_metric,
        )
        if decision.publish:
            decision.reason = (
                f"candidate {candidate_metric} within {self.max_regression} "
                f"of champion {champion_metric}"
            )
            return self._passed(decision)
        decision.reason = (
            f"candidate {candidate_metric} regresses champion {champion_metric} "
            f"(generation {champion_id}) by {regression}, beyond "
            f"max-regression {self.max_regression}"
        )
        metrics.registry.counter(GATED_COUNTER).inc()
        log.warning("challenger gated: %s", decision.reason)
        return decision

    @staticmethod
    def _passed(decision: GateDecision) -> GateDecision:
        metrics.registry.counter(PASSED_COUNTER).inc()
        return decision
