"""Champion/challenger gate: should a new candidate be published?

The MLUpdate evaluation contract is higher-is-better (MLUpdate.java's
evaluate). The gate compares the freshly-trained challenger's eval metric
against the current champion's (read from its manifest) and blocks the
publish when the challenger regresses by more than
``oryx.ml.gate.max-regression`` — an *absolute* tolerance in the metric's
own units (negated RMSE for ALS, silhouette-like score for k-means, ...).
A gated generation is still promoted to the model dir with
``status = "gated"`` in its manifest — archived for forensics, invisible
to serving.

The gate is deliberately permissive on missing evidence: no champion yet,
an unreadable champion manifest, a champion with no recorded metric, or a
NaN challenger metric (test-fraction = 0 trains have nothing to evaluate
against) all publish. Gating on absent data would wedge a pipeline that
never evaluates.

Online mode (``oryx.ml.gate.online.*``, docs/experiments.md) layers a
second, evidence-gated stage on top: a candidate that passes the offline
gate is published *without* moving the CHAMPION pointer, serving routes a
slice of traffic to it (``oryx.serving.ab``), and
:meth:`ChampionGate.decide_online` promotes or refuses it from the
accumulated per-arm outcomes — a paired one-sided sign test supplies the
confidence.
"""

from __future__ import annotations

import logging
import math
from dataclasses import dataclass

from oryx_tpu.common import metrics
from oryx_tpu.common.config import Config
from oryx_tpu.registry.store import RegistryStore

log = logging.getLogger(__name__)

GATED_COUNTER = "ml.gate.gated"
PASSED_COUNTER = "ml.gate.passed"
ONLINE_PROMOTED_COUNTER = "ml.gate.online.promoted"
ONLINE_REFUSED_COUNTER = "ml.gate.online.refused"


def sign_test_confidence(wins: int, losses: int) -> float:
    """Confidence that the 'wins' side is genuinely better, from a
    one-sided paired sign test: 1 minus the probability of seeing at
    least this many wins out of ``wins + losses`` informative (non-tied)
    pairs under the null of no difference (Binomial(n, 1/2))."""
    n = wins + losses
    if n == 0:
        return 0.0
    tail = sum(math.comb(n, k) for k in range(wins, n + 1)) / 2.0**n
    return 1.0 - tail


@dataclass
class OnlineGateConfig:
    """``oryx.ml.gate.online`` knob block."""

    enabled: bool = False
    min_samples: int = 50
    min_lift: float = 0.0
    max_harm: float = 0.05
    confidence: float = 0.95
    check_interval_s: float = 2.0

    @classmethod
    def from_config(cls, config: Config) -> "OnlineGateConfig":
        block = config.get_config("oryx.ml.gate.online")
        return cls(
            enabled=block.get_bool("enabled"),
            min_samples=block.get_int("min-samples"),
            min_lift=block.get_float("min-lift"),
            max_harm=block.get_float("max-harm"),
            confidence=block.get_float("confidence"),
            check_interval_s=block.get_float("check-interval-s"),
        )


@dataclass
class OnlineDecision:
    """Outcome of one online-gate evaluation."""

    verdict: str  # "promote" | "refuse" | "continue"
    reason: str
    champion_samples: int = 0
    challenger_samples: int = 0
    lift: float | None = None
    confidence: float | None = None

    @property
    def concluded(self) -> bool:
        return self.verdict in ("promote", "refuse")


@dataclass
class GateDecision:
    publish: bool
    reason: str | None = None
    champion_id: str | None = None
    champion_metric: float | None = None
    candidate_metric: float | None = None


class ChampionGate:
    def __init__(self, config: Config) -> None:
        self.max_regression = config.get_optional_float("oryx.ml.gate.max-regression")
        self.online = OnlineGateConfig.from_config(config)

    @property
    def enabled(self) -> bool:
        return self.max_regression is not None

    def decide_online(
        self,
        champion_samples: int,
        challenger_samples: int,
        champion_hit_rate: float | None,
        challenger_hit_rate: float | None,
        challenger_wins: int,
        champion_wins: int,
    ) -> OnlineDecision:
        """Evaluate accumulated online evidence against the promotion
        bars. Sample counts are *resolved* outcomes per arm; wins are
        the informative (non-tied) pairs from index-paired outcomes."""
        cfg = self.online
        if champion_samples < cfg.min_samples or challenger_samples < cfg.min_samples:
            return OnlineDecision(
                verdict="continue",
                reason=(
                    f"insufficient samples (champion {champion_samples}, "
                    f"challenger {challenger_samples}, need {cfg.min_samples} each)"
                ),
                champion_samples=champion_samples,
                challenger_samples=challenger_samples,
            )
        if champion_hit_rate is None or challenger_hit_rate is None:
            return OnlineDecision(
                verdict="continue",
                reason="hit rates not yet defined",
                champion_samples=champion_samples,
                challenger_samples=challenger_samples,
            )
        lift = challenger_hit_rate - champion_hit_rate
        promote_conf = sign_test_confidence(challenger_wins, champion_wins)
        refuse_conf = sign_test_confidence(champion_wins, challenger_wins)
        base = dict(
            champion_samples=champion_samples,
            challenger_samples=challenger_samples,
            lift=lift,
        )
        if lift >= cfg.min_lift and promote_conf >= cfg.confidence:
            decision = OnlineDecision(
                verdict="promote",
                reason=(
                    f"lift {lift:.4f} >= min-lift {cfg.min_lift} at "
                    f"confidence {promote_conf:.4f} >= {cfg.confidence} "
                    f"({challenger_wins}/{champion_wins} informative pairs)"
                ),
                confidence=promote_conf,
                **base,
            )
            metrics.registry.counter(ONLINE_PROMOTED_COUNTER).inc()
            log.info("online gate: %s", decision.reason)
            return decision
        if lift <= -cfg.max_harm and refuse_conf >= cfg.confidence:
            decision = OnlineDecision(
                verdict="refuse",
                reason=(
                    f"harm {-lift:.4f} >= max-harm {cfg.max_harm} at "
                    f"confidence {refuse_conf:.4f} >= {cfg.confidence} "
                    f"({champion_wins}/{challenger_wins} informative pairs)"
                ),
                confidence=refuse_conf,
                **base,
            )
            metrics.registry.counter(ONLINE_REFUSED_COUNTER).inc()
            log.warning("online gate: %s", decision.reason)
            return decision
        return OnlineDecision(
            verdict="continue",
            reason=(
                f"evidence inconclusive (lift {lift:.4f}, promote confidence "
                f"{promote_conf:.4f}, refuse confidence {refuse_conf:.4f})"
            ),
            confidence=max(promote_conf, refuse_conf),
            **base,
        )

    def decide(self, store: RegistryStore, candidate_metric: float | None) -> GateDecision:
        if not self.enabled:
            return GateDecision(publish=True, reason="gate disabled")
        champion_id = store.champion_id()
        if champion_id is None:
            return self._passed(GateDecision(publish=True, reason="no champion yet"))
        manifest = store.read_manifest(champion_id)
        champion_metric = manifest.eval_metric if manifest is not None else None
        if champion_metric is None or math.isnan(champion_metric):
            return self._passed(
                GateDecision(
                    publish=True,
                    reason="champion has no recorded eval metric",
                    champion_id=champion_id,
                )
            )
        if candidate_metric is None or math.isnan(candidate_metric):
            return self._passed(
                GateDecision(
                    publish=True,
                    reason="candidate has no eval metric (nothing to compare)",
                    champion_id=champion_id,
                    champion_metric=champion_metric,
                )
            )
        regression = champion_metric - candidate_metric
        decision = GateDecision(
            publish=regression <= self.max_regression,
            champion_id=champion_id,
            champion_metric=champion_metric,
            candidate_metric=candidate_metric,
        )
        if decision.publish:
            decision.reason = (
                f"candidate {candidate_metric} within {self.max_regression} "
                f"of champion {champion_metric}"
            )
            return self._passed(decision)
        decision.reason = (
            f"candidate {candidate_metric} regresses champion {champion_metric} "
            f"(generation {champion_id}) by {regression}, beyond "
            f"max-regression {self.max_regression}"
        )
        metrics.registry.counter(GATED_COUNTER).inc()
        log.warning("challenger gated: %s", decision.reason)
        return decision

    @staticmethod
    def _passed(decision: GateDecision) -> GateDecision:
        metrics.registry.counter(PASSED_COUNTER).inc()
        return decision
