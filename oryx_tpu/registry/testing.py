"""A PMML-aware probe ServingModelManager for registry tests.

The example app's manager speaks JSON word counts; registry e2e tests
need a manager that resolves MODEL / MODEL-REF messages exactly the way
the real apps do (app_pmml.read_pmml_from_update_message) and then lets
the test ask *which* generation it is serving — including through its
own ``/probe/model`` resource, so HTTP-level assertions exercise the full
router + manager + tracker stack. Configure with

    oryx.serving.model-manager-class =
        "oryx_tpu.registry.testing.PMMLProbeServingModelManager"
    oryx.serving.application-resources = ["oryx_tpu.registry.testing"]

Lives in the package (not tests/) because model-manager-class must be an
importable module path.
"""

from __future__ import annotations

import threading
import time
from typing import Iterator

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.serving.web import OryxServingException, Request, Response, ServingContext, resource


class ScriptedMetricUpdate(MLUpdate):
    """An MLUpdate whose eval metric is scripted by config
    (``oryx.test.scripted-metric``) — the knob registry e2e tests turn to
    push one generation past the champion gate and throw the next into it.
    The train/test split is overridden to a deterministic half/half so
    ``evaluate`` always runs (NaN metrics pass the gate by design)."""

    def __init__(self, config) -> None:
        super().__init__(config)
        self.scripted_metric = config.get_float("oryx.test.scripted-metric")

    def build_model(self, train_data, hyper_parameters, candidate_path):
        root = pmml_io.build_skeleton_pmml()
        pmml_io.sub(
            root,
            "Extension",
            {"name": "scripted-metric", "value": str(self.scripted_metric)},
        )
        return root

    def evaluate(self, model, model_parent_path, test_data, train_data):
        return self.scripted_metric

    def split_new_data_to_train_test(self, new_data):
        half = max(1, len(new_data) // 2)
        return new_data[:half], new_data[half:]


class PMMLProbeModel(ServingModel):
    def __init__(self, generation_id: str | None, extensions: dict[str, str]) -> None:
        self.generation_id = generation_id
        self.extensions = extensions

    def get_fraction_loaded(self) -> float:
        return 1.0


class PMMLProbeServingModelManager(AbstractServingModelManager):
    """Swaps in whatever PMML generation arrives; counts swaps so dedupe
    tests can assert a duplicate MODEL never re-triggered one."""

    def __init__(self, config) -> None:
        super().__init__(config)
        self._lock = threading.Lock()
        self._model: PMMLProbeModel | None = None
        self.model_swaps = 0

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        from oryx_tpu.app import pmml as app_pmml
        from oryx_tpu.common import pmml as pmml_io
        from oryx_tpu.registry.manifest import GENERATION_EXTENSION

        for km in update_iterator:
            if km.key not in ("MODEL", "MODEL-REF"):
                continue
            pmml = app_pmml.read_pmml_from_update_message(km.key, km.message)
            if pmml is None:
                continue
            extensions = {
                e.get("name"): e.get("value")
                for e in pmml_io.findall(pmml, "Extension")
                if e.get("name")
            }
            with self._lock:
                self._model = PMMLProbeModel(
                    extensions.get(GENERATION_EXTENSION), extensions
                )
                self.model_swaps += 1

    def get_model(self) -> PMMLProbeModel | None:
        with self._lock:
            return self._model


@resource("GET", "/probe/model")
def probe_model(ctx: ServingContext, req: Request) -> Response:
    model = ctx.model_manager.get_model() if ctx.model_manager else None
    if model is None:
        raise OryxServingException(503, "model not yet available")
    body = {"generation_id": model.generation_id, "extensions": model.extensions}
    return Response(200, body, content_type="application/json")


@resource("GET", "/probe/recommend/{userID}")
def probe_recommend(ctx: ServingContext, req: Request) -> Response:
    """A /recommend-shaped traffic target for the open-loop fleet harness
    (tools/fleet.py): per-user path (so the generator's power-law user
    skew exercises real routing) answering with the generation that
    served it — the response-level evidence a rotation happened under
    load with zero failures."""
    model = ctx.model_manager.get_model() if ctx.model_manager else None
    if model is None:
        raise OryxServingException(503, "model not yet available")
    # test-only overlay knob: scripted per-request service time, so the
    # overload/autoscale fleet tests can saturate a replica at a known
    # rate (Little's law) deterministically on a single-core host
    work_ms = ctx.config.get_optional_float("oryx.test.probe-work-ms") if ctx.config else None
    if work_ms:
        time.sleep(work_ms / 1000.0)
    body = {"user": req.params["userID"], "generation_id": model.generation_id}
    return Response(200, body, content_type="application/json")
