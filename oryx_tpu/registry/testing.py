"""A PMML-aware probe ServingModelManager for registry tests.

The example app's manager speaks JSON word counts; registry e2e tests
need a manager that resolves MODEL / MODEL-REF messages exactly the way
the real apps do (app_pmml.read_pmml_from_update_message) and then lets
the test ask *which* generation it is serving — including through its
own ``/probe/model`` resource, so HTTP-level assertions exercise the full
router + manager + tracker stack. Configure with

    oryx.serving.model-manager-class =
        "oryx_tpu.registry.testing.PMMLProbeServingModelManager"
    oryx.serving.application-resources = ["oryx_tpu.registry.testing"]

Lives in the package (not tests/) because model-manager-class must be an
importable module path.
"""

from __future__ import annotations

import hashlib
import threading
import time
from typing import Iterator

from oryx_tpu.api.serving import AbstractServingModelManager, ServingModel
from oryx_tpu.bus.core import KeyMessage
from oryx_tpu.common import pmml as pmml_io
from oryx_tpu.experiments import routing as experiments_routing
from oryx_tpu.ml.update import MLUpdate
from oryx_tpu.serving.web import OryxServingException, Request, Response, ServingContext, resource


class ScriptedMetricUpdate(MLUpdate):
    """An MLUpdate whose eval metric is scripted by config
    (``oryx.test.scripted-metric``) — the knob registry e2e tests turn to
    push one generation past the champion gate and throw the next into it.
    The train/test split is overridden to a deterministic half/half so
    ``evaluate`` always runs (NaN metrics pass the gate by design)."""

    def __init__(self, config) -> None:
        super().__init__(config)
        self.scripted_metric = config.get_float("oryx.test.scripted-metric")

    def build_model(self, train_data, hyper_parameters, candidate_path):
        root = pmml_io.build_skeleton_pmml()
        pmml_io.sub(
            root,
            "Extension",
            {"name": "scripted-metric", "value": str(self.scripted_metric)},
        )
        return root

    def evaluate(self, model, model_parent_path, test_data, train_data):
        return self.scripted_metric

    def split_new_data_to_train_test(self, new_data):
        half = max(1, len(new_data) // 2)
        return new_data[:half], new_data[half:]


class PMMLProbeModel(ServingModel):
    def __init__(self, generation_id: str | None, extensions: dict[str, str]) -> None:
        self.generation_id = generation_id
        self.extensions = extensions

    def get_fraction_loaded(self) -> float:
        return 1.0


class PMMLProbeServingModelManager(AbstractServingModelManager):
    """Swaps in whatever PMML generation arrives; counts swaps so dedupe
    tests can assert a duplicate MODEL never re-triggered one.

    Generation-aware: recent generations are retained by id, and
    ``get_model`` honors the per-request override the experiment router
    sets (oryx_tpu/experiments/routing.py), so a challenger-arm request
    is really answered by the challenger generation's model while the
    champion stays live for everyone else."""

    _RETAIN_GENERATIONS = 4

    def __init__(self, config) -> None:
        super().__init__(config)
        self._lock = threading.Lock()
        self._model: PMMLProbeModel | None = None
        self._by_generation: dict[str, PMMLProbeModel] = {}
        self.model_swaps = 0
        self.challenger_loads = 0

    def consume(self, update_iterator: Iterator[KeyMessage]) -> None:
        from oryx_tpu.app import pmml as app_pmml
        from oryx_tpu.common import pmml as pmml_io
        from oryx_tpu.registry.manifest import GENERATION_EXTENSION

        for km in update_iterator:
            if km.key not in ("MODEL", "MODEL-REF"):
                continue
            pmml = app_pmml.read_pmml_from_update_message(km.key, km.message)
            if pmml is None:
                continue
            extensions = {
                e.get("name"): e.get("value")
                for e in pmml_io.findall(pmml, "Extension")
                if e.get("name")
            }
            model = PMMLProbeModel(extensions.get(GENERATION_EXTENSION), extensions)
            challenger = experiments_routing.consuming_challenger()
            with self._lock:
                if model.generation_id is not None:
                    self._by_generation[model.generation_id] = model
                    while len(self._by_generation) > self._RETAIN_GENERATIONS:
                        self._by_generation.pop(next(iter(self._by_generation)))
                if (
                    model.generation_id is not None
                    and model.generation_id == challenger
                ):
                    # an online-gate challenger: loaded and servable via
                    # the per-request override, but the live default stays
                    # the champion until the gate promotes it
                    self.challenger_loads += 1
                else:
                    self._model = model
                    self.model_swaps += 1

    def get_model(self) -> PMMLProbeModel | None:
        requested = experiments_routing.requested_generation()
        with self._lock:
            if requested is not None:
                retained = self._by_generation.get(requested)
                if retained is not None:
                    return retained
            return self._model


@resource("GET", "/probe/model")
def probe_model(ctx: ServingContext, req: Request) -> Response:
    model = ctx.model_manager.get_model() if ctx.model_manager else None
    if model is None:
        raise OryxServingException(503, "model not yet available")
    body = {"generation_id": model.generation_id, "extensions": model.extensions}
    return Response(200, body, content_type="application/json")


@resource("GET", "/probe/recommend/{userID}")
def probe_recommend(ctx: ServingContext, req: Request) -> Response:
    """A /recommend-shaped traffic target for the open-loop fleet harness
    (tools/fleet.py): per-user path (so the generator's power-law user
    skew exercises real routing) answering with the generation that
    served it — the response-level evidence a rotation happened under
    load with zero failures."""
    model = ctx.model_manager.get_model() if ctx.model_manager else None
    if model is None:
        raise OryxServingException(503, "model not yet available")
    # test-only overlay knob: scripted per-request service time, so the
    # overload/autoscale fleet tests can saturate a replica at a known
    # rate (Little's law) deterministically on a single-core host
    work_ms = ctx.config.get_optional_float("oryx.test.probe-work-ms") if ctx.config else None
    if work_ms:
        time.sleep(work_ms / 1000.0)
    user = req.params["userID"]
    body = {
        "user": user,
        "generation_id": model.generation_id,
        # deterministic per-(generation, user) ranked item list: stable
        # across replicas and runs, different across generations — the
        # recommendation surface the experiment evaluator joins
        # interaction events against (docs/experiments.md)
        "items": probe_items(model.generation_id, user),
    }
    return Response(200, body, content_type="application/json")


def probe_items(generation_id: str | None, user: str, n: int = 3) -> list[str]:
    """The ranked items /probe/recommend serves for (generation, user) —
    exported so scripted feedback (loadgen) and tests can recompute the
    exact list without parsing responses."""
    seed = int.from_bytes(
        hashlib.blake2b(
            f"{generation_id}:{user}".encode("utf-8"), digest_size=4
        ).digest(),
        "big",
    )
    return [f"i{(seed + k) % 1000}" for k in range(n)]
