"""Serving-side generation tracking and duplicate-MODEL suppression.

The serving layer replays the update topic from offset 0 and then follows
it live. Every MODEL / MODEL-REF record that flows past carries its
generation identity — a ``generation`` Extension inside inline PMML, the
generation dir name inside a ref — and this tracker watches the stream to
answer "which generation is live right now?" for /healthz, /metrics, and
the ``models``/``health`` CLI probes.

It also makes the stream idempotent per generation: an at-least-once bus
(and the fault+ chaos wrapper deliberately) can deliver the same MODEL
twice, and without suppression the second delivery would re-trigger a
full model swap and skew the staleness clock. A record whose generation
equals the *current* live generation is filtered out of the block before
the model manager sees it. Only the current generation is deduped — a
rollback republish of an *older* generation changes the id and passes
through, which is exactly what rollback needs.

Records without a parseable generation (legacy inline PMML, foreign
paths) pass through untouched and reset tracking to "unknown" — never
dropped, so a registry-less producer keeps working.

With online experiments attached (``oryx.serving.ab``, docs/
experiments.md) the tracker holds TWO generations at once: the live
(champion) one and a challenger. A new-generation MODEL record is
classified against the registry's CHAMPION pointer — it becomes the
challenger when the pointer names a different generation (the online
gate published it without moving the pointer), and a live swap
otherwise (bootstrap, rollback republish, or an offline-promoted
generation). Duplicate suppression covers both ids, and a champion
swap mid-experiment keeps the challenger in place.
"""

from __future__ import annotations

import logging

import numpy as np

from oryx_tpu.common import metrics
from oryx_tpu.common.records import RecordBlock
from oryx_tpu.registry.manifest import GENERATION_EXTENSION
from oryx_tpu.registry.store import generation_id_from_ref

log = logging.getLogger(__name__)

_MODEL_KEYS = (b"MODEL", b"MODEL-REF")
_INDEX_KEY = b"INDEX-REF"

LIVE_GENERATION_GAUGE = "serving.model.live-generation"
CHALLENGER_GENERATION_GAUGE = "serving.model.challenger-generation"
DUPLICATES_COUNTER = "serving.model.duplicates-suppressed"
FLEET_SKEW_GAUGE = "serving.model.generation-skew"
INDEX_GENERATION_GAUGE = "serving.index.generation"


def record_fleet_skew(live_generations) -> int:
    """Generation skew across a fleet of serving replicas: the number of
    *extra* distinct generations live at once (0 = every replica that has
    a model agrees). Replicas that have not yet resolved a generation
    (None) don't count as skew — they are catching up, not disagreeing.
    Published as the ``serving.model.generation-skew`` gauge; the fleet
    driver (tools/fleet.py) polls replica /healthz bodies and records the
    skew each sample, and the rotation-under-load test asserts it returns
    to 0 after a rotation settles."""
    gens = {g for g in live_generations if g is not None}
    skew = max(0, len(gens) - 1)
    metrics.registry.gauge(FLEET_SKEW_GAUGE).set(skew)
    return skew


def generation_of_model_message(key: str, message: str) -> str | None:
    """The generation id a MODEL / MODEL-REF record carries, if any."""
    if key == "MODEL":
        try:
            from oryx_tpu.app import pmml as app_pmml
            from oryx_tpu.common import pmml as pmml_io

            return app_pmml.get_extension_value(
                pmml_io.from_string(message), GENERATION_EXTENSION
            )
        except Exception:
            return None
    if key == "MODEL-REF":
        return generation_id_from_ref(message)
    return None


class GenerationTracker:
    """Tracks the live generation over a stream of update RecordBlocks and
    filters duplicate deliveries of the live generation's MODEL record."""

    def __init__(self, health=None, experiments=None) -> None:
        self.live_generation: str | None = None
        self.challenger_generation: str | None = None
        # ANN index generations (serving/maintain.py) ride the same topic
        # as INDEX-REF records and get the same duplicate suppression —
        # an at-least-once redelivery must not re-trigger an index rebuild
        self.live_index_generation: str | None = None
        self._health = health
        # ExperimentCoordinator (or any object with wants_challenger /
        # on_challenger); None keeps the single-generation behavior
        self._experiments = experiments

    def _set_live(self, generation_id: str | None) -> None:
        self.live_generation = generation_id
        if self._health is not None:
            self._health.live_generation = generation_id
        if generation_id is not None and generation_id.isdigit():
            metrics.registry.gauge(LIVE_GENERATION_GAUGE).set(int(generation_id))

    def _set_challenger(self, generation_id: str | None) -> None:
        self.challenger_generation = generation_id
        if self._health is not None:
            self._health.challenger_generation = generation_id
        if generation_id is not None and generation_id.isdigit():
            metrics.registry.gauge(CHALLENGER_GENERATION_GAUGE).set(int(generation_id))
        if self._experiments is not None:
            self._experiments.on_challenger(generation_id)

    def _set_index(self, generation_id: str | None) -> None:
        self.live_index_generation = generation_id
        if self._health is not None:
            self._health.live_index_generation = generation_id
        if generation_id is not None and generation_id.isdigit():
            metrics.registry.gauge(INDEX_GENERATION_GAUGE).set(int(generation_id))

    def promote_challenger(self) -> None:
        """The online gate promoted the challenger: it becomes the live
        generation for all traffic on this replica."""
        generation = self.challenger_generation
        if generation is None:
            return
        self._set_challenger(None)
        self._set_live(generation)

    def drop_challenger(self) -> None:
        """The online gate refused the challenger: stop routing to it
        (the loaded model stays in the manager, unreferenced)."""
        self._set_challenger(None)

    def filter_block(self, block: RecordBlock | None) -> RecordBlock | None:
        """Apply tracking to one polled block; returns the block with
        duplicate live-generation MODEL records removed (None when nothing
        survives). Blocks without model records return unchanged — the
        no-model fast path is one vectorized key compare."""
        if block is None or len(block) == 0 or block.keys is None:
            return block
        keys = block.keys
        is_model = (keys == _MODEL_KEYS[0]) | (keys == _MODEL_KEYS[1])
        is_index = keys == _INDEX_KEY
        if not bool(is_model.any()) and not bool(is_index.any()):
            return block
        keep = np.ones(len(block), dtype=bool)
        msgs = block.messages
        for i in np.flatnonzero(is_index):
            message = msgs[i].decode("utf-8", "replace")
            generation = generation_id_from_ref(message)
            if generation is not None and generation == self.live_index_generation:
                keep[i] = False
                metrics.registry.counter(DUPLICATES_COUNTER).inc()
                log.info(
                    "suppressed duplicate INDEX-REF for index generation %s", generation
                )
            else:
                self._set_index(generation)
        for i in np.flatnonzero(is_model):
            key = keys[i].decode("utf-8", "replace")
            message = msgs[i].decode("utf-8", "replace")
            generation = generation_of_model_message(key, message)
            if generation is not None and generation == self.live_generation:
                keep[i] = False
                metrics.registry.counter(DUPLICATES_COUNTER).inc()
                log.info("suppressed duplicate %s for live generation %s", key, generation)
            elif generation is not None and generation == self.challenger_generation:
                keep[i] = False
                metrics.registry.counter(DUPLICATES_COUNTER).inc()
                log.info(
                    "suppressed duplicate %s for challenger generation %s", key, generation
                )
            elif (
                self._experiments is not None
                and generation is not None
                and self.live_generation is not None
                and self._experiments.wants_challenger(generation)
            ):
                # record still reaches the manager so the challenger
                # model is loaded and servable behind the arm router
                self._set_challenger(generation)
                log.info("tracking challenger generation %s (%s)", generation, key)
            else:
                self._set_live(generation)
        if bool(keep.all()):
            return block
        if not bool(keep.any()):
            return None
        return RecordBlock(
            keys[keep],
            msgs[keep],
            block.none_keys[keep] if block.none_keys is not None else None,
        )
