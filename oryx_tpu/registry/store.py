"""Registry store: generation listing, CHAMPION pointer, retention GC.

A thin, stateless view over ``oryx.batch.storage.model-dir`` through
``common/storage``, so it works identically on a local filesystem and on
an object store (``gs://...``). Layout::

    model_dir/
      CHAMPION                  <- pointer file: JSON {"generation_id": ...}
      <timestampMs>/            <- one generation
        model.pmml
        manifest.json
        ...side artifacts (X/, Y/, ...)

The CHAMPION pointer is updated by atomic rename (``storage.write_text``
goes through temp+rename locally, temp+mv on object stores) so every
reader sees either the old champion or the new one, never a torn write.
"""

from __future__ import annotations

import json
import logging
import re
import time

from oryx_tpu.common import metrics, storage
from oryx_tpu.registry.manifest import MANIFEST_FILE_NAME, GenerationManifest

log = logging.getLogger(__name__)

CHAMPION_FILE_NAME = "CHAMPION"
MODEL_FILE_NAME = "model.pmml"

_GENERATION_RE = re.compile(r"^\d+$")


def is_generation_id(name: str) -> bool:
    return bool(_GENERATION_RE.match(name))


def generation_id_from_ref(ref: str) -> str | None:
    """Parse the generation id out of a registry-resolvable MODEL-REF
    path (a generation dir, or a file directly under one). None when the
    path does not point into a registry layout."""
    parts = str(ref).rstrip("/").split("/")
    for name in reversed(parts):
        if is_generation_id(name):
            return name
    return None


class RegistryStore:
    """List/read generations and maintain the CHAMPION pointer."""

    def __init__(self, model_dir: str) -> None:
        self.model_dir = str(model_dir).rstrip("/")

    # -- paths ---------------------------------------------------------------

    def generation_dir(self, generation_id: str) -> str:
        return storage.join(self.model_dir, str(generation_id))

    def pmml_uri(self, generation_id: str) -> str:
        return storage.join(self.generation_dir(generation_id), MODEL_FILE_NAME)

    def manifest_uri(self, generation_id: str) -> str:
        return storage.join(self.generation_dir(generation_id), MANIFEST_FILE_NAME)

    # -- listing / manifests -------------------------------------------------

    def list_generations(self) -> list[str]:
        """Generation ids (numeric dir names), oldest first."""
        return sorted(
            (n for n in storage.list_names(self.model_dir) if is_generation_id(n)),
            key=int,
        )

    def read_manifest(self, generation_id: str) -> GenerationManifest | None:
        uri = self.manifest_uri(generation_id)
        try:
            if not storage.exists(uri):
                return None
            return GenerationManifest.from_json(storage.read_text(uri))
        except Exception:
            log.warning("unreadable manifest for generation %s", generation_id, exc_info=True)
            return None

    def write_manifest(self, manifest: GenerationManifest) -> None:
        storage.write_text(self.manifest_uri(manifest.generation_id), manifest.to_json())

    def read_pmml_text(self, generation_id: str) -> str | None:
        uri = self.pmml_uri(generation_id)
        if not storage.exists(uri):
            return None
        return storage.read_text(uri)

    def has_generation(self, generation_id: str) -> bool:
        return storage.exists(self.pmml_uri(generation_id))

    # -- champion pointer ----------------------------------------------------

    def champion_id(self) -> str | None:
        uri = storage.join(self.model_dir, CHAMPION_FILE_NAME)
        try:
            if not storage.exists(uri):
                return None
            data = json.loads(storage.read_text(uri))
            return str(data["generation_id"])
        except Exception:
            log.warning("unreadable CHAMPION pointer under %s", self.model_dir, exc_info=True)
            return None

    def champion_manifest(self) -> GenerationManifest | None:
        champion = self.champion_id()
        return self.read_manifest(champion) if champion else None

    def set_champion(self, generation_id: str, now_ms: int | None = None) -> None:
        """Atomic-rename update of the CHAMPION pointer."""
        storage.write_text(
            storage.join(self.model_dir, CHAMPION_FILE_NAME),
            json.dumps(
                {
                    "generation_id": str(generation_id),
                    "updated_at_ms": int(time.time() * 1000) if now_ms is None else now_ms,
                }
            ),
        )

    # -- retention GC --------------------------------------------------------

    def gc(self, max_generations: int, never_delete: set[str] | None = None) -> list[str]:
        """Keep the newest ``max_generations`` generations plus the
        champion plus every id in ``never_delete`` (the serving layer's
        live generation). Returns the deleted ids. ``max_generations < 0``
        disables."""
        if max_generations < 0:
            return []
        keep: set[str] = set(never_delete or ())
        champion = self.champion_id()
        if champion:
            keep.add(champion)
        gens = self.list_generations()
        newest = gens[len(gens) - max_generations :] if max_generations > 0 else []
        keep.update(newest)
        deleted = []
        for gen in gens:
            if gen in keep:
                continue
            storage.delete(self.generation_dir(gen), recursive=True)
            deleted.append(gen)
            metrics.registry.counter("ml.registry.gc.deleted").inc()
        if deleted:
            log.info(
                "registry GC: deleted %d generation(s) %s (kept %d)",
                len(deleted), deleted, len(gens) - len(deleted),
            )
        return deleted


def publish_generation(
    store: RegistryStore,
    generation_id: str,
    producer,
    max_message_size: int,
    retry_policy=None,
) -> str:
    """(Re)publish an archived generation onto the update topic: inline
    MODEL when the PMML fits the topic's max message size, MODEL-REF to
    the *generation dir* otherwise (the registry-resolvable form — never
    a bare file path). Shared by MLUpdate's publish path and the serving
    layer's rollback endpoint. Returns the key used."""
    pmml_text = store.read_pmml_text(generation_id)
    if pmml_text is None:
        raise FileNotFoundError(f"generation {generation_id} has no {MODEL_FILE_NAME}")
    if len(pmml_text.encode("utf-8")) <= max_message_size:
        key, payload = "MODEL", pmml_text
    else:
        key, payload = "MODEL-REF", store.generation_dir(generation_id)
    if retry_policy is not None:
        retry_policy.call(
            lambda: producer.send(key, payload),
            retry_on=(ConnectionError, OSError),
            metrics_prefix="batch.publish",
        )
    else:
        producer.send(key, payload)
    return key
