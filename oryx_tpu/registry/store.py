"""Registry store: generation listing, CHAMPION pointer, retention GC.

A thin, stateless view over ``oryx.batch.storage.model-dir`` through
``common/storage``, so it works identically on a local filesystem and on
an object store (``gs://...``). Layout::

    model_dir/
      CHAMPION                  <- pointer file: JSON {"generation_id": ...}
      <timestampMs>/            <- one generation
        model.pmml
        manifest.json
        ...side artifacts (X/, Y/, ...)

The CHAMPION pointer is updated by atomic rename (``storage.write_text``
goes through temp+rename locally, temp+mv on object stores) so every
reader sees either the old champion or the new one, never a torn write.
"""

from __future__ import annotations

import json
import logging
import os
import re
import time

from oryx_tpu.common import metrics, storage
from oryx_tpu.common.crashpoints import crashpoint
from oryx_tpu.registry.manifest import MANIFEST_FILE_NAME, GenerationManifest

log = logging.getLogger(__name__)

CHAMPION_FILE_NAME = "CHAMPION"
MODEL_FILE_NAME = "model.pmml"

_GENERATION_RE = re.compile(r"^\d+$")


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:  # pragma: no cover - pid exists, other user
        return True
    return True


def is_generation_id(name: str) -> bool:
    return bool(_GENERATION_RE.match(name))


def generation_id_from_ref(ref: str) -> str | None:
    """Parse the generation id out of a registry-resolvable MODEL-REF
    path (a generation dir, or a file directly under one). None when the
    path does not point into a registry layout."""
    parts = str(ref).rstrip("/").split("/")
    for name in reversed(parts):
        if is_generation_id(name):
            return name
    return None


class RegistryStore:
    """List/read generations and maintain the CHAMPION pointer."""

    def __init__(self, model_dir: str) -> None:
        self.model_dir = str(model_dir).rstrip("/")

    # -- paths ---------------------------------------------------------------

    def generation_dir(self, generation_id: str) -> str:
        return storage.join(self.model_dir, str(generation_id))

    def pmml_uri(self, generation_id: str) -> str:
        return storage.join(self.generation_dir(generation_id), MODEL_FILE_NAME)

    def manifest_uri(self, generation_id: str) -> str:
        return storage.join(self.generation_dir(generation_id), MANIFEST_FILE_NAME)

    # -- listing / manifests -------------------------------------------------

    def list_generations(self) -> list[str]:
        """Generation ids (numeric dir names), oldest first."""
        return sorted(
            (n for n in storage.list_names(self.model_dir) if is_generation_id(n)),
            key=int,
        )

    def read_manifest(self, generation_id: str) -> GenerationManifest | None:
        uri = self.manifest_uri(generation_id)
        try:
            if not storage.exists(uri):
                return None
            return GenerationManifest.from_json(storage.read_text(uri))
        except Exception:
            log.warning("unreadable manifest for generation %s", generation_id, exc_info=True)
            return None

    def write_manifest(self, manifest: GenerationManifest) -> None:
        storage.write_text(self.manifest_uri(manifest.generation_id), manifest.to_json())

    def read_pmml_text(self, generation_id: str) -> str | None:
        uri = self.pmml_uri(generation_id)
        if not storage.exists(uri):
            return None
        return storage.read_text(uri)

    def has_generation(self, generation_id: str) -> bool:
        return storage.exists(self.pmml_uri(generation_id))

    # -- champion pointer ----------------------------------------------------

    def champion_id(self) -> str | None:
        uri = storage.join(self.model_dir, CHAMPION_FILE_NAME)
        try:
            if not storage.exists(uri):
                return None
            data = json.loads(storage.read_text(uri))
            return str(data["generation_id"])
        except Exception:
            log.warning("unreadable CHAMPION pointer under %s", self.model_dir, exc_info=True)
            return None

    def champion_manifest(self) -> GenerationManifest | None:
        champion = self.champion_id()
        return self.read_manifest(champion) if champion else None

    def set_champion(self, generation_id: str, now_ms: int | None = None) -> None:
        """Atomic-rename update of the CHAMPION pointer."""
        crashpoint("registry.champion.pre")
        storage.write_text(
            storage.join(self.model_dir, CHAMPION_FILE_NAME),
            json.dumps(
                {
                    "generation_id": str(generation_id),
                    "updated_at_ms": int(time.time() * 1000) if now_ms is None else now_ms,
                }
            ),
        )

    # -- fsck / repair -------------------------------------------------------

    def fsck(self, repair: bool = False) -> dict:
        """Startup/operator audit of the registry layout: stale commit
        temp litter, a CHAMPION pointer that doesn't parse or points at
        a generation with no model.pmml, half-written generation dirs
        (promoted but never given their model.pmml), and manifests that
        no longer parse. Repair is recover-or-refuse, never silently
        wrong: damaged files are quarantined aside (forensics, not
        deletion) and the pointer falls back to the newest *intact*
        generation — consumers re-resolve, nothing serves a torn model.

        Must not run concurrently with an in-flight promote of the same
        store (a generation mid-upload looks half-written); MLUpdate runs
        it before promoting, the CLI runs it with the batch layer down.
        Returns a count report; repairs also land on registry.repair.*
        counters."""
        report = {
            "tmp-swept": 0, "champion-quarantined": 0, "champion-reset": 0,
            "generations-quarantined": 0, "manifests-quarantined": 0,
        }
        local = not storage.is_remote(self.model_dir)
        if local:
            report["tmp-swept"] += storage.sweep_tmp(self.model_dir)
            report["tmp-swept"] += self._sweep_promote_litter()
        intact: list[str] = []
        for gen in self.list_generations():
            gen_dir = self.generation_dir(gen)
            if local:
                report["tmp-swept"] += storage.sweep_tmp(gen_dir)
            manifest_uri = self.manifest_uri(gen)
            if storage.exists(manifest_uri):
                try:
                    GenerationManifest.from_json(storage.read_text(manifest_uri))
                except Exception:
                    report["manifests-quarantined"] += 1
                    if repair and local:
                        self._quarantine(storage.local_path(manifest_uri))
                        metrics.registry.counter(
                            "registry.repair.manifest-quarantined"
                        ).inc()
            if self.has_generation(gen):
                intact.append(gen)
                continue
            # a generation dir without model.pmml is a promote that died
            # mid-copy: nothing can ever serve it
            report["generations-quarantined"] += 1
            if repair and local:
                self._quarantine(storage.local_path(gen_dir))
                metrics.registry.counter("registry.repair.generation-quarantined").inc()
                log.warning(
                    "registry repair: quarantined half-written generation %s", gen
                )
        report.update(self._fsck_champion(repair, intact))
        return report

    def _fsck_champion(self, repair: bool, intact: list[str]) -> dict:
        report = {"champion-quarantined": 0, "champion-reset": 0}
        uri = storage.join(self.model_dir, CHAMPION_FILE_NAME)
        if not storage.exists(uri):
            return report
        champion: str | None = None
        try:
            champion = str(json.loads(storage.read_text(uri))["generation_id"])
        except Exception:
            report["champion-quarantined"] = 1
            if repair:
                if storage.is_remote(self.model_dir):
                    storage.delete(uri)
                else:
                    self._quarantine(storage.local_path(uri))
                metrics.registry.counter("registry.repair.champion-quarantined").inc()
                log.warning(
                    "registry repair: quarantined unreadable CHAMPION under %s",
                    self.model_dir,
                )
        if champion is not None and champion not in intact:
            # pointer at a missing/half-written generation: fall back to
            # the newest intact one (lineage stays within published gens)
            report["champion-reset"] = 1
            if repair:
                if intact:
                    self.set_champion(intact[-1])
                else:
                    storage.delete(uri)
                metrics.registry.counter("registry.repair.champion-reset").inc()
                log.warning(
                    "registry repair: CHAMPION pointed at unusable generation "
                    "%s; reset to %s", champion, intact[-1] if intact else "(none)",
                )
        return report

    def _sweep_promote_litter(self) -> int:
        """Remove ``.promote-<gen>-<pid>`` staging dirs whose promoter is
        dead (MLUpdate stages a candidate there before its atomic rename
        into the generation slot; a kill mid-copy strands the dir)."""
        import shutil

        root = storage.local_path(self.model_dir)
        if not root.is_dir():
            return 0
        removed = 0
        for p in root.iterdir():
            if not (p.is_dir() and p.name.startswith(".promote-")):
                continue
            try:
                pid = int(p.name.rsplit("-", 1)[1])
            except (IndexError, ValueError):
                continue
            if pid == os.getpid() or _pid_alive(pid):
                continue
            shutil.rmtree(p, ignore_errors=True)
            removed += 1
            log.warning("registry repair: swept dead promote staging dir %s", p)
        return removed

    @staticmethod
    def _quarantine(path) -> None:
        aside = path.with_name(f".quarantine-{path.name}-{os.getpid()}")
        try:
            os.replace(path, aside)
        except OSError:
            log.warning("registry repair: could not quarantine %s", path, exc_info=True)
            return
        # durable quarantine: a crash right after fsck must not resurrect
        # the corrupt file the repair just moved aside
        storage.fsync_dir(path.parent)

    # -- retention GC --------------------------------------------------------

    def gc(self, max_generations: int, never_delete: set[str] | None = None) -> list[str]:
        """Keep the newest ``max_generations`` generations plus the
        champion plus every id in ``never_delete`` (the serving layer's
        live generation). Returns the deleted ids. ``max_generations < 0``
        disables."""
        if max_generations < 0:
            return []
        keep: set[str] = set(never_delete or ())
        champion = self.champion_id()
        if champion:
            keep.add(champion)
        gens = self.list_generations()
        newest = gens[len(gens) - max_generations :] if max_generations > 0 else []
        keep.update(newest)
        deleted = []
        for gen in gens:
            if gen in keep:
                continue
            storage.delete(self.generation_dir(gen), recursive=True)
            deleted.append(gen)
            metrics.registry.counter("ml.registry.gc.deleted").inc()
        if deleted:
            log.info(
                "registry GC: deleted %d generation(s) %s (kept %d)",
                len(deleted), deleted, len(gens) - len(deleted),
            )
        return deleted


def publish_generation(
    store: RegistryStore,
    generation_id: str,
    producer,
    max_message_size: int,
    retry_policy=None,
) -> str:
    """(Re)publish an archived generation onto the update topic: inline
    MODEL when the PMML fits the topic's max message size, MODEL-REF to
    the *generation dir* otherwise (the registry-resolvable form — never
    a bare file path). Shared by MLUpdate's publish path and the serving
    layer's rollback endpoint. Returns the key used."""
    pmml_text = store.read_pmml_text(generation_id)
    if pmml_text is None:
        raise FileNotFoundError(f"generation {generation_id} has no {MODEL_FILE_NAME}")
    if len(pmml_text.encode("utf-8")) <= max_message_size:
        key, payload = "MODEL", pmml_text
    else:
        key, payload = "MODEL-REF", store.generation_dir(generation_id)
    crashpoint("registry.publish.pre")
    if retry_policy is not None:
        retry_policy.call(
            lambda: producer.send(key, payload),
            retry_on=(ConnectionError, OSError),
            metrics_prefix="batch.publish",
        )
    else:
        producer.send(key, payload)
    crashpoint("registry.publish.post")
    return key
