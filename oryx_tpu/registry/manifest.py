"""Per-generation manifest: the lineage record written at promotion.

One ``manifest.json`` lives next to each generation's ``model.pmml``.
It is the registry's source of truth for what a generation is (parent,
hyperparams, eval metric, record counts, wall time, content hash) and
what happened to it (published vs gated). The file is written atomically
(``common/storage`` temp+rename semantics) so a reader never observes a
half-written manifest, and the PMML document itself carries the
generation / parent ids as Extensions so an inline MODEL message is
self-describing on the update topic.
"""

from __future__ import annotations

import hashlib
import json
import math
from dataclasses import asdict, dataclass, field

MANIFEST_FILE_NAME = "manifest.json"

# PMML Extension names stamped on every promoted model, so MODEL messages
# (inline PMML) carry their generation identity on the wire
GENERATION_EXTENSION = "generation"
PARENT_EXTENSION = "parent-generation"

STATUS_PUBLISHED = "published"
STATUS_GATED = "gated"

# online-experiment lifecycle of a published generation
# (oryx.ml.gate.online, docs/experiments.md)
ONLINE_PENDING = "pending"  # serving as challenger, accumulating evidence
ONLINE_PROMOTED = "promoted"  # online gate moved the CHAMPION pointer here
ONLINE_REFUSED = "refused"  # online gate dropped it from routing


@dataclass
class GenerationManifest:
    """Everything the registry records about one generation."""

    generation_id: str
    parent_id: str | None = None
    status: str = STATUS_PUBLISHED
    hyperparams: list = field(default_factory=list)
    eval_metric: float | None = None
    # name of the metric's scale; always higher-is-better per the MLUpdate
    # evaluate contract, apps may negate (RMSE) or not (AUC/accuracy)
    train_count: int | None = None
    test_count: int | None = None
    wall_time_sec: float | None = None
    content_hash: str | None = None
    created_at_ms: int | None = None
    gate_reason: str | None = None
    # online-gate lineage: null for generations promoted offline,
    # pending/promoted/refused for evidence-gated ones, plus the
    # decision evidence the gate acted on
    online_status: str | None = None
    online_reason: str | None = None
    online_samples: dict | None = None
    online_lift: float | None = None
    online_confidence: float | None = None

    def to_json(self) -> str:
        d = asdict(self)
        # NaN is not JSON; an unevaluated sole candidate records null
        if d["eval_metric"] is not None and math.isnan(d["eval_metric"]):
            d["eval_metric"] = None
        return json.dumps(d, indent=2, sort_keys=True)

    @staticmethod
    def from_json(text: str) -> "GenerationManifest":
        d = json.loads(text)
        known = {f for f in GenerationManifest.__dataclass_fields__}
        return GenerationManifest(**{k: v for k, v in d.items() if k in known})

    @property
    def published(self) -> bool:
        return self.status == STATUS_PUBLISHED


def content_hash_of(data: bytes) -> str:
    """sha256 of the model document — the manifest's integrity anchor."""
    return "sha256:" + hashlib.sha256(data).hexdigest()
