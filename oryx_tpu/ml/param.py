"""Hyperparameter value-range algebra and grid construction.

Rebuild of framework/oryx-ml/.../param/ (HyperParams.java:32-195,
ContinuousRange/DiscreteRange/ContinuousAround/DiscreteAround/Unordered):
a range yields `num` trial values (evenly spaced; discrete ranges
enumerate when dense enough; "around" values step symmetrically about a
center), the full cross-product of per-param trials is built, and a
random subset is drawn when the grid exceeds the requested candidates.
"""

from __future__ import annotations

import abc
from typing import Any, Sequence

from oryx_tpu.common import rng
from oryx_tpu.common.config import Config

MAX_COMBOS = 65536


class HyperParamValues(abc.ABC):
    @abc.abstractmethod
    def get_trial_values(self, num: int) -> list:
        """`num` representative values across this range."""

    def sample(self, gen) -> Any:
        """One random draw from the range (random-search strategy).
        Ranges override with true uniform draws; discrete/neighborhood
        types draw among their trial values."""
        vals = self.get_trial_values(9)
        return vals[int(gen.integers(len(vals)))]


class _ContinuousRange(HyperParamValues):
    def __init__(self, lo: float, hi: float) -> None:
        if lo > hi:
            raise ValueError(f"min {lo} > max {hi}")
        self.lo, self.hi = float(lo), float(hi)

    def get_trial_values(self, num: int) -> list:
        assert num > 0
        if self.hi == self.lo:
            return [self.lo]
        if num == 1:
            return [(self.hi + self.lo) / 2.0]
        step = (self.hi - self.lo) / (num - 1)
        vals = [self.lo + i * step for i in range(num)]
        vals[-1] = self.hi
        return vals

    def sample(self, gen) -> float:
        return float(gen.uniform(self.lo, self.hi))


class _DiscreteRange(HyperParamValues):
    def __init__(self, lo: int, hi: int) -> None:
        if lo > hi:
            raise ValueError(f"min {lo} > max {hi}")
        self.lo, self.hi = int(lo), int(hi)

    def sample(self, gen) -> int:
        return int(gen.integers(self.lo, self.hi + 1))

    def get_trial_values(self, num: int) -> list:
        assert num > 0
        if self.hi == self.lo:
            return [self.lo]
        if num == 1:
            return [(self.hi + self.lo) // 2]
        if num == 2:
            return [self.lo, self.hi]
        if num > self.hi - self.lo:
            return list(range(self.lo, self.hi + 1))
        step = (self.hi - self.lo) / (num - 1)
        vals = [self.lo]
        for i in range(1, num - 1):
            vals.append(round(vals[i - 1] + step))
        vals.append(self.hi)
        return vals


class _ContinuousAround(HyperParamValues):
    def __init__(self, center: float, step: float) -> None:
        if step <= 0:
            raise ValueError("step must be positive")
        self.center, self.step = float(center), float(step)

    def get_trial_values(self, num: int) -> list:
        assert num > 0
        if num == 1:
            return [self.center]
        start = self.center - ((num - 1) / 2.0) * self.step
        vals = [start + i * self.step for i in range(num)]
        if num % 2 != 0:
            vals[num // 2] = self.center  # keep middle value exact
        return vals


class _DiscreteAround(HyperParamValues):
    def __init__(self, center: int, step: int) -> None:
        if step <= 0:
            raise ValueError("step must be positive")
        self.center, self.step = int(center), int(step)

    def get_trial_values(self, num: int) -> list:
        assert num > 0
        if num == 1:
            return [self.center]
        start = self.center - ((num - 1) * self.step // 2)
        return [start + i * self.step for i in range(num)]


class _Unordered(HyperParamValues):
    def __init__(self, values: Sequence[Any]) -> None:
        if not values:
            raise ValueError("no values")
        self.values = list(values)

    def get_trial_values(self, num: int) -> list:
        assert num > 0
        return self.values[:num] if num < len(self.values) else list(self.values)

    def sample(self, gen) -> Any:
        # over ALL values: the base default draws from get_trial_values(9),
        # which for unordered is a deterministic prefix — values past the
        # 9th would never be sampled
        return self.values[int(gen.integers(len(self.values)))]


def fixed(value: Any) -> HyperParamValues:
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        return _Unordered([value])
    if isinstance(value, int):
        return _DiscreteRange(value, value)
    return _ContinuousRange(value, value)


def range_param(lo, hi) -> HyperParamValues:
    if isinstance(lo, int) and isinstance(hi, int):
        return _DiscreteRange(lo, hi)
    return _ContinuousRange(lo, hi)


def around(center, step) -> HyperParamValues:
    if isinstance(center, int) and isinstance(step, int):
        return _DiscreteAround(center, step)
    return _ContinuousAround(center, step)


def unordered(values: Sequence[Any]) -> HyperParamValues:
    return _Unordered(values)


def from_config(config: Config, key: str) -> HyperParamValues:
    """Config value -> range (HyperParams.fromConfig:74-109 semantics):
    scalar int/float -> fixed; 2-element numeric list -> range; any other
    list -> unordered; other scalar -> unordered singleton."""
    v = config.get(key)
    if isinstance(v, list):
        if len(v) >= 2:
            if all(isinstance(x, int) and not isinstance(x, bool) for x in v[:2]):
                return _DiscreteRange(v[0], v[1])
            if all(isinstance(x, (int, float)) and not isinstance(x, bool) for x in v[:2]):
                return _ContinuousRange(v[0], v[1])
        return _Unordered([str(x) for x in v])
    if isinstance(v, bool) or v is None:
        return _Unordered([v])
    if isinstance(v, (int, float)):
        return fixed(v)
    s = str(v)
    try:
        return fixed(int(s))
    except ValueError:
        pass
    try:
        return fixed(float(s))
    except ValueError:
        pass
    return _Unordered([s])


def choose_values_per_hyper_param(num_params: int, candidates: int) -> int:
    """Smallest v with v**num_params >= candidates (HyperParams.java:179-193)."""
    if num_params < 1:
        return 0
    v = 0
    while True:
        v += 1
        if v**num_params >= candidates:
            return v


def choose_hyper_parameter_combos(
    ranges: Sequence[HyperParamValues], how_many: int, per_param: int
) -> list[list]:
    """Cross-product of per-param trial values, randomly subsampled to
    `how_many` and shuffled (HyperParams.chooseHyperParameterCombos:122-171).
    """
    if how_many <= 0:
        raise ValueError("how_many must be positive")
    if per_param < 0:
        raise ValueError("per_param must be >= 0")
    num_params = len(ranges)
    if num_params == 0 or per_param == 0:
        return [[]]
    if per_param**num_params > MAX_COMBOS:
        raise ValueError(f"{per_param}^{num_params} exceeds {MAX_COMBOS} combos")

    param_values = [r.get_trial_values(per_param) for r in ranges]
    total = 1
    for vals in param_values:
        total *= len(vals)

    combos: list[list] = []
    for combo in range(total):
        combination = []
        idx = combo
        for vals in param_values:
            combination.append(vals[idx % len(vals)])
            idx //= len(vals)
        combos.append(combination)

    gen = rng.get_random()
    if how_many >= total:
        gen.shuffle(combos)
        return combos
    picked = gen.permutation(total)[:how_many]
    return [combos[i] for i in picked]


def sample_hyper_parameter_combos(
    ranges: Sequence[HyperParamValues], how_many: int
) -> list[list]:
    """Random-search combos (oryx.ml.eval.hyperparam-search = "random"):
    each candidate draws every hyperparameter independently — continuous
    ranges uniformly over [lo, hi] rather than from a fixed grid, which
    dominates grid search when only a few of many dimensions matter
    (Bergstra & Bengio 2012). Duplicates are retried so small discrete
    spaces still yield distinct candidates when possible."""
    if how_many <= 0:
        raise ValueError("how_many must be positive")
    if len(ranges) == 0:
        return [[]]
    gen = rng.get_random()
    combos: list[list] = []
    seen: set = set()
    attempts = 0
    while len(combos) < how_many and attempts < how_many * 20:
        attempts += 1
        combo = [r.sample(gen) for r in ranges]
        key = tuple(combo)
        if key in seen:
            continue
        seen.add(key)
        combos.append(combo)
    return combos if combos else [[]]
