"""ML tier: the batch-training harness with hyperparameter search.

Rebuild of framework/oryx-ml (SURVEY.md §2.6): MLUpdate runs train/test
splits, builds candidate models across a hyperparameter grid (in
parallel), evaluates each, promotes the best into the versioned model
directory, and publishes it over the update topic as MODEL or MODEL-REF.
"""

from oryx_tpu.ml.param import (  # noqa: F401
    HyperParamValues,
    fixed,
    range_param,
    around,
    unordered,
    from_config,
    choose_hyper_parameter_combos,
    choose_values_per_hyper_param,
)
from oryx_tpu.ml.update import MLUpdate  # noqa: F401
