"""MLUpdate: the batch-training harness.

Rebuild of framework/oryx-ml/.../MLUpdate.java:59-373. Per generation:

1. split new data into train/test (random by default; apps may override
   with e.g. a time-ordered split — MLUpdate.java:338-372),
2. enumerate hyperparameter combos (param.py),
3. build + evaluate one candidate per combo, in parallel
   (findBestCandidatePath, MLUpdate.java:251-288) — each candidate trains
   via the abstract `build_model` and persists `model.pmml` under a
   temporary candidates dir,
4. promote the best candidate dir to `model_dir/<timestampMs>/`
   (temp→rename, MLUpdate.java:192-210),
5. publish ("MODEL", <pmml xml>) inline when it fits the update topic's
   max-size, else ("MODEL-REF", <generation dir>) (MLUpdate.java:212-241),
6. call `publish_additional_model_data` (ALS streams its factor matrices
   here, ALSUpdate.java:194-230).

Registry integration (oryx_tpu/registry/): each promoted generation gets
its id + parent stamped into the PMML as Extensions and a manifest.json
written next to model.pmml; a champion/challenger gate can archive a
regressed candidate instead of publishing it; the champion's model is
surfaced to `build_model` for warm-starting; and count-based retention GC
trims old generations after each successful run.
"""

from __future__ import annotations

import abc
import contextlib
import logging
import math
import os
import shutil
import tempfile
import time
from pathlib import Path
from typing import Iterable, Sequence
from xml.etree.ElementTree import Element

from oryx_tpu.api.batch import BatchLayerUpdate
from oryx_tpu.bus.core import KeyMessage, TopicProducer
from oryx_tpu.common import pmml as pmml_io, rng, storage, tracing
from oryx_tpu.common.config import Config
from oryx_tpu.common.crashpoints import crashpoint
from oryx_tpu.common.lang import collect_in_parallel
from oryx_tpu.common.records import ChainRecords, ListRecords, as_records
from oryx_tpu.common.resilience import RetryPolicy
from oryx_tpu.ml import param as hp
from oryx_tpu.registry.gate import ChampionGate
from oryx_tpu.registry.manifest import (
    GENERATION_EXTENSION,
    ONLINE_PENDING,
    PARENT_EXTENSION,
    STATUS_GATED,
    STATUS_PUBLISHED,
    GenerationManifest,
    content_hash_of,
)
from oryx_tpu.registry.store import RegistryStore

log = logging.getLogger(__name__)

MODEL_FILE_NAME = "model.pmml"


class MLUpdate(BatchLayerUpdate, abc.ABC):
    """Apps subclass this and implement get_hyper_parameter_values,
    build_model, and evaluate."""

    def __init__(self, config: Config) -> None:
        self.config = config
        self.test_fraction = config.get_float("oryx.ml.eval.test-fraction")
        candidates = config.get_int("oryx.ml.eval.candidates")
        self.eval_parallelism = config.get_int("oryx.ml.eval.parallelism")
        self.threshold = config.get_optional_float("oryx.ml.eval.threshold")
        self.hyperparam_search = config.get_string("oryx.ml.eval.hyperparam-search")
        if self.hyperparam_search not in ("grid", "random"):
            raise ValueError(
                f"oryx.ml.eval.hyperparam-search must be grid or random, "
                f"got {self.hyperparam_search!r}"
            )
        self.max_message_size = config.get_int("oryx.update-topic.message.max-size")
        if not 0.0 <= self.test_fraction <= 1.0:
            raise ValueError("test-fraction must be in [0,1]")
        if self.test_fraction == 0.0 and candidates > 1:
            log.info("test-fraction = 0 so forcing candidates to 1")
            candidates = 1
        self.candidates = max(1, candidates)
        # a trained model that fails to publish over a transient bus fault
        # is an entire generation of compute lost — retry under the batch
        # layer's policy before giving up
        self.publish_retry = RetryPolicy.from_config(config, "oryx.batch.retry")
        self.gate = ChampionGate(config)
        self.warm_start = config.get_bool("oryx.ml.warm-start")
        self.retention_max_generations = config.get_int(
            "oryx.ml.retention.max-generations"
        )
        # champion state surfaced to build_model for warm-starting; set per
        # run by load_previous_model
        self.previous_model: Element | None = None
        self.previous_model_dir: str | None = None
        self.previous_generation_id: str | None = None
        # per-phase wall of the winning candidate ({"build": s, "eval": s}),
        # refreshed each run — read by operators/benchmarks to see where a
        # generation's wall went without a profiler
        self.last_phase_seconds: dict[str, float] = {}

    # -- abstract app hooks --------------------------------------------------

    def get_hyper_parameter_values(self) -> list[hp.HyperParamValues]:
        """Ranges of hyperparameters to try; order matters and must match
        what build_model expects (MLUpdate.java:110-117)."""
        return []

    @abc.abstractmethod
    def build_model(
        self,
        train_data: Iterable[KeyMessage],
        hyper_parameters: Sequence,
        candidate_path: Path,
    ) -> Element:
        """Train and return the model as a PMML element tree; large side
        artifacts (e.g. factor matrices) go under candidate_path.
        train_data is re-iterable and may be a common.records.Records
        (columnar blocks for vectorized consumers)."""

    @abc.abstractmethod
    def evaluate(
        self,
        model: Element,
        model_parent_path: Path,
        test_data: list[KeyMessage],
        train_data: Iterable[KeyMessage],
    ) -> float:
        """Higher is better (MLUpdate.java evaluation contract)."""

    def publish_additional_model_data(
        self,
        pmml: Element,
        new_data: list[KeyMessage],
        past_data: Iterable[KeyMessage],
        model_parent_path: Path,
        model_update_topic: TopicProducer | None,
    ) -> None:
        """Optionally stream extra model payloads after MODEL
        (ALSUpdate.publishAdditionalModelData analogue)."""

    def split_new_data_to_train_test(
        self, new_data: list[KeyMessage]
    ) -> tuple[list[KeyMessage], list[KeyMessage]]:
        """Default random split by test-fraction (MLUpdate.java:360-372)."""
        if self.test_fraction <= 0.0:
            return new_data, []
        if self.test_fraction >= 1.0:
            return [], new_data
        gen = rng.get_random()
        mask = gen.random(len(new_data)) < self.test_fraction
        train = [d for d, is_test in zip(new_data, mask) if not is_test]
        test = [d for d, is_test in zip(new_data, mask) if is_test]
        return train, test

    # -- warm-start ----------------------------------------------------------

    def load_previous_model(self, store: RegistryStore) -> Element | None:
        """Surface the champion generation's PMML (and dir, for side
        artifacts like ALS factor shards) to ``build_model``. Called at
        the top of every run when ``oryx.ml.warm-start`` is on; apps read
        ``self.previous_model`` / ``self.previous_model_dir`` and decide
        whether shapes still match. Any read failure degrades to a cold
        start — warm-start is an optimization, never a correctness
        dependency."""
        self.previous_model = None
        self.previous_model_dir = None
        self.previous_generation_id = None
        try:
            champion = store.champion_id()
            if champion is None:
                return None
            text = store.read_pmml_text(champion)
            if text is None:
                return None
            self.previous_model = pmml_io.from_string(text)
            self.previous_model_dir = store.generation_dir(champion)
            self.previous_generation_id = champion
            log.info("warm-start: loaded champion generation %s", champion)
        except Exception:
            log.warning("failed to load previous model; cold-starting", exc_info=True)
            self.previous_model = None
            self.previous_model_dir = None
            self.previous_generation_id = None
        return self.previous_model

    # -- the harness ---------------------------------------------------------

    def run_update(
        self,
        timestamp_ms: int,
        new_data: Iterable[KeyMessage],
        past_data: Iterable[KeyMessage],
        model_dir: str,
        model_update_topic: TopicProducer | None,
    ) -> None:
        new_data = list(new_data)
        past_records = as_records(past_data)
        if not new_data and past_records.is_empty():
            log.info("no data at all; nothing to do")
            return

        train_new, test_new = self.split_new_data_to_train_test(new_data)
        # lazy concat: past data streams from storage block by block
        # (BatchUpdateFunction's union of past RDD + new RDD), so training
        # at 100M-rating scale never holds history as one Python list
        all_train = ChainRecords([past_records, ListRecords(train_new)])

        if self.hyperparam_search == "random":
            combos = hp.sample_hyper_parameter_combos(
                self.get_hyper_parameter_values(), self.candidates
            )
        else:
            combos = hp.choose_hyper_parameter_combos(
                self.get_hyper_parameter_values(),
                self.candidates,
                hp.choose_values_per_hyper_param(
                    len(self.get_hyper_parameter_values()), self.candidates
                ),
            )

        store = RegistryStore(str(model_dir))
        # repair-on-open: quarantine half-written generations / torn
        # pointers a killed predecessor left behind, BEFORE computing the
        # parent lineage against them (no concurrent promote can be in
        # flight — this process is the promoter)
        store.fsck(repair=True)
        generation_id = str(timestamp_ms)
        parent_id = store.champion_id()
        if self.warm_start:
            self.load_previous_model(store)

        candidates_root = Path(tempfile.mkdtemp(prefix="oryx-candidates-"))
        t0 = time.monotonic()
        try:
            best = self._find_best_candidate(candidates_root, combos, all_train, test_new)
            if best is None:
                log.info("unable to build any model")
                return
            best_score, best_path, best_pmml, best_params = best

            # stamp generation identity into the document itself, so an
            # inline MODEL message is self-describing on the wire (the
            # serving tracker reads it back out)
            from oryx_tpu.app import pmml as app_pmml

            app_pmml.add_extension(best_pmml, GENERATION_EXTENSION, generation_id)
            if parent_id is not None:
                app_pmml.add_extension(best_pmml, PARENT_EXTENSION, parent_id)
            local_pmml = Path(best_path) / MODEL_FILE_NAME
            pmml_io.write_pmml(best_pmml, local_pmml)

            decision = self.gate.decide(store, best_score)

            # promote to model_dir/<timestampMs>/: temp -> rename locally,
            # recursive upload (PMML last) to an object store. Capture the
            # PMML bytes before the local copy disappears — publishing must
            # not re-download what was on local disk a moment ago. A gated
            # generation is promoted too (archived for forensics), it just
            # never reaches the update topic or the CHAMPION pointer.
            pmml_bytes = local_pmml.read_bytes()
            pmml_text = (
                pmml_bytes.decode("utf-8")
                if len(pmml_bytes) <= self.max_message_size
                else None
            )
            if storage.is_remote(model_dir):
                final_dir = storage.join(model_dir, generation_id)
                # list, don't exists(): on object stores a bare prefix can
                # report absent while stale blobs from a previous partial
                # upload still live under it
                if storage.list_names(final_dir):
                    storage.delete(final_dir, recursive=True)
                storage.upload_dir(best_path, final_dir)
                shutil.rmtree(best_path, ignore_errors=True)
            else:
                final_dir = storage.local_path(model_dir) / generation_id
                final_dir.parent.mkdir(parents=True, exist_ok=True)
                if final_dir.exists():
                    shutil.rmtree(final_dir)
                # stage on the registry's OWN filesystem first, then one
                # atomic rename: moving straight from the /tmp candidate
                # dir can cross devices, where shutil.move degrades to
                # copy+delete and a crash mid-copy leaves a half-written
                # generation (ORX602). Dead .promote- litter is swept by
                # RegistryStore.fsck.
                promote_tmp = final_dir.parent / f".promote-{generation_id}-{os.getpid()}"
                if promote_tmp.exists():
                    shutil.rmtree(promote_tmp)
                shutil.move(str(best_path), str(promote_tmp))
                os.rename(promote_tmp, final_dir)
                storage.fsync_dir(final_dir.parent)
            crashpoint("ml.promote.mid")

            # online (evidence-gated) promotion: when the online gate is
            # enabled and a champion already exists, a publish-worthy
            # candidate goes out WITHOUT moving the CHAMPION pointer —
            # serving classifies it as the challenger arm and the online
            # gate moves the pointer only once live evidence clears the
            # bars (docs/experiments.md). Bootstrap (no champion yet)
            # promotes immediately, as offline mode does.
            online_pending = (
                decision.publish
                and self.gate.online.enabled
                and store.champion_id() is not None
            )

            store.write_manifest(
                GenerationManifest(
                    generation_id=generation_id,
                    parent_id=parent_id,
                    status=STATUS_PUBLISHED if decision.publish else STATUS_GATED,
                    hyperparams=list(best_params),
                    eval_metric=best_score,
                    train_count=sum(len(b) for b in all_train.blocks()),
                    test_count=len(test_new),
                    wall_time_sec=time.monotonic() - t0,
                    content_hash=content_hash_of(pmml_bytes),
                    created_at_ms=timestamp_ms,
                    gate_reason=None if decision.publish else decision.reason,
                    online_status=ONLINE_PENDING if online_pending else None,
                )
            )

            if not decision.publish:
                log.warning(
                    "generation %s gated, not published: %s", generation_id, decision.reason
                )
                return

            crashpoint("ml.champion.pre")
            if online_pending:
                log.info(
                    "generation %s published as online challenger: champion "
                    "pointer stays until the online gate promotes it",
                    generation_id,
                )
            else:
                store.set_champion(generation_id, now_ms=timestamp_ms)

            if model_update_topic is None:
                log.info("not publishing model to update topic since none is configured")
            else:
                # publish under a (sampled-root) trace span, with a "@trc"
                # header stamped with publish time: every replica that
                # applies this generation records a serving.model.apply
                # span in the same trace and derives its propagation skew
                # from the timestamp
                publish_ms = int(time.time() * 1000)
                with tracing.span(
                    "batch.publish-model",
                    attrs={"generation": generation_id},
                    root=True,
                ):
                    if pmml_text is not None:
                        records, _ = tracing.with_header(
                            [("MODEL", pmml_text)], ingest_ms=publish_ms
                        )
                    else:
                        # a MODEL-REF names the *generation dir* — registry-
                        # resolvable (manifest + side artifacts travel with
                        # the document), never a bare file path
                        ref = store.generation_dir(generation_id)
                        records, _ = tracing.with_header(
                            [("MODEL-REF", ref)], ingest_ms=publish_ms
                        )
                    crashpoint("ml.publish.pre")
                    self.publish_retry.call(
                        lambda: model_update_topic.send_many(records),
                        retry_on=(ConnectionError, OSError),
                        metrics_prefix="batch.publish",
                    )
                    self.publish_additional_model_data(
                        best_pmml, new_data, past_records, final_dir, model_update_topic
                    )
                    crashpoint("ml.publish.post")
        finally:
            shutil.rmtree(candidates_root, ignore_errors=True)
        store.gc(self.retention_max_generations, never_delete={generation_id})

    def _find_best_candidate(
        self,
        candidates_root: Path,
        combos: list[list],
        all_train: Iterable[KeyMessage],
        test_data: list[KeyMessage],
    ) -> tuple[float, Path, Element, Sequence] | None:
        # Disjoint sub-meshes: with N>1 parallel candidates and enough
        # devices, each candidate trains on its own contiguous device
        # subset — genuinely concurrent accelerator work, the analogue of
        # MLUpdate.java:256-288's parallel Spark jobs. With one device (or
        # parallelism 1) every group is the full device set: the serial
        # fallback costs nothing.
        from oryx_tpu.parallel import mesh as mesh_mod

        groups = (
            mesh_mod.partition_devices(self.eval_parallelism)
            if self.eval_parallelism > 1 and len(combos) > 1
            else None
        )

        def build_and_eval(i: int) -> tuple[float, Path, Element, Sequence] | None:
            candidate_path = candidates_root / str(i)
            candidate_path.mkdir(parents=True, exist_ok=True)
            hyper_parameters = combos[i]
            scope = (
                mesh_mod.device_scope(groups[i % len(groups)])
                if groups
                else contextlib.nullcontext()
            )
            t_build = time.monotonic()
            try:
                with scope:
                    model = self.build_model(all_train, hyper_parameters, candidate_path)
            except Exception:
                log.exception("failed to build candidate %d (%s)", i, hyper_parameters)
                return None
            build_sec = time.monotonic() - t_build
            pmml_io.write_pmml(model, candidate_path / MODEL_FILE_NAME)
            t_eval = time.monotonic()
            if not test_data and len(combos) == 1:
                eval_score = math.nan  # nothing to evaluate against; only candidate wins
            else:
                try:
                    eval_score = self.evaluate(
                        model, candidate_path, test_data, all_train
                    )
                except Exception:
                    log.exception("failed to evaluate candidate %d", i)
                    return None
            eval_sec = time.monotonic() - t_eval
            log.info(
                "candidate %d params=%s eval=%s (build %.2fs, eval %.2fs)",
                i, hyper_parameters, eval_score, build_sec, eval_sec,
            )
            return eval_score, candidate_path, model, hyper_parameters, build_sec, eval_sec

        results = collect_in_parallel(
            len(combos), build_and_eval, parallelism=self.eval_parallelism
        )
        best = None
        for r in results:
            if r is None:
                continue
            score = r[0]
            if self.threshold is not None and not math.isnan(score) and score < self.threshold:
                log.info("candidate %s below threshold %s; discarded", score, self.threshold)
                continue
            if best is None or (
                not math.isnan(score) and (math.isnan(best[0]) or score > best[0])
            ):
                best = r
        if best is None:
            return None
        score, path, model, params, build_sec, eval_sec = best
        self.last_phase_seconds = {"build": build_sec, "eval": eval_sec}
        log.info(
            "best candidate eval=%s (build %.2fs, eval %.2fs)", score, build_sec, eval_sec
        )
        return score, path, model, params
