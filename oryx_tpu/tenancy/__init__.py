"""Multi-tenant lambda: the tenant as a first-class identity.

The reference runs exactly one BatchLayerUpdate / SpeedModelManager /
ServingModelManager triple per process (PAPER.md); this package removes
that limit. A *tenant* is one packaged app (ALS, k-means, RDF, or the
test probe app) with its own input/update topics, registry lineage, SLO
and fair-share weight, declared under ``oryx.tenancy.tenants.<id>`` in
HOCON. The pieces:

- :mod:`oryx_tpu.tenancy.spec` — ``TenantSpec`` / ``TenantRegistry``
  parsing plus :func:`tenant_config`, the namespacing overlay that turns
  the base config into one tenant's private view (topics, data/model
  dirs, app classes);
- :mod:`oryx_tpu.tenancy.context` — the request-thread ContextVar that
  carries the resolved tenant from HTTP dispatch into the batcher and
  shed counters (the same pattern as the overload probe override);
- :mod:`oryx_tpu.tenancy.mux` — the serving-side model-manager facade
  multiplexing per-tenant managers behind the single
  ``ctx.model_manager`` the resources already use;
- :mod:`oryx_tpu.tenancy.pipelines` — N tenants' batch/speed layers in
  one process, each with its own MLUpdate lineage and crash/repair
  invariants.

Fairness (docs/multi-tenancy.md): the adaptive batcher services
per-tenant queues deficit-round-robin by ``weight``, and the admission
controller keeps a per-tenant shed ladder, so a hot tenant sheds itself
before it can starve its neighbours.
"""

from oryx_tpu.tenancy.context import (
    TENANT_HEADER,
    TENANT_PATH_PREFIX,
    current_tenant,
    split_tenant_path,
    tenant_scope,
)
from oryx_tpu.tenancy.spec import (
    APP_WIRING,
    TenantSpec,
    TenantRegistry,
    namespaced,
    tenant_config,
)

__all__ = [
    "APP_WIRING",
    "TENANT_HEADER",
    "TENANT_PATH_PREFIX",
    "TenantRegistry",
    "TenantSpec",
    "current_tenant",
    "namespaced",
    "split_tenant_path",
    "tenant_config",
    "tenant_scope",
]
