"""N tenants' batch/speed pipelines in one process.

The classic deployment runs one ``BatchLayer`` (or ``SpeedLayer``) per
process; :class:`TenantPipelines` runs one per *tenant* instead, each
constructed from the tenant's namespaced view of the shared config
(:func:`oryx_tpu.tenancy.spec.tenant_config`) — private input/update
topics, private data/model dirs, the tenant's own update class — so
every tenant keeps its own MLUpdate lineage, generation numbering,
offset-ledger identity and crash/repair invariants while sharing the
process, the bus brokers and the accelerator.

Per-tenant progress is visible as ``batch.generations.tenant.<tenant>``
and ``speed.updates.tenant.<tenant>`` counters, and each layer object is
registered on the resource ledger under its tenant id.
"""

from __future__ import annotations

import logging

from oryx_tpu.common import ledger, metrics
from oryx_tpu.common.config import Config
from oryx_tpu.tenancy.spec import TenantRegistry, TenantSpec, tenant_config

log = logging.getLogger(__name__)


class TenantPipelines:
    """Per-tenant batch and/or speed layers over one shared base config.

    ``kind`` selects which layer each tenant runs ("batch" or "speed");
    tenants whose app declares no update/speed class (the probe app) are
    skipped — they are serving-only tenants. Layers are built lazily in
    :meth:`start` so a config error in one tenant surfaces before any
    other tenant's layer spun up threads.
    """

    def __init__(self, base: Config, tenants: TenantRegistry, kind: str) -> None:
        if kind not in ("batch", "speed"):
            raise ValueError(f"kind must be 'batch' or 'speed', got {kind!r}")
        self.base = base
        self.tenants = tenants
        self.kind = kind
        self.layers: dict[str, object] = {}
        self._closed = False

    # -- lifecycle --

    def _wired(self, spec: TenantSpec) -> bool:
        key = "update-class" if self.kind == "batch" else "speed-manager"
        return spec.wiring(key) is not None

    def start(self) -> None:
        for spec in self.tenants:
            if not self._wired(spec):
                log.info(
                    "tenant %s: app %r has no %s pipeline; skipping",
                    spec.tenant_id,
                    spec.app,
                    self.kind,
                )
                continue
            tcfg = tenant_config(self.base, spec)
            layer = self._build(tcfg)
            self.layers[spec.tenant_id] = layer
            ledger.register(f"tenant-{self.kind}", layer, live=_layer_live)
        for tid, layer in self.layers.items():
            if self.kind == "batch":
                layer.prepare()
            else:
                layer.prepare_input()
            log.info("tenant %s: %s layer ready", tid, self.kind)

    def _build(self, tcfg: Config):
        if self.kind == "batch":
            from oryx_tpu.lambda_.batch import BatchLayer

            return BatchLayer(tcfg)
        from oryx_tpu.lambda_.speed import SpeedLayer

        return SpeedLayer(tcfg)

    # -- driving --

    def run_round(self) -> dict[str, int]:
        """One unit of work per tenant, round-robin: a batch generation
        (``run_one_generation``) or a speed micro-batch
        (``run_one_batch``). Returns tenant id -> work count this round
        (generations are always 1; a speed round reports records
        consumed). A tenant's failure propagates — the driver decides
        whether to retry or fail the round; other tenants' state is
        untouched because nothing is shared below the broker."""
        done: dict[str, int] = {}
        for tid, layer in self.layers.items():
            if self.kind == "batch":
                layer.run_one_generation()
                done[tid] = 1
                metrics.registry.counter(
                    f"batch.generations.tenant.{tid}"
                ).inc()
            else:
                n = layer.run_one_batch()
                done[tid] = n
                if n:
                    metrics.registry.counter(
                        f"speed.updates.tenant.{tid}"
                    ).inc()
        return done

    def generation_counts(self) -> dict[str, int]:
        """tenant id -> generations (batch) or micro-batches (speed)."""
        attr = "generation_count" if self.kind == "batch" else "batch_count"
        return {tid: getattr(l, attr) for tid, l in self.layers.items()}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        errors = []
        for tid, layer in self.layers.items():
            try:
                layer.close()
            except Exception as e:  # close every tenant before raising
                errors.append((tid, e))
        if errors:
            tid, e = errors[0]
            raise RuntimeError(f"closing tenant {tid} {self.kind} layer") from e

    def __enter__(self) -> "TenantPipelines":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _layer_live(layer) -> bool:
    return not getattr(layer, "_closed", False)
