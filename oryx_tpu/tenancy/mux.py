"""Serving-side tenant multiplexing.

One serving replica hosts every tenant's model behind the single
``ServingContext`` the resource handlers already know: the mux objects
below implement the same ``get_model()`` / ``send()`` surfaces as a
plain model manager / input producer, but resolve the *current* tenant
(``tenancy.context``) on every call. Handlers stay tenant-blind — the
HTTP layer scopes the tenant over the dispatch, and the mux picks the
right tenant's manager, tracker, or topic underneath them.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from oryx_tpu.tenancy.context import current_tenant
from oryx_tpu.tenancy.spec import TenantSpec


@dataclass
class TenantRuntime:
    """One tenant's live serving-side state on this replica."""

    spec: TenantSpec
    config: Any  # the tenant's namespaced view (tenancy.spec.tenant_config)
    manager: Any  # the tenant's serving model manager
    health: Any  # per-tenant ServingHealth (staleness / live generation)
    tracker: Any  # per-tenant GenerationTracker
    store: Any = None  # per-tenant RegistryStore (None without a model dir)
    consumer: Any = None  # per-tenant update-topic consumer
    thread: Any = None  # the SupervisedThread driving consume_blocks
    producer: Any = None  # per-tenant input-topic producer (ingest path)
    extras: dict = field(default_factory=dict)


class TenantServingMux:
    """Model-manager facade multiplexing per-tenant managers.

    Exposes the subset of the model-manager surface the serving layer and
    the resource handlers touch (``get_model``, ``consume_blocks`` is per
    tenant and never called on the mux, ``close``), resolving the tenant
    from the request-scoped ContextVar. With no tenant in scope the
    registry's default tenant answers, so untenanted legacy clients keep
    working on a tenant-enabled fleet.
    """

    def __init__(
        self,
        runtimes: dict[str, TenantRuntime],
        default_tenant: str | None = None,
    ) -> None:
        self._runtimes = dict(runtimes)
        self._default = default_tenant

    # -- resolution --

    def _resolve(self) -> TenantRuntime | None:
        tid = current_tenant() or self._default
        return self._runtimes.get(tid) if tid else None

    def runtime(self, tenant_id: str) -> TenantRuntime | None:
        return self._runtimes.get(tenant_id)

    def runtimes(self) -> dict[str, TenantRuntime]:
        return dict(self._runtimes)

    def ids(self) -> list[str]:
        return list(self._runtimes)

    # -- model-manager surface --

    def get_model(self):
        rt = self._resolve()
        return rt.manager.get_model() if rt is not None else None

    def tenant_models(self) -> dict[str, Any]:
        """tenant id -> current model (None while loading) — readiness."""
        return {tid: rt.manager.get_model() for tid, rt in self._runtimes.items()}

    def live_generations(self) -> dict[str, str | None]:
        """tenant id -> live generation, the fleet-skew input."""
        return {
            tid: rt.health.live_generation for tid, rt in self._runtimes.items()
        }

    def close(self) -> None:
        for rt in self._runtimes.values():
            manager_close = getattr(rt.manager, "close", None)
            if manager_close is not None:
                manager_close()

    def __getattr__(self, name: str):
        """Manager-specific surface (``is_read_only``, app-specific
        helpers) forwards to the CURRENT tenant's manager — resolved at
        attribute access, which happens on the request thread inside the
        dispatch's tenant scope."""
        if name.startswith("_"):
            raise AttributeError(name)
        rt = self._resolve()
        if rt is None:
            raise AttributeError(
                f"{name!r}: no tenant in scope and no default tenant"
            )
        return getattr(rt.manager, name)


class TenantInputMux:
    """Input-producer facade: ``send()`` routes to the current tenant's
    input topic, so the app ingest endpoints stay tenant-blind too."""

    def __init__(
        self,
        producers: dict[str, Any],
        default_tenant: str | None = None,
    ) -> None:
        self._producers = dict(producers)
        self._default = default_tenant

    def send(self, key, value) -> None:
        tid = current_tenant() or self._default
        producer = self._producers.get(tid) if tid else None
        if producer is None:
            raise RuntimeError(
                f"no input topic for tenant {tid!r}"
                if tid
                else "no tenant in scope for ingest"
            )
        producer.send(key, value)

    def close(self) -> None:
        for producer in self._producers.values():
            producer.close()
